"""Serving-engine load benchmark: throughput-per-latency-budget.

The serving twin of tools/feed_bench.py: drive the AOT-batched engine
(``sparknet_tpu/serve``) under synthetic load and print one JSON line
per arm, then a combined gate record (banked to
``docs/serve_bench_last.json`` under ``--bank``):

* **closed-loop** — per-bucket saturation: exact-fit bursts through
  each ladder bucket, requests/s and per-request p50/p99 (how much
  traffic a bucket sustains when demand always fills it).
* **open-loop** — Poisson arrivals at ``--rate`` req/s against the
  ``max_wait_ms`` deadline flush: the tail-latency claim under trickle
  load is that NO request's queue wait exceeds max_wait_ms by more
  than one scheduler tick (arrivals don't wait for service — the
  generator enqueues on schedule even when the engine lags).
* **swap** (``--swap``) — the hot-reload arm: a full rollout
  (candidate AOT-compiled on a builder thread, ``swap_model`` under
  the pump lock — sparknet_tpu/loop protocol) lands mid-stream under
  the same Poisson load; reports the swap-gap (max request stall and
  p99 over requests overlapping the swap) next to the lock-hold wall.
  With this arm the compile gate moves to the per-thread ledger
  (``engine.serve_path_compiles`` must read 0 — builder compiles are
  by design), and any unresolved ticket voids the record.

House rules: the recompile sentinel must read 0 post-warmup compiles
across both arms (AOT buckets — any recompile voids the run);
per-request latencies come from the engine's journaled decomposition;
``SPARKNET_BENCH_REQUIRE_MEASURED=1`` exits rc 4 when an accelerator
run falls back to CPU (the queue-runner contract).  CPU runs are
labeled host-side provenance (``platform: cpu``, ``chip_measured:
false``) — real relay numbers ride the r7 queue's serve_latency job.

ref: apps/ImageNetRunDBApp.scala:1 (the reference's batch-scoring
consumer; request-level load generation is new TPU-first surface).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LAST_PATH = "docs/serve_bench_last.json"


def _pctl(vals, q):
    from sparknet_tpu.serve.engine import percentile

    return percentile(list(vals), q)


def bench_closed_loop(engine, model, burst: int, rounds: int) -> dict:
    """Saturate one bucket: ``rounds`` exact-fit bursts of ``burst``
    requests, pumped back to back."""
    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    rs = np.random.RandomState(burst)
    items = synthetic_items(served, burst, rs)
    t0 = time.perf_counter()
    for _ in range(rounds):
        for item in items:
            engine.submit(model, item)
        engine.pump(force=True)
    dt = time.perf_counter() - t0
    lats = served.lat_total_ms[n0:]
    return {
        "metric": f"serve_closed_b{burst}_rps",
        "value": round(burst * rounds / dt, 1),
        "unit": f"req/s (bucket {burst}, {rounds} exact-fit bursts)",
        "p50_ms": round(_pctl(lats, 50), 3),
        "p99_ms": round(_pctl(lats, 99), 3),
    }


def bench_open_loop(engine, model, rate: float, seconds: float,
                    max_wait_ms: float, seed: int = 7) -> dict:
    """Poisson arrivals at ``rate`` req/s: the deadline-flush arm.

    The generator sleeps to each exponential inter-arrival time and
    never blocks on results — queue waits measure the BATCHER's
    deadline policy, not generator backpressure.  A worker thread
    drains flushes as they come due, exactly the ``serve_forever``
    production path.
    """
    import threading

    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    q0 = len(served.lat_queue_ms)
    rs = np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    items = synthetic_items(served, min(n, 64), rs)
    gaps = rs.exponential(1.0 / rate, n)
    stop = threading.Event()
    worker = threading.Thread(
        target=lambda: engine.serve_forever(until=stop.is_set),
        daemon=True)
    worker.start()
    tickets = []
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + float(np.sum(gaps[:i + 1]))
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(model, items[i % len(items)]))
    for t in tickets:
        t.wait(timeout=60.0)
    dt = time.perf_counter() - t0
    stop.set()
    worker.join(timeout=5.0)
    waits = served.lat_queue_ms[q0:]
    lats = served.lat_total_ms[n0:]
    # one scheduler tick of slack: wait_due wakes AT the deadline, but
    # the wake itself is at the mercy of the host scheduler
    tick_ms = 15.0
    bounded = _pctl(waits, 100) <= max_wait_ms + tick_ms
    return {
        "metric": "serve_open_poisson_p99_ms",
        "value": round(_pctl(lats, 99), 3),
        "unit": f"ms total latency (open loop, {rate:g} req/s Poisson, "
                f"{n} requests)",
        "p50_ms": round(_pctl(lats, 50), 3),
        "queue_max_ms": round(_pctl(waits, 100), 3),
        "max_wait_ms": max_wait_ms,
        "deadline_bounded": bool(bounded),
        "achieved_rps": round(n / dt, 1),
    }


def bench_swap_gap(engine, model, rate: float, seconds: float,
                   family: str, arm: str, buckets: tuple,
                   seed: int = 11) -> dict:
    """The hot-reload arm: open-loop Poisson load with a full rollout
    mid-stream (sparknet_tpu/loop protocol — candidate AOT-compiled on
    a builder thread, ``swap_model`` under the pump lock).

    The swap-gap claim: the candidate's compile cost never reaches the
    request path — the only request-visible stall is the pump-lock hold
    (queue steal + dict flip, microseconds) plus natural device
    contention from draining the incumbent.  Reported as the max total
    latency over requests whose lifetime OVERLAPS the swap interval,
    next to the run's overall p99 and the lock-hold wall itself.
    """
    import threading

    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    rs = np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    items = synthetic_items(served, min(n, 64), rs)
    gaps = rs.exponential(1.0 / rate, n)
    stop = threading.Event()
    worker = threading.Thread(
        target=lambda: engine.serve_forever(until=stop.is_set),
        daemon=True)
    worker.start()

    swap: dict = {}

    def builder() -> None:
        # build + swap land mid-run; engine.clock stamps the interval
        # in the same timebase as the tickets' t_submit/t_done
        time.sleep(seconds * 0.4)
        b0 = time.perf_counter()
        cand = engine.build_candidate(model, family=family, arm=arm,
                                      buckets=buckets, seed=seed)
        swap["build_s"] = time.perf_counter() - b0
        swap["t0"] = engine.clock()
        swap.update(engine.swap_model(model, cand))
        swap["t1"] = engine.clock()

    bthread = threading.Thread(target=builder, daemon=True)
    tickets = []
    t0 = time.perf_counter()
    bthread.start()
    for i in range(n):
        target = t0 + float(np.sum(gaps[:i + 1]))
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(model, items[i % len(items)]))
    for t in tickets:
        t.wait(timeout=60.0)
    bthread.join(timeout=120.0)
    stop.set()
    worker.join(timeout=5.0)

    overlap = [t for t in tickets
               if t.t_done is not None and t.t_done >= swap["t0"]
               and t.t_submit <= swap["t1"]]
    stalls = [(t.t_done - t.t_submit) * 1e3 for t in overlap]
    # every request the swap could have touched resolved — the
    # zero-dropped-tickets half of the hot-reload contract
    dropped = sum(1 for t in tickets if not t.done())
    lats = [ms for m in (engine._models[model],
                         engine._models[model].previous) if m
            for ms in m.lat_total_ms[n0 if m is served else 0:]]
    swap_wall_ms = swap.get("swap_wall_s", 0.0) * 1e3
    return {
        "metric": "serve_swap_gap_ms",
        "value": round(max(stalls) if stalls else swap_wall_ms, 3),
        "unit": f"ms max request stall overlapping the hot swap "
                f"(open loop, {rate:g} req/s Poisson, {n} requests)",
        "p99_ms_during": round(_pctl(stalls, 99), 3) if stalls else 0.0,
        "p99_ms_overall": round(_pctl(lats, 99), 3),
        "swap_wall_ms": round(swap_wall_ms, 3),
        "candidate_build_s": round(swap.get("build_s", 0.0), 3),
        "overlapping_requests": len(overlap),
        "drained": swap.get("drained", 0),
        "version": swap.get("version", 0),
        "dropped": dropped,
    }


def bench_replica_aggregate(replicas: int, family: str, arm: str,
                            buckets: tuple, max_wait_ms: float,
                            rate: float, seconds: float,
                            seed: int = 11,
                            chunk_s: float = 0.005) -> dict:
    """The pod arm: K replicas under one open-loop Poisson stream.

    Arrivals are submitted in ~``chunk_s`` chunks through the router's
    ``submit_many`` (one lock walk per chunk — at pod offered rates
    per-request locking alone is measurable against the serving
    budget), with ``shed=True`` so overload rejects at the door.  One
    pump thread runs the fair sweep (``router.serve_forever``).

    Two latency views: the overall queue p99, and the WARM p99 over
    requests submitted after a 0.5 s ramp — cold-start arrivals land
    before the drain-rate estimators have any evidence, so their
    waits measure the admission rule's blind window, not its steady
    state.  The deadline gate reads the warm view and says so.
    """
    import threading

    from sparknet_tpu.serve.engine import SHED_TICK_MS
    from sparknet_tpu.serve.loadgen import (open_loop_schedule,
                                            synthetic_items)
    from sparknet_tpu.serve.router import ReplicaRouter

    router = ReplicaRouter(replicas=replicas, family=family, arm=arm,
                           buckets=buckets, max_wait_ms=max_wait_ms,
                           seed=seed)
    rs = np.random.RandomState(seed)
    router.warmup(rs)
    items = synthetic_items(
        next(iter(router._replicas.values())).model, 512, rs)
    stop = threading.Event()
    worker = threading.Thread(target=router.serve_forever,
                              kwargs={"until": stop.is_set},
                              daemon=True)
    worker.start()
    sched = open_loop_schedule(rate, seconds, seed=seed)
    tickets: list = []
    shed = 0
    t0 = time.perf_counter()
    i = 0
    while i < len(sched):
        now = time.perf_counter() - t0
        j = i
        horizon = now + chunk_s
        while j < len(sched) and sched[j] <= horizon:
            j += 1
        if j == i:  # next arrival beyond the horizon: sleep to it
            time.sleep(min(chunk_s, sched[i] - now))
            continue
        adm, n_shed = router.submit_many(
            [items[k % len(items)] for k in range(i, j)], shed=True)
        tickets.extend(adm)
        shed += n_shed
        i = j
    deadline = time.perf_counter() + 60.0
    while (any(not t.done() for t in tickets)
           and time.perf_counter() < deadline):
        time.sleep(0.002)
    wall = time.perf_counter() - t0
    stop.set()
    worker.join(timeout=10.0)
    dropped = sum(1 for t in tickets if not t.done())
    stats = router.stats()
    router.shutdown()

    ramp_s = 0.5
    first = tickets[0].t_submit if tickets else 0.0
    waits = [(t.t_batch - t.t_submit) * 1e3 for t in tickets
             if t.t_batch is not None]
    warm = [(t.t_batch - t.t_submit) * 1e3 for t in tickets
            if t.t_batch is not None
            and t.t_submit - first > ramp_s]
    bound_ms = max_wait_ms + SHED_TICK_MS
    warm_p99 = _pctl(warm, 99)
    return {
        "metric": "serve_replica_aggregate_rps",
        "value": round(len(tickets) / wall, 1) if wall > 0 else 0.0,
        "unit": f"req/s aggregate (open loop, {replicas} replica(s), "
                f"{rate:g} req/s offered Poisson, {len(sched)} "
                f"arrivals, {chunk_s * 1e3:g} ms submit chunks)",
        "replicas": replicas,
        "offered_rps": rate,
        "admitted": len(tickets),
        "shed": shed,
        "dropped": dropped,
        "rerouted": stats["rerouted"],
        "queue_p99_ms": round(_pctl(waits, 99), 3),
        "queue_p99_warm_ms": round(warm_p99, 3),
        "warm_ramp_s": ramp_s,
        "deadline_bound_ms": bound_ms,
        "deadline_bounded": bool(warm_p99 <= bound_ms),
        "serve_path_compiles": stats["serve_path_compiles"],
        "wall_s": round(wall, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--family", default="cifar10_quick")
    ap.add_argument("--arm", default="f32",
                    choices=("f32", "fold_bn", "int8"))
    ap.add_argument("--buckets", default="1,8,64,256")
    ap.add_argument("--rounds", type=int, default=8,
                    help="closed-loop bursts per bucket")
    ap.add_argument("--rate", type=float, default=40.0,
                    help="open-loop Poisson arrival rate (req/s)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="open-loop duration")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the POD arm instead of the single-copy "
                    "arms: K replicas (sparknet_tpu/serve/router) "
                    "under one open-loop Poisson stream, chunked "
                    "submit_many + deadline shed; clamped to the "
                    "visible device count (the relay exposes one chip "
                    "— the clamp is recorded, never silent)")
    ap.add_argument("--agg-rate", type=float, default=16000.0,
                    help="pod-arm offered rate (req/s)")
    ap.add_argument("--agg-seconds", type=float, default=2.0,
                    help="pod-arm open-loop duration")
    ap.add_argument("--swap", action="store_true",
                    help="add the hot-reload arm: a full "
                    "build_candidate + swap_model rollout mid-stream "
                    "under open-loop Poisson load, measuring the "
                    "swap-gap (max request stall and p99 during the "
                    "hot reload — sparknet_tpu/loop protocol)")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (the config route wins "
                    "over JAX_PLATFORMS site pins); cpu = host-side run")
    ap.add_argument("--bank", action="store_true",
                    help=f"bank the gate record to {LAST_PATH} via "
                    "common.bank_guard")
    args = ap.parse_args()

    if args.platform == "cpu" and args.replicas > 1:
        # a CPU pod rehearsal needs K virtual devices, not one — same
        # mesh pin as the dryrun/graphcheck (must land before the
        # backend initializes)
        from sparknet_tpu.analysis.graphcheck import _pin_cpu_mesh

        _pin_cpu_mesh(max(8, args.replicas))
    elif args.platform:
        from sparknet_tpu.common import force_platform

        force_platform(args.platform)
    import jax

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"
    # an armed queue job expects the accelerator unless the cpu platform
    # was EXPLICITLY requested — a wedge-induced CPU fallback must rc 4
    # (window death), never bank host walls as chip evidence
    want_accel = args.platform != "cpu"
    if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
            and want_accel and not on_accel):
        print(json.dumps({"metric": "serve_bench", "skipped":
                          f"accelerator required, got {platform}"}))
        return 4

    from sparknet_tpu.obs.sentinel import get_sentinel
    from sparknet_tpu.serve.engine import ServeEngine
    from sparknet_tpu.serve.loadgen import synthetic_items

    if args.replicas:
        # pod mode replaces the single-copy arms wholesale: transformer
        # family on the serve ladder's lower rungs (the pod headline is
        # row throughput under a 25 ms deadline, docs/SERVING.md
        # "Replication & elasticity")
        get_sentinel().install()
        asked = args.replicas
        replicas = min(asked, len(jax.devices()))
        record = bench_replica_aggregate(
            replicas, family="transformer", arm=args.arm,
            buckets=(1, 8, 64), max_wait_ms=25.0,
            rate=args.agg_rate, seconds=args.agg_seconds)
        record.update({
            "family": "transformer",
            "arm": args.arm,
            "buckets": [1, 8, 64],
            "max_wait_ms": 25.0,
            "replicas_requested": asked,
            "platform": platform,
            "measured": True,
            "host_side": not on_accel,
            "chip_measured": on_accel,
        })
        if record["serve_path_compiles"] != 0:
            record["measured"] = False
            record["compile_inconsistency"] = (
                f"{record['serve_path_compiles']} serving-path "
                "compile(s) post-warmup — the pod AOT contract is "
                "broken; latencies include compile walls")
        if record["dropped"] != 0:
            record["measured"] = False
            record["drop_inconsistency"] = (
                f"{record['dropped']} admitted ticket(s) unresolved — "
                "the zero-drop ledger is broken")
        if not record["deadline_bounded"]:
            record["measured"] = False
            record["deadline_inconsistency"] = (
                f"warm queue p99 {record['queue_p99_warm_ms']} ms over "
                f"the {record['deadline_bound_ms']:g} ms bound — the "
                "shed rule failed to hold the tail")
        print(json.dumps(record))
        if args.bank:
            from sparknet_tpu.common import bank_guard

            bank_guard(LAST_PATH, record, measured=record["measured"])
        if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
                and not record["measured"]):
            return 4
        return 0

    buckets = tuple(int(b) for b in args.buckets.split(","))
    sentinel = get_sentinel().install()
    engine = ServeEngine(buckets=buckets, max_wait_ms=args.max_wait_ms)
    t0 = time.perf_counter()
    engine.load_model("m", family=args.family, arm=args.arm)
    load_s = time.perf_counter() - t0
    served = engine._models["m"]
    # warmup: one flush per bucket, then snapshot the sentinel — the
    # AOT claim is zero compiles caused by TRAFFIC
    rs = np.random.RandomState(0)
    for b in buckets:
        for item in synthetic_items(served, max(1, b // 2), rs):
            engine.submit("m", item)
        engine.pump(force=True)
    compiles0 = sentinel.count

    arms = []
    for b in buckets:
        r = bench_closed_loop(engine, "m", b, args.rounds)
        arms.append(r)
        print(json.dumps(r))
    open_arm = bench_open_loop(engine, "m", args.rate, args.seconds,
                               args.max_wait_ms)
    print(json.dumps(open_arm))
    swap_arm = None
    if args.swap:
        swap_arm = bench_swap_gap(engine, "m", args.rate, args.seconds,
                                  args.family, args.arm, buckets)
        print(json.dumps(swap_arm))
    # with --swap the builder thread's candidate compiles are by design;
    # what must stay zero is the engine's serving-path ledger (per-thread
    # sentinel attribution, obs/sentinel.py)
    compiles_post = (engine.serve_path_compiles if args.swap
                     else sentinel.count - compiles0)
    engine.shutdown()

    best = max(arms, key=lambda r: r["value"])
    record = {
        "metric": "serve_bench_gate",
        "value": best["value"],
        "unit": best["unit"],
        "family": args.family,
        "arm": args.arm,
        "buckets": list(buckets),
        "aot_load_s": round(load_s, 3),
        "closed_loop": {r["metric"]: {k: r[k] for k in
                        ("value", "p50_ms", "p99_ms")} for r in arms},
        "open_loop": open_arm,
        **({"swap": swap_arm} if swap_arm else {}),
        "compiles_post_warmup": compiles_post,
        "max_wait_ms": args.max_wait_ms,
        "platform": platform,
        # host-side provenance on CPU: real walls on this box, but NOT
        # chip numbers — those ride the r7 queue's serve_latency job
        "measured": True,
        "host_side": not on_accel,
        "chip_measured": on_accel,
    }
    if compiles_post != 0:
        record["measured"] = False
        record["compile_inconsistency"] = (
            f"{compiles_post} backend compile(s) during steady-state "
            "traffic — the AOT-bucket contract is broken; latencies "
            "include compile walls and are not evidence")
    if swap_arm is not None and swap_arm["dropped"] != 0:
        record["measured"] = False
        record["swap_inconsistency"] = (
            f"{swap_arm['dropped']} ticket(s) unresolved across the "
            "hot swap — the zero-dropped drain contract is broken")
    print(json.dumps(record))
    if args.bank:
        from sparknet_tpu.common import bank_guard

        bank_guard(LAST_PATH, record, measured=record["measured"])
    if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
            and not record["measured"]):
        return 4
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
