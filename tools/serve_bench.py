"""Serving-engine load benchmark: throughput-per-latency-budget.

The serving twin of tools/feed_bench.py: drive the AOT-batched engine
(``sparknet_tpu/serve``) under synthetic load and print one JSON line
per arm, then a combined gate record (banked to
``docs/serve_bench_last.json`` under ``--bank``):

* **closed-loop** — per-bucket saturation: exact-fit bursts through
  each ladder bucket, requests/s and per-request p50/p99 (how much
  traffic a bucket sustains when demand always fills it).
* **open-loop** — Poisson arrivals at ``--rate`` req/s against the
  ``max_wait_ms`` deadline flush: the tail-latency claim under trickle
  load is that NO request's queue wait exceeds max_wait_ms by more
  than one scheduler tick (arrivals don't wait for service — the
  generator enqueues on schedule even when the engine lags).
* **swap** (``--swap``) — the hot-reload arm: a full rollout
  (candidate AOT-compiled on a builder thread, ``swap_model`` under
  the pump lock — sparknet_tpu/loop protocol) lands mid-stream under
  the same Poisson load; reports the swap-gap (max request stall and
  p99 over requests overlapping the swap) next to the lock-hold wall.
  With this arm the compile gate moves to the per-thread ledger
  (``engine.serve_path_compiles`` must read 0 — builder compiles are
  by design), and any unresolved ticket voids the record.

House rules: the recompile sentinel must read 0 post-warmup compiles
across both arms (AOT buckets — any recompile voids the run);
per-request latencies come from the engine's journaled decomposition;
``SPARKNET_BENCH_REQUIRE_MEASURED=1`` exits rc 4 when an accelerator
run falls back to CPU (the queue-runner contract).  CPU runs are
labeled host-side provenance (``platform: cpu``, ``chip_measured:
false``) — real relay numbers ride the r7 queue's serve_latency job.

ref: apps/ImageNetRunDBApp.scala:1 (the reference's batch-scoring
consumer; request-level load generation is new TPU-first surface).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LAST_PATH = "docs/serve_bench_last.json"


def _pctl(vals, q):
    from sparknet_tpu.serve.engine import percentile

    return percentile(list(vals), q)


def bench_closed_loop(engine, model, burst: int, rounds: int) -> dict:
    """Saturate one bucket: ``rounds`` exact-fit bursts of ``burst``
    requests, pumped back to back."""
    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    rs = np.random.RandomState(burst)
    items = synthetic_items(served, burst, rs)
    t0 = time.perf_counter()
    for _ in range(rounds):
        for item in items:
            engine.submit(model, item)
        engine.pump(force=True)
    dt = time.perf_counter() - t0
    lats = served.lat_total_ms[n0:]
    return {
        "metric": f"serve_closed_b{burst}_rps",
        "value": round(burst * rounds / dt, 1),
        "unit": f"req/s (bucket {burst}, {rounds} exact-fit bursts)",
        "p50_ms": round(_pctl(lats, 50), 3),
        "p99_ms": round(_pctl(lats, 99), 3),
    }


def bench_open_loop(engine, model, rate: float, seconds: float,
                    max_wait_ms: float, seed: int = 7) -> dict:
    """Poisson arrivals at ``rate`` req/s: the deadline-flush arm.

    The generator sleeps to each exponential inter-arrival time and
    never blocks on results — queue waits measure the BATCHER's
    deadline policy, not generator backpressure.  A worker thread
    drains flushes as they come due, exactly the ``serve_forever``
    production path.
    """
    import threading

    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    q0 = len(served.lat_queue_ms)
    rs = np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    items = synthetic_items(served, min(n, 64), rs)
    gaps = rs.exponential(1.0 / rate, n)
    stop = threading.Event()
    worker = threading.Thread(
        target=lambda: engine.serve_forever(until=stop.is_set),
        daemon=True)
    worker.start()
    tickets = []
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + float(np.sum(gaps[:i + 1]))
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(model, items[i % len(items)]))
    for t in tickets:
        t.wait(timeout=60.0)
    dt = time.perf_counter() - t0
    stop.set()
    worker.join(timeout=5.0)
    waits = served.lat_queue_ms[q0:]
    lats = served.lat_total_ms[n0:]
    # one scheduler tick of slack: wait_due wakes AT the deadline, but
    # the wake itself is at the mercy of the host scheduler
    tick_ms = 15.0
    bounded = _pctl(waits, 100) <= max_wait_ms + tick_ms
    return {
        "metric": "serve_open_poisson_p99_ms",
        "value": round(_pctl(lats, 99), 3),
        "unit": f"ms total latency (open loop, {rate:g} req/s Poisson, "
                f"{n} requests)",
        "p50_ms": round(_pctl(lats, 50), 3),
        "queue_max_ms": round(_pctl(waits, 100), 3),
        "max_wait_ms": max_wait_ms,
        "deadline_bounded": bool(bounded),
        "achieved_rps": round(n / dt, 1),
    }


def bench_swap_gap(engine, model, rate: float, seconds: float,
                   family: str, arm: str, buckets: tuple,
                   seed: int = 11) -> dict:
    """The hot-reload arm: open-loop Poisson load with a full rollout
    mid-stream (sparknet_tpu/loop protocol — candidate AOT-compiled on
    a builder thread, ``swap_model`` under the pump lock).

    The swap-gap claim: the candidate's compile cost never reaches the
    request path — the only request-visible stall is the pump-lock hold
    (queue steal + dict flip, microseconds) plus natural device
    contention from draining the incumbent.  Reported as the max total
    latency over requests whose lifetime OVERLAPS the swap interval,
    next to the run's overall p99 and the lock-hold wall itself.
    """
    import threading

    from sparknet_tpu.serve.loadgen import synthetic_items

    served = engine._models[model]
    n0 = len(served.lat_total_ms)
    rs = np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    items = synthetic_items(served, min(n, 64), rs)
    gaps = rs.exponential(1.0 / rate, n)
    stop = threading.Event()
    worker = threading.Thread(
        target=lambda: engine.serve_forever(until=stop.is_set),
        daemon=True)
    worker.start()

    swap: dict = {}

    def builder() -> None:
        # build + swap land mid-run; engine.clock stamps the interval
        # in the same timebase as the tickets' t_submit/t_done
        time.sleep(seconds * 0.4)
        b0 = time.perf_counter()
        cand = engine.build_candidate(model, family=family, arm=arm,
                                      buckets=buckets, seed=seed)
        swap["build_s"] = time.perf_counter() - b0
        swap["t0"] = engine.clock()
        swap.update(engine.swap_model(model, cand))
        swap["t1"] = engine.clock()

    bthread = threading.Thread(target=builder, daemon=True)
    tickets = []
    t0 = time.perf_counter()
    bthread.start()
    for i in range(n):
        target = t0 + float(np.sum(gaps[:i + 1]))
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(engine.submit(model, items[i % len(items)]))
    for t in tickets:
        t.wait(timeout=60.0)
    bthread.join(timeout=120.0)
    stop.set()
    worker.join(timeout=5.0)

    overlap = [t for t in tickets
               if t.t_done is not None and t.t_done >= swap["t0"]
               and t.t_submit <= swap["t1"]]
    stalls = [(t.t_done - t.t_submit) * 1e3 for t in overlap]
    # every request the swap could have touched resolved — the
    # zero-dropped-tickets half of the hot-reload contract
    dropped = sum(1 for t in tickets if not t.done())
    lats = [ms for m in (engine._models[model],
                         engine._models[model].previous) if m
            for ms in m.lat_total_ms[n0 if m is served else 0:]]
    swap_wall_ms = swap.get("swap_wall_s", 0.0) * 1e3
    return {
        "metric": "serve_swap_gap_ms",
        "value": round(max(stalls) if stalls else swap_wall_ms, 3),
        "unit": f"ms max request stall overlapping the hot swap "
                f"(open loop, {rate:g} req/s Poisson, {n} requests)",
        "p99_ms_during": round(_pctl(stalls, 99), 3) if stalls else 0.0,
        "p99_ms_overall": round(_pctl(lats, 99), 3),
        "swap_wall_ms": round(swap_wall_ms, 3),
        "candidate_build_s": round(swap.get("build_s", 0.0), 3),
        "overlapping_requests": len(overlap),
        "drained": swap.get("drained", 0),
        "version": swap.get("version", 0),
        "dropped": dropped,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--family", default="cifar10_quick")
    ap.add_argument("--arm", default="f32",
                    choices=("f32", "fold_bn", "int8"))
    ap.add_argument("--buckets", default="1,8,64,256")
    ap.add_argument("--rounds", type=int, default=8,
                    help="closed-loop bursts per bucket")
    ap.add_argument("--rate", type=float, default=40.0,
                    help="open-loop Poisson arrival rate (req/s)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="open-loop duration")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--swap", action="store_true",
                    help="add the hot-reload arm: a full "
                    "build_candidate + swap_model rollout mid-stream "
                    "under open-loop Poisson load, measuring the "
                    "swap-gap (max request stall and p99 during the "
                    "hot reload — sparknet_tpu/loop protocol)")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (the config route wins "
                    "over JAX_PLATFORMS site pins); cpu = host-side run")
    ap.add_argument("--bank", action="store_true",
                    help=f"bank the gate record to {LAST_PATH} via "
                    "common.bank_guard")
    args = ap.parse_args()

    if args.platform:
        from sparknet_tpu.common import force_platform

        force_platform(args.platform)
    import jax

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"
    # an armed queue job expects the accelerator unless the cpu platform
    # was EXPLICITLY requested — a wedge-induced CPU fallback must rc 4
    # (window death), never bank host walls as chip evidence
    want_accel = args.platform != "cpu"
    if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
            and want_accel and not on_accel):
        print(json.dumps({"metric": "serve_bench", "skipped":
                          f"accelerator required, got {platform}"}))
        return 4

    from sparknet_tpu.obs.sentinel import get_sentinel
    from sparknet_tpu.serve.engine import ServeEngine
    from sparknet_tpu.serve.loadgen import synthetic_items

    buckets = tuple(int(b) for b in args.buckets.split(","))
    sentinel = get_sentinel().install()
    engine = ServeEngine(buckets=buckets, max_wait_ms=args.max_wait_ms)
    t0 = time.perf_counter()
    engine.load_model("m", family=args.family, arm=args.arm)
    load_s = time.perf_counter() - t0
    served = engine._models["m"]
    # warmup: one flush per bucket, then snapshot the sentinel — the
    # AOT claim is zero compiles caused by TRAFFIC
    rs = np.random.RandomState(0)
    for b in buckets:
        for item in synthetic_items(served, max(1, b // 2), rs):
            engine.submit("m", item)
        engine.pump(force=True)
    compiles0 = sentinel.count

    arms = []
    for b in buckets:
        r = bench_closed_loop(engine, "m", b, args.rounds)
        arms.append(r)
        print(json.dumps(r))
    open_arm = bench_open_loop(engine, "m", args.rate, args.seconds,
                               args.max_wait_ms)
    print(json.dumps(open_arm))
    swap_arm = None
    if args.swap:
        swap_arm = bench_swap_gap(engine, "m", args.rate, args.seconds,
                                  args.family, args.arm, buckets)
        print(json.dumps(swap_arm))
    # with --swap the builder thread's candidate compiles are by design;
    # what must stay zero is the engine's serving-path ledger (per-thread
    # sentinel attribution, obs/sentinel.py)
    compiles_post = (engine.serve_path_compiles if args.swap
                     else sentinel.count - compiles0)
    engine.shutdown()

    best = max(arms, key=lambda r: r["value"])
    record = {
        "metric": "serve_bench_gate",
        "value": best["value"],
        "unit": best["unit"],
        "family": args.family,
        "arm": args.arm,
        "buckets": list(buckets),
        "aot_load_s": round(load_s, 3),
        "closed_loop": {r["metric"]: {k: r[k] for k in
                        ("value", "p50_ms", "p99_ms")} for r in arms},
        "open_loop": open_arm,
        **({"swap": swap_arm} if swap_arm else {}),
        "compiles_post_warmup": compiles_post,
        "max_wait_ms": args.max_wait_ms,
        "platform": platform,
        # host-side provenance on CPU: real walls on this box, but NOT
        # chip numbers — those ride the r7 queue's serve_latency job
        "measured": True,
        "host_side": not on_accel,
        "chip_measured": on_accel,
    }
    if compiles_post != 0:
        record["measured"] = False
        record["compile_inconsistency"] = (
            f"{compiles_post} backend compile(s) during steady-state "
            "traffic — the AOT-bucket contract is broken; latencies "
            "include compile walls and are not evidence")
    if swap_arm is not None and swap_arm["dropped"] != 0:
        record["measured"] = False
        record["swap_inconsistency"] = (
            f"{swap_arm['dropped']} ticket(s) unresolved across the "
            "hot swap — the zero-dropped drain contract is broken")
    print(json.dumps(record))
    if args.bank:
        from sparknet_tpu.common import bank_guard

        bank_guard(LAST_PATH, record, measured=record["measured"])
    if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
            and not record["measured"]):
        return 4
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
