"""Survival-modeled evidence scheduling for the TPU window runner.

SparkNet's pitch was extracting useful work from unreliable workers;
this repo's equivalent scarce, flaky resource is the axon relay, whose
healthy windows last 5-30 minutes and whose wedges last hours
(CLAUDE.md "TPU tunnel protocol").  Seven rounds of journaled history
(``docs/evidence_r*/journal.jsonl``) record every dial, window death,
and job outcome — enough data to stop scheduling by folklore
("cheap-first, traces-last") and start scheduling by model:

* **Window survival** — a Kaplan-Meier product-limit fit over window
  lifetimes (healthy ``dial_end`` -> the ``job_end`` that killed the
  window).  Windows still healthy when the queue drained or the runner
  stopped are right-CENSORED, not dropped — censoring is most of the
  r4 data and ignoring it would bias lifetimes short.
* **Heal times** — the same estimator over dead-dial streaks (first
  dead dial -> the next healthy ``dial_end``); a trailing streak with
  no heal is censored.  Seeds the capped-exponential redial backoff.
* **Job runtimes** — per-name (then per-tool) medians of journaled
  successful runs, refreshed mid-round as jobs finish early/late;
  queue-declared ``est_runtime_s`` fills the gap for never-run jobs.

The policy itself is one line: pick the runnable job maximizing
``value x P(survive est_runtime | window age)`` — expected evidence
value banked before the wedge.  Hard constraints stay hard: traces go
last (2-for-2 correlated with wedges in r1/r3), and predicted-OOM jobs
never reach the candidate set (the runner's memcheck pre-flight
refuses them before any dial, collapsing the model's OOM-risk term to
a hard gate).

Deliberately stdlib-only, like ``analysis/mem_model`` and
``obs/schema``: the window runner imports this while babysitting a
wedged relay, so nothing here may initialize a backend.  Offline
verification lives in ``tools/sched_sim.py`` (fault-injected replay of
the journal histories — zero chip time); docs/SCHEDULING.md is the
narrative.

CLI (inspection only):
    python tools/window_policy.py            # fit + summary JSON
    python tools/window_policy.py j1.jsonl   # fit named journals
"""

from __future__ import annotations

import calendar
import glob
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone invocation: tools/ is not a package
    sys.path.insert(0, REPO)

from sparknet_tpu.obs import schema  # noqa: E402  (stdlib-only by contract)

# journal wall-stamp format (schema._UTC_FMT is private; the format is
# frozen by seven rounds of banked history and restated here)
_UTC_FMT = "%Y-%m-%d %H:%M:%SZ"

# survival below this is "the window is already gone" — conditional
# probabilities divide by it, so it doubles as the division floor
_EPS = 1e-9

# redial backoff rails: never below the runner's anti-hot-spin floor,
# base capped so the FIRST deferred dial never waits longer than a dead
# dial would, total capped at 30 min (a heal can land any time — the
# backoff exists to stop burning 25-min dead dials seconds apart, not
# to stop dialing)
BACKOFF_FLOOR_S = 120.0
BACKOFF_BASE_CAP_S = 900.0
BACKOFF_CAP_S = 1800.0

# with zero journaled heals (fresh repo), assume the observed r4/r5
# shape: wedges are hours-scale, so the backoff base lands mid-rail
DEFAULT_HEAL_MEDIAN_S = 6000.0

# Every banked heal so far straddles a runner restart (the operator
# restarted the runner when the relay healed: r4 probes 29/40, r5
# probe 16 all land seconds after a runner_start).  A restart whose
# offline gap is under this bound continues the same wedge — censoring
# there would discard every observed heal; a longer gap means the box
# was genuinely offline, so the streak closes censored.
RESTART_BRIDGE_S = 7200.0


def default_history_paths(repo: str = REPO) -> list[str]:
    """Every banked runner journal, oldest round first."""
    return sorted(glob.glob(
        os.path.join(repo, "docs", "evidence_r*", "journal.jsonl")))


def _ts(ev: dict) -> float | None:
    """Journal wall stamp -> epoch seconds (None when absent/torn)."""
    utc = ev.get("utc")
    if not isinstance(utc, str):
        return None
    try:
        return float(calendar.timegm(time.strptime(utc, _UTC_FMT)))
    except ValueError:
        return None


def job_tool(argv: list) -> str:
    """The tool a queue job runs: the first ``*.py`` basename, or the
    module named by ``-m`` — the runtime model's fallback pool when a
    job NAME has no history (e.g. a fresh A/B arm of a known bench)."""
    toks = [str(a) for a in argv]
    for i, tok in enumerate(toks):
        if tok == "-m" and i + 1 < len(toks):
            return toks[i + 1]
        if tok.endswith(".py"):
            return os.path.basename(tok)
    return toks[0] if toks else "?"


def is_trace_job(job: dict) -> bool:
    """Traces go LAST — the one folklore rule the policy keeps as a
    hard constraint (2-for-2 correlated with wedges in r1/r3; the
    ``queue-job-hygiene`` lint rule enforces the same ordering on the
    static queue)."""
    argv = [str(a) for a in job.get("argv", [])]
    return "--trace" in argv or str(job.get("name", "")).startswith("trace")


class KaplanMeier:
    """Product-limit survival estimator over right-censored durations.

    ``durations[i]`` is a window lifetime (or heal time) in seconds;
    ``observed[i]`` True when the death/heal was actually seen, False
    when the observation was cut short (queue drained, runner stopped).
    Beyond the last observation the curve is extrapolated with the
    curve's own average hazard (exponential tail) so conditional
    survival keeps decaying instead of flat-lining at the last step —
    a policy that believes windows become immortal past the observed
    support would happily start a 20-minute trace at minute 29.
    """

    def __init__(self, durations: list[float], observed: list[bool]):
        pairs = sorted(zip(durations, observed))
        self.n = len(pairs)
        self.events = sum(1 for _, obs in pairs if obs)
        self.steps: list[tuple[float, float]] = []  # (t, S(t)) at deaths
        at_risk = self.n
        s = 1.0
        i = 0
        while i < self.n:
            t = pairs[i][0]
            deaths = 0
            j = i
            while j < self.n and pairs[j][0] == t:
                deaths += int(pairs[j][1])
                j += 1
            if deaths and at_risk > 0:
                s *= 1.0 - deaths / at_risk
                self.steps.append((t, max(s, 0.0)))
            at_risk -= j - i
            i = j
        self.t_max = pairs[-1][0] if pairs else 0.0
        s_end = self.steps[-1][1] if self.steps else 1.0
        # average hazard over the observed support; 0 when the curve
        # never dropped (censored-only data — no basis for a rate)
        if self.t_max > 0 and s_end < 1.0:
            self._tail_rate = -math.log(max(s_end, _EPS)) / self.t_max
        else:
            self._tail_rate = 0.0

    def survival(self, t: float) -> float:
        """S(t): probability of lasting at least ``t`` seconds."""
        if t <= 0:
            return 1.0
        s = 1.0
        for step_t, step_s in self.steps:
            if step_t <= t:
                s = step_s
            else:
                break
        if t > self.t_max and self._tail_rate > 0:
            s = min(s, max(self.steps[-1][1] if self.steps else 1.0,
                           _EPS)) * math.exp(
                -self._tail_rate * (t - self.t_max))
        return max(s, 0.0)

    def conditional(self, age: float, dt: float) -> float:
        """P(survive ``age + dt`` | survived ``age``) — the policy's
        "will this job outlive the wedge" term."""
        base = self.survival(age)
        if base <= _EPS:
            return 0.0
        return min(self.survival(age + dt) / base, 1.0)

    def quantile(self, q: float) -> float:
        """Smallest t with S(t) <= 1 - q (e.g. q=0.5 -> median)."""
        target = 1.0 - q
        for step_t, step_s in self.steps:
            if step_s <= target:
                return step_t
        if self._tail_rate > 0:
            s_end = max(self.steps[-1][1] if self.steps else 1.0, _EPS)
            if target < s_end:
                return self.t_max + math.log(s_end / max(target, _EPS)) \
                    / self._tail_rate
        return self.t_max  # censored-only curve: best available bound

    def sample(self, u: float) -> float:
        """Inverse-transform draw: the duration whose survival equals
        ``u`` (pass ``rng.random()``); capped at 4x the observed
        support so censored-heavy curves cannot return infinities."""
        u = min(max(u, _EPS), 1.0)
        return min(self.quantile(1.0 - u),
                   max(self.t_max, 1.0) * 4.0)

    def to_dict(self) -> dict:
        return {"n": self.n, "events": self.events,
                "median_s": round(self.quantile(0.5), 1),
                "steps": [[round(t, 1), round(s, 4)]
                          for t, s in self.steps]}


class RuntimeModel:
    """Expected job runtime from journaled outcomes, by name then tool.

    Lookup order (docs/SCHEDULING.md "Runtime model"): the job NAME's
    own successful history (median — robust to the one 1204 s rc-4
    outlier in r4), else the queue-declared ``est_runtime_s`` policy
    field, else the TOOL's history pooled across job names, else half
    the deadline (the runner's only prior).  ``observe`` feeds the
    current round back in, so mid-window re-planning sees a job that
    just ran 3x its estimate."""

    def __init__(self) -> None:
        self.by_name: dict[str, list[float]] = {}
        self.by_tool: dict[str, list[float]] = {}

    def observe(self, name: str, tool: str, dt_s: float,
                rc: object) -> None:
        if rc == 0 and dt_s > 0:
            self.by_name.setdefault(name, []).append(float(dt_s))
            self.by_tool.setdefault(tool, []).append(float(dt_s))

    @staticmethod
    def _median(xs: list[float]) -> float:
        ys = sorted(xs)
        mid = len(ys) // 2
        return ys[mid] if len(ys) % 2 else 0.5 * (ys[mid - 1] + ys[mid])

    def estimate(self, job: dict) -> float:
        name = str(job.get("name", "?"))
        if self.by_name.get(name):
            return self._median(self.by_name[name])
        declared = job.get("est_runtime_s")
        if isinstance(declared, (int, float)) and declared > 0:
            return float(declared)
        tool = job_tool(job.get("argv", []))
        if self.by_tool.get(tool):
            return self._median(self.by_tool[tool])
        return 0.5 * float(job.get("deadline_s", 1200))


class History:
    """One journal's survival observations: censored window lifetimes,
    censored heal times, and per-job run outcomes."""

    def __init__(self) -> None:
        self.windows: list[tuple[float, bool]] = []
        self.heals: list[tuple[float, bool]] = []
        self.runs: list[tuple[str, str, float, object, bool]] = []
        # ordered replay trace for the simulator: dicts with kind
        # "dead" (wedge time) or "window" (healthy lifetime + whether
        # the death was observed)
        self.trace: list[dict] = []


def parse_history(events: list[dict]) -> History:
    """Walk one runner journal into survival observations.

    Window lifetime runs from the healthy ``dial_end`` to the
    ``job_end`` that carries the death (``timed_out`` / rc-None /
    ``window_death``); a window still open at the next ``dial_start``,
    ``runner_start``, or end-of-journal closes CENSORED at its last
    activity.  Heal time runs from the first dead dial of a streak to
    the next healthy ``dial_end``; a ``runner_start`` BRIDGES the
    streak when the offline gap is under :data:`RESTART_BRIDGE_S` (the
    wedge did not heal just because the runner restarted — every
    observed heal in r4/r5 straddles a restart) and censors it on a
    longer gap (wall time across a genuinely offline stretch would
    inflate heals).  Setup jobs never touch windows (they run before
    any dial)."""
    h = History()
    window_open: float | None = None     # healthy dial_end ts
    last_activity: float | None = None   # last ts inside the window
    streak_start: float | None = None    # first dead dial's dial_start
    last_dial_start: float | None = None
    prev_ts: float | None = None         # last stamped event seen
    argv_by_job: dict[str, list] = {}

    def close_window(end: float | None, observed: bool) -> None:
        nonlocal window_open, last_activity
        if window_open is None:
            return
        end = end if end is not None else last_activity
        if end is not None and end >= window_open:
            h.windows.append((end - window_open, observed))
            h.trace.append({"kind": "window", "dur": end - window_open,
                            "observed": observed})
        window_open, last_activity = None, None

    def close_streak(end: float | None, observed: bool) -> None:
        nonlocal streak_start
        if streak_start is None:
            return
        if end is not None and end >= streak_start:
            h.heals.append((end - streak_start, observed))
            h.trace.append({"kind": "dead", "dur": end - streak_start})
        streak_start = None

    for ev in events:
        kind = ev.get("event")
        ts = _ts(ev)
        if kind == "runner_start":
            close_window(None, False)
            if streak_start is not None:
                gap = (ts - prev_ts if ts is not None
                       and prev_ts is not None else None)
                if gap is None or gap > RESTART_BRIDGE_S:
                    close_streak(prev_ts, False)
        elif kind == "dial_start":
            close_window(None, False)
            last_dial_start = ts
        elif kind == "dial_end":
            if ev.get("ok"):
                close_streak(ts, True)
                window_open = ts
                last_activity = ts
            elif streak_start is None:
                streak_start = (last_dial_start if last_dial_start
                                is not None else ts)
        elif kind == "job_start":
            if not ev.get("setup"):
                argv_by_job[str(ev.get("job", "?"))] = \
                    ev.get("argv") or []
        elif kind == "job_end":
            if ev.get("setup"):
                continue
            name = str(ev.get("job", "?"))
            rc = ev.get("rc")
            dead = (rc is None or bool(ev.get("timed_out"))
                    or bool(ev.get("window_death")))
            h.runs.append((name, job_tool(argv_by_job.get(name, [])),
                           float(ev.get("dt_s", 0) or 0), rc, dead))
            if window_open is not None and ts is not None:
                last_activity = ts
                if dead:
                    close_window(ts, True)
                    streak_start = ts  # the wedge starts at the death
        if ts is not None:
            prev_ts = ts
    close_window(None, False)
    close_streak(prev_ts, False)
    return h


class SurvivalScheduler:
    """The ``--policy survival`` brain: fitted curves + the picker.

    Everything the runner journals about a decision comes from
    :meth:`pick`'s decision dict, already shaped for the ``sched``
    obsnet event (``schema.EVENTS``)."""

    POLICY = "survival"

    def __init__(self, window_km: KaplanMeier, heal_km: KaplanMeier,
                 runtime: RuntimeModel, sources: list[str]):
        self.window_km = window_km
        self.heal_km = heal_km
        self.runtime = runtime
        self.sources = sources

    # -- fitting ---------------------------------------------------------

    @classmethod
    def fit(cls, journal_paths: list[str] | None = None
            ) -> "SurvivalScheduler":
        paths = (default_history_paths() if journal_paths is None
                 else list(journal_paths))
        wd: list[float] = []
        wo: list[bool] = []
        hd: list[float] = []
        ho: list[bool] = []
        runtime = RuntimeModel()
        used: list[str] = []
        for path in paths:
            events = schema.load_journal(path)
            if not events:
                continue
            h = parse_history(events)
            used.append(path)
            for dur, obs in h.windows:
                wd.append(dur)
                wo.append(obs)
            for dur, obs in h.heals:
                hd.append(dur)
                ho.append(obs)
            for name, tool, dt_s, rc, _dead in h.runs:
                runtime.observe(name, tool, dt_s, rc)
        return cls(KaplanMeier(wd, wo), KaplanMeier(hd, ho), runtime,
                   used)

    # -- the policy ------------------------------------------------------

    def p_survive(self, age_s: float, runtime_s: float) -> float:
        return self.window_km.conditional(age_s, runtime_s)

    def score_job(self, job: dict, age_s: float,
                  oom_risk: float = 0.0) -> dict:
        """One candidate's decision record: value x P(survive runtime |
        window age) x (1 - oom_risk).  The runner's memcheck pre-flight
        refuses predicted-OOM jobs before the candidate set forms, so
        its ``oom_risk`` is a hard {0, 1} collapsed upstream; the term
        stays explicit for the simulator and any softer future gate."""
        est = self.runtime.estimate(job)
        p = self.p_survive(age_s, est)
        value = float(job.get("value", 1.0))
        return {
            "job": str(job.get("name", "?")),
            "window_age_s": round(age_s, 1),
            "est_runtime_s": round(est, 1),
            "p_survive": round(p, 4),
            "value": value,
            "score": round(value * p * (1.0 - oom_risk), 4),
        }

    def pick(self, jobs: list[dict], age_s: float
             ) -> tuple[dict | None, dict | None]:
        """The next job to spend window time on, plus its journalable
        decision.  Among runnable candidates: traces are only eligible
        once no non-trace candidate remains (hard constraint), then
        argmax score, ties to the CHEAPER estimate (a tie in expected
        value should not gamble more window), then queue order."""
        if not jobs:
            return None, None
        pool = [j for j in jobs if not is_trace_job(j)] or list(jobs)
        best = None
        best_key = None
        best_decision = None
        for idx, job in enumerate(pool):
            d = self.score_job(job, age_s)
            key = (-d["score"], d["est_runtime_s"], idx)
            if best_key is None or key < best_key:
                best, best_key, best_decision = job, key, d
        best_decision["policy"] = self.POLICY
        best_decision["candidates"] = len(jobs)
        return best, best_decision

    def observe(self, job: dict, dt_s: float, rc: object) -> None:
        """Fold a just-finished run back into the runtime model — the
        mid-window re-planning input (a job that ran 3x its estimate
        re-prices every subsequent pick this window)."""
        self.runtime.observe(str(job.get("name", "?")),
                             job_tool(job.get("argv", [])), dt_s, rc)

    # -- redial backoff --------------------------------------------------

    @property
    def heal_median_s(self) -> float:
        if self.heal_km.events:
            return self.heal_km.quantile(0.5)
        return DEFAULT_HEAL_MEDIAN_S

    def redial_delay(self, consecutive_dead: int) -> float:
        """Capped exponential backoff between dials while the relay is
        wedged, seeded from the fitted heal-time distribution: base =
        heal_median / 32 clamped to [120 s, 900 s], doubled per
        consecutive death signal, capped at 30 min.  A dead dial's own
        ~1505 s self-fail already paces the early streak (the runner
        subtracts elapsed time), so the exponential only starts adding
        real sleep once the streak says the wedge is hours-long."""
        base = min(max(self.heal_median_s / 32.0, BACKOFF_FLOOR_S),
                   BACKOFF_BASE_CAP_S)
        return min(base * (2.0 ** max(consecutive_dead - 1, 0)),
                   BACKOFF_CAP_S)

    # -- provenance ------------------------------------------------------

    def describe(self) -> dict:
        """Fit summary for the ``sched`` fit event and the simulator's
        banked record."""
        return {
            "windows": self.window_km.n,
            "window_deaths": self.window_km.events,
            "median_window_s": round(self.window_km.quantile(0.5), 1),
            "heals": self.heal_km.n,
            "heals_observed": self.heal_km.events,
            "heal_median_s": round(self.heal_median_s, 1),
            "sources": [os.path.relpath(p, REPO) if os.path.isabs(p)
                        else p for p in self.sources],
        }


def main() -> int:
    paths = sys.argv[1:] or default_history_paths()
    sched = SurvivalScheduler.fit(paths)
    out = sched.describe()
    out["window_km"] = sched.window_km.to_dict()
    out["heal_km"] = sched.heal_km.to_dict()
    out["runtime_names"] = {
        name: round(RuntimeModel._median(runs), 1)
        for name, runs in sorted(sched.runtime.by_name.items())}
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
