"""Fault-injected replay gate for the survival scheduling policy.

The policy's correctness claim ("never bank less evidence than the
static cheap-first order; strictly more when the relay is wedge-heavy")
is verifiable with ZERO chip time: replay the r8 queue through every
banked journal history (``docs/evidence_r*/journal.jsonl``) and through
seeded synthetic histories whose wedges and dead-dials are drawn from
the fitted Kaplan-Meier curves themselves, and compare total banked
evidence value under both orders.

Replay model (docs/SCHEDULING.md "The replay gate"):

* A history is the sequence of dead stretches and healthy windows that
  ``window_policy.parse_history`` extracts from a journal (real
  histories), or that inverse-transform sampling from the fitted
  window/heal curves generates (synthetic; ``--seed`` pins the draw).
  Wedge-heavy synthetic histories sample windows from the short-lived
  half of the survival curve and get few of them — the regime the
  policy exists for.
* Inside a window, both arms face the same physics: a job's true
  runtime is the runtime model's estimate times a deterministic
  per-(history, window, job) jitter in [0.85, 1.25) — estimate error
  is simulated, and identical for both arms so selection order is the
  ONLY degree of freedom.  A job that overruns the window dies with
  the window (a timeout, not a failed attempt — the runner's own
  ledger rule), the rest of the window is lost, and the next window
  starts fresh.  Completed jobs bank their declared ``value``.
* The static arm drains in queue order (cheap-first, the r3-r7
  protocol); the survival arm calls ``SurvivalScheduler.pick`` with
  the live window age, exactly the code path the runner runs under
  ``--policy survival``.  Job-level rc failures are not modeled (both
  arms would retry identically; window survival is the contested
  resource).

The gate: policy total >= static total on EVERY history, and strictly
greater on at least one wedge-heavy one.  ``--bank`` writes the full
per-history table to ``docs/sched_sim_last.json`` through bank_guard —
host-side, chip-free, deterministic under its banked seed.  Exit 1 on
any gate miss (the r8 queue runs this as a setup job, so a regressed
policy refuses to schedule a round with itself).

Usage:
    python tools/sched_sim.py [--seed 801] [--queue tools/tpu_queue_r8.json]
                              [--bank]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS)
for p in (REPO, TOOLS):
    if p not in sys.path:  # tools/ is not a package
        sys.path.insert(0, p)

import window_policy as wp  # noqa: E402

DEFAULT_QUEUE = os.path.join(TOOLS, "tpu_queue_r8.json")
LAST_PATH = os.path.join(REPO, "docs", "sched_sim_last.json")

# synthetic-history shape: a normal history gets a full night of
# windows; a wedge-heavy one gets few and short — the r3/r5 regime
# (22 dials, 2 windows) that motivated the policy
NORMAL_WINDOWS = 6
WEDGE_WINDOWS = 3


def _jitter(seed: int, hist: str, job: str, widx: int) -> float:
    """Deterministic runtime jitter, identical across policy arms (a
    shared rng SEQUENCE would diverge the moment the arms pick in
    different orders — key the draw by coordinates instead)."""
    return random.Random(f"{seed}:{hist}:{job}:{widx}").uniform(0.85, 1.25)


def real_histories() -> list[tuple[str, list[dict]]]:
    """(name, trace) per banked journal, via the same parser the
    policy fits from."""
    from sparknet_tpu.obs import schema

    out = []
    for path in wp.default_history_paths(REPO):
        events = schema.load_journal(path)
        if not events:
            continue
        name = os.path.basename(os.path.dirname(path))
        out.append((name, wp.parse_history(events).trace))
    return out


def synth_history(model: wp.SurvivalScheduler, rng: random.Random,
                  wedge_heavy: bool) -> list[dict]:
    """Alternating dead/window segments drawn from the fitted curves by
    inverse transform.  Wedge-heavy: window draws confined to the
    short-lived u-range (u near 1 = low survival), heal draws to the
    long half."""
    trace: list[dict] = []
    n = WEDGE_WINDOWS if wedge_heavy else NORMAL_WINDOWS
    for _ in range(n):
        u_heal = rng.random()
        u_win = rng.random()
        if wedge_heavy:
            u_heal = 0.5 * u_heal            # long heals
            u_win = 0.7 + 0.3 * u_win        # short windows
        heal = (model.heal_km.sample(u_heal) if model.heal_km.events
                else wp.DEFAULT_HEAL_MEDIAN_S)
        trace.append({"kind": "dead", "dur": heal})
        trace.append({"kind": "window",
                      "dur": model.window_km.sample(u_win),
                      "observed": True})
    return trace


def replay(jobs: list[dict], trace: list[dict],
           model: wp.SurvivalScheduler, policy: str, seed: int,
           hist: str, max_attempts: int = 10,
           max_timeouts: int = 8) -> dict:
    """One arm's pass over one history.  Mirrors the runner's drain
    semantics: green jobs never re-run, a window death is a timeout
    (capped separately, never counted vs max_attempts), one shot per
    job per window, ``needs`` gates on a green dependency."""
    green: set[str] = set()
    timeouts: dict[str, int] = {}
    banked = 0.0
    windows = 0
    deaths = 0
    widx = 0
    for seg in trace:
        if seg["kind"] != "window":
            continue
        widx += 1
        windows += 1
        horizon = float(seg["dur"])
        age = 0.0
        attempted: set[str] = set()
        while True:
            cands = []
            for j in jobs:
                n = j["name"]
                if (n in green or n in attempted
                        or timeouts.get(n, 0) >= max_timeouts):
                    continue
                need = j.get("needs")
                if need and need not in green:
                    continue
                cands.append(j)
            if not cands:
                break
            if policy == "static":
                job = cands[0]
            else:
                job, _decision = model.pick(cands, age)
            name = job["name"]
            attempted.add(name)
            runtime = model.runtime.estimate(job) * _jitter(
                seed, hist, name, widx)
            if age + runtime <= horizon:
                age += runtime
                green.add(name)
                banked += float(job.get("value", 1.0))
            else:
                timeouts[name] = timeouts.get(name, 0) + 1
                deaths += 1
                break
    return {"banked_value": round(banked, 3), "jobs_banked": len(green),
            "windows": windows, "window_deaths": deaths}


def run(queue_path: str, seed: int) -> dict:
    with open(queue_path) as f:
        spec = json.load(f)
    jobs = spec["jobs"]
    model = wp.SurvivalScheduler.fit()
    histories: list[tuple[str, bool, list[dict]]] = [
        (name, False, trace) for name, trace in real_histories()]
    rng = random.Random(seed)
    for k in range(3):
        histories.append((f"synth_{k}", False,
                          synth_history(model, rng, wedge_heavy=False)))
    for k in range(3):
        histories.append((f"synth_wedge_{k}", True,
                          synth_history(model, rng, wedge_heavy=True)))

    rows = []
    for name, wedge_heavy, trace in histories:
        static = replay(jobs, trace, model, "static", seed, name)
        surv = replay(jobs, trace, model, "survival", seed, name)
        rows.append({
            "history": name,
            "wedge_heavy": wedge_heavy,
            "windows": static["windows"],
            "static_value": static["banked_value"],
            "policy_value": surv["banked_value"],
            "static_jobs": static["jobs_banked"],
            "policy_jobs": surv["jobs_banked"],
            "delta": round(surv["banked_value"]
                           - static["banked_value"], 3),
        })
    never_worse = all(r["policy_value"] >= r["static_value"]
                      for r in rows)
    strictly = any(r["wedge_heavy"]
                   and r["policy_value"] > r["static_value"]
                   for r in rows)
    return {
        "tool": "sched_sim",
        "queue": os.path.relpath(queue_path, REPO),
        "seed": seed,
        "model": model.describe(),
        "histories": rows,
        "policy_never_worse": never_worse,
        "strictly_better_on_wedge_heavy": strictly,
        "ok": never_worse and strictly,
        # chip-free by construction: a deterministic replay of banked
        # journal histories — "measured" in the feed_bench host_side
        # sense (real evidence, no accelerator in the loop)
        "measured": True,
        "host_side": True,
        "chip_free": True,
        "provenance": "offline replay of docs/evidence_r*/journal.jsonl"
                      " + seeded KM-sampled fault injection",
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--queue", default=DEFAULT_QUEUE)
    ap.add_argument("--seed", type=int, default=801)
    ap.add_argument("--bank", action="store_true",
                    help=f"bank the record to {LAST_PATH}")
    args = ap.parse_args()
    record = run(args.queue, args.seed)
    print(json.dumps(record, indent=1))
    # The measured-or-die queue contract (round-5 learning; rc 4 =
    # window death to the runner).  This gate is host-side evidence by
    # construction, so the record is always measured — but the knob is
    # honored explicitly so a future unmeasured arm can never slip a
    # rehearsal into the bank under an armed queue job.
    if (os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1"
            and not record["measured"]):
        return 4
    if args.bank:
        # lazy: common imports jax; the gate itself must stay runnable
        # on a box where only stdlib is healthy
        from sparknet_tpu.common import bank_guard

        bank_guard(LAST_PATH, record, measured=record["measured"])
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
