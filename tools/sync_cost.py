"""Quantify the τ-round model-averaging sync cost vs model size.

The reference's sync round is a Spark star topology: every worker
serializes the full `WeightCollection` to the driver, the driver
tree-reduces and broadcasts back (~2 directions × model bytes × workers,
through JNA float-by-float copies — ref: src/main/scala/libs/Net.scala:131-171,
CifarApp.scala:132-134, measured as the hot spot in
WeightCollectionSpec.scala:20-32).  Here the same round is ONE in-program
`lax.pmean` over the mesh: weights never leave HBM and the transport is
ICI.  This tool measures the averaging program per model and prints the
analytic ICI payload math next to it (docs/BENCHMARKS.md records the
results).

Run: python tools/sync_cost.py [--platform cpu] [--devices 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.devices}"
            ).strip()
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparknet_tpu import models
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.common import Phase
    from sparknet_tpu.parallel.mesh import data_parallel_mesh

    mesh = data_parallel_mesh(args.devices)
    p = mesh.shape["data"]
    if p < args.devices:
        raise SystemExit(
            f"only {p} device(s) visible, {args.devices} requested — on a "
            "CPU-only host pass --platform cpu so the virtual mesh flag "
            "is set before jax initializes"
        )
    spec = NamedSharding(mesh, P("data"))

    # v5e public specs for the analytic column: per-chip ICI egress
    # ~4 links x 45 GB/s; ring all-reduce moves 2*S*(p-1)/p bytes/chip.
    ICI_BW = 180e9

    rows = []
    for name, builder in (
        ("lenet", lambda: models.lenet(8)),
        ("cifar10_quick", lambda: models.cifar10_quick(8)),
        ("alexnet", lambda: models.alexnet(8, num_classes=1000)),
    ):
        net = Network(builder(), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))
        nbytes = sum(
            int(np.prod(b.shape)) * 4
            for bl in variables.params.values()
            for b in bl
        )
        stacked = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                jnp.broadcast_to(x[None], (p,) + x.shape), spec
            ),
            variables.params,
        )
        # Each dispatch consumes the previous dispatch's DEVICE-side
        # probe scalar as a perturbation of the first leaf: the calls
        # form a serial value chain (no two carry identical args, no
        # host round-trip inside the timed loop), and the probe sums
        # one element of EVERY averaged leaf so none is dead code.  The
        # 1e-6 scale is representable against ~1e-1 params (a smaller
        # epsilon would be absorbed by f32, leaving the probe constant
        # and the chain fake); the single end-of-loop fence fetches the
        # probe VALUE (relay timing traps — see common.value_fence).
        def avg_fn(t, salt):
            leaves, treedef = jax.tree_util.tree_flatten(t)
            outs = []
            for i, x in enumerate(leaves):
                if i == 0:
                    x = x + (salt * 1e-6).astype(x.dtype)
                outs.append(x.mean(0))
            probe = sum(o.ravel()[0].astype(jnp.float32) for o in outs)
            return jax.tree_util.tree_unflatten(treedef, outs), probe

        avg = jax.jit(avg_fn)
        from sparknet_tpu.common import value_fence as fence

        _, probe = avg(stacked, jnp.float32(0.0))  # warm
        fence(probe)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            _, probe = avg(stacked, probe)
        fence(probe)
        dt = (time.perf_counter() - t0) / args.iters

        analytic_ici_ms = 2 * nbytes * (p - 1) / p / ICI_BW * 1e3
        # the reference's round: 2 directions x model bytes serialized
        # through the driver per WORKER, at its measured JNA copy rate
        # (~61M floats in ~a second each way, WeightCollectionSpec)
        rows.append({
            "model": name,
            "param_mb": round(nbytes / 1e6, 1),
            "measured_avg_ms": round(dt * 1e3, 2),
            "analytic_ici_allreduce_ms": round(analytic_ici_ms, 3),
            "workers": p,
        })
        print(json.dumps(rows[-1]))

    print(json.dumps({"sync_cost_table": rows,
                      "platform": jax.devices()[0].platform}))


if __name__ == "__main__":
    main()
