"""The SparkNet tau tradeoff at AlexNet scale (VERDICT r3 item 8).

The paper's fig. 5 axis — accuracy vs synchronization cadence at a
fixed per-worker local-step budget — measured with the ACTUAL AlexNet
topology (conv stack, LRN, grouped convs, dropout; ref:
caffe/models/bvlc_alexnet/train_val.prototxt) rather than LeNet, and
with the ImageNet recipe's tau=50 cadence represented (ref:
ImageNetApp.scala:151 runs 50 local iterations between syncs).

Input scale: this box is a 1-core CPU host driving a virtual 8-device
mesh, so the spatial size is reduced (``--crop 67`` keeps every layer
shape-valid: 67 -> conv1/4 -> 15 -> pool 7 -> pool2 3 -> pool5 1) and
the data is synthetic-but-structured — 10 fixed pixel-scale class
templates + heavy noise, a task whose gradient structure (not its
semantics) is what the sync-cadence claim is about.

Run:  python tools/tau_sweep_alexnet.py [--budget 100] [--taus 1,10,50]
Writes docs/tau_sweep_alexnet.json and prints one JSON line per row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--taus", default="1,10,50")
    p.add_argument("--budget", type=int, default=100,
                   help="local steps per worker (fixed across taus)")
    p.add_argument("--crop", type=int, default=67)
    p.add_argument("--batch", type=int, default=8,
                   help="per-worker minibatch")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--noise", type=float, default=40.0,
                   help="pixel-noise sigma (templates are +-80); lower = "
                   "higher SNR so every arm escapes the softmax plateau "
                   "inside the budget")
    p.add_argument("--out", default="docs/tau_sweep_alexnet.json")
    return p.parse_args()


def make_task(classes: int, crop: int, seed: int = 0, noise: float = 40.0):
    """Fixed pixel-scale templates (+-80) + N(0, noise) pixels (the zoo
    fillers are calibrated for raw-pixel inputs — see
    .claude/skills/verify)."""
    import numpy as np

    rs = np.random.RandomState(seed)
    templates = rs.randn(classes, 3, crop, crop).astype(np.float32) * 80

    def sample(rng, n):
        y = rng.randint(0, classes, n)
        x = templates[y] + (
            rng.randn(n, 3, crop, crop).astype(np.float32) * noise)
        return x, y.astype(np.int32)

    return sample


def main() -> int:
    args = parse_args()
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from sparknet_tpu import models
    from sparknet_tpu.parallel.mesh import data_parallel_mesh
    from sparknet_tpu.parallel.trainer import ParallelTrainer
    from sparknet_tpu.solvers.solver import Solver
    from sparknet_tpu.solvers.solver import SolverConfig

    sample = make_task(args.classes, args.crop, noise=args.noise)
    eval_rs = np.random.RandomState(99)
    xte, yte = sample(eval_rs, 256)
    B = args.batch
    mesh = data_parallel_mesh()
    workers = mesh.shape["data"]

    def test_fn(b):
        return {"data": xte[b * 32:(b + 1) * 32],
                "label": yte[b * 32:(b + 1) * 32]}

    # AlexNet recipe hyperparameters, shortened schedule (ref:
    # caffe/models/bvlc_alexnet/solver.prototxt -- step policy, momentum
    # 0.9, weight_decay 5e-4); base_lr tuned down only if it diverges at
    # this reduced spatial scale.
    cfg = SolverConfig(base_lr=args.lr, lr_policy="fixed", momentum=0.9,
                       weight_decay=5e-4, solver_type="SGD")

    rows = []
    for tau in (int(t) for t in args.taus.split(",")):
        rounds = args.budget // tau
        if rounds == 0:
            # never bank a row for an arm that trained zero steps (the
            # previous arm's loss would leak into it)
            print(json.dumps({"tau_row_skipped": {
                "tau": tau,
                "reason": f"budget {args.budget} < tau {tau}",
            }}), flush=True)
            continue
        net = models.alexnet(B if tau > 1 else B * workers,
                             num_classes=args.classes, crop=args.crop)
        solver = Solver(cfg, net)
        trainer = ParallelTrainer(solver, mesh=mesh, tau=tau)
        rng = np.random.RandomState(7)

        def data_fn(it):
            if tau == 1:
                x, y = sample(rng, B * workers)
                return {"data": x, "label": y}
            stack_x, stack_y = [], []
            for _ in range(tau):
                x, y = sample(rng, B * workers)
                stack_x.append(x)
                stack_y.append(y)
            return {"data": np.stack(stack_x), "label": np.stack(stack_y)}

        t0 = time.time()
        for _ in range(rounds):
            loss = trainer.train_round(data_fn)
        wall = time.time() - t0
        acc = trainer.test(8, test_fn)["accuracy"]
        row = {
            "tau": tau,
            "sync_rounds": rounds,
            "local_steps_per_worker": rounds * tau,
            "test_accuracy": round(float(acc), 4),
            "final_loss": round(float(loss), 4),
            "seconds": round(wall, 1),
        }
        rows.append(row)
        print(json.dumps({"tau_row": row}), flush=True)

    out = {
        "model": "alexnet", "crop": args.crop, "workers": workers,
        "per_worker_batch": B, "budget": args.budget,
        "recipe": "bvlc_alexnet solver (fixed lr variant)",
        "noise_sigma": args.noise,
        "rows": rows,
        "utc": time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime()),
    }
    out_path = args.out
    if not os.path.isabs(out_path):
        # bank relative outputs under the repo root regardless of cwd —
        # a multi-hour sweep must not lose its evidence to a wrong cwd
        out_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            out_path)
    rc = 0
    try:
        with open(out_path + ".tmp", "w") as f:
            json.dump(out, f, indent=1)
        os.replace(out_path + ".tmp", out_path)
    except OSError as e:
        print(f"tau_sweep: could not write {out_path}: {e}", file=sys.stderr)
        rc = 1
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
