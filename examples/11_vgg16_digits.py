"""VGG-16 on real pixels: the compute-roofline family learns.

The zoo's second post-reference model family (``zoo:vgg16`` — Simonyan &
Zisserman config D, the Caffe model-zoo VGG_ILSVRC_16_layers wiring)
trained on sklearn's bundled handwritten digits, the same real-pixel
corpus examples/05 and /10 use, upscaled 8->64 so the five 2x2/2 pools
leave a 2x2x512 pool5 map (crop 32 collapses it to 1x1 before the fc
tail and the corpus tops out ~78%; 64 matches examples/10 and crosses
the bar).

Two things this demonstrates that the other families don't:

- **The init footgun is real and the knob fixes it.** The published
  train_val init (gaussian std 0.01) shrinks activations ~1e-5 by
  conv5_3 — config D famously never trained from scratch; the paper
  bootstrapped it from config A, and He et al. 2015 derived msra filling
  from exactly this failure.  ``zoo.vgg16(msra_init=True)`` is the
  from-scratch recipe; the default stays faithful to the zoo file for
  finetune-from-caffemodel parity.
- **Unit-scale data for msra nets.** The raw-pixel scale the gauss-0.01
  zoo recipes need (mean-subtracted 0..255) is exactly wrong for a
  variance-preserving init — it propagates a ~90-std signal into the
  lr-sensitive fc tail (the round-4 CPU drive diverged on it).  The
  msra path wants unit-ish inputs, so this example feeds digits/8-0.5.

Run:

    python examples/11_vgg16_digits.py [--steps 120]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing check: few steps, finiteness instead "
                    "of the accuracy bar (CI; the full run is the "
                    "convergence evidence)")
    args = ap.parse_args()
    if args.smoke:
        args.steps, args.batch = min(args.steps, 3), min(args.batch, 4)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    crop = 64  # five 2x2/2 pools: 64 -> 2x2 pool5
    xtr, ytr, xte, yte = load_digits_dataset(upscale=crop)
    # grayscale -> 3-channel, UNIT scale (digits are 0..16): msra wants
    # variance ~1, not the raw-pixel scale the gauss-0.01 recipes need
    prep = lambda x: np.repeat(x, 3, axis=1) / 8.0 - 0.5
    xtr, xte = prep(xtr), prep(xte)

    # Adam for the short schedule: the published SGD recipe's lr ladder
    # assumes ImageNet-scale epochs; on 1.4k digits Adam 2e-4 crosses
    # 90% test accuracy inside the default 250 steps (1e-4/120 reached
    # only 74% — the 16.8M-param fc tail wants the longer schedule)
    cfg = dataclasses.replace(
        zoo.vgg16_solver(),
        base_lr=2e-4, solver_type="Adam", momentum=0.9, momentum2=0.999,
        lr_policy="fixed", weight_decay=0.0,
        max_iter=args.steps, display=10,
    )
    solver = Solver(cfg, zoo.vgg16(
        batch=args.batch, num_classes=10, crop=crop, msra_init=True))

    train_fn = minibatch_fn(xtr, ytr, args.batch, seed=0)

    def test_fn(b):
        idx = np.arange(b * args.batch, (b + 1) * args.batch) % len(yte)
        return {"data": xte[idx], "label": yte[idx]}

    n_test = 2 if args.smoke else max(1, len(yte) // args.batch)

    before = solver.test(n_test, test_fn)
    print(f"untrained: {before}")
    solver.step(args.steps, train_fn)
    after = solver.test(n_test, test_fn)
    print(f"after {args.steps} steps: {after}")
    if args.smoke:
        ok = bool(np.isfinite(after["loss"]))
        print("PASS (smoke: finite)" if ok else "FAIL (loss not finite)")
    else:
        ok = after["accuracy"] >= 0.90
        print("PASS" if ok else "FAIL (expected >=0.90)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
