"""Fine-tuning from a donor snapshot — the reference's 03-fine-tuning
notebook (ref: caffe/examples/03-fine-tuning.ipynb +
finetune_flickr_style/): train a donor model, transplant its trunk into
a net with a NEW head (different num_output), and show the finetuned
model converges faster than from scratch.

Run:  python examples/03_fine_tuning.py  [--platform cpu]
"""

import sys
import tempfile

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu import models
from sparknet_tpu.compiler.graph import NetVars
from sparknet_tpu.net import TPUNet, copy_caffemodel_params
from sparknet_tpu.proto.text_format import Message


def batches(num_classes, batch=32, seed=0):
    """Class-banded data at MNIST's trained scale; the finetune task is
    the donor task restricted to 3 classes, so trunk features transfer —
    the point of the notebook."""
    rs = np.random.RandomState(seed)
    while True:
        y = rs.randint(0, num_classes, batch)
        # the LeNet recipe expects 1/256-scaled inputs (the reference
        # prototxt's scale: 0.00390625) — feed [0,1]-scale data
        x = rs.randn(batch, 1, 28, 28).astype(np.float32) * 0.15
        for i, k in enumerate(y):
            x[i, 0, 2 * k : 2 * k + 2, :] += 0.5
        yield {"data": x, "label": y.astype(np.int32)}


def retarget_head(net_param, num_classes):
    """New final-layer width AND name, so the donor's head is skipped
    (the notebook renames fc8 -> fc8_flickr for the same reason)."""
    for lp in net_param.get_all("layer"):
        if lp.get_str("name") == "ip2":
            lp.set("name", "ip2_task")
            lp.get_msg("inner_product_param").set("num_output", num_classes)
    return net_param


def main():
    donor = TPUNet(models.lenet_solver(), models.lenet(batch=32))
    donor.set_train_data(batches(10, seed=0))
    donor.train(150)
    with tempfile.NamedTemporaryFile(suffix=".caffemodel", delete=False) as f:
        weights = f.name
    donor.save_caffemodel(weights)

    tuned = TPUNet(models.lenet_solver(), retarget_head(models.lenet(batch=32), 3))
    params, loaded = copy_caffemodel_params(
        tuned.solver.variables.params, weights, strict_shapes=False
    )
    tuned.solver.variables = NetVars(params=params, state=tuned.solver.variables.state)
    print("layers transplanted:", loaded)  # trunk only; ip2_task stays fresh

    scratch = TPUNet(models.lenet_solver(), retarget_head(models.lenet(batch=32), 3))
    results = {}
    for name, net in (("finetuned", tuned), ("scratch", scratch)):
        net.set_train_data(batches(3, seed=2))
        net.set_test_data(batches(3, seed=3), length=5)
        net.train(30)
        results[name] = net.test()
        print(name, results[name])
    # transfer shows up as much faster convergence in the same budget
    assert results["finetuned"]["loss"] < results["scratch"]["loss"]
    return 0


if __name__ == "__main__":
    sys.exit(main())
