"""The DB-backed data path across all three backends — the reference's
create-then-train flow (ref: caffe/examples/cifar10/create_cifar10.sh +
train_full.sh: convert binaries into a LevelDB, compute the mean, train
the prototxt whose Data layers read the DB; and
src/main/scala/apps/CifarDBApp.scala for the SparkNet variant).

Materializes a tiny synthetic dataset into each backend (native record
DB, LMDB, LevelDB — the latter two byte-compatible with Caffe's own),
trains the same Data-layer prototxt from each via ``--data proto``
semantics, and converts between formats.

Run:  python examples/08_db_backends.py  [--platform cpu]
"""

import os
import sys
import tempfile

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu.data.createdb import convert_db, create_db, db_minibatches
from sparknet_tpu.data.leveldb_io import is_leveldb
from sparknet_tpu.data.lmdb_io import is_lmdb
from sparknet_tpu.net import TPUNet
from sparknet_tpu.proto import parse
from sparknet_tpu.solvers.solver import SolverConfig

NET = """
name: "dbnet"
layer {{ name: "d" type: "Data" top: "data" top: "label"
  data_param {{ source: "{source}" batch_size: 16 }}
  transform_param {{ mean_value: 84 }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param {{ num_output: 10
    weight_filler {{ type: "gaussian" std: 0.001 }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
  top: "loss" }}
layer {{ name: "acc" type: "Accuracy" bottom: "ip" bottom: "label"
  top: "accuracy" include {{ phase: TEST }} }}
"""


def synthetic_samples(n=160, seed=0):
    """Class-separable uint8 images: class k carries a bright row band."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        k = i % 10
        img = rs.randint(0, 60, (3, 12, 12)).astype(np.uint8)
        img[:, k : k + 2, :] += 180
        out.append((img, k))
    return out


def train_from_db(path, iters=60):
    """Data-layer prototxt + its own DB source = the caffe-train flow."""
    from sparknet_tpu.data.listfile import source_from_net

    net_param = parse(NET.format(source=path))
    net = TPUNet(SolverConfig(base_lr=0.001, momentum=0.9), net_param)
    train_src = source_from_net(net.train_net, seed=1)
    eval_src = source_from_net(net.test_net, seed=2)
    net.set_train_data(train_src)
    net.set_test_data(eval_src, length=3)
    net.train(iters)
    return net.test()


def main():
    workdir = tempfile.mkdtemp(prefix="db_backends_")
    os.chdir(workdir)
    samples = synthetic_samples()

    results = {}
    for backend, check in (
        ("record", os.path.exists),
        ("lmdb", is_lmdb),
        ("leveldb", is_leveldb),
    ):
        path = f"train_{backend}"
        n = create_db(path, samples, backend=backend)
        assert n == len(samples) and check(path)
        scores = train_from_db(path)
        results[backend] = scores["accuracy"]
        print(f"{backend:8s}: {n} records, accuracy {scores['accuracy']:.2f}")

    # every backend fed identical records: training trajectories agree
    accs = list(results.values())
    assert max(accs) - min(accs) < 0.35, results
    assert max(accs) > 0.5, f"nothing learned: {results}"

    # cross-format conversion keeps records byte-identical
    convert_db("train_leveldb", "roundtrip_lmdb", backend="lmdb")
    a = next(db_minibatches("train_leveldb", 8))
    b = next(db_minibatches("roundtrip_lmdb", 8))
    np.testing.assert_array_equal(a["data"], b["data"])
    np.testing.assert_array_equal(a["label"], b["label"])
    print("leveldb -> lmdb conversion: records identical")
    print("OK")


if __name__ == "__main__":
    main()
