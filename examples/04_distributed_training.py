"""Distributed training walkthrough — the SparkNet algorithm, TPU-native.

No reference notebook covers this (the reference's distribution lives in
its Spark apps); this example shows the three sync modes of
`ParallelTrainer` on a device mesh and compares them on one task:

  1. tau=1   — fully-synchronous data parallelism (gradient all-reduce
               every step; the P2PSync analog),
  2. tau=5   — the SparkNet algorithm: 5 local SGD steps per worker,
               then model averaging (the paper's communication-reduction
               knob, ref: CifarApp.scala:119 tau=10),
  3. EASGD   — elastic coupling to a center variable (the reference's
               unrealized roadmap item).

Runs on any mesh: real TPU chips, or a virtual 8-device CPU mesh via
--platform cpu (XLA_FLAGS=--xla_force_host_platform_device_count=8).

--smoke is the plumbing check (CI): all three trainers compile and run
a couple of rounds each, gated on finiteness instead of the accuracy
bar — the full run is the convergence evidence (~10 min on a 1-core
box; the smoke arm fits the tier-1 deadline).
"""

import os
import sys

if "--platform" in sys.argv:
    platform = sys.argv[sys.argv.index("--platform") + 1]
    if platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    from sparknet_tpu.common import force_platform

    force_platform(platform)

import numpy as np


def make_batch(rs, batch):
    """Pixel-scale class-banded CIFAR-shaped task."""
    y = rs.randint(0, 10, batch)
    x = (rs.randn(batch, 3, 32, 32) * 40).astype(np.float32)
    for i, k in enumerate(y):
        x[i, k % 3, (k // 3) * 3 : (k // 3) * 3 + 3, :] += 80.0
    return {"data": x, "label": y.astype(np.int32)}


def main():
    import jax

    from sparknet_tpu import models
    from sparknet_tpu.parallel.trainer import ParallelTrainer
    from sparknet_tpu.solvers.solver import Solver

    smoke = "--smoke" in sys.argv
    n = len(jax.devices())
    per_worker = 8
    global_batch = per_worker * n
    rounds = 2 if smoke else 30
    n_test = 1 if smoke else 5
    print(f"mesh: {n} devices; global batch {global_batch}"
          + (" (smoke)" if smoke else ""))

    def solver(batch):
        return Solver(models.cifar10_quick_solver(), models.cifar10_quick(batch))

    results = {}

    # 1. Fully-synchronous DP: one batch per round, grads psum'd in-step.
    rs = np.random.RandomState(0)
    sync = ParallelTrainer(solver(global_batch), tau=1)
    for _ in range(rounds * 5):  # same optimizer-step budget as tau=5
        loss = sync.train_round(lambda it: make_batch(rs, global_batch))
    results["sync tau=1"] = sync.test(
        n_test, lambda b: make_batch(rs, global_batch)
    )

    # 2. The SparkNet algorithm: tau local steps, then average.  Feeds
    #    carry a [tau, B_global, ...] axis — tau batches per round.
    rs = np.random.RandomState(0)
    tau = 5
    spark = ParallelTrainer(solver(per_worker), tau=tau)

    def tau_feeds(it):
        bs = [make_batch(rs, global_batch) for _ in range(tau)]
        return {k: np.stack([b[k] for b in bs]) for k in bs[0]}

    for _ in range(rounds):
        loss = spark.train_round(tau_feeds)
    results[f"tau={tau} averaging"] = spark.test(
        n_test, lambda b: make_batch(rs, global_batch)
    )

    # 3. EASGD: same feed contract, elastic center instead of averaging.
    rs = np.random.RandomState(0)
    easgd = ParallelTrainer(
        solver(per_worker), tau=tau, elastic_alpha=0.9 / n
    )
    for _ in range(rounds):
        loss = easgd.train_round(tau_feeds)
    results["easgd"] = easgd.test(
        n_test, lambda b: make_batch(rs, global_batch)
    )

    del loss
    for name, scores in results.items():
        print(f"{name:18s} accuracy={scores['accuracy']:.3f} "
              f"loss={scores['loss']:.4f}")
        if smoke:
            assert np.isfinite(scores["loss"]), (name, scores)
        else:
            assert scores["accuracy"] > 0.5, (name, scores)
    if smoke:
        print("PASS (smoke: all three sync modes ran, losses finite)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
