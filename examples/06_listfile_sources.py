"""Listfile data sources end to end — the reference's ImageData and
WindowData training flows (ref: models/finetune_flickr_style/
train_val.prototxt sources ImageData from a "<path> <label>" list;
examples/finetune_pascal_detection/ sources WindowData from an R-CNN
window file), at miniature scale on generated images.

Writes a tiny on-disk dataset, then:
1. trains a conv net whose prototxt sources ImageData (the host reader
   handles decode/resize/shuffle/crop/mirror — the layer itself is just
   a feed declaration in-graph);
2. samples fg/bg R-CNN windows through WindowDataSource and trains a
   tiny window classifier.

The CLI equivalent of part 1 is:
    tpunet train --solver solver.prototxt --data proto

Run:  python examples/06_listfile_sources.py  [--platform cpu]
"""

import os
import sys
import tempfile

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu.data.listfile import WindowDataSource, source_from_net
from sparknet_tpu.proto import parse
from sparknet_tpu.solvers.solver import Solver, SolverConfig


def write_dataset(root: str, n: int = 24, classes: int = 3):
    """Tiny PNG dataset: class k gets a bright band in channel k."""
    from PIL import Image

    rs = np.random.RandomState(0)
    lines = []
    os.makedirs(os.path.join(root, "imgs"), exist_ok=True)
    for i in range(n):
        label = i % classes
        arr = (rs.randn(16, 16, 3) * 20 + 110).clip(0, 255).astype(np.uint8)
        arr[:, :, label] = np.clip(arr[:, :, label] + 90, 0, 255)
        Image.fromarray(arr).save(os.path.join(root, "imgs", f"i{i}.png"))
        lines.append(f"i{i}.png {label}")
    list_path = os.path.join(root, "list.txt")
    with open(list_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return list_path


def part1_imagedata(root: str, list_path: str):
    npz = parse(
        'name: "flickr_mini" '
        'layer { name: "d" type: "ImageData" top: "data" top: "label" '
        f'image_data_param {{ source: "{list_path}" '
        f'root_folder: "{root}/imgs/" batch_size: 8 '
        "new_height: 14 new_width: 14 shuffle: true } "
        "transform_param { crop_size: 12 mirror: true mean_value: 110 "
        "scale: 0.02 } } "
        'layer { name: "conv" type: "Convolution" bottom: "data" top: "conv" '
        "convolution_param { num_output: 8 kernel_size: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" } '
        'layer { name: "ip" type: "InnerProduct" bottom: "conv" top: "ip" '
        "inner_product_param { num_output: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }'
    )
    solver = Solver(SolverConfig(base_lr=0.05, momentum=0.9, max_iter=40), npz)
    src = source_from_net(solver.train_net)  # reads the layer's own params
    step, variables, slots, key = solver.jitted_train_step()
    first = last = None
    for i in range(40):
        variables, slots, loss = step(variables, slots, i, src(i), key)
        if i == 0:
            first = float(np.asarray(loss))
    last = float(np.asarray(loss))
    print(f"[imagedata] loss {first:.3f} -> {last:.3f}")
    assert last < first


def part2_windowdata(root: str):
    """R-CNN window sampling: fg windows cover the class band, bg windows
    miss it; the window head learns fg-vs-bg."""
    from PIL import Image

    rs = np.random.RandomState(1)
    win_lines = []
    for i in range(6):
        arr = (rs.randn(24, 24, 3) * 15 + 100).clip(0, 255).astype(np.uint8)
        arr[6:18, 6:18] = 220  # the "object"
        path = os.path.join(root, "imgs", f"w{i}.png")
        Image.fromarray(arr).save(path)
        win_lines += [f"# {i}", path, "3 24 24", "3",
                      "1 0.9 6 6 17 17",    # fg: on the object
                      "0 0.1 0 0 6 6",      # bg: corner
                      "0 0.2 16 16 23 23"]  # bg: other corner
    win_path = os.path.join(root, "windows.txt")
    with open(win_path, "w") as f:
        f.write("\n".join(win_lines) + "\n")

    lp = parse(
        'layer { name: "w" type: "WindowData" top: "data" top: "label" '
        f'window_data_param {{ source: "{win_path}" batch_size: 16 '
        "fg_threshold: 0.5 bg_threshold: 0.5 fg_fraction: 0.5 "
        'context_pad: 2 crop_mode: "warp" } '
        "transform_param { crop_size: 12 mirror: true mean_value: 100 } }"
    ).get_all("layer")[0]
    src = WindowDataSource(lp, train=True, seed=0)
    b = src(0)
    n_fg = int((b["label"] > 0).sum())
    print(f"[windowdata] batch of {len(b['label'])}: {n_fg} fg / "
          f"{len(b['label']) - n_fg} bg windows, crop {b['data'].shape[2:]}")
    assert n_fg == 8  # fg_fraction 0.5 of 16


def main():
    with tempfile.TemporaryDirectory() as root:
        list_path = write_dataset(root)
        part1_imagedata(root, list_path)
        part2_windowdata(root)
    print("listfile sources example OK")


if __name__ == "__main__":
    main()
