"""MobileNet v1 on real pixels: the depthwise family learns + folds.

The zoo's fourth post-reference family (`zoo:mobilenet` — MobileNet v1
1.0x, 4,231,976 params) on the same real-digit corpus as
examples/05/10/11/12/13.  Two things this walkthrough demonstrates:

- the depthwise-separable stack (13 blocks of group==channels 3x3 +
  1x1 pointwise, each with BatchNorm/Scale) trains end to end through
  the standard solver path — BN makes it schedule-tolerant where the
  BN-free families needed init/optimizer care;
- the FULL deploy pipeline on the depthwise family: after training,
  all 27 Conv+BN+Scale chains fold (`merge_bn`) and the folded net
  scores identically — the same flow `tpunet classify --fold-bn`
  ships, pinned here on a trained net rather than a fixture.

Run:

    python examples/14_mobilenet_digits.py [--steps 350]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=350)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing check: few steps, finiteness instead "
                    "of the accuracy bar (CI; the full run is the "
                    "convergence evidence)")
    args = ap.parse_args()
    if args.smoke:
        args.steps, args.batch = min(args.steps, 2), min(args.batch, 4)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    crop = 64
    xtr, ytr, xte, yte = load_digits_dataset(upscale=crop)
    prep = lambda x: np.repeat(x, 3, axis=1) / 8.0 - 0.5  # noqa: E731
    xtr, xte = prep(xtr), prep(xte)

    # bn_fraction 0.9 so eval statistics track a short schedule (the
    # recipe 0.999 assumes thousands of iterations — zoo.resnet50 note)
    cfg = dataclasses.replace(
        zoo.mobilenet_solver(),
        base_lr=0.01, lr_policy="fixed", weight_decay=0.0,
        max_iter=args.steps, display=25,
    )
    solver = Solver(cfg, zoo.mobilenet(
        batch=args.batch, num_classes=10, crop=crop, bn_fraction=0.9))

    train_fn = minibatch_fn(xtr, ytr, args.batch, seed=0)

    def test_fn(b):
        idx = np.arange(b * args.batch, (b + 1) * args.batch) % len(yte)
        return {"data": xte[idx], "label": yte[idx]}

    n_test = 1 if args.smoke else max(1, len(yte) // args.batch)

    before = solver.test(n_test, test_fn)
    print(f"untrained: {before}")
    solver.step(args.steps, train_fn)
    after = solver.test(n_test, test_fn)
    print(f"after {args.steps} steps: {after}")

    # deploy leg: fold all 27 BN chains, verify identical scoring
    import jax.numpy as jnp

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import NetVars, Network
    from sparknet_tpu.models.fold_bn import fold_batchnorm

    net_param = solver.train_net.net_param
    net2, params2, state2, folded = fold_batchnorm(
        net_param, solver.variables.params, solver.variables.state)
    print(f"folded {len(folded)} Conv+BN+Scale chains")
    feeds = test_fn(0)
    ref_net = Network(net_param, Phase.TEST)
    ref, _, _ = ref_net.apply(solver.variables,
                              {k: jnp.asarray(v) for k, v in feeds.items()},
                              rng=None, train=False)
    out_net = Network(net2, Phase.TEST)
    out, _, _ = out_net.apply(NetVars(params=params2, state=state2),
                              {k: jnp.asarray(v) for k, v in feeds.items()},
                              rng=None, train=False)
    fold_ok = bool(np.allclose(np.asarray(out["flat7"]),
                               np.asarray(ref["flat7"]),
                               rtol=2e-4, atol=2e-4))
    print(f"folded net scores identically: {fold_ok}")

    if args.smoke:
        ok = bool(np.isfinite(after["loss"])) and len(folded) == 27
        print("PASS (smoke: finite + 27 folds)" if ok else "FAIL")
    else:
        ok = after["accuracy"] >= 0.90 and len(folded) == 27 and fold_ok
        print("PASS" if ok else
              f"FAIL (top-1 {after['accuracy']:.3f}, folds {len(folded)}, "
              f"fold_ok {fold_ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
