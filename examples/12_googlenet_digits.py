"""GoogLeNet on real pixels: both auxiliary towers train end-to-end.

The last zoo family without a convergence demonstration (LeNet 98.4%,
ResNet-50 94.3%, VGG-16 95.2% — docs/CONVERGENCE.md).  GoogLeNet is the
compiler stress test (9 inception blocks, a 3-way DAG per block) and the
one net whose TRAINING semantics include weighted auxiliary losses: the
published recipe sums loss3 + 0.3*loss1 + 0.3*loss2 from two mid-network
classifier towers (ref: caffe/models/bvlc_googlenet/train_val.prototxt
loss_weight 0.3 at the loss1/loss and loss2/loss heads).  This
walkthrough shows all three heads learning together on sklearn's bundled
handwritten digits — the same real-pixel corpus examples/05/10/11 use —
upscaled 8->96 so every published kernel stays shape-valid (96 is the
smallest multiple of 32 that keeps the aux towers' 5x5/3 average pools
alive; pool5 is sized crop/32, the published 7x7 == 224/32 global-avg
intent).

What the run demonstrates:

- top-1 >= 90% on held-out digits within the default 400 steps at
  batch 32 (measured 94.3%, SGD 0.01 momentum 0.9 — the published
  optimizer family; Adam at any lr sits at chance here, see the recipe
  comment in main());
- BOTH aux losses decrease alongside the main head — the 0.3-weighted
  gradient paths through inception_4a/4d are live, which is exactly the
  semantic `caffe train` exercises and a forward-only check cannot.

Run:

    python examples/12_googlenet_digits.py [--steps 400]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing check: few steps, finiteness instead "
                    "of the accuracy bar (CI; the full run is the "
                    "convergence evidence)")
    args = ap.parse_args()
    if args.smoke:
        args.steps, args.batch = min(args.steps, 2), min(args.batch, 2)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    crop = 96  # smallest 32-multiple keeping the aux 5x5/3 pools valid
    xtr, ytr, xte, yte = load_digits_dataset(upscale=crop)
    # grayscale -> 3-channel at unit-ish scale: the zoo's xavier fillers
    # are variance-preserving, same reasoning as examples/11's msra path
    prep = lambda x: np.repeat(x, 3, axis=1) / 8.0 - 0.5  # noqa: E731
    xtr, xte = prep(xtr), prep(xte)

    # The PUBLISHED optimizer family (SGD momentum 0.9, ref:
    # bvlc_googlenet/quick_solver.prototxt), fixed lr for the short
    # schedule.  Adam variants (1e-3..1e-4) sit at chance here: its
    # uniform absolute step is ~1%/step RELATIVE against the
    # xavier-scale weights — the net is randomized faster than the
    # 22-layer credit assignment can integrate, while SGD's
    # gradient-proportional steps train cleanly (measured round 5).
    # Dropout ratios stay the published 0.7/0.7/0.4.
    cfg = dataclasses.replace(
        zoo.googlenet_solver(),
        base_lr=0.01, solver_type="SGD", momentum=0.9,
        lr_policy="fixed", weight_decay=0.0,
        max_iter=args.steps, display=25,
    )
    solver = Solver(cfg, zoo.googlenet(
        batch=args.batch, num_classes=10, crop=crop))

    train_fn = minibatch_fn(xtr, ytr, args.batch, seed=0)

    def test_fn(b):
        idx = np.arange(b * args.batch, (b + 1) * args.batch) % len(yte)
        return {"data": xte[idx], "label": yte[idx]}

    n_test = 1 if args.smoke else max(1, len(yte) // args.batch)

    before = solver.test(n_test, test_fn)
    print(f"untrained: {before}")
    solver.step(args.steps, train_fn)
    after = solver.test(n_test, test_fn)
    print(f"after {args.steps} steps: {after}")

    def head_losses(scores):
        """The three softmax losses by their prototxt names."""
        return {k: v for k, v in scores.items() if k.endswith("loss" )
                or "/loss" in k}

    print("aux/main losses:",
          {k: (round(before[k], 3), round(after[k], 3))
           for k in sorted(head_losses(after))})
    acc_key = ("loss3/top-1" if "loss3/top-1" in after
               else next(k for k in after if "top-1" in k))
    if args.smoke:
        ok = bool(np.isfinite(after["loss3/loss3"]))
        print("PASS (smoke: finite)" if ok else "FAIL (loss not finite)")
    else:
        aux_down = all(after[k] < before[k] for k in head_losses(after))
        ok = after[acc_key] >= 0.90 and aux_down
        print("PASS" if ok else
              f"FAIL (top-1 {after[acc_key]:.3f}, aux_down={aux_down})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
