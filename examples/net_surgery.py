"""Casting an InnerProduct classifier to a Convolution — the reference's
net_surgery notebook (ref: caffe/examples/net_surgery.ipynb +
net_surgery/bvlc_caffenet_full_conv.prototxt): reshape fc weights into
1x1-or-larger conv kernels so the net runs on larger inputs and emits a
score MAP instead of a single prediction.

Run:  python examples/net_surgery.py  [--platform cpu]
"""

import sys

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

import jax.numpy as jnp

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu.proto import parse

FC_NET = """
name: "tiny_fc"
input: "data" input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
        convolution_param { num_output: 4 kernel_size: 3 stride: 1
          weight_filler { type: "xavier" } } }
layer { name: "fc" type: "InnerProduct" bottom: "conv" top: "fc"
        inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }
"""

CONV_NET = """
name: "tiny_full_conv"
input: "data" input_shape { dim: 1 dim: 3 dim: 12 dim: 12 }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
        convolution_param { num_output: 4 kernel_size: 3 stride: 1
          weight_filler { type: "xavier" } } }
layer { name: "fc_conv" type: "Convolution" bottom: "conv" top: "fc_conv"
        convolution_param { num_output: 5 kernel_size: 6
          weight_filler { type: "xavier" } } }
"""


def main():
    import jax as _jax

    fc_net = Network(parse(FC_NET), Phase.TEST)
    fc_vars = fc_net.init(_jax.random.PRNGKey(0))

    conv_net = Network(parse(CONV_NET), Phase.TEST)
    conv_vars = conv_net.init(_jax.random.PRNGKey(1))

    # the surgery: fc (5, 4*6*6) -> conv kernel (5, 4, 6, 6)
    w_fc, b_fc = fc_vars.params["fc"]
    conv_vars.params["conv"][:] = fc_vars.params["conv"]
    conv_vars.params["fc_conv"][:] = [w_fc.reshape(5, 4, 6, 6), b_fc]

    rs = np.random.RandomState(0)
    small = rs.randn(1, 3, 8, 8).astype(np.float32)
    big = np.zeros((1, 3, 12, 12), np.float32)
    big[:, :, :8, :8] = small  # the small input sits in the corner

    fc_out, _, _ = fc_net.apply(fc_vars, {"data": jnp.asarray(small)}, rng=None)
    conv_out, _, _ = conv_net.apply(conv_vars, {"data": jnp.asarray(big)}, rng=None)

    # corner of the score map == the fc net's prediction
    map_scores = np.asarray(conv_out["fc_conv"])[0, :, 0, 0]
    fc_scores = np.asarray(fc_out["fc"])[0]
    print("fc scores:  ", fc_scores)
    print("map corner: ", map_scores)
    np.testing.assert_allclose(map_scores, fc_scores, atol=1e-4)
    print("score map shape:", np.asarray(conv_out["fc_conv"]).shape)  # (1,5,5,5)
    return 0


if __name__ == "__main__":
    sys.exit(main())
