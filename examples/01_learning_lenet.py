"""Define + train LeNet from Python — the reference's 01-learning-lenet
notebook (ref: caffe/examples/01-learning-lenet.ipynb), TPU-native.

Builds the model with the inline DSL (no prototxt file needed), trains
on a synthetic MNIST-like task, evaluates, snapshots, and reloads.

Run:  python examples/01_learning_lenet.py  [--platform cpu]
"""

import sys

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu import models
from sparknet_tpu.net import TPUNet


def batches(batch=64, seed=0):
    """Synthetic digits at MNIST's trained scale: class k lights a
    distinct row band."""
    rs = np.random.RandomState(seed)
    while True:
        y = rs.randint(0, 10, batch)
        # the LeNet recipe expects 1/256-scaled inputs (the reference
        # prototxt's scale: 0.00390625) — feed [0,1]-scale data
        x = rs.randn(batch, 1, 28, 28).astype(np.float32) * 0.15
        for i, k in enumerate(y):
            x[i, 0, 2 * k : 2 * k + 2, :] += 0.5
        yield {"data": x, "label": y.astype(np.int32)}


def main():
    net = TPUNet(models.lenet_solver(), models.lenet(batch=64))
    net.set_train_data(batches(seed=0))
    net.set_test_data(batches(seed=1), length=10)

    print("untrained:", net.test())          # ~10% = chance
    net.train(200)                            # a few seconds on one chip
    scores = net.test()
    print("trained:", scores)

    path = net.save_caffemodel("/tmp/lenet_example.caffemodel")
    print("saved:", path)

    net2 = TPUNet(models.lenet_solver(), models.lenet(batch=64))
    net2.load_caffemodel(path)
    net2.set_test_data(batches(seed=1), length=10)
    print("reloaded:", net2.test())
    assert scores["accuracy"] > 0.9
    return 0


if __name__ == "__main__":
    sys.exit(main())
