"""ResNet-50 on real pixels: the BatchNorm/residual family learns.

The zoo's first post-reference model family (``zoo:resnet50``) trained
on sklearn's bundled handwritten digits — the same real-pixel corpus the
LeNet convergence evidence uses (examples/05, docs/CONVERGENCE.md) —
upscaled 8->64 so the stride-32 trunk keeps non-degenerate stage-5 maps
(2x2 at crop 64).  Digits are grayscale; the 3-channel stem reads the
stroke replicated per channel (the standard grayscale-through-RGB-stem
trick, same spirit as examples/00's channel handling).

What this shows: BN batch statistics + residual shortcuts + the msra
init train END TO END through the framework's real solver path (SGD
momentum, weight decay, multistep lr) from chance (10%) to high test
accuracy on genuine scans.  Run:

    python examples/10_resnet50_digits.py [--steps 150]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--crop", type=int, default=64)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing check: few steps, finiteness instead "
                    "of the accuracy bar (CI; the full run is the "
                    "convergence evidence)")
    ap.add_argument("--int8", action="store_true",
                    help="after folding, also score the int8-PTQ net "
                    "(quant.py): the fold+quantize deploy pipeline on a "
                    "properly trained BN net")
    args = ap.parse_args()
    if args.smoke:
        args.steps, args.batch = min(args.steps, 4), min(args.batch, 4)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    xtr, ytr, xte, yte = load_digits_dataset(upscale=args.crop)
    # grayscale -> 3-channel stem; recipe pixel scale (digits are 0..16,
    # recipe expects mean-subtracted raw-pixel scale: x16 -> 0..256-ish)
    prep = lambda x: np.repeat(x, 3, axis=1) * 16.0 - 128.0
    xtr, xte = prep(xtr), prep(xte)

    cfg = dataclasses.replace(
        zoo.resnet50_solver(),
        base_lr=0.005,           # recipe 0.1 is tuned for batch 256
        clip_gradients=50.0,     # catch pathological tiny-batch spikes only
        stepvalue=(int(args.steps * 0.75), int(args.steps * 0.92)),
        max_iter=args.steps, display=10,
    )
    # bn_fraction 0.9: the recipe's 0.999 averages over ~1000s of
    # iterations — a short schedule needs eval stats that track training
    solver = Solver(cfg, zoo.resnet50(
        batch=args.batch, num_classes=10, crop=args.crop,
        bn_fraction=0.9))

    # the shuffled-epoch feed helper examples/05 uses
    train_fn = minibatch_fn(xtr, ytr, args.batch, seed=0)

    def test_fn(b):
        idx = np.arange(b * args.batch, (b + 1) * args.batch) % len(yte)
        return {"data": xte[idx], "label": yte[idx]}

    n_test = 2 if args.smoke else max(1, len(yte) // args.batch)

    # Untrained baseline with BATCH statistics: a never-trained BN net
    # has zero moving stats, so the TEST-phase (global-stats) path
    # legitimately explodes — chance level is only measurable the way
    # training sees the data.
    import jax.numpy as jnp

    hits = tot = 0
    for b in range(n_test):
        feed = test_fn(b)
        outs, _, _ = solver.train_net.apply(
            solver.variables,
            {k: jnp.asarray(v) for k, v in feed.items()},
            rng=jax.random.key(0), train=True)
        hits += int((np.asarray(outs["fc1000"]).argmax(1)
                     == feed["label"]).sum())
        tot += len(feed["label"])
    print(f"untrained (batch-stats) accuracy: {hits / tot:.3f}")

    solver.step(args.steps, train_fn)
    after = solver.test(n_test, test_fn)
    print(f"after {args.steps} steps: {after}")

    if args.smoke:
        ok = bool(np.isfinite(after["loss"]))
        print("PASS (smoke: finite)" if ok else "FAIL (loss not finite)")
        return 0 if ok else 1

    # Deploy-time BN folding (the merge_bn flow, models/fold_bn.py): all
    # 53 Conv+BN+Scale chains collapse into their convolutions and the
    # folded net must score what the TEST phase scored — on the REAL
    # trained statistics, not a synthetic fixture.
    from sparknet_tpu.compiler.graph import Network, NetVars
    from sparknet_tpu.common import Phase
    from sparknet_tpu.models.fold_bn import fold_batchnorm

    net2, params2, state2, folded = fold_batchnorm(
        solver.train_net.net_param, solver.variables.params,
        solver.variables.state)
    folded_net = Network(net2, Phase.TEST)
    v2 = NetVars(params=params2, state=state2)
    fwd = jax.jit(lambda v, f: folded_net.apply(
        v, f, rng=None, train=False)[0])
    hits = tot = 0
    for b in range(n_test):
        feed = test_fn(b)
        outs = fwd(v2, {k: jnp.asarray(v) for k, v in feed.items()})
        hits += int((np.asarray(outs["fc1000"]).argmax(1)
                     == feed["label"]).sum())
        tot += len(feed["label"])
    folded_acc = hits / tot
    print(f"folded ({len(folded)} BN chains merged): accuracy {folded_acc:.3f}")

    int8_ok = True
    if args.int8:
        # fold + int8 PTQ: per-tensor scales calibrated on one training
        # batch, per-channel int8 weights — the MXU deploy pipeline on a
        # net with REAL margins (quantization noise flips argmax only
        # near ties, so a well-trained net holds its accuracy)
        from sparknet_tpu import quant

        calib = {k: jnp.asarray(v) for k, v in train_fn(0).items()}
        qstate = quant.calibrate(folded_net, v2, [calib])
        qfwd = jax.jit(lambda v, f: folded_net.apply(
            v, f, rng=None, train=False)[0])
        hits = tot = 0
        with quant.quantized_inference(qstate):
            for b in range(n_test):
                feed = test_fn(b)
                outs = qfwd(v2, {k: jnp.asarray(v)
                                 for k, v in feed.items()})
                hits += int((np.asarray(outs["fc1000"]).argmax(1)
                             == feed["label"]).sum())
                tot += len(feed["label"])
        int8_acc = hits / tot
        print(f"folded + int8 PTQ: accuracy {int8_acc:.3f}")
        int8_ok = int8_acc >= 0.85

    bars = {
        "accuracy >= 0.90": after["accuracy"] >= 0.90,
        "fold parity": abs(folded_acc - after["accuracy"]) < 0.01,
    }
    if args.int8:
        bars["int8 >= 0.85"] = int8_ok
    failed = [name for name, held in bars.items() if not held]
    print("PASS" if not failed else f"FAIL ({', '.join(failed)})")
    return 0 if not failed else 1


if __name__ == "__main__":
    sys.exit(main())
