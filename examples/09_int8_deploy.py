"""int8 deploy walkthrough: classify with a quantized net.

The reference's classification example runs a float deploy net
(ref: caffe/examples/cpp_classification/classification.cpp,
00-classification.ipynb); this adds the TPU-native deploy twist — the
MXU's int8 mode doubles the v5e's matmul peak, and post-training
quantization (sparknet_tpu.quant) gets a prototxt net onto it without
retraining:

1. train LeNet on real digit pixels (the unmodified zoo recipe),
2. calibrate int8 scales on a few training batches,
3. compare float vs int8 predictions + wall time.

Run:  python examples/09_int8_deploy.py [--platform cpu]
"""

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()
    import jax

    jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from sparknet_tpu import models, quant
    from sparknet_tpu.data.digits import load_digits_dataset
    from sparknet_tpu.solvers.solver import Solver

    xtr, ytr, xte, yte = load_digits_dataset()
    xtr, xte = xtr / 16.0, xte / 16.0
    B = 64
    solver = Solver(models.lenet_solver(), models.lenet(B))
    rs = np.random.RandomState(0)
    solver.step(args.iters, lambda it: (
        lambda idx: {"data": xtr[idx], "label": ytr[idx]}
    )(rs.randint(0, len(ytr), B)))

    net, variables = solver.test_net, solver.variables
    calib = [{"data": xtr[i * B:(i + 1) * B],
              "label": ytr[i * B:(i + 1) * B]} for i in range(4)]
    qstate = quant.calibrate(net, variables, calib)

    feeds = {"data": xte[:128], "label": yte[:128]}

    def top1(fn_label, ctx):
        import contextlib

        def fwd(v, f):
            return net.apply(v, f, rng=None, train=False)[0]["ip2"]

        with ctx or contextlib.nullcontext():
            jf = jax.jit(fwd)
            # np.asarray IS the fence: it copies the VALUE of the
            # program's own output buffer (block_until_ready only proves
            # readiness, which relay backends report early — see
            # common.value_fence; graftlint fence-by-value)
            out = np.asarray(jf(variables, feeds))
            t0 = time.perf_counter()
            out = np.asarray(jf(variables, feeds))
            ms = (time.perf_counter() - t0) * 1e3
        pred = np.argmax(out, -1)
        acc = float((pred == yte[:128]).mean())
        print(json.dumps({"arm": fn_label, "accuracy": round(acc, 4),
                          "ms_per_batch": round(ms, 2)}))
        return pred

    f_pred = top1("float", None)
    q_pred = top1("int8", quant.quantized_inference(qstate))
    agree = float((f_pred == q_pred).mean())
    print(json.dumps({"top1_agreement": round(agree, 4),
                      "quantized_layers": sorted(qstate)}))


if __name__ == "__main__":
    main()
