"""Logistic regression on HDF5 features — the reference's
02-brewing-logreg notebook (ref: caffe/examples/02-brewing-logreg.ipynb
+ examples/hdf5_classification/).

Writes an HDF5 dataset, defines a logreg net whose HDF5Data layer reads
it, trains, and compares against a two-layer variant.

Run:  python examples/02_brewing_logreg.py  [--platform cpu]
"""

import sys
import tempfile

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu.data.hdf5 import hdf5_minibatches, write_hdf5_file
from sparknet_tpu.net import TPUNet
from sparknet_tpu.proto import parse
from sparknet_tpu.solvers.solver import SolverConfig

NET = """
name: "logreg"
layer {{ name: "data" type: "HDF5Data" top: "data" top: "label"
        hdf5_data_param {{ source: "{source}" batch_size: 32 }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param {{ num_output: 2 weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }}
layer {{ name: "acc" type: "Accuracy" bottom: "ip" bottom: "label" top: "acc" }}
"""


def main():
    rs = np.random.RandomState(0)
    # two gaussian blobs, linearly separable-ish (the notebook's sklearn data)
    n = 512
    y = rs.randint(0, 2, n)
    x = rs.randn(n, 4).astype(np.float32) + y[:, None] * 2.0

    with tempfile.NamedTemporaryFile(suffix=".h5", delete=False) as f:
        h5 = f.name
    write_hdf5_file(h5, {"data": x, "label": y.astype(np.float32)})

    listfile = h5 + ".txt"
    with open(listfile, "w") as f:
        f.write(h5 + "\n")

    net_param = parse(NET.format(source=listfile))
    net = TPUNet(
        SolverConfig(base_lr=0.1, momentum=0.9), net_param,
        feed_shapes={"data": (32, 4), "label": (32,)},
        feed_dtypes={"label": np.int32},
    )

    # stream minibatches from the HDF5 list file (the HDF5Data layer's
    # host-plane role), labels cast to int for the loss
    def stream():
        for b in hdf5_minibatches(listfile, 32, loop=True):
            yield {"data": b["data"], "label": b["label"].astype(np.int32)}

    net.set_train_data(stream())
    net.set_test_data(stream(), length=8)
    print("untrained:", net.test())
    net.train(150)
    scores = net.test()
    print("trained:", scores)
    assert scores["acc"] > 0.85
    return 0


if __name__ == "__main__":
    sys.exit(main())
