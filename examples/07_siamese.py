"""Siamese embedding training — the reference's siamese example
(ref: caffe/examples/siamese/: mnist_siamese_train_test.prototxt +
mnist_siamese.ipynb), TPU-native and self-contained.

Two weight-tied LeNet towers fed a stacked digit pair, trained with
ContrastiveLoss to pull genuine pairs together and push impostor pairs
apart in a 2-D embedding.  The reference builds the pair stream with
``create_mnist_siamese`` LevelDBs; here a synthetic digit task plays
MNIST, and the pair channel-stacking + similarity labels are built
in-stream (same `pair_data`/`sim` feed contract as the prototxt).

Run:  python examples/07_siamese.py  [--platform cpu]
"""

import sys

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu import models
from sparknet_tpu.net import TPUNet


def digit(rs, k):
    """28x28 synthetic digit at the LeNet input scale: class k lights a
    distinct row band over noise."""
    x = rs.randn(28, 28).astype(np.float32) * 0.15
    x[2 * k : 2 * k + 2, :] += 0.5
    return x


def pair_batches(batch=64, seed=0):
    """The reference pair stream: channel-stacked (2, 28, 28) pairs with
    sim=1 for same-class, sim=0 for different-class (half and half)."""
    rs = np.random.RandomState(seed)
    while True:
        pairs = np.empty((batch, 2, 28, 28), np.float32)
        sim = np.empty((batch,), np.int32)
        for i in range(batch):
            a = rs.randint(0, 10)
            same = rs.rand() < 0.5
            b = a if same else (a + rs.randint(1, 10)) % 10
            pairs[i, 0] = digit(rs, a)
            pairs[i, 1] = digit(rs, b)
            sim[i] = int(same)
        yield {"pair_data": pairs, "sim": sim}


def embed_distances(net, batches_fn, n_batches=5):
    """Mean embedding distance for genuine vs impostor pairs using the
    trained net's forward pass (feat / feat_p tops)."""
    gen, imp = [], []
    it = batches_fn()
    for _ in range(n_batches):
        feed = next(it)
        outs = net.forward(feed)
        d = np.linalg.norm(
            np.asarray(outs["feat"]) - np.asarray(outs["feat_p"]), axis=1
        )
        sim = feed["sim"]
        gen.extend(d[sim == 1])
        imp.extend(d[sim == 0])
    return float(np.mean(gen)), float(np.mean(imp))


def main():
    net = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(batch=64))
    net.set_train_data(pair_batches(seed=0))

    d_gen0, d_imp0 = embed_distances(net, lambda: pair_batches(seed=1))
    print(f"untrained distances: genuine {d_gen0:.3f}  impostor {d_imp0:.3f}")

    net.train(300)

    d_gen, d_imp = embed_distances(net, lambda: pair_batches(seed=1))
    print(f"trained distances:   genuine {d_gen:.3f}  impostor {d_imp:.3f}")

    # contrastive training must separate the pair populations; margin=1
    assert d_imp > d_gen * 2, (d_gen, d_imp)
    assert d_imp > 0.5, d_imp
    print("OK: embedding separates genuine from impostor pairs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
