"""Classifying images with a trained net — the reference's
00-classification notebook (ref: caffe/examples/00-classification.ipynb),
TPU-native and self-contained.

The notebook downloads CaffeNet weights and classifies a cat through
``caffe.Classifier`` (deploy prototxt + .caffemodel, 10-crop oversample).
Zero-egress equivalent: train cifar10_quick on a synthetic 10-class
image task, snapshot a ``.caffemodel``, then load it back through
:class:`sparknet_tpu.models.classifier.Classifier` — same deploy-time
surface (deploy prototxt with net-level inputs, Transformer
preprocessing, center-crop vs 10-crop oversampled prediction).

Run:  python examples/00_classification.py  [--platform cpu]
"""

import sys

import numpy as np

if "--platform" in sys.argv:
    import jax

    jax.config.update("jax_platforms", sys.argv[sys.argv.index("--platform") + 1])

from sparknet_tpu import models
from sparknet_tpu.models.classifier import Classifier
from sparknet_tpu.net import TPUNet
from sparknet_tpu.proto import parse

# Deploy variant of cifar10_quick: net-level inputs + Softmax head, layer
# names matching the train net so the caffemodel params map by name (the
# notebook's deploy.prototxt plays this role for CaffeNet).
DEPLOY = """
name: "CIFAR10_quick_deploy"
input: "data"
input_dim: 10 input_dim: 3 input_dim: 32 input_dim: 32
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 32 kernel_size: 5 pad: 2 } }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "relu1" type: "ReLU" bottom: "pool1" top: "pool1" }
layer { name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
  convolution_param { num_output: 32 kernel_size: 5 pad: 2 } }
layer { name: "relu2" type: "ReLU" bottom: "conv2" top: "conv2" }
layer { name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
  pooling_param { pool: AVE kernel_size: 3 stride: 2 } }
layer { name: "conv3" type: "Convolution" bottom: "pool2" top: "conv3"
  convolution_param { num_output: 64 kernel_size: 5 pad: 2 } }
layer { name: "relu3" type: "ReLU" bottom: "conv3" top: "conv3" }
layer { name: "pool3" type: "Pooling" bottom: "conv3" top: "pool3"
  pooling_param { pool: AVE kernel_size: 3 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool3" top: "ip1"
  inner_product_param { num_output: 64 } }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 10 } }
layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
"""


def make_images(n, seed):
    """(H, W, C) float images at raw-pixel scale: class k brightens one
    8x8 block (see the fillers' raw-pixel calibration in the zoo)."""
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, n)
    x = rs.randn(n, 32, 32, 3).astype(np.float32) * 40.0
    for i, k in enumerate(y):
        x[i, (k % 4) * 8 : (k % 4) * 8 + 8, (k // 4) * 8 : (k // 4) * 8 + 8, :] += 80.0
    return x, y


def train_batches(batch=100, seed=0):
    while True:
        seed += 1
        x, y = make_images(batch, seed)
        yield {"data": x.transpose(0, 3, 1, 2), "label": y.astype(np.int32)}


def main():
    # -- train + snapshot (the notebook's "download pretrained weights") --
    net = TPUNet(models.cifar10_quick_solver(), models.cifar10_quick(batch=100))
    net.set_train_data(train_batches())
    net.train(150)
    path = net.save_caffemodel("/tmp/cifar10_quick_example.caffemodel")
    print("snapshotted:", path)

    # -- deploy-time classification, pycaffe Classifier surface --
    clf = Classifier(parse(DEPLOY), pretrained_file=path)
    images, labels = make_images(50, seed=999)

    center = clf.predict(list(images), oversample=False)
    ten_crop = clf.predict(list(images), oversample=True)
    for name, probs in (("center-crop", center), ("10-crop", ten_crop)):
        assert probs.shape == (50, 10)
        assert np.allclose(probs.sum(1), 1.0, atol=1e-3)  # softmax rows
        acc = float((probs.argmax(1) == labels).mean())
        print(f"{name} accuracy on held-out images: {acc:.2f}")
        assert acc > 0.5, f"deploy-time {name} accuracy stuck at {acc}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
