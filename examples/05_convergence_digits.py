"""Real-pixel convergence evidence + the SparkNet tau tradeoff.

The reference's canonical checks train on real MNIST/CIFAR bytes with
published accuracy targets (ref: src/test/scala/libs/CifarSpec.scala:10-94;
caffe/examples/mnist lenet ~99%; caffe/examples/cifar10 quick ~75%).
This environment has zero egress and no MNIST/CIFAR files on disk, so the
strongest real-pixel substitute is sklearn's bundled handwritten digits
(1,797 genuine 8x8 scans — `sparknet_tpu.data.digits`): the unmodified
zoo LeNet reaches >=98% test accuracy on them in a few hundred
iterations.  docs/CONVERGENCE.md records the mapping to the reference
targets and the measured numbers.

Part 2 reproduces the SparkNet paper's core tradeoff qualitatively on
the virtual 8-device mesh: at a fixed local-step budget, higher tau
(fewer synchronizations) trades a little accuracy for fewer
communication rounds (paper: https://arxiv.org/abs/1511.06051, fig. 5 —
tau tolerates slow networks).

Run:  python examples/05_convergence_digits.py [--platform cpu]
      [--iters 400] [--taus 1,5,10]
"""

import argparse
import json
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default="cpu",
                   help="jax platform (cpu = virtual 8-device mesh)")
    p.add_argument("--iters", type=int, default=400,
                   help="single-chip training iterations")
    p.add_argument("--taus", default="1,5,10",
                   help="comma-separated tau values for the mesh table")
    p.add_argument("--tau-iters", type=int, default=200,
                   help="per-worker local-step budget for the tau table")
    p.add_argument("--batch", type=int, default=64)
    return p.parse_args()


def main():
    args = parse_args()
    import os

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    jax.config.update("jax_platforms", args.platform)

    from sparknet_tpu import models
    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.parallel.mesh import data_parallel_mesh
    from sparknet_tpu.parallel.trainer import ParallelTrainer
    from sparknet_tpu.solvers.solver import Solver

    xtr, ytr, xte, yte = load_digits_dataset()
    # lenet's recipe expects [0,1]-scaled inputs (the MNIST prototxt data
    # layer applies scale 1/256); digits pixels are 0..16
    xtr, xte = xtr / 16.0, xte / 16.0
    B = args.batch
    nb_test = len(yte) // B

    def test_fn(b):
        return {"data": xte[b * B : (b + 1) * B],
                "label": yte[b * B : (b + 1) * B]}

    # ---- Part 1: single-chip LeNet on real pixels ----
    solver = Solver(models.lenet_solver(), models.lenet(B))
    t0 = time.time()
    solver.step(args.iters, minibatch_fn(xtr, ytr, B, seed=0))
    acc = solver.test(nb_test, test_fn)["accuracy"]
    single = {"iters": args.iters, "test_accuracy": round(float(acc), 4),
              "seconds": round(time.time() - t0, 1)}
    print(json.dumps({"lenet_digits_single": single}))

    # ---- Part 2: the tau table on the 8-way mesh ----
    mesh = data_parallel_mesh()
    workers = mesh.shape["data"]
    rows = []
    for tau in (int(t) for t in args.taus.split(",")):
        s = Solver(models.lenet_solver(), models.lenet(B))
        trainer = ParallelTrainer(s, mesh=mesh, tau=tau)
        outer = args.tau_iters // tau
        fn = minibatch_fn(xtr, ytr, B, seed=1)

        if tau == 1:
            def data_fn(it, fn=fn, workers=workers):
                parts = [fn(it * workers + w) for w in range(workers)]
                return {k: np.concatenate([p[k] for p in parts])
                        for k in parts[0]}
        else:
            counter = [0]

            def data_fn(it, fn=fn, workers=workers, tau=tau, counter=counter):
                slots = []
                for _ in range(tau):
                    parts = []
                    for _ in range(workers):
                        parts.append(fn(counter[0]))
                        counter[0] += 1
                    slots.append({k: np.concatenate([p[k] for p in parts])
                                  for k in parts[0]})
                return {k: np.stack([s_[k] for s_ in slots])
                        for k in slots[0]}

        t0 = time.time()
        for _ in range(outer):
            trainer.train_round(data_fn)
        wall = time.time() - t0
        acc = trainer.test(nb_test, test_fn)["accuracy"]
        rows.append({
            "tau": tau,
            "sync_rounds": outer,
            "local_steps_per_worker": outer * tau,
            "test_accuracy": round(float(acc), 4),
            "seconds": round(wall, 1),
        })
        print(json.dumps({"tau_row": rows[-1]}))

    print(json.dumps({"lenet_digits_tau_table": rows, "workers": workers}))


if __name__ == "__main__":
    main()
