"""SqueezeNet v1.1 on real pixels: the deploy-efficiency family learns.

The zoo's third post-reference family (`zoo:squeezenet` — the official
forresti/SqueezeNet v1.1 Caffe wiring, 1,235,496 params) trained on
sklearn's bundled handwritten digits, the real-pixel corpus
examples/05/10/11/12 use, upscaled 8->64 (conv1/2 + three 3x3/2 pools +
a global average pool make any crop >= ~47 shape-valid).

What this demonstrates beyond the other families:

- **The xavier wiring does not train from scratch** (same class of
  finding as VGG's gauss-0.01): activation variance loses ~2.5x per
  Fire module through the ReLU stack, reaching std ~1.7e-3 by fire9 at
  unit-scale inputs.  ``zoo.squeezenet(msra_init=True)`` is the
  from-scratch recipe; the default stays faithful to the published
  prototxt for finetune-from-caffemodel parity.
- **The ReLU-before-global-pool head has a real death mode**: at lr
  0.008 the net begins learning then collapses to loss == ln(10)
  exactly and stays — one hot step drives every conv10 pre-activation
  negative, relu_conv10 clamps all logits to zero, and the gradient
  through the head is zero forever after.  lr 0.004 trains cleanly;
  measured round 5.

Run:

    python examples/13_squeezenet_digits.py [--steps 500]
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--smoke", action="store_true",
                    help="plumbing check: few steps, finiteness instead "
                    "of the accuracy bar (CI; the full run is the "
                    "convergence evidence)")
    args = ap.parse_args()
    if args.smoke:
        args.steps, args.batch = min(args.steps, 2), min(args.batch, 4)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    crop = 64
    xtr, ytr, xte, yte = load_digits_dataset(upscale=crop)
    # grayscale -> 3-channel at unit-ish scale (msra wants variance ~1)
    prep = lambda x: np.repeat(x, 3, axis=1) / 8.0 - 0.5  # noqa: E731
    xtr, xte = prep(xtr), prep(xte)

    # Fixed lr for the short schedule (the official poly decay assumes
    # ImageNet-scale epochs); 0.004 sits under the measured lr-0.008
    # head-death cliff documented above.
    cfg = dataclasses.replace(
        zoo.squeezenet_solver(),
        base_lr=0.004, lr_policy="fixed", weight_decay=0.0,
        max_iter=args.steps, display=25,
    )
    solver = Solver(cfg, zoo.squeezenet(
        batch=args.batch, num_classes=10, crop=crop, msra_init=True))

    train_fn = minibatch_fn(xtr, ytr, args.batch, seed=0)

    def test_fn(b):
        idx = np.arange(b * args.batch, (b + 1) * args.batch) % len(yte)
        return {"data": xte[idx], "label": yte[idx]}

    n_test = 1 if args.smoke else max(1, len(yte) // args.batch)

    before = solver.test(n_test, test_fn)
    print(f"untrained: {before}")
    solver.step(args.steps, train_fn)
    after = solver.test(n_test, test_fn)
    print(f"after {args.steps} steps: {after}")
    if args.smoke:
        ok = bool(np.isfinite(after["loss"]))
        print("PASS (smoke: finite)" if ok else "FAIL (loss not finite)")
    else:
        ok = after["accuracy"] >= 0.90
        print("PASS" if ok else
              f"FAIL (expected >=0.90, got {after['accuracy']:.3f})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
