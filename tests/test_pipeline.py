"""Streaming data plane (`data/pipeline.py`): ring ordering, worker
failure surfacing, shared-memory hygiene, NHWC zero-transpose wire, obs
feed telemetry, and the `_decoded_pairs` decode overlap fix.

Small shapes throughout — the smoke tier runs all of it; the throughput
gate itself lives in ``tools/feed_bench.py --pipeline`` (host-side,
banked per docs/BENCHMARKS.md "Feed").
"""

import os
import signal
import time

import numpy as np
import pytest

from sparknet_tpu.data.pipeline import (
    ArraySource,
    DataFnSource,
    FeedSpec,
    PrestagedSource,
    ProcessPipeline,
    SyntheticImageSource,
    TransformStage,
    device_feed,
)
from sparknet_tpu.data.transform import DataTransformer, TransformConfig

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def no_leaked_shm():
    """Every test must leave /dev/shm exactly as it found it — the
    unlink-on-close contract (ISSUE 6 satellite), asserted in teardown."""
    if not os.path.isdir("/dev/shm"):
        yield
        return
    before = set(os.listdir("/dev/shm"))
    yield
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = set(os.listdir("/dev/shm")) - before
        if not leaked:
            return
        time.sleep(0.1)
    assert not leaked, f"leaked shared-memory segments: {leaked}"


# ---------------------------------------------------------------- ordering


def test_delivery_is_global_order_and_deterministic():
    src = SyntheticImageSource(batch=4, shape=(3, 12, 12), seed=7)
    with ProcessPipeline(src, num_batches=6, workers=2) as pipe:
        got = [f["label"].copy() for f in pipe.batches()]
    assert len(got) == 6
    for g, labels in enumerate(got):
        np.testing.assert_array_equal(labels, src.get(0, g)["label"])


def test_skewed_workers_still_deliver_in_order():
    """The reorder-deadlock shape: one worker much slower than the
    other, more batches than ring slots — per-worker slot ownership
    must keep the stream both live and ordered."""

    def skew(it):
        time.sleep(0.04 if it % 2 == 0 else 0.0)
        return {"x": np.full(2, it, np.float32)}

    with ProcessPipeline(DataFnSource(skew), num_batches=16,
                         workers=2) as pipe:
        vals = [int(f["x"][0]) for f in pipe.batches()]
    assert vals == list(range(16))


def test_transform_runs_in_workers():
    src = SyntheticImageSource(batch=4, shape=(3, 12, 12), seed=1)
    stage = TransformStage(TransformConfig(crop_size=8, mirror=True,
                                           seed=2), train=True)
    with ProcessPipeline(src, stage, num_batches=3, workers=1) as pipe:
        for feeds in pipe.batches():
            assert feeds["data"].shape == (4, 3, 8, 8)
            assert feeds["data"].dtype == np.float32
            assert feeds["label"].dtype == np.int32


def test_epoch_assignment_walks_array_source():
    arrays = {"data": np.arange(24, dtype=np.float32).reshape(12, 2),
              "label": np.arange(12, dtype=np.int32)}
    src = ArraySource(arrays, batch=4)  # 3 batches/epoch
    assert src.batches_per_epoch == 3
    with ProcessPipeline(src, num_batches=7, workers=2) as pipe:
        firsts = [int(f["label"][0]) for f in pipe.batches()]
    # epochs wrap deterministically: batches 0,4,8 | 0,4,8 | 0
    assert firsts == [0, 4, 8, 0, 4, 8, 0]


def test_spec_mismatch_is_a_worker_error():
    state = {"n": 0}

    def fn(it):
        return {"x": np.zeros(3 if it == 2 else 2, np.float32)}

    with ProcessPipeline(DataFnSource(fn), num_batches=4,
                         workers=1) as pipe:
        with pytest.raises(RuntimeError, match="FeedSpec"):
            list(pipe.batches())


# ---------------------------------------------------------------- failure


def test_worker_exception_surfaces_promptly():
    def fn(it):
        if it == 2:
            raise ValueError("decode exploded")
        return {"x": np.zeros(2, np.float32)}

    t0 = time.monotonic()
    with ProcessPipeline(DataFnSource(fn), num_batches=8,
                         workers=2) as pipe:
        with pytest.raises(RuntimeError, match="decode exploded"):
            list(pipe.batches())
    assert time.monotonic() - t0 < 30.0  # promptly, not a hang


def test_silent_worker_death_detected():
    slow = DataFnSource(
        lambda it: (time.sleep(0.1), {"x": np.zeros(2, np.float32)})[1])
    pipe = ProcessPipeline(slow, num_batches=50, workers=1)
    try:
        it = pipe.batches()
        next(it)
        os.kill(pipe._procs[0].pid, signal.SIGKILL)
        with pytest.raises(RuntimeError, match="died with exitcode"):
            for _ in it:
                pass
    finally:
        pipe.close()


def test_respawn_completes_stream_with_correct_contents():
    """Opt-in bounded respawn (ISSUE 8 satellite): a SIGKILLed worker's
    shard is deterministically re-owned by a replacement and the stream
    still delivers every batch, in order, with the exact bytes the
    source defines for each global id."""
    src = SyntheticImageSource(batch=4, shape=(3, 12, 12), seed=7)
    N = 24
    with ProcessPipeline(src, num_batches=N, workers=2,
                         max_respawns=2) as pipe:
        it = pipe.batches()
        got = [{k: np.array(v) for k, v in next(it).items()}
               for _ in range(4)]
        os.kill(pipe._procs[0].pid, signal.SIGKILL)
        got += [{k: np.array(v) for k, v in next(it).items()}
                for _ in range(N - 4)]
        assert pipe._respawns_used == 1
    for g, feeds in enumerate(got):
        ref = src.get(0, g)
        for k in ref:
            np.testing.assert_array_equal(feeds[k], ref[k])


def test_respawn_budget_zero_keeps_raising():
    """Default FeedSpec.max_respawns == 0 preserves the PR 6 contract:
    the first death raises (test_silent_worker_death_detected pins the
    silent-kill arm; this pins that respawn never engages unasked)."""
    assert FeedSpec.from_arrays({"x": np.zeros(2, np.float32)}
                                ).max_respawns == 0
    # a spec carrying a policy still EQUALS one probed from arrays:
    # max_respawns is policy, not geometry (compare=False)
    a = FeedSpec.from_arrays({"x": np.zeros(2, np.float32)})
    b = FeedSpec(a.fields, max_respawns=3)
    assert a == b


def test_respawn_exhausted_budget_raises():
    """A deterministically-raising source kills its replacement too:
    the bounded budget drains and the original error surfaces."""
    def fn(it):
        if it == 2:
            raise ValueError("decode exploded")
        return {"x": np.zeros(2, np.float32)}

    with ProcessPipeline(DataFnSource(fn), num_batches=8, workers=2,
                         max_respawns=1) as pipe:
        with pytest.raises(RuntimeError, match="decode exploded"):
            list(pipe.batches())
        assert pipe._respawns_used == 1


def test_respawn_journals_feed_stall_event(tmp_path):
    """Every absorbed death lands in the obs journal as a ``feed``
    stall event naming the worker and the re-owned shard start."""
    from sparknet_tpu.obs import schema
    from sparknet_tpu.obs.recorder import Recorder, set_recorder

    out = str(tmp_path / "feed.jsonl")
    set_recorder(Recorder(out))
    try:
        src = SyntheticImageSource(batch=2, shape=(3, 8, 8), seed=3)
        with ProcessPipeline(src, num_batches=12, workers=2,
                             max_respawns=1, name="spawny") as pipe:
            it = pipe.batches()
            next(it)
            os.kill(pipe._procs[1].pid, signal.SIGKILL)
            for _ in range(11):
                next(it)
    finally:
        set_recorder(None)
    n, _, errors = schema.validate_journal(out)
    assert not errors, errors
    stalls = [e for e in schema.load_journal(out)
              if e["event"] == "feed" and e["name"] == "spawny.respawn"]
    assert len(stalls) == 1
    assert "worker 1 died" in stalls[0]["note"]
    assert "respawn 1/1" in stalls[0]["note"]


def test_close_mid_consumption_releases_everything():
    """The ctrl-C shape: abandon the stream mid-run; close() must stop
    workers and unlink the ring (the autouse fixture asserts /dev/shm)."""
    src = SyntheticImageSource(batch=4, shape=(3, 8, 8))
    pipe = ProcessPipeline(src, num_batches=200, workers=2)
    it = pipe.batches()
    next(it)
    next(it)
    pipe.close()
    for p in pipe._procs:
        assert not p.is_alive()
    pipe.close()  # idempotent


def test_prefetcher_error_surfaces_promptly():
    """DevicePrefetcher twin of the worker-raise contract: a data_fn
    that raises must reach the consumer, not hang the queue."""
    from sparknet_tpu.data.prefetch import DevicePrefetcher

    def fn(it):
        if it == 1:
            raise RuntimeError("thread feed boom")
        return {"x": np.zeros(2, np.float32)}

    t0 = time.monotonic()
    pf = DevicePrefetcher(fn, num_iters=10)
    with pytest.raises(RuntimeError, match="thread feed boom"):
        list(pf)
    pf.close()
    assert time.monotonic() - t0 < 30.0


# ---------------------------------------------------------------- layout


def test_nhwc_pipeline_is_zero_transpose_end_to_end():
    """The PR-4 cash-out, pinned: a channels-last pipeline run does
    zero rank-4 host transposes (native NHWC synthesis + transform;
    C-contiguous channels-last views; the host adapter never runs) and
    zero ENTRY transposes (the DeviceAugment program the feed dispatches
    lowers with no rank-4 transpose — the layout census machinery)."""
    import jax

    from sparknet_tpu.analysis.graphcheck import layout_census
    from sparknet_tpu.data.device_transform import DeviceAugment
    from sparknet_tpu.ops import layout as L

    calls = {"n": 0}
    orig = L.feeds_to_internal

    def counting(feeds, layout=None):
        calls["n"] += 1
        return orig(feeds, layout)

    src = SyntheticImageSource(batch=2, shape=(3, 12, 12), seed=5,
                               layout="nhwc")
    stage = TransformStage(TransformConfig(mean_value=(1.0, 2.0, 3.0)),
                           train=True, layout="nhwc", out_dtype="|u1")
    L.feeds_to_internal = counting
    try:
        with ProcessPipeline(src, stage, num_batches=3,
                             workers=1) as pipe:
            for feeds in pipe.batches():
                data = feeds["data"]
                assert data.shape == (2, 12, 12, 3)  # channels-last wire
                assert data.flags.c_contiguous  # no lazy transpose view
    finally:
        L.feeds_to_internal = orig
    assert calls["n"] == 0  # the canonical->internal host adapter never ran

    # the entry program: device-side augment on the NHWC uint8 wire batch
    aug = DeviceAugment(TransformConfig(crop_size=8, mirror=True),
                        layout="nhwc")
    batch = src.get(0, 0)["data"]
    lowered = jax.jit(aug).lower(batch, jax.random.key(0))
    census = layout_census(lowered.as_text(),
                           lowered.compile().as_text())
    assert census["stablehlo_transposes_4d"] == 0, census


def test_nhwc_host_transformer_matches_nchw_math():
    """Same seed, same canonical pixels: the channels-last transformer
    must produce the transpose of the NCHW result (identical crops and
    mirror coins — the RNG draw order is layout-invariant)."""
    rs = np.random.RandomState(3)
    nchw = rs.randint(0, 255, (4, 3, 12, 12)).astype(np.uint8)
    nhwc = np.ascontiguousarray(nchw.transpose(0, 2, 3, 1))
    mean = rs.rand(3, 12, 12).astype(np.float32) * 255
    cfg = dict(mean_image=mean, crop_size=8, mirror=True, seed=11)
    out_nchw = DataTransformer(TransformConfig(**cfg))(nchw, True)
    out_nhwc = DataTransformer(TransformConfig(**cfg),
                               layout="nhwc")(nhwc, True)
    np.testing.assert_allclose(out_nhwc, out_nchw.transpose(0, 2, 3, 1),
                               atol=1e-5)
    # and the deterministic TEST path is bit-identical
    out_nchw = DataTransformer(TransformConfig(**cfg))(nchw, False)
    out_nhwc = DataTransformer(TransformConfig(**cfg),
                               layout="nhwc")(nhwc, False)
    np.testing.assert_array_equal(out_nhwc,
                                  out_nchw.transpose(0, 2, 3, 1))


def test_decode_jpeg_nhwc_skips_the_transpose():
    import io

    from PIL import Image

    from sparknet_tpu.data.minibatch import decode_jpeg

    buf = io.BytesIO()
    arr = np.random.RandomState(0).randint(
        0, 255, (16, 16, 3)).astype(np.uint8)
    Image.fromarray(arr).save(buf, format="JPEG")
    chw = decode_jpeg(buf.getvalue(), 8, 8)
    hwc = decode_jpeg(buf.getvalue(), 8, 8, layout="nhwc")
    assert chw.shape == (3, 8, 8)
    assert hwc.shape == (8, 8, 3)
    np.testing.assert_array_equal(hwc, chw.transpose(1, 2, 0))
    assert hwc.flags.c_contiguous


def test_wire_spec_from_net_shapes():
    from sparknet_tpu.ops.data_layers import wire_spec

    shapes = {"data": (8, 227, 227, 3), "label": (8,)}
    spec = wire_spec(shapes, raw=True)
    assert spec["data"] == ((8, 227, 227, 3), "|u1")
    assert spec["label"] == ((8,), "<i4")
    assert wire_spec(shapes)["data"][1] == "<f4"


# ---------------------------------------------------------------- device


def test_device_feed_yields_device_batches_in_order():
    import jax

    src = SyntheticImageSource(batch=2, shape=(3, 8, 8), seed=9)
    pipe = ProcessPipeline(src, num_batches=5, workers=2)
    with pipe, device_feed(pipe, depth=2) as pf:
        labels = []
        for feeds in pf:
            assert isinstance(feeds["data"], jax.Array)
            labels.append(np.asarray(feeds["label"]))
    assert len(labels) == 5
    for g, got in enumerate(labels):
        np.testing.assert_array_equal(got, src.get(0, g)["label"])


def test_as_data_fn_serves_solver_contract():
    src = SyntheticImageSource(batch=2, shape=(3, 8, 8), seed=4)
    with ProcessPipeline(src, num_batches=4, workers=1) as pipe:
        fn = pipe.as_data_fn(copy=True)
        feeds = [fn(i) for i in range(4)]
    for g, f in enumerate(feeds):
        np.testing.assert_array_equal(f["label"], src.get(0, g)["label"])


# ---------------------------------------------------------------- obs


def test_feed_events_are_schema_valid(tmp_path):
    from sparknet_tpu.obs import schema
    from sparknet_tpu.obs.recorder import Recorder, set_recorder

    journal = str(tmp_path / "feed.jsonl")
    rec = set_recorder(Recorder(journal))
    try:
        src = PrestagedSource({"data": np.zeros((2, 8, 8, 3), np.uint8),
                               "label": np.zeros(2, np.int32)})
        with ProcessPipeline(src, num_batches=6, workers=1,
                             obs_every=2) as pipe:
            for _ in pipe.batches():
                pass
        rec.close()
    finally:
        set_recorder(None)
    n_lines, _, errors = schema.validate_journal(journal)
    assert not errors, errors
    feed_events = list(schema.iter_events(journal, "feed"))
    assert feed_events, "no feed telemetry journaled"
    for ev in feed_events:
        assert set(ev["stages"]) <= {"slot_wait", "source", "decode",
                                     "transform", "write", "put"}
        assert ev["batches"] > 0 and ev["images"] > 0


def test_feed_disarmed_writes_nothing(tmp_path):
    """SPARKNET_OBS off => zero journal writes from the pipeline (the
    obs off-contract extends to the feed)."""
    marker = tmp_path / "should_not_exist.jsonl"
    src = SyntheticImageSource(batch=2, shape=(3, 8, 8))
    with ProcessPipeline(src, num_batches=3, workers=1) as pipe:
        for _ in pipe.batches():
            pass
        assert pipe.stats["batches"] == 3  # attribution still accumulates
    assert not marker.exists()


def test_report_renders_feed_stage_table(tmp_path):
    from sparknet_tpu.obs.recorder import Recorder, set_recorder
    from sparknet_tpu.obs.report import render_path

    journal = str(tmp_path / "feed.jsonl")
    rec = set_recorder(Recorder(journal))
    try:
        src = SyntheticImageSource(batch=2, shape=(3, 8, 8))
        with ProcessPipeline(src, num_batches=4, workers=1,
                             obs_every=2, name="feed.test") as pipe:
            for _ in pipe.batches():
                pass
        rec.close()
    finally:
        set_recorder(None)
    text = render_path(journal)
    assert "feed stages (host-side)" in text
    assert "feed.test" in text
    assert "slot_wait" in text


# ---------------------------------------------------------------- decode


def test_decoded_pairs_overlap_across_chunk_boundary():
    """The satellite fix pinned structurally: with the pipelined window
    the pool pulls sample ``chunk`` before yielding result 1 (the old
    ``pool.map``-per-chunk flush pulled it only after the whole first
    chunk had been yielded)."""
    from sparknet_tpu.data import minibatch as mb

    events = []

    def sample_stream(n):
        for i in range(n):
            events.append(("pull", i))
            yield (b"x%d" % i, i)

    def fake_decode(data, h, w, layout="nchw"):
        return np.zeros((3, h, w), np.uint8)

    orig = mb.decode_jpeg
    mb.decode_jpeg = fake_decode
    try:
        for arr, label in mb._decoded_pairs(sample_stream(10), 4, 4,
                                            workers=2, chunk=4):
            events.append(("yield", label))
    finally:
        mb.decode_jpeg = orig
    labels = [e[1] for e in events if e[0] == "yield"]
    assert labels == list(range(10))  # order identical to serial
    # overlap: sample 4 (second chunk) is pulled before result 1 yields
    assert events.index(("pull", 4)) < events.index(("yield", 1)), events


def test_pooled_decode_output_identical_with_broken_images():
    """Order + drop semantics unchanged by the overlap fix (belt and
    braces beside tests/test_data.py's pooled-vs-serial pin)."""
    import io

    from PIL import Image

    from sparknet_tpu.data.minibatch import make_minibatches_compressed

    rs = np.random.RandomState(5)

    def jpeg(i):
        buf = io.BytesIO()
        Image.fromarray(rs.randint(0, 255, (12, 12, 3)).astype(np.uint8)
                        ).save(buf, format="JPEG")
        return (buf.getvalue(), i)

    samples = [jpeg(i) for i in range(7)]
    samples.insert(2, (b"broken", 99))
    serial = list(make_minibatches_compressed(samples, 2, 8, 8, workers=1))
    pooled = list(make_minibatches_compressed(samples, 2, 8, 8, workers=3))
    assert len(serial) == len(pooled)
    for (si, sl), (pi, pl) in zip(serial, pooled):
        np.testing.assert_array_equal(si, pi)
        np.testing.assert_array_equal(sl, pl)


# ---------------------------------------------------------------- misc


def test_ring_too_small_raises():
    src = SyntheticImageSource(batch=2, shape=(3, 8, 8))
    with pytest.raises(ValueError, match="deadlock"):
        ProcessPipeline(src, num_batches=2, workers=2, slots=2)


def test_feed_spec_roundtrip():
    feeds = {"data": np.zeros((2, 4, 4, 3), np.uint8),
             "label": np.zeros(2, np.int32)}
    spec = FeedSpec.from_arrays(feeds)
    assert spec.slot_bytes == 2 * 4 * 4 * 3 + 2 * 4
    buf = bytearray(spec.slot_bytes)
    views = spec.views(memoryview(buf), 0)
    assert views["data"].shape == (2, 4, 4, 3)
    assert views["label"].dtype == np.int32
    spec.check(feeds)
    with pytest.raises(ValueError, match="FeedSpec"):
        spec.check({"data": feeds["data"],
                    "label": feeds["label"].astype(np.int64)})
