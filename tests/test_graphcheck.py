"""graphcheck: per-contract fixtures + the banked-manifest smoke gate.

Mirrors test_graftlint.py one layer down: each contract family gets a
deliberately defective fixture — an unsharded "tensor-parallel" param,
a smuggled f32 upcast under bf16, an undonated carry, a comm census
that misses/violates its model — and each must produce EXACTLY its
finding.  The gate tests then lower the cheap real modes (dp + tau) on
the virtual 8-device mesh and diff them against the golden manifests
in docs/graph_contracts/, so any PR that changes the lowered
communication structure of the SparkNet step fails tier-1 until it
regenerates the manifests (`python -m sparknet_tpu.analysis graph
--update`).  The full 10-mode sweep is the slow-marked twin.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparknet_tpu.analysis.comm_model import CommExpectation, expected_comm
from sparknet_tpu.analysis.graphcheck import (
    GRAPH_RULES,
    audit_target,
    census_summary,
    collective_census,
    dtype_census,
    run_graphcheck,
    sources_fingerprint,
    trace_artifacts,
)
from sparknet_tpu.parallel.modes import TraceTarget, list_modes

pytestmark = pytest.mark.smoke


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _rules_of(problems):
    return sorted(p["rule"] for p in problems)


def _audit(target, exp):
    return audit_target(target, trace_artifacts(target), exp)


_NO_EXPECTATION = CommExpectation(required={}, forbidden=())


# -- HLO/StableHLO parsing (pure text, no lowering) -------------------------

_HLO_FIXTURE = """\
HloModule jit_step, entry_computation_layout={()->()}

%region_0.19_spmd (a: f32[]) -> f32[] {
  %ar.1 = f32[] all-reduce(f32[] %a), replica_groups=[1,8]<=[8], to_apply=%add
}

%while_body (b: (s32[], f32[])) -> (s32[], f32[]) {
  %call.1 = f32[] call(f32[] %x), to_apply=%region_0.19_spmd
}

ENTRY %main_spmd (p0: f32[4]) -> f32[4] {
  %w = (s32[], f32[]) while((s32[], f32[]) %init), condition=%cond, body=%while_body
  %big = f32[64,1024]{1,0} all-reduce(f32[64,1024]{1,0} %g), to_apply=%add
  %gath = f32[2,8]{1,0} all-gather(f32[2,1]{1,0} %s), dimensions={1}
  %done = f32[] all-reduce-done(f32[] %start)
}
"""


def test_collective_census_parses_kinds_bytes_and_loops():
    ops = collective_census(_HLO_FIXTURE)
    kinds = sorted((o.kind, o.bytes, o.in_loop) for o in ops)
    # the -done op must NOT count; the call inside the while body makes
    # region_0's all-reduce loop-resident transitively
    assert kinds == [
        ("all-gather", 64, False),
        ("all-reduce", 4, True),
        ("all-reduce", 262144, False),
    ]
    summary = census_summary(ops)
    assert summary["all-reduce"] == {
        "count": 2, "bytes": 262148,
        "in_loop_count": 1, "in_loop_bytes": 4,
    }


def test_dtype_census_flags_f32_dots_only():
    shlo = """\
    %3 = stablehlo.convolution(%0, %1) {} : (tensor<2x3xbf16>, tensor<3x4xbf16>) -> tensor<2x4xbf16>
    %4 = stablehlo.dot_general %0, %1, contracting_dims = [1] x [0] : (tensor<2x3xf32>, tensor<3x4xf32>) -> tensor<2x4xf32>
    %5 = stablehlo.exponential %4 : tensor<2x4xf32>
    """
    out = dtype_census(shlo)
    assert out["dot_conv_total"] == 2
    assert out["dot_conv_f32"] == 1
    assert out["f32_ops"][0][0] == "dot_general"


# -- fixture targets: each defect produces exactly its finding --------------


def test_fixture_unsharded_param_is_caught():
    """A mode that declares tensor parallelism whose params all lowered
    replicated -> graph-replicated-param, and nothing else."""
    mesh = _mesh()
    rep = NamedSharding(mesh, P())
    fn = jax.jit(lambda w, x: (w, (w[0] * x).sum()),
                 in_shardings=(rep, NamedSharding(mesh, P("data"))),
                 out_shardings=(rep, rep), donate_argnums=(0,))
    w = jax.device_put(jnp.ones((128, 4)), rep)
    x = jax.device_put(jnp.ones((16, 4)),
                       NamedSharding(mesh, P("data")))
    target = TraceTarget(
        name="fx_tp", fn=fn, args=(w, x), meta={"dtype": "f32"},
        param_bytes=int(w.nbytes), state_bytes=0,
        carry_argnums=(0,), carry_out_leaves=1,
        expects_sharded_params=True,
    )
    problems, contract = _audit(target, _NO_EXPECTATION)
    assert _rules_of(problems) == ["graph-replicated-param"]
    assert contract["sharding"]["params_sharded"] == 0


def test_fixture_smuggled_f32_upcast_is_caught():
    """bf16 config with a matmul upcast to f32 -> graph-dtype-upcast."""
    def smuggle(a, b):
        return (a.astype(jnp.float32) @ b.astype(jnp.float32)
                ).astype(jnp.bfloat16)

    a = jnp.ones((8, 8), jnp.bfloat16)
    target = TraceTarget(
        name="fx_bf16", fn=jax.jit(smuggle), args=(a, a),
        meta={"dtype": "bf16"}, param_bytes=0, state_bytes=0,
    )
    problems, contract = _audit(target, _NO_EXPECTATION)
    assert _rules_of(problems) == ["graph-dtype-upcast"]
    assert contract["dtype"]["dot_conv_f32"] == 1
    # the clean twin: same matmul kept in bf16 passes
    clean = TraceTarget(
        name="fx_bf16_ok", fn=jax.jit(lambda a, b: a @ b), args=(a, a),
        meta={"dtype": "bf16"}, param_bytes=0, state_bytes=0,
    )
    problems, _ = _audit(clean, _NO_EXPECTATION)
    assert problems == []


def test_fixture_undonated_carry_is_caught():
    """A train-step-shaped carry jitted without donation ->
    graph-undonated-carry with the byte figure."""
    fn = jax.jit(lambda w, x: (w - 0.1 * x.sum() * w, (w ** 2).sum()))
    w = jnp.ones((256,), jnp.float32)
    target = TraceTarget(
        name="fx_nodonate", fn=fn, args=(w, jnp.ones((4,))),
        meta={"dtype": "f32"}, param_bytes=int(w.nbytes), state_bytes=0,
        carry_argnums=(0,), carry_out_leaves=1,
    )
    problems, contract = _audit(target, _NO_EXPECTATION)
    assert _rules_of(problems) == ["graph-undonated-carry"]
    assert contract["donation"]["undonated_bytes"] == w.nbytes
    assert "1,024" in problems[0]["message"]


def _scalar_reduce_target(name="fx_comm"):
    """A sharded-input scalar reduction: exactly one 4-byte all-reduce."""
    mesh = _mesh()
    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    fn = jax.jit(lambda w, x: (w, (x * w[0]).sum()),
                 in_shardings=(rep, data), out_shardings=(rep, rep),
                 donate_argnums=(0,))
    w = jax.device_put(jnp.ones((4,)), rep)
    x = jax.device_put(jnp.ones((16,)), data)
    return TraceTarget(
        name=name, fn=fn, args=(w, x), meta={"dtype": "f32"},
        param_bytes=int(w.nbytes), state_bytes=0,
        carry_argnums=(0,), carry_out_leaves=1,
    )


def test_fixture_comm_count_mismatch_is_caught():
    """The comm-budget family from both sides: a required collective
    that is absent, a byte total outside the model window, and a
    forbidden collective that is present."""
    mesh = _mesh()
    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    # no cross-shard math at all -> required all-reduce missing
    silent_fn = jax.jit(lambda w, x: (w, x * 2.0),
                        in_shardings=(rep, data),
                        out_shardings=(rep, data), donate_argnums=(0,))
    w = jax.device_put(jnp.ones((4,)), rep)
    x = jax.device_put(jnp.ones((16,)), data)
    silent = TraceTarget(
        name="fx_silent", fn=silent_fn, args=(w, x),
        meta={"dtype": "f32"}, param_bytes=16, state_bytes=0,
        carry_argnums=(0,), carry_out_leaves=1,
    )
    exp = CommExpectation(required={"all-reduce": (16, 32)}, forbidden=())
    problems, _ = _audit(silent, exp)
    assert _rules_of(problems) == ["graph-comm-missing"]

    # a 4-byte loss sync where the model demands a grad-sized one
    problems, _ = _audit(_scalar_reduce_target(),
                         CommExpectation(required={"all-reduce": (1000, 2000)},
                                         forbidden=()))
    assert _rules_of(problems) == ["graph-comm-bytes"]

    # the same op where the mode forbids the family outright
    problems, _ = _audit(_scalar_reduce_target(),
                         CommExpectation(required={},
                                         forbidden=("all-reduce",)))
    assert _rules_of(problems) == ["graph-comm-forbidden"]


def test_fixture_collective_inside_local_step_loop_is_caught():
    """A loop-carried cross-shard reduction inside lax.scan — per-step
    sync in a tau-averaging mode -> graph-comm-in-loop."""
    mesh = _mesh()
    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))

    def f(w, x):
        def body(c, _):
            return (x * c).sum() * 1e-3, 0.0

        out, _ = jax.lax.scan(body, 1.0, None, length=8)
        return w, out

    fn = jax.jit(f, in_shardings=(rep, data), out_shardings=(rep, rep),
                 donate_argnums=(0,))
    w = jax.device_put(jnp.ones((4,)), rep)
    x = jax.device_put(jnp.ones((16,)), data)
    target = TraceTarget(
        name="fx_loop", fn=fn, args=(w, x), meta={"dtype": "f32"},
        param_bytes=16, state_bytes=0, carry_argnums=(0,),
        carry_out_leaves=1,
    )
    exp = CommExpectation(required={}, forbidden=(),
                          loop_collectives_ok=False, loop_bytes_floor=0)
    problems, _ = _audit(target, exp)
    assert _rules_of(problems) == ["graph-comm-in-loop"]


def test_fixture_recompile_hazard_is_caught():
    """alt_args whose avals differ (weak-type flapping) re-lower to
    different StableHLO -> graph-recompile-hazard."""
    fn = jax.jit(lambda a, s: a * s)
    a = jnp.ones((8,), jnp.float32)
    target = TraceTarget(
        name="fx_recompile", fn=fn,
        args=(a, jnp.float32(2.0)), alt_args=(a, 2),
        meta={"dtype": "f32"}, param_bytes=0, state_bytes=0,
    )
    problems, contract = _audit(target, _NO_EXPECTATION)
    assert _rules_of(problems) == ["graph-recompile-hazard"]
    assert contract["recompile_hazard"] is True


# -- manifest machinery -----------------------------------------------------


def test_manifest_bank_diff_and_allow(tmp_path):
    """moe (sub-second to lower) exercises the full manifest loop:
    missing -> banked -> drift -> allow-suppressed."""
    banked = str(tmp_path / "contracts")
    findings, _ = run_graphcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["graph-manifest-missing"]

    findings, manifests = run_graphcheck(["moe"], banked_dir=banked,
                                         update=True)
    assert findings == []
    mpath = tmp_path / "contracts" / "moe.json"
    assert mpath.exists()

    findings, _ = run_graphcheck(["moe"], banked_dir=banked)
    assert findings == []  # steady state: re-run diffs clean

    banked_manifest = json.loads(mpath.read_text())
    banked_manifest["contract"]["comm"]["all-to-all"]["count"] = 99
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_graphcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["graph-manifest-drift"]
    assert not findings[0].suppressed
    assert "all-to-all" in findings[0].message

    banked_manifest["allow"] = {
        "graph-manifest-drift": "fixture: tampered count"}
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_graphcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["graph-manifest-drift"]
    assert findings[0].suppressed


def test_expected_comm_rejects_unknown_mode():
    with pytest.raises(KeyError):
        expected_comm("warp-speed", param_bytes=1)


def test_sources_fingerprint_covers_the_contract_surface():
    fp = sources_fingerprint()
    assert "sparknet_tpu/models/zoo.py" in fp
    assert "sparknet_tpu/parallel/trainer.py" in fp
    assert "sparknet_tpu/analysis/graphcheck.py" in fp
    assert all(len(h) == 64 for h in fp.values())


# -- the gate: real modes vs the golden manifests ---------------------------


def test_graphcheck_smoke_gate_dp_and_tau():
    """THE ratchet, graph edition: the two cheap SparkNet modes (tau=1
    sync DP and the tau-averaging round) must lower to exactly the
    banked contract — comm census, sharding, dtype, donation — with
    zero unsuppressed findings.  Catches both code drift (the lowered
    graph changed: regenerate manifests or fix the regression) and
    contract violations (a new undonated carry, a smuggled collective).
    """
    findings, manifests = run_graphcheck(["dp", "tau"])
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "unsuppressed graphcheck findings:\n" + "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    # spot-pin the load-bearing physics: DP all-reduces the full grads,
    # tau's model-sized sync stays OUT of the local-step loop
    dp = manifests["dp"]["contract"]["comm"]["all-reduce"]
    assert dp["bytes"] >= manifests["dp"]["model"]["param_bytes"]
    tau = manifests["tau"]["contract"]["comm"]["all-reduce"]
    assert tau["in_loop_bytes"] == 0


def test_rule_catalog_and_modes():
    assert set(GRAPH_RULES) >= {
        "graph-comm-missing", "graph-comm-forbidden", "graph-comm-bytes",
        "graph-comm-in-loop", "graph-replicated-param",
        "graph-carry-reshard", "graph-dtype-upcast",
        "graph-undonated-carry", "graph-recompile-hazard",
        "graph-manifest-missing", "graph-manifest-drift",
    }
    modes = list_modes()
    assert len(modes) >= 6
    assert {"solo", "dp", "dp_bf16", "tau", "easgd", "tp", "sp",
            "mobilenet_dp"} <= set(modes)


# -- CLI: shared schema with lint ------------------------------------------


def test_cli_graph_json_schema(tmp_path, capsys, monkeypatch):
    """`graph --json` emits the same findings schema as `lint --json`."""
    from sparknet_tpu.analysis import graphcheck as gc
    from sparknet_tpu.analysis.__main__ import main as cli_main

    # point the CLI at a tmp manifest dir so this test never writes docs/
    monkeypatch.setattr(gc, "MANIFEST_DIR", str(tmp_path))
    rc = cli_main(["graph", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # manifest missing in the tmp dir
    assert set(out) == {"findings", "unsuppressed", "suppressed"}
    assert out["findings"][0]["rule"] == "graph-manifest-missing"
    for key in ("rule", "path", "line", "message", "suppressed"):
        assert key in out["findings"][0]

    rc = cli_main(["graph", "--mode", "moe", "--update"])
    assert rc == 0
    capsys.readouterr()
    rc = cli_main(["graph", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["unsuppressed"] == 0


def test_cli_graph_unknown_mode_is_usage_error(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["graph", "--mode", "no-such-mode"]) == 2
