"""Real-pixel convergence: zoo LeNet on sklearn's handwritten digits.

The reference's statistical end-to-end check trains on real data and
asserts accuracy properties (ref: src/test/scala/libs/CifarSpec.scala:92
— untrained ~chance; caffe/examples/mnist — lenet ~99%).  Real
MNIST/CIFAR bytes are unavailable in this zero-egress environment
(caffe/data/*/get_*.sh are download scripts), so the evidence runs on
the bundled real digits corpus instead: docs/CONVERGENCE.md records the
target mapping.  Marked slow: ~1 min of CPU training.
"""

import numpy as np
import pytest

sklearn_datasets = pytest.importorskip("sklearn.datasets")

from sparknet_tpu import models
from sparknet_tpu.data.digits import load_digits_dataset, minibatch_fn
from sparknet_tpu.solvers.solver import Solver

pytestmark = pytest.mark.slow


def test_lenet_digits_chance_then_98pct():
    xtr, ytr, xte, yte = load_digits_dataset()
    xtr, xte = xtr / 16.0, xte / 16.0  # lenet recipe expects [0,1] scale
    B = 64
    nb = len(yte) // B

    def test_fn(b):
        return {"data": xte[b * B : (b + 1) * B],
                "label": yte[b * B : (b + 1) * B]}

    solver = Solver(models.lenet_solver(), models.lenet(B))
    untrained = solver.test(nb, test_fn)["accuracy"]
    assert 0.02 <= untrained <= 0.25, untrained  # ~chance (CifarSpec bound)

    solver.step(400, minibatch_fn(xtr, ytr, B, seed=0))
    trained = solver.test(nb, test_fn)["accuracy"]
    assert trained >= 0.97, trained  # measured 0.984; margin for jitter
