"""Plumbing check for the weak-scaling harness (tools/scaling_bench.py).

Runs the real tool as a subprocess on a virtual 4-device CPU mesh — the
same route a pod session uses, minus the hardware — and validates the
record shape: both legs measured, efficiency in (0, ~1], and the
plumbing-only marker present so CPU numbers can never masquerade as
chip evidence.
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_scaling_bench_plumbing_virtual_mesh():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "scaling_bench.py"),
         "--allow-cpu", "--devices", "4"],
        capture_output=True, text=True, timeout=540, env=env, cwd=ROOT,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "sync_dp_scaling_efficiency"
    assert rec["plumbing_only_cpu"] is True
    assert rec["measured"] is False
    assert rec["devices"] == 4
    assert rec["img_s_1"] > 0 and rec["img_s_n"] > 0
    # a CPU "mesh" shares one memory system: efficiency is meaningless as
    # a number but must be finite and positive, proving the sharded
    # program ran both legs
    assert 0 < rec["value"] < 4
