"""Serving engine gates (sparknet_tpu/serve; ROADMAP item 1).

Four contract families:

1. **Batcher policy** — stdlib-only unit tests on a fake clock: the
   smallest-fitting-bucket choice, the ``max_wait_ms`` deadline flush
   under trickle load (no request's queue wait exceeds the deadline),
   zero-loss drain on shutdown, and refusal of post-close submits.
   No jax, no sleeps.
2. **The EXACT gate** — a padded dynamic batch is BITWISE identical to
   batch-1 serial inference, per zoo family x deploy arm.  This is the
   whole correctness claim of bucket padding: batching is a latency
   policy, never a numerics change.  mobilenet is the documented
   exception (depthwise stack is not batch-stable on this backend —
   docs/SERVING.md "Exactness") and gets an allclose gate instead.
3. **Priced admission** — the over-HBM model load refuses BEFORE any
   compile, end to end through the journal (the queue pre-flight's
   policy, applied to residency).
4. **The AOT load run** — every bucket exercised with the recompile
   sentinel pinned at ZERO post-warmup compiles.

ref: apps/FeaturizerApp.scala:1 (the reference's batch scoring app;
dynamic request batching is new TPU-first surface).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from sparknet_tpu.serve import AdmissionRefused, DynamicBatcher, ServeEngine
from sparknet_tpu.serve.engine import EXEC_FLOOR, exec_batch, percentile


class FakeClock:
    """Injectable time for the deadline tests: advances only on demand."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# -- batcher policy (jax-free) ----------------------------------------------


@pytest.mark.smoke
def test_bucket_for_picks_smallest_fitting():
    b = DynamicBatcher(buckets=(1, 8, 64, 256))
    assert b.bucket_for(1) == 1
    assert b.bucket_for(2) == 8
    assert b.bucket_for(8) == 8
    assert b.bucket_for(9) == 64
    assert b.bucket_for(65) == 256
    # overflow clamps to the largest (the queue drains it as batches)
    assert b.bucket_for(1000) == 256


@pytest.mark.smoke
def test_deadline_flush_under_trickle_load():
    """A trickle never waits past max_wait_ms: the flush fires at the
    OLDEST request's deadline, not when a bucket happens to fill."""
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8, 64), max_wait_ms=5.0, clock=clock)
    tickets = []
    # one request every 2 ms, pump ticking at 1 ms — never enough
    # pending to fill the 64-bucket, so every flush is deadline-driven
    for tick in range(30):
        clock.t = tick * 1e-3
        if tick % 2 == 0 and len(tickets) < 6:
            tickets.append(b.submit(f"req{len(tickets)}"))
        b.take()
    assert not b.pending()
    for t in tickets:
        assert t.t_batch is not None, f"request {t.id} never flushed"
        wait_ms = (t.t_batch - t.t_submit) * 1e3
        # the flush fires at the first pump tick AT/AFTER the deadline,
        # so the bound is max_wait plus one pump tick of quantization
        assert wait_ms <= 5.0 + 1.0 + 1e-6, \
            f"request {t.id} waited {wait_ms}ms"
        assert t.deadline_flush  # trickle: every flush was deadline-driven


@pytest.mark.smoke
def test_full_bucket_flushes_without_deadline():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    tickets = [b.submit(i) for i in range(8)]
    batch = b.take()  # due immediately: the largest bucket is full
    assert batch is not None and len(batch) == 8
    assert all(not t.deadline_flush for t in tickets)
    assert all(t.bucket == 8 for t in tickets)


@pytest.mark.smoke
def test_partial_flush_stamps_smallest_bucket():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8, 64), max_wait_ms=5.0, clock=clock)
    for i in range(3):
        b.submit(i)
    clock.t = 0.006  # past the deadline
    batch = b.take()
    assert [t.bucket for t in batch] == [8, 8, 8]  # 3 rides the 8-bucket
    assert all(t.deadline_flush and t.batch_n == 3 for t in batch)


@pytest.mark.smoke
def test_close_drains_every_inflight_request():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    tickets = [b.submit(i) for i in range(11)]
    batches = b.close(drain=True)
    drained = [t for batch in batches for t in batch]
    assert sorted(t.id for t in drained) == sorted(t.id for t in tickets)
    with pytest.raises(RuntimeError, match="closed"):
        b.submit("late")


@pytest.mark.smoke
def test_close_without_drain_fails_tickets():
    b = DynamicBatcher(buckets=(1, 8), clock=FakeClock())
    t = b.submit("x")
    b.close(drain=False)
    with pytest.raises(RuntimeError, match="without drain"):
        t.wait(timeout=0.1)


@pytest.mark.smoke
def test_overflow_drains_as_multiple_batches():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    for i in range(20):
        b.submit(i)
    sizes = []
    while (batch := b.take(force=True)) is not None:
        sizes.append(len(batch))
    assert sizes == [8, 8, 4]


@pytest.mark.smoke
def test_percentile_nearest_rank():
    vals = list(range(1, 101))
    assert percentile(vals, 50) == 50
    assert percentile(vals, 99) == 99
    assert percentile(vals, 100) == 100
    assert percentile([7.0], 99) == 7.0
    assert percentile([], 50) == 0.0


@pytest.mark.smoke
def test_exec_batch_floor():
    # the 1-bucket compiles at the exec floor: a single-row program
    # lowers to a gemv whose reduction order breaks bitwise parity with
    # the batched gemm (docs/SERVING.md "Exactness")
    assert exec_batch(1) == EXEC_FLOOR == 2
    assert exec_batch(8) == 8
    assert exec_batch(256) == 256


# -- the EXACT gate ---------------------------------------------------------

# the three batch-stable zoo families (mobilenet's depthwise stack is
# not batch-stable on this backend at ANY batch — allclose gate below)
EXACT_CASES = [
    pytest.param("cifar10_quick", "f32", marks=pytest.mark.smoke),
    ("cifar10_quick", "fold_bn"),
    ("cifar10_quick", "int8"),
    ("lenet", "f32"),
    ("lenet", "fold_bn"),
    ("lenet", "int8"),
    ("transformer", "f32"),
    ("transformer", "fold_bn"),
    ("transformer", "int8"),
]


def _serve_items(engine, name, n, seed=3):
    from sparknet_tpu.serve.loadgen import synthetic_items

    return synthetic_items(engine._models[name],
                           n, np.random.RandomState(seed))


@pytest.mark.parametrize("family,arm", EXACT_CASES)
def test_exact_gate_padded_batch_matches_serial(family, arm):
    """Bitwise: serial batch-1, a full 8-batch, and a padded 3-batch all
    produce identical per-row scores for the same items."""
    engine = ServeEngine(buckets=(1, 8))
    engine.load_model("m", family=family, arm=arm)
    items = _serve_items(engine, "m", 8)

    serial = [np.asarray(engine.infer("m", it)) for it in items]

    full = [engine.submit("m", it) for it in items]
    assert engine.pump(force=True) == 1
    for t, ref in zip(full, serial):
        assert t.bucket == 8 and t.batch_n == 8
        assert np.array_equal(np.asarray(t.result), ref), (family, arm)

    padded = [engine.submit("m", it) for it in items[:3]]
    assert engine.pump(force=True) == 1
    for t, ref in zip(padded, serial[:3]):
        assert t.bucket == 8 and t.batch_n == 3  # 5 pad rows
        assert np.array_equal(np.asarray(t.result), ref), (family, arm)
    engine.shutdown()


@pytest.mark.slow
def test_mobilenet_batched_is_allclose():
    """The documented exception: depthwise convs are not batch-stable
    on this backend, so mobilenet gets a tolerance gate, not EXACT."""
    engine = ServeEngine(buckets=(1, 8))
    engine.load_model("m", family="mobilenet", arm="f32")
    items = _serve_items(engine, "m", 4)
    serial = [np.asarray(engine.infer("m", it)) for it in items]
    batched = [engine.submit("m", it) for it in items]
    engine.pump(force=True)
    for t, ref in zip(batched, serial):
        np.testing.assert_allclose(np.asarray(t.result), ref,
                                   rtol=1e-4, atol=1e-5)
    engine.shutdown()


# -- priced admission -------------------------------------------------------


@pytest.mark.smoke
def test_over_hbm_load_refused_and_journaled(tmp_path):
    """resnet50 at bucket 256 prices over the v5e budget: the load
    refuses BEFORE any jax work and the verdict lands in the journal."""
    from sparknet_tpu.obs.recorder import Recorder, set_recorder

    path = str(tmp_path / "refusal.jsonl")
    rec = set_recorder(Recorder(path, run_id="serve-test"))
    try:
        engine = ServeEngine()  # banked fit table, real HBM budget
        with pytest.raises(AdmissionRefused) as ei:
            engine.load_model("big", family="resnet50",
                              buckets=(1, 8, 64, 256))
    finally:
        rec.close()
        set_recorder(None)
    v = ei.value.verdict
    assert v["priced"] and not v["fits"]
    assert v["predicted_bytes"] > v["budget_bytes"]
    assert "big" not in engine.models()
    with open(path, encoding="utf-8") as f:
        events = [json.loads(line) for line in f]
    refusals = [e for e in events
                if e.get("event") == "serve"
                and e.get("kind") == "load_refused"]
    assert len(refusals) == 1
    assert refusals[0]["predicted_bytes"] == v["predicted_bytes"]


@pytest.mark.smoke
def test_unpriced_family_admits():
    """A family absent from the fit table admits (lenet banks 0 params
    in no table row) — pricing gates what it can price, nothing else."""
    from sparknet_tpu.serve.residency import AdmissionPolicy

    policy = AdmissionPolicy(fit_table={"families": {}})
    verdict = policy.admit("lenet", max_bucket=256, resident_bytes=0)
    assert verdict["fits"] and not verdict["priced"]


@pytest.mark.smoke
def test_shape_checked_submit():
    engine = ServeEngine(buckets=(1,))
    engine.load_model("m", family="lenet")
    with pytest.raises(ValueError, match="item shape"):
        engine.submit("m", np.zeros((3, 32, 32), np.float32))
    engine.shutdown()


# -- the AOT load run -------------------------------------------------------


def test_load_run_zero_postwarmup_compiles(tmp_path):
    """A small closed-loop load run: every bucket exercised, shutdown
    drains clean, and the recompile sentinel reads ZERO compiles in the
    traffic phase — the AOT-bucket contract at test scale."""
    from sparknet_tpu.serve.loadgen import load_run

    summary = load_run(requests=40, family="cifar10_quick",
                       buckets=(1, 8), refusal_family="resnet50")
    assert summary["requests"] >= 40
    assert summary["buckets_exercised"] == [1, 8]
    assert summary["compiles_post_warmup"] == 0
    assert summary["refused"]
    assert summary["p99_ms"] >= summary["p50_ms"] > 0
    assert summary["padded_rows"] > 0  # the trickle padded into buckets
    stats = summary["stats"]
    assert set(stats) == {"primary", "aux"}  # multi-model residency
    for s in stats.values():
        assert s["p99_ms"] >= s["p50_ms"] >= 0


@pytest.mark.smoke
def test_unload_model_releases_residency():
    engine = ServeEngine(buckets=(1,))
    engine.load_model("m", family="lenet")
    engine.unload_model("m")
    assert engine.models() == []
    assert engine.resident_bytes() == 0
    with pytest.raises(KeyError):
        engine.submit("m", np.zeros((1, 28, 28), np.float32))
