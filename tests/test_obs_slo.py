"""Declarative SLO gates (sparknet_tpu/obs/slo.py; docs/slo_manifest.json).

Two layers: gate semantics on synthetic journals (burn detection,
vacuous passes, the disturbance suspension that keeps fault-rehearsal
legs honest), and the repo-level smoke check — every banked evidence
journal, including the four chip-free dryrun specimens in
docs/evidence_r7/, must pass the checked-in manifest.  A burn here
means either the telemetry regressed or the manifest's promise did;
both are PR-blocking by design.

Stdlib-only under the obs-package contract (no jax import anywhere on
this path), so the whole file rides the smoke tier.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys

import pytest

from sparknet_tpu.obs import schema, slo

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.smoke


def _results_by_id(results):
    return {r["id"]: r for r in results}


def _request(run_id="r", wait=1.0, model="live", bucket=8, **extra):
    return {"event": "request", "run_id": run_id, "model": model,
            "bucket": bucket, "queue_wait_ms": wait,
            "batch_assembly_ms": 0.1, "device_ms": 2.0,
            "total_ms": wait + 2.1, **extra}


@pytest.fixture
def manifest():
    return slo.load_manifest()


# -- manifest ---------------------------------------------------------------


def test_manifest_loads_and_every_kind_has_an_evaluator(manifest):
    for spec in manifest["slos"]:
        assert spec["kind"] in slo._GATES, spec


def test_unknown_gate_kind_burns_loudly():
    results = slo.evaluate([], {"slos": [{"id": "x", "kind": "nope"}]})
    assert results[0]["ok"] is False
    assert "unknown gate kind" in results[0]["detail"]


# -- gate semantics ---------------------------------------------------------


def test_all_gates_vacuous_on_a_pure_runner_ledger(manifest):
    events = [{"event": "dial_start", "probe": 1},
              {"event": "dial_end", "probe": 1, "ok": True, "dt_s": 1.0}]
    results = slo.evaluate(events, manifest)
    assert all(r["ok"] for r in results)
    assert all(not r["applicable"] for r in results)


def test_warm_queue_p99_skips_warmup_then_burns_on_the_tail(manifest):
    # 8 warmup tickets at 500 ms are forgiven; steady traffic at 40+ms
    # burns the 40 ms bound
    events = [_request(wait=500.0) for _ in range(8)]
    events += [_request(wait=80.0) for _ in range(50)]
    by_id = _results_by_id(slo.evaluate(events, manifest))
    gate = by_id["warm-queue-p99"]
    assert gate["applicable"] and not gate["ok"]
    assert gate["value"] > 40.0


def test_warm_queue_p99_passes_on_steady_traffic(manifest):
    events = [_request(wait=500.0) for _ in range(8)]
    events += [_request(wait=3.0) for _ in range(50)]
    gate = _results_by_id(slo.evaluate(events, manifest))["warm-queue-p99"]
    assert gate["applicable"] and gate["ok"]


def test_warm_queue_p99_suspends_on_disturbance_journals(manifest):
    # a replica kill mid-traffic: elevated waits are BY DESIGN, the
    # journal answers to zero-drop/compiles-zero — the latency gate
    # must suspend itself (vacuous pass, reason in the detail), never
    # silently forgive nor falsely burn
    events = [_request(wait=500.0) for _ in range(60)]
    events.append({"event": "replica", "run_id": "r",
                   "kind": "replica_down", "replica": 1, "rerouted": 3})
    gate = _results_by_id(slo.evaluate(events, manifest))["warm-queue-p99"]
    assert gate["ok"] and not gate["applicable"]
    assert "disturbance" in gate["detail"]


def test_slot_wait_share_burns_past_five_percent(manifest):
    feed = {"event": "feed", "run_id": "r", "name": "train",
            "batches": 10, "images": 100, "wall_s": 1.0,
            "stages": {"slot_wait": 0.2, "source": 0.8, "write": 1.0}}
    gate = _results_by_id(slo.evaluate([feed], manifest))["slot-wait-share"]
    assert gate["applicable"] and not gate["ok"]
    assert gate["value"] == 0.1  # 0.2 of 2.0 staged seconds


def test_compiles_zero_burns_on_unexpected_but_not_expected(manifest):
    expected = {"event": "recompile", "run_id": "r", "count": 1,
                "total": 1, "where": "elastic", "expected": True}
    gate = _results_by_id(
        slo.evaluate([expected], manifest))["post-warmup-compiles"]
    assert gate["applicable"] and gate["ok"]
    unexpected = dict(expected, expected=False)
    gate = _results_by_id(
        slo.evaluate([unexpected], manifest))["post-warmup-compiles"]
    assert not gate["ok"]


def test_dropped_zero_burns_on_any_dropped_ticket(manifest):
    summary = {"event": "replica", "run_id": "r", "kind": "summary",
               "requests": 100, "dropped": 1}
    gate = _results_by_id(slo.evaluate([summary], manifest))["zero-drop"]
    assert gate["applicable"] and not gate["ok"]


def test_roofline_gate_burns_on_value_above_bound(manifest):
    bench = {"event": "bench", "run_id": "r", "metric": "m",
             "measured": True, "fenced": True,
             "record": {"metric": "m", "value": 99999.0,
                        "roofline_img_s_upper_bound": 13213.0}}
    gate = _results_by_id(
        slo.evaluate([bench], manifest))["roofline-ceiling"]
    assert gate["applicable"] and not gate["ok"]
    # a rehearsal (measured: false) record is not evidence and not gated
    rehearsal = dict(bench, measured=False)
    gate = _results_by_id(
        slo.evaluate([rehearsal], manifest))["roofline-ceiling"]
    assert not gate["applicable"] and gate["ok"]


# -- verdict event ----------------------------------------------------------


def test_verdict_fields_make_a_schema_valid_slo_event(manifest):
    results = slo.evaluate([_request()], manifest)
    fields = slo.verdict_fields(
        "some_job", results, journal="docs/evidence_r7/x.jsonl",
        manifest_path="docs/slo_manifest.json")
    line = schema.make_event("slo", **fields)
    assert schema.validate_line(line) == []
    assert line["ok"] is True and "burned" not in line


def test_verdict_fields_name_the_burned_gates(manifest):
    events = [_request(wait=500.0) for _ in range(60)]
    results = slo.evaluate(events, manifest)
    fields = slo.verdict_fields("j", results)
    assert fields["ok"] is False
    assert "warm-queue-p99" in fields["burned"]


# -- the repo's own evidence passes its own gates ---------------------------


def test_every_banked_evidence_journal_passes_the_manifest(manifest):
    """The acceptance gate: `python -m sparknet_tpu.obs slo` green over
    all docs/evidence_r*/ journals, the four dryrun specimens included.
    """
    journals = sorted(glob.glob(
        os.path.join(ROOT, "docs", "evidence_r*", "*.jsonl")))
    assert len(journals) >= 7  # r3/r4/r5 ledgers + the four r7 dryruns
    names = {os.path.basename(p) for p in journals}
    for required in ("elastic_dryrun.jsonl", "serve_dryrun.jsonl",
                     "loop_dryrun.jsonl", "replica_dryrun.jsonl"):
        assert required in names, f"banked dryrun specimen missing: {required}"
    for path in journals:
        results = slo.evaluate_journal(path, manifest)
        burned = [r for r in results if not r["ok"]]
        assert not burned, (path, burned)


def test_slo_cli_discovers_and_passes(tmp_path):
    """`obs slo` with no args discovers the banked journals; exit 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "sparknet_tpu.obs", "slo", "--quiet"],
        capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_slo_cli_burns_exit_nonzero(tmp_path):
    journal = tmp_path / "burn.jsonl"
    events = [_request(wait=500.0) for _ in range(60)]
    journal.write_text("".join(json.dumps(e) + "\n" for e in events))
    proc = subprocess.run(
        [sys.executable, "-m", "sparknet_tpu.obs", "slo", str(journal)],
        capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 1
    assert "BURN" in proc.stdout
