"""Differential testing against torch (CPU) as an independent oracle.

The reference validates layer math with hand-derived GradientChecker
bounds (ref: caffe/src/caffe/test/test_convolution_layer.cpp et al.);
gradient checks here live in test_gradients.py.  This file adds what the
reference could not: a second, independently-implemented framework
computing the same math.  Each case runs a sparknet_tpu op and the
equivalent torch functional on identical inputs/weights and requires
agreement to float32 tolerance — catching semantic drift (layout, group
handling, normalization constants) that self-consistent gradient checks
cannot see.

Only configurations whose semantics are *defined identically* in both
frameworks are compared (e.g. AVE pooling is compared on exact-tiling
windows: Caffe's padded-divisor edge rule intentionally differs from
torch's and is pinned by the Caffe-semantics tests in test_compiler.py).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from sparknet_tpu.common import Phase  # noqa: E402
from sparknet_tpu.ops import create_layer  # noqa: E402
from sparknet_tpu.proto import parse  # noqa: E402


def make_layer(prototxt: str, phase=Phase.TRAIN):
    msg = parse(prototxt)
    return create_layer(msg.get_all("layer")[0], phase)


def apply_layer(layer, params, inputs):
    out = layer.apply(
        [jnp.asarray(p) for p in params],
        {},
        [jnp.asarray(x) for x in inputs],
        train=False,
        rng=jax.random.key(0),
    )
    return [np.asarray(o) for o in out.outputs]


def t(x):
    return torch.from_numpy(np.asarray(x))


ATOL = 2e-4  # f32 accumulation-order noise across two frameworks
RTOL = 2e-4


class TestConvolution:
    @pytest.mark.parametrize(
        "stride,pad,group,dilation",
        [(1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 1, 2), (1, 1, 2, 1), (3, 2, 4, 1)],
    )
    def test_forward(self, rng, stride, pad, group, dilation):
        n, cin, cout, k = 2, 8, 12, 3
        x = rng.randn(n, cin, 12, 10).astype(np.float32)
        w = rng.randn(cout, cin // group, k, k).astype(np.float32)
        b = rng.randn(cout).astype(np.float32)
        layer = make_layer(
            f"""layer {{ name: "c" type: "Convolution" bottom: "x" top: "y"
              convolution_param {{ num_output: {cout} kernel_size: {k}
                stride: {stride} pad: {pad} group: {group}
                dilation: {dilation} }} }}"""
        )
        (ours,) = apply_layer(layer, [w, b], [x])
        theirs = F.conv2d(
            t(x), t(w), t(b), stride=stride, padding=pad,
            dilation=dilation, groups=group,
        ).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)

    def test_grad_wrt_input_and_weight(self, rng):
        n, cin, cout, k = 2, 4, 6, 3
        x = rng.randn(n, cin, 8, 8).astype(np.float32)
        w = rng.randn(cout, cin, k, k).astype(np.float32)
        b = rng.randn(cout).astype(np.float32)
        layer = make_layer(
            f"""layer {{ name: "c" type: "Convolution" bottom: "x" top: "y"
              convolution_param {{ num_output: {cout} kernel_size: {k}
                stride: 1 pad: 1 }} }}"""
        )

        def loss(xa, wa, ba):
            out = layer.apply(
                [wa, ba], {}, [xa], train=True, rng=jax.random.key(0)
            )
            return jnp.sum(out.outputs[0] ** 2)

        gx, gw, gb = jax.grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)
        )

        xt, wt, bt = t(x).requires_grad_(), t(w).requires_grad_(), t(b).requires_grad_()
        F.conv2d(xt, wt, bt, stride=1, padding=1).pow(2).sum().backward()
        np.testing.assert_allclose(np.asarray(gx), xt.grad.numpy(), atol=1e-3, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(gw), wt.grad.numpy(), atol=1e-3, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(gb), bt.grad.numpy(), atol=1e-3, rtol=1e-3)


class TestDeconvolution:
    def test_forward(self, rng):
        n, cin, cout, k, stride = 2, 6, 4, 4, 2
        x = rng.randn(n, cin, 5, 7).astype(np.float32)
        # Caffe deconv weight (in, out/group, kh, kw) == torch conv_transpose2d
        w = rng.randn(cin, cout, k, k).astype(np.float32)
        b = rng.randn(cout).astype(np.float32)
        layer = make_layer(
            f"""layer {{ name: "d" type: "Deconvolution" bottom: "x" top: "y"
              convolution_param {{ num_output: {cout} kernel_size: {k}
                stride: {stride} pad: 1 }} }}"""
        )
        (ours,) = apply_layer(layer, [w, b], [x])
        theirs = F.conv_transpose2d(t(x), t(w), t(b), stride=stride, padding=1).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class TestPooling:
    @pytest.mark.parametrize("pad", [0, 1])
    def test_max_ceil_mode(self, rng, pad):
        # Caffe pooling is always ceil-mode; torch matches with
        # ceil_mode=True (both clip windows to the real input for MAX)
        x = rng.randn(2, 3, 9, 11).astype(np.float32)
        layer = make_layer(
            f"""layer {{ name: "p" type: "Pooling" bottom: "x" top: "y"
              pooling_param {{ pool: MAX kernel_size: 3 stride: 2 pad: {pad} }} }}"""
        )
        (ours,) = apply_layer(layer, [], [x])
        theirs = F.max_pool2d(
            t(x), kernel_size=3, stride=2, padding=pad, ceil_mode=True
        ).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)

    def test_ave_exact_tiling(self, rng):
        # exact-tiling window: no edge/padding divisor ambiguity between
        # the two frameworks' AVE rules
        x = rng.randn(2, 4, 8, 8).astype(np.float32)
        layer = make_layer(
            """layer { name: "p" type: "Pooling" bottom: "x" top: "y"
              pooling_param { pool: AVE kernel_size: 2 stride: 2 } }"""
        )
        (ours,) = apply_layer(layer, [], [x])
        theirs = F.avg_pool2d(t(x), kernel_size=2, stride=2).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)

    def test_global_ave(self, rng):
        x = rng.randn(2, 5, 7, 7).astype(np.float32)
        layer = make_layer(
            """layer { name: "p" type: "Pooling" bottom: "x" top: "y"
              pooling_param { pool: AVE global_pooling: true } }"""
        )
        (ours,) = apply_layer(layer, [], [x])
        theirs = F.adaptive_avg_pool2d(t(x), 1).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class TestLRN:
    def test_across_channels(self, rng):
        # both define: x / (k + alpha/n * sum_window x^2)^beta
        x = rng.randn(2, 16, 6, 6).astype(np.float32)
        layer = make_layer(
            """layer { name: "l" type: "LRN" bottom: "x" top: "y"
              lrn_param { local_size: 5 alpha: 0.0001 beta: 0.75 k: 2.0 } }"""
        )
        (ours,) = apply_layer(layer, [], [x])
        theirs = F.local_response_norm(
            t(x), size=5, alpha=1e-4, beta=0.75, k=2.0
        ).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)


class TestInnerProductAndLosses:
    def test_inner_product(self, rng):
        x = rng.randn(4, 3, 4, 4).astype(np.float32)
        w = rng.randn(10, 48).astype(np.float32)
        b = rng.randn(10).astype(np.float32)
        layer = make_layer(
            """layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
              inner_product_param { num_output: 10 } }"""
        )
        (ours,) = apply_layer(layer, [w, b], [x])
        # Caffe flattens NCHW trailing axes; torch .view(N, -1) is the same
        theirs = F.linear(t(x).view(4, -1), t(w), t(b)).numpy()
        np.testing.assert_allclose(ours, theirs, atol=ATOL, rtol=RTOL)

    def test_softmax_with_loss(self, rng):
        logits = rng.randn(8, 10).astype(np.float32)
        labels = rng.randint(0, 10, 8).astype(np.int32)
        layer = make_layer(
            """layer { name: "loss" type: "SoftmaxWithLoss"
              bottom: "ip" bottom: "label" top: "loss" }"""
        )
        (ours,) = apply_layer(layer, [], [logits, labels])
        theirs = F.cross_entropy(t(logits), t(labels).long()).item()
        np.testing.assert_allclose(float(ours), theirs, atol=ATOL, rtol=RTOL)

    def test_softmax_loss_grad(self, rng):
        logits = rng.randn(8, 10).astype(np.float32)
        labels = rng.randint(0, 10, 8).astype(np.int32)
        layer = make_layer(
            """layer { name: "loss" type: "SoftmaxWithLoss"
              bottom: "ip" bottom: "label" top: "loss" }"""
        )

        def loss(la):
            out = layer.apply([], {}, [la, jnp.asarray(labels)],
                              train=True, rng=jax.random.key(0))
            return out.outputs[0].reshape(())

        g = jax.grad(loss)(jnp.asarray(logits))
        lt = t(logits).requires_grad_()
        F.cross_entropy(lt, t(labels).long()).backward()
        np.testing.assert_allclose(np.asarray(g), lt.grad.numpy(),
                                   atol=1e-5, rtol=1e-4)

    def test_sigmoid_cross_entropy(self, rng):
        # Caffe normalizes the summed elementwise BCE by batch size
        # (ref: sigmoid_cross_entropy_loss_layer.cpp)
        logits = rng.randn(6, 5).astype(np.float32)
        targets = (rng.rand(6, 5) > 0.5).astype(np.float32)
        layer = make_layer(
            """layer { name: "loss" type: "SigmoidCrossEntropyLoss"
              bottom: "x" bottom: "t" top: "loss" }"""
        )
        (ours,) = apply_layer(layer, [], [logits, targets])
        theirs = (
            F.binary_cross_entropy_with_logits(
                t(logits), t(targets), reduction="sum"
            ).item() / 6
        )
        np.testing.assert_allclose(float(ours), theirs, atol=ATOL, rtol=RTOL)

    def test_euclidean_loss(self, rng):
        # Caffe: sum((a-b)^2) / (2*N)
        a = rng.randn(4, 7).astype(np.float32)
        b = rng.randn(4, 7).astype(np.float32)
        layer = make_layer(
            """layer { name: "loss" type: "EuclideanLoss"
              bottom: "a" bottom: "b" top: "loss" }"""
        )
        (ours,) = apply_layer(layer, [], [a, b])
        theirs = F.mse_loss(t(a), t(b), reduction="sum").item() / (2 * 4)
        np.testing.assert_allclose(float(ours), theirs, atol=ATOL, rtol=RTOL)


class _TorchLeNet(torch.nn.Module):
    """torch twin of models.lenet (ref: caffe/examples/mnist/lenet.prototxt)."""

    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 20, 5)
        self.conv2 = torch.nn.Conv2d(20, 50, 5)
        self.ip1 = torch.nn.Linear(50 * 4 * 4, 500)
        self.ip2 = torch.nn.Linear(500, 10)

    def forward(self, x):
        x = F.max_pool2d(self.conv1(x), 2, 2, ceil_mode=True)
        x = F.max_pool2d(self.conv2(x), 2, 2, ceil_mode=True)
        x = F.relu(self.ip1(x.view(x.shape[0], -1)))
        return self.ip2(x)


class TestLeNetEndToEnd:
    """Whole-model twin test: same weights, same input -> same logits,
    same loss, same parameter gradients (the strongest cross-framework
    statement: every layer, the flatten boundary, and autodiff agree)."""

    def _build(self, rng):
        from sparknet_tpu import models
        from sparknet_tpu.compiler.graph import Network

        net = Network(models.lenet(batch=4), Phase.TRAIN)
        variables = net.init(jax.random.key(3))

        tnet = _TorchLeNet()
        with torch.no_grad():
            for name, mod in (
                ("conv1", tnet.conv1), ("conv2", tnet.conv2),
                ("ip1", tnet.ip1), ("ip2", tnet.ip2),
            ):
                w, b = variables.params[name]
                mod.weight.copy_(t(np.asarray(w)))
                mod.bias.copy_(t(np.asarray(b)))
        return net, variables, tnet

    def test_forward_loss_and_grads(self, rng):
        net, variables, tnet = self._build(rng)
        x = rng.randn(4, 1, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, 4).astype(np.int32)
        feeds = {"data": jnp.asarray(x), "label": jnp.asarray(y)}

        from sparknet_tpu.compiler.graph import NetVars

        def loss_fn(params):
            v = NetVars(params, variables.state)
            blobs, _, loss = net.apply(v, feeds, rng=jax.random.key(0),
                                       train=True)
            return loss.reshape(()), blobs["ip2"]

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            variables.params
        )

        xt = t(x)
        tl = tnet(xt)
        tloss = F.cross_entropy(tl, t(y).long())
        tloss.backward()

        np.testing.assert_allclose(np.asarray(logits), tl.detach().numpy(),
                                   atol=1e-3, rtol=1e-3)
        np.testing.assert_allclose(float(loss), tloss.item(), atol=1e-4, rtol=1e-4)
        for name, mod in (
            ("conv1", tnet.conv1), ("conv2", tnet.conv2),
            ("ip1", tnet.ip1), ("ip2", tnet.ip2),
        ):
            gw, gb = grads[name]
            np.testing.assert_allclose(
                np.asarray(gw), mod.weight.grad.numpy(), atol=1e-3, rtol=1e-3,
                err_msg=f"{name} weight grad",
            )
            np.testing.assert_allclose(
                np.asarray(gb), mod.bias.grad.numpy(), atol=1e-3, rtol=1e-3,
                err_msg=f"{name} bias grad",
            )


class TestBatchNorm:
    """Caffe BatchNorm (no affine — pair with Scale) vs torch batch_norm.
    Train mode: batch statistics, biased variance (E[x^2]-E[x]^2), matching
    torch's training=True normalization; global mode: stored sums scaled by
    scale_factor, the running-stat path (batch_norm_layer.cpp:27-56)."""

    def test_train_mode_matches_batch_stats(self, rng):
        x = rng.randn(4, 3, 5, 6).astype(np.float32) * 2 + 1
        layer = make_layer(
            """layer { name: "bn" type: "BatchNorm" bottom: "x" top: "y"
              batch_norm_param { eps: 1e-5 } }"""
        )
        _, state = layer.init(jax.random.key(0), [x.shape])
        out = layer.apply([], state, [jnp.asarray(x)], train=True,
                          rng=jax.random.key(0))
        theirs = F.batch_norm(
            t(x), None, None, weight=None, bias=None,
            training=True, eps=1e-5,
        ).numpy()
        np.testing.assert_allclose(
            np.asarray(out.outputs[0]), theirs, atol=1e-4, rtol=1e-4
        )

    def test_global_stats_match_running_stats(self, rng):
        x = rng.randn(4, 3, 5, 6).astype(np.float32)
        rm = rng.randn(3).astype(np.float32)
        rv = (rng.rand(3).astype(np.float32) + 0.5)
        layer = make_layer(
            """layer { name: "bn" type: "BatchNorm" bottom: "x" top: "y"
              batch_norm_param { use_global_stats: true eps: 1e-5 } }"""
        )
        # Caffe stores SUMS + a scale factor; stored/scale = the stat
        state = {
            "mean": jnp.asarray(rm * 4.0),
            "variance": jnp.asarray(rv * 4.0),
            "scale_factor": jnp.asarray([4.0]),
        }
        out = layer.apply([], state, [jnp.asarray(x)], train=False,
                          rng=jax.random.key(0))
        theirs = F.batch_norm(
            t(x), t(rm), t(rv), training=False, eps=1e-5
        ).numpy()
        np.testing.assert_allclose(
            np.asarray(out.outputs[0]), theirs, atol=1e-4, rtol=1e-4
        )

    def test_train_mode_input_gradient_matches_torch(self, rng):
        """Backward through batch statistics (the ResNet training path);
        large unnormalized activations exercise the variance clamp
        without changing the gradient where var > 0."""
        x = rng.randn(2, 4, 3, 3).astype(np.float32) * 40
        co = rng.randn(2, 4, 3, 3).astype(np.float32)
        layer = make_layer(
            """layer { name: "bn" type: "BatchNorm" bottom: "x" top: "y"
              batch_norm_param { eps: 1e-5 } }"""
        )
        _, state = layer.init(jax.random.key(0), [x.shape])

        def f(xx):
            out = layer.apply([], state, [xx], train=True,
                              rng=jax.random.key(0))
            return jnp.vdot(out.outputs[0], jnp.asarray(co))

        ours = jax.grad(f)(jnp.asarray(x))
        xt = t(x).requires_grad_()
        yt = F.batch_norm(xt, None, None, training=True, eps=1e-5)
        yt.backward(t(co))
        np.testing.assert_allclose(np.asarray(ours), xt.grad.numpy(),
                                   atol=1e-5, rtol=1e-4)

    def test_bf16_inputs_use_f32_statistics(self, rng):
        """Mixed-precision contract: E[x^2]-E[x]^2 in bf16 is catastrophic
        on mean-shifted activations (std came out 293x); stats must run
        in f32 with only the output cast back."""
        x = (rng.randn(4, 8, 6, 6) + 100).astype(np.float32)
        layer = make_layer(
            """layer { name: "bn" type: "BatchNorm" bottom: "x" top: "y"
              batch_norm_param { eps: 1e-5 } }"""
        )
        _, state = layer.init(jax.random.key(0), [x.shape])
        out = layer.apply([], state, [jnp.asarray(x, jnp.bfloat16)],
                          train=True, rng=jax.random.key(0))
        y = np.asarray(out.outputs[0], np.float32)
        assert out.outputs[0].dtype == jnp.bfloat16
        assert abs(float(y.std()) - 1.0) < 0.05, y.std()
        assert abs(float(y.mean())) < 0.05, y.mean()


class TestPReLU:
    @pytest.mark.parametrize("shared", [False, True])
    def test_forward_and_grad(self, rng, shared):
        x = rng.randn(3, 4, 5, 5).astype(np.float32)
        a = (rng.rand(1 if shared else 4).astype(np.float32) * 0.5)
        layer = make_layer(
            f"""layer {{ name: "p" type: "PReLU" bottom: "x" top: "y"
              prelu_param {{ channel_shared: {'true' if shared else 'false'} }} }}"""
        )

        def loss(xa, aa):
            out = layer.apply([aa], {}, [xa], train=True, rng=None)
            return jnp.sum(out.outputs[0] ** 3)

        (ours_fwd,) = apply_layer(layer, [a], [x])
        gx, ga = jax.grad(loss, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(a))

        xt = t(x).requires_grad_()
        at = t(a).requires_grad_()
        theirs = F.prelu(xt, at)
        theirs.pow(3).sum().backward()
        np.testing.assert_allclose(ours_fwd, theirs.detach().numpy(),
                                   atol=ATOL, rtol=RTOL)
        np.testing.assert_allclose(np.asarray(gx), xt.grad.numpy(),
                                   atol=1e-3, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(ga), at.grad.numpy(),
                                   atol=1e-3, rtol=1e-3)


class TestEmbed:
    def test_forward_and_weight_grad(self, rng):
        vocab, dim = 11, 7
        idx = rng.randint(0, vocab, (4, 3)).astype(np.int32)
        w = rng.randn(vocab, dim).astype(np.float32)
        layer = make_layer(
            f"""layer {{ name: "e" type: "Embed" bottom: "i" top: "y"
              embed_param {{ input_dim: {vocab} num_output: {dim}
                bias_term: false }} }}"""
        )
        (ours,) = apply_layer(layer, [w], [idx])
        theirs = F.embedding(t(idx).long(), t(w))
        np.testing.assert_allclose(ours, theirs.numpy(), atol=ATOL, rtol=RTOL)

        def loss(wa):
            out = layer.apply([wa], {}, [jnp.asarray(idx)], train=True, rng=None)
            return jnp.sum(out.outputs[0] ** 2)

        gw = jax.grad(loss)(jnp.asarray(w))
        wt = t(w).requires_grad_()
        F.embedding(t(idx).long(), wt).pow(2).sum().backward()
        np.testing.assert_allclose(np.asarray(gw), wt.grad.numpy(),
                                   atol=1e-3, rtol=1e-3)


class TestMVN:
    @pytest.mark.parametrize("across", [False, True])
    def test_matches_manual_layer_norm_math(self, rng, across):
        """MVN = instance/layer norm without affine; torch's
        F.instance_norm / F.layer_norm are the oracles."""
        x = rng.randn(3, 4, 6, 5).astype(np.float32) * 3 + 2
        layer = make_layer(
            f"""layer {{ name: "m" type: "MVN" bottom: "x" top: "y"
              mvn_param {{ across_channels: {'true' if across else 'false'}
                normalize_variance: true eps: 1e-9 }} }}"""
        )
        (ours,) = apply_layer(layer, [], [x])
        if across:
            theirs = F.layer_norm(t(x), x.shape[1:], eps=1e-9).numpy()
        else:
            theirs = F.instance_norm(t(x), eps=1e-9).numpy()
        # MVN divides by (std + eps), torch by sqrt(var + eps): identical
        # to float tolerance at these magnitudes
        np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-4)
