"""Every net prototxt shipped in the reference tree must load and compile.

The strongest parity statement the compiler can make: the reference's own
model files (zoo models + every example, V1 and V2 schemas, BatchNorm/
sigmoid variants, finetuning nets, HDF5 nets, deploy nets) all build
(ref: Net::Init over the same files, net.cpp:40-540)."""

import glob
import os

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.proto import parse_file

REF = "/root/reference/caffe"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")

# the one exclusion: linreg's Python layer names module "pyloss", which
# imports the pycaffe package itself — covered instead by
# test_python_layer.py with an importable module
EXCLUDE = {"linreg.prototxt"}


def _net_files():
    files = sorted(glob.glob(f"{REF}/**/*.prototxt", recursive=True))
    return [
        f for f in files
        if "solver" not in os.path.basename(f)
        and os.path.basename(f) not in EXCLUDE
    ]


@needs_ref
@pytest.mark.smoke
def test_smoke_reference_alexnet_compiles():
    """Smoke-tier single compile: the canonical AlexNet train_val file
    builds in both phases (the full sweep covers every file)."""
    npz = parse_file(f"{REF}/models/bvlc_alexnet/train_val.prototxt")
    for phase in (Phase.TRAIN, Phase.TEST):
        net = Network(npz, phase)
        assert net.layers


@needs_ref
@pytest.mark.parametrize("path", _net_files(), ids=lambda p: p.split("caffe/")[-1])
def test_reference_prototxt_compiles(path):
    npz = parse_file(path)
    for phase in (Phase.TRAIN, Phase.TEST):
        net = Network(npz, phase)
        assert net.layers or net.net_inputs


@needs_ref
def test_reference_example_nets_shape_infer():
    """Full init (shape inference + param materialization) on the small
    example nets, with runtime-shaped feeds for DB-backed data layers."""
    cases = {
        "examples/mnist/mnist_autoencoder.prototxt": {"data": (4, 1, 28, 28)},
        "examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt": {
            "data": (4, 3, 32, 32), "label": (4,)},
        "examples/hdf5_classification/nonlinear_train_val.prototxt": {
            "data": (4, 4), "label": (4,)},
        "examples/siamese/mnist_siamese_train_test.prototxt": {
            "pair_data": (4, 2, 28, 28), "sim": (4,)},
    }
    for rel, shapes in cases.items():
        net = Network(parse_file(f"{REF}/{rel}"), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0), feed_shapes=shapes)
        assert variables.params, rel


# ---------------------------------------------------------------------------
# Full init of EVERY reference net — shape inference + param materialization,
# not just graph construction (ref: Net::Init, net.cpp:40-540, is the real
# contract: Caffe nets that "parse" but can't shape-infer are broken).
# DB/HDF5/ImageData/WindowData-backed feeds don't declare shapes in the
# prototxt (they come from the data source), so each such net gets the
# runtime-shaped feed hint its source would produce, at batch 2.
# ---------------------------------------------------------------------------
B = 2
_IMNET = {"data": (B, 3, 227, 227), "label": (B,)}
_MNIST = {"data": (B, 1, 28, 28), "label": (B,)}
_CIFAR = {"data": (B, 3, 32, 32), "label": (B,)}
FEED_HINTS = {
    "models/bvlc_alexnet/train_val.prototxt": _IMNET,
    "models/bvlc_reference_caffenet/train_val.prototxt": _IMNET,
    "models/bvlc_googlenet/train_val.prototxt": {"data": (B, 3, 224, 224),
                                                 "label": (B,)},
    "models/finetune_flickr_style/train_val.prototxt": _IMNET,
    "examples/cifar10/cifar10_full.prototxt": _CIFAR,
    "examples/cifar10/cifar10_full_java_train_test.prototxt": _CIFAR,
    "examples/cifar10/cifar10_full_sigmoid_train_test.prototxt": _CIFAR,
    "examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt": _CIFAR,
    "examples/cifar10/cifar10_full_train_test.prototxt": _CIFAR,
    "examples/cifar10/cifar10_quick.prototxt": _CIFAR,
    "examples/cifar10/cifar10_quick_train_test.prototxt": _CIFAR,
    "examples/feature_extraction/imagenet_val.prototxt": _IMNET,
    "examples/finetune_pascal_detection/pascal_finetune_trainval_test.prototxt":
        _IMNET,
    "examples/hdf5_classification/nonlinear_auto_test.prototxt":
        {"data": (B, 4), "label": (B,)},
    "examples/hdf5_classification/nonlinear_auto_train.prototxt":
        {"data": (B, 4), "label": (B,)},
    "examples/hdf5_classification/nonlinear_train_val.prototxt":
        {"data": (B, 4), "label": (B,)},
    "examples/hdf5_classification/train_val.prototxt":
        {"data": (B, 4), "label": (B,)},
    "examples/mnist/lenet_train_test.prototxt": _MNIST,
    "examples/mnist/mnist_autoencoder.prototxt": {"data": (B, 1, 28, 28)},
    "examples/siamese/mnist_siamese_train_test.prototxt":
        {"pair_data": (B, 2, 28, 28), "sim": (B,)},
}

# the canonical published param counts (alexnet readme: ~61M; googlenet
# readme: ~13.4M including both auxiliary towers)
PARAM_COUNT_PINS = {
    "models/bvlc_alexnet/train_val.prototxt": 60_965_224,
    "models/bvlc_googlenet/train_val.prototxt": 13_378_280,
}


def _param_count(variables) -> int:
    return sum(int(a.size) for plist in variables.params.values() for a in plist)


@needs_ref
@pytest.mark.slow
@pytest.mark.parametrize("path", _net_files(), ids=lambda p: p.split("caffe/")[-1])
def test_reference_prototxt_full_init(path):
    rel = path.split("caffe/")[-1]
    npz = parse_file(path)
    net = Network(npz, Phase.TRAIN, batch_override=B)
    variables = net.init(jax.random.PRNGKey(0),
                         feed_shapes=FEED_HINTS.get(rel))
    if rel in PARAM_COUNT_PINS:
        assert _param_count(variables) == PARAM_COUNT_PINS[rel], rel


@needs_ref
@pytest.mark.slow
def test_zoo_googlenet_matches_reference_file():
    """The DSL GoogLeNet is the published recipe: same param count as a full
    init of the reference train_val file (13,378,280 — INCLUDING both aux
    towers), and the TRAIN loss is three weighted terms (0.3 + 0.3 + 1.0)."""
    from sparknet_tpu.models import zoo

    ref = Network(parse_file(f"{REF}/models/bvlc_googlenet/train_val.prototxt"),
                  Phase.TRAIN, batch_override=B)
    ref_vars = ref.init(jax.random.PRNGKey(0),
                        feed_shapes={"data": (B, 3, 224, 224), "label": (B,)})

    dsl = Network(zoo.googlenet(batch=B), Phase.TRAIN)
    dsl_vars = dsl.init(jax.random.PRNGKey(0))

    assert _param_count(dsl_vars) == _param_count(ref_vars) == 13_378_280

    loss_terms = {
        l.name: list(l.loss_weights()) for l in dsl.layers
        if any(w != 0 for w in l.loss_weights())
    }
    assert loss_terms == {
        "loss1/loss": [0.3], "loss2/loss": [0.3], "loss3/loss3": [1.0],
    }


@needs_ref
def test_every_reference_solver_prototxt_parses():
    """All 29 solver prototxts in the reference tree produce a valid
    SolverConfig (every optimizer recipe, LR policy, and test_state form
    the zoo ships)."""
    from sparknet_tpu.solvers.solver import SolverConfig

    files = sorted(glob.glob(f"{REF}/**/*solver*.prototxt", recursive=True))
    assert len(files) >= 25
    for f in files:
        cfg = SolverConfig.from_proto(parse_file(f))
        assert cfg.base_lr > 0, f  # every zoo recipe sets a real LR


class TestResNet50:
    """zoo:resnet50 — the first post-reference family (He et al. 2016,
    Caffe deploy wiring: bias-free convs + BatchNorm/Scale pairs).  The
    load-bearing pin is the published parameter count."""

    def test_param_pin_and_bn_state(self):
        from sparknet_tpu.models import zoo

        net = Network(zoo.resnet50(batch=2), Phase.TRAIN)
        v = net.init(jax.random.PRNGKey(0))
        assert _param_count(v) == 25_557_032  # torchvision resnet50
        # 53 BatchNorm layers (conv1 + 16 blocks x 3 + 4 projections),
        # each holding mean/variance/scale_factor in mutable state
        bn_states = [k for k, s in v.state.items() if "scale_factor" in s]
        assert len(bn_states) == 53

    def test_trains_and_bn_stats_move(self):
        import dataclasses

        import numpy as np

        from sparknet_tpu.models import zoo
        from sparknet_tpu.solvers.solver import Solver

        # small-scale smoke at crop 64 / batch 4: stage-5 maps are 2x2,
        # keeping per-channel BN statistics non-degenerate (crop 32
        # collapses them to 1x1 over batch 2 = two samples per channel,
        # where 1/sigma legitimately explodes); the recipe lr (0.1,
        # tuned for batch 256) is scaled down for the 4-image fixture
        cfg = dataclasses.replace(zoo.resnet50_solver(), base_lr=1e-3)
        net_param = zoo.resnet50(batch=4, num_classes=5, crop=64)
        solver = Solver(cfg, net_param)
        rs = np.random.RandomState(0)

        def feed(it):
            return {
                "data": rs.randn(4, 3, 64, 64).astype(np.float32) * 40,
                "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
            }

        losses = [float(solver.step(1, feed)) for _ in range(4)]
        assert np.all(np.isfinite(losses)), losses  # BN var clamp holds
        sf = next(s["scale_factor"] for k, s in solver.variables.state.items()
                  if "scale_factor" in s)
        assert float(sf[0]) > 0  # moving stats accumulated

    def test_eval_uses_global_stats(self):
        """TEST phase consumes the train-accumulated moving stats (a
        never-trained net's zero stats legitimately explode through 53
        unnormalized layers — the realistic flow trains first)."""
        import dataclasses

        import numpy as np

        from sparknet_tpu.models import zoo
        from sparknet_tpu.solvers.solver import Solver

        cfg = dataclasses.replace(zoo.resnet50_solver(), base_lr=1e-3)
        solver = Solver(cfg, zoo.resnet50(batch=4, num_classes=5, crop=64))
        rs = np.random.RandomState(1)

        def feed(it):
            return {
                "data": rs.randn(4, 3, 64, 64).astype(np.float32) * 40,
                "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
            }

        solver.step(2, feed)
        scores = solver.test(2, feed)
        assert np.isfinite(scores["loss"]), scores
        assert 0.0 <= scores["accuracy"] <= 1.0

    def test_bn_fraction_knob(self):
        """bn_fraction reaches every BatchNorm layer's proto param (the
        short-schedule eval-stats knob examples/10 uses)."""
        from sparknet_tpu.models import zoo

        net = zoo.resnet50(batch=2, bn_fraction=0.9)
        fracs = [
            lp.get_msg("batch_norm_param").get_float(
                "moving_average_fraction", 0.999)
            for lp in net.get_all("layer") if lp.get_str("type") == "BatchNorm"
        ]
        assert len(fracs) == 53 and all(f == 0.9 for f in fracs), fracs


class TestVGG16:
    """zoo:vgg16 — the second post-reference family (Simonyan &
    Zisserman 2015 configuration D, Caffe model-zoo
    VGG_ILSVRC_16_layers wiring).  Load-bearing pin: the published
    138,357,544 parameter count; the family exists as the zoo's
    compute-roofline (MXU-saturating) member."""

    def test_param_pin_and_shape(self):
        from sparknet_tpu.models import zoo

        net = Network(zoo.vgg16(batch=2), Phase.TRAIN)
        v = net.init(jax.random.PRNGKey(0))
        assert _param_count(v) == 138_357_544  # torchvision vgg16
        # 13 convs + 3 FCs carry weights; nothing else does
        assert sum(1 for k in v.params if "conv" in k) == 13
        assert sum(1 for k in v.params if k.startswith("fc")) == 3

    def test_trains_at_small_scale(self):
        import dataclasses

        import numpy as np

        from sparknet_tpu.models import zoo
        from sparknet_tpu.solvers.solver import Solver

        # crop 64 keeps pool5 at 2x2 (five 2x2/2 pools); gauss-0.01 FC
        # init at lr 0.01 is the published recipe but too hot for a
        # 4-image fixture, so scale down as the resnet50 smoke does
        cfg = dataclasses.replace(zoo.vgg16_solver(), base_lr=1e-3)
        net_param = zoo.vgg16(batch=4, num_classes=5, crop=64)
        solver = Solver(cfg, net_param)
        rs = np.random.RandomState(0)

        def feed(it):
            return {
                "data": rs.randn(4, 3, 64, 64).astype(np.float32) * 40,
                "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
            }

        losses = [float(solver.step(1, feed)) for _ in range(3)]
        assert np.all(np.isfinite(losses)), losses
        scores = solver.test(2, feed)
        assert 0.0 <= scores["accuracy"] <= 1.0

    def test_msra_init_knob(self):
        """msra_init=True swaps every conv filler (the published gauss
        0.01 vanishes ~1e-5 by conv5_3 — config D never trained from
        scratch; verified in the round-4 CPU drive where the default sat
        at chance and msra reached 1.0 on the overfit fixture)."""
        from sparknet_tpu.models import zoo

        for flag, want in ((False, "gaussian"), (True, "msra")):
            net = zoo.vgg16(batch=2, msra_init=flag)
            fillers = {
                lp.get_msg("convolution_param").get_msg(
                    "weight_filler").get_str("type")
                for lp in net.get_all("layer")
                if lp.get_str("type") == "Convolution"
            }
            assert fillers == {want}, (flag, fillers)


class TestSqueezeNet:
    """zoo:squeezenet — post-reference family #3 (Iandola et al. 2016
    v1.1, the official Caffe release's wiring).  Load-bearing pin: the
    published 1,235,496 parameter count (~50x smaller than AlexNet);
    the family exists as the zoo's deploy-efficiency member — the
    all-conv classifier + global average pool is exactly the form the
    int8 PTQ path quantizes without BN folding."""

    def test_param_pin_and_shape(self):
        from sparknet_tpu.models import zoo

        net = Network(zoo.squeezenet(batch=2), Phase.TRAIN)
        v = net.init(jax.random.PRNGKey(0))
        assert _param_count(v) == 1_235_496
        # 8 fire modules x 3 convs + conv1 + conv10 carry weights; no fc
        assert sum(1 for k in v.params if k.startswith("fire")) == 24
        assert not any(k.startswith("fc") for k in v.params)

    def test_trains_at_small_scale(self):
        import dataclasses

        import numpy as np

        from sparknet_tpu.models import zoo
        from sparknet_tpu.solvers.solver import Solver

        cfg = dataclasses.replace(zoo.squeezenet_solver(), base_lr=1e-3)
        solver = Solver(cfg, zoo.squeezenet(batch=4, num_classes=5, crop=64))
        rs = np.random.RandomState(0)

        def feed(it):
            return {
                "data": rs.randn(4, 3, 64, 64).astype(np.float32) * 40,
                "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
            }

        losses = [float(solver.step(1, feed)) for _ in range(3)]
        assert np.all(np.isfinite(losses)), losses
        scores = solver.test(2, feed)
        assert 0.0 <= scores["accuracy"] <= 1.0

    def test_int8_quantizes_without_folding(self):
        """The deploy story: every weighted layer is a Convolution, so
        quant.calibrate covers the whole net with no BN-fold prepass."""
        import numpy as np

        from sparknet_tpu import quant
        from sparknet_tpu.models import zoo

        net = Network(zoo.squeezenet(batch=2, num_classes=5, crop=64),
                      Phase.TEST)
        v = net.init(jax.random.PRNGKey(0))
        rs = np.random.RandomState(0)
        feeds = {"data": jnp.asarray(rs.randn(2, 3, 64, 64) * 40,
                                     jnp.float32),
                 "label": jnp.asarray([0, 1], jnp.int32)}
        qstate = quant.calibrate(net, v, [feeds])
        assert len(qstate) >= 26  # conv1 + 24 fire convs + conv10
        with quant.quantized_inference(qstate):
            blobs, _, _ = net.apply(v, feeds, rng=None, train=False)
        assert np.all(np.isfinite(np.asarray(blobs["flat10"])))


class TestMobileNet:
    """zoo:mobilenet — post-reference family #4 (MobileNet v1 1.0x,
    Howard et al. 2017).  Load-bearing pin: the standard 4,231,976
    parameter count; the family exists as the zoo's depthwise member —
    the only net whose hot op is grouped convolution at group ==
    channels (the MXU's bandwidth-bound worst case)."""

    def test_param_pin_and_shape(self):
        from sparknet_tpu.models import zoo

        net = Network(zoo.mobilenet(batch=2), Phase.TRAIN)
        v = net.init(jax.random.PRNGKey(0))
        assert _param_count(v) == 4_231_976
        # 13 dw + 13 sep + conv1 + fc7 carry conv weights
        assert sum(1 for k in v.params if "/dw" in k and k.startswith("conv")) == 13
        # depthwise blobs are (C, 1, 3, 3)
        assert np.asarray(v.params["conv5_1/dw"][0]).shape == (512, 1, 3, 3)

    def test_trains_at_small_scale(self):
        import dataclasses

        from sparknet_tpu.models import zoo
        from sparknet_tpu.solvers.solver import Solver

        cfg = dataclasses.replace(zoo.mobilenet_solver(), base_lr=1e-3)
        solver = Solver(cfg, zoo.mobilenet(batch=4, num_classes=5, crop=64,
                                           bn_fraction=0.9))
        rs = np.random.RandomState(0)

        def feed(it):
            return {
                "data": rs.randn(4, 3, 64, 64).astype(np.float32),
                "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
            }

        losses = [float(solver.step(1, feed)) for _ in range(3)]
        assert np.all(np.isfinite(losses)), losses
        scores = solver.test(2, feed)
        assert 0.0 <= scores["accuracy"] <= 1.0

    def test_all_27_bn_chains_fold(self):
        """Every Conv+BN+Scale chain (conv1 + 13 dw + 13 sep) folds for
        deployment, and the folded net scores identically — the
        merge_bn flow on the depthwise family."""
        import dataclasses

        from sparknet_tpu.compiler.graph import NetVars
        from sparknet_tpu.models import zoo
        from sparknet_tpu.models.fold_bn import fold_batchnorm
        from sparknet_tpu.solvers.solver import Solver

        cfg = dataclasses.replace(zoo.mobilenet_solver(), base_lr=1e-3)
        solver = Solver(cfg, zoo.mobilenet(batch=4, num_classes=5, crop=64,
                                           bn_fraction=0.9))
        rs = np.random.RandomState(0)
        solver.step(3, lambda it: {
            "data": rs.randn(4, 3, 64, 64).astype(np.float32),
            "label": rs.randint(0, 5, size=(4,)).astype(np.int32)})

        net_param = solver.train_net.net_param
        feeds = {"data": jnp.asarray(rs.randn(4, 3, 64, 64), jnp.float32),
                 "label": jnp.asarray(rs.randint(0, 5, 4), jnp.int32)}
        ref_net = Network(net_param, Phase.TEST)
        ref, _, _ = ref_net.apply(solver.variables, feeds, rng=None,
                                  train=False)

        net2, params2, state2, folded = fold_batchnorm(
            net_param, solver.variables.params, solver.variables.state)
        assert len(folded) == 27, folded
        out_net = Network(net2, Phase.TEST)
        out, _, _ = out_net.apply(NetVars(params=params2, state=state2),
                                  feeds, rng=None, train=False)
        np.testing.assert_allclose(np.asarray(out["flat7"]),
                                   np.asarray(ref["flat7"]),
                                   rtol=2e-4, atol=2e-4)
