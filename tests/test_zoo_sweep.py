"""Every net prototxt shipped in the reference tree must load and compile.

The strongest parity statement the compiler can make: the reference's own
model files (zoo models + every example, V1 and V2 schemas, BatchNorm/
sigmoid variants, finetuning nets, HDF5 nets, deploy nets) all build
(ref: Net::Init over the same files, net.cpp:40-540)."""

import glob
import os

import jax
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.proto import parse_file

REF = "/root/reference/caffe"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")

# the one exclusion: linreg's Python layer names module "pyloss", which
# imports the pycaffe package itself — covered instead by
# test_python_layer.py with an importable module
EXCLUDE = {"linreg.prototxt"}


def _net_files():
    files = sorted(glob.glob(f"{REF}/**/*.prototxt", recursive=True))
    return [
        f for f in files
        if "solver" not in os.path.basename(f)
        and os.path.basename(f) not in EXCLUDE
    ]


@needs_ref
@pytest.mark.parametrize("path", _net_files(), ids=lambda p: p.split("caffe/")[-1])
def test_reference_prototxt_compiles(path):
    npz = parse_file(path)
    for phase in (Phase.TRAIN, Phase.TEST):
        net = Network(npz, phase)
        assert net.layers or net.net_inputs


@needs_ref
def test_reference_example_nets_shape_infer():
    """Full init (shape inference + param materialization) on the small
    example nets, with runtime-shaped feeds for DB-backed data layers."""
    cases = {
        "examples/mnist/mnist_autoencoder.prototxt": {"data": (4, 1, 28, 28)},
        "examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt": {
            "data": (4, 3, 32, 32), "label": (4,)},
        "examples/hdf5_classification/nonlinear_train_val.prototxt": {
            "data": (4, 4), "label": (4,)},
        "examples/siamese/mnist_siamese_train_test.prototxt": {
            "pair_data": (4, 2, 28, 28), "sim": (4,)},
    }
    for rel, shapes in cases.items():
        net = Network(parse_file(f"{REF}/{rel}"), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0), feed_shapes=shapes)
        assert variables.params, rel


@needs_ref
def test_every_reference_solver_prototxt_parses():
    """All 29 solver prototxts in the reference tree produce a valid
    SolverConfig (every optimizer recipe, LR policy, and test_state form
    the zoo ships)."""
    from sparknet_tpu.solvers.solver import SolverConfig

    files = sorted(glob.glob(f"{REF}/**/*solver*.prototxt", recursive=True))
    assert len(files) >= 25
    for f in files:
        cfg = SolverConfig.from_proto(parse_file(f))
        assert cfg.base_lr > 0, f  # every zoo recipe sets a real LR
