"""Sequence parallelism through the model front door.

The flagship long-context feature composed with the framework proper: a
prototxt/DSL transformer's MultiHeadAttention layers run ring or Ulysses
attention over a 'seq' mesh axis when trained under `ParallelTrainer`
(ref boundary: SURVEY §5 long-context — absent in the reference; this is
the TPU-first extra, now reachable without touching the primitives).
"""

import jax
import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.parallel.mesh import auto_mesh
from sparknet_tpu.parallel.sharding import ShardingRules
from sparknet_tpu.parallel.trainer import ParallelTrainer
from sparknet_tpu.solvers.solver import Solver

B, S = 16, 32


def _feeds(n, seed=0):
    rs = np.random.RandomState(seed)
    return [
        {
            "data": rs.randint(0, 64, (B, S)).astype(np.int32),
            "label": rs.randint(0, 10, B).astype(np.int32),
        }
        for _ in range(n)
    ]


def _train_single(feeds):
    s = Solver(models.transformer_solver(), models.transformer(B, seq_len=S))
    for f in feeds:
        s.step(1, lambda it, f=f: f)
    return s


def _train_mesh(feeds, impl, seq_parallel=4):
    mesh = auto_mesh(seq_parallel=seq_parallel)
    s = Solver(models.transformer_solver(), models.transformer(B, seq_len=S))
    tr = ParallelTrainer(
        s, mesh=mesh, tau=1, rules=ShardingRules(attention_impl=impl)
    )
    for f in feeds:
        loss = tr.train_round(lambda it, f=f: f)
    tr.sync_to_solver()
    return s, tr, loss


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_transformer_seq_parallel_matches_single_device(impl):
    """3 SGD steps on a (data=2, seq=4) mesh == single device, for both
    attention impls (transformer has 4 heads -> ulysses 4-way works)."""
    feeds = _feeds(3)
    ref = _train_single(feeds)
    got, _, loss = _train_mesh(feeds, impl)
    assert np.isfinite(loss)
    for lname, plist in ref.variables.params.items():
        for a, b in zip(plist, got.variables.params[lname]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, err_msg=lname
            )


def test_seq_parallel_eval_matches_single_device():
    feeds = _feeds(2)
    ref = _train_single(feeds)
    got, tr, _ = _train_mesh(feeds, "ring")
    test_feeds = _feeds(2, seed=7)
    ref_scores = ref.test(2, lambda b: test_feeds[b])
    got_scores = tr.test(2, lambda b: test_feeds[b])
    assert got_scores["accuracy"] == pytest.approx(
        ref_scores["accuracy"], abs=1e-5
    )


def test_seq_axis_requires_tau_1():
    mesh = auto_mesh(seq_parallel=4)
    s = Solver(models.transformer_solver(), models.transformer(B, seq_len=S))
    with pytest.raises(ValueError, match="tau=1"):
        ParallelTrainer(s, mesh=mesh, tau=3)


def test_seq_feed_divisibility():
    """Explicitly-listed seq feeds fail loudly on a non-divisible length;
    the auto default falls back to batch-only sharding and still trains
    (sharding is layout, not semantics)."""
    rs = np.random.RandomState(0)
    feed = {
        "data": rs.randint(0, 64, (B, 30)).astype(np.int32),
        "label": rs.randint(0, 10, B).astype(np.int32),
    }

    mesh = auto_mesh(seq_parallel=4)
    s = Solver(
        models.transformer_solver(), models.transformer(B, seq_len=30)
    )
    tr = ParallelTrainer(
        s, mesh=mesh, tau=1, rules=ShardingRules(seq_feeds=("data",))
    )
    with pytest.raises(ValueError, match="not divisible"):
        tr.train_round(lambda it: feed)

    s2 = Solver(
        models.transformer_solver(), models.transformer(B, seq_len=30)
    )
    tr2 = ParallelTrainer(s2, mesh=mesh, tau=1)
    loss = tr2.train_round(lambda it: feed)
    assert np.isfinite(loss)


def test_ulysses_head_divisibility_error():
    """8-way seq axis > 4 heads: the layer's dispatch raises the clear
    ulysses error at trace time."""
    mesh = auto_mesh(seq_parallel=8)
    s = Solver(
        models.transformer_solver(),
        models.transformer(8, seq_len=S, heads=4),
    )
    tr = ParallelTrainer(
        s, mesh=mesh, tau=1, rules=ShardingRules(attention_impl="ulysses")
    )
    feeds = _feeds(1)[0]
    feeds = {"data": feeds["data"][:8], "label": feeds["label"][:8]}
    with pytest.raises(ValueError, match="divisible"):
        tr.train_round(lambda it: feeds)


def test_rules_can_disable_sequence_parallel():
    """sequence_parallel=False: same mesh, but feeds replicate the seq
    axis and attention stays local (still correct, no SP collectives)."""
    feeds = _feeds(2)
    ref = _train_single(feeds)
    mesh = auto_mesh(seq_parallel=4)
    s = Solver(models.transformer_solver(), models.transformer(B, seq_len=S))
    tr = ParallelTrainer(
        s, mesh=mesh, tau=1, rules=ShardingRules(sequence_parallel=False)
    )
    for f in feeds:
        loss = tr.train_round(lambda it, f=f: f)
    assert np.isfinite(loss)
    tr.sync_to_solver()
    a = ref.variables.params["attn1"][0]
    b = s.variables.params["attn1"][0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)
