"""HDF5 weight/data IO, WorkerStore, and remat tests."""

import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.data.hdf5 import hdf5_minibatches, read_hdf5_file, write_hdf5_file
from sparknet_tpu.net import TPUNet
from sparknet_tpu.worker_store import WorkerStore, worker_store

h5py = pytest.importorskip("h5py")


# ---------------------------------------------------------------- hdf5 data
def test_hdf5_minibatches_across_files(tmp_path):
    rs = np.random.RandomState(0)
    for i, n in enumerate([5, 7]):
        write_hdf5_file(
            str(tmp_path / f"part{i}.h5"),
            {"data": rs.randn(n, 3, 4, 4).astype(np.float32),
             "label": np.arange(n, dtype=np.int32) + i * 100},
        )
    src = tmp_path / "source.txt"
    src.write_text("part0.h5\npart1.h5\n")  # relative paths resolve vs source
    batches = list(hdf5_minibatches(str(src), 4))
    # 12 samples -> 3 full batches, ragged tail dropped
    assert len(batches) == 3
    assert batches[0]["data"].shape == (4, 3, 4, 4)
    # batch 2 spans the file boundary: labels 4 then 100
    np.testing.assert_array_equal(batches[1]["label"], [4, 100, 101, 102])


def test_hdf5_file_mismatched_dims_raises(tmp_path):
    p = str(tmp_path / "bad.h5")
    write_hdf5_file(p, {"data": np.zeros((4, 2)), "label": np.zeros(3)})
    with pytest.raises(ValueError, match="leading dim"):
        read_hdf5_file(p)


def test_hdf5_minibatches_loop(tmp_path):
    write_hdf5_file(str(tmp_path / "a.h5"),
                    {"data": np.zeros((4, 2), np.float32),
                     "label": np.arange(4, dtype=np.int32)})
    (tmp_path / "src.txt").write_text("a.h5\n")
    it = hdf5_minibatches(str(tmp_path / "src.txt"), 3, loop=True)
    a = next(it)
    b = next(it)  # second epoch restarts cleanly
    np.testing.assert_array_equal(a["label"], [0, 1, 2])
    np.testing.assert_array_equal(b["label"], [0, 1, 2])


# ---------------------------------------------------------------- hdf5 weights
def test_tpunet_hdf5_weights_roundtrip(tmp_path):
    net = TPUNet(models.lenet_solver(), models.lenet(2))
    p = str(tmp_path / "w.caffemodel.h5")
    net.save_weights_to_file(p)
    net2 = TPUNet(models.lenet_solver(), models.lenet(2))
    net2.load_weights_from_file(p)
    for lname, plist in net.solver.variables.params.items():
        for a, b in zip(plist, net2.solver.variables.params[lname]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------- worker store
def test_worker_store_contract():
    ws = WorkerStore()
    ws.set("net", {"x": 1})
    assert ws.contains("net")
    assert ws.get("net")["x"] == 1
    with pytest.raises(KeyError):
        ws.get("missing")
    ws.remove("net")
    assert not ws.contains("net")
    # module singleton exists and is the same object across imports
    from sparknet_tpu.worker_store import worker_store as ws2

    assert ws2 is worker_store


# ---------------------------------------------------------------- remat
def test_remat_solver_trains_identically():
    """jax.checkpoint must not change the math — losses match exactly."""
    import dataclasses

    from sparknet_tpu.solvers.solver import Solver

    rs = np.random.RandomState(0)
    feeds = {
        "data": rs.randn(4, 1, 28, 28).astype(np.float32),
        "label": rs.randint(0, 10, 4).astype(np.int32),
    }
    base = models.lenet_solver()
    s1 = Solver(base, models.lenet(4))
    s2 = Solver(dataclasses.replace(base, remat=True), models.lenet(4))
    l1 = s1.step(3, lambda it: feeds)
    l2 = s2.step(3, lambda it: feeds)
    assert np.allclose(l1, l2, atol=1e-6), (l1, l2)


def test_hdf5_minibatches_too_small_loop_raises(tmp_path):
    write_hdf5_file(str(tmp_path / "t.h5"),
                    {"data": np.zeros((2, 2), np.float32),
                     "label": np.zeros(2, np.int32)})
    (tmp_path / "s.txt").write_text("t.h5\n")
    with pytest.raises(ValueError, match="spin forever"):
        next(hdf5_minibatches(str(tmp_path / "s.txt"), 3, loop=True))


def test_copy_hdf5_params_permissive_skips_mismatched_layer(tmp_path):
    """strict_shapes=False skips a size-mismatched layer (the finetune
    changed-head case) instead of raising — parity with the caffemodel
    loader's permissive mode."""
    import jax
    import pytest

    from sparknet_tpu import models
    from sparknet_tpu.net import TPUNet, copy_hdf5_params
    from sparknet_tpu.solvers.solver import SolverConfig

    donor = TPUNet(SolverConfig(), models.lenet(4, num_classes=10))
    path = str(tmp_path / "donor.h5")
    donor.save_hdf5(path)

    target = TPUNet(SolverConfig(), models.lenet(4, num_classes=3))
    with pytest.raises(ValueError, match="ip2"):
        copy_hdf5_params(target.solver.variables.params, path)
    params, loaded = copy_hdf5_params(
        target.solver.variables.params, path, strict_shapes=False
    )
    assert "conv1" in loaded and "ip2" not in loaded
    assert np.array_equal(
        np.asarray(params["conv1"][0]),
        np.asarray(donor.solver.variables.params["conv1"][0]),
    )
    # the skipped head keeps its fresh init shape
    assert params["ip2"][0].shape == target.solver.variables.params["ip2"][0].shape


def test_copy_hdf5_legacy_empty_bn_group_skips(tmp_path):
    """Pre-round-4 exports wrote an EMPTY group for BatchNorm layers
    (no params, state did not ride the wire yet); the state-aware
    strict loader must SKIP such layers — keeping the net's current
    statistics, mirroring the binary loader's empty-blob skip — not
    raise a strict-shape error (round-4 advisor finding)."""
    import h5py

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.net import copy_hdf5_params
    from sparknet_tpu.proto import parse

    BN_NET = """
    name: "bn_net"
    layer { name: "data" type: "MemoryData" top: "data" top: "label"
            memory_data_param { batch_size: 2 channels: 3 height: 8 width: 8 } }
    layer { name: "conv" type: "Convolution" bottom: "data" top: "a"
            convolution_param { num_output: 4 kernel_size: 3 bias_term: false
                                weight_filler { type: "gaussian" std: 0.1 } } }
    layer { name: "bn" type: "BatchNorm" bottom: "a" top: "a" }
    """
    import jax

    net = Network(parse(BN_NET), Phase.TRAIN)
    v = net.init(jax.random.PRNGKey(0))

    path = str(tmp_path / "legacy.h5")
    with h5py.File(path, "w") as f:
        data = f.create_group("data")
        g = data.create_group("conv")
        g.create_dataset("0", data=np.ones_like(np.asarray(v.params["conv"][0])))
        data.create_group("bn")  # legacy: EMPTY group, no state blobs

    params, new_state, loaded = copy_hdf5_params(
        v.params, path, strict_shapes=True, state=v.state)
    assert "conv" in loaded and "bn" not in loaded
    assert np.all(np.asarray(params["conv"][0]) == 1.0)
    # bn keeps its current (fresh) statistics untouched
    for k, a in v.state["bn"].items():
        assert np.array_equal(np.asarray(new_state["bn"][k]), np.asarray(a))
