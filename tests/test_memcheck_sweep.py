"""The full memcheck mode sweep + batch-fit verification vs the bank.

Slow-marked twin of tests/test_memcheck.py's solo+dp smoke gate: every
registered parallel mode (plus the ``kernels`` VMEM audit) is traced on
the virtual 8-device mesh and diffed against ``docs/mem_contracts/``,
and the batch-fit solver re-derives a representative family slice
(cheap vehicle, conv family with TP-shardable fc blobs, the
sequence-parallel transformer row) against the banked table.  CLI
equivalents: ``python -m sparknet_tpu.analysis mem`` / ``mem --fit``
(regenerate with ``--update``).
"""

import pytest

from sparknet_tpu.analysis.mem_model import (
    HBM_USABLE_FRAC,
    PEAK_RATIO_WINDOW,
    RESIDENCY_TOL_BYTES,
    V5E_HBM_BYTES,
)
from sparknet_tpu.analysis.memcheck import run_batch_fit, run_memcheck
from sparknet_tpu.parallel.modes import list_modes

pytestmark = pytest.mark.slow


def test_memcheck_full_sweep_is_clean():
    findings, manifests = run_memcheck()
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    assert set(manifests) == set(list_modes()) | {"kernels"}
    budget = int(V5E_HBM_BYTES * HBM_USABLE_FRAC)
    lo, hi = PEAK_RATIO_WINDOW
    for mode, manifest in manifests.items():
        if mode == "kernels":
            assert all(p["fits"] for p in manifest["contract"]["points"])
            continue
        c = manifest["contract"]
        assert c["residency_delta_bytes"] <= RESIDENCY_TOL_BYTES, mode
        assert lo <= c["peak_ratio"] <= hi, mode
        assert max(c["analytic"]["peak_bytes"],
                   c["xla"]["peak_bytes"]) < budget, mode


def test_batch_fit_representative_families_match_bank():
    """Re-deriving a slice of the banked table must diff clean — the
    pre-flight's pricing source is reproducible, not a stale artifact."""
    findings, table = run_batch_fit(
        families=["cifar10_quick", "alexnet", "transformer"])
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    alex = table["families"]["alexnet"]
    # TP must actually shave the fc-heavy params+slots on alexnet
    for dtype in ("f32", "bf16"):
        assert (alex[dtype]["tp_params_slots_bytes"]
                < alex[dtype]["params_slots_bytes"])
    # the sequence-parallel divisor only prices the transformer row
    assert "sp" in table["families"]["transformer"]["f32"]["max_batch"]
    assert "sp" not in alex["f32"]["max_batch"]
