"""conccheck: fixture snippets per defect class + the repo-wide gate.

Mirrors test_graftlint's structure for the fourth analysis engine: each
rule gets a positive fixture, a suppressed twin, and a clean rewrite,
all run through ``run_conccheck`` against a tmp repo so the engine's
boundary is pinned from both sides with zero chip time.  The repo-wide
test at the bottom is the CI wiring for the acceptance criterion:
``python -m sparknet_tpu.analysis conc`` exits 0 with every suppression
justified inline and the docs/conc_contracts/ manifests fresh.
"""

import json
import os

import pytest

from sparknet_tpu.analysis.conccheck import (
    CONC_RULES,
    iter_rules,
    run_conccheck,
)

pytestmark = pytest.mark.smoke


def _run(tmp_path, files, *, update=False, patterns=None):
    """Materialize fixture files into a tmp repo and run the engine."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return run_conccheck(
        paths=patterns or tuple(files),
        repo=str(tmp_path),
        manifest_dir=str(tmp_path / "docs" / "conc_contracts"),
        update=update)


def _hits(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def _suppressed(findings, rule):
    return [f for f in findings if f.rule == rule and f.suppressed]


# -- registry ---------------------------------------------------------------


def test_rule_catalog():
    rules = dict(iter_rules())
    assert rules == CONC_RULES
    assert set(CONC_RULES) == {
        "conc-unguarded-write", "conc-lock-order-cycle",
        "conc-blocking-under-lock", "conc-jax-in-worker",
        "conc-manifest-missing", "conc-manifest-drift"}


# -- conc-unguarded-write ---------------------------------------------------

UNGUARDED = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def guarded(self):
        with self._lock:
            self._n = 1

    def bare(self):
        self._n = 2
"""


def test_unguarded_write_positive(tmp_path):
    findings, _ = _run(tmp_path, {"fix.py": UNGUARDED})
    found = _hits(findings, "conc-unguarded-write")
    assert len(found) == 1
    assert "Counter._n" in found[0].message or "_n" in found[0].message
    assert "guarded by" in found[0].message


def test_unguarded_write_suppressed(tmp_path):
    src = UNGUARDED.replace(
        "        self._n = 2",
        "        # conccheck: unguarded=single-writer init race is "
        "benign here\n        self._n = 2")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-unguarded-write")
    assert _suppressed(findings, "conc-unguarded-write")


def test_unguarded_write_clean_when_all_guarded(tmp_path):
    src = UNGUARDED.replace(
        "    def bare(self):\n        self._n = 2",
        "    def bare(self):\n        with self._lock:\n"
        "            self._n = 2")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-unguarded-write")


def test_locked_suffix_methods_are_caller_held(tmp_path):
    src = UNGUARDED.replace(
        "    def bare(self):\n        self._n = 2",
        "    def _bump_locked(self):\n        self._n = 2")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-unguarded-write")


# -- conc-lock-order-cycle --------------------------------------------------

CYCLE = """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
"""


def test_lock_order_cycle_positive(tmp_path):
    findings, manifests = _run(tmp_path, {"fix.py": CYCLE})
    found = _hits(findings, "conc-lock-order-cycle")
    assert len(found) == 1
    assert "Pair._a" in found[0].message
    assert "Pair._b" in found[0].message
    edges = {tuple(e)
             for e in manifests["lock_graph"]["contract"]["edges"]}
    assert ("Pair._a", "Pair._b") in edges
    assert ("Pair._b", "Pair._a") in edges


def test_lock_order_clean_when_consistent(tmp_path):
    src = CYCLE.replace(
        "        with self._b:\n            with self._a:",
        "        with self._a:\n            with self._b:")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-lock-order-cycle")


def test_cross_function_cycle_through_calls(tmp_path):
    # inner acquisitions reached THROUGH a call under a held lock
    src = """
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def take_a(self):
        with self._a:
            pass

    def take_b(self):
        with self._b:
            pass

    def ab(self):
        with self._a:
            self.take_b()

    def ba(self):
        with self._b:
            self.take_a()
"""
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert len(_hits(findings, "conc-lock-order-cycle")) == 1


# -- conc-blocking-under-lock -----------------------------------------------

BLOCKING = """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self, lowered, q, t):
        with self._lock:
            lowered.compile()
            q.get()
            t.join()

    def fine(self, lowered, q, t):
        lowered.compile()
        with self._lock:
            q.get(timeout=1.0)
            t.join(timeout=1.0)
"""


def test_blocking_under_lock_positive(tmp_path):
    findings, _ = _run(tmp_path, {"fix.py": BLOCKING})
    found = _hits(findings, "conc-blocking-under-lock")
    assert len(found) == 3
    names = " ".join(f.message for f in found)
    assert ".compile()" in names
    assert ".get()" in names
    assert ".join()" in names


def test_blocking_under_lock_suppressed(tmp_path):
    src = BLOCKING.replace(
        "            lowered.compile()\n",
        "            # conccheck: blocking=warmup path, no concurrent "
        "holders yet\n            lowered.compile()\n").replace(
        "            q.get()\n",
        "            # conccheck: blocking=producer is this thread\n"
        "            q.get()\n").replace(
        "            t.join()\n",
        "            # conccheck: blocking=target never takes this "
        "lock\n            t.join()\n")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-blocking-under-lock")
    assert len(_suppressed(findings, "conc-blocking-under-lock")) == 3


def test_blocking_clean_with_timeouts_outside(tmp_path):
    src = BLOCKING.replace(
        "            lowered.compile()\n            q.get()\n"
        "            t.join()\n", "            pass\n")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-blocking-under-lock")


def test_shm_unlink_under_lock_flagged(tmp_path):
    src = """
import threading

class Ring:
    def __init__(self):
        self._lock = threading.Lock()

    def teardown(self, shm):
        with self._lock:
            shm.unlink()
"""
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert len(_hits(findings, "conc-blocking-under-lock")) == 1


# -- conc-jax-in-worker -----------------------------------------------------

JAX_WORKER = """
import multiprocessing as mp


def worker(src):
    import jax
    return jax.devices()


def spawn():
    p = mp.Process(target=worker, args=(None,))
    p.start()
"""


def test_jax_in_worker_positive(tmp_path):
    findings, manifests = _run(tmp_path, {"fix.py": JAX_WORKER})
    found = _hits(findings, "conc-jax-in-worker")
    assert len(found) == 1
    assert "worker" in found[0].message
    tax = manifests["taxonomy"]["contract"]
    assert any("worker" in r for r in tax["process_roots"])
    assert "fix.py::worker" in tax["process_reachable"]


def test_jax_in_worker_suppressed(tmp_path):
    src = JAX_WORKER.replace(
        "    import jax\n",
        "    # conccheck: jax=device-bound worker by design, not a "
        "ring worker\n    import jax\n")
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-jax-in-worker")
    assert _suppressed(findings, "conc-jax-in-worker")


def test_jax_clean_in_host_only_worker(tmp_path):
    src = """
import multiprocessing as mp


def worker(src):
    return src.get(0, 0)


def spawn():
    p = mp.Process(target=worker, args=(None,))
    p.start()
"""
    findings, _ = _run(tmp_path, {"fix.py": src})
    assert not _hits(findings, "conc-jax-in-worker")


def test_typed_param_resolves_worker_callee_across_modules(tmp_path):
    # the records.py shape: the worker's source parameter is typed by
    # annotation and its .get override lives in ANOTHER audited module
    files = {
        "base.py": """
import multiprocessing as mp


class Source:
    def get(self, epoch, index):
        raise NotImplementedError


def worker(source: Source):
    return source.get(0, 0)


def spawn():
    mp.Process(target=worker).start()
""",
        "sub.py": """
from base import Source


class JaxSource(Source):
    def get(self, epoch, index):
        import jax
        return jax.numpy.zeros(())
""",
    }
    findings, manifests = _run(tmp_path, files)
    found = _hits(findings, "conc-jax-in-worker")
    assert any("JaxSource.get" in f.message for f in found)
    reach = manifests["taxonomy"]["contract"]["process_reachable"]
    assert "sub.py::JaxSource.get" in reach


# -- manifest bank / drift / allow loop -------------------------------------


def test_manifest_bank_drift_allow_loop(tmp_path):
    files = {"fix.py": UNGUARDED.replace(
        "    def bare(self):\n        self._n = 2\n", "")}
    # 1. unbanked: missing findings for both manifests
    findings, _ = _run(tmp_path, files)
    assert len(_hits(findings, "conc-manifest-missing")) == 2

    # 2. bank, then re-run clean
    _run(tmp_path, files, update=True)
    mdir = tmp_path / "docs" / "conc_contracts"
    assert sorted(p.name for p in mdir.iterdir()) == [
        "SOURCES.json", "lock_graph.json", "taxonomy.json"]
    findings, _ = _run(tmp_path, files)
    assert not [f for f in findings if not f.suppressed]

    # 3. drift: a second lock changes the contract
    drifted = dict(files)
    drifted["fix.py"] += (
        "\n_extra = threading.Lock()\n"
        "def touch():\n    with _extra:\n        pass\n")
    findings, _ = _run(tmp_path, drifted)
    drift = _hits(findings, "conc-manifest-drift")
    assert drift and "lock_graph" in drift[0].message

    # 4. allow: an explicit allow entry suppresses the drift finding
    for name in ("lock_graph", "taxonomy"):
        path = mdir / f"{name}.json"
        data = json.loads(path.read_text())
        data["allow"] = {"conc-manifest-drift":
                         "intentional fixture drift"}
        path.write_text(json.dumps(data))
    findings, _ = _run(tmp_path, drifted)
    assert not _hits(findings, "conc-manifest-drift")
    assert _suppressed(findings, "conc-manifest-drift")

    # 5. --update re-banks and clears the drift (allow map survives)
    _run(tmp_path, drifted, update=True)
    findings, _ = _run(tmp_path, drifted)
    assert not [f for f in findings if f.rule == "conc-manifest-drift"]
    kept = json.loads((mdir / "lock_graph.json").read_text())
    assert kept["allow"] == {"conc-manifest-drift":
                             "intentional fixture drift"}


# -- CLI + repo-wide gate ---------------------------------------------------


def test_cli_list_rules_and_json(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    rc = cli_main(["conc", "--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in CONC_RULES:
        assert rule in out


def test_repo_wide_conc_is_clean_and_manifests_fresh(capsys):
    """The acceptance criterion: zero unsuppressed findings over the
    real audited surface, against the banked manifests."""
    from sparknet_tpu.analysis.__main__ import main as cli_main

    rc = cli_main(["conc", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["unsuppressed"] == 0
    # the suppressions that ARE banked must each carry a justification
    # (the grammar requires one; this pins the count so a new stray
    # suppression shows up in review)
    assert payload["suppressed"] == 3


def test_repo_manifests_match_sources_fingerprint():
    """SOURCES.json covers exactly the audited surface, window runner
    included (the /tools/ anchor of conc-manifest-fresh)."""
    from sparknet_tpu.analysis.conccheck import (
        MANIFEST_DIR, sources_fingerprint)

    with open(os.path.join(MANIFEST_DIR, "SOURCES.json"),
              encoding="utf-8") as f:
        banked = json.load(f)
    assert banked == sources_fingerprint()
    assert "tools/tpu_window_runner.py" in banked
