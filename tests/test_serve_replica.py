"""Pod-scale serving gates (sparknet_tpu/serve router + shed + decode).

Four contract families, mirroring tests/test_serve.py's layering:

1. **Shed rule** — stdlib-only batcher tests on a fake clock: the
   windowed drain-rate EWMA (a window opens at a take that leaves
   backlog, closes into a sample after >= _WIN_S, and is invalidated
   by any take that empties the queue), the asymmetric smoothing
   (slowdowns adopted fast, speedups reluctantly), the projection's
   one-take-period term, the largest-bucket floor, the cold-start
   two-quanta cap, and the vectorized ``submit_many`` FIFO-tail shed.
   No jax, no sleeps.
2. **Loadgen determinism** — ``open_loop_schedule`` is a pure function
   of (rate, seconds, seed): same seed, same schedule, bitwise.
3. **Router policy** — re-route-on-death pinned EXACTLY (the stolen
   count equals the victim's pending depth, zero tickets drop, the
   SAME Ticket objects resolve on a survivor), projected-wait pick
   over raw depth-JSQ, the fair one-batch-per-model pump cap, the
   chunked submit path, and join weight consistency (bitwise).
4. **Continuous batching** — a request decoded interleaved with
   churning neighbors equals the same request decoded alone, bitwise,
   with ZERO decode-path compiles (one fixed-shape AOT arena).

ref: caffe/src/caffe/parallel.cpp P2PSync (the reference's replica
fan-out — train-side gradient exchange; serve-side routing, shedding,
and slot-level decode admission are new TPU-first surface).
"""

from __future__ import annotations

import numpy as np
import pytest

from sparknet_tpu.serve.batcher import DynamicBatcher, Ticket


class FakeClock:
    """Injectable time: advances only on demand (no test sleeps)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _feed(b: DynamicBatcher, n: int) -> list:
    return [b.submit(f"p{i}") for i in range(n)]


def _establish_rate(b: DynamicBatcher, clock: FakeClock):
    """Drive one sampling window to a known close: 4 accumulating
    takes of 8 rows over exactly _WIN_S seconds -> 640 rows/s, take
    period 12.5 ms.  Leaves backlog so the next window is open."""
    _feed(b, 100)
    clock.t = 0.0
    assert len(b.take(force=True)) == 8  # opens the window (rows not counted)
    for k in range(1, 5):
        clock.t = k * 0.0125
        b.take(force=True)
    assert b._ewma_rate == pytest.approx(32 / 0.05)  # 640 rows/s
    # first take-period sample blends against the 0.0 init through the
    # slow-down alpha (12.5 ms > 0): 0.5 * 12.5
    assert b._ewma_take_ms == pytest.approx(6.25)


# -- shed rule (jax-free) ----------------------------------------------------


@pytest.mark.smoke
def test_window_opens_only_when_backlog_persists():
    """A take that empties the queue invalidates the window: the gap
    after it would measure idle time, not drain capability."""
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _feed(b, 8)
    b.take(force=True)  # empties -> no window, no sample
    assert b._win_t0 is None and b._ewma_rate is None
    _feed(b, 20)
    clock.t = 1.0
    b.take(force=True)  # leaves 12 pending -> window opens
    assert b._win_t0 == 1.0 and b._ewma_rate is None
    clock.t = 1.2
    b.take(force=True)  # leaves 4: dt 0.2 >= _WIN_S -> sample closes
    assert b._ewma_rate == pytest.approx(8 / 0.2)
    clock.t = 1.3
    b.take(force=True)  # empties again -> window invalidated
    assert b._win_t0 is None
    assert b._ewma_rate == pytest.approx(8 / 0.2)  # estimate survives


@pytest.mark.smoke
def test_windowed_rate_and_projection_arithmetic():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _establish_rate(b, clock)
    # 60 pending at 640 rows/s + one 6.25 ms take period
    assert b.pending() == 60
    expect = 60 / 640 * 1e3 + 6.25
    assert b.projected_wait_ms() == pytest.approx(expect)
    assert b.projected_wait_snapshot() == pytest.approx(expect)


@pytest.mark.smoke
def test_asymmetric_ewma_adopts_slowdowns_fast():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _establish_rate(b, clock)  # 640 rows/s, window re-opened at 0.05
    _feed(b, 200)
    # faster window: 10 takes x 8 rows over 0.05 s -> 1600 rows/s
    # sample ABOVE the estimate -> reluctant alpha 0.2
    for k in range(1, 11):
        clock.t = 0.05 + k * 0.005
        b.take(force=True)
    assert b._ewma_rate == pytest.approx(0.2 * 1600 + 0.8 * 640)
    before = b._ewma_rate
    # slower window: 2 takes x 8 rows over 0.05 s -> 320 rows/s
    # sample BELOW the estimate -> eager alpha 0.5
    for k in range(1, 3):
        clock.t = 0.1 + k * 0.025
        b.take(force=True)
    assert b._ewma_rate == pytest.approx(0.5 * 320 + 0.5 * before)


@pytest.mark.smoke
def test_shed_rejects_over_projection_and_counts():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _establish_rate(b, clock)  # 60 pending project ~100 ms >> 5 ms
    assert b.shed("late") is None
    assert b.shed_count == 1
    assert b.last_projected_ms == pytest.approx(60 / 640 * 1e3 + 6.25)
    # a pump tick of grace moves the bound, not the verdict here
    assert b.shed("late2", tick_ms=15.0) is None
    assert b.shed_count == 2


@pytest.mark.smoke
def test_shed_largest_bucket_floor_never_chokes():
    """Below one largest-bucket quantum nothing sheds, no matter how
    stale-low the EWMA reads — one pump visit clears the queue, and
    admission must keep feeding the estimator."""
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _establish_rate(b, clock)
    while b.pending() >= b.buckets[-1]:
        b.take(force=True)
    assert 0 < b.pending() < 8
    assert b.projected_wait_ms() > b.max_wait_ms  # projection says shed
    t = b.shed("floor")  # ... the floor says admit
    assert isinstance(t, Ticket)
    assert b.shed_count == 0


@pytest.mark.smoke
def test_cold_start_cap_bounds_blind_backlog():
    """With NO rate evidence, pending is capped at two largest-bucket
    quanta — a saturating burst can't park a deep backlog while the
    estimator is still blind."""
    b = DynamicBatcher(buckets=(1, 8, 64), max_wait_ms=5.0,
                       clock=FakeClock())
    admitted = [b.shed(i) for i in range(130)]
    assert sum(t is not None for t in admitted) == 128  # 2 * 64
    assert admitted[-1] is None and b.shed_count == 2


@pytest.mark.smoke
def test_submit_many_cold_cap_and_fifo_tail():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    tickets, n_shed = b.submit_many([f"p{i}" for i in range(20)],
                                    shed=True)
    assert len(tickets) == 16 and n_shed == 4  # cold cap: 2 * 8
    # earlier arrivals win — FIFO fairness survives chunking
    assert [t.payload for t in tickets] == [f"p{i}" for i in range(16)]
    assert b.shed_count == 4
    # without shed the chunk admits wholesale under one timestamp
    more, none_shed = b.submit_many(["a", "b"])
    assert none_shed == 0 and more[0].t_submit == more[1].t_submit


@pytest.mark.smoke
def test_submit_many_rate_cap_floors_at_one_quantum():
    clock = FakeClock()
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0, clock=clock)
    _establish_rate(b, clock)
    while b.take(force=True):  # drain so cap headroom is visible
        pass
    # bound_s = max(0, 5 - 6.25) ms = 0 -> cap floors at buckets[-1]
    tickets, n_shed = b.submit_many([f"p{i}" for i in range(20)],
                                    shed=True)
    assert len(tickets) == 8 and n_shed == 12


@pytest.mark.smoke
def test_ticket_event_is_lazy_and_resolve_lock_free():
    t = Ticket(0, "x", 0.0)
    assert t._done is None and not t.done()
    t.resolve(result=41)  # resolve before any waiter: no event built
    assert t._done is None and t.done()
    assert t.wait(timeout=0.0) == 41  # fast path: no event even now
    u = Ticket(1, "y", 0.0)
    u._event()  # a waiter materialized the event first
    u.resolve(error=RuntimeError("boom"))
    assert u._done.is_set()
    with pytest.raises(RuntimeError, match="boom"):
        u.wait(timeout=0.0)
    v = Ticket(2, "z", 0.0)
    with pytest.raises(TimeoutError):
        v.wait(timeout=0.0)


# -- loadgen determinism -----------------------------------------------------


@pytest.mark.smoke
def test_open_loop_schedule_deterministic():
    from sparknet_tpu.serve.loadgen import open_loop_schedule

    a = open_loop_schedule(2000.0, 0.5, seed=11)
    b = open_loop_schedule(2000.0, 0.5, seed=11)
    assert np.array_equal(a, b)  # same seed -> same schedule, bitwise
    c = open_loop_schedule(2000.0, 0.5, seed=12)
    assert not np.array_equal(a, c)
    assert np.all(np.diff(a) >= 0) and a[-1] < 0.5
    # open-loop: the mean offered rate is the asked-for rate
    assert len(a) == pytest.approx(1000, rel=0.2)
    with pytest.raises(ValueError, match="positive"):
        open_loop_schedule(0.0, 1.0)


# -- router policy -----------------------------------------------------------


def _router(replicas=2, **kw):
    from sparknet_tpu.serve.router import ReplicaRouter

    kw.setdefault("family", "lenet")
    kw.setdefault("buckets", (1, 8))
    kw.setdefault("max_wait_ms", 5.0)
    return ReplicaRouter(replicas=replicas, **kw)


def _items(router, n, seed=3):
    from sparknet_tpu.serve.loadgen import synthetic_items

    model = next(iter(router._replicas.values())).model
    return synthetic_items(model, n, np.random.RandomState(seed))


def test_kill_reroutes_pending_exactly_zero_drop():
    """The dead replica's queue moves WHOLE: rerouted == its pending
    depth at the kill, the SAME Ticket objects resolve on a survivor,
    and the pod ledger shows zero dropped."""
    router = _router(replicas=2)
    tickets = [router.submit(it) for it in _items(router, 12)]
    victim = router.replica_ids()[0]
    pending = router._replicas[victim].outstanding()
    assert pending > 0  # JSQ spread put work on both replicas
    rerouted = router.kill_replica(victim)
    assert rerouted == pending  # pinned exactly
    assert router.width() == 1
    router.pump(force=True)
    assert all(t.done() for t in tickets)  # zero dropped, same objects
    stats = router.emit_summary(wall_s=1.0)
    assert stats["dropped"] == 0
    assert stats["rerouted"] == rerouted
    router.shutdown()


def test_pick_replica_prefers_low_projected_wait():
    """A replica whose drain-rate evidence collapsed projects long
    waits even with a SHORT queue — projected-wait pick routes around
    it where depth-JSQ would keep feeding it."""
    router = _router(replicas=2)
    slow, fast = list(router._replicas.values())
    slow.model.batcher._ewma_rate = 10.0  # 1 pending -> 100 ms wait
    slow.model.batcher.submit("stuck")
    fast.model.batcher._ewma_rate = 10_000.0
    for i in range(4):  # deeper queue, but ~0.4 ms projected
        fast.model.batcher.submit(f"q{i}")
    assert router._pick_replica() is fast
    slow.model.batcher._ewma_rate = None  # no evidence: depth breaks tie
    assert router._pick_replica() is slow
    for rep in (slow, fast):  # junk payloads must not reach _execute
        rep.model.batcher.steal()
    router.shutdown()


def test_pump_caps_one_batch_per_model_per_sweep():
    """engine.pump(max_batches=1) takes at most ONE batch per model —
    the fair-sweep primitive that stops a deep queue from starving its
    pod neighbors; force-pump still drains everything."""
    router = _router(replicas=1)
    rep = next(iter(router._replicas.values()))
    for it in _items(router, 20):
        router.submit(it)
    assert rep.engine.pump(force=True, max_batches=1) == 1
    assert rep.outstanding() == 12  # one 8-batch taken, rest parked
    assert router.pump(force=True) == 2  # sweeps until drained
    assert rep.outstanding() == 0
    router.shutdown()


def test_submit_many_routes_chunk_and_counts():
    router = _router(replicas=2)
    tickets, n_shed = router.submit_many(_items(router, 10), shed=True)
    assert len(tickets) == 10 and n_shed == 0
    assert router.submitted == 10
    # the whole chunk landed on ONE replica (chunk-granularity JSQ)
    depths = sorted(r.outstanding() for r in router._replicas.values())
    assert depths == [0, 10]
    router.pump(force=True)
    assert all(t.done() for t in tickets)
    router.shutdown()


def test_join_copies_live_weights_bitwise():
    router = _router(replicas=1)
    item = _items(router, 1)[0]
    before = np.asarray(next(iter(
        router._replicas.values())).engine.infer("model", item))
    rid = router.join_replica()
    assert router.width() == 2
    joined = router._replicas[rid]
    after = np.asarray(joined.engine.infer("model", item))
    assert np.array_equal(before, after)  # score-consistent pool
    router.shutdown()


# -- continuous batching -----------------------------------------------------


def test_continuous_decode_interleaved_matches_alone():
    """Slot-level admission never changes a generation: decoded alone
    == decoded among churning neighbors, bitwise, with zero
    decode-path compiles (one fixed-shape AOT arena program)."""
    from sparknet_tpu.serve.continuous import ContinuousDecoder

    alone = ContinuousDecoder(slots=4, seq_len=16, vocab=32, seed=0)
    t_alone = alone.submit([1, 2, 3], 8)
    alone.run()

    churn = ContinuousDecoder(slots=4, seq_len=16, vocab=32, seed=0)
    for i in range(6):  # staggered lengths force slot churn
        churn.submit([5 + i], 4 + i)
    t_mix = churn.submit([1, 2, 3], 8)
    churn.run()

    assert t_alone.wait(5.0) == t_mix.wait(5.0)
    assert churn.decode_path_compiles == 0
    stats = churn.stats()
    assert stats["admitted"] == 7 > churn.slots  # slots were reused
    assert stats["completed"] == 7


@pytest.mark.smoke
def test_continuous_decoder_validates_submits():
    from sparknet_tpu.serve.continuous import ContinuousDecoder

    with pytest.raises(ValueError, match="slots"):
        ContinuousDecoder(slots=1)
    d = ContinuousDecoder(slots=2, seq_len=8, vocab=16, seed=0)
    with pytest.raises(ValueError, match="non-empty"):
        d.submit([], 4)
    with pytest.raises(ValueError, match="outside"):
        d.submit([99], 4)
    with pytest.raises(ValueError, match="positive"):
        d.submit([1], 0)


@pytest.mark.smoke
def test_obs_report_renders_replica_section():
    """The obs report grows a replica-pool section: membership, the
    re-routed-ticket ledger on a kill, and the aggregate summary."""
    from sparknet_tpu.obs import schema
    from sparknet_tpu.obs.report import render

    events = [
        {"event": "run_start", "run_id": "pod",
         "utc": "2026-08-05 00:00:00Z", "pid": 1},
        {"event": "replica", "run_id": "pod",
         "utc": "2026-08-05 00:00:01Z", "kind": "replica_up",
         "replica": 3, "width": 4, "note": "elastic join"},
        {"event": "replica", "run_id": "pod",
         "utc": "2026-08-05 00:00:02Z", "kind": "replica_down",
         "replica": 1, "width": 3, "rerouted": 10, "outstanding": 10,
         "dropped": 0},
        {"event": "replica", "run_id": "pod",
         "utc": "2026-08-05 00:00:03Z", "kind": "rollout",
         "replica": 0, "version": 2, "drained": 4},
        {"event": "replica", "run_id": "pod",
         "utc": "2026-08-05 00:00:04Z", "kind": "summary", "width": 4,
         "requests": 504, "rps": 10860.0, "shed": 12, "dropped": 0,
         "rerouted": 10},
        {"event": "run_end", "run_id": "pod",
         "utc": "2026-08-05 00:00:05Z", "rounds": 0, "spans": 0,
         "compiles": 0},
    ]
    for ev in events:
        assert schema.validate_line(ev) == [], ev
    text = render(events, source="t")
    assert "replica pool (pod-scale serving)" in text
    assert "**UP** replica 3" in text
    assert "10 in-flight ticket(s) re-routed" in text
    assert "dropped 0" in text
    assert "rollout replica 0 -> version 2" in text
    assert "10860 req/s aggregate" in text
