"""Model-zoo compile tests.

GoogLeNet is the compiler stress test (ref: bvlc_googlenet/train_val.prototxt
— 166-layer multi-tower DAG, SURVEY §7 hard part (e)); the others pin the
published architectures' output shapes and parameter counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu import models


def _init_and_forward(net_param, feeds):
    net = Network(net_param, Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    return net, variables, blobs, loss


def _param_count(variables):
    return sum(
        int(np.prod(p.shape))
        for plist in variables.params.values()
        for p in plist
    )


def test_lenet_shapes():
    B = 4
    feeds = {
        "data": jnp.zeros((B, 1, 28, 28), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.lenet(B), feeds)
    assert blobs["ip2"].shape == (B, 10)
    # LeNet: 20*1*25+20 + 50*20*25+50 + 500*800+500 + 10*500+10 = 431080
    assert _param_count(variables) == 431080


def test_cifar10_quick_shapes():
    B = 2
    feeds = {
        "data": jnp.zeros((B, 3, 32, 32), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.cifar10_quick(B), feeds)
    assert blobs["ip2"].shape == (B, 10)
    assert jnp.isfinite(loss)


def test_cifar10_full_shapes():
    B = 2
    feeds = {
        "data": jnp.zeros((B, 3, 32, 32), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.cifar10_full(B), feeds)
    assert blobs["ip1"].shape == (B, 10)
    assert jnp.isfinite(loss)


def test_alexnet_shapes():
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 227, 227), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.alexnet(B), feeds)
    # Published AlexNet feature-map shapes on 227x227 input.
    assert blobs["conv1"].shape == (B, 96, 55, 55)
    assert blobs["pool5"].shape == (B, 256, 6, 6)
    assert blobs["fc8"].shape == (B, 1000)
    # ~60.9M learnable parameters.
    assert abs(_param_count(variables) - 60_965_224) < 10_000


def test_caffenet_matches_alexnet_size():
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 227, 227), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, _ = _init_and_forward(models.caffenet(B), feeds)
    assert blobs["fc8"].shape == (B, 1000)
    assert abs(_param_count(variables) - 60_965_224) < 10_000


def test_googlenet_stress():
    """The multi-tower concat DAG compiles, runs, and has ~7M params."""
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 224, 224), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.googlenet(B), feeds)
    assert blobs["inception_3a/output"].shape == (B, 256, 28, 28)
    assert blobs["inception_4e/output"].shape == (B, 832, 14, 14)
    assert blobs["pool5/7x7_s1"].shape == (B, 1024, 1, 1)
    assert blobs["loss3/classifier"].shape == (B, 1000)
    assert jnp.isfinite(loss)
    n = _param_count(variables)
    assert 6_900_000 < n < 7_100_000, n


@pytest.mark.parametrize(
    "build,feed_chw",
    [
        (models.lenet, (1, 28, 28)),
        (models.cifar10_quick, (3, 32, 32)),
        (models.cifar10_full, (3, 32, 32)),
    ],
)
def test_no_dangling_tops(build, feed_chw):
    """Every intermediate blob is consumed: the net's outputs are exactly the
    loss/accuracy heads.  A dangling ReLU/Dropout/LRN top means the zoo
    mis-wired the in-place prototxt semantics and the nonlinearity is a dead
    branch (the compiler treats top==bottom as in-place rebinding)."""
    net = Network(build(2), Phase.TRAIN)
    outs = set(net.output_blobs())
    assert all(("loss" in o) or ("accuracy" in o) or ("top-" in o) for o in outs), outs


def test_relu_actually_applied():
    """Post-activation blobs are nonnegative — the in-place wiring really
    rebinds the blob name to the activated tensor."""
    B = 2
    net = Network(models.cifar10_quick(B), Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "data": jnp.asarray(np.random.RandomState(0).randn(B, 3, 32, 32) * 50,
                            jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    blobs, _, _ = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    # pool1 is rebound by the in-place relu1; conv2 reads the activated blob
    assert bool(jnp.all(blobs["pool1"] >= 0))


def test_googlenet_gradients_flow():
    """value_and_grad through the full DAG produces finite grads everywhere."""
    B = 1
    m = models.googlenet(B, num_classes=10)
    net = Network(m, Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "data": jnp.asarray(np.random.RandomState(0).randn(B, 3, 224, 224),
                            jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }

    def loss_fn(params):
        from sparknet_tpu.compiler.graph import NetVars
        _, _, loss = net.apply(
            NetVars(params=params, state=variables.state), feeds,
            rng=jax.random.PRNGKey(1))
        return loss

    grads = jax.grad(loss_fn)(variables.params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in leaves)
