"""Model-zoo compile tests.

GoogLeNet is the compiler stress test (ref: bvlc_googlenet/train_val.prototxt
— 166-layer multi-tower DAG, SURVEY §7 hard part (e)); the others pin the
published architectures' output shapes and parameter counts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu import models


def _init_and_forward(net_param, feeds):
    net = Network(net_param, Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    return net, variables, blobs, loss


def _param_count(variables):
    return sum(
        int(np.prod(p.shape))
        for plist in variables.params.values()
        for p in plist
    )


def test_lenet_shapes():
    B = 4
    feeds = {
        "data": jnp.zeros((B, 1, 28, 28), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.lenet(B), feeds)
    assert blobs["ip2"].shape == (B, 10)
    # LeNet: 20*1*25+20 + 50*20*25+50 + 500*800+500 + 10*500+10 = 431080
    assert _param_count(variables) == 431080


def test_cifar10_quick_shapes():
    B = 2
    feeds = {
        "data": jnp.zeros((B, 3, 32, 32), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.cifar10_quick(B), feeds)
    assert blobs["ip2"].shape == (B, 10)
    assert jnp.isfinite(loss)


def test_cifar10_full_shapes():
    B = 2
    feeds = {
        "data": jnp.zeros((B, 3, 32, 32), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.cifar10_full(B), feeds)
    assert blobs["ip1"].shape == (B, 10)
    assert jnp.isfinite(loss)


def test_alexnet_shapes():
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 227, 227), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.alexnet(B), feeds)
    # Published AlexNet feature-map shapes on 227x227 input.
    assert blobs["conv1"].shape == (B, 96, 55, 55)
    assert blobs["pool5"].shape == (B, 256, 6, 6)
    assert blobs["fc8"].shape == (B, 1000)
    # ~60.9M learnable parameters.
    assert abs(_param_count(variables) - 60_965_224) < 10_000


def test_caffenet_matches_alexnet_size():
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 227, 227), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, _ = _init_and_forward(models.caffenet(B), feeds)
    assert blobs["fc8"].shape == (B, 1000)
    assert abs(_param_count(variables) - 60_965_224) < 10_000


def test_googlenet_stress():
    """The multi-tower concat DAG compiles, runs, and has the canonical
    13,378,280 params (main tower ~7M + two auxiliary classifier towers,
    ref: bvlc_googlenet/train_val.prototxt:823-953,1586-1716)."""
    B = 1
    feeds = {
        "data": jnp.zeros((B, 3, 224, 224), jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    net, variables, blobs, loss = _init_and_forward(models.googlenet(B), feeds)
    assert blobs["inception_3a/output"].shape == (B, 256, 28, 28)
    assert blobs["inception_4e/output"].shape == (B, 832, 14, 14)
    assert blobs["pool5/7x7_s1"].shape == (B, 1024, 1, 1)
    assert blobs["loss3/classifier"].shape == (B, 1000)
    assert jnp.isfinite(loss)
    n = _param_count(variables)
    assert n == 13_378_280, n


@pytest.mark.parametrize(
    "build,feed_chw",
    [
        (models.lenet, (1, 28, 28)),
        (models.cifar10_quick, (3, 32, 32)),
        (models.cifar10_full, (3, 32, 32)),
    ],
)
def test_no_dangling_tops(build, feed_chw):
    """Every intermediate blob is consumed: the net's outputs are exactly the
    loss/accuracy heads.  A dangling ReLU/Dropout/LRN top means the zoo
    mis-wired the in-place prototxt semantics and the nonlinearity is a dead
    branch (the compiler treats top==bottom as in-place rebinding)."""
    net = Network(build(2), Phase.TRAIN)
    outs = set(net.output_blobs())
    assert all(("loss" in o) or ("accuracy" in o) or ("top-" in o) for o in outs), outs


def test_relu_actually_applied():
    """Post-activation blobs are nonnegative — the in-place wiring really
    rebinds the blob name to the activated tensor."""
    B = 2
    net = Network(models.cifar10_quick(B), Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "data": jnp.asarray(np.random.RandomState(0).randn(B, 3, 32, 32) * 50,
                            jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }
    blobs, _, _ = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    # pool1 is rebound by the in-place relu1; conv2 reads the activated blob
    assert bool(jnp.all(blobs["pool1"] >= 0))


def test_googlenet_gradients_flow():
    """value_and_grad through the full DAG produces finite grads everywhere."""
    B = 1
    m = models.googlenet(B, num_classes=10)
    net = Network(m, Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "data": jnp.asarray(np.random.RandomState(0).randn(B, 3, 224, 224),
                            jnp.float32),
        "label": jnp.zeros((B,), jnp.int32),
    }

    def loss_fn(params):
        from sparknet_tpu.compiler.graph import NetVars
        _, _, loss = net.apply(
            NetVars(params=params, state=variables.state), feeds,
            rng=jax.random.PRNGKey(1))
        return loss

    grads = jax.grad(loss_fn)(variables.params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in leaves)


def test_siamese_weight_sharing():
    """The two towers share arrays: the alias map routes conv1_p/ip*_p to
    the first tower's params, placeholders are zero-size, gradients
    accumulate from BOTH towers into the owner."""
    B = 4
    m = models.mnist_siamese(B)
    net = Network(m, Phase.TRAIN)
    # aliases: every _p tower param points at the bare-tower owner
    assert net.param_aliases[("conv1_p", 0)] == ("conv1", 0)
    assert net.param_aliases[("feat_p", 1)] == ("feat", 1)
    variables = net.init(jax.random.PRNGKey(0))
    assert variables.params["conv1_p"][0].size == 0  # placeholder
    assert variables.params["conv1"][0].shape == (20, 1, 5, 5)

    rs = np.random.RandomState(0)
    feeds = {
        "pair_data": jnp.asarray(rs.randn(B, 2, 28, 28), jnp.float32),
        "sim": jnp.asarray(rs.randint(0, 2, B), jnp.float32),
    }
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    assert blobs["feat"].shape == (B, 2) and blobs["feat_p"].shape == (B, 2)
    assert jnp.isfinite(loss)

    # gradient of the shared conv1 weight sees both towers: zeroing the _p
    # tower's input must CHANGE the owner's grad
    def loss_fn(params, f):
        from sparknet_tpu.compiler.graph import NetVars
        _, _, l = net.apply(NetVars(params=params, state=variables.state),
                            f, rng=jax.random.PRNGKey(1))
        return l

    g1 = jax.grad(loss_fn)(variables.params, feeds)
    feeds2 = dict(feeds)
    feeds2["pair_data"] = feeds["pair_data"].at[:, 1].set(0.0)
    g2 = jax.grad(loss_fn)(variables.params, feeds2)
    assert not np.allclose(np.asarray(g1["conv1"][0]),
                           np.asarray(g2["conv1"][0]))
    # placeholder grads are empty
    assert g1["conv1_p"][0].size == 0


def test_siamese_trains_contrastive():
    """Same-class pairs end up closer than different-class pairs."""
    from sparknet_tpu.net import TPUNet

    B = 32
    rs = np.random.RandomState(0)

    def digits(n):
        labels = rs.randint(0, 4, n)
        imgs = rs.randn(n, 1, 28, 28).astype(np.float32) * 0.3
        for i, k in enumerate(labels):
            imgs[i, 0, :, 4 + k * 5] += 2.5
        return imgs, labels

    def gen():
        while True:
            a_img, a_lab = digits(B)
            b_img, b_lab = digits(B)
            yield {
                "pair_data": np.concatenate([a_img, b_img], axis=1),
                "sim": (a_lab == b_lab).astype(np.float32),
            }

    net = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(B))
    net.set_train_data(gen())
    net.train(120)

    # embed a fresh batch; same-class distance << diff-class distance
    a_img, a_lab = digits(B)
    b_img, b_lab = digits(B)
    blobs = net.forward({
        "pair_data": np.concatenate([a_img, b_img], axis=1),
        "sim": (a_lab == b_lab).astype(np.float32),
    })
    d = np.linalg.norm(np.asarray(blobs["feat"]) - np.asarray(blobs["feat_p"]), axis=1)
    same = d[a_lab == b_lab].mean()
    diff = d[a_lab != b_lab].mean()
    assert same < 0.5 * diff, (same, diff)


def test_siamese_caffemodel_shared_roundtrip(tmp_path):
    """Shared params export with the owner's values duplicated per layer
    (Caffe's ToProto layout) and reload into placeholders cleanly."""
    from sparknet_tpu.net import TPUNet

    net = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(4))
    p = str(tmp_path / "siam.caffemodel")
    net.save_caffemodel(p)
    from sparknet_tpu.proto.binary import load_caffemodel

    m = load_caffemodel(p)
    by = m.by_name()
    np.testing.assert_array_equal(by["conv1"].blobs[0], by["conv1_p"].blobs[0])

    net2 = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(4))
    loaded = net2.load_caffemodel(p)
    assert "conv1" in loaded and "conv1_p" in loaded
    np.testing.assert_array_equal(
        np.asarray(net.solver.variables.params["conv1"][0]),
        np.asarray(net2.solver.variables.params["conv1"][0]))
    assert net2.solver.variables.params["conv1_p"][0].size == 0


def test_siamese_hdf5_shared_roundtrip(tmp_path):
    """HDF5 snapshots duplicate shared blobs per layer (owner values) and
    reload placeholders cleanly — same contract as the caffemodel path."""
    import h5py
    from sparknet_tpu.net import TPUNet

    net = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(4))
    p = str(tmp_path / "siam.h5")
    net.save_hdf5(p)
    with h5py.File(p, "r") as f:
        a = np.asarray(f["data/conv1/0"])
        b = np.asarray(f["data/conv1_p/0"])
    np.testing.assert_array_equal(a, b)
    assert a.shape == (20, 1, 5, 5)

    net2 = TPUNet(models.mnist_siamese_solver(), models.mnist_siamese(4))
    loaded = net2.load_hdf5(p)
    assert "conv1" in loaded
    np.testing.assert_array_equal(
        np.asarray(net.solver.variables.params["conv1"][0]),
        np.asarray(net2.solver.variables.params["conv1"][0]))
    assert net2.solver.variables.params["conv1_p"][0].size == 0


def test_shared_param_mismatched_shape_rejected():
    """Sharing a name across incompatible blobs raises the clear error, not
    a deep conv shape failure (Caffe's 'Cannot share param' CHECK)."""
    from sparknet_tpu.layers_dsl import _filler
    from sparknet_tpu.proto.text_format import Message

    def named(m, name):
        m.add("param", Message().set("name", name))
        return m

    from sparknet_tpu.layers_dsl import (
        ConvolutionLayer as Conv, InnerProductLayer as Ip, NetParam, RDDLayer,
        SoftmaxWithLoss,
    )

    m = NetParam(
        "bad",
        RDDLayer("data", shape=[2, 1, 8, 8]),
        RDDLayer("label", shape=[2]),
        named(Conv("c1", ["data"], kernel=(3, 3), num_output=4), "w"),
        named(Conv("c2", ["c1"], kernel=(3, 3), num_output=8), "w"),
        SoftmaxWithLoss("loss", ["c2", "label"]),
    )
    net = Network(m, Phase.TRAIN)
    with pytest.raises(ValueError, match="Cannot share param 'w'"):
        net.init(jax.random.PRNGKey(0))


def test_shared_param_on_paramless_layer_rejected():
    """A param{name} alias on a layer position that never materializes a
    blob must raise, not silently train unshared (Caffe CHECK-fails in
    AppendParam, ref: net.cpp:470+)."""
    from sparknet_tpu.proto.text_format import Message

    def named(m, name):
        m.add("param", Message().set("name", name))
        return m

    from sparknet_tpu.layers_dsl import (
        ConvolutionLayer as Conv, NetParam, PoolingLayer, Pooling, RDDLayer,
        SoftmaxWithLoss,
    )

    m = NetParam(
        "bad2",
        RDDLayer("data", shape=[2, 1, 8, 8]),
        RDDLayer("label", shape=[2]),
        named(Conv("c1", ["data"], kernel=(3, 3), num_output=4), "w"),
        named(PoolingLayer("p1", ["c1"], Pooling.Max, kernel=(2, 2)), "w"),
        SoftmaxWithLoss("loss", ["p1", "label"]),
    )
    net = Network(m, Phase.TRAIN)
    with pytest.raises(ValueError, match="param name 'w'.*'p1'"):
        net.init(jax.random.PRNGKey(0))


def test_replace_data_layers_honors_exclude_rules():
    """Data-layer surgery must use full NetStateRule semantics: a layer with
    `exclude { phase: TEST }` is TRAIN-only (ref: Net::FilterNet)."""
    from sparknet_tpu.proto import parse
    from sparknet_tpu.proto_loader import replace_data_layers

    npz = parse(
        """
        name: "x"
        layer { name: "d_tr" type: "Data" top: "data" top: "label"
                exclude { phase: TEST } }
        layer { name: "d_te" type: "Data" top: "tdata" top: "tlabel"
                include { phase: TEST } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
                inner_product_param { num_output: 2 } }
        """
    )
    out = replace_data_layers(npz, 4, 2, 1, 8, 8)
    rdd = [
        (l.get_str("name"), [str(t) for t in l.get_all("top")])
        for l in out.get_all("layer")
        if l.get_str("type") == "JavaData"
    ]
    by_name = dict(rdd)
    assert by_name["data_train"] == ["data"]
    assert by_name["tdata_test"] == ["tdata"]
    # the excluded-from-TEST tops must NOT appear as TEST feed layers
    assert "data_test" not in by_name


def test_mnist_autoencoder_trains():
    """Reconstruction loss falls; the euclidean monitor top carries
    loss_weight 0 (ref: examples/mnist/mnist_autoencoder.prototxt)."""
    from sparknet_tpu.net import TPUNet
    from sparknet_tpu.solvers.solver import SolverConfig

    net = TPUNet(
        SolverConfig(base_lr=0.01, momentum=0.9), models.mnist_autoencoder(16)
    )
    rs = np.random.RandomState(0)
    base = rs.rand(64, 1, 28, 28).astype(np.float32)

    def batch(it):
        idx = rs.randint(0, 64, 16)
        return {"data": base[idx]}

    # sparse gaussian filler AT INIT (training densifies): keep-probability
    # is sparse/num_outputs = 15/500 for encode2's (500, 1000) weight
    # (ref: filler.hpp GaussianFiller sparse_)
    w = np.asarray(net.solver.variables.params["encode2"][0])
    assert 0.6 * (15 / 500) < (w != 0).mean() < 1.6 * (15 / 500)

    net.set_train_data(batch)
    l0 = net.train(1)
    net.train(40)
    l1 = net.train(1)
    assert l1 < l0 * 0.9, (l0, l1)


def test_siamese_bias_lr_mult_matches_reference():
    """Biases train at lr_mult=2 like the reference siamese prototxt."""
    net = Network(models.mnist_siamese(2), Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    specs = net.param_specs_for(variables)
    assert specs["conv1"][0].lr_mult == 1.0
    assert specs["conv1"][1].lr_mult == 2.0


def test_dsl_attention_and_moe_builders():
    """DSL builders agree with the prototxt path for the extra layer
    types (key names + value types reach the op-side readers)."""
    import jax

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.layers_dsl import (
        MoELayer,
        MultiHeadAttentionLayer,
        NetParam,
    )
    from sparknet_tpu.proto.text_format import Message

    net_param = NetParam(
        "dsl_extras",
        MultiHeadAttentionLayer("attn", ["x"], num_heads=2, causal=True, top="h"),
        MoELayer("moe", ["h"], num_experts=4, hidden_dim=32, top="y"),
    )
    net_param.add("input", "x")
    net_param.add(
        "input_shape", Message().add("dim", 2).add("dim", 6).add("dim", 8)
    )
    net = Network(net_param, Phase.TEST)
    attn, moe = net.layers[-2], net.layers[-1]
    assert attn.num_heads == 2 and attn.causal is True
    assert moe.num_experts == 4 and moe.hidden_dim == 32
    variables = net.init(jax.random.PRNGKey(0))
    shapes = [tuple(p.shape) for p in variables.params["moe"]]
    assert shapes == [(4, 8), (4, 32, 8), (4, 32), (4, 8, 32), (4, 8)]
    blobs, _, _ = net.apply(
        variables, {"x": jnp.zeros((2, 6, 8), jnp.float32)}, rng=None, train=False
    )
    assert blobs["y"].shape == (2, 6, 8)
