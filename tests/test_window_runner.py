"""tools/tpu_window_runner.py — queue/journal logic.

The runner babysits the fragile TPU relay and spends short healthy
windows on the evidence queue; its correctness decides whether scarce
chip minutes turn into banked measurements, so the pure logic (journal
accounting, dependency gating, per-window retry policy, deadline kill)
is pinned here with the dial stubbed out.
"""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def runner(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "tpu_window_runner", os.path.join(ROOT, "tools", "tpu_window_runner.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.setattr(
        mod, "JOURNAL", str(tmp_path / "evidence" / "journal.jsonl")
    )
    return mod


def _queue(tmp_path, jobs, **kw):
    p = tmp_path / "queue.json"
    p.write_text(json.dumps({"max_hours": 0.01, "jobs": jobs, **kw}))
    return str(p)


def ok_job(name, needs=None):
    j = {"name": name, "argv": [sys.executable, "-c", "print('done')"],
         "deadline_s": 30}
    if needs:
        j["needs"] = needs
    return j


def fail_job(name):
    return {"name": name, "argv": [sys.executable, "-c", "raise SystemExit(3)"],
            "deadline_s": 30}


def test_drains_dependency_chain_in_one_window(runner, tmp_path, monkeypatch):
    """leg2 needs leg1: both must run in the SAME healthy window."""
    monkeypatch.setattr(runner, "dial", lambda: True)
    q = _queue(tmp_path, [ok_job("leg1"), ok_job("leg2", needs="leg1")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    state = runner.load_done()
    assert state == {"leg1": -1, "leg2": -1}


def test_failed_job_gets_one_shot_per_window(runner, tmp_path, monkeypatch):
    dials = []

    def dial():
        dials.append(1)
        return len(dials) <= 3  # three windows, then stop dialing green

    monkeypatch.setattr(runner, "dial", dial)
    q = _queue(tmp_path, [fail_job("flaky"), ok_job("solid")],
               max_attempts=2)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    # flaky burned one attempt per window up to max_attempts=2; solid
    # still ran (the failure didn't block the rest of the window)
    assert state["flaky"] == 2
    assert state["solid"] == -1


def test_dependent_of_failed_job_never_runs(runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "dial", lambda: True)
    q = _queue(tmp_path, [fail_job("base"), ok_job("dep", needs="base")],
               max_attempts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    assert state["base"] == 1
    assert "dep" not in state
    assert not os.path.exists(
        os.path.join(runner.EVIDENCE_DIR, "dep.txt"))


def test_timeout_kills_job_and_returns_to_dialing(runner, tmp_path, monkeypatch):
    windows = []

    def dial():
        windows.append(1)
        return len(windows) == 1  # one window only

    monkeypatch.setattr(runner, "dial", dial)
    hang = {"name": "hang",
            "argv": [sys.executable, "-c", "import time; time.sleep(60)"],
            "deadline_s": 2}
    q = _queue(tmp_path, [hang, ok_job("after")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    # the hang counts as an attempt; 'after' did NOT run in that window
    # (a hung job means the window closed)
    assert state["hang"] == 1
    assert "after" not in state
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    end = [e for e in events if e.get("event") == "job_end"][0]
    assert end["timed_out"] is True and end["rc"] is None


def test_journal_marks_success_permanently(runner, tmp_path, monkeypatch):
    """A second invocation skips already-green jobs (resume semantics)."""
    monkeypatch.setattr(runner, "dial", lambda: True)
    q = _queue(tmp_path, [ok_job("once")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    n_before = sum(
        1 for l in open(runner.JOURNAL)
        if json.loads(l).get("event") == "job_start"
    )
    assert runner.main() == 0  # re-run: queue already drained
    n_after = sum(
        1 for l in open(runner.JOURNAL)
        if json.loads(l).get("event") == "job_start"
    )
    assert n_before == n_after == 1


def test_job_output_banked_to_evidence_file(runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "dial", lambda: True)
    q = _queue(tmp_path, [{
        "name": "emits",
        "argv": [sys.executable, "-c", "print('the-evidence-line')"],
        "deadline_s": 30,
    }])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    out = open(os.path.join(runner.EVIDENCE_DIR, "emits.txt")).read()
    assert "the-evidence-line" in out
