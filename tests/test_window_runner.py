"""tools/tpu_window_runner.py — queue/journal logic.

The runner babysits the fragile TPU relay and spends short healthy
windows on the evidence queue; its correctness decides whether scarce
chip minutes turn into banked measurements, so the pure logic (journal
accounting, dependency gating, per-window retry policy, deadline kill)
is pinned here with the dial stubbed out.
"""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def runner(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "tpu_window_runner", os.path.join(ROOT, "tools", "tpu_window_runner.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.setattr(
        mod, "JOURNAL", str(tmp_path / "evidence" / "journal.jsonl")
    )
    # dial stubs return instantly; without this the fast-failure backoff
    # would add real sleeps to every test with a failing dial
    monkeypatch.setattr(mod, "MIN_DIAL_PERIOD_S", 0.05)
    return mod


def _queue(tmp_path, jobs, **kw):
    p = tmp_path / "queue.json"
    p.write_text(json.dumps({"max_hours": 0.01, "jobs": jobs, **kw}))
    return str(p)


def ok_job(name, needs=None):
    j = {"name": name, "argv": [sys.executable, "-c", "print('done')"],
         "deadline_s": 30}
    if needs:
        j["needs"] = needs
    return j


def fail_job(name):
    return {"name": name, "argv": [sys.executable, "-c", "raise SystemExit(3)"],
            "deadline_s": 30}


def test_drains_dependency_chain_in_one_window(runner, tmp_path, monkeypatch):
    """leg2 needs leg1: both must run in the SAME healthy window."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("leg1"), ok_job("leg2", needs="leg1")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    state = runner.load_done()
    assert state == {"leg1": -1, "leg2": -1}


def test_failed_job_gets_one_shot_per_window(runner, tmp_path, monkeypatch):
    dials = []

    def dial(probe_id=0):
        dials.append(1)
        return len(dials) <= 3  # three windows, then stop dialing green

    monkeypatch.setattr(runner, "dial", dial)
    q = _queue(tmp_path, [fail_job("flaky"), ok_job("solid")],
               max_attempts=2)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    # flaky burned one attempt per window up to max_attempts=2; solid
    # still ran (the failure didn't block the rest of the window)
    assert state["flaky"] == 2
    assert state["solid"] == -1


def test_dependent_of_failed_job_never_runs(runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [fail_job("base"), ok_job("dep", needs="base")],
               max_attempts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    # a queue whose remaining jobs can never run is BLOCKED, not drained:
    # rc 3 so a babysitting shell can tell "all green" from "gave up"
    assert runner.main() == 3
    state = runner.load_done()
    assert state["base"] == 1
    assert "dep" not in state
    assert not os.path.exists(
        os.path.join(runner.EVIDENCE_DIR, "dep.txt"))
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    done = [e for e in events if e.get("event") == "runner_done"][-1]
    assert done["reason"] == "queue blocked"
    assert set(done["blocked_jobs"]) == {"base", "dep"}


def test_timeout_kills_job_and_returns_to_dialing(runner, tmp_path, monkeypatch):
    windows = []

    def dial(probe_id=0):
        windows.append(1)
        return len(windows) == 1  # one window only

    monkeypatch.setattr(runner, "dial", dial)
    hang = {"name": "hang",
            "argv": [sys.executable, "-c", "import time; time.sleep(60)"],
            "deadline_s": 2}
    q = _queue(tmp_path, [hang, ok_job("after")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    # a deadline kill means the WINDOW closed, not that the job failed:
    # it must not burn one of the job's max_attempts (it is tallied
    # separately under count_timeouts), and 'after' did NOT run in that
    # window
    assert "hang" not in state
    assert runner.load_done(count_timeouts=True)["hang"] == 1
    assert "after" not in state
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    end = [e for e in events if e.get("event") == "job_end"][0]
    assert end["timed_out"] is True and end["rc"] is None


def test_chronic_hangs_eventually_block(runner, tmp_path, monkeypatch):
    """A job that hangs in EVERY window is capped by max_timeouts so it
    cannot eat healthy windows to round end."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    hang = {"name": "chronic",
            "argv": [sys.executable, "-c", "import time; time.sleep(60)"],
            "deadline_s": 1}
    q = _queue(tmp_path, [hang], max_timeouts=2)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3
    assert runner.load_done(count_timeouts=True)["chronic"] == 2


def test_journal_marks_success_permanently(runner, tmp_path, monkeypatch):
    """A second invocation skips already-green jobs (resume semantics)."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("once")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    n_before = sum(
        1 for l in open(runner.JOURNAL)
        if json.loads(l).get("event") == "job_start"
    )
    assert runner.main() == 0  # re-run: queue already drained
    n_after = sum(
        1 for l in open(runner.JOURNAL)
        if json.loads(l).get("event") == "job_start"
    )
    assert n_before == n_after == 1


def test_job_output_banked_to_evidence_file(runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [{
        "name": "emits",
        "argv": [sys.executable, "-c", "print('the-evidence-line')"],
        "deadline_s": 30,
    }])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    out = open(os.path.join(runner.EVIDENCE_DIR, "emits.txt")).read()
    assert "the-evidence-line" in out


def test_probe_id_exported_to_job_env(runner, tmp_path, monkeypatch):
    """Jobs see the dial's probe id so bench records carry provenance."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [{
        "name": "probe_echo",
        "argv": [sys.executable, "-c",
                 "import os; print('probe=' + os.environ['SPARKNET_WINDOW_PROBE'])"],
        "deadline_s": 30,
    }])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    out = open(os.path.join(runner.EVIDENCE_DIR, "probe_echo.txt")).read()
    assert "probe=1" in out


def test_transitive_dead_dependency_blocks_not_spins(runner, tmp_path,
                                                     monkeypatch):
    """leg3 needs leg2 needs leg1: leg1 exhausting its attempts must mark
    the WHOLE chain blocked (rc 3), not leave leg3 'pending' and the
    runner dialing until max_hours then exiting 0."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [fail_job("leg1"), ok_job("leg2", needs="leg1"),
                          ok_job("leg3", needs="leg2")], max_attempts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    done = [e for e in events if e.get("event") == "runner_done"][-1]
    assert done["reason"] == "queue blocked"
    assert set(done["blocked_jobs"]) == {"leg1", "leg2", "leg3"}


def test_needs_typo_is_blocked_not_eternal(runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("good"), ok_job("typo", needs="no-such-job")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3
    assert runner.load_done()["good"] == -1


def test_needs_cycle_is_blocked_not_false_drained(runner, tmp_path,
                                                  monkeypatch):
    """a needs b, b needs a: neither can ever run — that is rc 3 blocked,
    not 'queue drained' success."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("a", needs="b"), ok_job("b", needs="a")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    done = [e for e in events if e.get("event") == "runner_done"][-1]
    assert done["reason"] == "queue blocked"
    assert set(done["blocked_jobs"]) == {"a", "b"}


def test_probe_ids_unique_across_restarts(runner, tmp_path, monkeypatch):
    """A restarted runner must continue the journal's probe sequence, or
    bench records' provenance field would be ambiguous."""
    dialed = []

    def dial(probe_id=0):
        dialed.append(probe_id)
        # the real dial() journals its probe id; seeding reads it back
        runner.log({"event": "dial_start", "probe": probe_id})
        return True

    monkeypatch.setattr(runner, "dial", dial)
    q = _queue(tmp_path, [ok_job("a")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    # second invocation with a fresh queue against the SAME journal
    q2 = _queue(tmp_path, [ok_job("b")])
    monkeypatch.setattr(sys, "argv", ["runner", q2])
    assert runner.main() == 0
    assert dialed == sorted(set(dialed)), dialed  # strictly increasing


def test_queue_reload_picks_up_appended_job(runner, tmp_path, monkeypatch):
    """Appending a job to the queue file mid-round is honored without a
    runner restart (the spec is re-read before every dial AND between
    jobs inside a window)."""
    q = _queue(tmp_path, [ok_job("first")])
    # the first job itself appends a second job to the queue file, the
    # way an agent appends a perf A/B while the runner babysits the relay
    append = (
        "import json; spec = json.load(open({q!r}));"
        "spec['jobs'].append({{'name': 'appended',"
        " 'argv': [{py!r}, '-c', 'print(1)'], 'deadline_s': 30}});"
        "json.dump(spec, open({q!r}, 'w'))"
    ).format(q=q, py=sys.executable)
    spec = json.loads(open(q).read())
    spec["jobs"][0]["argv"] = [sys.executable, "-c", append]
    open(q, "w").write(json.dumps(spec))

    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    state = runner.load_done()
    assert state == {"first": -1, "appended": -1}


def test_setup_jobs_run_before_any_dial(runner, tmp_path, monkeypatch):
    """Top-level "setup" jobs are host-side pre-steps: they run at
    runner start (journaled with setup:true) even when every dial is
    dead, and a failing setup retries once then journals setup_failed."""
    dials = []

    def dead_dial(probe_id):
        dials.append(len(open(runner.JOURNAL).readlines()))
        return False

    monkeypatch.setattr(runner, "dial", dead_dial)
    marker = tmp_path / "fixture.txt"
    q = _queue(
        tmp_path, [ok_job("j1")],
        setup=[{"name": "fix", "deadline_s": 30,
                "argv": [sys.executable, "-c",
                         f"open(r'{marker}', 'w').write('x'); print('ok')"]},
               {"name": "bad", "deadline_s": 30,
                "argv": [sys.executable, "-c", "raise SystemExit(2)"]}],
    )
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    assert marker.exists()  # setup ran despite zero healthy windows
    events = [json.loads(l) for l in open(runner.JOURNAL)]
    setup_ends = [e for e in events if e.get("event") == "job_end"
                  and e.get("setup")]
    assert [e["job"] for e in setup_ends] == ["fix", "bad", "bad"]  # 1 retry
    assert any(e.get("event") == "setup_failed" and e["job"] == "bad"
               for e in events)
    # every setup event was already journaled when the first dial fired
    # (the stub snapshots the journal length at call time)
    assert dials, "dial never attempted"
    last_setup = max(i for i, e in enumerate(events) if e.get("setup"))
    assert last_setup < dials[0]


def test_runner_journal_lines_are_schema_valid(runner, tmp_path,
                                               monkeypatch):
    """Every line the runner writes must satisfy the shared journal
    schema (sparknet_tpu/obs/schema.py) with ZERO allowlist help — the
    legacy allowlist is for pre-schema rounds, not for new writes."""
    from sparknet_tpu.obs import schema

    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("a"), fail_job("b"), ok_job("c")],
               max_attempts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    n, allowlisted, errors = schema.validate_journal(runner.JOURNAL)
    assert n > 0
    assert allowlisted == 0
    assert not errors, "\n".join(errors)


def test_rc4_backend_unreachable_is_window_death_not_failure(
        runner, tmp_path, monkeypatch):
    """bench.py exits 4 when its own probe says the backend is gone
    (SPARKNET_BENCH_REQUIRE_MEASURED): that is the WINDOW dying, not the
    job failing — it must not count toward max_attempts (a wedged relay
    would otherwise kill every pending bench job 300 s at a time), and
    the drain loop must go back to dialing instead of burning the next
    job against a dead backend."""
    dials = []

    def dial(probe_id=0):
        dials.append(1)
        return len(dials) <= 2  # two "healthy" windows, then give up

    monkeypatch.setattr(runner, "dial", dial)
    rc4 = {"name": "bench_rc4",
           "argv": [sys.executable, "-c", "raise SystemExit(4)"],
           # rc-4-as-window-death is OPT-IN via the bench contract env;
           # a job without it exiting 4 is a plain failure (argparse
           # errors etc. must still burn attempts)
           "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
           "deadline_s": 30}
    q = _queue(tmp_path, [rc4, ok_job("after")], max_hours=0.005)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    state = runner.load_done()
    # rc4 never became a counted failure...
    assert state.get("bench_rc4", 0) == 0
    # ...and the job AFTER it never ran in the dead window (drain broke)
    assert state.get("after", 0) == 0
    # ...but it DOES count on the hang ledger so a chronically rc-4 job
    # still blocks eventually instead of spinning forever
    assert runner.load_done(count_timeouts=True).get("bench_rc4") == 2


def test_job_journals_discovery(runner):
    """Journal discovery scans argv *.jsonl tokens plus SPARKNET_OBS,
    resolves against the job's cwd, and never surfaces the runner's own
    ledger (a job must not be judged on the runner's bookkeeping)."""
    job = {"name": "j", "cwd": "/work",
           "argv": ["python", "-u", "tool.py",
                    "--out", "out/run.jsonl", "--n", "5"],
           "env": {"SPARKNET_OBS": "/abs/obs.jsonl"}}
    got = runner.job_journals(job)
    assert got == ["/work/out/run.jsonl", "/abs/obs.jsonl"]
    # the runner's own journal is excluded even when a job names it
    self_ref = {"name": "s", "argv": ["python", runner.JOURNAL]}
    assert runner.job_journals(self_ref) == []


def test_drained_job_gets_a_schema_valid_slo_verdict(runner, tmp_path,
                                                     monkeypatch):
    """Module doc step 4: after a job ends, its obs journal is gated
    against docs/slo_manifest.json and the verdict is journaled as a
    schema-valid `slo` event naming the job and the journal."""
    from sparknet_tpu.obs import schema

    obs_journal = tmp_path / "job_obs.jsonl"
    ev = {"event": "request", "run_id": "t", "model": "live",
          "bucket": 8, "queue_wait_ms": 1.0, "batch_assembly_ms": 0.1,
          "device_ms": 2.0, "total_ms": 3.1}
    obs_journal.write_text("".join(json.dumps(ev) + "\n"
                                   for _ in range(20)))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    job = {"name": "telemetry_job",
           "argv": [sys.executable, "-c", "print('ok')",
                    str(obs_journal)],
           "deadline_s": 30}
    q = _queue(tmp_path, [job])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    events = [json.loads(ln) for ln in open(runner.JOURNAL)]
    verdicts = [e for e in events if e.get("event") == "slo"]
    assert len(verdicts) == 1
    v = verdicts[0]
    assert v["job"] == "telemetry_job" and v["ok"] is True
    assert v["journal"].endswith("job_obs.jsonl")
    assert schema.validate_line(v) == []
    # the verdict landed after the job_end it gates
    kinds = [e.get("event") for e in events]
    assert kinds.index("slo") > kinds.index("job_end")


def test_slo_burn_is_journaled_but_never_fails_the_job(runner, tmp_path,
                                                       monkeypatch):
    """A burned SLO is evidence, not a retry trigger: the job stays
    green on the queue ledger while the verdict names the burn."""
    obs_journal = tmp_path / "burn_obs.jsonl"
    ev = {"event": "request", "run_id": "t", "model": "live",
          "bucket": 8, "queue_wait_ms": 900.0, "batch_assembly_ms": 0.1,
          "device_ms": 2.0, "total_ms": 902.1}
    obs_journal.write_text("".join(json.dumps(ev) + "\n"
                                   for _ in range(60)))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    job = {"name": "hot_job",
           "argv": [sys.executable, "-c", "print('ok')",
                    str(obs_journal)],
           "deadline_s": 30}
    q = _queue(tmp_path, [job])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0  # queue drains green despite the burn
    assert runner.load_done() == {"hot_job": -1}
    verdicts = [json.loads(ln) for ln in open(runner.JOURNAL)
                if json.loads(ln).get("event") == "slo"]
    assert verdicts and verdicts[0]["ok"] is False
    assert "warm-queue-p99" in verdicts[0]["burned"]


def test_window_death_skips_slo_evaluation(runner, tmp_path, monkeypatch):
    """A deadline-killed job's half-written journal is not a specimen:
    no slo verdict is journaled for it."""
    obs_journal = tmp_path / "partial.jsonl"
    obs_journal.write_text("")
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    hang = {"name": "hang",
            "argv": [sys.executable, "-c",
                     "import time; time.sleep(60)", str(obs_journal)],
            "deadline_s": 1}
    q = _queue(tmp_path, [hang], max_hours=0.001, max_timeouts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    events = [json.loads(ln) for ln in open(runner.JOURNAL)]
    assert any(e.get("event") == "job_end" and e.get("rc") is None
               for e in events)
    assert not any(e.get("event") == "slo" for e in events)


# -- --policy survival (tools/window_policy.py) -----------------------------


def test_wedge_end_to_end_policy_replans_on_survivors(runner, tmp_path,
                                                      monkeypatch):
    """The full wedge path under ``--policy survival``: a mid-window job
    that ignores SIGTERM is SIGKILLed at its deadline, the death is
    journaled as a window death (NOT a counted attempt), the survival
    backoff defers the redial, and the next window's pick re-plans on
    the surviving candidates."""
    from sparknet_tpu.obs import schema

    monkeypatch.setattr(runner, "TERM_GRACE_S", 0.5)
    wp = runner.load_policy_module()  # cached: main() reuses this object
    # shrink the backoff rails so the deferred redial is a real sleep
    # the test can afford (the journal event is what's under test)
    monkeypatch.setattr(wp, "BACKOFF_FLOOR_S", 0.05)
    monkeypatch.setattr(wp, "BACKOFF_BASE_CAP_S", 0.05)
    monkeypatch.setattr(wp, "BACKOFF_CAP_S", 0.1)
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    # the stubborn hang: ignores SIGTERM, so only the grace-period
    # SIGKILL ends it — the worst-case wedge casualty
    stubborn = {"name": "stubborn_hang",
                "argv": [sys.executable, "-c",
                         "import signal, time;"
                         " signal.signal(signal.SIGTERM, signal.SIG_IGN);"
                         " time.sleep(60)"],
                "deadline_s": 1, "value": 5, "est_runtime_s": 1}
    survivor = dict(ok_job("survivor"), value=2, est_runtime_s=1)
    q = _queue(tmp_path, [stubborn, survivor], max_timeouts=1)
    monkeypatch.setattr(sys, "argv", ["runner", q, "--policy", "survival"])
    # rc 3: the hang exhausted max_timeouts, so the queue ends blocked
    assert runner.main() == 3
    state = runner.load_done()
    # the kill burned ZERO of the hang's max_attempts...
    assert "stubborn_hang" not in state
    # ...but did land on the timeout ledger, and the survivor banked
    assert runner.load_done(count_timeouts=True)["stubborn_hang"] == 1
    assert state["survivor"] == -1

    events = [json.loads(ln) for ln in open(runner.JOURNAL)]
    end = [e for e in events if e.get("event") == "job_end"
           and e["job"] == "stubborn_hang"][0]
    assert end["rc"] is None and end["timed_out"] is True
    sched = [e for e in events if e.get("event") == "sched"]
    # fit journaled once, before any pick
    assert [e["kind"] for e in sched if e["kind"] == "fit"] == ["fit"]
    # window 1 picked the higher-value hang (5 x p beats 2 x p); after
    # the death, window 2 re-planned on the survivors and picked the
    # only live candidate
    picks = [e for e in sched if e["kind"] == "pick"]
    assert [e["job"] for e in picks] == ["stubborn_hang", "survivor"]
    assert picks[0]["probe"] == 1 and picks[1]["probe"] == 2
    # the redial after the death was deferred and journaled
    backoffs = [e for e in sched if e["kind"] == "redial_backoff"]
    assert backoffs and backoffs[0]["consecutive_dead"] == 1
    # per-window reconciliation: the dead window banked nothing, the
    # second banked exactly the survivor's declared value
    summaries = [e for e in sched if e["kind"] == "window_summary"]
    assert [s["jobs_banked"] for s in summaries] == [0, 1]
    assert summaries[0]["banked_value"] == 0.0
    assert summaries[1]["banked_value"] == 2.0
    # every line the policy path writes is schema-valid, zero allowlist
    n, allowlisted, errors = schema.validate_journal(runner.JOURNAL)
    assert n > 0 and allowlisted == 0
    assert not errors, "\n".join(errors)


def test_default_path_writes_no_sched_events(runner, tmp_path, monkeypatch):
    """Without ``--policy`` the journal stays byte-compatible with every
    prior round: no sched events, no backoff sleeps."""
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [ok_job("plain")])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    events = [json.loads(ln) for ln in open(runner.JOURNAL)]
    assert not any(e.get("event") == "sched" for e in events)


def test_unknown_policy_is_usage_error(runner, tmp_path, monkeypatch):
    q = _queue(tmp_path, [ok_job("a")])
    monkeypatch.setattr(sys, "argv", ["runner", q, "--policy", "greedy"])
    assert runner.main() == 2
    assert not os.path.exists(runner.JOURNAL)  # refused before any write
