"""obsnet (sparknet_tpu/obs): schema, Recorder, sentinel, report, hooks.

Four contracts pinned here:

1. **Disabled path is bit-identical** — with SPARKNET_OBS off, the
   instrumented ``Solver.step`` / ``ParallelTrainer.train_round`` lower
   to the same StableHLO and dispatch the same number of device calls
   as an uninstrumented run (the acceptance criterion of the obs PR).
2. **Per-round records** — dp and tau rounds on the virtual 8-device
   CPU mesh journal fenced walls, img/s, loss EMA, and the
   comm_model-predicted collective budget.
3. **Recompile sentinel** — backend compilations are counted, and a
   shape-polymorphic step recompiling after warmup is flagged live.
4. **Report honesty** — golden-file rendering, refusal of unstamped
   walls, refusal of any throughput above its stated roofline bound.

Schema/validator/report tests are smoke-tier (stdlib-fast, CI wiring
per the obs PR); trainer-round tests ride the default tier; the full
dp+tau dryrun CLI is slow-tier.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import bank_guard
from sparknet_tpu.layers_dsl import (
    InnerProductLayer,
    NetParam,
    RDDLayer,
    SoftmaxWithLoss,
)
from sparknet_tpu.obs import schema
from sparknet_tpu.obs.recorder import Recorder, set_recorder
from sparknet_tpu.obs.report import render, render_path
from sparknet_tpu.obs.sentinel import get_sentinel
from sparknet_tpu.parallel import ParallelTrainer
from sparknet_tpu.solvers import Solver, SolverConfig

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rec(tmp_path):
    """An armed Recorder as the process singleton, detached afterwards."""
    path = str(tmp_path / "journal.jsonl")
    recorder = set_recorder(Recorder(path, run_id="test"))
    yield recorder
    set_recorder(None)


def events_of(recorder, kind=None):
    evs = schema.load_journal(recorder.path)
    return [e for e in evs if kind is None or e.get("event") == kind]


# -- nets -------------------------------------------------------------------


def tiny_net(batch):
    return NetParam(
        "obs_net",
        RDDLayer("data", shape=[batch, 4]),
        RDDLayer("label", shape=[batch]),
        InnerProductLayer("ip", ["data"], num_output=10),
        SoftmaxWithLoss("loss", ["ip", "label"]),
    )


def tiny_feeds(batch, tau=0, seed=0):
    rs = np.random.RandomState(seed)
    data = rs.randn(batch, 4).astype(np.float32)
    label = rs.randint(0, 10, batch).astype(np.int32)
    if tau:
        data = np.stack([data] * tau)
        label = np.stack([label] * tau)
    return {"data": data, "label": label}


def tiny_solver(batch=8):
    return Solver(SolverConfig(base_lr=0.1), tiny_net(batch))


# -- schema -----------------------------------------------------------------


@pytest.mark.smoke
def test_make_event_stamps_and_validates():
    line = schema.make_event("dial_start", probe=3)
    assert line["event"] == "dial_start" and line["probe"] == 3
    assert schema.validate_line(line) == []


@pytest.mark.smoke
def test_make_event_rejects_schema_violations():
    with pytest.raises(ValueError, match="missing required"):
        schema.make_event("dial_start")  # no probe
    with pytest.raises(ValueError, match="unknown event"):
        schema.make_event("no_such_event", x=1)
    with pytest.raises(ValueError, match="unknown field"):
        schema.make_event("dial_start", probe=1, bogus=2)
    with pytest.raises(ValueError, match="schema wants"):
        schema.make_event("dial_start", probe="one")


@pytest.mark.smoke
def test_existing_evidence_journals_validate():
    """Every banked journal passes; legacy deviations pass ONLY through
    the explicit allowlist (r3 predates probe ids), never silently."""
    import glob

    paths = sorted(glob.glob(
        os.path.join(ROOT, "docs", "evidence_r*", "journal.jsonl")))
    assert paths, "no banked journals found"
    saw_allowlisted = False
    for path in paths:
        n, allowlisted, errors = schema.validate_journal(path)
        assert n > 0
        assert not errors, "\n".join(errors)
        saw_allowlisted |= allowlisted > 0
    assert saw_allowlisted, "r3's probe-less dials should ride the allowlist"


@pytest.mark.smoke
def test_allowlist_is_journal_specific():
    """The r3 allowlist entry must not forgive the same deviation in a
    NEW journal (tmp path does not match the allowlisted suffix)."""
    import tempfile

    with tempfile.NamedTemporaryFile(
            "w", suffix=".jsonl", delete=False) as f:
        f.write(json.dumps({"event": "dial_start",
                            "utc": "2026-08-04 00:00:00Z"}) + "\n")
        path = f.name
    try:
        _, allowlisted, errors = schema.validate_journal(path)
        assert allowlisted == 0
        assert errors and "probe" in errors[0]
    finally:
        os.unlink(path)


@pytest.mark.smoke
def test_validator_cli(tmp_path, capsys):
    from sparknet_tpu.obs.__main__ import validate_main

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(schema.make_event("runner_done",
                                                 reason="ok")) + "\n")
    assert validate_main([str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "job_end"}\n')
    assert validate_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out


# -- report (golden + refusals) ---------------------------------------------

GOLDEN_EVENTS = [
    {"event": "run_start", "run_id": "golden",
     "utc": "2026-08-04 00:00:00Z", "pid": 1},
    {"event": "round", "run_id": "golden", "utc": "2026-08-04 00:00:01Z",
     "mode": "dp", "tau": 1, "devices": 8, "iters": 1, "batch": 16,
     "wall_s": 0.5, "images_per_sec": 32.0, "loss": 2.3026,
     "loss_ema": 2.3026, "fenced": True, "compiles": 12,
     "comm": {"param_bytes": 1000, "state_bytes": 0,
              "predicted": {"all-reduce": [950, 1665]},
              "note": "tau=1 sync SGD"}},
    {"event": "round", "run_id": "golden", "utc": "2026-08-04 00:00:02Z",
     "mode": "tau", "tau": 3, "devices": 8, "iters": 3, "batch": 16,
     "wall_s": 0.25, "images_per_sec": 192.0, "loss": 2.2,
     "loss_ema": 2.2923, "fenced": False, "compiles": 0},
    {"event": "span", "run_id": "golden", "utc": "2026-08-04 00:00:03Z",
     "name": "solver.solve", "wall_s": 1.25, "fenced": True,
     "fence_value": 0.125},
    {"event": "span", "run_id": "golden", "utc": "2026-08-04 00:00:04Z",
     "name": "stage-db", "wall_s": 0.01, "fenced": False, "host": True},
    {"event": "span", "run_id": "golden", "utc": "2026-08-04 00:00:05Z",
     "name": "leaky", "wall_s": 0.5, "fenced": False},
    {"event": "recompile", "run_id": "golden",
     "utc": "2026-08-04 00:00:06Z", "count": 2, "total": 14,
     "where": "dp", "expected": False},
    {"event": "bench", "run_id": "golden", "utc": "2026-08-04 00:00:07Z",
     "metric": "alexnet_train_images_per_sec_per_chip", "measured": True,
     "fenced": True,
     "record": {"metric": "alexnet_train_images_per_sec_per_chip",
                "value": 12290.0, "unit": "img/s", "probe": 16,
                "roofline_img_s_upper_bound": 13213.0}},
    {"event": "bench", "run_id": "golden", "utc": "2026-08-04 00:00:08Z",
     "metric": "bogus_img_s", "measured": True, "fenced": True,
     "record": {"metric": "bogus_img_s", "value": 99999.0,
                "unit": "img/s", "roofline_img_s_upper_bound": 13213.0}},
    {"event": "bank", "run_id": "golden", "utc": "2026-08-04 00:00:09Z",
     "path": "docs/bench_last_good.json", "measured": True,
     "metric": "alexnet_train_images_per_sec_per_chip", "value": 12290.0},
    {"event": "bank", "run_id": "golden", "utc": "2026-08-04 00:00:10Z",
     "path": "/tmp/int8_bench_rehearsal.json", "measured": False,
     "rehearsal": True},
    {"event": "request", "run_id": "golden",
     "utc": "2026-08-04 00:00:10Z", "model": "live", "bucket": 8,
     "queue_wait_ms": 1.5, "batch_assembly_ms": 0.2, "device_ms": 4.0,
     "total_ms": 5.7, "batch_n": 5, "padded": True,
     "lineage": {"span": "req:live:1", "parent": "gen:live:v1"}},
    {"event": "request", "run_id": "golden",
     "utc": "2026-08-04 00:00:10Z", "model": "live", "bucket": 8,
     "queue_wait_ms": 1.9, "batch_assembly_ms": 0.2, "device_ms": 4.0,
     "total_ms": 6.1, "deadline_flush": True,
     "lineage": {"span": "req:live:2", "parent": "gen:live:v1"}},
    {"event": "metrics", "run_id": "golden",
     "utc": "2026-08-04 00:00:11Z", "seq": 1,
     "counters": {"serve/requests": 2},
     "gauges": {"train/loss_ema/dp": 2.3026},
     "hists": {"serve/total_ms/live/b8": {
         "count": 2, "sum": 11.8, "min": 5.7, "max": 6.1,
         "buckets": {"30": 1, "31": 1}}}},
    {"event": "run_end", "run_id": "golden", "utc": "2026-08-04 00:00:11Z",
     "rounds": 2, "spans": 3, "compiles": 14},
    # a window-runner ledger line (no run_id): the report renders these
    # in their own section, and the slo verdict is the runner's per-job
    # gate (tools/tpu_window_runner.py module doc step 4)
    {"event": "slo", "utc": "2026-08-04 00:00:12Z", "job": "loop_dryrun",
     "ok": True, "gates": 5, "applicable": 2,
     "journal": "docs/evidence_r7/loop_dryrun.jsonl",
     "manifest": "docs/slo_manifest.json"},
]


@pytest.mark.smoke
def test_golden_events_are_schema_valid():
    for ev in GOLDEN_EVENTS:
        assert schema.validate_line(ev) == [], ev


@pytest.mark.smoke
def test_report_golden_file(tmp_path):
    """The rendered report is pinned byte-for-byte: formatting drift is
    a deliberate decision (regenerate tests/data/obs_report_golden.md),
    not an accident."""
    journal = tmp_path / "golden.jsonl"
    journal.write_text(
        "".join(json.dumps(ev) + "\n" for ev in GOLDEN_EVENTS))
    text = render_path(str(journal))
    golden = os.path.join(ROOT, "tests", "data", "obs_report_golden.md")
    with open(golden, encoding="utf-8") as f:
        assert text == f.read()


@pytest.mark.smoke
def test_report_refuses_unstamped_walls():
    text = render(GOLDEN_EVENTS, source="t")
    # the unfenced tau round's throughput is withheld
    assert "REFUSED (unfenced)" in text
    assert "192.0" not in text
    # the unfenced, non-host span's wall is withheld
    assert "span closed without a fence stamp" in text


@pytest.mark.smoke
def test_report_never_prints_throughput_above_roofline():
    text = render(GOLDEN_EVENTS, source="t")
    assert "exceeds its stated roofline bound" in text
    assert "99999" not in text  # the bogus value never prints
    # the honest bench record still prints, with its bound
    assert "12290" in text


# -- Recorder ---------------------------------------------------------------


def test_disabled_recorder_is_falsy_and_writes_nothing(tmp_path):
    recorder = Recorder(None)
    assert not recorder
    recorder.round(mode="solo", tau=1, devices=1, iters=1, batch=4,
                   wall_s=0.1, loss=1.0, fenced=True)
    with recorder.span("x") as sp:
        sp.fence(jnp.float32(1.0))  # no-op when disabled
    recorder.close()


def test_span_fence_and_unfenced_marking(rec):
    with rec.span("fenced") as sp:
        sp.fence(jnp.float32(2.5))
    with rec.span("unfenced"):
        pass
    with rec.span("host-side", host=True):
        pass
    spans = {e["name"]: e for e in events_of(rec, "span")}
    assert spans["fenced"]["fenced"] is True
    assert spans["fenced"]["fence_value"] == 2.5
    assert spans["unfenced"]["fenced"] is False
    assert spans["host-side"]["host"] is True


def test_bank_guard_writes_are_journaled(rec, tmp_path):
    """bank_guard and obs share one code path for measured stamping:
    every banked write lands in the journal with the same flag."""
    measured_path = str(tmp_path / "x_last.json")
    bank_guard(measured_path,
               {"metric": "m", "value": 1.5, "measured": True},
               measured=True)
    bank_guard(str(tmp_path / "y_last.json"), {"metric": "m2"},
               measured=False)  # diverts to /tmp + rehearsal stamp
    banks = events_of(rec, "bank")
    assert len(banks) == 2
    assert banks[0]["path"] == measured_path
    assert banks[0]["measured"] is True and banks[0]["value"] == 1.5
    assert banks[1]["measured"] is False
    assert banks[1]["rehearsal"] is True
    assert "y_last_rehearsal" in banks[1]["path"]
    # a detached recorder stops observing
    set_recorder(None)
    bank_guard(str(tmp_path / "z_last.json"), {"metric": "m3"},
               measured=False)
    assert len(events_of(rec, "bank")) == 2


# -- sentinel ---------------------------------------------------------------


def test_sentinel_counts_backend_compiles():
    sentinel = get_sentinel().install()
    assert sentinel.available
    f = jax.jit(lambda x: x * 2 + 1)
    c0 = sentinel.count
    f(jnp.ones((3,)))
    assert sentinel.count > c0  # cold call compiled
    c1 = sentinel.count
    f(jnp.ones((3,)))
    assert sentinel.count == c1  # cache hit: no compile event
    f(jnp.ones((5,)))
    assert sentinel.count > c1  # new shape: recompile


def test_recompile_flagged_on_shape_polymorphic_step(rec):
    """A step whose feed shapes change after warmup recompiles; the
    sentinel flags it live (expected=False) — the runtime complement of
    graphcheck's static graph-recompile-hazard."""
    solver = tiny_solver(batch=8)
    solver.step(1, lambda it: tiny_feeds(8))     # warmup round: expected
    solver.step(1, lambda it: tiny_feeds(6))     # batch moved: recompile
    rounds = events_of(rec, "round")
    assert len(rounds) == 2
    assert rounds[1]["compiles"] > 0
    alarms = events_of(rec, "recompile")
    assert alarms and alarms[0]["expected"] is False
    assert alarms[0]["where"] == "solo"


def test_absorb_compiles_keeps_deploy_builds_expected(rec):
    """Deploy-arm candidate AOT builds happen BETWEEN training rounds;
    absorb_compiles folds them into the by-design ledger so the next
    round does not claim them as phantom unexpected recompiles (the
    ProductionLoop.rollout -> elastic round seam)."""
    get_sentinel().install()
    rec.round(mode="elastic", tau=1, devices=2, iters=1, batch=8,
              wall_s=0.1, loss=1.0, fenced=True)  # warms "elastic"
    # a candidate build compiles off the round path
    jax.jit(lambda x: x * 3 - 1)(jnp.ones((11,)))
    n = rec.absorb_compiles("deploy")
    assert n > 0
    alarms = events_of(rec, "recompile")
    assert len(alarms) == 1
    assert alarms[0]["where"] == "deploy"
    assert alarms[0]["expected"] is True
    assert alarms[0]["count"] == n
    # the next warm round sees a clean ledger: no phantom alarm
    rec.round(mode="elastic", tau=1, devices=2, iters=1, batch=8,
              wall_s=0.1, loss=1.0, fenced=True)
    assert len(events_of(rec, "recompile")) == 1
    # idempotent when nothing compiled since
    assert rec.absorb_compiles("deploy") == 0
    assert len(events_of(rec, "recompile")) == 1


# -- Solver instrumentation -------------------------------------------------


def test_solver_round_record_contents(rec):
    solver = tiny_solver(batch=8)
    loss = solver.step(3, lambda it: tiny_feeds(8, seed=it))
    rounds = events_of(rec, "round")
    assert len(rounds) == 1
    r = rounds[0]
    assert r["mode"] == "solo" and r["tau"] == 1 and r["devices"] == 1
    assert r["iters"] == 3 and r["batch"] == 8
    assert r["fenced"] is True
    assert r["images_per_sec"] > 0 and r["wall_s"] > 0
    assert np.isfinite(r["loss"]) and np.isfinite(r["loss_ema"])
    assert r["iteration"] == 3
    assert np.isfinite(loss)


def test_solver_solve_emits_fenced_span(rec):
    solver = Solver(SolverConfig(base_lr=0.1, max_iter=2,
                                 snapshot_after_train=False), tiny_net(8))
    solver.solve(lambda it: tiny_feeds(8, seed=it))
    spans = events_of(rec, "span")
    assert [s["name"] for s in spans] == ["solver.solve"]
    assert spans[0]["fenced"] is True
    # the inner step() call journaled its own round under the span
    assert len(events_of(rec, "round")) == 1


# -- the disabled-path guarantee --------------------------------------------


def _lowered_text(solver):
    feeds = {k: jnp.asarray(v) for k, v in tiny_feeds(8).items()}
    return solver._train_step.lower(
        solver.variables, solver.slots, 0, feeds, solver._key).as_text()


def test_disabled_path_stablehlo_identical(tmp_path):
    """SPARKNET_OBS=0 (default): the solver's lowered StableHLO is the
    same whether or not obs instrumentation ever ran — the hooks live
    entirely outside the jitted programs."""
    baseline = tiny_solver(batch=8)
    text_off = _lowered_text(baseline)

    instrumented = tiny_solver(batch=8)
    recorder = set_recorder(
        Recorder(str(tmp_path / "j.jsonl"), run_id="hash"))
    try:
        instrumented.step(2, lambda it: tiny_feeds(8, seed=it))
        text_on = _lowered_text(instrumented)
    finally:
        set_recorder(None)
    assert events_of(recorder, "round"), "obs was armed and recording"
    assert text_on == text_off


def test_disabled_path_dispatch_count_identical(tmp_path):
    """Same dispatch count with obs on and off: the fence is a VALUE
    fetch of an existing output, never an extra device call."""

    def count_dispatches(solver, armed):
        calls = []
        orig = solver._train_step

        def counting(*a, **kw):
            calls.append(1)
            return orig(*a, **kw)

        solver._train_step = counting
        if armed:
            set_recorder(Recorder(str(tmp_path / "d.jsonl"),
                                  run_id="dispatch"))
        try:
            solver.step(3, lambda it: tiny_feeds(8, seed=it))
        finally:
            if armed:
                set_recorder(None)
        return len(calls)

    assert count_dispatches(tiny_solver(batch=8), armed=False) == 3
    assert count_dispatches(tiny_solver(batch=8), armed=True) == 3


# -- ParallelTrainer rounds on the 8-device mesh ----------------------------


def test_dp_round_records_on_cpu_mesh(rec):
    assert jax.device_count() == 8, "conftest must fake 8 CPU devices"
    trainer = ParallelTrainer(tiny_solver(batch=16), tau=1)
    for i in range(2):
        loss = trainer.train_round(lambda it: tiny_feeds(16, seed=it))
    assert np.isfinite(loss)
    rounds = events_of(rec, "round")
    assert len(rounds) == 2
    r = rounds[0]
    assert r["mode"] == "dp" and r["tau"] == 1
    assert r["devices"] == 8 and r["workers"] == 8
    assert r["iters"] == 1 and r["batch"] == 16
    assert r["fenced"] is True and r["images_per_sec"] > 0
    # the analytic comm budget rides the record: one grad-sized
    # all-reduce window derived from the ACTUAL param bytes
    comm = r["comm"]
    lo, hi = comm["predicted"]["all-reduce"]
    assert lo <= comm["param_bytes"] <= hi
    # round 2 of a warm mode must not recompile
    assert rounds[1]["compiles"] == 0
    assert not events_of(rec, "recompile")


def test_tau_round_records_on_cpu_mesh(rec):
    tau = 2
    trainer = ParallelTrainer(tiny_solver(batch=2), tau=tau)
    for i in range(2):
        trainer.train_round(lambda it: tiny_feeds(16, tau=tau, seed=it))
    rounds = events_of(rec, "round")
    assert len(rounds) == 2
    r = rounds[0]
    assert r["mode"] == "tau" and r["tau"] == tau
    assert r["iters"] == tau and r["batch"] == 16
    assert r["fenced"] is True
    # tau's budget is the round's ONE model-sized pmean (params+state)
    comm = r["comm"]
    lo, hi = comm["predicted"]["all-reduce"]
    assert lo <= comm["param_bytes"] + comm["state_bytes"] <= hi
    assert r["loss_ema"] == pytest.approx(r["loss"], rel=1e-6)
    assert rounds[1]["compiles"] == 0


# -- Timer (satellite: fence-by-value, contract-clean) ----------------------


def test_timer_stop_fences_by_value():
    from sparknet_tpu.utils.timing import Timer

    t = Timer().start()
    out = jax.jit(lambda x: jnp.sum(x) * 2)(jnp.ones((4,)))
    ms = t.stop(out)
    assert ms >= 0 and t.elapsed_ms == ms


def test_timer_stop_rejects_large_leaf():
    from sparknet_tpu.utils.timing import Timer

    with pytest.raises(ValueError, match="last leaf"):
        Timer().start().stop(jnp.zeros((512, 1024), jnp.float32))


# -- the dryrun CLI (the zero-chip-time acceptance path) --------------------


@pytest.mark.slow
def test_dryrun_cli_journal_and_report(tmp_path):
    from sparknet_tpu.obs.__main__ import main

    out = str(tmp_path / "dry.jsonl")
    assert main(["dryrun", "--out", out, "--rounds", "2"]) == 0
    assert main(["validate", out]) == 0
    rounds = [e for e in schema.load_journal(out)
              if e.get("event") == "round"]
    assert {r["mode"] for r in rounds} == {"dp", "tau"}
    assert all(r["fenced"] and r["images_per_sec"] > 0 for r in rounds)
    assert all("comm" in r for r in rounds)
    text = render_path(out)
    assert "| dp |" in text and "| tau |" in text
    # every wall in a dryrun is fenced: no refusal markers in the body
    assert "REFUSED (unfenced)" not in text
    assert "REFUSED:" not in text and "REFUSED —" not in text
