"""Streaming metrics (sparknet_tpu/obs/metrics.py): the bounded-memory
percentile contract, pinned on adversarial distributions.

The hub's histograms make a precision CLAIM — fixed log boundaries at
40 buckets/decade (~5.93% relative width), nearest-rank percentile on
bucket upper bounds clamped to the observed [min, max], so estimates
are exact at the extremes, never under-report a tail, and sit within
one bucket width of exact everywhere else — and a MERGE claim:
snapshots combine by integer bucket-count addition, associatively.
These tests feed the shapes that break naive implementations (values
ON bucket boundaries, single samples, bimodal mass at the extremes)
and check the claims against exact nearest-rank computed the slow way.

All stdlib + numpy-free, smoke-tier: the obs package must stay
importable (and testable) next to a wedged relay with no jax anywhere.
"""

from __future__ import annotations

import json
import math

import pytest

from sparknet_tpu.obs import schema
from sparknet_tpu.obs.metrics import (
    BUCKETS_PER_DECADE,
    Histogram,
    JournalTail,
    MetricsHub,
    bucket_index,
    bucket_lower,
    merge_snapshots,
    percentile,
)

pytestmark = pytest.mark.smoke

# one bucket's relative width: 10^(1/40) - 1 (~5.93%) — the histogram's
# own stated estimate bound
_REL = 10.0 ** (1.0 / BUCKETS_PER_DECADE) - 1.0


def _exact_nearest_rank(values: list[float], q: float) -> float:
    """Exact nearest-rank percentile (the definition the histogram
    approximates): the smallest value with at least ceil(q/100 * n)
    observations at or below it."""
    ordered = sorted(values)
    rank = max(1, math.ceil(q / 100.0 * len(ordered)))
    return ordered[rank - 1]


def _hist_of(values) -> Histogram:
    h = Histogram()
    for v in values:
        h.observe(v)
    return h


# -- bucket geometry --------------------------------------------------------


def test_bucket_boundaries_are_fixed_and_half_open():
    # a value sitting EXACTLY on a bucket's lower boundary belongs to
    # that bucket (half-open [lo, hi)): 10.0 is bucket 40's lower edge
    assert bucket_lower(0) == 1.0
    assert bucket_lower(BUCKETS_PER_DECADE) == pytest.approx(10.0)
    i = bucket_index(10.0)
    assert bucket_lower(i) <= 10.0 < bucket_lower(i + 1)
    # determinism: the same value always lands in the same bucket —
    # no float drift between observe-time and merge-time binning
    assert all(bucket_index(10.0) == i for _ in range(100))


def test_bucket_index_spans_decades():
    for v in (1e-6, 0.004, 1.0, 37.5, 1e4, 1e9):
        i = bucket_index(v)
        assert bucket_lower(i) <= v < bucket_lower(i + 1)


# -- percentile precision on adversarial distributions ----------------------


def test_single_sample_every_percentile_is_exact():
    h = _hist_of([37.2])
    snap = h.snapshot()
    for q in (0.0, 1.0, 50.0, 99.0, 100.0):
        assert percentile(snap, q) == 37.2


def test_boundary_values_hold_the_precision_bound():
    # every observation ON a bucket boundary: the nearest-rank answer
    # IS a boundary, and clamping keeps the estimate exact at both ends
    values = [bucket_lower(i) for i in range(0, 81, 8)]
    snap = _hist_of(values).snapshot()
    for q in (50.0, 90.0, 99.0):
        exact = _exact_nearest_rank(values, q)
        est = percentile(snap, q)
        assert exact <= est <= exact * (1.0 + _REL), (q, exact, est)
    assert percentile(snap, 100.0) == max(values)
    # the low extreme is conservative-side too: never BELOW min, at
    # most one bucket width above it
    assert min(values) <= percentile(snap, 0.0) <= min(values) * (1 + _REL)


def test_bimodal_mass_never_under_reports_the_tail():
    # half the mass at 1, half at 100: p50 must stay in the low mode
    # (within one bucket width), p99/p100 must report the HIGH mode
    # exactly — a tail estimate below 100 would launder a latency spike
    values = [1.0, 1.0, 100.0, 100.0]
    snap = _hist_of(values).snapshot()
    assert 1.0 <= percentile(snap, 50.0) <= 1.0 * (1.0 + _REL)
    assert percentile(snap, 99.0) == 100.0
    assert percentile(snap, 100.0) == 100.0


def test_estimates_within_one_bucket_width_of_exact():
    # a deterministic spread over 3 decades (no RNG in tests that pin
    # numeric claims): j*j+0.5 hits awkward non-boundary values
    values = [(j * j + 0.5) / 7.0 for j in range(1, 120)]
    snap = _hist_of(values).snapshot()
    for q in (25.0, 50.0, 75.0, 95.0, 99.0):
        exact = _exact_nearest_rank(values, q)
        est = percentile(snap, q)
        assert exact * (1.0 - 1e-12) <= est <= exact * (1.0 + _REL), (
            q, exact, est)


def test_zero_and_negative_values_have_their_own_bucket():
    snap = _hist_of([0.0, 0.0, 5.0]).snapshot()
    assert percentile(snap, 50.0) == 0.0
    assert percentile(snap, 100.0) == 5.0


def test_percentile_of_empty_snapshot_is_none():
    assert percentile(Histogram().snapshot(), 50.0) is None


# -- merge: exact and associative -------------------------------------------


def test_merge_equals_single_pass():
    # dyadic values: float sums are exact, so merged == single-pass
    # bitwise, not approximately
    a = [0.5, 2.0, 8.0, 64.0]
    b = [0.25, 4.0, 1024.0]
    merged = merge_snapshots(_hist_of(a).snapshot(), _hist_of(b).snapshot())
    assert merged == _hist_of(a + b).snapshot()


def test_merge_is_associative_and_commutative():
    parts = [[0.5, 1.0], [2.0, 4.0, 8.0], [0.125, 1024.0]]
    sa, sb, sc = (_hist_of(p).snapshot() for p in parts)
    left = merge_snapshots(merge_snapshots(sa, sb), sc)
    right = merge_snapshots(sa, merge_snapshots(sb, sc))
    flipped = merge_snapshots(sc, merge_snapshots(sb, sa))
    assert left == right == flipped
    assert left == _hist_of(parts[0] + parts[1] + parts[2]).snapshot()


def test_merge_with_empty_is_identity():
    s = _hist_of([1.0, 3.0]).snapshot()
    empty = Histogram().snapshot()
    assert merge_snapshots(s, empty) == s
    assert merge_snapshots(empty, s) == s


# -- the hub ----------------------------------------------------------------


def test_hub_folds_request_events_and_flushes_on_schedule():
    hub = MetricsHub(flush_every=3)
    ev = {"model": "live", "bucket": 8, "queue_wait_ms": 1.0,
          "batch_assembly_ms": 0.1, "device_ms": 4.0, "total_ms": 5.1}
    assert hub.observe_event("request", ev) is None
    assert hub.observe_event("request", ev) is None
    snap = hub.observe_event("request", ev)  # third event: flush due
    assert snap is not None and snap["seq"] == 1
    assert snap["counters"]["serve/requests"] == 3
    assert snap["hists"]["serve/total_ms/live/b8"]["count"] == 3
    # snapshots are CUMULATIVE: the next flush supersedes, not deltas
    for _ in range(3):
        nxt = hub.observe_event("request", ev)
    assert nxt["seq"] == 2
    assert nxt["counters"]["serve/requests"] == 6


def test_hub_flush_fields_make_a_schema_valid_metrics_event():
    hub = MetricsHub(flush_every=1 << 62)
    hub.observe_event("round", {"mode": "dp", "wall_s": 0.5,
                                "iters": 1, "batch": 16,
                                "loss_ema": 2.3, "fenced": True})
    fields = hub.flush_fields()
    assert fields is not None
    line = schema.make_event("metrics", run_id="t", **fields)
    assert schema.validate_line(line) == []


def test_hub_with_nothing_to_flush_returns_none():
    assert MetricsHub(flush_every=1).flush_fields() is None


# -- the tail ---------------------------------------------------------------


def test_journal_tail_reads_only_complete_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    tail = JournalTail(str(path))
    assert list(tail.poll()) == []  # file does not exist yet
    with open(path, "w") as f:
        f.write(json.dumps({"event": "a"}) + "\n")
        f.write('{"event": "tor')  # torn mid-append
    got = [ev["event"] for ev in tail.poll()]
    assert got == ["a"]
    with open(path, "a") as f:
        f.write('n"}\n')  # the append completes
    got = [ev["event"] for ev in tail.poll()]
    assert got == ["torn"]
    assert list(tail.poll()) == []  # nothing new
