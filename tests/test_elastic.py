"""Elastic τ-averaging: fault injection on the virtual 8-device mesh.

The suite pins ISSUE 8's contracts with zero chip time:

* deterministic shard reassignment (``round_shards`` modulo ownership —
  no example dropped or double-counted across a resize);
* the loss-trajectory-equivalence gates: kill-at-the-first-boundary ==
  never-started-with-that-worker (exact), and kill-mid-run == a fresh
  pool of the surviving width seeded from the survivors' state;
* staleness damping: s = 0 reduces exactly to the fixed-mesh tau
  trajectory (vs ``ParallelTrainer``), a rejoining straggler enters the
  weighted average with the documented ``decay ** s`` weight (checked
  against a hand-built per-worker simulation), and a worker past the
  staleness bound is dropped, never averaged;
* membership telemetry: worker_lost / worker_joined / mesh_resize
  events schema-validate and render in the obs report;
* the fused-arena path (PR 7) packs/unpacks across a resize
  (slow tier: fused elastic trajectory == unfused).
"""

import json
import os

import jax
import numpy as np
import pytest

from sparknet_tpu.models.zoo import GRAPH_SWEEP_FAMILIES
from sparknet_tpu.parallel.elastic import (
    ElasticTrainer,
    FaultEvent,
    FaultPlan,
    delay,
    join,
    kill,
    round_shards,
)
from sparknet_tpu.solvers.solver import Solver

FAM = GRAPH_SWEEP_FAMILIES["cifar10_quick"]
B = 2  # per-worker batch


def shard_fn(g):
    """The shard-id data contract: a pure function of g."""
    from sparknet_tpu.parallel.modes import _feeds_for

    return _feeds_for(FAM, B, np.random.RandomState(g % 1009))


def make_trainer(width, tau=2, plan=None, **kw):
    return ElasticTrainer(Solver(FAM.solver(), FAM.net(B)), width=width,
                          tau=tau, plan=plan, **kw)


# -- shard reassignment -----------------------------------------------------


def test_round_shards_modulo_ownership():
    grid = round_shards(cursor=5, tau=3, width=4)
    assert grid.shape == (3, 4)
    for w in range(4):
        assert all(int(g) % 4 == w for g in grid[:, w])
    # consecutive block, nothing dropped or double-counted
    assert sorted(grid.ravel().tolist()) == list(range(5, 17))


def test_round_shards_cover_epoch_across_resize():
    """An epoch's ids are consumed exactly once even when the width
    changes mid-epoch (the cursor advances by tau*W' per round)."""
    consumed = []
    cursor = 0
    for width in (8, 6, 4, 7):  # a resize between every round
        grid = round_shards(cursor, 2, width)
        consumed.extend(int(g) for g in grid.ravel())
        cursor += 2 * width
    assert sorted(consumed) == list(range(cursor))
    assert len(set(consumed)) == len(consumed)


def test_round_shards_validation():
    with pytest.raises(ValueError, match="width"):
        round_shards(0, 1, 0)


# -- fault plan -------------------------------------------------------------


def test_fault_plan_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan([FaultEvent(round=0, kind="explode")])
    with pytest.raises(ValueError, match="steps > 0"):
        FaultPlan([FaultEvent(round=0, kind="delay", worker=0, steps=0)])
    with pytest.raises(ValueError, match="count > 0"):
        FaultPlan([FaultEvent(round=0, kind="join", count=0)])
    plan = FaultPlan([kill(1, at_round=2), join(at_round=1)])
    assert [e.round for e in plan.events] == [1, 2]
    assert plan.at(2) == [kill(1, at_round=2)]


def test_kill_unknown_or_last_worker_raises():
    tr = make_trainer(2, plan=FaultPlan([kill(9, at_round=0)]))
    with pytest.raises(ValueError, match="not active"):
        tr.train_round(shard_fn)
    tr1 = make_trainer(1, plan=FaultPlan([kill(0, at_round=0)]))
    with pytest.raises(ValueError, match="last active worker"):
        tr1.train_round(shard_fn)


# -- loss-trajectory-equivalence gates --------------------------------------


def test_kill_at_start_equals_never_started():
    """The headline gate: a worker killed at the first round boundary
    leaves a trajectory identical to a pool that never had it —
    deterministic shard reassignment + per-position RNG + the hard
    averaging boundary make the equality exact, not approximate."""
    killed = make_trainer(6, plan=FaultPlan([kill(5, at_round=0)]))
    never = make_trainer(5)
    lk = [killed.train_round(shard_fn) for _ in range(3)]
    ln = [never.train_round(shard_fn) for _ in range(3)]
    assert killed.width == 5
    np.testing.assert_allclose(lk, ln, rtol=0, atol=0)


def test_kill_mid_run_equals_restart_without_worker():
    """Kill at a later boundary: the continuation equals a fresh
    trainer of the surviving width seeded from the survivors' state
    (params are the round consensus; each survivor keeps its own slot
    history — the optimizer-state-carrying handoff)."""
    tr = make_trainer(4, plan=FaultPlan([kill(3, at_round=2)]))
    for _ in range(2):
        tr.train_round(shard_fn)
    # state snapshot BEFORE the boundary applies: take it from a twin
    # trainer that ran the same two rounds with no plan, then drop the
    # doomed worker's row by hand
    twin = make_trainer(4)
    for _ in range(2):
        twin.train_round(shard_fn)
    state = twin.state_dict()
    keep = [0, 1, 2]
    state["width"] = 3
    state["wids"] = [state["wids"][i] for i in keep]
    state["variables"] = jax.tree_util.tree_map(
        lambda x: x[keep], state["variables"])
    state["slots"] = jax.tree_util.tree_map(
        lambda x: x[keep], state["slots"])
    fresh = make_trainer(3)
    fresh.load_state_dict(state)
    lc = [tr.train_round(shard_fn) for _ in range(2)]
    lf = [fresh.train_round(shard_fn) for _ in range(2)]
    assert tr.width == 3
    np.testing.assert_allclose(lc, lf, rtol=0, atol=0)


def test_staleness_zero_reduces_to_plain_tau():
    """s = 0 (no faults, all weights 1): the weighted round IS the
    fixed-mesh SparkNet tau round — the elastic trainer's trajectory
    matches ParallelTrainer on the same assembled feeds."""
    from sparknet_tpu.parallel.trainer import ParallelTrainer

    tau, W = 3, 8
    el = make_trainer(W, tau=tau)
    pt = ParallelTrainer(Solver(FAM.solver(), FAM.net(B)), tau=tau)
    cursor = 0
    le, lp = [], []
    for _ in range(3):
        grid = round_shards(cursor, tau, W)
        steps = []
        for t in range(tau):
            per = [shard_fn(int(g)) for g in grid[t]]
            steps.append({k: np.concatenate([f[k] for f in per])
                          for k in per[0]})
        feeds = {k: np.stack([s[k] for s in steps]) for k in steps[0]}
        le.append(el.train_round(shard_fn))
        lp.append(pt.train_round(lambda it: feeds))
        cursor += tau * W
    np.testing.assert_allclose(le, lp, rtol=1e-6, atol=1e-7)


# -- staleness damping ------------------------------------------------------


def test_straggler_rejoins_with_documented_weight():
    """A worker parked for s rounds rejoins with weight decay**s in the
    round average: verified against a hand-built simulation that runs
    every worker's tau steps through the Solver's own step function and
    forms the weighted average x̄ = Σ w_i x_i / Σ w_i on host."""
    decay, tau, W = 0.5, 1, 4
    # park worker 0 at round 1 for one round (steps=tau -> 1 round)
    tr = make_trainer(W, tau=tau, staleness_decay=decay,
                      plan=FaultPlan([delay(0, at_round=1, steps=tau)]))
    tr.train_round(shard_fn)  # round 0: full pool
    tr.train_round(shard_fn)  # round 1: worker 0 parked (W=3)
    assert tr.width == W - 1
    # boundary of round 2: worker 0 rejoins with s=1 -> weight 0.5
    state = tr.state_dict()
    parked = tr._parked[0]
    rows_v = [jax.tree_util.tree_map(lambda x, i=i: np.asarray(x[i]),
                                     state["variables"])
              for i in range(W - 1)] + [parked.variables]
    rows_s = [jax.tree_util.tree_map(lambda x, i=i: np.asarray(x[i]),
                                     state["slots"])
              for i in range(W - 1)] + [parked.slots]
    cursor, it = tr.cursor, tr.iter
    loss = tr.train_round(shard_fn)  # round 2: rejoin round
    assert tr.width == W
    assert np.isfinite(loss)
    np.testing.assert_allclose(tr._round_weights,
                               [1.0, 1.0, 1.0, decay])

    # hand simulation of the rejoin round
    step = tr.solver._make_train_step(debug=False)
    grid = round_shards(cursor, tau, W)
    post_v = []
    for pos in range(W):
        v, sl = rows_v[pos], rows_s[pos]
        wkey = jax.random.fold_in(tr.solver._key, pos)
        for t in range(tau):
            v, sl, _ = step(
                jax.tree_util.tree_map(np.asarray, v), sl,
                it + t, shard_fn(int(grid[t, pos])), wkey)
        post_v.append(jax.tree_util.tree_map(np.asarray, v))
    w = np.asarray([1.0, 1.0, 1.0, decay])

    def wavg(*xs):
        return np.tensordot(w / w.sum(), np.stack(xs), axes=1)

    want = jax.tree_util.tree_map(wavg, *post_v)
    got = jax.tree_util.tree_map(
        lambda x: np.asarray(x[0]), jax.device_get(tr.variables))
    for a, b in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_staleness_bound_drops_worker():
    """A straggler past the bound is dropped (worker_lost), never
    averaged: the pool stays at the shrunken width and every weight is
    fresh (1.0)."""
    tr = make_trainer(4, tau=1, staleness_bound=1,
                      plan=FaultPlan([delay(2, at_round=1, steps=3)]))
    for _ in range(5):
        tr.train_round(shard_fn)
    # parked for 3 rounds > bound 1 -> dropped at its rejoin boundary
    assert tr.width == 3
    assert not tr._parked
    np.testing.assert_allclose(tr._round_weights, np.ones(3))


# -- membership telemetry ---------------------------------------------------


def test_membership_events_schema_valid_and_rendered(tmp_path):
    from sparknet_tpu.obs import schema
    from sparknet_tpu.obs.recorder import Recorder, set_recorder
    from sparknet_tpu.obs.report import render_path

    out = str(tmp_path / "elastic.jsonl")
    set_recorder(Recorder(out))
    try:
        plan = FaultPlan([kill(3, at_round=1), join(at_round=2),
                          delay(0, at_round=2, steps=2)])
        tr = make_trainer(4, tau=2, plan=plan)
        for _ in range(4):
            tr.train_round(shard_fn)
    finally:
        set_recorder(None)
    n, allowed, errors = schema.validate_journal(out)
    assert not errors, errors
    events = [e["event"] for e in schema.load_journal(out)]
    assert "worker_lost" in events
    assert "worker_joined" in events
    assert "mesh_resize" in events
    rounds = [e for e in schema.load_journal(out) if e["event"] == "round"]
    assert all(r["mode"] == "elastic" and r["fenced"] for r in rounds)
    text = render_path(out)
    assert "elastic membership" in text
    assert "worker_lost" in text and "mesh_resize" in text


def test_obs_off_emits_nothing(tmp_path):
    """Disarmed recorder: the elastic loop journals nothing and the
    membership helper is a no-op (the off-contract)."""
    from sparknet_tpu.obs.recorder import Recorder, set_recorder

    set_recorder(Recorder(None))
    try:
        tr = make_trainer(3, tau=1,
                          plan=FaultPlan([kill(2, at_round=1)]))
        for _ in range(2):
            tr.train_round(shard_fn)
        assert tr.width == 2
    finally:
        set_recorder(None)


# -- state surface ----------------------------------------------------------


def test_state_dict_roundtrip_continues_trajectory():
    a = make_trainer(3, tau=2)
    for _ in range(2):
        a.train_round(shard_fn)
    b = make_trainer(3, tau=2)
    b.load_state_dict(a.state_dict())
    la = [a.train_round(shard_fn) for _ in range(2)]
    lb = [b.train_round(shard_fn) for _ in range(2)]
    np.testing.assert_allclose(la, lb, rtol=0, atol=0)


def test_sync_to_solver_folds_consensus():
    tr = make_trainer(3, tau=1)
    tr.train_round(shard_fn)
    tr.sync_to_solver()
    assert tr.solver.iter == tr.iter
    # post-round replicas are the consensus: every row equals the mean
    host = jax.device_get(tr.variables)
    for leaf in jax.tree_util.tree_leaves(host.params):
        np.testing.assert_allclose(leaf[0], leaf.mean(0), rtol=1e-6,
                                   atol=1e-6)


def test_join_adopts_entry_consensus_including_departing():
    """A kill and a join at the same boundary: the joiner's slots are
    the mean over the ENTRY pool — the departing worker's optimizer
    state folds into the consensus it adopts (the handoff contract)."""
    tr = make_trainer(3, tau=1,
                      plan=FaultPlan([kill(2, at_round=1),
                                      join(at_round=1)]))
    tr.train_round(shard_fn)  # round 0: slots diverge per worker
    host_s = jax.device_get(tr.slots)
    entry_rows = [jax.tree_util.tree_map(lambda x, i=i: np.asarray(x[i]),
                                         host_s) for i in range(3)]
    want = jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack(xs), axis=0), *entry_rows)
    tr._apply_boundary(1)
    assert tr._wids == [0, 1, 3]  # 2 killed, 3 joined
    got = jax.tree_util.tree_map(
        lambda x: np.asarray(x[2]), jax.device_get(tr.slots))
    for a, b in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# -- fused-arena interop (PR 7) ---------------------------------------------


@pytest.mark.slow
def test_fused_arena_packs_across_resize():
    """``Config.fused_update`` on: the arena pack/unpack lives inside
    the jitted step, so mesh re-formation (kill + join) moves only
    blob-wise state — the fused elastic trajectory matches the
    unfused one."""
    from sparknet_tpu.common import set_config

    plan = lambda: FaultPlan([kill(3, at_round=1), join(at_round=2)])
    losses = {}
    for fused in (False, True):
        set_config(fused_update=fused)
        try:
            tr = make_trainer(4, tau=2, plan=plan())
            losses[fused] = [tr.train_round(shard_fn) for _ in range(3)]
            assert tr.width == 4
        finally:
            set_config(fused_update=False)
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=1e-5, atol=1e-6)


# -- graph/mem twins --------------------------------------------------------


def test_elastic_modes_registered_at_banked_widths():
    from sparknet_tpu.parallel.modes import ELASTIC_WIDTHS, list_modes

    modes = list_modes()
    assert len(ELASTIC_WIDTHS) >= 2
    for w in ELASTIC_WIDTHS:
        assert f"elastic_w{w}" in modes


def test_elastic_manifests_banked_in_both_families():
    """The width-parameterized contract twins exist on disk with the
    width actually recorded — the coverage the elastic-manifest-fresh
    lint rule enforces at the source side."""
    from sparknet_tpu.analysis.graphcheck import MANIFEST_DIR as GDIR
    from sparknet_tpu.analysis.memcheck import MANIFEST_DIR as MDIR
    from sparknet_tpu.parallel.modes import ELASTIC_WIDTHS

    for w in ELASTIC_WIDTHS:
        for d in (GDIR, MDIR):
            path = os.path.join(d, f"elastic_w{w}.json")
            assert os.path.exists(path), path
            with open(path, encoding="utf-8") as f:
                manifest = json.load(f)
            assert manifest["meta"]["mesh"] == {"data": w}
            assert manifest["meta"]["elastic"] is True
    # the comm contract is width-invariant: same collective families,
    # model-sized window, in every banked width
    kinds = set()
    for w in ELASTIC_WIDTHS:
        with open(os.path.join(GDIR, f"elastic_w{w}.json"),
                  encoding="utf-8") as f:
            comm = json.load(f)["contract"]["comm"]
        kinds.add(tuple(sorted(comm)))
        assert "all-reduce" in comm
    assert len(kinds) == 1, kinds


@pytest.mark.slow
def test_elastic_graphcheck_slice_green():
    """Lower + audit the banked elastic twins against their manifests
    (the drift gate for the width-parameterized contract)."""
    from sparknet_tpu.analysis.graphcheck import run_graphcheck
    from sparknet_tpu.parallel.modes import ELASTIC_WIDTHS

    findings, _ = run_graphcheck(
        [f"elastic_w{w}" for w in ELASTIC_WIDTHS])
    assert not [f for f in findings if not f.suppressed], findings
