"""Property-based round-trips for the clean-room DB codecs.

The LMDB and LevelDB writers/readers implement published on-disk formats
from spec with no reference library in the environment to cross-check
against, so randomized structure is the next-best adversary: arbitrary
key/value sizes force every packing regime (inline leaf nodes, overflow
pages, multi-level B+trees; log fragmentation across 32 KiB blocks,
multi-block SSTs) through the same code paths a hand-picked fixture
would miss.
"""

import itertools

import pytest

# gate, don't hard-import: boxes without hypothesis must still COLLECT
# the suite (a bare ImportError here interrupts the whole pytest run)
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from sparknet_tpu.data.leveldb_io import LevelDbReader, LevelDbWriter
from sparknet_tpu.data.leveldb_io import snappy_decompress
from sparknet_tpu.data.lmdb_io import LmdbReader, LmdbWriter

# keys: LMDB bounds them at 511 bytes, non-empty; values: span the
# inline/overflow boundary (half a 4096 page) and multi-page sizes
KEYS = st.binary(min_size=1, max_size=64)
VALUES = st.binary(min_size=0, max_size=12_000)
ITEMS = st.dictionaries(KEYS, VALUES, min_size=0, max_size=40)

_SEQ = itertools.count()  # hypothesis reuses tmp_path across examples

COMMON = dict(
    deadline=None,  # filesystem tests on a contended box
    max_examples=25,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)


@settings(**COMMON)
@given(items=ITEMS)
def test_lmdb_roundtrip_any_shape(tmp_path, items):
    p = str(tmp_path / f"db_{next(_SEQ)}")
    with LmdbWriter(p) as w:
        for k, v in items.items():
            w.put(k, v)
    with LmdbReader(p) as r:
        assert len(r) == len(items)
        assert dict(r) == items
        # sorted-cursor contract
        assert [k for k, _ in r] == sorted(items)


@settings(**COMMON)
@given(items=ITEMS, sst=st.booleans())
def test_leveldb_roundtrip_any_shape(tmp_path, items, sst):
    p = str(tmp_path / f"ldb_{next(_SEQ)}")
    with LevelDbWriter(p, sst=sst) as w:
        for k, v in items.items():
            w.put(k, v)
    with LevelDbReader(p) as r:
        assert len(r) == len(items)
        assert dict(r) == items
        assert [k for k, _ in r] == sorted(items)


@settings(**COMMON)
@given(data=st.binary(min_size=0, max_size=5000))
def test_snappy_decode_of_literal_chunks(data):
    """Any byte string chunked into literal elements decodes back —
    the degenerate-compressor identity every snappy encoder may emit."""
    out = bytearray()
    n = len(data)
    # varint length
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    pos = 0
    while pos < n:
        chunk = data[pos : pos + 60]
        out.append((len(chunk) - 1) << 2)
        out += chunk
        pos += len(chunk)
    assert snappy_decompress(bytes(out)) == data


@settings(**COMMON)
@given(
    payloads=st.lists(st.binary(min_size=0, max_size=80_000), max_size=6)
)
def test_log_format_fragmentation_roundtrip(payloads):
    """Record framing survives arbitrary payload sizes (incl. > two
    32 KiB blocks, zero-length, and trailer-straddling boundaries)."""
    from sparknet_tpu.data import leveldb_io

    raw = leveldb_io._write_log_records(payloads)
    assert list(leveldb_io._log_records(raw)) == payloads


@settings(**COMMON)
@given(data=st.binary(min_size=0, max_size=20_000))
def test_snappy_compress_roundtrip(data):
    from sparknet_tpu.data.leveldb_io import snappy_compress

    assert snappy_decompress(snappy_compress(data)) == data


@settings(**COMMON)
@given(data=st.binary(min_size=1, max_size=200))
def test_snappy_compress_repetitive_shrinks_and_roundtrips(data):
    """Repetitive input must both shrink (copies actually emitted) and
    survive the round trip through the overlap-copy path."""
    from sparknet_tpu.data.leveldb_io import snappy_compress

    big = data * 64
    packed = snappy_compress(big)
    assert snappy_decompress(packed) == big
    assert len(packed) < len(big)
