"""Pin the timing-fence contract (round-4 judge item 1).

The committed round-4 trace artifacts carried physically impossible
"untraced wall" numbers because ``tpunet time`` stage 2 fenced a derived
device computation over un-threaded repeat calls (VERDICT r4 §weak 1).
These tests pin the two halves of the repaired contract:

* ``value_fence`` fetches the VALUE of the last pytree leaf by direct
  buffer copy, and for a solver step's ``(variables, slots, loss)``
  output that leaf IS the loss — so the fetched scalar has data
  dependence on the whole step (ref integrity model:
  caffe/src/caffe/util/benchmark.cpp:18-82 — the Timer exists so walls
  are real).
* Large last leaves raise instead of silently timing a multi-MB
  device-to-host copy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import value_fence
from sparknet_tpu.proto import parse
from sparknet_tpu.solvers import Solver, SolverConfig

TINY_NET = """
name: "fence_net"
layer { name: "data" type: "MemoryData" top: "data" top: "target"
        memory_data_param { batch_size: 4 channels: 3 height: 1 width: 1 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "pred"
        inner_product_param { num_output: 1 weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "target" top: "loss" }
"""


def _feeds():
    rs = np.random.RandomState(0)
    return {
        "data": jnp.asarray(rs.randn(4, 3, 1, 1), jnp.float32),
        "target": jnp.asarray(rs.randn(4, 1), jnp.float32),
    }


@pytest.mark.smoke
def test_fence_leaf_is_the_loss():
    """The fenced scalar of a train-step output equals the step's loss —
    i.e. the fence has data dependence on the full computation, not on
    an incidental leaf."""
    solver = Solver(SolverConfig(base_lr=0.1, solver_type="SGD"),
                    parse(TINY_NET), feed_shapes={"target": (4, 1)})
    step, v, s, key = solver.jitted_train_step(donate=False)
    out = step(v, s, 0, _feeds(), key)
    _, _, loss = out
    fenced = value_fence(out)
    assert fenced == float(np.asarray(loss))
    # and the last leaf of the full output pytree is exactly that loss
    last = jax.tree_util.tree_leaves(out)[-1]
    assert np.asarray(last) == np.asarray(loss)


def test_fence_rejects_large_leaf():
    """A big trailing leaf (e.g. fencing raw logits) is an error, not a
    silent multi-MB copy inside a timed region."""
    big = jnp.zeros((512, 1024), jnp.float32)
    with pytest.raises(ValueError, match="last leaf"):
        value_fence((1.0, big))


def test_fence_fetches_value_not_readiness():
    """The fence returns the numeric value of the scalar — a caller can
    (and bench.py does) assert finiteness on it."""
    assert value_fence(jnp.float32(2.5)) == 2.5
    assert value_fence((jnp.zeros((3,)), jnp.float32(7.0))) == 7.0
