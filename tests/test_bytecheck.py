"""bytecheck: per-defect fixtures + the banked byte-contract smoke gate.

Mirrors test_memcheck.py for the fifth analysis engine: the class-model
floor is pinned against hand computation, the floor<=census invariant
fires on a doctored program, the manifest loop round-trips
bank/drift/allow, the headline census reconciles with the banked
measured step bytes inside the stated window (and a doctored
measurement trips the divergence rule), the remat search's saved-bytes
monotonicity and winner selection are pinned on a real family plus
defect fixtures, and the off-by-default path is the IDENTITY — the
mechanism by which every banked graph/mem manifest stays byte-unchanged
with ``Config.remat`` off.
"""

import json
import os
import types

import jax.numpy as jnp
import pytest

from sparknet_tpu.analysis.byte_model import (
    HEADLINE_RATIO_WINDOW,
    REMAT_POLICIES,
    REMAT_RECOMPUTE_ORDER,
    gbytes,
    gross_traffic,
    monotonicity_violations,
    reconcile,
    selected_policy,
    step_traffic,
    xla_cost_step_bytes,
)
from sparknet_tpu.analysis.bytecheck import (
    BYTE_RULES,
    census_mode,
    run_bytecheck,
    run_headline,
    run_remat_search,
    sources_fingerprint,
)
from sparknet_tpu.analysis.mem_model import MemEqn, MemProgram

pytestmark = pytest.mark.smoke

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the class-model floor vs hand computation ------------------------------


def test_step_traffic_hand_computation():
    """S=100 params, 10 slots, 20 saved activations, 5 feed: forward
    read 100 + backward read 100 + update write 100; grads written and
    read = 200; slots r+w = 20; activations w+r = 40; feed 5."""
    t = step_traffic(param_bytes=100, slot_bytes=10,
                     saved_activation_bytes=20, feed_bytes=5)
    assert t["params_read_bytes"] == 200
    assert t["params_write_bytes"] == 100
    assert t["grad_bytes"] == 200
    assert t["slot_bytes"] == 20
    assert t["saved_activation_bytes"] == 40
    assert t["total_bytes"] == 200 + 100 + 200 + 20 + 40 + 5


def test_step_traffic_recompute_trades_param_reads_for_activations():
    """One recompute pass adds exactly one forward's param reads — the
    byte-side price of rematerialization the search weighs against the
    activation savings."""
    none = step_traffic(param_bytes=100, saved_activation_bytes=200)
    full = step_traffic(param_bytes=100, saved_activation_bytes=5,
                        recompute_passes=1)
    assert full["params_read_bytes"] - none["params_read_bytes"] == 100
    # the trade pays iff 2*saved_delta > extra param reads (here 390 > 100)
    assert full["total_bytes"] < none["total_bytes"]
    # ...and does NOT pay when the activation footprint is small
    small = step_traffic(param_bytes=100, saved_activation_bytes=5,
                         recompute_passes=1)
    base = step_traffic(param_bytes=100, saved_activation_bytes=50)
    assert small["total_bytes"] > base["total_bytes"]


def test_step_traffic_forward_only():
    t = step_traffic(param_bytes=100, slot_bytes=10, state_bytes=7,
                     saved_activation_bytes=3, train=False)
    assert t["params_read_bytes"] == 100
    assert t["params_write_bytes"] == 0
    assert t["grad_bytes"] == 0 and t["slot_bytes"] == 0
    assert t["state_bytes"] == 14 and t["saved_activation_bytes"] == 6


def test_gross_traffic_toy():
    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("t1",)),
              MemEqn(reads=("t1", "b"), writes=("out",))],
        sizes={"a": 100, "b": 40, "t1": 30, "out": 20},
        inputs=["a", "b"], outputs=["out"])
    # eqn0: 100+30; eqn1: 30+40+20
    assert gross_traffic(prog) == 220


# -- the single source of "step bytes" (bench.py / cli.py reconcile) --------


def test_xla_cost_step_bytes_shapes():
    assert xla_cost_step_bytes({"bytes accessed": 3.0}) == 3.0
    assert xla_cost_step_bytes([{"bytes accessed": 4.0}]) == 4.0  # old jax
    assert xla_cost_step_bytes([]) == 0.0
    assert xla_cost_step_bytes(None) == 0.0
    assert xla_cost_step_bytes({"flops": 1.0}) == 0.0


def test_gbytes_is_the_one_rounding():
    assert gbytes(12_334_999_999) == 12.33
    assert gbytes(0) == 0.0


def test_bench_and_cli_route_through_the_byte_model():
    """The reconciliation's two sides must share one extraction: both
    bench.py (banks step_gbytes) and the CLI's --hlo branch (prints
    hbm_bytes_per_step) read XLA's cost dict through
    ``byte_model.xla_cost_step_bytes`` — no inline re-implementation
    allowed to drift."""
    with open(os.path.join(ROOT, "bench.py"), encoding="utf-8") as f:
        bench_src = f.read()
    with open(os.path.join(ROOT, "sparknet_tpu", "cli.py"),
              encoding="utf-8") as f:
        cli_src = f.read()
    assert "xla_cost_step_bytes" in bench_src
    assert "xla_cost_step_bytes" in cli_src
    for src in (bench_src, cli_src):
        assert 'float(cost.get("bytes accessed"' not in src


# -- reconciliation + table arithmetic --------------------------------------


def test_reconcile_window():
    good = reconcile(10e9, 12e9)
    assert good["within"] and good["ratio"] == 1.2
    assert good["census_gbytes"] == 12.0
    lo, hi = HEADLINE_RATIO_WINDOW
    assert not reconcile(10e9, (hi + 1) * 10e9)["within"]
    assert not reconcile(10e9, (lo / 2) * 10e9)["within"]
    assert not reconcile(0, 12e9)["within"]  # no measurement != pass


def test_selected_policy_defaults():
    table = {"selected": {"alexnet": {"bf16": {"policy": "dots"}}}}
    assert selected_policy(table, "alexnet", "bf16") == "dots"
    assert selected_policy(table, "vgg16", "bf16") == "full"
    assert selected_policy({}, "alexnet", "bf16") == "full"
    assert selected_policy(None, "alexnet", "bf16") == "full"
    bad = {"selected": {"alexnet": {"bf16": {"policy": "no_such"}}}}
    assert selected_policy(bad, "alexnet", "bf16") == "full"


def test_monotonicity_violations():
    ok = {"none": 100, "dots": 40, "blocks": 30, "full": 10}
    assert monotonicity_violations(ok) == []
    bad = {"none": 100, "dots": 40, "blocks": 30, "full": 60}
    assert monotonicity_violations(bad) == [("dots", "full"),
                                            ("blocks", "full")]
    # absent policies are skipped, not violated
    assert monotonicity_violations({"none": 1}) == []
    # every ordered pair is over policies the search actually runs
    for a, b in REMAT_RECOMPUTE_ORDER:
        assert a in REMAT_POLICIES and b in REMAT_POLICIES


# -- off-by-default is the identity path ------------------------------------


def test_remat_off_is_the_identity_path():
    """The bit-identity mechanism: with both knobs off, apply_remat
    returns the SAME function object — the step builders trace exactly
    the pre-remat program, which is why every banked graph/mem
    manifest's stablehlo_sha256 stays byte-unchanged."""
    from sparknet_tpu.common import get_config
    from sparknet_tpu.solvers.solver import apply_remat, remat_policy

    assert get_config().remat == ""  # SPARKNET_REMAT unset => off

    def loss_fn(x):
        return x

    assert apply_remat(loss_fn, "") is loss_fn
    assert apply_remat(loss_fn, "none") is loss_fn
    assert apply_remat(loss_fn, "full") is not loss_fn
    with pytest.raises(ValueError):
        apply_remat(loss_fn, "everything")

    from sparknet_tpu import models
    cfg = models.cifar10_quick_solver()
    assert remat_policy(cfg) == ""  # both knobs off


def test_config_remat_validation():
    from sparknet_tpu.common import set_config

    try:
        assert set_config(remat="none").remat == ""
        assert set_config(remat="dots").remat == "dots"
        with pytest.raises(ValueError):
            set_config(remat="most")
    finally:
        set_config(remat="")  # never leak a policy into later tests


# -- per-defect fixture: floor exceeds census -------------------------------


def _fake_target(name="solo", param_elems=1000):
    """A minimal trainer-shaped target: big params, tiny feed."""
    return types.SimpleNamespace(
        name=name,
        args=(jnp.zeros((param_elems,), jnp.float32),
              jnp.zeros((8,), jnp.float32), 0,
              jnp.zeros((4,), jnp.float32)),
        carry_argnums=(0, 1),
        param_bytes=param_elems * 4,
        state_bytes=0,
        meta={},
    )


def test_census_flags_floor_exceeding_census():
    """A program whose eqn census moves almost nothing while the args
    say 4 KB of params must trip the invariant — the two estimators
    are describing different programs."""
    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("out",))],
        sizes={"a": 10, "out": 10}, inputs=["a"], outputs=["out"])
    problems, contract = census_mode(_fake_target(), prog)
    assert [p["rule"] for p in problems] == ["byte-floor-exceeds-census"]
    assert contract["floor_vs_census_checked"] is True
    assert contract["floor"]["total_bytes"] > contract["gross_census_bytes"]


def test_census_skips_the_invariant_for_control_flow_bodies():
    """A scan/while body's internals are not in the census (counted
    once as liveness ``extra``), so the floor comparison would be
    one-sided — recorded as skipped, never a false positive."""
    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("out",), extra=512)],
        sizes={"a": 10, "out": 10}, inputs=["a"], outputs=["out"])
    problems, contract = census_mode(_fake_target(), prog)
    assert problems == []
    assert contract["floor_vs_census_checked"] is False


# -- the smoke gate on the cheap real modes ---------------------------------


def test_bytecheck_smoke_gate_solo_and_dp():
    """THE ratchet, traffic edition: the two cheap modes must match the
    banked manifests with zero unsuppressed findings, and the floor
    must sit at or below the gross census wherever the comparison is
    two-sided."""
    findings, manifests = run_bytecheck(["solo", "dp"])
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "unsuppressed bytecheck findings:\n" + "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    for mode in ("solo", "dp"):
        c = manifests[mode]["contract"]
        if c["floor_vs_census_checked"]:
            assert c["floor"]["total_bytes"] <= c["gross_census_bytes"]
        assert c["ingredients"]["param_bytes"] > 0
        assert c["ingredients"]["train"] is True
    # dp pays the grad all-reduce solo never does
    assert manifests["dp"]["contract"]["ingredients"]["collective_bytes"] > 0
    assert manifests["solo"]["contract"]["ingredients"][
        "collective_bytes"] == 0


def test_remat_twin_censuses_the_banked_policy():
    """solo_remat's census must carry the banked winner's policy and a
    recompute pass — the twin exists to prove the modeled drop lowers."""
    findings, manifests = run_bytecheck(["solo_remat"])
    assert not [f for f in findings if not f.suppressed]
    ing = manifests["solo_remat"]["contract"]["ingredients"]
    assert ing["remat_policy"] in REMAT_POLICIES[1:]  # never "none"
    assert ing["recompute_passes"] == 1


# -- manifest machinery -----------------------------------------------------


def test_manifest_bank_diff_and_allow(tmp_path):
    """moe (sub-second to trace) exercises the full manifest loop:
    missing -> banked -> clean -> drift -> allow-suppressed."""
    banked = str(tmp_path / "contracts")
    findings, _ = run_bytecheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["byte-manifest-missing"]

    findings, _ = run_bytecheck(["moe"], banked_dir=banked, update=True)
    assert findings == []
    mpath = tmp_path / "contracts" / "moe.json"
    assert mpath.exists()

    findings, _ = run_bytecheck(["moe"], banked_dir=banked)
    assert findings == []  # steady state: re-run diffs clean

    banked_manifest = json.loads(mpath.read_text())
    banked_manifest["contract"]["gross_census_bytes"] = 99
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_bytecheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["byte-manifest-drift"]
    assert not findings[0].suppressed
    assert "gross_census_bytes" in findings[0].message

    banked_manifest["allow"] = {
        "byte-manifest-drift": "fixture: tampered census"}
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_bytecheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["byte-manifest-drift"]
    assert findings[0].suppressed


def test_sources_fingerprint_covers_the_contract_surface():
    fp = sources_fingerprint()
    for rel in ("sparknet_tpu/models/zoo.py",
                "sparknet_tpu/compiler/graph.py",
                "sparknet_tpu/solvers/solver.py",
                "sparknet_tpu/parallel/modes.py",
                "sparknet_tpu/serve/engine.py",
                "sparknet_tpu/analysis/byte_model.py"):
        assert rel in fp
    assert all(len(h) == 64 for h in fp.values())


def test_lint_rule_surface_matches_the_engine():
    """The byte-manifest-fresh lint rule duplicates the source surface
    (rules.py stays importable without bytecheck); the two spellings
    must never drift."""
    from sparknet_tpu.analysis.bytecheck import BYTE_SOURCE_PATTERNS
    from sparknet_tpu.analysis.rules import (
        _BYTE_SOURCE_DIRS,
        _BYTE_SOURCE_FILES,
    )

    assert set(BYTE_SOURCE_PATTERNS) == \
        set(_BYTE_SOURCE_DIRS) | set(_BYTE_SOURCE_FILES)


def test_rule_catalog():
    assert set(BYTE_RULES) == {
        "byte-floor-exceeds-census", "byte-headline-divergence",
        "byte-remat-no-gain", "byte-remat-nonmonotonic",
        "byte-manifest-missing", "byte-manifest-drift",
    }


# -- the headline reconciliation gate ---------------------------------------


def test_headline_reconciles_with_the_banked_measurement(tmp_path):
    """The acceptance gate: the alexnet b256 bf16 census must land
    inside the stated ratio window of the banked measured 12.33
    GB/step — the 'bytes-bound' sentence as a machine check."""
    findings, manifest = run_headline(
        banked_path=str(tmp_path / "headline.json"), update=True)
    assert findings == []
    rec = manifest["reconciliation"]
    assert rec["within"] is True
    lo, hi = HEADLINE_RATIO_WINDOW
    assert lo <= rec["ratio"] <= hi
    assert manifest["tolerance"]["ratio_window"] == [lo, hi]
    # bank -> verify round-trip diffs clean
    findings, _ = run_headline(banked_path=str(tmp_path / "headline.json"))
    assert findings == []


def test_headline_divergence_fixture(tmp_path, monkeypatch):
    """A doctored measurement far outside the window must trip
    byte-headline-divergence (census side stubbed: the defect under
    test is the gate, not the trace)."""
    import sparknet_tpu.analysis.bytecheck as bc

    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("out",))],
        sizes={"a": 500, "out": 500}, inputs=["a"], outputs=["out"])
    monkeypatch.setattr(bc, "_abstract_census", lambda *a, **k: {
        "prog": prog, "prog_undonated": prog, "params_bytes": 400,
        "state_bytes": 0, "slots_bytes": 400, "feed_bytes": 100,
        "n_slots": 1})
    fake_bench = tmp_path / "bench_last_good.json"
    fake_bench.write_text(json.dumps({"step_gbytes": 1000.0}))
    monkeypatch.setattr(bc, "BENCH_LAST_GOOD", str(fake_bench))
    findings, manifest = run_headline(
        banked_path=str(tmp_path / "headline.json"))
    assert "byte-headline-divergence" in [f.rule for f in findings]
    assert manifest["reconciliation"]["within"] is False


def test_headline_without_measurement_is_a_stated_vacuous_pass(
        tmp_path, monkeypatch):
    import sparknet_tpu.analysis.bytecheck as bc

    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("out",))],
        sizes={"a": 500, "out": 500}, inputs=["a"], outputs=["out"])
    monkeypatch.setattr(bc, "_abstract_census", lambda *a, **k: {
        "prog": prog, "prog_undonated": prog, "params_bytes": 400,
        "state_bytes": 0, "slots_bytes": 400, "feed_bytes": 100,
        "n_slots": 1})
    monkeypatch.setattr(bc, "BENCH_LAST_GOOD",
                        str(tmp_path / "no_such_bench.json"))
    findings, manifest = run_headline(
        banked_path=str(tmp_path / "headline.json"), update=True)
    assert findings == []
    assert "vacuous" in manifest["reconciliation"]["note"]


# -- the remat schedule search ----------------------------------------------


def test_remat_search_real_family_is_monotone(tmp_path, monkeypatch):
    """cifar10_quick through the real abstract-trace path: heavier
    recompute never saves more activation bytes, the winner's drop is
    non-negative, and the banked table reloads clean."""
    import sparknet_tpu.analysis.bytecheck as bc

    monkeypatch.setattr(bc, "SEARCH_DTYPES", ("f32",))
    path = str(tmp_path / "remat_policy.json")
    findings, table = run_remat_search(
        families=["cifar10_quick"], banked_path=path, update=True)
    assert findings == []
    scores = table["families"]["cifar10_quick"]["f32"]
    assert set(scores) == set(REMAT_POLICIES)
    for a, b in REMAT_RECOMPUTE_ORDER:
        assert scores[b]["saved_activation_bytes"] \
            <= scores[a]["saved_activation_bytes"]
    for policy in REMAT_POLICIES:
        # donating params+slots never raises the liveness peak
        assert scores[policy]["peak_bytes_donated"] \
            <= scores[policy]["peak_bytes_undonated"]
    sel = table["selected"]["cifar10_quick"]["f32"]
    assert sel["policy"] in REMAT_POLICIES
    assert sel["donation"] == "donate_params_slots"
    assert sel["drop_frac_vs_none"] >= 0
    assert sel["step_bytes_solo"] == \
        scores[sel["policy"]]["step_bytes"]["solo"]
    # bank -> verify round-trip diffs clean
    findings, _ = run_remat_search(
        families=["cifar10_quick"], banked_path=path)
    assert findings == []


def test_remat_search_defect_fixtures(tmp_path, monkeypatch):
    """Doctored scores: a nonmonotonic save table and a no-gain winner
    for the headline family must each raise their rule."""
    import sparknet_tpu.analysis.bytecheck as bc

    monkeypatch.setattr(bc, "_abstract_census", lambda *a, **k: None)
    flat = {p: {"saved_activation_bytes":
                {"none": 10, "dots": 40, "blocks": 5, "full": 5}[p],
                "recompute_passes": 0 if p == "none" else 1,
                "step_bytes": {"solo": 1000, "dp": 1100},
                "step_gbytes": {"solo": 0.0, "dp": 0.0},
                "peak_bytes_donated": 1, "peak_bytes_undonated": 2}
            for p in REMAT_POLICIES}
    monkeypatch.setattr(bc, "_family_step_bytes",
                        lambda cen, policy: dict(flat[policy]))
    monkeypatch.setattr(bc, "SEARCH_DTYPES", ("bf16",))
    findings, table = run_remat_search(
        families=["alexnet"],
        banked_path=str(tmp_path / "remat_policy.json"))
    rules = sorted(f.rule for f in findings)
    # dots saves MORE than none => nonmonotonic; every policy byte-tied
    # => winner "none", drop 0 < 25% => no-gain
    assert "byte-remat-nonmonotonic" in rules
    assert "byte-remat-no-gain" in rules
    assert table["selected"]["alexnet"]["bf16"]["policy"] == "none"


def test_banked_remat_policy_reader(tmp_path, monkeypatch):
    """parallel/modes reads the banked table through selected_policy;
    a missing table falls back to 'full' (deterministic before the
    first bank)."""
    import sparknet_tpu.parallel.modes as modes

    assert modes._banked_remat_policy("no_such_family", "f32") in \
        REMAT_POLICIES  # table present or not, always a valid policy


# -- CLI: shared schema with lint/graph/mem/conc ----------------------------


def test_cli_bytes_json_schema(tmp_path, capsys, monkeypatch):
    from sparknet_tpu.analysis import bytecheck as bc
    from sparknet_tpu.analysis.__main__ import main as cli_main

    monkeypatch.setattr(bc, "MANIFEST_DIR", str(tmp_path))
    rc = cli_main(["bytes", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # manifest missing in the tmp dir
    assert set(out) == {"findings", "unsuppressed", "suppressed"}
    assert out["findings"][0]["rule"] == "byte-manifest-missing"
    for key in ("rule", "path", "line", "message", "suppressed"):
        assert key in out["findings"][0]

    rc = cli_main(["bytes", "--mode", "moe", "--update"])
    assert rc == 0
    capsys.readouterr()
    rc = cli_main(["bytes", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["unsuppressed"] == 0


def test_cli_bytes_unknown_mode_is_usage_error(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["bytes", "--mode", "no-such-mode"]) == 2


def test_cli_bytes_list_rules(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["bytes", "--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "byte-headline-divergence" in out
    assert "byte-remat-nonmonotonic" in out
