"""Native data-plane tests: record DB durability + cursor snapshots, and
augmenter equivalence with the pure-Python DataTransformer.

Gated on a working toolchain (g++/make); the library builds on first use.
"""

import numpy as np
import pytest

native = pytest.importorskip("sparknet_tpu.native")

if not native.available():  # no toolchain: skip the whole module
    pytest.skip("native library unavailable", allow_module_level=True)

from sparknet_tpu.data.createdb import create_db, db_minibatches, decode_datum, encode_datum
from sparknet_tpu.native import RecordDB, transform_batch


# ---------------------------------------------------------------- record db
def test_recorddb_roundtrip(tmp_path):
    p = str(tmp_path / "x.sndb")
    with RecordDB(p, "w") as db:
        db.put(b"a", b"1")
        db.put(b"b", b"22")
        db.commit()
    with RecordDB(p, "r") as db:
        assert len(db) == 2
        assert list(db) == [(b"a", b"1"), (b"b", b"22")]


def test_recorddb_uncommitted_invisible(tmp_path):
    """Readers see only committed records (the torn-write guarantee)."""
    p = str(tmp_path / "x.sndb")
    w = RecordDB(p, "w")
    w.put(b"a", b"1")
    w.commit()
    w.put(b"b", b"2")  # not committed
    # header still says 1 — a reader opening now sees one record
    with RecordDB(p, "r") as r:
        assert len(r) == 1
        assert list(r) == [(b"a", b"1")]
    w.commit()
    w.close()
    with RecordDB(p, "r") as r:
        assert len(r) == 2


def test_recorddb_write_handle_has_no_cursor(tmp_path):
    with RecordDB(str(tmp_path / "x.sndb"), "w") as db:
        with pytest.raises(OSError):
            list(db)


def test_recorddb_missing_file(tmp_path):
    with pytest.raises(OSError):
        RecordDB(str(tmp_path / "nope.sndb"), "r")


def test_createdb_minibatches(tmp_path):
    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 8, 8)).astype(np.uint8), i % 5)
               for i in range(10)]
    p = str(tmp_path / "set.sndb")
    assert create_db(p, samples, commit_every=4) == 10
    batches = list(db_minibatches(p, 4))
    assert len(batches) == 2  # tail of 2 dropped
    assert batches[0]["data"].shape == (4, 3, 8, 8)
    np.testing.assert_array_equal(batches[0]["label"], [0, 1, 2, 3])
    np.testing.assert_allclose(batches[0]["data"][0], samples[0][0])


def test_datum_roundtrip():
    img = np.arange(3 * 4 * 5, dtype=np.uint8).reshape(3, 4, 5)
    out, label = decode_datum(encode_datum(img, 7))
    np.testing.assert_array_equal(out, img)
    assert label == 7


# ---------------------------------------------------------------- augmenter
def test_augmenter_center_crop_matches_python():
    from sparknet_tpu.data import DataTransformer, TransformConfig

    rs = np.random.RandomState(0)
    x = rs.randint(0, 255, (8, 3, 12, 12)).astype(np.uint8)
    mean = rs.rand(3, 12, 12).astype(np.float32) * 100
    py = DataTransformer(TransformConfig(crop_size=8, mean_image=mean))(x, train=False)
    nat = transform_batch(x, mean=mean, crop=8, train=False)
    np.testing.assert_allclose(nat, py, atol=1e-4)


def test_augmenter_mean_values_and_scale():
    x = np.full((2, 3, 4, 4), 40, np.uint8)
    out = transform_batch(x, mean_values=(10.0, 20.0, 30.0), scale=0.5)
    np.testing.assert_allclose(out[:, 0], 15.0)
    np.testing.assert_allclose(out[:, 2], 5.0)


def test_augmenter_train_crops_are_windows():
    rs = np.random.RandomState(1)
    x = rs.randint(0, 255, (6, 3, 10, 10)).astype(np.uint8)
    out = transform_batch(x, crop=6, mirror=True, train=True, seed=42)
    assert out.shape == (6, 3, 6, 6)
    src = x.astype(np.float32)
    for i in range(6):
        found = any(
            np.array_equal(out[i], win) or np.array_equal(out[i], win[:, :, ::-1])
            for ho in range(5) for wo in range(5)
            for win in [src[i, :, ho:ho+6, wo:wo+6]]
        )
        assert found, i


def test_augmenter_deterministic_by_seed():
    rs = np.random.RandomState(2)
    x = rs.randint(0, 255, (4, 3, 10, 10)).astype(np.uint8)
    a = transform_batch(x, crop=6, mirror=True, train=True, seed=7)
    b = transform_batch(x, crop=6, mirror=True, train=True, seed=7)
    c = transform_batch(x, crop=6, mirror=True, train=True, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # multithreaded result identical to single-threaded
    d = transform_batch(x, crop=6, mirror=True, train=True, seed=7, nthreads=1)
    np.testing.assert_array_equal(a, d)


def test_augmenter_throughput_vs_python():
    """The native path must not be slower than numpy on a realistic batch
    (it replaces the reference's 1.2 s/batch JNA hot spot)."""
    import time

    from sparknet_tpu.data import DataTransformer, TransformConfig

    rs = np.random.RandomState(0)
    x = rs.randint(0, 255, (64, 3, 64, 64)).astype(np.uint8)
    mean = rs.rand(3, 64, 64).astype(np.float32)

    t0 = time.perf_counter()
    for _ in range(5):
        transform_batch(x, mean=mean, crop=56, mirror=True, train=True, seed=1)
    native_s = time.perf_counter() - t0

    py = DataTransformer(TransformConfig(crop_size=56, mirror=True, mean_image=mean, seed=1))
    t0 = time.perf_counter()
    for _ in range(5):
        py(x, train=True)
    python_s = time.perf_counter() - t0
    # generous bound: CI noise tolerant, still catches pathological slowness
    assert native_s < python_s * 3, (native_s, python_s)


def test_datatransformer_native_backend():
    """TransformConfig(backend='native') routes uint8 batches through C++."""
    from sparknet_tpu.data import DataTransformer, TransformConfig

    rs = np.random.RandomState(0)
    x = rs.randint(0, 255, (4, 3, 12, 12)).astype(np.uint8)
    mean = rs.rand(3, 12, 12).astype(np.float32)
    t = DataTransformer(TransformConfig(
        crop_size=8, mean_image=mean, backend="native", seed=3))
    out = t(x, train=False)
    ref = DataTransformer(TransformConfig(crop_size=8, mean_image=mean))(x, train=False)
    np.testing.assert_allclose(out, ref, atol=1e-4)
    assert t._native_calls == 1


def test_augmenter_rejects_oversize_crop():
    x = np.zeros((2, 3, 8, 8), np.uint8)
    with pytest.raises(ValueError, match="crop"):
        transform_batch(x, crop=16, train=True)


def test_db_minibatches_too_small_loop_raises(tmp_path):
    p = str(tmp_path / "tiny.sndb")
    create_db(p, [(np.zeros((1, 2, 2), np.uint8), 0)])
    with pytest.raises(ValueError, match="spin forever"):
        next(db_minibatches(p, 8, loop=True))


def test_db_minibatches_remainder_kept(tmp_path):
    """drop_remainder=False yields the final short batch (stats passes see
    every record — the compute_image_mean contract)."""
    p = str(tmp_path / "r.sndb")
    create_db(p, [(np.full((1, 2, 2), i, np.uint8), i) for i in range(5)])
    batches = list(db_minibatches(p, 2, drop_remainder=False))
    assert [len(b["label"]) for b in batches] == [2, 2, 1]
    assert sum(len(b["label"]) for b in batches) == 5


def test_augmenter_concurrent_callers_match_serial():
    """Race stress: the multithreaded C++ augmenter must be reentrant —
    concurrent transform_batch calls (each itself multithreaded) produce
    exactly the serial results (SURVEY §5: thread safety by construction;
    the reference relies on BlockingQueue/InternalThread isolation)."""
    import concurrent.futures

    from sparknet_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")

    rs = np.random.RandomState(3)
    batches = [
        (rs.rand(8, 3, 16, 16) * 255).astype(np.uint8) for _ in range(12)
    ]

    def run(i):
        return native.transform_batch(
            batches[i], mean=None, mean_values=(10.0, 20.0, 30.0),
            scale=0.5, crop=12, mirror=True, train=True,
            seed=(i + 1) << 32,
        )

    serial = [run(i) for i in range(len(batches))]
    with concurrent.futures.ThreadPoolExecutor(max_workers=6) as ex:
        parallel = list(ex.map(run, range(len(batches))))
    for s, p in zip(serial, parallel):
        assert np.array_equal(s, p)
