"""numcheck: per-defect fixtures + the banked num-contract smoke gate.

Mirrors test_bytecheck.py for the sixth analysis engine: the contract
rules are pinned from both sides on hand-built census records (a seeded
bf16-accumulating dot and a smuggled f32->bf16 downcast each produce
EXACTLY one finding), the jaxpr walk is validated against a real traced
function with known dtype flow, the off-by-default path is the IDENTITY
(the mechanism by which every banked graph/mem/byte manifest stays
byte-unchanged with ``Config.activation_dtype`` off), the manifest loop
round-trips bank/drift/allow, the mixed-precision search's winner
selection, probe-order early exit, error-gate fallback, no-gain and
monotonicity defects are pinned on fixtures, and the banked
``mixed_policy.json`` headline (alexnet >= 15% modeled drop under the
error gate) is asserted against the committed artifact.
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.analysis import numcheck as nc
from sparknet_tpu.analysis.num_model import (
    ACT_SEARCH_POLICIES,
    MIXED_DROP_FLOOR,
    accum_dtype,
    act_monotonicity_violations,
    census_problems,
    error_gate,
    is_narrow_float,
    mixed_saved_bytes,
    normalize_dtype,
    selected_act_policy,
    summarize_census,
)
from sparknet_tpu.analysis.numcheck import (
    NUM_RULES,
    run_mixed_search,
    run_numcheck,
    sources_fingerprint,
)

pytestmark = pytest.mark.smoke

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F32_META = {"dtype": "f32"}
STORAGE_META = {"dtype": "f32", "act": "blocks"}
BF16_META = {"dtype": "bf16"}


def _census(matmuls=(), reduces=(), casts=(), loss="f32"):
    return {"matmuls": list(matmuls), "reduces": list(reduces),
            "casts": list(casts), "loss_dtype": loss}


# -- the dtype model --------------------------------------------------------


def test_normalize_and_narrow():
    assert normalize_dtype("float32") == "f32"
    assert normalize_dtype("bfloat16") == "bf16"
    assert normalize_dtype("weird") == "weird"
    assert is_narrow_float("bf16") and is_narrow_float("float16")
    assert not is_narrow_float("f32") and not is_narrow_float("s32")


def test_accum_dtype_prefers_the_explicit_pin():
    assert accum_dtype({"out": "bf16", "preferred": "f32"}) == "f32"
    assert accum_dtype({"out": "bf16", "preferred": None}) == "bf16"


# -- defect fixtures: exactly one finding each ------------------------------


def test_bf16_accumulating_dot_is_exactly_one_finding():
    # the seeded defect of ISSUE 20's acceptance: one dot pinning an
    # explicit bf16 accumulator among otherwise-clean ops
    census = _census(
        matmuls=[
            {"op": "dot_general", "operands": ["f32", "f32"],
             "out": "f32", "preferred": None},
            {"op": "dot_general", "operands": ["bf16", "bf16"],
             "out": "bf16", "preferred": "bf16"},
        ],
        reduces=[{"op": "reduce_sum", "operand": "f32", "out": "f32"}],
    )
    problems = census_problems(census, F32_META)
    assert len(problems) == 1
    assert problems[0]["rule"] == "num-accum-dtype"
    assert "matmul #1" in problems[0]["message"]


def test_f32_to_bf16_downcast_ahead_of_loss_is_exactly_one_finding():
    # the second seeded defect: a smuggled downcast in a mode with no
    # bf16 arm configured
    census = _census(
        casts=[
            {"src": "s32", "dst": "f32", "roundtrip": False},
            {"src": "f32", "dst": "bf16", "roundtrip": False},
        ],
    )
    problems = census_problems(census, F32_META)
    assert len(problems) == 1
    assert problems[0]["rule"] == "num-cast-downcast"
    assert "cast #1" in problems[0]["message"]


def test_downcast_is_licensed_by_a_configured_arm():
    census = _census(
        casts=[{"src": "f32", "dst": "bf16", "roundtrip": False}])
    assert not census_problems(census, STORAGE_META)
    assert not census_problems(census, BF16_META)


def test_roundtrip_is_flagged_in_every_config():
    census = _census(
        casts=[{"src": "f32", "dst": "bf16", "roundtrip": True}])
    for meta in (F32_META, STORAGE_META, BF16_META):
        rules = [p["rule"] for p in census_problems(census, meta)]
        assert "num-cast-roundtrip" in rules, meta


def test_storage_config_narrow_operand_is_a_missed_upcast():
    census = _census(
        matmuls=[{"op": "conv_general_dilated",
                  "operands": ["bf16", "f32"], "out": "f32",
                  "preferred": "f32"}])
    # under bf16 STORAGE the layer entry must have upcast first
    problems = census_problems(census, STORAGE_META)
    assert [p["rule"] for p in problems] == ["num-accum-dtype"]
    # plain f32 mode: a narrow operand without storage config is not
    # this rule's business (accumulation is f32)
    assert not census_problems(census, F32_META)


def test_storage_config_narrow_sum_reduce():
    census = _census(
        reduces=[
            {"op": "reduce_sum", "operand": "bf16", "out": "bf16"},
            {"op": "reduce_max", "operand": "bf16", "out": "bf16"},
        ])
    problems = census_problems(census, STORAGE_META)
    # max reductions are rounding-free: only the sum is flagged
    assert [p["rule"] for p in problems] == ["num-reduce-dtype"]
    assert not census_problems(census, F32_META)


def test_narrow_compute_mode_accumulates_narrow_by_design():
    # dp_bf16's backward dots pin preferred=bf16 — the MXU-rate trade
    # the mode exists to make; counts are drift-pinned, not flagged
    census = _census(
        matmuls=[{"op": "dot_general", "operands": ["bf16", "bf16"],
                  "out": "bf16", "preferred": "bf16"}])
    assert not census_problems(census, BF16_META)


def test_loss_must_be_f32_in_every_config():
    census = _census(loss="bf16")
    for meta in (F32_META, STORAGE_META, BF16_META):
        rules = [p["rule"] for p in census_problems(census, meta)]
        assert rules == ["num-f32-pin"], meta
    # forward-only programs (loss None) are exempt
    assert not census_problems(_census(loss=None), F32_META)


def test_summarize_census_counts():
    census = _census(
        matmuls=[
            {"op": "dot_general", "operands": ["f32", "f32"],
             "out": "f32", "preferred": None},
            {"op": "dot_general", "operands": ["bf16", "bf16"],
             "out": "bf16", "preferred": "bf16"},
        ],
        reduces=[
            {"op": "reduce_sum", "operand": "bf16", "out": "bf16"},
            {"op": "reduce_max", "operand": "f32", "out": "f32"},
        ],
        casts=[
            {"src": "f32", "dst": "bf16", "roundtrip": False},
            {"src": "bf16", "dst": "f32", "roundtrip": False},
            {"src": "f32", "dst": "bf16", "roundtrip": True},
        ])
    s = summarize_census(census)
    assert s["matmul"] == {"total": 2, "by_accum": {"f32": 1, "bf16": 1},
                           "narrow_accum": 1, "narrow_operand": 1}
    assert s["reduce"] == {"sum_total": 1, "sum_narrow_operand": 1,
                           "other_total": 1}
    assert s["cast"]["pairs"] == {"f32->bf16": 2, "bf16->f32": 1}
    assert s["cast"]["roundtrips"] == 1
    assert s["cast"]["float_downcasts"] == 2
    assert s["loss_dtype"] == "f32"


# -- the jaxpr walk on real programs ----------------------------------------


def test_walk_records_dot_reduce_and_casts():
    def f(x, w):
        y = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jnp.sum(y)

    closed = jax.make_jaxpr(f)(
        jnp.zeros((4, 8), jnp.bfloat16), jnp.zeros((8, 2), jnp.bfloat16))
    census = nc._census_of(closed)
    assert len(census["matmuls"]) == 1
    rec = census["matmuls"][0]
    assert rec["operands"] == ["bf16", "bf16"]
    assert rec["preferred"] == "f32"
    assert accum_dtype(rec) == "f32"
    assert any(r["op"] == "reduce_sum" for r in census["reduces"])
    assert census["loss_dtype"] == "f32"


def test_walk_detects_the_compute_free_roundtrip():
    def bad(x):
        return x.astype(jnp.float32).astype(jnp.bfloat16)

    census = nc._census_of(
        jax.make_jaxpr(bad)(jnp.zeros((4,), jnp.bfloat16)))
    assert sum(1 for c in census["casts"] if c["roundtrip"]) == 1

    def good(x):
        # compute between the casts: the f32 hop buys real precision
        y = x.astype(jnp.float32)
        return (y * y).astype(jnp.bfloat16)

    census = nc._census_of(
        jax.make_jaxpr(good)(jnp.zeros((4,), jnp.bfloat16)))
    assert not any(c["roundtrip"] for c in census["casts"])


def test_walk_recurses_into_sub_jaxprs():
    def f(x):
        def body(c, _):
            return c @ c, jnp.sum(c).astype(jnp.bfloat16)

        _, ys = jax.lax.scan(body, x, None, length=2)
        return ys

    census = nc._census_of(jax.make_jaxpr(f)(jnp.zeros((3, 3))))
    assert census["matmuls"], "dot inside scan body must be censused"
    assert any(normalize_dtype(c["dst"]) == "bf16"
               for c in census["casts"])


# -- the off path is the identity -------------------------------------------


def test_activation_dtype_off_is_the_identity_path():
    from sparknet_tpu.analysis.memcheck import _family_net
    from sparknet_tpu.common import Phase, get_config, set_config
    from sparknet_tpu.compiler.graph import NetVars, Network

    net_param, _ = _family_net("cifar10_quick", 2)
    net = Network(net_param, Phase.TRAIN)
    variables = net.init(jnp.zeros((2,), jnp.uint32))
    feeds = {n: jnp.zeros(s, jnp.int32 if n == "label" else jnp.float32)
             for n, s in net.feed_shapes().items()}
    rng = jnp.zeros((2,), jnp.uint32)

    def trace(policy):
        prior = get_config().activation_dtype
        set_config(activation_dtype=policy)
        try:
            return str(jax.make_jaxpr(
                lambda p: net.apply(
                    NetVars(params=p, state=variables.state), feeds,
                    rng, train=True)[2])(variables.params))
        finally:
            set_config(activation_dtype=prior)

    base = trace("")
    assert get_config().activation_dtype == ""  # off by default
    assert trace("") == base  # idempotent
    full = trace("full")
    assert full != base
    assert full.count("bfloat16") > base.count("bfloat16")


def test_config_activation_dtype_validates_and_aliases():
    from sparknet_tpu.common import (
        act_storage_policy,
        get_config,
        set_config,
    )

    prior = get_config().activation_dtype
    try:
        set_config(activation_dtype="bf16")  # alias -> "blocks"
        assert get_config().activation_dtype == "blocks"
        set_config(activation_dtype="none")
        assert get_config().activation_dtype == ""
        with pytest.raises(ValueError):
            set_config(activation_dtype="f8")
        # the normalizing read guard: an unvalidated env seed cannot
        # half-apply at the trace site
        assert act_storage_policy("bfloat16") == "blocks"
        with pytest.raises(ValueError):
            act_storage_policy("garbage")
    finally:
        set_config(activation_dtype=prior)


# -- mixed-policy arithmetic ------------------------------------------------


def test_mixed_saved_bytes_hand_computation():
    assert mixed_saved_bytes(1000, 400, 200, "none") == 1000
    assert mixed_saved_bytes(1000, 400, 200, "full") == 500
    assert mixed_saved_bytes(1000, 400, 200, "io") == 900
    assert mixed_saved_bytes(1000, 400, 200, "blocks") == 800
    # partial discounts clamp at the full floor
    assert mixed_saved_bytes(1000, 5000, 200, "blocks") == 500
    with pytest.raises(ValueError):
        mixed_saved_bytes(1000, 0, 0, "nope")


def test_act_monotonicity():
    good = {"none": 100, "io": 90, "blocks": 80, "full": 50}
    assert not act_monotonicity_violations(good)
    bad = dict(good, full=95)
    assert ("io", "full") in act_monotonicity_violations(bad)
    assert ("blocks", "full") in act_monotonicity_violations(bad)


def test_selected_act_policy_reader():
    table = {"selected": {"alexnet": {"bf16": {"policy": "io"}}}}
    assert selected_act_policy(table, "alexnet") == "io"
    assert selected_act_policy(table, "vgg16") == "blocks"
    assert selected_act_policy({}, "alexnet", default="full") == "full"
    corrupt = {"selected": {"alexnet": {"bf16": {"policy": "nope"}}}}
    assert selected_act_policy(corrupt, "alexnet") == "blocks"


FIXED_CENSUS = {
    "saved_bytes": 1_000_000, "boundary_bytes": 400_000,
    "float_feed_bytes": 200_000, "params_bytes": 50_000,
    "state_bytes": 0, "slots_bytes": 50_000, "feed_bytes": 60_000,
}


def _search(tmp_path, monkeypatch, census=FIXED_CENSUS, probe=0.001,
            families=("alexnet",), update=False):
    calls = []

    def fake_probe(family, policy, batch=2):
        calls.append(policy)
        return probe if not callable(probe) else probe(policy)

    monkeypatch.setattr(nc, "_family_mixed_census",
                        lambda family, batch: dict(census))
    monkeypatch.setattr(nc, "_error_probe", fake_probe)
    findings, table = run_mixed_search(
        update=update, banked_path=str(tmp_path / "mixed_policy.json"),
        families=list(families))
    return findings, table, calls


def test_mixed_search_selects_bytes_minimal_safe_policy(
        tmp_path, monkeypatch):
    findings, table, calls = _search(tmp_path, monkeypatch)
    sel = table["selected"]["alexnet"]["bf16"]
    assert sel["policy"] == "full"
    assert sel["drop_frac_vs_f32"] > MIXED_DROP_FLOOR
    # ascending-bytes probe order stops at the first safe policy:
    # "full" models the fewest bytes, passes, nothing else is probed
    assert calls == ["full"]
    assert not [f for f in findings if f.rule != "num-manifest-missing"]


def test_mixed_search_error_gate_falls_back_to_none(tmp_path, monkeypatch):
    findings, table, calls = _search(tmp_path, monkeypatch, probe=0.9)
    sel = table["selected"]["alexnet"]["bf16"]
    assert sel["policy"] == "none"
    assert sel["drop_frac_vs_f32"] == 0.0
    # every storage policy was probed (and failed) before the fallback
    assert set(calls) == {"io", "blocks", "full"}
    # "none" on the headline family means no gain: the defect fires
    assert "num-mixed-no-gain" in [f.rule for f in findings]


def test_mixed_search_no_gain_defect_fixture(tmp_path, monkeypatch):
    # saved activations are a rounding error next to params: even the
    # "full" winner cannot clear the headline drop floor
    census = dict(FIXED_CENSUS, saved_bytes=10, boundary_bytes=4,
                  float_feed_bytes=2, params_bytes=10_000_000)
    findings, table, _ = _search(tmp_path, monkeypatch, census=census)
    assert table["selected"]["alexnet"]["bf16"]["policy"] == "full"
    assert [f.rule for f in findings
            if f.rule == "num-mixed-no-gain"] == ["num-mixed-no-gain"]


def test_mixed_search_nonmonotonic_defect_fixture(tmp_path, monkeypatch):
    def doctored(saved, boundary, feed, policy):
        return {"none": 100, "io": 90, "blocks": 80, "full": 95}[policy]

    monkeypatch.setattr(nc, "mixed_saved_bytes", doctored)
    findings, _, _ = _search(tmp_path, monkeypatch)
    assert "num-mixed-nonmonotonic" in [f.rule for f in findings]


def test_mixed_search_non_headline_family_skips_the_drop_gate(
        tmp_path, monkeypatch):
    census = dict(FIXED_CENSUS, saved_bytes=10, boundary_bytes=4,
                  float_feed_bytes=2, params_bytes=10_000_000)
    findings, _, _ = _search(tmp_path, monkeypatch, census=census,
                             families=("vgg16",))
    assert "num-mixed-no-gain" not in [f.rule for f in findings]


def test_mixed_search_banks_and_rereads(tmp_path, monkeypatch):
    _search(tmp_path, monkeypatch, update=True)
    banked = json.loads((tmp_path / "mixed_policy.json").read_text())
    assert banked["selected"]["alexnet"]["bf16"]["policy"] == "full"
    assert banked["policies"] == list(ACT_SEARCH_POLICIES)
    assert selected_act_policy(banked, "alexnet") == "full"
    # a second non-update run diffs clean against the bank
    findings, _, _ = _search(tmp_path, monkeypatch)
    assert not [f for f in findings if not f.suppressed]


# -- manifest loop ----------------------------------------------------------


def test_manifest_bank_diff_and_allow(tmp_path, monkeypatch):
    findings, _ = run_numcheck(["moe"], banked_dir=str(tmp_path))
    assert [f.rule for f in findings] == ["num-manifest-missing"]

    findings, manifests = run_numcheck(["moe"], banked_dir=str(tmp_path),
                                       update=True)
    assert not findings
    mpath = tmp_path / "moe.json"
    assert mpath.exists()
    # no SOURCES.json on a partial or non-default-dir run
    assert not (tmp_path / "SOURCES.json").exists()

    # clean re-run diffs empty
    findings, _ = run_numcheck(["moe"], banked_dir=str(tmp_path))
    assert not findings

    # doctor the banked contract -> drift; allow-map suppresses it
    banked = json.loads(mpath.read_text())
    banked["contract"]["matmul"]["total"] += 1
    mpath.write_text(json.dumps(banked))
    findings, _ = run_numcheck(["moe"], banked_dir=str(tmp_path))
    assert [f.rule for f in findings] == ["num-manifest-drift"]
    assert not findings[0].suppressed
    banked["allow"] = {"num-manifest-drift": "fixture"}
    mpath.write_text(json.dumps(banked))
    findings, _ = run_numcheck(["moe"], banked_dir=str(tmp_path))
    assert [f.rule for f in findings] == ["num-manifest-drift"]
    assert findings[0].suppressed


def test_unknown_mode_raises_keyerror():
    with pytest.raises(KeyError):
        run_numcheck(["no-such-mode"])


# -- the banked artifacts (the committed contract) --------------------------


def test_banked_manifests_cover_every_mode():
    from sparknet_tpu.parallel.modes import list_modes

    cdir = os.path.join(ROOT, "docs", "num_contracts")
    for mode in list_modes():
        assert os.path.exists(os.path.join(cdir, f"{mode}.json")), mode
    assert os.path.exists(os.path.join(cdir, "SOURCES.json"))


def test_banked_mixed_policy_headline_acceptance():
    # ISSUE 20 acceptance: the alexnet/bf16 winner drops modeled step
    # bytes >= 15% vs the f32-activation baseline AND passes the
    # error-probe gate
    path = os.path.join(ROOT, "docs", "num_contracts",
                        "mixed_policy.json")
    table = json.loads(open(path, encoding="utf-8").read())
    sel = table["selected"]["alexnet"]["bf16"]
    assert sel["policy"] in ACT_SEARCH_POLICIES and sel["policy"] != "none"
    assert sel["drop_frac_vs_f32"] >= MIXED_DROP_FLOOR
    assert sel["probe_error"] <= sel["error_gate"] == error_gate("alexnet")


def test_banked_act_policy_reader_routes_the_table():
    from sparknet_tpu.parallel.modes import _banked_act_policy

    path = os.path.join(ROOT, "docs", "num_contracts",
                        "mixed_policy.json")
    table = json.loads(open(path, encoding="utf-8").read())
    assert _banked_act_policy("alexnet") == \
        table["selected"]["alexnet"]["bf16"]["policy"]


def test_act_twins_are_registered_with_the_banked_policy():
    from sparknet_tpu.parallel.modes import build_target, list_modes

    assert "solo_act_bf16" in list_modes()
    assert "dp_act_bf16" in list_modes()
    target = build_target("solo_act_bf16")
    assert target.meta["act"] in ("io", "blocks", "full")
    assert target.meta["dtype"] == "f32"


# -- fingerprints + rule surface --------------------------------------------


def test_sources_fingerprint_covers_the_contract_surface():
    fp = sources_fingerprint()
    assert "sparknet_tpu/analysis/numcheck.py" in fp
    assert "sparknet_tpu/analysis/num_model.py" in fp
    assert "sparknet_tpu/common.py" in fp
    assert "sparknet_tpu/compiler/graph.py" in fp
    for rel, digest in fp.items():
        assert os.path.exists(os.path.join(ROOT, rel)), rel
        assert len(digest) == 64


def test_rule_catalog():
    assert set(dict(nc.iter_rules())) == set(NUM_RULES)
    expected = {
        "num-accum-dtype", "num-reduce-dtype", "num-f32-pin",
        "num-cast-roundtrip", "num-cast-downcast", "num-mixed-no-gain",
        "num-mixed-nonmonotonic", "num-manifest-missing",
        "num-manifest-drift",
    }
    assert set(NUM_RULES) == expected


# -- CLI --------------------------------------------------------------------


def test_cli_num_json_schema(tmp_path, capsys, monkeypatch):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    monkeypatch.setattr(nc, "MANIFEST_DIR", str(tmp_path))
    rc = cli_main(["num", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # nothing banked yet
    assert out["findings"][0]["rule"] == "num-manifest-missing"

    rc = cli_main(["num", "--mode", "moe", "--update"])
    assert rc == 0
    capsys.readouterr()
    rc = cli_main(["num", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["unsuppressed"] == 0


def test_cli_num_unknown_mode_is_usage_error(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["num", "--mode", "no-such-mode"]) == 2


def test_cli_num_list_rules(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["num", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in NUM_RULES:
        assert rule_id in out


# -- `analysis all` (the meta-subcommand) -----------------------------------


def test_all_engines_lists_all_six():
    import sparknet_tpu.analysis.__main__ as am

    labels = [label for label, _ in am._all_engines()]
    assert labels == ["graftlint", "conccheck", "graphcheck",
                      "memcheck", "bytecheck", "numcheck"]


def test_cli_all_merges_and_exits_once(capsys, monkeypatch):
    import sparknet_tpu.analysis.__main__ as am
    from sparknet_tpu.analysis.core import Finding

    hit = Finding("stub-rule", "x.py", 1, "a stub finding")
    ok = Finding("stub-ok", "y.py", 2, "suppressed", suppressed=True)
    monkeypatch.setattr(am, "_all_engines", lambda: [
        ("alpha", lambda: [hit]),
        ("beta", lambda: [ok]),
    ])
    rc = am.main(["all", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["unsuppressed"] == 1 and out["suppressed"] == 1
    assert {f["rule"] for f in out["findings"]} == {"stub-rule", "stub-ok"}

    monkeypatch.setattr(am, "_all_engines",
                        lambda: [("alpha", lambda: [ok])])
    assert am.main(["all", "--json"]) == 0
    capsys.readouterr()


def test_cli_all_engine_crash_is_not_masked(capsys, monkeypatch):
    import sparknet_tpu.analysis.__main__ as am

    def boom():
        raise RuntimeError("engine exploded")

    monkeypatch.setattr(am, "_all_engines", lambda: [
        ("alpha", boom),
        ("beta", lambda: []),
    ])
    rc = am.main(["all"])
    assert rc == 1
    assert "CRASHED" in capsys.readouterr().err
