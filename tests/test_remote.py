"""Object-store abstraction for remote shard ingest.

The reference streams tar shards from S3 (ref:
src/main/scala/loaders/ImageNetLoader.scala:25-86); here the store
interface is exercised with the local filesystem as both the file://
backend and an on-disk fake for a remote scheme, including the lazy
fetch-to-cache path ImageNetLoader uses for gs://-style roots.
"""

import io
import os
import tarfile

import numpy as np
import pytest

from sparknet_tpu.data.archive import ImageNetLoader
from sparknet_tpu.data.remote import (
    CliStore,
    LocalStore,
    get_store,
    register_store,
)


def test_local_store_list_and_fetch(tmp_path):
    (tmp_path / "a.tar").write_bytes(b"A")
    (tmp_path / "b.tar").write_bytes(b"BB")
    (tmp_path / "sub").mkdir()
    store = LocalStore()
    urls = store.list_prefix(str(tmp_path))
    assert [os.path.basename(u) for u in urls] == ["a.tar", "b.tar"]
    # prefix (non-directory) listing filters by basename
    urls = store.list_prefix(str(tmp_path / "a"))
    assert [os.path.basename(u) for u in urls] == ["a.tar"]

    cache = tmp_path / "cache"
    dest = store.fetch(str(tmp_path / "b.tar"), str(cache))
    assert open(dest, "rb").read() == b"BB"
    # idempotent re-fetch reuses the cached copy
    before = os.path.getmtime(dest)
    assert store.fetch(str(tmp_path / "b.tar"), str(cache)) == dest
    assert os.path.getmtime(dest) == before


def test_get_store_schemes(tmp_path):
    assert isinstance(get_store("file:///x"), LocalStore)
    assert isinstance(get_store(str(tmp_path)), LocalStore)
    assert isinstance(get_store("gs://bucket/p"), CliStore)
    assert isinstance(get_store("s3://bucket/p"), CliStore)
    with pytest.raises(ValueError, match="no object store"):
        get_store("ftp://host/p")


def test_cli_store_absent_tool_is_loud(monkeypatch):
    monkeypatch.setattr("shutil.which", lambda _: None)
    with pytest.raises(RuntimeError, match="gsutil not found"):
        CliStore("gs").list_prefix("gs://bucket/prefix")


def test_cli_store_s3_ls_parse(monkeypatch):
    """`aws s3 ls` rows: skip PRE sub-prefixes, keep keys with spaces."""
    store = CliStore("s3")
    monkeypatch.setattr(store, "_run", lambda argv: (
        "                           PRE nested/\n"
        "2023-01-01 12:00:00     1234 s0.tar\n"
        "2023-01-01 12:00:01     1234 train set/s1.tar\n"
    ))
    assert store.list_prefix("s3://bucket/shards/") == [
        "s3://bucket/shards/s0.tar",
        "s3://bucket/shards/train set/s1.tar",
    ]


def _make_shards(root, n_shards=2, per=3):
    labels = {}
    os.makedirs(root, exist_ok=True)
    for shard in range(n_shards):
        with tarfile.open(os.path.join(root, f"s{shard}.tar"), "w") as tf:
            for i in range(per):
                name = f"f_{shard}_{i}.jpg"
                data = bytes([shard, i]) * 4
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                labels[name] = shard * per + i
    return labels


def test_imagenet_loader_remote_scheme_with_fake(tmp_path, monkeypatch):
    """A registered fake store plays the S3 role: the loader lists the
    prefix, lazily fetches each shard into cache_dir, and streams the
    same (bytes, label) partition a local root would."""
    bucket = tmp_path / "bucket"
    labels = _make_shards(str(bucket))
    label_file = tmp_path / "train.txt"
    label_file.write_text("".join(f"{n} {l}\n" for n, l in labels.items()))

    fetched = []

    class FakeStore(LocalStore):
        def list_prefix(self, url):
            return super().list_prefix(url.replace("mock://", str(tmp_path) + "/"))

        def fetch(self, url, dest_dir):
            fetched.append(url)
            return super().fetch(url, dest_dir)

    register_store("mock", FakeStore)
    cache = tmp_path / "cache"
    loader = ImageNetLoader("mock://bucket", str(label_file),
                            cache_dir=str(cache))
    assert len(loader) == 2
    s0 = list(loader.shard(0, 2))
    assert {l for _, l in s0} == {0, 1, 2}
    # only worker 0's shard was fetched (lazy, per-slice)
    assert len(fetched) == 1
    assert os.path.exists(cache / "s0.tar")


def test_imagenet_loader_remote_requires_cache_dir(tmp_path):
    with pytest.raises(ValueError, match="cache_dir"):
        ImageNetLoader("gs://bucket/shards", str(tmp_path / "nope.txt"))
