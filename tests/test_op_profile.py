"""Profiler-trace aggregation (tpunet time --trace plumbing).

Device-op lanes only exist on accelerator backends, so the parsing and
layer-attribution logic is pinned here against a synthetic Chrome trace
shaped like a real TPU export (process_name metadata + X events with
L.<layer> scopes in long_name); the live path is exercised for its
graceful no-device-lane fallback on CPU.
"""

import gzip
import json
import os

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sparknet_tpu.utils.op_profile import (
    _device_events,
    aggregate_by_layer,
    layer_time_table,
)


def _write_trace(tmp_path, events, pname="/device:TPU:0"):
    d = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(d, exist_ok=True)
    raw = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": pname}},
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
    ]
    for name, scope, dur, pid in events:
        raw.append({
            "ph": "X", "pid": pid, "tid": 0, "ts": 0, "dur": dur,
            "name": name, "args": {"long_name": scope},
        })
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": raw}, f)
    return str(tmp_path)


def test_device_events_filters_host_lane(tmp_path):
    root = _write_trace(tmp_path, [
        ("fusion.1", "jit(step)/L.conv1/conv", 100.0, 7),
        ("python_call", "", 999.0, 1),  # host lane: excluded
    ])
    events = _device_events(root)
    assert len(events) == 1
    assert events[0][1] == 100.0


def test_aggregate_by_layer_scopes_and_other(tmp_path):
    root = _write_trace(tmp_path, [
        ("fusion.1", "jit(step)/L.conv1/conv_general", 100.0, 7),
        ("fusion.2", "jit(step)/L.conv1/add", 50.0, 7),
        ("fusion.3", "jit(step)/L.ip1/dot_general", 30.0, 7),
        ("copy.4", "", 20.0, 7),  # unscoped: optimizer/copies
    ])
    per_layer, total = aggregate_by_layer(_device_events(root), iters=2)
    assert per_layer["conv1"] == 75.0  # (100+50)/2
    assert per_layer["ip1"] == 15.0
    assert per_layer["(other)"] == 10.0
    assert total == 100.0


def test_aggregate_googlenet_style_names(tmp_path):
    # compiler flattens '/' in layer names to '.' before named_scope
    root = _write_trace(tmp_path, [
        ("fusion.9", "jit(x)/L.inception_3a.1x1/conv", 40.0, 7),
    ])
    per_layer, _ = aggregate_by_layer(_device_events(root), iters=1)
    assert per_layer == {"inception_3a.1x1": 40.0}


def test_aggregate_fwd_bwd_split(tmp_path):
    """Backward ops carry transpose(jvp(L.<name>)) in the HLO scope path
    (verified against jax lowering); forward ops plain or jvp-wrapped —
    the caffe time Forward/Backward per-layer split (caffe.cpp:290-380)."""
    from sparknet_tpu.utils.op_profile import aggregate_fwd_bwd

    root = _write_trace(tmp_path, [
        ("fusion.1", "jit(step)/jvp(L.conv1)/conv_general", 100.0, 7),
        ("fusion.2", "jit(step)/transpose(jvp(L.conv1))/conv_general",
         200.0, 7),
        ("fusion.3", "jit(step)/L.ip1/dot_general", 30.0, 7),  # eval-style
        ("copy.4", "", 20.0, 7),
    ])
    split = aggregate_fwd_bwd(_device_events(root), iters=2)
    assert split["conv1"] == (50.0, 100.0)
    assert split["ip1"] == (15.0, 0.0)
    assert split["(other)"] == (10.0, 0.0)


def test_table_from_trace_fwd_bwd_rows(tmp_path):
    from sparknet_tpu.utils.op_profile import table_from_trace

    root = _write_trace(tmp_path, [
        ("f1", "jit(s)/jvp(L.conv1)/conv", 40.0, 7),
        ("f2", "jit(s)/transpose(jvp(L.conv1))/conv", 80.0, 7),
    ])
    prof = {"events": _device_events(root), "wall_step_us": 130.0,
            "trace_dir": str(tmp_path)}
    t = table_from_trace(prof, ["conv1"], iters=1)
    assert t["rows"] == [("conv1", 120.0)]
    assert t["rows_fwd_bwd"] == [("conv1", 40.0, 80.0)]


def test_layer_time_table_cpu_fallback():
    """On CPU the trace has no device lanes: empty rows, measured wall
    time still reported, nothing raises."""
    import jax

    f = jax.jit(lambda x: (x @ x).sum())
    x = np.eye(64, dtype=np.float32)
    table = layer_time_table(f, (x,), ["a", "b"], iters=2)
    assert table["wall_us_per_step"] > 0
    assert table["rows"] == [] or all(
        isinstance(n, str) for n, _ in table["rows"]
    )


def test_trace_report_renders_rows(tmp_path):
    """tools/trace_report.py renders full and partial artifacts (partial =
    the wedge-mid-trace case the staged banking exists for)."""
    import json
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_report.py")
    art = {
        "stage": "final", "argv_solver": "zoo:alexnet", "batch": 256,
        "dtype": "bf16", "utc": "t", "device_kind": "v5e",
        "wall_ms_per_step": 20.0, "img_per_sec": 12800.0,
        "gflop_per_step": 986.0, "hbm_gb_per_step": 12.3,
        "mfu": 0.25, "mfu_vs_peak": "v5e_bf16",
        "rows": [["conv1", 2000.0], ["norm1", 5000.0], ["(other)", 1000.0]],
        "rows_fwd_bwd": {"conv1": [800.0, 1200.0], "norm1": [2000.0, 3000.0]},
        "device_us_per_step": 8000.0, "attributed_frac": 0.875,
    }
    # the live table_from_trace payload serializes triples
    # [name, fwd, bwd] (see test_table_from_trace_fwd_bwd_rows); the
    # report also accepts the dict / (name, (f, b)) shapes
    triples = [[k, f, b] for k, (f, b) in art["rows_fwd_bwd"].items()]
    for fb in (art["rows_fwd_bwd"], triples,
               [[k, [f, b]] for k, f, b in triples]):
        art["rows_fwd_bwd"] = fb
        p = tmp_path / "a.json"
        p.write_text(json.dumps(art))
        out = subprocess.run(
            [sys.executable, tool, str(p)],
            capture_output=True, text=True, check=True).stdout
        assert "| norm1 | 2.000 | 3.000 | 5.000 | 62.5% |" in out
        assert "TOTAL (device)" in out and "87.5%" in out

    partial = {"stage": "wall_untraced", "argv_solver": "zoo:alexnet",
               "batch": 256, "dtype": "bf16",
               "wall_ms_per_step_untraced": 20.5,
               "img_per_sec_untraced": 12500.0,
               "gflop_per_step": 986.0, "hbm_gb_per_step": 12.3,
               "fence_protocol": "loss-value+threaded-args"}
    p = tmp_path / "b.json"
    p.write_text(json.dumps(partial))
    out = subprocess.run(
        [sys.executable, tool, str(p)],
        capture_output=True, text=True, check=True).stdout
    assert "No per-layer rows banked" in out and "20.500 ms" in out

    # an UNSTAMPED untraced wall (pre-round-5 artifact) is refused with
    # an explanatory note, not silently rendered — the unstamped fence
    # banked physically impossible walls (VERDICT r4 §weak 1)
    del partial["fence_protocol"]
    p = tmp_path / "c.json"
    p.write_text(json.dumps(partial))
    out = subprocess.run(
        [sys.executable, tool, str(p)],
        capture_output=True, text=True, check=True).stdout
    assert "20.500 ms" not in out
    assert "no `fence_protocol` stamp" in out


def _write_tpu_style_trace(tmp_path, lanes, ops):
    """TPU xprof export shape: ONE device pid with stacked named lanes
    (Steps / XLA Modules / XLA Ops), scopes in args.tf_op, args.long_name
    carrying raw HLO text (no scopes)."""
    d = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(d, exist_ok=True)
    raw = [{"ph": "M", "name": "process_name", "pid": 3,
            "args": {"name": "/device:TPU:0"}}]
    for tid, lname in lanes.items():
        raw.append({"ph": "M", "name": "thread_name", "pid": 3,
                    "tid": tid, "args": {"name": lname}})
    for tid, name, tf_op, dur in ops:
        raw.append({
            "ph": "X", "pid": 3, "tid": tid, "ts": 0, "dur": dur,
            "name": name,
            "args": {"tf_op": tf_op,
                     "long_name": "%fusion.1 = f32[8,8]{1,0:T(8,128)}"},
        })
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": raw}, f)
    return str(tmp_path)


def test_tpu_stacked_lanes_counted_once(tmp_path):
    """The probe-40 regression: Steps + XLA Modules + XLA Ops lanes each
    carry the full step interval; only the op lane may be summed (the
    artifact shipped 80.5 ms 'device total' for a 26.8 ms step), and the
    L.<layer> scope lives in tf_op, not long_name (raw HLO on TPU)."""
    root = _write_tpu_style_trace(
        tmp_path,
        lanes={1: "Steps", 2: "XLA Modules", 3: "XLA Ops",
               4: "Async XLA Ops"},
        ops=[
            (1, "0", "", 1000.0),               # step marker
            (2, "jit_step(123)", "", 1000.0),   # module marker
            (3, "fusion.7", "jit(step)/jvp(L.conv1)/conv_general_dilated:", 600.0),
            (3, "fusion.9", "jit(step)/transpose(jvp(L.conv1))/mul:", 300.0),
            (3, "copy.1", "", 100.0),
            (4, "async-copy", "", 500.0),       # async lane: excluded
        ])
    per_layer, total = aggregate_by_layer(_device_events(root), iters=1)
    assert total == 1000.0  # op lane only — no triple count
    assert per_layer["conv1"] == 900.0
    assert per_layer["(other)"] == 100.0


def test_named_lanes_without_ops_name_pick_busiest(tmp_path):
    """An export whose op lane is named unrecognizably must not fall
    back to summing every stacked lane: the busiest lane wins."""
    root = _write_tpu_style_trace(
        tmp_path,
        lanes={1: "Steps", 2: "op timeline (v2)"},
        ops=[
            (1, "0", "", 1000.0),
            (2, "fusion.1", "jit(step)/L.fc/dot_general:", 700.0),
            (2, "fusion.2", "", 200.0),
            (2, "fusion.3", "", 100.0),
        ])
    per_layer, total = aggregate_by_layer(_device_events(root), iters=1)
    assert total == 1000.0  # busiest lane (3 events), not Steps + it
    assert per_layer["fc"] == 700.0


def test_gpu_style_stream_lanes_all_counted(tmp_path):
    """Concurrent named stream lanes under one device pid are DISTINCT
    real work (the GPU export shape), not stacked views — every stream
    must be summed, with only aggregate lanes (Steps/Modules) excluded."""
    root = _write_tpu_style_trace(
        tmp_path,
        lanes={1: "Steps", 14: "Stream #14(compute)",
               15: "Stream #15(memcpy)"},
        ops=[
            (1, "0", "", 1000.0),
            (14, "kern.1", "jit(step)/L.conv1/conv:", 600.0),
            (15, "memcpy.1", "", 250.0),
        ])
    per_layer, total = aggregate_by_layer(_device_events(root), iters=1)
    assert total == 850.0  # both streams, no Steps aggregate
    assert per_layer["conv1"] == 600.0
    assert per_layer["(other)"] == 250.0


def test_reparse_trace_rewrites_artifact(tmp_path):
    """tools/reparse_trace.py: a banked artifact whose per-layer rows
    came out wrong (the probe-40 parser bug) is re-derived offline from
    its raw trace dir — iters honored, wall fallback to the untraced
    stage, reparse provenance stamped."""
    import json as _json
    import subprocess
    import sys as _sys

    root = _write_tpu_style_trace(
        tmp_path,
        lanes={1: "Steps", 3: "XLA Ops"},
        ops=[
            (1, "0", "", 1000.0),
            (3, "fusion.1", "jit(step)/jvp(L.ip)/dot_general:", 800.0),
            (3, "fusion.2", "", 200.0),
        ])
    art = tmp_path / "trace.artifact.json"
    art.write_text(_json.dumps({
        "stage": "wall_timed",  # wedge-truncated: no final wall banked
        "iters": 2,
        "wall_ms_per_step_untraced": 0.6,
        "rows": [["(other)", 3000.0]],  # the triple-counted bad parse
        "attributed_frac": 0.0,
        "trace_dir": root,
    }))
    out = subprocess.run(
        [_sys.executable,
         os.path.join(ROOT, "tools", "reparse_trace.py"), str(art)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    a = _json.loads(art.read_text())
    rows = dict((n, us) for n, us in a["rows"])
    assert rows["ip"] == 400.0          # 800 us over iters=2
    assert a["device_us_per_step"] == 500.0  # op lane only, per step
    assert a["attributed_frac"] == 0.8
    assert a["reparse_note"]
