"""graftlint: fixture snippets per rule + the repo-wide self-lint gate.

Each rule gets three fixtures — a positive hit, the same hit suppressed
with a justified directive, and a clean rewrite — so the rule's
boundary is pinned from both sides.  ``test_repo_self_lint_is_clean``
is the CI wiring: it runs the analyzer over the repo's contract surface
(``sparknet_tpu/``, ``tools/``, ``bench.py``) and fails on any
unsuppressed finding, so future PRs cannot reintroduce unfenced timing
or unguarded evidence banking (the probe-40 / round-4 artifact class).

All smoke-marked: the analyzer is stdlib-AST only, no jax dispatch.
"""
# graftlint: disable-file=no-pkill-self -- PKILL_BAD/PKILL_GOOD are this rule's own fixture strings

import json
import os

import pytest

from sparknet_tpu.analysis import RULES, lint_paths, lint_source
from sparknet_tpu.analysis.__main__ import default_paths
from sparknet_tpu.analysis.__main__ import main as cli_main

pytestmark = pytest.mark.smoke

EXPECTED_RULES = {
    "fence-by-value",
    "no-env-platform",
    "bank-guard",
    "require-measured",
    "stale-args-dispatch",
    "no-pkill-self",
    "graph-manifest-fresh",
    "mem-manifest-fresh",
    "fused-update-manifest",
    "elastic-manifest-fresh",
    "serve-manifest-fresh",
    "loop-manifest-fresh",
    "replica-manifest-fresh",
    "paged-manifest-fresh",
    "queue-job-hygiene",
    "queue-policy-fields",
    "obs-fenced-span",
    "feed-shm-cleanup",
    "obs-vocab-coverage",
    "conc-manifest-fresh",
    "byte-manifest-fresh",
    "ctl-manifest-fresh",
    "num-manifest-fresh",
}


def hits(src, rule_id, path="snippet.py"):
    """Unsuppressed findings of one rule for a source fixture."""
    return [f for f in lint_source(src, path)
            if f.rule == rule_id and not f.suppressed]


def suppressed_hits(src, rule_id, path="snippet.py"):
    return [f for f in lint_source(src, path)
            if f.rule == rule_id and f.suppressed]


# -- registry ---------------------------------------------------------------


def test_rule_catalog_complete():
    assert set(RULES) == EXPECTED_RULES
    for info in RULES.values():
        assert info.summary, info.id


# -- fence-by-value ---------------------------------------------------------

FENCE_BAD = """
import time
import jax

def timed(step, x):
    out = step(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = step(out)
    jax.block_until_ready(out)
    return time.perf_counter() - t0
"""

FENCE_GOOD = """
import time
from sparknet_tpu.common import value_fence

def timed(step, x):
    out = step(x)
    value_fence(out)
    t0 = time.perf_counter()
    out = step(out)
    value_fence(out)
    return time.perf_counter() - t0
"""


def test_fence_by_value_positive():
    found = hits(FENCE_BAD, "fence-by-value")
    assert len(found) == 2
    assert "value_fence" in found[0].message


def test_fence_by_value_suppressed():
    src = FENCE_BAD.replace(
        "    jax.block_until_ready(out)",
        "    jax.block_until_ready(out)  "
        "# graftlint: disable=fence-by-value -- local-backend test rig")
    assert not hits(src, "fence-by-value")
    assert len(suppressed_hits(src, "fence-by-value")) == 2


def test_fence_by_value_clean():
    assert not hits(FENCE_GOOD, "fence-by-value")


def test_fence_outside_timing_window_is_fine():
    # readiness sync with no clock in scope is not a timing lie
    src = "import jax\ndef sync(x):\n    jax.block_until_ready(x)\n"
    assert not hits(src, "fence-by-value")


# -- no-env-platform --------------------------------------------------------

ENV_BAD = """
import os
import jax

os.environ["JAX_PLATFORMS"] = "cpu"
print(jax.devices())
"""

ENV_GOOD_PAIRED = """
import os
import jax

os.environ["JAX_PLATFORMS"] = "cpu"          # for subprocesses
jax.config.update("jax_platforms", "cpu")    # the route that wins
"""

ENV_GOOD_NO_JAX = """
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # consumed by a child's own contract
"""


def test_no_env_platform_positive():
    found = hits(ENV_BAD, "no-env-platform")
    assert len(found) == 1
    assert "site hook" in found[0].message


def test_no_env_platform_setdefault_positive():
    src = ENV_BAD.replace('os.environ["JAX_PLATFORMS"] = "cpu"',
                          'os.environ.setdefault("JAX_PLATFORMS", "cpu")')
    assert len(hits(src, "no-env-platform")) == 1


def test_no_env_platform_suppressed():
    src = ENV_BAD.replace(
        'os.environ["JAX_PLATFORMS"] = "cpu"',
        'os.environ["JAX_PLATFORMS"] = "cpu"  '
        "# graftlint: disable=no-env-platform -- child processes only")
    assert not hits(src, "no-env-platform")
    assert suppressed_hits(src, "no-env-platform")


def test_no_env_platform_clean_when_config_pinned():
    # the conftest.py / multihost_worker.py shape: env var AND config pin
    assert not hits(ENV_GOOD_PAIRED, "no-env-platform")


def test_no_env_platform_clean_without_jax():
    assert not hits(ENV_GOOD_NO_JAX, "no-env-platform")


# -- bank-guard -------------------------------------------------------------

BANK_BAD = """
import json
import os

def save(rec):
    path = "docs/int8_bench_last.json"
    with open(path + ".tmp", "w") as f:
        json.dump(rec, f)
    os.replace(path + ".tmp", path)
"""

BANK_GOOD = """
from sparknet_tpu.common import bank_guard

def save(rec, on_accel):
    bank_guard("docs/int8_bench_last.json", rec, measured=on_accel)
"""

BANK_MODULE_CONST = """
import json

PATH = "docs/bench_last_good.json"

def save(rec):
    with open(PATH, "w") as f:
        json.dump(rec, f)
"""


def test_bank_guard_positive():
    found = hits(BANK_BAD, "bank-guard")
    assert len(found) == 1
    assert "bank_guard" in found[0].message


def test_bank_guard_sees_module_level_path_constants():
    # the bench.py LAST_GOOD_PATH shape: string at module scope, write in
    # a function — module strings are ambient
    assert len(hits(BANK_MODULE_CONST, "bank-guard")) == 1


def test_bank_guard_suppressed():
    src = BANK_BAD.replace(
        'with open(path + ".tmp", "w") as f:',
        'with open(path + ".tmp", "w") as f:  '
        "# graftlint: disable=bank-guard -- offline re-attribution tool")
    assert not hits(src, "bank-guard")
    assert suppressed_hits(src, "bank-guard")


def test_bank_guard_clean_via_helper():
    assert not hits(BANK_GOOD, "bank-guard")


def test_bank_guard_read_is_fine():
    src = ('import json\n'
           'def load():\n'
           '    with open("docs/bench_last_good.json") as f:\n'
           '        return json.load(f)\n')
    assert not hits(src, "bank-guard")


def test_bank_guard_non_evidence_write_is_fine():
    src = ('import json\n'
           'def save(rec):\n'
           '    with open("docs/tau_sweep_alexnet.json", "w") as f:\n'
           '        json.dump(rec, f)\n')
    assert not hits(src, "bank-guard")


# -- require-measured -------------------------------------------------------

REQ_BAD = """
import json

def main():
    print(json.dumps({"metric": "x_img_s", "measured": False}))
    return 0

if __name__ == "__main__":
    main()
"""

REQ_GOOD = """
import json
import os

def main():
    print(json.dumps({"metric": "x_img_s", "measured": False}))
    if os.environ.get("SPARKNET_BENCH_REQUIRE_MEASURED") == "1":
        return 4
    return 0

if __name__ == "__main__":
    main()
"""

REQ_GOOD_HELPER = """
import json
import bench

def main():
    print(json.dumps({"metric": "x_img_s", "measured": False}))
    return 4 if bench._require_measured() else 0

if __name__ == "__main__":
    main()
"""


def test_require_measured_positive():
    found = hits(REQ_BAD, "require-measured")
    assert len(found) == 1
    assert "SPARKNET_BENCH_REQUIRE_MEASURED" in found[0].message


def test_require_measured_suppressed():
    src = REQ_BAD.replace(
        '    print(json.dumps({"metric": "x_img_s", "measured": False}))',
        '    print(json.dumps({"metric": "x_img_s", "measured": False}))  '
        "# graftlint: disable=require-measured -- never queued on chip")
    assert not hits(src, "require-measured")
    assert suppressed_hits(src, "require-measured")


def test_require_measured_clean_env_literal():
    assert not hits(REQ_GOOD, "require-measured")


def test_require_measured_clean_bench_helper():
    assert not hits(REQ_GOOD_HELPER, "require-measured")


def test_require_measured_ignores_libraries_and_hostside_tools():
    # no __main__ guard -> library module, not a queueable script
    assert not hits("x = {'measured': True}\n", "require-measured")
    # script without measured records (host-side tool) is fine too
    src = ('import json\n'
           'def main():\n'
           '    print(json.dumps({"metric": "feed_ms"}))\n'
           'if __name__ == "__main__":\n'
           '    main()\n')
    assert not hits(src, "require-measured")


# -- stale-args-dispatch ----------------------------------------------------

STALE_BAD = """
import time
import jax

def bench(step, feeds):
    t0 = time.perf_counter()
    for _ in range(20):
        loss = step(feeds)
    return time.perf_counter() - t0
"""

STALE_GOOD_THREADED = """
import time
import jax

def bench(step, variables, slots, feeds, key):
    t0 = time.perf_counter()
    for i in range(20):
        variables, slots, loss = step(variables, slots, i, feeds, key)
    float(loss)
    return time.perf_counter() - t0
"""

STALE_NO_JAX = """
import time

def bench(xform, raw):
    t0 = time.perf_counter()
    for _ in range(20):
        out = xform(raw)
    return time.perf_counter() - t0
"""


def test_stale_args_positive():
    found = hits(STALE_BAD, "stale-args-dispatch")
    assert len(found) == 1
    assert "thread" in found[0].message


def test_stale_args_suppressed():
    src = STALE_BAD.replace(
        "        loss = step(feeds)",
        "        loss = step(feeds)  "
        "# graftlint: disable=stale-args-dispatch -- local diagnostic")
    assert not hits(src, "stale-args-dispatch")
    assert suppressed_hits(src, "stale-args-dispatch")


def test_stale_args_clean_when_threaded():
    assert not hits(STALE_GOOD_THREADED, "stale-args-dispatch")


def test_stale_args_ignores_hostside_modules():
    # no jax import: a numpy/PIL loop really does the work every call
    assert not hits(STALE_NO_JAX, "stale-args-dispatch")


def test_stale_args_ignores_untimed_loops():
    src = ('import jax\n'
           'def warmup(step, feeds):\n'
           '    for _ in range(3):\n'
           '        loss = step(feeds)\n'
           '    return loss\n')
    assert not hits(src, "stale-args-dispatch")


# -- no-pkill-self ----------------------------------------------------------

PKILL_BAD = """
import subprocess

def stop_runner():
    subprocess.run("pkill -f tpu_window_runner", shell=True)
"""

PKILL_GOOD = """
import subprocess

def stop_runner():
    pids = subprocess.run(["pgrep", "-f", "tools/tpu_window_[r]unner"],
                          capture_output=True, text=True).stdout.split()
    for pid in pids:
        subprocess.run(["kill", pid])
"""


def test_no_pkill_positive():
    found = hits(PKILL_BAD, "no-pkill-self")
    assert len(found) == 1
    assert "pgrep" in found[0].message


def test_no_pkill_suppressed():
    src = PKILL_BAD.replace(
        '    subprocess.run("pkill -f tpu_window_runner", shell=True)',
        '    subprocess.run("pkill -f tpu_window_runner", shell=True)  '
        "# graftlint: disable=no-pkill-self -- pattern can never match a "
        "shell cmdline here")
    assert not hits(src, "no-pkill-self")
    assert suppressed_hits(src, "no-pkill-self")


def test_no_pkill_clean():
    assert not hits(PKILL_GOOD, "no-pkill-self")


# -- graph-manifest-fresh ---------------------------------------------------

FRESH_SRC = "import jax\n\ndef round_fn(v):\n    return v\n"


def _graph_tree(tmp_path, src=FRESH_SRC, record=True, stale=False):
    """A fake repo: sparknet_tpu/parallel/x.py (+ optional SOURCES.json
    recording its hash, optionally stale)."""
    import hashlib
    import json as _json

    mod = tmp_path / "sparknet_tpu" / "parallel" / "x.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(src)
    if record:
        digest = hashlib.sha256(src.encode()).hexdigest()
        if stale:
            digest = "0" * 64
        cdir = tmp_path / "docs" / "graph_contracts"
        cdir.mkdir(parents=True)
        (cdir / "SOURCES.json").write_text(
            _json.dumps({"sparknet_tpu/parallel/x.py": digest}))
    return str(mod)


def test_graph_manifest_fresh_positive_on_stale_hash(tmp_path):
    path = _graph_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "graph-manifest-fresh", path=path)
    assert len(found) == 1
    assert "--update" in found[0].message


def test_graph_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _graph_tree(tmp_path, record=False)
    found = hits(FRESH_SRC, "graph-manifest-fresh", path=path)
    assert len(found) == 1
    assert "SOURCES.json missing" in found[0].message


def test_graph_manifest_fresh_suppressed(tmp_path):
    path = _graph_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=graph-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "graph-manifest-fresh", path=path)
    assert suppressed_hits(src, "graph-manifest-fresh", path=path)


def test_graph_manifest_fresh_clean_when_hash_matches(tmp_path):
    path = _graph_tree(tmp_path)
    assert not hits(FRESH_SRC, "graph-manifest-fresh", path=path)


# -- byte-manifest-fresh ----------------------------------------------------


def _byte_tree(tmp_path, src=FRESH_SRC, record=True, stale=False,
               rel="sparknet_tpu/solvers/solver.py"):
    """A fake repo: one byte-contract source file (+ optional
    docs/byte_contracts/SOURCES.json recording its hash)."""
    import hashlib
    import json as _json

    mod = tmp_path.joinpath(*rel.split("/"))
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(src)
    if record:
        digest = hashlib.sha256(src.encode()).hexdigest()
        if stale:
            digest = "0" * 64
        cdir = tmp_path / "docs" / "byte_contracts"
        cdir.mkdir(parents=True)
        (cdir / "SOURCES.json").write_text(_json.dumps({rel: digest}))
    return str(mod)


def test_byte_manifest_fresh_positive_on_stale_hash(tmp_path):
    path = _byte_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "byte-manifest-fresh", path=path)
    assert len(found) == 1
    assert "bytes --update" in found[0].message


def test_byte_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _byte_tree(tmp_path, record=False)
    found = hits(FRESH_SRC, "byte-manifest-fresh", path=path)
    assert len(found) == 1
    assert "SOURCES.json missing" in found[0].message


def test_byte_manifest_fresh_covers_the_serve_dir(tmp_path):
    path = _byte_tree(tmp_path, record=False,
                      rel="sparknet_tpu/serve/engine.py")
    assert hits(FRESH_SRC, "byte-manifest-fresh", path=path)


def test_byte_manifest_fresh_ignores_non_surface_files(tmp_path):
    path = _byte_tree(tmp_path, record=False,
                      rel="sparknet_tpu/obs/report.py")
    assert not hits(FRESH_SRC, "byte-manifest-fresh", path=path)


def test_byte_manifest_fresh_suppressed(tmp_path):
    path = _byte_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=byte-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "byte-manifest-fresh", path=path)
    assert suppressed_hits(src, "byte-manifest-fresh", path=path)


def test_byte_manifest_fresh_clean_when_hash_matches(tmp_path):
    path = _byte_tree(tmp_path)
    assert not hits(FRESH_SRC, "byte-manifest-fresh", path=path)


# -- num-manifest-fresh -----------------------------------------------------


def _num_tree(tmp_path, src=FRESH_SRC, record=True, stale=False,
              rel="sparknet_tpu/common.py"):
    """A fake repo: one numerics-contract source file (+ optional
    docs/num_contracts/SOURCES.json recording its hash).  Defaults to
    common.py — num surface (the activation_dtype policy semantics)
    but NOT byte surface, so the two rules stay distinguishable."""
    import hashlib
    import json as _json

    mod = tmp_path.joinpath(*rel.split("/"))
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(src)
    if record:
        digest = hashlib.sha256(src.encode()).hexdigest()
        if stale:
            digest = "0" * 64
        cdir = tmp_path / "docs" / "num_contracts"
        cdir.mkdir(parents=True)
        (cdir / "SOURCES.json").write_text(_json.dumps({rel: digest}))
    return str(mod)


def test_num_manifest_fresh_positive_on_stale_hash(tmp_path):
    path = _num_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "num-manifest-fresh", path=path)
    assert len(found) == 1
    assert "num --update" in found[0].message


def test_num_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _num_tree(tmp_path, record=False)
    found = hits(FRESH_SRC, "num-manifest-fresh", path=path)
    assert len(found) == 1
    assert "SOURCES.json missing" in found[0].message


def test_num_manifest_fresh_covers_common_py_unlike_byte(tmp_path):
    # common.py carries the activation_dtype policy semantics: num
    # surface, but deliberately NOT byte surface
    path = _num_tree(tmp_path, record=False)
    assert hits(FRESH_SRC, "num-manifest-fresh", path=path)
    assert not hits(FRESH_SRC, "byte-manifest-fresh", path=path)


def test_num_manifest_fresh_ignores_non_surface_files(tmp_path):
    path = _num_tree(tmp_path, record=False,
                     rel="sparknet_tpu/obs/report.py")
    assert not hits(FRESH_SRC, "num-manifest-fresh", path=path)


def test_num_manifest_fresh_suppressed(tmp_path):
    path = _num_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=num-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "num-manifest-fresh", path=path)
    assert suppressed_hits(src, "num-manifest-fresh", path=path)


def test_num_manifest_fresh_clean_when_hash_matches(tmp_path):
    path = _num_tree(tmp_path)
    assert not hits(FRESH_SRC, "num-manifest-fresh", path=path)


def test_num_manifest_fresh_surface_matches_numcheck():
    # the rule duplicates numcheck.NUM_SOURCE_PATTERNS so rules.py
    # stays importable without jax-adjacent modules; pin the two lists
    # against each other so they cannot drift apart silently
    from sparknet_tpu.analysis import rules
    from sparknet_tpu.analysis.numcheck import NUM_SOURCE_PATTERNS

    dup = set(rules._NUM_SOURCE_DIRS) | set(rules._NUM_SOURCE_FILES)
    assert dup == set(NUM_SOURCE_PATTERNS)


def test_graph_manifest_fresh_ignores_non_contract_files(tmp_path):
    other = tmp_path / "sparknet_tpu" / "ops" / "y.py"
    other.parent.mkdir(parents=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "graph-manifest-fresh", path=str(other))
    # and plain fixture paths (no sparknet_tpu/ segment) never fire
    assert not hits(FRESH_SRC, "graph-manifest-fresh")


# -- mem-manifest-fresh -----------------------------------------------------


def _mem_tree(tmp_path, rel="sparknet_tpu/solvers/solver.py",
              src=FRESH_SRC, record=True, stale=False):
    """A fake repo: one memory-contract source file (+ optional
    docs/mem_contracts/SOURCES.json recording its hash)."""
    import hashlib
    import json as _json

    mod = tmp_path / rel
    mod.parent.mkdir(parents=True)
    mod.write_text(src)
    if record:
        digest = hashlib.sha256(src.encode()).hexdigest()
        if stale:
            digest = "0" * 64
        cdir = tmp_path / "docs" / "mem_contracts"
        cdir.mkdir(parents=True)
        (cdir / "SOURCES.json").write_text(_json.dumps({rel: digest}))
    return str(mod)


def test_mem_manifest_fresh_positive_on_stale_hash(tmp_path):
    path = _mem_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "mem-manifest-fresh", path=path)
    assert len(found) == 1
    assert "mem --update" in found[0].message


def test_mem_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _mem_tree(tmp_path, rel="sparknet_tpu/ops/pallas_kernels.py",
                     record=False)
    found = hits(FRESH_SRC, "mem-manifest-fresh", path=path)
    assert len(found) == 1
    assert "SOURCES.json missing" in found[0].message


def test_mem_manifest_fresh_suppressed(tmp_path):
    path = _mem_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=mem-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "mem-manifest-fresh", path=path)
    assert suppressed_hits(src, "mem-manifest-fresh", path=path)


def test_mem_manifest_fresh_clean_when_hash_matches(tmp_path):
    path = _mem_tree(tmp_path)
    assert not hits(FRESH_SRC, "mem-manifest-fresh", path=path)


def _conc_tree(tmp_path, rel="sparknet_tpu/serve/batcher.py",
               src=FRESH_SRC, record=True, stale=False):
    """A fake repo: one concurrency-contract source file (+ optional
    docs/conc_contracts/SOURCES.json recording its hash)."""
    import hashlib
    import json as _json

    mod = tmp_path / rel
    mod.parent.mkdir(parents=True)
    mod.write_text(src)
    if record:
        digest = hashlib.sha256(src.encode()).hexdigest()
        if stale:
            digest = "0" * 64
        cdir = tmp_path / "docs" / "conc_contracts"
        cdir.mkdir(parents=True)
        (cdir / "SOURCES.json").write_text(_json.dumps({rel: digest}))
    return str(mod)


def test_conc_manifest_fresh_positive_on_stale_hash(tmp_path):
    path = _conc_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "conc-manifest-fresh", path=path)
    assert len(found) == 1
    assert "conc --update" in found[0].message


def test_conc_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _conc_tree(tmp_path, rel="sparknet_tpu/loop/controller.py",
                      record=False)
    found = hits(FRESH_SRC, "conc-manifest-fresh", path=path)
    assert len(found) == 1
    assert "SOURCES.json missing" in found[0].message


def test_conc_manifest_fresh_covers_window_runner(tmp_path):
    # the one audited file OUTSIDE sparknet_tpu/: the /tools/ anchor
    path = _conc_tree(tmp_path, rel="tools/tpu_window_runner.py",
                      stale=True)
    found = hits(FRESH_SRC, "conc-manifest-fresh", path=path)
    assert len(found) == 1


def test_conc_manifest_fresh_suppressed(tmp_path):
    path = _conc_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=conc-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "conc-manifest-fresh", path=path)
    assert suppressed_hits(src, "conc-manifest-fresh", path=path)


def test_conc_manifest_fresh_clean_when_hash_matches(tmp_path):
    path = _conc_tree(tmp_path)
    assert not hits(FRESH_SRC, "conc-manifest-fresh", path=path)


def test_conc_manifest_fresh_ignores_non_contract_files(tmp_path):
    # parallel/ is graph/mem surface, not concurrency surface
    other = tmp_path / "sparknet_tpu" / "parallel" / "modes.py"
    other.parent.mkdir(parents=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "conc-manifest-fresh", path=str(other))


def test_mem_manifest_fresh_ignores_non_contract_files(tmp_path):
    # ops/vision.py changes the math, not the memory contract surface
    other = tmp_path / "sparknet_tpu" / "ops" / "vision.py"
    other.parent.mkdir(parents=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "mem-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "mem-manifest-fresh")


# -- fused-update-manifest --------------------------------------------------


def _fused_tree(tmp_path, rel="sparknet_tpu/solvers/arena.py",
                src=FRESH_SRC, families=("graph_contracts",
                                         "mem_contracts"),
                record=True, stale=False):
    """A fake repo: one fused-update source file + SOURCES.json in the
    given manifest families recording its hash (optionally stale)."""
    import hashlib
    import json as _json

    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(src)
    digest = hashlib.sha256(src.encode()).hexdigest()
    if stale:
        digest = "0" * 64
    if record:
        for fam in families:
            cdir = tmp_path / "docs" / fam
            cdir.mkdir(parents=True, exist_ok=True)
            (cdir / "SOURCES.json").write_text(_json.dumps({rel: digest}))
    return str(mod)


def test_fused_update_manifest_positive_on_stale_hash(tmp_path):
    # arena.py is BOTH graph- and mem-contract source: a stale hash in
    # each family yields one finding per family
    path = _fused_tree(tmp_path, stale=True)
    found = hits(FRESH_SRC, "fused-update-manifest", path=path)
    assert len(found) == 2
    msgs = " ".join(f.message for f in found)
    assert "graph --update" in msgs and "mem --update" in msgs


def test_fused_update_manifest_positive_when_never_banked(tmp_path):
    path = _fused_tree(tmp_path, record=False)
    found = hits(FRESH_SRC, "fused-update-manifest", path=path)
    assert len(found) == 2
    assert "SOURCES.json missing" in found[0].message


def test_fused_update_manifest_graph_only_files(tmp_path):
    # solver.py's mem freshness is mem-manifest-fresh's job; this rule
    # adds only the graph-side check — exactly one finding
    path = _fused_tree(tmp_path, rel="sparknet_tpu/solvers/solver.py",
                       families=("graph_contracts",), stale=True)
    found = hits(FRESH_SRC, "fused-update-manifest", path=path)
    assert len(found) == 1
    assert "graph_contracts" in found[0].message


def test_fused_update_manifest_suppressed(tmp_path):
    path = _fused_tree(tmp_path, stale=True)
    src = ("# graftlint: disable-file=fused-update-manifest -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "fused-update-manifest", path=path)
    assert suppressed_hits(src, "fused-update-manifest", path=path)


def test_fused_update_manifest_clean_when_hash_matches(tmp_path):
    path = _fused_tree(tmp_path)
    assert not hits(FRESH_SRC, "fused-update-manifest", path=path)


def test_fused_update_manifest_ignores_non_contract_files(tmp_path):
    other = tmp_path / "sparknet_tpu" / "solvers" / "lr_policy.py"
    other.parent.mkdir(parents=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "fused-update-manifest", path=str(other))
    assert not hits(FRESH_SRC, "fused-update-manifest")


# -- elastic-manifest-fresh -------------------------------------------------


def _elastic_tree(tmp_path, record=True, covered=True, widths=(8, 6),
                  families=("graph_contracts", "mem_contracts")):
    """A fake repo around parallel/elastic.py: SOURCES.json (optionally
    not covering it) + elastic_w*.json twin manifests per family."""
    import hashlib
    import json as _json

    rel = "sparknet_tpu/parallel/elastic.py"
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(FRESH_SRC)
    digest = hashlib.sha256(FRESH_SRC.encode()).hexdigest()
    for fam in families:
        cdir = tmp_path / "docs" / fam
        cdir.mkdir(parents=True, exist_ok=True)
        if record:
            entry = {rel: digest} if covered else {"other.py": digest}
            (cdir / "SOURCES.json").write_text(_json.dumps(entry))
        for w in widths:
            (cdir / f"elastic_w{w}.json").write_text("{}")
    return str(mod)


def test_elastic_manifest_fresh_clean_when_banked(tmp_path):
    path = _elastic_tree(tmp_path)
    assert not hits(FRESH_SRC, "elastic-manifest-fresh", path=path)


def test_elastic_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _elastic_tree(tmp_path, record=False, widths=())
    found = hits(FRESH_SRC, "elastic-manifest-fresh", path=path)
    assert len(found) == 2  # one per family
    assert "SOURCES.json missing" in found[0].message


def test_elastic_manifest_fresh_positive_when_not_folded_in(tmp_path):
    # manifests exist but predate the elastic layer: elastic.py absent
    # from the fingerprint — exactly the silent-non-coverage hole the
    # dir-hash rules cannot see
    path = _elastic_tree(tmp_path, covered=False)
    found = hits(FRESH_SRC, "elastic-manifest-fresh", path=path)
    assert len(found) == 2
    assert all("not folded into" in f.message for f in found)


def test_elastic_manifest_fresh_positive_below_min_widths(tmp_path):
    path = _elastic_tree(tmp_path, widths=(8,))
    found = hits(FRESH_SRC, "elastic-manifest-fresh", path=path)
    assert len(found) == 2
    assert all(">= 2 mesh widths" in f.message for f in found)


def test_elastic_manifest_fresh_suppressed(tmp_path):
    path = _elastic_tree(tmp_path, record=False, widths=())
    src = ("# graftlint: disable-file=elastic-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "elastic-manifest-fresh", path=path)
    assert suppressed_hits(src, "elastic-manifest-fresh", path=path)


def test_elastic_manifest_fresh_ignores_other_parallel_files(tmp_path):
    other = tmp_path / "sparknet_tpu" / "parallel" / "trainer.py"
    other.parent.mkdir(parents=True, exist_ok=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "elastic-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "elastic-manifest-fresh")


# -- serve-manifest-fresh ---------------------------------------------------


def _serve_tree(tmp_path, record=True, covered=True,
                buckets=(1, 8, 64, 256),
                families=("graph_contracts", "mem_contracts")):
    """A fake repo around serve/engine.py: SOURCES.json (optionally not
    covering it) + serve_b*.json twin manifests per family."""
    import hashlib
    import json as _json

    rel = "sparknet_tpu/serve/engine.py"
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(FRESH_SRC)
    digest = hashlib.sha256(FRESH_SRC.encode()).hexdigest()
    for fam in families:
        cdir = tmp_path / "docs" / fam
        cdir.mkdir(parents=True, exist_ok=True)
        if record:
            entry = {rel: digest} if covered else {"other.py": digest}
            (cdir / "SOURCES.json").write_text(_json.dumps(entry))
        for b in buckets:
            (cdir / f"serve_b{b}.json").write_text("{}")
    return str(mod)


def test_serve_manifest_fresh_clean_when_banked(tmp_path):
    path = _serve_tree(tmp_path)
    assert not hits(FRESH_SRC, "serve-manifest-fresh", path=path)


def test_serve_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _serve_tree(tmp_path, record=False, buckets=())
    found = hits(FRESH_SRC, "serve-manifest-fresh", path=path)
    assert len(found) == 2  # one per family
    assert "SOURCES.json missing" in found[0].message


def test_serve_manifest_fresh_positive_when_not_folded_in(tmp_path):
    # manifests exist but predate the serving layer: engine.py absent
    # from the fingerprint — the silent-non-coverage hole
    path = _serve_tree(tmp_path, covered=False)
    found = hits(FRESH_SRC, "serve-manifest-fresh", path=path)
    assert len(found) == 2
    assert all("not folded into" in f.message for f in found)


def test_serve_manifest_fresh_positive_below_bucket_ladder(tmp_path):
    path = _serve_tree(tmp_path, buckets=(1, 8))
    found = hits(FRESH_SRC, "serve-manifest-fresh", path=path)
    assert len(found) == 2
    assert all("4 buckets" in f.message for f in found)


def test_serve_manifest_fresh_suppressed(tmp_path):
    path = _serve_tree(tmp_path, record=False, buckets=())
    src = ("# graftlint: disable-file=serve-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "serve-manifest-fresh", path=path)
    assert suppressed_hits(src, "serve-manifest-fresh", path=path)


def test_serve_manifest_fresh_ignores_other_packages(tmp_path):
    other = tmp_path / "sparknet_tpu" / "parallel" / "trainer.py"
    other.parent.mkdir(parents=True, exist_ok=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "serve-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "serve-manifest-fresh")


# -- replica-manifest-fresh -------------------------------------------------


def _replica_tree(tmp_path, record=True, covered=True, widths=(1, 2, 4),
                  families=("graph_contracts", "mem_contracts")):
    """A fake repo around serve/router.py: SOURCES.json (optionally not
    covering it) + serve_r*.json pool-width twin manifests per family."""
    import hashlib
    import json as _json

    rel = "sparknet_tpu/serve/router.py"
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(FRESH_SRC)
    digest = hashlib.sha256(FRESH_SRC.encode()).hexdigest()
    for fam in families:
        cdir = tmp_path / "docs" / fam
        cdir.mkdir(parents=True, exist_ok=True)
        if record:
            entry = {rel: digest} if covered else {"other.py": digest}
            (cdir / "SOURCES.json").write_text(_json.dumps(entry))
        for w in widths:
            (cdir / f"serve_r{w}.json").write_text("{}")
    return str(mod)


def test_replica_manifest_fresh_clean_when_banked(tmp_path):
    path = _replica_tree(tmp_path)
    assert not hits(FRESH_SRC, "replica-manifest-fresh", path=path)


def test_replica_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _replica_tree(tmp_path, record=False, widths=())
    found = hits(FRESH_SRC, "replica-manifest-fresh", path=path)
    assert len(found) == 2  # one per family
    assert "SOURCES.json missing" in found[0].message


def test_replica_manifest_fresh_positive_when_not_folded_in(tmp_path):
    # manifests exist but predate the replica layer: router.py absent
    # from the fingerprint — the silent-non-coverage hole
    path = _replica_tree(tmp_path, covered=False)
    found = hits(FRESH_SRC, "replica-manifest-fresh", path=path)
    assert len(found) == 2
    assert all("not folded into" in f.message for f in found)


def test_replica_manifest_fresh_positive_below_min_widths(tmp_path):
    path = _replica_tree(tmp_path, widths=(4,))
    found = hits(FRESH_SRC, "replica-manifest-fresh", path=path)
    assert len(found) == 2
    assert all(">= 2" in f.message for f in found)


def test_replica_manifest_fresh_suppressed(tmp_path):
    path = _replica_tree(tmp_path, record=False, widths=())
    src = ("# graftlint: disable-file=replica-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "replica-manifest-fresh", path=path)
    assert suppressed_hits(src, "replica-manifest-fresh", path=path)


def test_replica_manifest_fresh_ignores_other_serve_files(tmp_path):
    other = tmp_path / "sparknet_tpu" / "serve" / "engine.py"
    other.parent.mkdir(parents=True, exist_ok=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "replica-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "replica-manifest-fresh")


# -- paged-manifest-fresh ---------------------------------------------------


def _paged_tree(tmp_path, record=True, covered=True, occupancies=(1, 4),
                rect=True,
                families=("graph_contracts", "mem_contracts",
                          "byte_contracts")):
    """A fake repo around serve/paged.py: SOURCES.json (optionally not
    covering it) + decode_paged_o*.json occupancy twins and the
    decode_rect.json baseline per family."""
    import hashlib
    import json as _json

    rel = "sparknet_tpu/serve/paged.py"
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(FRESH_SRC)
    digest = hashlib.sha256(FRESH_SRC.encode()).hexdigest()
    for fam in families:
        cdir = tmp_path / "docs" / fam
        cdir.mkdir(parents=True, exist_ok=True)
        if record:
            entry = {rel: digest} if covered else {"other.py": digest}
            (cdir / "SOURCES.json").write_text(_json.dumps(entry))
        for o in occupancies:
            (cdir / f"decode_paged_o{o}.json").write_text("{}")
        if rect:
            (cdir / "decode_rect.json").write_text("{}")
    return str(mod)


def test_paged_manifest_fresh_clean_when_banked(tmp_path):
    path = _paged_tree(tmp_path)
    assert not hits(FRESH_SRC, "paged-manifest-fresh", path=path)


def test_paged_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _paged_tree(tmp_path, record=False, occupancies=(), rect=False)
    found = hits(FRESH_SRC, "paged-manifest-fresh", path=path)
    assert len(found) == 3  # one per family (graph + mem + byte)
    assert "SOURCES.json missing" in found[0].message


def test_paged_manifest_fresh_positive_when_not_folded_in(tmp_path):
    # manifests exist but predate the paged layer: paged.py absent
    # from the fingerprint — the silent-non-coverage hole
    path = _paged_tree(tmp_path, covered=False)
    found = hits(FRESH_SRC, "paged-manifest-fresh", path=path)
    assert len(found) == 3
    assert all("not folded into" in f.message for f in found)


def test_paged_manifest_fresh_positive_below_min_occupancies(tmp_path):
    path = _paged_tree(tmp_path, occupancies=(4,))
    found = hits(FRESH_SRC, "paged-manifest-fresh", path=path)
    assert len(found) == 3
    assert all(">= 2" in f.message for f in found)


def test_paged_manifest_fresh_positive_without_rect_baseline(tmp_path):
    path = _paged_tree(tmp_path, rect=False)
    found = hits(FRESH_SRC, "paged-manifest-fresh", path=path)
    assert len(found) == 3
    assert all("decode_rect" in f.message for f in found)


def test_paged_manifest_fresh_suppressed(tmp_path):
    path = _paged_tree(tmp_path, record=False, occupancies=(), rect=False)
    src = ("# graftlint: disable-file=paged-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "paged-manifest-fresh", path=path)
    assert suppressed_hits(src, "paged-manifest-fresh", path=path)


def test_paged_manifest_fresh_ignores_other_serve_files(tmp_path):
    other = tmp_path / "sparknet_tpu" / "serve" / "continuous.py"
    other.parent.mkdir(parents=True, exist_ok=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "paged-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "paged-manifest-fresh")


# -- loop-manifest-fresh ----------------------------------------------------


def _loop_tree(tmp_path, record=True, covered=True,
               families=("graph_contracts", "mem_contracts")):
    """A fake repo around loop/controller.py: SOURCES.json per family,
    optionally not covering it (the loop banks no twins of its own)."""
    import hashlib
    import json as _json

    rel = "sparknet_tpu/loop/controller.py"
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(FRESH_SRC)
    digest = hashlib.sha256(FRESH_SRC.encode()).hexdigest()
    for fam in families:
        cdir = tmp_path / "docs" / fam
        cdir.mkdir(parents=True, exist_ok=True)
        if record:
            entry = {rel: digest} if covered else {"other.py": digest}
            (cdir / "SOURCES.json").write_text(_json.dumps(entry))
    return str(mod)


def test_loop_manifest_fresh_clean_when_banked(tmp_path):
    path = _loop_tree(tmp_path)
    assert not hits(FRESH_SRC, "loop-manifest-fresh", path=path)


def test_loop_manifest_fresh_positive_when_never_banked(tmp_path):
    path = _loop_tree(tmp_path, record=False)
    found = hits(FRESH_SRC, "loop-manifest-fresh", path=path)
    assert len(found) == 2  # one per family
    assert "SOURCES.json missing" in found[0].message


def test_loop_manifest_fresh_positive_when_not_folded_in(tmp_path):
    # manifests exist but predate the loop layer: controller.py absent
    # from the fingerprint — the silent-non-coverage hole
    path = _loop_tree(tmp_path, covered=False)
    found = hits(FRESH_SRC, "loop-manifest-fresh", path=path)
    assert len(found) == 2
    assert all("not folded into" in f.message for f in found)


def test_loop_manifest_fresh_suppressed(tmp_path):
    path = _loop_tree(tmp_path, record=False)
    src = ("# graftlint: disable-file=loop-manifest-fresh -- "
           "manifest regen follows in this PR\n" + FRESH_SRC)
    assert not hits(src, "loop-manifest-fresh", path=path)
    assert suppressed_hits(src, "loop-manifest-fresh", path=path)


def test_loop_manifest_fresh_ignores_other_packages(tmp_path):
    other = tmp_path / "sparknet_tpu" / "serve" / "engine.py"
    other.parent.mkdir(parents=True, exist_ok=True)
    other.write_text(FRESH_SRC)
    assert not hits(FRESH_SRC, "loop-manifest-fresh", path=str(other))
    assert not hits(FRESH_SRC, "loop-manifest-fresh")


# -- queue-job-hygiene ------------------------------------------------------

RUNNER_SRC = "def main():\n    return 0\n"


def _runner_tree(tmp_path, queues):
    """A fake tools/ dir: the runner + queue JSON files beside it."""
    import json as _json

    tools = tmp_path / "tools"
    tools.mkdir()
    runner = tools / "tpu_window_runner.py"
    runner.write_text(RUNNER_SRC)
    for fname, spec in queues.items():
        body = spec if isinstance(spec, str) else _json.dumps(spec)
        (tools / fname).write_text(body)
    return str(runner)


def _bench_job(name, u=True, rm=True):
    j = {"name": name,
         "argv": ["python"] + (["-u"] if u else []) + ["bench.py"],
         "deadline_s": 60}
    if rm:
        j["env"] = {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"}
    return j


def _trace_job(name):
    return {"name": name,
            "argv": ["python", "-u", "-m", "sparknet_tpu.cli", "time",
                     "--trace"],
            "deadline_s": 60}


def test_queue_hygiene_flags_all_three_contracts(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": {"jobs": [
        _bench_job("no_unbuffered", u=False),
        _bench_job("no_measured", rm=False),
        _trace_job("trace_early"),
        _bench_job("after_trace"),
    ]}})
    found = hits(RUNNER_SRC, "queue-job-hygiene", path=path)
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 3
    assert "no_unbuffered" in msgs and "without -u" in msgs
    assert "no_measured" in msgs and "REQUIRE_MEASURED" in msgs
    assert "after_trace" in msgs and "LAST" in msgs


def test_queue_hygiene_legacy_queues_excused(tmp_path):
    bad = {"jobs": [_bench_job("no_measured", rm=False)]}
    path = _runner_tree(tmp_path, {"tpu_queue_r3.json": bad,
                                   "tpu_queue_r4.json": bad})
    assert not hits(RUNNER_SRC, "queue-job-hygiene", path=path)


def test_queue_hygiene_unreadable_queue_is_flagged(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": "{not json"})
    found = hits(RUNNER_SRC, "queue-job-hygiene", path=path)
    assert len(found) == 1
    assert "unreadable" in found[0].message


def test_queue_hygiene_clean_queue_passes(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": {
        "jobs": [_bench_job("headline"), _trace_job("trace_last")],
        "setup": [{"name": "fixture", "argv": ["python", "x.py"]}],
    }})
    assert not hits(RUNNER_SRC, "queue-job-hygiene", path=path)


def test_queue_hygiene_only_fires_from_the_runner(tmp_path):
    """Another tool in the same dir must not re-report every queue."""
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": {"jobs": [
        _bench_job("no_measured", rm=False)]}})
    other = os.path.join(os.path.dirname(path), "tunnel_log.py")
    assert hits(RUNNER_SRC, "queue-job-hygiene", path=path)
    assert not hits(RUNNER_SRC, "queue-job-hygiene", path=other)


def test_queue_hygiene_suppressible(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": {"jobs": [
        _bench_job("no_measured", rm=False)]}})
    src = ("# graftlint: disable-file=queue-job-hygiene -- "
           "fixture queue under construction\n" + RUNNER_SRC)
    assert not hits(src, "queue-job-hygiene", path=path)
    assert suppressed_hits(src, "queue-job-hygiene", path=path)


# -- queue-policy-fields ----------------------------------------------------


def _priced(job, value=5, est=300):
    j = dict(job)
    j["value"] = value
    j["est_runtime_s"] = est
    return j


def test_queue_policy_flags_missing_and_invalid_fields(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r9.json": {"jobs": [
        _bench_job("unpriced"),                              # both missing
        _priced(_bench_job("zero_value"), value=0),          # non-positive
        dict(_priced(_bench_job("bool_value")), value=True),  # bool sneaks
        _priced(_bench_job("clean")),
    ]}})
    found = hits(RUNNER_SRC, "queue-policy-fields", path=path)
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 4  # unpriced x2 fields + zero_value + bool_value
    assert "unpriced" in msgs and "'value'" in msgs
    assert "'est_runtime_s'" in msgs
    assert "zero_value" in msgs and "bool_value" in msgs
    assert "clean" not in msgs


def test_queue_policy_legacy_rounds_excused_r8_not(tmp_path):
    bare = {"jobs": [_bench_job("unpriced")]}
    queues = {f"tpu_queue_r{n}.json": dict(bare) for n in range(3, 8)}
    queues["tpu_queue_r8.json"] = bare
    path = _runner_tree(tmp_path, queues)
    found = hits(RUNNER_SRC, "queue-policy-fields", path=path)
    assert found
    assert all("tpu_queue_r8.json" in f.message for f in found)


def test_queue_policy_clean_priced_queue_passes(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r8.json": {"jobs": [
        _priced(_bench_job("headline"), value=10, est=900),
        _priced(_trace_job("trace_last"), value=3, est=900),
    ]}})
    assert not hits(RUNNER_SRC, "queue-policy-fields", path=path)


def test_queue_policy_unreadable_left_to_hygiene(tmp_path):
    # one finding per rule, not two for the same broken file
    path = _runner_tree(tmp_path, {"tpu_queue_r8.json": "{not json"})
    assert not hits(RUNNER_SRC, "queue-policy-fields", path=path)
    assert hits(RUNNER_SRC, "queue-job-hygiene", path=path)


def test_queue_policy_only_fires_from_the_runner(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r8.json": {"jobs": [
        _bench_job("unpriced")]}})
    other = os.path.join(os.path.dirname(path), "tunnel_log.py")
    assert hits(RUNNER_SRC, "queue-policy-fields", path=path)
    assert not hits(RUNNER_SRC, "queue-policy-fields", path=other)


def test_queue_policy_suppressible(tmp_path):
    path = _runner_tree(tmp_path, {"tpu_queue_r8.json": {"jobs": [
        _bench_job("unpriced")]}})
    src = ("# graftlint: disable-file=queue-policy-fields -- "
           "draft queue not yet priced\n" + RUNNER_SRC)
    assert not hits(src, "queue-policy-fields", path=path)
    assert suppressed_hits(src, "queue-policy-fields", path=path)


# -- feed-shm-cleanup -------------------------------------------------------

SHM_BAD = """
from multiprocessing import shared_memory

def build_ring(nbytes):
    shm = shared_memory.SharedMemory(create=True, size=nbytes)
    return shm
"""

SHM_GOOD_FINALLY = """
from multiprocessing import shared_memory

def run(nbytes):
    shm = shared_memory.SharedMemory(create=True, size=nbytes)
    try:
        work(shm)
    finally:
        shm.close()
        shm.unlink()
"""

SHM_GOOD_CLOSE_METHOD = """
from multiprocessing import shared_memory

class Ring:
    def __init__(self, nbytes):
        self.shm = shared_memory.SharedMemory(create=True, size=nbytes)

    def close(self):
        self.shm.close()
        self.shm.unlink()
"""

SHM_ATTACH_ONLY = """
from multiprocessing import shared_memory

def attach(name):
    return shared_memory.SharedMemory(name=name)
"""


def test_shm_cleanup_positive_without_unlink():
    assert hits(SHM_BAD, "feed-shm-cleanup")


def test_shm_cleanup_clean_with_finally_unlink():
    assert not hits(SHM_GOOD_FINALLY, "feed-shm-cleanup")


def test_shm_cleanup_clean_with_close_method():
    assert not hits(SHM_GOOD_CLOSE_METHOD, "feed-shm-cleanup")


def test_shm_cleanup_attach_side_exempt():
    assert not hits(SHM_ATTACH_ONLY, "feed-shm-cleanup")


def test_shm_cleanup_unlink_in_ordinary_helper_still_flagged():
    """An unlink buried in a non-teardown-named helper is the rule's
    documented blind-spot boundary: still a finding."""
    assert hits(SHM_BAD + "\ndef helper(shm):\n    shm.unlink()\n",
                "feed-shm-cleanup")


def test_shm_cleanup_suppressible():
    src = SHM_BAD.replace(
        "create=True, size=nbytes)",
        "create=True, size=nbytes)  # graftlint: disable=feed-shm-cleanup"
        " -- fixture: lifetime owned by the caller")
    assert not hits(src, "feed-shm-cleanup")
    assert suppressed_hits(src, "feed-shm-cleanup")


# -- obs-fenced-span --------------------------------------------------------

SPAN_BAD = """
import jax

def timed(rec, step, feeds):
    with rec.span("train") as sp:
        out = step(feeds)
    return out
"""

SPAN_BAD_NO_AS = """
import jax

def timed(rec, step, feeds):
    with rec.span("train"):
        out = step(feeds)
    return out
"""

SPAN_GOOD_FENCED = """
import jax

def timed(rec, step, feeds):
    with rec.span("train") as sp:
        out = step(feeds)
        sp.fence(out)
    return out
"""

SPAN_GOOD_FENCE_VALUE = """
import jax

def timed(rec, solver, fn):
    with rec.span("solve") as sp:
        loss = solver.solve(fn)
        sp.fence_value(loss)
    return loss
"""

SPAN_GOOD_HOST = """
import jax

def staged(rec, paths):
    with rec.span("stage-db", host=True):
        return [open(p).read() for p in paths]
"""


def test_obs_fenced_span_positive():
    found = hits(SPAN_BAD, "obs-fenced-span")
    assert len(found) == 1
    assert "fence stamp" in found[0].message


def test_obs_fenced_span_positive_without_as_binding():
    found = hits(SPAN_BAD_NO_AS, "obs-fenced-span")
    assert len(found) == 1
    assert "`as` binding" in found[0].message


def test_obs_fenced_span_suppressed():
    src = SPAN_BAD.replace(
        '    with rec.span("train") as sp:',
        '    with rec.span("train") as sp:  '
        "# graftlint: disable=obs-fenced-span -- fenced by the helper")
    assert not hits(src, "obs-fenced-span")
    assert suppressed_hits(src, "obs-fenced-span")


def test_obs_fenced_span_clean_when_fenced():
    assert not hits(SPAN_GOOD_FENCED, "obs-fenced-span")
    assert not hits(SPAN_GOOD_FENCE_VALUE, "obs-fenced-span")


def test_obs_fenced_span_clean_when_host():
    assert not hits(SPAN_GOOD_HOST, "obs-fenced-span")


def test_obs_fenced_span_ignores_non_jax_modules():
    # a host-side tool's span times host work by construction
    assert not hits(SPAN_BAD.replace("import jax", "import os"),
                    "obs-fenced-span")


# -- suppression machinery --------------------------------------------------


def test_disable_next_line_directive():
    src = FENCE_BAD.replace(
        "    jax.block_until_ready(out)",
        "    # graftlint: disable-next-line=fence-by-value -- rig\n"
        "    jax.block_until_ready(out)")
    assert not hits(src, "fence-by-value")
    assert len(suppressed_hits(src, "fence-by-value")) == 2


def test_disable_file_directive():
    src = ("# graftlint: disable-file=fence-by-value -- whole-file rig\n"
           + FENCE_BAD)
    assert not hits(src, "fence-by-value")
    assert len(suppressed_hits(src, "fence-by-value")) == 2


def test_disable_all_and_comma_lists():
    src = FENCE_BAD.replace(
        "    jax.block_until_ready(out)",
        "    jax.block_until_ready(out)  # graftlint: disable=all")
    assert not hits(src, "fence-by-value")
    src2 = STALE_BAD.replace(
        "        loss = step(feeds)",
        "        loss = step(feeds)  "
        "# graftlint: disable=stale-args-dispatch,fence-by-value -- x")
    assert not hits(src2, "stale-args-dispatch")


def test_suppression_is_per_line_not_per_file():
    # a directive on ONE hit must not hide the other
    src = FENCE_BAD.replace(
        "    out = step(out)\n    jax.block_until_ready(out)",
        "    out = step(out)\n    jax.block_until_ready(out)  "
        "# graftlint: disable=fence-by-value -- only this one")
    assert len(hits(src, "fence-by-value")) == 1


def test_parse_error_is_reported_not_raised():
    findings = lint_source("def broken(:\n", "bad.py")
    assert findings and findings[0].rule == "parse-error"


# -- CLI --------------------------------------------------------------------


def test_cli_json_format_and_exit_code(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(PKILL_BAD)
    rc = cli_main([str(bad), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["unsuppressed"] == 1
    assert out["findings"][0]["rule"] == "no-pkill-self"


def test_cli_clean_file_exits_zero(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text(FENCE_GOOD)
    rc = cli_main([str(good)])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_single_rule_filter(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(PKILL_BAD + ENV_BAD)
    rc = cli_main([str(bad), "--rule", "no-env-platform", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in out["findings"]} == {"no-env-platform"}


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in EXPECTED_RULES:
        assert rule_id in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert cli_main(["--rule", "no-such-rule"]) == 2


# -- CI wiring: the repo lints itself ---------------------------------------


def test_default_scope_covers_contract_surface():
    paths = default_paths()
    tails = {p.rsplit("/", 1)[-1] for p in paths}
    assert {"sparknet_tpu", "tools", "bench.py"} <= tails


def test_repo_self_lint_is_clean():
    """THE ratchet: zero unsuppressed findings over sparknet_tpu/,
    tools/, and bench.py.  A new violation fails tier-1; an intentional
    exception must carry a justified ``# graftlint: disable=...``."""
    findings = lint_paths(default_paths())
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "unsuppressed graftlint findings:\n" + "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in bad)


# -- obs-vocab-coverage -----------------------------------------------------


_VOCAB_SCHEMA = (
    'EVENTS: dict[str, tuple[dict, dict]] = {\n'
    '    "round": ({"run_id": str}, {}),\n'
    '    "serve": ({"run_id": str, "kind": str}, {}),\n'
    '}\n'
)


def _vocab_tree(tmp_path, report_has=("round", "serve"),
                doc_has=("round", "serve"), write_report=True,
                write_doc=True):
    """A fake repo around obs/schema.py: a report.py rendering some
    event names as quoted literals, an OBSERVABILITY.md documenting
    some as backticked terms."""
    rel = tmp_path / "sparknet_tpu" / "obs" / "schema.py"
    rel.parent.mkdir(parents=True, exist_ok=True)
    rel.write_text(_VOCAB_SCHEMA)
    if write_report:
        body = "\n".join(
            f'    if ev.get("event") == "{n}":\n        pass'
            for n in report_has)
        (rel.parent / "report.py").write_text(
            f"def render(ev):\n{body or '    pass'}\n")
    if write_doc:
        docs = tmp_path / "docs"
        docs.mkdir(exist_ok=True)
        (docs / "OBSERVABILITY.md").write_text(
            "# obs\n" + "".join(f"the `{n}` event\n" for n in doc_has))
    return str(rel)


def test_obs_vocab_clean_when_fully_covered(tmp_path):
    path = _vocab_tree(tmp_path)
    assert not hits(_VOCAB_SCHEMA, "obs-vocab-coverage", path=path)


def test_obs_vocab_positive_when_report_misses_an_event(tmp_path):
    path = _vocab_tree(tmp_path, report_has=("round",))
    found = hits(_VOCAB_SCHEMA, "obs-vocab-coverage", path=path)
    assert len(found) == 1
    assert "'serve'" in found[0].message
    assert "report.py" in found[0].message
    # the finding lands at the offending EVENTS key's own line
    assert found[0].line == 3


def test_obs_vocab_positive_when_docs_miss_an_event(tmp_path):
    path = _vocab_tree(tmp_path, doc_has=("serve",))
    found = hits(_VOCAB_SCHEMA, "obs-vocab-coverage", path=path)
    assert len(found) == 1
    assert "'round'" in found[0].message
    assert "OBSERVABILITY.md" in found[0].message


def test_obs_vocab_positive_when_consumer_files_missing(tmp_path):
    path = _vocab_tree(tmp_path, write_report=False, write_doc=False)
    found = hits(_VOCAB_SCHEMA, "obs-vocab-coverage", path=path)
    # two missing-consumer findings; per-name findings only against
    # the consumers that could be read
    assert len(found) == 2
    assert all("missing or unreadable" in f.message for f in found)


def test_obs_vocab_ignores_other_obs_files(tmp_path):
    # the rule anchors on schema.py alone — report.py itself (which
    # contains the same names) must not trigger it
    tree = _vocab_tree(tmp_path)
    report = os.path.join(os.path.dirname(tree), "report.py")
    assert not hits(_VOCAB_SCHEMA, "obs-vocab-coverage", path=report)
    assert not hits(_VOCAB_SCHEMA, "obs-vocab-coverage")


def test_obs_vocab_suppressible(tmp_path):
    path = _vocab_tree(tmp_path, report_has=("round",))
    src = ("# graftlint: disable-file=obs-vocab-coverage -- "
           "renderer lands later in this PR\n" + _VOCAB_SCHEMA)
    assert not hits(src, "obs-vocab-coverage", path=path)
    assert suppressed_hits(src, "obs-vocab-coverage", path=path)


def test_obs_vocab_real_repo_is_covered():
    """The live schema/report/docs triple passes its own rule."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    real = os.path.join(root, "sparknet_tpu", "obs", "schema.py")
    with open(real, encoding="utf-8") as f:
        src = f.read()
    assert not hits(src, "obs-vocab-coverage", path=real)
