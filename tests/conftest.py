"""Test harness config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's in-process multi-device testing trick
(ref: caffe/src/caffe/test/test_gradient_based_solver.cpp:197-208 simulates
multi-GPU P2PSync without a cluster): we fake an 8-way TPU pod with XLA's
host-platform device-count flag so sharding/collective paths are exercised
in CI without hardware.  Must run before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# A site hook may pin JAX_PLATFORMS to a hardware plugin before conftest runs;
# the config route wins over the env var, so force CPU here too.
jax.config.update("jax_platforms", "cpu")

import threading  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


# Thread-leak gate (ISSUE 16 satellite, the threading mirror of
# test_pipeline's /dev/shm fixture): any test leaving a live NON-daemon
# thread behind fails — a leaked pump/builder thread keeps locks and
# file handles alive across tests and turns the next failure into a
# haunted one.  Daemon threads are exempt (jax/XLA runtime pools, mp
# feeder threads); named allowlist for non-daemon framework threads
# that are reaped at interpreter exit by design.
THREAD_LEAK_ALLOWLIST = (
    # concurrent.futures workers are non-daemon since 3.9 and are
    # joined by threading's atexit hook, not by the spawning test
    "ThreadPoolExecutor-",
)


@pytest.fixture(autouse=True)
def no_leaked_threads():
    before = {t.ident for t in threading.enumerate()}
    yield
    deadline = time.monotonic() + 2.0
    leaked: list = []
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before and t.is_alive()
                  and not t.daemon
                  and not t.name.startswith(THREAD_LEAK_ALLOWLIST)]
        if not leaked:
            return
        time.sleep(0.05)
    pytest.fail("test leaked live non-daemon thread(s): "
                f"{[t.name for t in leaked]}")


# Known environment drift (CHANGES.md PR 3/7): some jax builds reject
# the cross-process device_put equality check outright — the capability
# under test does not exist on this CPU backend, so the multihost tests
# skip instead of carrying a standing red that every PR re-verifies.
CPU_MULTIPROCESS_DRIFT = "Multiprocess computations aren't implemented"


def skip_if_cpu_multiprocess_drift(outs):
    """Skip the calling multihost test when any subprocess output shows
    the known CPU-backend multiprocess rejection (shared by
    test_parallel and test_utils_apps so the guard stays in one place)."""
    if any(CPU_MULTIPROCESS_DRIFT in (o or "") for o in outs):
        pytest.skip(
            "CPU backend rejects multiprocess device_put "
            "(\"Multiprocess computations aren't implemented on the "
            "CPU backend\") — known jax env drift, see CHANGES.md PR 3")
