"""Legacy proto schema migration (ref: caffe/src/caffe/util/upgrade_proto.cpp,
test cases modeled on caffe/src/caffe/test/test_upgrade_proto.cpp)."""

import jax
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.proto import parse, serialize
from sparknet_tpu.proto.upgrade import (
    net_needs_data_upgrade,
    net_needs_v0_upgrade,
    net_needs_v1_upgrade,
    upgrade_net,
    upgrade_solver,
)

# the NIPS-era V0 schema: layers { layer { ... } bottom: ... top: ... }
V0_LENET = """
name: "v0_lenet"
input: "data"
input_dim: 2 input_dim: 1 input_dim: 28 input_dim: 28
input: "label"
input_dim: 2 input_dim: 1 input_dim: 1 input_dim: 1
layers {
  layer {
    name: "conv1" type: "conv" num_output: 4 kernelsize: 5 stride: 1
    weight_filler { type: "xavier" } blobs_lr: 1.0 blobs_lr: 2.0
    weight_decay: 1.0 weight_decay: 0.0
  }
  bottom: "data" top: "conv1"
}
layers {
  layer { name: "pool1" type: "pool" pool: MAX kernelsize: 2 stride: 2 }
  bottom: "conv1" top: "pool1"
}
layers {
  layer { name: "relu1" type: "relu" }
  bottom: "pool1" top: "pool1"
}
layers {
  layer {
    name: "ip1" type: "innerproduct" num_output: 10
    weight_filler { type: "gaussian" std: 0.01 }
  }
  bottom: "pool1" top: "ip1"
}
layers {
  layer { name: "loss" type: "softmax_loss" }
  bottom: "ip1" bottom: "label" top: "loss"
}
"""

V1_SNIPPET = """
name: "v1_net"
input: "data"
input_dim: 2 input_dim: 1 input_dim: 8 input_dim: 8
input: "label"
input_dim: 2 input_dim: 1 input_dim: 1 input_dim: 1
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  blobs_lr: 1 blobs_lr: 2 weight_decay: 1 weight_decay: 0
  convolution_param { num_output: 3 kernel_size: 3
    weight_filler { type: "xavier" } }
}
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 5 weight_filler { type: "xavier" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label" }
"""


class TestV0:
    def test_detection(self):
        npz = parse(V0_LENET)
        assert net_needs_v0_upgrade(npz)
        assert not net_needs_v1_upgrade(npz)

    def test_field_moves(self):
        up = upgrade_net(parse(V0_LENET))
        layers = {l.get_str("name"): l for l in up.get_all("layer")}
        assert not up.get_all("layers")
        c1 = layers["conv1"]
        assert c1.get_str("type") == "Convolution"
        cp = c1.get_msg("convolution_param")
        assert cp.get_int("num_output") == 4
        assert [int(v) for v in cp.get_all("kernel_size")] == [5]
        assert cp.get_msg("weight_filler").get_str("type") == "xavier"
        p1 = layers["pool1"].get_msg("pooling_param")
        assert p1.get_str("pool") == "MAX"
        assert p1.get_int("kernel_size") == 2 and p1.get_int("stride") == 2
        assert layers["ip1"].get_str("type") == "InnerProduct"
        assert layers["loss"].get_str("type") == "SoftmaxWithLoss"
        # connection-level bottoms/tops preserved
        assert [str(b) for b in layers["loss"].get_all("bottom")] == ["ip1", "label"]

    def test_blobs_lr_fold(self):
        up = upgrade_net(parse(V0_LENET))
        c1 = next(l for l in up.get_all("layer") if l.get_str("name") == "conv1")
        pmsgs = c1.get_all("param")
        assert len(pmsgs) == 2
        assert pmsgs[0].get_float("lr_mult") == 1.0
        assert pmsgs[1].get_float("lr_mult") == 2.0
        assert pmsgs[1].get_float("decay_mult") == 0.0

    def test_upgraded_net_compiles_and_runs(self):
        net = Network(upgrade_net(parse(V0_LENET)), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))
        assert variables.params["conv1"][0].shape == (4, 1, 5, 5)
        feeds = {
            "data": np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32),
            "label": np.zeros((2, 1, 1, 1), np.int32),
        }
        _, _, loss = net.apply(variables, feeds, rng=jax.random.key(0))
        assert np.isfinite(float(loss))
        # lr_mult from blobs_lr reaches the solver's param specs
        specs = net.param_specs_for(variables)
        assert specs["conv1"][1].lr_mult == 2.0
        assert specs["conv1"][1].decay_mult == 0.0

    def test_network_auto_upgrades(self):
        # Network() takes the V0 message directly
        net = Network(parse(V0_LENET), Phase.TRAIN)
        assert [l.name for l in net.layers][:2] == ["conv1", "pool1"]

    def test_transform_fields_move(self):
        npz = parse(
            """
            layers {
              layer { name: "d" type: "data" source: "/x" batchsize: 4
                      scale: 0.00390625 cropsize: 24 mirror: true
                      meanfile: "/m.binaryproto" }
              top: "data" top: "label"
            }
            """
        )
        up = upgrade_net(npz)
        d = up.get_all("layer")[0]
        dp = d.get_msg("data_param")
        assert dp.get_str("source") == "/x" and dp.get_int("batch_size") == 4
        tp = d.get_msg("transform_param")
        assert tp.get_float("scale") == pytest.approx(0.00390625)
        assert tp.get_int("crop_size") == 24
        assert tp.get_bool("mirror") is True
        assert tp.get_str("mean_file") == "/m.binaryproto"

    def test_unknown_v0_field_warns_not_raises(self):
        npz = parse(
            'layers { layer { name: "r" type: "relu" num_output: 3 } '
            'bottom: "x" top: "y" }'
        )
        with pytest.warns(UserWarning, match="num_output"):
            up = upgrade_net(npz)
        assert up.get_all("layer")[0].get_str("type") == "ReLU"


class TestV1:
    def test_detection_and_types(self):
        npz = parse(V1_SNIPPET)
        assert net_needs_v1_upgrade(npz)
        up = upgrade_net(npz)
        layers = up.get_all("layer")
        assert layers[0].get_str("type") == "Convolution"
        assert layers[1].get_str("type") == "InnerProduct"
        assert layers[2].get_str("type") == "SoftmaxWithLoss"
        pmsgs = layers[0].get_all("param")
        assert [p.get_float("lr_mult") for p in pmsgs] == [1.0, 2.0]
        assert [p.get_float("decay_mult") for p in pmsgs] == [1.0, 0.0]
        # typed params carried through untouched
        assert layers[0].get_msg("convolution_param").get_int("num_output") == 3

    def test_v1_net_compiles(self):
        net = Network(parse(V1_SNIPPET), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))
        assert variables.params["conv1"][0].shape == (3, 1, 3, 3)


class TestDataUpgradeAndIdempotence:
    def test_v2_transform_move(self):
        npz = parse(
            """
            layer { name: "d" type: "Data" top: "data"
                    data_param { source: "/x" batch_size: 2 scale: 0.5
                                 crop_size: 8 mirror: true } }
            """
        )
        assert net_needs_data_upgrade(npz)
        up = upgrade_net(npz)
        d = up.get_all("layer")[0]
        assert not d.get_msg("data_param").has("scale")
        assert d.get_msg("transform_param").get_float("scale") == 0.5
        assert not net_needs_data_upgrade(up)

    def test_data_upgrade_does_not_mutate_caller(self):
        from sparknet_tpu.proto import serialize as ser

        npz = parse(
            """
            layer { name: "d" type: "Data" top: "data"
                    data_param { source: "/x" batch_size: 2 scale: 0.5 } }
            """
        )
        before = ser(npz)
        up = upgrade_net(npz)
        assert ser(npz) == before  # caller's message untouched
        assert up is not npz
        assert up.get_all("layer")[0].get_msg("transform_param").has("scale")

    def test_current_net_untouched(self):
        from sparknet_tpu import models

        m = models.lenet(2)
        before = serialize(m)
        out = upgrade_net(m)
        assert out is m
        assert serialize(out) == before


class TestSolverUpgrade:
    def test_enum_to_string(self):
        s = parse("base_lr: 0.01 solver_type: ADAM momentum: 0.9")
        up = upgrade_solver(s)
        assert up.get_str("type") == "Adam"
        assert not up.has("solver_type")

    def test_existing_type_wins(self):
        s = parse('base_lr: 0.01 type: "Nesterov"')
        assert upgrade_solver(s).get_str("type") == "Nesterov"


class TestCLI:
    def test_upgrade_net_proto_text_roundtrip(self, tmp_path, capsys):
        from sparknet_tpu.cli import main

        src = tmp_path / "v0.prototxt"
        src.write_text(V0_LENET)
        out = tmp_path / "v2.prototxt"
        assert main(["upgrade_net_proto_text", str(src), str(out)]) == 0
        # output is valid current-schema prototxt that compiles
        from sparknet_tpu.proto import parse_file

        npz = parse_file(str(out))
        assert not npz.get_all("layers")
        net = Network(npz, Phase.TRAIN)
        net.init(jax.random.PRNGKey(0))

    def test_upgrade_solver_proto_text(self, tmp_path, capsys):
        from sparknet_tpu.cli import main

        src = tmp_path / "s.prototxt"
        src.write_text("base_lr: 0.01\nsolver_type: RMSPROP\n")
        out = tmp_path / "s2.prototxt"
        assert main(["upgrade_solver_proto_text", str(src), str(out)]) == 0
        assert 'type: "RMSProp"' in out.read_text()


class TestBinaryUpgrade:
    def test_v1_binary_caffemodel_roundtrips_to_v2(self, tmp_path, capsys, rng):
        """A fabricated V1-format binary net (layers in field 2, enum
        types, blobs in field 6) upgrades to the V2 wire layout
        (ref: tools/upgrade_net_proto_binary.cpp)."""
        from sparknet_tpu.cli import main
        from sparknet_tpu.proto.binary import (
            _encode_blob,
            _len_field,
            _tag,
            _varint,
            load_caffemodel,
        )

        import struct as _struct

        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        b = rng.randn(4).astype(np.float32)
        # V1LayerParameter: bottom=2, top=3, name=4, type(enum)=5
        # (4=CONVOLUTION, 17=POOLING, 18=RELU), blobs=6, blobs_lr=7,
        # weight_decay=8, conv_param=10, pooling_param=19, include=32
        conv_param = _len_field(1, b"")  # placeholder sub-bytes below
        # ConvolutionParameter: num_output=1, kernel_size(repeated)=4
        conv_param = (_tag(1, 0) + _varint(4)) + (_tag(4, 0) + _varint(3))
        include_rule = _tag(1, 0) + _varint(0)  # NetStateRule.phase = TRAIN
        v1_conv = (
            _len_field(2, b"data") + _len_field(3, b"conv1")
            + _len_field(4, b"conv1")
            + _tag(5, 0) + _varint(4)
            + _len_field(6, _encode_blob(w))
            + _len_field(6, _encode_blob(b))
            + _tag(7, 5) + _struct.pack("<f", 1.0)
            + _tag(7, 5) + _struct.pack("<f", 2.0)
            + _tag(8, 5) + _struct.pack("<f", 1.0)
            + _tag(8, 5) + _struct.pack("<f", 0.0)
            + _tag(1002, 0) + _varint(0)   # blob_share_mode STRICT
            + _tag(1002, 0) + _varint(1)   # blob_share_mode PERMISSIVE
            + _len_field(10, conv_param)
            + _len_field(32, include_rule)
        )
        # POOLING (enum 17) — one of the values the old table mismapped
        pool_param = (_tag(1, 0) + _varint(0)) + (_tag(2, 0) + _varint(2))
        v1_pool = (
            _len_field(2, b"conv1") + _len_field(3, b"pool1")
            + _len_field(4, b"pool1")
            + _tag(5, 0) + _varint(17)
            + _len_field(19, pool_param)
        )
        v1_relu = (
            _len_field(2, b"pool1") + _len_field(3, b"pool1")
            + _len_field(4, b"relu1") + _tag(5, 0) + _varint(18)
        )
        net = (_len_field(1, b"old_net") + _len_field(2, v1_conv)
               + _len_field(2, v1_pool) + _len_field(2, v1_relu))
        src = tmp_path / "v1.caffemodel"
        src.write_bytes(net)

        out = tmp_path / "v2.caffemodel"
        assert main(["upgrade_net_proto_binary", str(src), str(out)]) == 0

        model = load_caffemodel(str(out))
        assert model.name == "old_net"
        assert [l.type for l in model.layers] == ["Convolution", "Pooling", "ReLU"]
        assert np.allclose(model.layers[0].blobs[0], w)
        assert np.allclose(model.layers[0].blobs[1], b)
        # the rewritten file is current-schema AND structurally complete:
        # parse it as a Message-equivalent by field numbers
        raw = out.read_bytes()
        from sparknet_tpu.proto.binary import _scan

        fields = [f for f, _, _ in _scan(raw)]
        assert 100 in fields and 2 not in fields
        layers = [v for f, _, v in _scan(raw) if f == 100]
        conv_fields = {f: v for f, _, v in _scan(layers[0])}
        assert conv_fields[1] == b"conv1"        # name
        assert conv_fields[3] == b"data"         # bottom
        assert conv_fields[4] == b"conv1"        # top
        assert 106 in conv_fields                # convolution_param moved
        assert 8 in conv_fields                  # include rule preserved
        # blobs_lr/weight_decay folded into ParamSpec (field 6)
        pspecs = [v for f, _, v in _scan(layers[0]) if f == 6]
        assert len(pspecs) == 2
        lr2 = [v for f, _, v in _scan(pspecs[1]) if f == 3][0]
        assert _struct.unpack("<f", _struct.pack("<i", lr2))[0] == 2.0
        # blob_share_mode folded to ParamSpec.share_mode (field 2)
        modes = [
            [v for f, _, v in _scan(pm) if f == 2] for pm in pspecs
        ]
        assert modes == [[0], [1]]
        pool_fields = {f: v for f, _, v in _scan(layers[1])}
        assert 121 in pool_fields                # pooling_param moved

    def test_empty_input_rejected(self, tmp_path):
        from sparknet_tpu.cli import main

        src = tmp_path / "empty.caffemodel"
        src.write_bytes(b"")
        with pytest.raises(SystemExit, match="no layers"):
            main(["upgrade_net_proto_binary", str(src), str(tmp_path / "o")])
