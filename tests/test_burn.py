"""Streaming burn-rate engine (sparknet_tpu/obs/burn.py): the
multi-window trip/clear contract on synthetic event streams.

The engine's CLAIMS: a bounded gate trips only when BOTH windows sit
over the level (fast catches the spike, slow proves it is not a blip);
clearing is asymmetric — the FAST window alone proves recovery, so the
slow window's 30 s memory cannot latch the alarm past the drained
backlog; disturbances suspend only the latency gate and EXPIRE; the
zero-tolerance ledgers burn on any in-window occurrence.  Every test
drives virtual time through the injectable clock — no sleeps, no wall
clock, smoke-tier.

Also pins the JournalTail rotation/truncation contract the live
``feed_tail`` path leans on (torn-tail-then-append is pinned in
tests/test_obs_metrics.py).
"""

from __future__ import annotations

import json

import pytest

from sparknet_tpu.obs import schema
from sparknet_tpu.obs.burn import (
    DEFAULT_CLEAR_RATIO,
    BurnEngine,
    GateState,
    _Window,
    _p99,
)
from sparknet_tpu.obs.metrics import JournalTail

pytestmark = pytest.mark.smoke


def _manifest(*specs) -> dict:
    return {"version": 1, "slos": list(specs)}


_P99_GATE = {"id": "warm-queue-p99", "kind": "warm_queue_p99",
             "max_ms": 40.0, "warmup_requests": 0}
_DROP_GATE = {"id": "zero-drop", "kind": "dropped_zero"}


def _engine(*specs, fast_s=1.0, slow_s=30.0, suspend_s=5.0):
    return BurnEngine(_manifest(*specs), fast_s=fast_s, slow_s=slow_s,
                      suspend_s=suspend_s, clock=lambda: 0.0)


def _request(wait_ms: float) -> dict:
    return {"model": "m", "bucket": 8, "queue_wait_ms": wait_ms}


def _state(engine: BurnEngine, gate_id: str):
    return next(g for g in engine.gates if g.gate_id == gate_id)


# -- window mechanics -------------------------------------------------------


def test_window_prunes_by_duration():
    w = _Window(1.0)
    w.add(0.0, 1.0)
    w.add(0.5, 2.0)
    w.add(1.2, 3.0)
    assert w.values(1.3) == [2.0, 3.0]  # 0.0 aged out of [0.3, 1.3]
    assert w.total(2.5) == 0.0


def test_p99_nearest_rank_small_and_large():
    assert _p99([7.0]) == 7.0
    assert _p99([1.0, 2.0, 3.0, 4.0]) == 4.0
    big = [float(i) for i in range(1, 201)]
    assert _p99(big) == 198.0  # rank round(0.99*200) = 198


# -- trip: both windows must burn -------------------------------------------


def test_fast_spike_alone_does_not_trip():
    eng = _engine(_P99_GATE)
    # long healthy history fills the slow window under the bound
    for i in range(60):
        eng.observe("request", _request(10.0), t=i * 0.5)
    # one fast-window spike: fast > 1.0 but slow p99 still healthy
    eng.observe("request", _request(500.0), t=30.0)
    [res] = eng.evaluate(30.1)
    assert res["fast"] > 1.0
    assert res["slow"] <= 1.0
    assert not res["burning"]


def test_sustained_breach_trips_both_windows():
    eng = _engine(_P99_GATE)
    for i in range(40):
        eng.observe("request", _request(90.0), t=i * 0.1)
    [res] = eng.evaluate(4.0)
    assert res["fast"] > 1.0 and res["slow"] > 1.0
    assert res["burning"]
    assert eng.burning(4.0) == ["warm-queue-p99"]


# -- clear: fast window alone, with hysteresis ------------------------------


def test_clear_on_fast_window_only():
    eng = _engine(_P99_GATE)
    for i in range(40):
        eng.observe("request", _request(90.0), t=i * 0.1)
    assert eng.burning(4.0) == ["warm-queue-p99"]
    # recovery: fast window fills with healthy waits; the slow window
    # STILL holds the 90 ms burn era (its p99 stays over the level)
    for i in range(20):
        eng.observe("request", _request(5.0), t=4.1 + i * 0.05)
    [res] = eng.evaluate(5.2)
    assert res["slow"] > 1.0  # the 30 s memory has not forgotten
    assert res["fast"] <= DEFAULT_CLEAR_RATIO
    assert not res["burning"]  # ... and yet the alarm clears


def test_clear_needs_hysteresis_margin():
    eng = _engine(_P99_GATE)
    for i in range(40):
        eng.observe("request", _request(90.0), t=i * 0.1)
    assert eng.burning(4.0) == ["warm-queue-p99"]
    # fast p99 drops to 0.95x the level: under trip, but NOT under the
    # 0.9 clear ratio — the latch must hold
    for i in range(20):
        eng.observe("request", _request(38.0), t=4.1 + i * 0.05)
    [res] = eng.evaluate(5.2)
    assert DEFAULT_CLEAR_RATIO < res["fast"] <= 1.0
    assert res["burning"]


def test_empty_fast_window_clears_a_latched_gate():
    eng = _engine(_P99_GATE)
    for i in range(40):
        eng.observe("request", _request(90.0), t=i * 0.1)
    assert eng.burning(4.0) == ["warm-queue-p99"]
    # traffic stops entirely: the fast window empties — no evidence of
    # continued burn means the alarm releases
    [res] = eng.evaluate(10.0)
    assert res["fast"] is None
    assert not res["burning"]


# -- disturbance suspension -------------------------------------------------


def test_disturbance_suspends_latency_gate_then_expires():
    eng = _engine(_P99_GATE, _DROP_GATE, suspend_s=5.0)
    for i in range(40):
        eng.observe("request", _request(90.0), t=i * 0.1)
    assert eng.burning(4.0) == ["warm-queue-p99"]
    # a replica join lands: elevated waits are by design for suspend_s
    eng.observe("replica", {"kind": "replica_up"}, t=4.5)
    res = {r["id"]: r for r in eng.evaluate(4.6)}
    assert res["warm-queue-p99"]["suspended"]
    assert not res["warm-queue-p99"]["burning"]
    # ... but suspension EXPIRES: the breach persists past the settle
    # window and the gate re-arms
    for i in range(40):
        eng.observe("request", _request(90.0), t=9.6 + i * 0.01)
    res = {r["id"]: r for r in eng.evaluate(10.1)}
    assert not res["warm-queue-p99"]["suspended"]
    assert res["warm-queue-p99"]["burning"]


def test_suspension_does_not_cover_zero_bound_gates():
    eng = _engine(_P99_GATE, _DROP_GATE)
    eng.observe("replica", {"kind": "replica_up"}, t=0.0)
    eng.observe("replica", {"kind": "summary", "dropped": 3}, t=0.1)
    assert eng.burning(0.2) == ["zero-drop"]


# -- zero-tolerance immediacy -----------------------------------------------


def test_dropped_burns_on_single_occurrence():
    eng = _engine(_DROP_GATE)
    [res] = eng.evaluate(0.0)
    assert not res["burning"]  # applicable by absence: quiet is healthy
    eng.observe("serve", {"kind": "summary", "dropped": 1}, t=0.5)
    assert eng.burning(0.6) == ["zero-drop"]
    # the occurrence ages out of BOTH windows -> clears
    assert eng.burning(100.0) == []


def test_unexpected_recompile_burns_compiles_gate():
    eng = _engine({"id": "post-warmup-compiles", "kind": "compiles_zero"})
    eng.observe("recompile", {"expected": True, "count": 1}, t=0.0)
    assert eng.burning(0.1) == []  # expected compiles are by design
    eng.observe("recompile", {"expected": False, "count": 1}, t=0.2)
    assert eng.burning(0.3) == ["post-warmup-compiles"]


def test_warmup_requests_skipped_per_model_bucket():
    spec = dict(_P99_GATE, warmup_requests=2)
    eng = _engine(spec)
    state = _state(eng, "warm-queue-p99")
    for i in range(2):  # warmup: never folded
        eng.observe("request", _request(900.0), t=i * 0.1)
    assert state.fast.values(0.2) == []
    eng.observe("request", _request(900.0), t=0.3)  # first counted
    assert state.fast.values(0.4) == [900.0]


# -- feed / feed_tail -------------------------------------------------------


def test_feed_tail_folds_journal_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        for _ in range(12):
            f.write(json.dumps({"event": "serve", "kind": "summary",
                                "dropped": 1}) + "\n")
    eng = _engine(_DROP_GATE)
    assert eng.feed_tail(JournalTail(str(path)), t=1.0) == 12
    assert eng.burning(1.1) == ["zero-drop"]


def test_journal_tail_truncation_resets_cursor(tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        for i in range(5):
            f.write(json.dumps({"event": "a", "i": i}) + "\n")
    tail = JournalTail(str(path))
    assert len(list(tail.poll())) == 5
    # a fresh run re-arms the same path: the file SHRINKS underneath
    # the tail — the cursor must reset to 0 and re-read from the top
    with open(path, "w") as f:
        f.write(json.dumps({"event": "b"}) + "\n")
    got = [ev["event"] for ev in tail.poll()]
    assert got == ["b"]


def test_journal_tail_rotation_replaced_file(tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        for i in range(3):
            f.write(json.dumps({"event": "old", "i": i}) + "\n")
    tail = JournalTail(str(path))
    assert len(list(tail.poll())) == 3
    # rotate: the path is replaced by a shorter successor file
    rotated = tmp_path / "j.jsonl.1"
    path.rename(rotated)
    with open(path, "w") as f:
        f.write(json.dumps({"event": "new"}) + "\n")
    assert [ev["event"] for ev in tail.poll()] == ["new"]


# -- the ctl event family is schema-valid -----------------------------------


def test_ctl_events_validate():
    for fields in (
        {"kind": "observe", "t": 1.0, "gates": [], "burning": []},
        {"kind": "decide", "t": 1.0, "gate": "warm-queue-p99",
         "action": "join_replica", "reason": "why", "fast": 1.2,
         "slow": 1.1},
        {"kind": "act", "t": 1.0, "action": "join_replica",
         "replica": 2, "width": 3, "fits": True},
        {"kind": "act", "t": 2.0, "action": "lend_width",
         "from_width": 6, "to_width": 5, "count": 1, "round": 8},
        {"kind": "cooldown", "t": 1.0, "gate": "warm-queue-p99",
         "cooldown_s": 2.5, "note": "suppressed"},
        {"kind": "summary", "t": 9.0, "ok": True, "observes": 4,
         "decides": 1, "acts": 1, "cooldowns": 0, "refused": 0,
         "burning": []},
    ):
        line = schema.make_event("ctl", run_id="t", **fields)
        assert schema.validate_line(line) == [], fields


def test_gate_state_rejects_nothing_silently():
    # an event the gate does not subscribe to must not perturb state
    g = GateState(dict(_P99_GATE), 1.0, 30.0)
    g.fold("feed", {"stages": {"slot_wait": 1.0}}, 0.0)
    assert g.fast.values(0.1) == []
