"""Slow-tier NCHW↔NHWC equivalence sweep over every zoo conv model.

The smoke twin (tests/test_layout.py) gates the headline shape
(zoo:alexnet); this sweep demands the same contract from the whole conv
zoo — same seeded params (layout-invariant wire order), same canonical
feed bytes, one SGD step per layout, loss AND post-step params allclose.
Covers every layer family the layout touches: grouped + depthwise
convs, LRN (ACROSS and WITHIN channel), BatchNorm/Scale, global and
ceil-mode pooling, Slice/Concat DAGs (siamese, inception, fire), the
fc-as-conv boundary, and dropout's canonical-order mask.

BN models accumulate their batch moments over a permuted axis order
under nhwc, so their tolerance is loose-ish (f32 summation order);
everything else matches near-exactly.
"""

import dataclasses

import numpy as np
import pytest

from sparknet_tpu.common import get_config, set_config
from sparknet_tpu.models import zoo
from sparknet_tpu.ops.layout import to_internal
from sparknet_tpu.solvers.solver import Solver

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _restore_layout():
    prior = get_config().layout
    yield
    set_config(layout=prior)


def _lr(solver_cfg, lr=1e-3):
    return dataclasses.replace(solver_cfg, base_lr=lr)


# name -> (net(B), solver_cfg(), feed builder, rtol)
CASES = {
    "lenet": (
        lambda B: zoo.lenet(B), zoo.lenet_solver, (1, 28, 28), 10, 1e-5),
    "cifar10_quick": (
        lambda B: zoo.cifar10_quick(B), zoo.cifar10_quick_solver,
        (3, 32, 32), 10, 1e-5),
    # WITHIN_CHANNEL LRN + ACROSS both live here
    "cifar10_full": (
        lambda B: zoo.cifar10_full(B), zoo.cifar10_full_solver,
        (3, 32, 32), 10, 1e-5),
    "alexnet": (
        lambda B: zoo.alexnet(B, 10, crop=63),
        zoo.alexnet_solver, (3, 63, 63), 10, 1e-5),
    "caffenet": (
        lambda B: zoo.caffenet(B, 10, crop=63),
        zoo.caffenet_solver, (3, 63, 63), 10, 1e-5),
    "vgg16": (
        lambda B: zoo.vgg16(B, 5, crop=32, msra_init=True),
        lambda: _lr(zoo.vgg16_solver()), (3, 32, 32), 5, 1e-5),
    "squeezenet": (
        lambda B: zoo.squeezenet(B, 5, crop=64, msra_init=True),
        lambda: _lr(zoo.squeezenet_solver()), (3, 64, 64), 5, 1e-5),
    # depthwise group conv + BN/Scale chains
    "mobilenet": (
        lambda B: zoo.mobilenet(batch=B, num_classes=5, crop=64),
        lambda: _lr(zoo.mobilenet_solver()), (3, 64, 64), 5, 5e-4),
    # bottleneck BN everywhere
    "resnet50": (
        lambda B: zoo.resnet50(batch=B, num_classes=5, crop=64),
        lambda: _lr(zoo.resnet50_solver()), (3, 64, 64), 5, 5e-4),
    # published geometry only: the aux heads' 5x5/3 pools and the final
    # 7x7 pool pin the 224 crop
    "googlenet": (
        lambda B: zoo.googlenet(B, 10, crop=224),
        zoo.googlenet_solver, (3, 224, 224), 10, 1e-5),
}


def _one_step(lay, make_net, make_cfg, feeds, B):
    set_config(layout=lay)
    solver = Solver(make_cfg(), make_net(B))
    internal = {k: to_internal(v) for k, v in feeds.items()}
    loss = solver.step(1, lambda it: internal)
    return loss, solver


@pytest.mark.parametrize("name", sorted(CASES))
def test_zoo_conv_model_layout_equivalence(name):
    make_net, make_cfg, shape, ncls, rtol = CASES[name]
    B = 1 if name == "googlenet" else 2
    rs = np.random.RandomState(11)
    feeds = {
        "data": (rs.randn(B, *shape) * 10).astype(np.float32),
        "label": rs.randint(0, ncls, B).astype(np.int32),
    }
    loss_c, solver_c = _one_step("nchw", make_net, make_cfg, feeds, B)
    loss_h, solver_h = _one_step("nhwc", make_net, make_cfg, feeds, B)
    assert np.allclose(loss_c, loss_h, rtol=rtol, atol=rtol), (
        name, loss_c, loss_h)
    for lname, plist in solver_c.variables.params.items():
        for i, (p_c, p_h) in enumerate(
                zip(plist, solver_h.variables.params[lname])):
            np.testing.assert_allclose(
                np.asarray(p_c), np.asarray(p_h), rtol=rtol, atol=rtol,
                err_msg=f"{name}: post-step params diverge at "
                        f"{lname}[{i}]")


def test_siamese_slice_dag_layout_equivalence():
    """mnist_siamese: the pair blob is rank-4 with channel=2 pairs —
    Slice on canonical axis 1 must cut the internal channel axis."""
    B = 4
    rs = np.random.RandomState(11)
    feeds = {
        "pair_data": rs.randn(B, 2, 28, 28).astype(np.float32),
        "sim": rs.randint(0, 2, B).astype(np.float32),
    }
    out = {}
    for lay in ("nchw", "nhwc"):
        set_config(layout=lay)
        solver = Solver(zoo.mnist_siamese_solver(), zoo.mnist_siamese(B))
        internal = {k: to_internal(v) for k, v in feeds.items()}
        out[lay] = solver.step(1, lambda it: internal)
    assert np.allclose(out["nchw"], out["nhwc"], rtol=1e-5, atol=1e-6), out
