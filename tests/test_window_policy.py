"""tools/window_policy.py + tools/sched_sim.py — the survival scheduler.

Pure host-side logic (stdlib by contract: the runner imports the policy
while babysitting a wedged relay), so the whole surface pins chip-free
and rides the smoke tier: the Kaplan-Meier estimator's censoring
arithmetic, the journal parser's window/heal extraction (including the
restart-bridge rule every observed heal depends on), the pick's
value x P(survive) ordering with its hard traces-last constraint, the
seeded replay gate's determinism, and the `sched` journal vocabulary.
"""

import importlib.util
import json
import os
import sys
import time

import pytest

pytestmark = pytest.mark.smoke

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def wp():
    return _load("window_policy")


@pytest.fixture(scope="module")
def sim():
    return _load("sched_sim")


# journal fixtures: hand-built events with real wall stamps, the same
# format every banked journal uses
BASE = 1700000000


def _ev(kind, t, **kw):
    utc = time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime(BASE + t))
    return {"event": kind, "utc": utc, **kw}


# -- KaplanMeier ------------------------------------------------------------


def test_km_all_observed_steps(wp):
    km = wp.KaplanMeier([10.0, 20.0, 30.0], [True, True, True])
    assert km.n == 3 and km.events == 3
    assert km.survival(5) == 1.0
    assert km.survival(15) == pytest.approx(2 / 3)
    assert km.survival(25) == pytest.approx(1 / 3)
    assert km.survival(30) == 0.0
    assert km.quantile(0.5) == 20.0


def test_km_censoring_shrinks_risk_set_not_the_curve(wp):
    """A censored duration leaves the curve flat but removes the subject
    from the risk set — the whole point of the estimator (dropping
    censored windows would bias lifetimes short; module doc)."""
    km = wp.KaplanMeier([10.0, 20.0, 30.0], [True, False, True])
    assert km.events == 2
    assert km.survival(15) == pytest.approx(2 / 3)
    assert km.survival(25) == pytest.approx(2 / 3)  # no step at 20
    # the death at 30 faces a risk set of ONE (censoring ate the other)
    assert km.survival(30) == 0.0


def test_km_conditional_decays_and_caps_at_one(wp):
    km = wp.KaplanMeier([10.0, 20.0, 30.0], [True, True, True])
    assert km.conditional(0, 15) == pytest.approx(km.survival(15))
    assert 0.0 <= km.conditional(12, 10) <= 1.0
    # a window that outlived every observation keeps decaying via the
    # exponential tail instead of becoming immortal
    assert km.conditional(30, 10) < 1.0 or km.survival(40) == 0.0


def test_km_tail_extrapolates_past_support(wp):
    km = wp.KaplanMeier([100.0, 200.0], [True, False])
    s_end = km.survival(200.0)
    assert km.survival(400.0) < s_end  # hazard keeps running
    assert km.survival(400.0) > 0.0


def test_km_sample_is_monotone_and_capped(wp):
    km = wp.KaplanMeier([100.0, 200.0, 300.0], [True, True, False])
    lo, hi = km.sample(0.9), km.sample(0.1)
    assert lo <= hi            # higher survival draw -> shorter duration
    assert hi <= km.t_max * 4  # censored-heavy curves cannot blow up
    assert km.sample(0.5) > 0.0


def test_km_censored_only_curve_never_dies(wp):
    km = wp.KaplanMeier([100.0], [False])
    assert km.events == 0 and km.steps == []
    assert km.survival(1e6) == 1.0  # no basis for a death rate


# -- parse_history ----------------------------------------------------------


def test_parse_observed_window_and_wedge_start(wp):
    events = [
        _ev("dial_start", 0, probe=1),
        _ev("dial_end", 10, probe=1, ok=True),
        _ev("job_start", 12, job="a", argv=["python", "bench.py"]),
        _ev("job_end", 100, job="a", rc=None, timed_out=True, dt_s=88),
    ]
    h = wp.parse_history(events)
    assert h.windows == [(90.0, True)]  # healthy dial_end -> the kill
    # the wedge starts at the death; EOF closes the streak censored
    assert h.heals and h.heals[-1][1] is False
    assert [seg["kind"] for seg in h.trace] == ["window", "dead"]


def test_parse_censored_window_at_next_dial(wp):
    events = [
        _ev("dial_start", 0, probe=1),
        _ev("dial_end", 10, probe=1, ok=True),
        _ev("job_start", 12, job="a", argv=["python", "bench.py"]),
        _ev("job_end", 50, job="a", rc=0, dt_s=38),
        _ev("dial_start", 200, probe=2),  # window still healthy here
    ]
    h = wp.parse_history(events)
    # censored at its LAST ACTIVITY (the green job_end), not the next
    # dial's stamp — the 150 s idle gap was not observed window life
    assert h.windows == [(40.0, False)]


def test_parse_restart_bridges_short_gap_censors_long(wp):
    """Every observed heal in r4/r5 straddles a runner restart; a
    restart under RESTART_BRIDGE_S continues the wedge, a longer gap
    closes the streak censored (module doc)."""
    short = [
        _ev("dial_start", 0, probe=1),
        _ev("dial_end", 1505, probe=1, ok=False),
        _ev("runner_start", 3000, queue="q", jobs=[]),  # gap 1495 s
        _ev("dial_start", 3010, probe=2),
        _ev("dial_end", 3020, probe=2, ok=True),
    ]
    h = wp.parse_history(short)
    assert h.heals == [(3020.0, True)]  # first dead DIAL_START -> heal
    long = [
        _ev("dial_start", 0, probe=1),
        _ev("dial_end", 1505, probe=1, ok=False),
        _ev("runner_start", 20000, queue="q", jobs=[]),  # gap > bridge
        _ev("dial_start", 20010, probe=2),
        _ev("dial_end", 20020, probe=2, ok=True),
    ]
    h = wp.parse_history(long)
    # the streak closed censored at the last pre-restart stamp; no
    # observed heal survives the offline stretch
    assert (1505.0, False) in h.heals
    assert not any(obs for _, obs in h.heals)


def test_parse_trailing_streak_closes_censored_at_eof(wp):
    events = [
        _ev("dial_start", 0, probe=1),
        _ev("dial_end", 1505, probe=1, ok=False),
        _ev("dial_start", 1600, probe=2),
        _ev("dial_end", 3100, probe=2, ok=False),
    ]
    h = wp.parse_history(events)
    assert h.heals == [(3100.0, False)]  # still wedged when journal ends


def test_parse_setup_jobs_never_touch_windows(wp):
    events = [
        _ev("job_start", 0, job="fix", argv=["python", "x.py"], setup=True),
        _ev("job_end", 5, job="fix", rc=0, dt_s=5, setup=True),
    ]
    h = wp.parse_history(events)
    assert h.windows == [] and h.runs == []


# -- RuntimeModel -----------------------------------------------------------


def test_runtime_fallback_chain(wp):
    m = wp.RuntimeModel()
    job = {"name": "bench_x", "argv": ["python", "-u", "bench.py"],
           "deadline_s": 600}
    assert m.estimate(job) == 300.0  # nothing known: half the deadline
    job["est_runtime_s"] = 120
    assert m.estimate(job) == 120.0  # declared beats the prior
    m.observe("other_bench", "bench.py", 80.0, 0)
    del job["est_runtime_s"]
    assert m.estimate(job) == 80.0   # tool pool beats the prior
    job["est_runtime_s"] = 120
    assert m.estimate(job) == 120.0  # declared beats the tool pool
    m.observe("bench_x", "bench.py", 45.0, 0)
    assert m.estimate(job) == 45.0   # own history beats everything


def test_runtime_ignores_failed_runs(wp):
    m = wp.RuntimeModel()
    m.observe("j", "t.py", 500.0, 1)     # failure
    m.observe("j", "t.py", 500.0, None)  # deadline kill
    assert not m.by_name  # neither is evidence of a working runtime


# -- SurvivalScheduler ------------------------------------------------------


def _sched(wp, window=None, heal=None):
    return wp.SurvivalScheduler(
        window or wp.KaplanMeier([600.0, 1200.0], [True, True]),
        heal or wp.KaplanMeier([3200.0], [True]),
        wp.RuntimeModel(), [])


def _job(name, value, est, trace=False):
    argv = ["python", "-u", "bench.py"] + (["--trace"] if trace else [])
    return {"name": name, "argv": argv, "deadline_s": 900,
            "value": value, "est_runtime_s": est}


def test_pick_maximizes_value_times_survival(wp):
    s = _sched(wp)
    jobs = [_job("cheap_low", 2, 100), _job("cheap_high", 8, 100)]
    job, d = s.pick(jobs, age_s=0.0)
    assert job["name"] == "cheap_high"
    assert d["policy"] == "survival" and d["candidates"] == 2
    assert d["score"] == pytest.approx(8 * s.p_survive(0, 100), abs=1e-3)


def test_pick_reorders_as_the_window_ages(wp):
    """Late in the window a long job's survival collapses while a short
    one still fits — the whole reason the policy re-plans per pick."""
    s = _sched(wp)
    jobs = [_job("long_big", 10, 900), _job("short_small", 4, 60)]
    early, _ = s.pick(jobs, age_s=0.0)
    late, _ = s.pick(jobs, age_s=550.0)
    assert early["name"] == "long_big"
    assert late["name"] == "short_small"


def test_pick_holds_traces_for_last(wp):
    s = _sched(wp)
    jobs = [_job("trace_hot", 100, 10, trace=True), _job("bench", 1, 800)]
    job, _ = s.pick(jobs, age_s=0.0)
    assert job["name"] == "bench"  # value cannot buy a trace an early slot
    job, d = s.pick([jobs[0]], age_s=0.0)
    assert job["name"] == "trace_hot"  # only traces left: eligible now


def test_pick_tie_goes_to_cheaper_estimate(wp):
    # censored-only curve: survival == 1 everywhere, so equal values tie
    s = _sched(wp, window=wp.KaplanMeier([1000.0], [False]))
    jobs = [_job("pricey", 5, 700), _job("thrifty", 5, 200)]
    job, _ = s.pick(jobs, age_s=0.0)
    assert job["name"] == "thrifty"  # equal expected value: gamble less


def test_observe_reprices_mid_window(wp):
    s = _sched(wp)
    job = _job("bench", 5, 60)
    s.observe(job, 590.0, 0)  # ran 10x the declared estimate
    assert s.runtime.estimate(job) == 590.0


def test_redial_delay_exponential_with_caps(wp):
    s = _sched(wp)  # heal median 3200 -> base clamps to the 120 s floor
    assert s.heal_median_s == 3200.0
    assert s.redial_delay(1) == 120.0
    assert s.redial_delay(2) == 240.0
    assert s.redial_delay(3) == 480.0
    assert s.redial_delay(10) == wp.BACKOFF_CAP_S  # capped at 30 min
    # zero observed heals: the default hours-scale wedge shape seeds it
    s2 = _sched(wp, heal=wp.KaplanMeier([100.0], [False]))
    assert s2.heal_median_s == wp.DEFAULT_HEAL_MEDIAN_S


def test_fit_from_real_banked_journals(wp):
    """The committed evidence_r* journals must keep fitting: they are
    the curve every --policy survival run prices against."""
    s = wp.SurvivalScheduler.fit()
    d = s.describe()
    assert d["windows"] >= 1 and d["window_deaths"] >= 1
    assert d["heals"] >= 1
    assert d["median_window_s"] > 0
    assert d["sources"]  # relpaths, journaled for provenance


# -- sched vocabulary -------------------------------------------------------


def test_sched_event_kinds_are_schema_valid():
    from sparknet_tpu.obs import schema

    samples = [
        {"kind": "fit", "policy": "survival", "windows": 4,
         "window_deaths": 3, "median_window_s": 1968.0, "heals": 6,
         "heals_observed": 2, "heal_median_s": 41857.0, "sources": []},
        {"kind": "pick", "policy": "survival", "job": "headline_bench",
         "probe": 3, "window_age_s": 12.0, "est_runtime_s": 300.0,
         "p_survive": 0.61, "value": 10.0, "score": 6.1,
         "candidates": 5},
        {"kind": "window_summary", "policy": "survival", "probe": 3,
         "window_age_s": 900.0, "expected_value": 12.2,
         "banked_value": 10.0, "jobs_banked": 2},
        {"kind": "redial_backoff", "policy": "survival", "delay_s": 240.0,
         "consecutive_dead": 2, "heal_median_s": 41857.0},
    ]
    for fields in samples:
        ev = schema.make_event("sched", **fields)
        assert schema.validate_line(ev) == [], fields["kind"]


# -- sched_sim (the replay gate) --------------------------------------------


def test_sched_sim_gate_holds_and_is_deterministic(sim):
    """The banked claim itself: never worse than cheap-first on any
    replayed history, strictly better on a wedge-heavy one — and the
    record is a pure function of (queue, seed), so the banked JSON is
    reproducible byte-for-byte."""
    a = sim.run(sim.DEFAULT_QUEUE, seed=801)
    b = sim.run(sim.DEFAULT_QUEUE, seed=801)
    assert a == b
    assert a["ok"] and a["policy_never_worse"]
    assert a["strictly_better_on_wedge_heavy"]
    assert a["chip_free"] and a["host_side"]
    assert any(r["wedge_heavy"] for r in a["histories"])


def test_sched_sim_banked_record_matches_live_run(sim):
    """docs/sched_sim_last.json must be regeneratable: a stale bank
    (code moved, record didn't) would misstate the gate's margin."""
    with open(sim.LAST_PATH) as f:
        banked = json.load(f)
    live = sim.run(sim.DEFAULT_QUEUE, seed=banked["seed"])
    assert banked["histories"] == live["histories"]
    assert banked["ok"] is True


def test_sched_sim_jitter_is_coordinate_keyed(sim):
    """Both arms must face identical physics: the jitter is keyed by
    (seed, history, job, window), never drawn from a shared sequence
    whose consumption order differs between arms."""
    assert sim._jitter(1, "h", "j", 2) == sim._jitter(1, "h", "j", 2)
    assert sim._jitter(1, "h", "j", 2) != sim._jitter(1, "h", "j", 3)
    assert 0.85 <= sim._jitter(9, "x", "y", 0) < 1.25
