"""Subprocess body for the multi-host (DCN) distributed test.

Two of these processes form a 2-process x 4-device CPU cluster — the
in-CI stand-in for two TPU hosts on DCN (ref: SURVEY §2.4: multi-host
orchestration via jax.distributed.initialize; the reference's analog is
Spark driver + executors over TCP).  Each process feeds only its own
batch shard, runs sync-DP and tau-averaging rounds through
ParallelTrainer, and prints a parameter digest the parent test compares
across processes (replicas must agree bit-for-bit).

Usage: python multihost_worker.py <process_id> <coordinator_port> [ckpt_dir]
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    pid, port = int(sys.argv[1]), int(sys.argv[2])

    from sparknet_tpu.parallel.mesh import (
        data_parallel_mesh,
        initialize_distributed,
    )

    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8  # 2 hosts x 4 local devices

    from sparknet_tpu import models
    from sparknet_tpu.parallel.trainer import ParallelTrainer
    from sparknet_tpu.solvers.solver import Solver

    mesh = data_parallel_mesh()
    per_proc = 8  # global batch 16, 2 per device
    rs = np.random.RandomState(100 + pid)  # different data per host

    def batch(b):
        return {
            "data": (rs.randn(b, 3, 32, 32) * 40).astype(np.float32),
            "label": rs.randint(0, 10, b).astype(np.int32),
        }

    # Mode 1: tau=1 sync DP, global batch assembled from per-process shards.
    solver = Solver(models.cifar10_quick_solver(), models.cifar10_quick(16))
    trainer = ParallelTrainer(solver, mesh=mesh, tau=1)
    loss = trainer.train(3, lambda it: batch(per_proc))
    assert np.isfinite(loss), loss

    # Mode 1b: dispatch-batched sync DP (round-4 scan path) — two fused
    # rounds in one program; per-process shards still assemble the
    # global batch, and the cross-host digest below proves the replicas
    # stayed identical through it.
    loss_scan = trainer.train_rounds(2, lambda it: batch(per_proc))
    assert np.isfinite(loss_scan), loss_scan

    # Mode 2: tau=2 local SGD + model averaging.
    tau = 2
    solver2 = Solver(models.cifar10_quick_solver(), models.cifar10_quick(2))
    trainer2 = ParallelTrainer(solver2, mesh=mesh, tau=tau)
    feeds = [batch(per_proc) for _ in range(tau)]
    stacked = {k: np.stack([f[k] for f in feeds]) for k in feeds[0]}
    loss2 = trainer2.train_round(lambda it: stacked)
    assert np.isfinite(loss2), loss2

    # Parameter digest: replicas must be identical on every host.  Sum
    # THIS process's local shard data only (addressable_shards) so each
    # host's digest provably reflects its own replica — a global reduce
    # could be satisfied from either host's copy.

    def digest_of(tree):
        total = 0.0
        for leaf in jax.tree_util.tree_leaves(tree):
            total += float(
                np.sum(np.asarray(leaf.addressable_shards[0].data, np.float64))
            )
        return total

    digest = digest_of(trainer.variables.params)
    digest2 = digest_of(trainer2.variables.params)

    # Distributed checkpoint: every process writes its own shards, and a
    # fresh trainer restores them with the live shardings.
    try:
        import orbax.checkpoint  # noqa: F401

        base = sys.argv[3] if len(sys.argv) > 3 else f"/tmp/mh_ckpt_{port}"
        ckpt = trainer2.save(os.path.join(base, "live") if len(sys.argv) > 3 else base)
        fresh = ParallelTrainer(
            Solver(models.cifar10_quick_solver(), models.cifar10_quick(2)),
            mesh=mesh,
            tau=tau,
        )
        fresh.restore(ckpt)
        assert fresh.iter == trainer2.iter
        assert abs(digest_of(fresh.variables.params) - digest2) < 1e-6
        # both processes finish restoring before process 0 removes the
        # directory (standalone runs have no parent to clean up)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt_done")
        if pid == 0 and len(sys.argv) <= 3:
            import shutil

            shutil.rmtree(ckpt, ignore_errors=True)
        print(f"CKPT {pid} ok", flush=True)
    except ImportError:
        print(f"CKPT {pid} skipped", flush=True)
    print(f"DIGEST {pid} {digest:.10e} {digest2:.10e} {loss:.6f} {loss2:.6f}", flush=True)


if __name__ == "__main__":
    main()
