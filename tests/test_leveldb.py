"""Clean-room LevelDB codec: round-trips + byte-level format invariants.

ref: caffe/src/caffe/util/db_leveldb.cpp (the reference's LevelDB
Cursor/Transaction).  No libleveldb exists in this environment, so the
format is pinned the same two ways as the LMDB codec: round-trips
through our own reader/writer, and byte-level invariants against the
published on-disk layout (log record framing + CRC32C masking, SSTable
footer magic, VersionEdit tags, snappy block encoding).
"""

import os
import struct

import numpy as np
import pytest

from sparknet_tpu.data import leveldb_io
from sparknet_tpu.data.leveldb_io import (
    LevelDbReader,
    LevelDbWriter,
    crc32c,
    crc_mask,
    crc_unmask,
    is_leveldb,
    snappy_decompress,
)


def _write(path, items, sst=False):
    with LevelDbWriter(str(path), sst=sst) as w:
        for k, v in items:
            w.put(k, v)
    return str(path)


class TestPrimitives:
    def test_crc32c_known_vectors(self):
        # RFC 3720 test vectors for CRC32C
        assert crc32c(b"") == 0x00000000
        assert crc32c(b"\x00" * 32) == 0x8A9136AA
        assert crc32c(b"\xff" * 32) == 0x62A8AB43
        assert crc32c(bytes(range(32))) == 0x46DD794E

    def test_crc_mask_roundtrip(self):
        for v in (0, 1, 0xDEADBEEF, 0xFFFFFFFF):
            assert crc_unmask(crc_mask(v)) == v
        # masking must actually change the value (the point of the mask)
        assert crc_mask(0x12345678) != 0x12345678

    def test_snappy_literal(self):
        # tag: literal, len 5-1=4 -> (4<<2)|0
        src = bytes([5, (4 << 2) | 0]) + b"hello"
        assert snappy_decompress(src) == b"hello"

    def test_snappy_copy1_rle(self):
        # "aaaaaaaa": literal 'a' then copy1 len 7 offset 1 (overlap RLE)
        src = bytes([8, (0 << 2) | 0]) + b"a" + bytes([((7 - 4) << 2) | 1, 1])
        assert snappy_decompress(src) == b"a" * 8

    def test_snappy_copy2(self):
        # "abcdabcd": literal "abcd", copy2 len 4 offset 4
        src = (bytes([8]) + bytes([(3 << 2) | 0]) + b"abcd"
               + bytes([((4 - 1) << 2) | 2]) + struct.pack("<H", 4))
        assert snappy_decompress(src) == b"abcdabcd"

    def test_snappy_length_mismatch_rejected(self):
        src = bytes([9, (4 - 1) << 2]) + b"hell"
        with pytest.raises(ValueError, match="declared"):
            snappy_decompress(src)

    def test_log_fragmentation_roundtrip(self):
        # a payload spanning >2 blocks exercises FIRST/MIDDLE/LAST
        big = os.urandom(70_000)
        raw = leveldb_io._write_log_records([b"small", big, b"tail"])
        assert len(raw) > 2 * leveldb_io.BLOCK_SIZE
        got = list(leveldb_io._log_records(raw))
        assert got == [b"small", big, b"tail"]

    def test_log_crc_detects_corruption(self):
        raw = bytearray(leveldb_io._write_log_records([b"payload"]))
        raw[9] ^= 0xFF  # flip a payload byte
        with pytest.raises(ValueError, match="CRC"):
            list(leveldb_io._log_records(bytes(raw)))


class TestRoundTrip:
    def test_log_only_db(self, tmp_path):
        items = [(f"{i:08d}".encode(), f"value-{i}".encode()) for i in range(7)]
        p = _write(tmp_path / "db", items)
        assert is_leveldb(p)
        with LevelDbReader(p) as r:
            assert len(r) == 7
            assert list(r) == items

    def test_sst_db(self, tmp_path):
        items = [(f"{i:08d}".encode(), os.urandom(40)) for i in range(500)]
        p = _write(tmp_path / "db", items, sst=True)
        with LevelDbReader(p) as r:
            assert len(r) == 500
            assert list(r) == sorted(items)

    def test_sst_compressed_blocks(self, tmp_path):
        """compress=True writes snappy blocks (kept only when they
        shrink >=12.5%, table_builder.cc rule) that read back exactly."""
        # repetitive values compress well; random ones stay raw
        items = [(f"{i:08d}".encode(), bytes([i % 7]) * 500)
                 for i in range(64)]
        p = str(tmp_path / "db")
        with LevelDbWriter(p, sst=True, compress=True) as w:
            for k, v in items:
                w.put(k, v)
        raw = open(os.path.join(p, "000005.ldb"), "rb").read()
        uncompressed_size = sum(len(k) + len(v) + 8 for k, v in items)
        assert len(raw) < uncompressed_size // 2  # compression engaged
        with LevelDbReader(p) as r:
            assert dict(r) == dict(items)

    def test_sst_multi_block(self, tmp_path):
        # values big enough to force several 4 KiB data blocks
        items = [(f"{i:08d}".encode(), os.urandom(900)) for i in range(64)]
        p = _write(tmp_path / "db", items, sst=True)
        with LevelDbReader(p) as r:
            assert dict(r) == dict(items)

    def test_duplicate_key_last_wins(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"first"), (b"k", b"second")])
        with LevelDbReader(p) as r:
            assert dict(r) == {b"k": b"second"}

    def test_empty_db(self, tmp_path):
        p = _write(tmp_path / "db", [])
        with LevelDbReader(p) as r:
            assert len(r) == 0

    def test_log_overrides_sst(self, tmp_path):
        """Memtable (log) entries are newer than flushed tables: the log
        replay must win — the recovery-order rule."""
        p = _write(tmp_path / "db", [(b"k", b"old"), (b"z", b"zv")], sst=True)
        # append a live log with a higher sequence updating k
        batch = leveldb_io._encode_batch(100, [(b"k", b"new")])
        with open(os.path.join(p, "000006.log"), "wb") as f:
            f.write(leveldb_io._write_log_records([batch]))
        with LevelDbReader(p) as r:
            assert dict(r) == {b"k": b"new", b"z": b"zv"}

    def test_deletion_drops_key(self, tmp_path):
        p = _write(tmp_path / "db", [(b"a", b"1"), (b"b", b"2")])
        # hand-build a deletion batch in the live log (seq above writer's)
        payload = bytearray(struct.pack("<QI", 50, 1))
        payload.append(0)  # kTypeDeletion
        payload.append(1)  # varint key len
        payload += b"a"
        raw = open(os.path.join(p, "000003.log"), "rb").read()
        with open(os.path.join(p, "000003.log"), "wb") as f:
            f.write(raw + leveldb_io._write_log_records([bytes(payload)]))
        with LevelDbReader(p) as r:
            assert dict(r) == {b"b": b"2"}


class TestWriterValidation:
    def test_refuses_existing_leveldb_dir(self, tmp_path):
        """Overlaying a new DB on an old one would merge stale logs with
        higher sequences over the fresh records — refuse loudly."""
        p = _write(tmp_path / "db", [(b"k", b"v")])
        with pytest.raises(ValueError, match="already holds"):
            LevelDbWriter(p)

    def test_key_validation(self, tmp_path):
        w = LevelDbWriter(str(tmp_path / "db"))
        with pytest.raises(ValueError, match="key"):
            w.put(b"", b"v")
        w.close()


class TestFormatInvariants:
    def test_current_and_manifest(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")])
        cur = open(os.path.join(p, "CURRENT"), "rb").read()
        assert cur == b"MANIFEST-000002\n"
        # manifest decodes as VersionEdits naming the bytewise comparator
        state = {}
        raw = open(os.path.join(p, "MANIFEST-000002"), "rb").read()
        for payload in leveldb_io._log_records(raw):
            leveldb_io._decode_version_edit(payload, state)
        assert state["comparator"] == b"leveldb.BytewiseComparator"
        assert state["last_seq"] == 1

    def test_sst_footer_magic(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")], sst=True)
        raw = open(os.path.join(p, "000005.ldb"), "rb").read()
        magic = struct.unpack_from("<Q", raw, len(raw) - 8)[0]
        assert magic == 0xDB4775248B80FB57

    def test_block_crc_detects_corruption(self, tmp_path):
        p = _write(tmp_path / "db", [(b"key", b"value")], sst=True)
        f = os.path.join(p, "000005.ldb")
        raw = bytearray(open(f, "rb").read())
        raw[2] ^= 0xFF  # flip a data-block byte
        open(f, "wb").write(bytes(raw))
        with pytest.raises(ValueError, match="CRC"):
            with LevelDbReader(p) as r:
                list(r)

    def test_unknown_comparator_rejected(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")])
        edit = leveldb_io._encode_version_edit(
            comparator=b"my.custom.Comparator", log_number=3,
            next_file=4, last_seq=1,
        )
        with open(os.path.join(p, "MANIFEST-000002"), "wb") as f:
            f.write(leveldb_io._write_log_records([edit]))
        with pytest.raises(ValueError, match="comparator"):
            LevelDbReader(p)

    def test_snappy_compressed_block_reads(self, tmp_path):
        """A table whose block carries compression byte 1 (snappy) —
        what a stock leveldb build writes — must decode."""
        # build an SST by hand with one snappy block: literal-only stream
        entries = leveldb_io._encode_block(
            [(b"k" + struct.pack("<Q", (1 << 8) | 1), b"vv")]
        )
        compressed = bytearray()
        leveldb_io._put_varint(compressed, len(entries))
        pos = 0
        while pos < len(entries):  # chunk into <=60-byte literals
            chunk = entries[pos : pos + 60]
            compressed.append((len(chunk) - 1) << 2)
            compressed += chunk
            pos += len(chunk)
        out = bytearray()
        h_data = (0, len(compressed))
        out += compressed
        out.append(1)  # snappy
        out += struct.pack(
            "<I", crc_mask(crc32c(bytes(compressed) + b"\x01")))
        # index block (uncompressed)
        h = bytearray()
        leveldb_io._put_varint(h, h_data[0])
        leveldb_io._put_varint(h, h_data[1])
        idx = leveldb_io._encode_block(
            [(b"k" + struct.pack("<Q", (1 << 8) | 1), bytes(h))])
        idx_handle = leveldb_io._append_block(out, idx)
        mi_handle = leveldb_io._append_block(out, leveldb_io._encode_block([]))
        footer = bytearray()
        for v in (*mi_handle, *idx_handle):
            leveldb_io._put_varint(footer, v)
        footer += b"\x00" * (40 - len(footer))
        footer += struct.pack("<Q", 0xDB4775248B80FB57)
        out += footer
        got = list(leveldb_io._sst_entries(bytes(out)))
        assert got == [(1, 1, b"k", b"vv")]


class TestDataLayerIngest:
    """A LevelDB written by CreateDB feeds the Data-layer minibatch path
    unchanged — the CifarDBApp flow on its actual backend."""

    def _images(self, n, shape=(3, 8, 8)):
        rs = np.random.RandomState(0)
        return [
            (rs.randint(0, 255, shape).astype(np.uint8), i % 10)
            for i in range(n)
        ]

    def test_leveldb_feeds_db_minibatches(self, tmp_path):
        from sparknet_tpu.data.createdb import create_db, db_minibatches

        samples = self._images(20)
        p = str(tmp_path / "caffe_leveldb")
        n = create_db(p, samples, backend="leveldb")
        assert n == 20 and is_leveldb(p)
        batches = list(db_minibatches(p, 8))
        assert len(batches) == 2
        np.testing.assert_array_equal(
            batches[0]["data"][0], samples[0][0].astype(np.float32)
        )
        assert batches[0]["label"][:4].tolist() == [0, 1, 2, 3]

    def test_convert_leveldb_to_lmdb(self, tmp_path):
        from sparknet_tpu.data.createdb import convert_db, create_db, db_minibatches

        samples = self._images(12)
        src = str(tmp_path / "ldb")
        dst = str(tmp_path / "mdb")
        create_db(src, samples, backend="leveldb")
        assert convert_db(src, dst, backend="lmdb") == 12
        batches = list(db_minibatches(dst, 12))
        np.testing.assert_array_equal(
            batches[0]["data"],
            np.stack([s[0] for s in samples]).astype(np.float32),
        )

    def test_cli_train_from_leveldb(self, tmp_path, monkeypatch):
        """tpunet train --data db:<leveldb> — backend: LEVELDB parity for
        the cifar10_full-style prototxt."""
        from sparknet_tpu.cli import main
        from sparknet_tpu.data.createdb import create_db

        monkeypatch.chdir(tmp_path)
        samples = self._images(24, shape=(3, 12, 12))
        db = str(tmp_path / "train_leveldb")
        create_db(db, samples, backend="leveldb")
        (tmp_path / "net.prototxt").write_text(
            'name: "ldbnet"\n'
            'layer { name: "d" type: "Data" top: "data" top: "label"\n'
            '  data_param { source: "train_leveldb" batch_size: 8\n'
            "    backend: LEVELDB }\n"
            "}\n"
            'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
            "  inner_product_param { num_output: 4 } }\n"
            'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
            'bottom: "label" top: "loss" }\n'
        )
        (tmp_path / "solver.prototxt").write_text(
            'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 2\ndisplay: 0\n'
        )
        assert main([
            "train", "--solver", str(tmp_path / "solver.prototxt"),
            "--data", "proto", "--iterations", "2",
            "--output", str(tmp_path / "out"),
        ]) == 0


class TestWriterAutoSst:
    def test_small_write_stays_log_only(self, tmp_path):
        p = str(tmp_path / "small")
        with LevelDbWriter(p) as w:  # sst=None: auto by payload size
            w.put(b"k", b"v" * 100)
        names = set(os.listdir(p))
        assert not any(n.endswith((".ldb", ".sst")) for n in names), names

    def test_large_write_flushes_as_sstable(self, tmp_path):
        """Past write_buffer_size (~4 MB, the bound a real memtable
        flushes at) the auto writer emits a Level-0 table, so a reader's
        one-record geometry peek never replays a dataset-sized log into
        RAM (ADVICE r3: leveldb_io eager-load)."""
        p = str(tmp_path / "big")
        blob = bytes(range(256)) * 2048  # 512 KiB, incompressible-ish
        with LevelDbWriter(p) as w:
            for i in range(10):  # ~5 MB total
                w.put(f"{i:04d}".encode(), blob)
        assert any(n.endswith(".ldb") for n in os.listdir(p))
        with LevelDbReader(p) as r:
            # lazy overlay: opening + first record must not need the log
            assert r._overlay_cache is None
            k, v = next(iter(r))
            assert (k, v) == (b"0000", blob)
        with LevelDbReader(p) as r:
            assert len(r) == 10
            assert [k for k, _ in r] == [f"{i:04d}".encode() for i in range(10)]
