"""Graph-compiler tests: the reference model zoo must compile, shape-infer,
and run forward (parity target: Net::Init over the same prototxts,
ref: caffe/src/caffe/net.cpp:40-540; LayerSpec.scala:10-51)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.proto import parse_file

REF = "/root/reference/caffe"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")


def _feeds_for(net, shapes=None, seed=0, num_classes=10):
    rng = np.random.RandomState(seed)
    merged = dict(net.feed_shapes())
    merged.update(shapes or {})
    feeds = {}
    for name, shape in merged.items():
        if name == "label":
            feeds[name] = jnp.asarray(rng.randint(0, num_classes, size=shape), jnp.int32)
        else:
            feeds[name] = jnp.asarray(rng.randn(*shape), jnp.float32)
    return feeds

CIFAR_SHAPES = {"data": (100, 3, 32, 32), "label": (100,)}


@needs_ref
def test_cifar10_full_train_compiles_and_runs():
    npz = parse_file(f"{REF}/examples/cifar10/cifar10_full_train_test.prototxt")
    net = Network(npz, Phase.TRAIN)
    variables = net.init(jax.random.key(0), feed_shapes=CIFAR_SHAPES)
    # conv1 32x3x5x5 weights + bias
    assert variables.params["conv1"][0].shape == (32, 3, 5, 5)
    assert variables.params["conv1"][1].shape == (32,)
    assert variables.params["ip1"][0].shape == (10, 64 * 4 * 4)
    blobs, _, loss = net.apply(variables, _feeds_for(net, CIFAR_SHAPES), rng=jax.random.key(1))
    assert blobs["ip1"].shape == (100, 10)
    assert np.isfinite(float(loss))
    # untrained loss should be near ln(10)
    assert abs(float(loss) - np.log(10)) < 0.5


@needs_ref
def test_cifar10_full_test_phase_has_accuracy():
    npz = parse_file(f"{REF}/examples/cifar10/cifar10_full_train_test.prototxt")
    net = Network(npz, Phase.TEST)
    variables = net.init(jax.random.key(0), feed_shapes=CIFAR_SHAPES)
    blobs, _, _ = net.apply(variables, _feeds_for(net, CIFAR_SHAPES), rng=None)
    assert blobs["accuracy"].shape == ()
    assert 0.0 <= float(blobs["accuracy"]) <= 1.0


@needs_ref
def test_alexnet_shapes():
    npz = parse_file(f"{REF}/models/bvlc_alexnet/train_val.prototxt")
    net = Network(npz, Phase.TRAIN, batch_override=4)
    # Data layer has no declared shape; AlexNet feeds 227x227 crops
    variables = net.init(
        jax.random.key(0), feed_shapes={"data": (4, 3, 227, 227), "label": (4,)}
    )
    info = net.blob_info()
    # canonical AlexNet activations (ref: bvlc_alexnet/train_val.prototxt)
    assert info["conv1"].shape == (4, 96, 55, 55)
    assert info["pool1"].shape == (4, 96, 27, 27)
    assert info["conv2"].shape == (4, 256, 27, 27)  # group=2, pad=2
    assert info["pool5"].shape == (4, 256, 6, 6)
    assert info["fc6"].shape == (4, 4096)
    assert variables.params["fc6"][0].shape == (4096, 9216)
    assert info["fc8"].shape == (4, 1000)


@needs_ref
def test_googlenet_compiles():
    """166-layer multi-tower prototxt — the compiler stress test
    (SURVEY.md 'hard parts' (e))."""
    npz = parse_file(f"{REF}/models/bvlc_googlenet/train_val.prototxt")
    net = Network(npz, Phase.TRAIN, batch_override=2)
    variables = net.init(
        jax.random.key(0), feed_shapes={"data": (2, 3, 224, 224), "label": (2,)}
    )
    info = net.blob_info()
    assert info["inception_3a/output"].shape == (2, 256, 28, 28)
    assert info["pool5/7x7_s1"].shape == (2, 1024, 1, 1)
    assert info["loss3/classifier"].shape == (2, 1000)
    # 3 weighted losses (two aux at 0.3)
    feeds = _feeds_for(net, {"data": (2, 3, 224, 224), "label": (2,)}, num_classes=1000)
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.key(1))
    expected = float(blobs["loss3/loss3"] + 0.3 * blobs["loss1/loss1"] + 0.3 * blobs["loss2/loss1"])
    assert abs(float(loss) - expected) < 1e-4


@needs_ref
def test_lenet_deploy_net_level_inputs():
    npz = parse_file(f"{REF}/examples/mnist/lenet.prototxt")
    net = Network(npz, Phase.TEST)
    variables = net.init(jax.random.key(0))
    blobs, _, _ = net.apply(
        variables, {"data": jnp.zeros((64, 1, 28, 28))}, rng=None
    )
    assert blobs["prob"].shape == (64, 10)
    assert np.allclose(np.sum(np.asarray(blobs["prob"]), axis=1), 1.0, atol=1e-5)


def test_phase_filtering_rules():
    from sparknet_tpu.proto import parse
    from sparknet_tpu.compiler import filter_phase

    npz = parse(
        """
        layer { name: "a" type: "ReLU" include { phase: TRAIN } }
        layer { name: "b" type: "ReLU" exclude { phase: TRAIN } }
        layer { name: "c" type: "ReLU" }
        layer { name: "d" type: "ReLU" include { min_level: 2 } }
        layer { name: "e" type: "ReLU" include { stage: "deploy" } }
        """
    )
    names = [l.get_str("name") for l in filter_phase(npz, Phase.TRAIN)]
    assert names == ["a", "c"]
    names = [l.get_str("name") for l in filter_phase(npz, Phase.TEST)]
    assert names == ["b", "c"]
    names = [l.get_str("name") for l in filter_phase(npz, Phase.TRAIN, level=3, stages={"deploy"})]
    assert names == ["a", "c", "d", "e"]


def test_jit_apply_and_grad():
    """The whole net must trace under jit and differentiate."""
    from sparknet_tpu.proto import parse

    npz = parse(
        """
        name: "tiny"
        layer { name: "data" type: "MemoryData" top: "data" top: "label"
                memory_data_param { batch_size: 8 channels: 3 height: 8 width: 8 } }
        layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
                convolution_param { num_output: 4 kernel_size: 3 pad: 1
                  weight_filler { type: "xavier" } } }
        layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
        layer { name: "pool" type: "Pooling" bottom: "conv" top: "pool"
                pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
        layer { name: "ip" type: "InnerProduct" bottom: "pool" top: "ip"
                inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
        """
    )
    net = Network(npz, Phase.TRAIN)
    variables = net.init(jax.random.key(0))
    feeds = {
        "data": jnp.ones((8, 3, 8, 8)),
        "label": jnp.zeros((8,), jnp.int32),
    }

    @jax.jit
    def loss_fn(params, state, feeds):
        _, new_state, loss = net.apply(
            type(variables)(params=params, state=state), feeds, rng=jax.random.key(0)
        )
        return loss

    g = jax.grad(loss_fn)(variables.params, variables.state, feeds)
    assert g["conv"][0].shape == (4, 3, 3, 3)
    assert float(jnp.sum(jnp.abs(g["conv"][0]))) > 0


def test_mixed_precision_bf16_compute():
    """compute_dtype=bfloat16: activations run bf16, loss stays f32, params
    untouched (master f32), grads f32, and training still learns."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu import models
    from sparknet_tpu.common import Phase, set_config
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.solvers.solver import Solver

    try:
        set_config(compute_dtype=jnp.bfloat16)
        net = Network(models.lenet(4), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))
        feeds = {
            "data": np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32),
            "label": np.zeros(4, np.int32),
        }
        blobs, new_state, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
        assert blobs["conv1"].dtype == jnp.bfloat16
        assert loss.dtype == jnp.float32 and bool(jnp.isfinite(loss))
        # grads flow in f32 (master params f32)
        def loss_fn(params):
            from sparknet_tpu.compiler.graph import NetVars
            _, _, l = net.apply(NetVars(params=params, state=variables.state),
                                feeds, rng=jax.random.PRNGKey(1))
            return l
        g = jax.grad(loss_fn)(variables.params)
        leaf = jax.tree_util.tree_leaves(g)[0]
        assert leaf.dtype == jnp.float32
        # a few solver steps still reduce the loss
        solver = Solver(models.lenet_solver(), models.lenet(4))
        l0 = solver.step(1, lambda it: feeds)
        l5 = solver.step(5, lambda it: feeds)
        assert l5 < l0 + 1e-3
    finally:
        set_config(compute_dtype=jnp.float32)


def test_forward_from_to_partial_execution():
    """Partial forward (ref: Net::ForwardFromTo net.cpp:565-583):
    end-only prefix runs, resume-from-intermediate matches the full
    pass, and helpful errors for bad ranges/missing blobs."""
    from sparknet_tpu import models
    from sparknet_tpu.net import TPUNet
    from sparknet_tpu.solvers.solver import SolverConfig

    net = TPUNet(SolverConfig(), models.lenet(4))
    rs = np.random.RandomState(0)
    feeds = {
        "data": rs.randn(4, 1, 28, 28).astype(np.float32) * 40,
        "label": rs.randint(0, 10, 4).astype(np.int32),
    }
    full = net.forward(feeds)

    # prefix: stop after conv1 — later blobs absent
    prefix = net.forward(feeds, end="conv1")
    assert "conv1" in prefix and "ip2" not in prefix
    np.testing.assert_allclose(
        np.asarray(prefix["conv1"]), np.asarray(full["conv1"]), atol=1e-5
    )

    # resume from an intermediate blob: pool1 onward reproduces the full run
    resumed = net.forward(
        {"pool1": full["pool1"], "label": feeds["label"]}, start="conv2"
    )
    np.testing.assert_allclose(
        np.asarray(resumed["ip2"]), np.asarray(full["ip2"]), atol=1e-4
    )

    # end-only runs still start at layer 0: the strict input contract holds
    with pytest.raises(ValueError, match="missing feed"):
        net.test_net.apply(
            net.solver.variables, {"data": feeds["data"]},
            train=False, end="conv1",
        )

    with pytest.raises(KeyError, match="no layer named"):
        net.forward(feeds, end="nope")
    with pytest.raises(ValueError, match="comes after"):
        net.test_net.apply(
            net.solver.variables, feeds, train=False, start="ip2", end="conv1"
        )
    with pytest.raises(ValueError, match="needs blob"):
        net.test_net.apply(
            net.solver.variables, {"label": feeds["label"]},
            train=False, start="conv2", end="ip2",
        )


def test_backward_from_to_and_wrt_inputs():
    """Partial backward (ref: Net::BackwardFromTo net.cpp:635-646):
    range-restricted grads, and bottom-diffs via wrt='inputs'."""
    from sparknet_tpu import models
    from sparknet_tpu.net import TPUNet
    from sparknet_tpu.solvers.solver import SolverConfig

    net = TPUNet(SolverConfig(), models.lenet(4))
    rs = np.random.RandomState(0)
    feeds = {
        "data": rs.randn(4, 1, 28, 28).astype(np.float32) * 40,
        "label": rs.randint(0, 10, 4).astype(np.int32),
    }
    full_g = net.backward(feeds)
    assert any(float(jnp.abs(g).sum()) > 0 for g in full_g["conv1"])

    # head-only range: grads flow to head params, conv trunk untouched
    blobs = net.forward(feeds)
    head_g = net.backward(
        {"pool2": blobs["pool2"], "label": feeds["label"]}, start="ip1"
    )
    assert any(float(jnp.abs(g).sum()) > 0 for g in head_g["ip1"])
    assert all(float(jnp.abs(g).sum()) == 0 for g in head_g["conv1"])

    # bottom diffs: d(loss)/d(fed blob)
    in_g = net.backward(
        {"pool2": blobs["pool2"], "label": feeds["label"]},
        start="ip1", wrt="inputs",
    )
    assert set(in_g) == {"pool2"}
    assert float(jnp.abs(in_g["pool2"]).sum()) > 0

    with pytest.raises(ValueError, match="wrt must be"):
        net.backward(feeds, wrt="blobs")
