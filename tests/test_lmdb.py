"""Clean-room LMDB codec + Caffe-dataset ingest compatibility.

ref: caffe/src/caffe/util/db_lmdb.cpp (the reference's LMDB Cursor/
Transaction).  No liblmdb exists in this environment, so the format is
pinned two ways: round-trips through our own reader/writer, and
byte-level invariants against the published on-disk layout (meta magic /
version / dual-meta txnid rule, page flags, node packing).
"""

import os
import struct

import numpy as np
import pytest

from sparknet_tpu.data import lmdb_io
from sparknet_tpu.data.createdb import (
    convert_db,
    create_db,
    db_minibatches,
    decode_datum,
)
from sparknet_tpu.data.io_utils import datum_to_array
from sparknet_tpu.data.lmdb_io import LmdbReader, LmdbWriter, is_lmdb


def _write(path, items, subdir=True):
    with LmdbWriter(str(path), subdir=subdir) as w:
        for k, v in items:
            w.put(k, v)
    return str(path)


class TestRoundTrip:
    def test_small(self, tmp_path):
        items = [(f"{i:08d}".encode(), f"value-{i}".encode()) for i in range(5)]
        p = _write(tmp_path / "db", items)
        with LmdbReader(p) as r:
            assert len(r) == 5
            assert list(r) == items

    def test_keys_returned_in_sorted_order(self, tmp_path):
        items = [(b"zeta", b"3"), (b"alpha", b"1"), (b"mid", b"2")]
        p = _write(tmp_path / "db", items)
        with LmdbReader(p) as r:
            assert [k for k, _ in r] == [b"alpha", b"mid", b"zeta"]

    def test_multipage_tree(self, tmp_path):
        # thousands of entries forces multiple leaves + branch levels
        items = [
            (f"{i:08d}".encode(), os.urandom(50 + i % 100)) for i in range(3000)
        ]
        p = _write(tmp_path / "db", items)
        with LmdbReader(p) as r:
            assert len(r) == 3000
            got = list(r)
        assert got == sorted(items)

    def test_overflow_values(self, tmp_path):
        # > half-page values go to OVERFLOW page runs (the ImageNet JPEG
        # case); include a multi-page one and an exact-page-boundary one
        items = [
            (b"big-a", os.urandom(3000)),
            (b"big-b", os.urandom(5 * 4096)),
            (b"big-c", os.urandom(4096 - 16)),  # exactly one overflow page
            (b"small", b"x"),
        ]
        p = _write(tmp_path / "db", items)
        with LmdbReader(p) as r:
            assert dict(r) == dict(items)

    def test_empty_db(self, tmp_path):
        p = _write(tmp_path / "db", [])
        with LmdbReader(p) as r:
            assert len(r) == 0
            assert list(r) == []

    def test_nosubdir_file(self, tmp_path):
        p = _write(tmp_path / "data.mdb", [(b"k", b"v")], subdir=False)
        assert os.path.isfile(p)
        with LmdbReader(p) as r:
            assert list(r) == [(b"k", b"v")]


class TestFormatInvariants:
    """Byte-level checks against the published LMDB layout."""

    def test_meta_pages(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")])
        raw = open(os.path.join(p, "data.mdb"), "rb").read()
        assert len(raw) % 4096 == 0
        for pgno in (0, 1):
            off = pgno * 4096
            # page header: pgno, pad, flags(P_META=0x08)
            hdr_pgno, _, flags, _, _ = struct.unpack_from("<QHHHH", raw, off)
            assert hdr_pgno == pgno and flags == 0x08
            magic, version = struct.unpack_from("<II", raw, off + 16)
            assert magic == 0xBEEFC0DE and version == 1
        # dual-meta rule: differing txnids, reader takes the newer
        tail = 16 + 24 + 2 * 48
        txn0 = struct.unpack_from("<Q", raw, tail + 8)[0]
        txn1 = struct.unpack_from("<Q", raw, 4096 + tail + 8)[0]
        assert {txn0, txn1} == {0, 1}

    def test_leaf_page_flags_and_node(self, tmp_path):
        p = _write(tmp_path / "db", [(b"key0", b"val0")])
        raw = open(os.path.join(p, "data.mdb"), "rb").read()
        # single-leaf DB: root page is page 2, a LEAF (0x02)
        _, _, flags, lower, upper = struct.unpack_from("<QHHHH", raw, 2 * 4096)
        assert flags == 0x02
        n = (lower - 16) // 2
        assert n == 1
        (ptr,) = struct.unpack_from("<H", raw, 2 * 4096 + 16)
        assert ptr == upper
        lo, hi, nflags, ksize = struct.unpack_from("<HHHH", raw, 2 * 4096 + ptr)
        assert (lo | hi << 16) == 4 and nflags == 0 and ksize == 4
        node = raw[2 * 4096 + ptr + 8 :][:8]
        assert node == b"key0val0"

    def test_detection(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")])
        assert is_lmdb(p)
        other = tmp_path / "not_lmdb"
        other.write_bytes(b"\x00" * 8192)
        assert not is_lmdb(str(other))

    def test_corrupt_magic_rejected(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"v")])
        f = os.path.join(p, "data.mdb")
        raw = bytearray(open(f, "rb").read())
        raw[16:20] = b"\x00\x00\x00\x00"  # meta 0 magic
        raw[4096 + 16 : 4096 + 20] = b"\x00\x00\x00\x00"  # meta 1 magic
        open(f, "wb").write(bytes(raw))
        with pytest.raises(ValueError, match="meta"):
            LmdbReader(f if os.path.isfile(f) else p)


class TestWriterValidation:
    def test_key_bounds(self, tmp_path):
        w = LmdbWriter(str(tmp_path / "db"))
        with pytest.raises(ValueError, match="key length"):
            w.put(b"", b"v")
        with pytest.raises(ValueError, match="key length"):
            w.put(b"k" * 512, b"v")

    def test_duplicate_key_last_wins(self, tmp_path):
        p = _write(tmp_path / "db", [(b"k", b"first"), (b"k", b"second")])
        with LmdbReader(p) as r:
            assert dict(r) == {b"k": b"second"}


class TestDataLayerIngest:
    """The VERDICT round-trip: a fixture LMDB (Caffe Datum values) feeds
    the Data-layer minibatch path unchanged."""

    def _images(self, n, shape=(3, 8, 8)):
        rs = np.random.RandomState(0)
        return [
            (rs.randint(0, 255, shape).astype(np.uint8), i % 10)
            for i in range(n)
        ]

    @pytest.mark.smoke
    def test_lmdb_feeds_db_minibatches(self, tmp_path):
        samples = self._images(20)
        p = str(tmp_path / "caffe_lmdb")
        n = create_db(p, samples, backend="lmdb")
        assert n == 20 and is_lmdb(p)
        batches = list(db_minibatches(p, 8))
        assert len(batches) == 2  # 20 // 8, remainder dropped
        np.testing.assert_array_equal(
            batches[0]["data"][0], samples[0][0].astype(np.float32)
        )
        assert batches[0]["label"][:4].tolist() == [0, 1, 2, 3]

    def test_lmdb_values_are_real_datums(self, tmp_path):
        samples = self._images(3)
        p = str(tmp_path / "caffe_lmdb")
        create_db(p, samples, backend="lmdb")
        with LmdbReader(p) as r:
            for (key, value), (img, label) in zip(r, samples):
                arr, lab = datum_to_array(value)
                np.testing.assert_array_equal(arr, img)
                assert lab == label

    def test_convert_lmdb_to_recorddb(self, tmp_path):
        samples = self._images(12)
        src = str(tmp_path / "caffe_lmdb")
        dst = str(tmp_path / "native.rdb")
        create_db(src, samples, backend="lmdb")
        n = convert_db(src, dst, backend="record")
        assert n == 12
        batches = list(db_minibatches(dst, 12))
        np.testing.assert_array_equal(
            batches[0]["data"], np.stack([s[0] for s in samples]).astype(np.float32)
        )

    def test_convert_recorddb_to_lmdb(self, tmp_path):
        samples = self._images(7)
        src = str(tmp_path / "native.rdb")
        dst = str(tmp_path / "out_lmdb")
        create_db(src, samples, backend="record")
        n = convert_db(src, dst, backend="lmdb")
        assert n == 7 and is_lmdb(dst)
        with LmdbReader(dst) as r:
            arr, lab = datum_to_array(dict(r)[b"00000003"])
            np.testing.assert_array_equal(arr, samples[3][0])
            assert lab == 3

    def test_cli_convert_db(self, tmp_path, capsys):
        import json

        from sparknet_tpu.cli import main

        samples = self._images(5)
        src = str(tmp_path / "caffe_lmdb")
        dst = str(tmp_path / "native.rdb")
        create_db(src, samples, backend="lmdb")
        assert main(["convert_db", "--src", src, "--dst", dst]) == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["records"] == 5


def test_cli_train_from_lmdb(tmp_path, capsys, monkeypatch):
    """tpunet train --data db:<lmdb> — the CifarDBApp flow end to end
    from a real Caffe-format LMDB through the CLI."""
    import numpy as np

    monkeypatch.chdir(tmp_path)  # cmd_train writes its event log to cwd

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [
        (rs.randint(0, 255, (1, 28, 28)).astype(np.uint8), i % 10)
        for i in range(64)
    ]
    p = str(tmp_path / "train_lmdb")
    create_db(p, samples, backend="lmdb")
    out = str(tmp_path / "model")
    assert main([
        "train", "--solver", "zoo:lenet", "--batch", "16",
        "--iterations", "2", "--data", f"db:{p}", "--output", out,
    ]) == 0


def _write_tiny_data_net(tmp_path, *, source, batch=4, num_output=3,
                         transform_param="", name="tiny"):
    """The minimal Data-layer train_val + solver pair the CLI tests share
    (only source/batch/transform vary per case)."""
    tp = (f"  transform_param {{ {transform_param} }}\n"
          if transform_param else "")
    (tmp_path / "net.prototxt").write_text(
        f'name: "{name}"\n'
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        f'  data_param {{ source: "{source}" batch_size: {batch} }}\n'
        f"{tp}"
        "}\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        f"  inner_product_param {{ num_output: {num_output} }} }}\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 2\ndisplay: 0\n'
    )
    return str(tmp_path / "solver.prototxt")


def test_cli_train_data_layer_prototxt_from_db(tmp_path, capsys, monkeypatch):
    """A reference-style train_val prototxt whose source is a DB-backed
    ``Data`` layer (no declared geometry anywhere) trains end to end:
    the CLI peeks the first datum of --data db: for the blob shape, the
    way Caffe's DataLayerSetUp reads datum 0 (ref: data_layer.cpp:40-48)."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [
        (rs.randint(0, 255, (3, 12, 12)).astype(np.uint8), i % 4)
        for i in range(32)
    ]
    db = str(tmp_path / "train_lmdb")
    create_db(db, samples, backend="lmdb")

    _write_tiny_data_net(tmp_path, source="missing_on_this_host_lmdb",
                         batch=8, num_output=4, name="dbnet")
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", f"db:{db}", "--iterations", "2",
        "--output", str(tmp_path / "out"),
    ]) == 0
    assert (tmp_path / "out.solverstate.npz").exists()


def test_cli_train_data_layer_crop_from_db(tmp_path, monkeypatch):
    """transform_param.crop_size on a Data layer: records larger than the
    net's blob are cropped host-side (random in TRAIN / center in TEST,
    ref: data_transformer.cpp:49,83) — the AlexNet-from-256-pixel-DB
    recipe in miniature."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 16, 16)).astype(np.uint8), i % 4)
               for i in range(24)]
    db = str(tmp_path / "big_lmdb")
    create_db(db, samples, backend="lmdb")

    _write_tiny_data_net(
        tmp_path, source="not_here_lmdb", batch=8, num_output=4,
        transform_param="crop_size: 10 mirror: true scale: 0.0039",
        name="cropnet")
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", f"db:{db}", "--iterations", "2",
        "--output", str(tmp_path / "out"),
    ]) == 0


def test_cli_train_data_proto_streams_own_source(tmp_path, monkeypatch):
    """``tpunet train --solver x --data proto`` with a Data-layer net whose
    data_param.source is on disk = the ``caffe train --solver=x`` flow:
    the net's own DB streams, transform_param applies, nothing else needed
    (ref: data_layer.cpp DataReader + DataTransformer)."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 14, 14)).astype(np.uint8), i % 3)
               for i in range(16)]
    create_db(str(tmp_path / "own_lmdb"), samples, backend="lmdb")

    _write_tiny_data_net(
        tmp_path, source="own_lmdb", batch=4,
        transform_param="crop_size: 12 scale: 0.0039", name="selffeed")
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", "proto", "--iterations", "2",
        "--output", str(tmp_path / "out"),
    ]) == 0
    assert (tmp_path / "out.solverstate.npz").exists()


def test_cli_data_auto_streams_own_source(tmp_path, monkeypatch, capsys):
    """Default --data (auto): a prototxt whose Data layer has a readable
    source trains from IT — `caffe train --solver=x` semantics — with no
    data flag at all."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 10, 10)).astype(np.uint8), i % 3)
               for i in range(12)]
    create_db(str(tmp_path / "auto_lmdb"), samples, backend="lmdb")
    _write_tiny_data_net(tmp_path, source="auto_lmdb", name="auto")
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--iterations", "2", "--output", str(tmp_path / "out"),
    ]) == 0


def test_cli_time_and_extract_features_db_peek(tmp_path, monkeypatch, capsys):
    """Every brew shares the DB-geometry peek: `time --hlo` and
    `extract_features` on a Data-layer prototxt + --data db: work like
    train/test do."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    import json

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 10, 10)).astype(np.uint8), i % 3)
               for i in range(16)]
    db = str(tmp_path / "peek_lmdb")
    create_db(db, samples, backend="lmdb")
    _write_tiny_data_net(tmp_path, source="elsewhere_lmdb", name="peek")
    assert main(["time", "--hlo", "--solver", str(tmp_path / "solver.prototxt"),
                 "--data", f"db:{db}"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["flops_per_step"] > 0 and out["batch"] == 4

    assert main(["extract_features",
                 "--solver", str(tmp_path / "solver.prototxt"),
                 "--data", f"db:{db}", "--blob", "ip",
                 "--iterations", "2",
                 "--out", str(tmp_path / "f.npy")]) == 0
    feats = np.load(tmp_path / "f.npy")
    assert feats.shape == (8, 3)  # 2 batches x 4, ip num_output 3


def test_cli_data_auto_missing_source_is_loud(tmp_path, monkeypatch):
    """auto must NOT fall back to random noise when the net points at a
    source that cannot stream — that silent substitution would train a
    garbage model."""
    import pytest

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main

    (tmp_path / "net.prototxt").write_text(
        'name: "x"\n'
        'layer { name: "d" type: "ImageData" top: "data" top: "label"\n'
        '  image_data_param { source: "no_such_list.txt" batch_size: 2 }\n'
        "  transform_param { crop_size: 4 } }\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        "  inner_product_param { num_output: 2 } }\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 1\n'
    )
    with pytest.raises(SystemExit, match="cannot stream"):
        main(["train", "--solver", str(tmp_path / "solver.prototxt"),
              "--iterations", "1"])


def test_data_layer_peeks_its_own_source(tmp_path, monkeypatch):
    """When data_param.source IS on disk, the net shape-infers with no
    feed help at all — Network.feed_shapes() carries the peeked geometry
    (with transform_param crop applied, ref: data_transformer
    InferBlobShape)."""
    import numpy as np

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.data.createdb import create_db
    from sparknet_tpu.proto.text_format import parse

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 16, 16)).astype(np.uint8), 0)
               for _ in range(4)]
    db = str(tmp_path / "src_lmdb")
    create_db(db, samples, backend="lmdb")

    net = parse(
        'name: "n"\n'
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        f'  data_param {{ source: "{db}" batch_size: 6 }}\n'
        "  transform_param { crop_size: 10 }\n"
        "}\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        "  inner_product_param { num_output: 2 } }\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    shapes = Network(net, Phase.TRAIN).feed_shapes()
    assert shapes["data"] == (6, 3, 10, 10)
    assert shapes["label"] == (6,)


def test_cli_train_db_shape_mismatch(tmp_path, monkeypatch):
    import numpy as np
    import pytest

    monkeypatch.chdir(tmp_path)  # cmd_train writes its event log to cwd

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 8, 8)).astype(np.uint8), 0)
               for _ in range(8)]
    p = str(tmp_path / "bad_lmdb")
    create_db(p, samples, backend="lmdb")
    with pytest.raises(SystemExit, match="do not match"):
        main(["train", "--solver", "zoo:lenet", "--batch", "4",
              "--iterations", "1", "--data", f"db:{p}"])


def test_peek_db_shape_invalidates_on_rebuild(tmp_path):
    """A DB rebuilt at the same path in-process (CifarDBApp
    re-materialize, convert_db, tests) must not serve stale geometry
    from the peek cache (ADVICE r3: createdb lru_cache by path)."""
    import shutil
    import time

    import numpy as np

    from sparknet_tpu.data.createdb import create_db, peek_db_shape

    rs = np.random.RandomState(0)
    p = str(tmp_path / "db")
    create_db(p, [(rs.randint(0, 255, (1, 8, 8)).astype(np.uint8), 0)])
    assert peek_db_shape(p) == (1, 8, 8)
    shutil.rmtree(p, ignore_errors=True) or os.path.exists(p) and os.remove(p)
    time.sleep(0.01)  # ensure a distinct mtime_ns on coarse filesystems
    create_db(p, [(rs.randint(0, 255, (3, 12, 12)).astype(np.uint8), 0)])
    assert peek_db_shape(p) == (3, 12, 12)


def test_cli_test_stream_honors_test_phase_transform(tmp_path, monkeypatch):
    """A TEST-phase Data layer declaring its OWN transform_param (here a
    different crop) drives the test stream; before the r4 fix the TRAIN
    layer's params were applied to both phases (ADVICE r3: cli db:
    branch), which mis-shapes the eval feed."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [
        (rs.randint(0, 255, (3, 12, 12)).astype(np.uint8), i % 4)
        for i in range(32)
    ]
    db = str(tmp_path / "lmdb")
    create_db(db, samples, backend="lmdb")

    (tmp_path / "net.prototxt").write_text(
        'name: "phases"\n'
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        '  include { phase: TRAIN }\n'
        f'  data_param {{ source: "{db}" batch_size: 8 }}\n'
        "  transform_param { crop_size: 10 }\n"
        "}\n"
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        '  include { phase: TEST }\n'
        f'  data_param {{ source: "{db}" batch_size: 8 }}\n'
        "  transform_param { crop_size: 8 }\n"
        "}\n"
        'layer { name: "conv" type: "Convolution" bottom: "data" top: "c"\n'
        "  convolution_param { num_output: 2 kernel_size: 3 } }\n"
        'layer { name: "pool" type: "Pooling" bottom: "c" top: "p"\n'
        "  pooling_param { pool: AVE global_pooling: true } }\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "p" top: "ip"\n'
        "  inner_product_param { num_output: 4 } }\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 2\ndisplay: 0\n'
    )
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", f"db:{db}", "--iterations", "2", "--test-iters", "1",
        "--output", str(tmp_path / "out"),
    ]) == 0


def test_cli_test_phase_without_transform_gets_defaults(tmp_path,
                                                        monkeypatch):
    """A TEST-phase Data layer with NO transform_param gets Caffe's
    defaults (no crop) — it must not inherit the TRAIN declaration."""
    import numpy as np

    monkeypatch.chdir(tmp_path)

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    rs = np.random.RandomState(0)
    samples = [
        (rs.randint(0, 255, (3, 12, 12)).astype(np.uint8), i % 4)
        for i in range(32)
    ]
    db = str(tmp_path / "lmdb")
    create_db(db, samples, backend="lmdb")

    (tmp_path / "net.prototxt").write_text(
        'name: "defaults"\n'
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        '  include { phase: TRAIN }\n'
        f'  data_param {{ source: "{db}" batch_size: 8 }}\n'
        "  transform_param { crop_size: 10 }\n"
        "}\n"
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        '  include { phase: TEST }\n'
        f'  data_param {{ source: "{db}" batch_size: 8 }}\n'
        "}\n"
        'layer { name: "conv" type: "Convolution" bottom: "data" top: "c"\n'
        "  convolution_param { num_output: 2 kernel_size: 3 } }\n"
        'layer { name: "pool" type: "Pooling" bottom: "c" top: "p"\n'
        "  pooling_param { pool: AVE global_pooling: true } }\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "p" top: "ip"\n'
        "  inner_product_param { num_output: 4 } }\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 2\ndisplay: 0\n'
    )
    assert main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", f"db:{db}", "--iterations", "2", "--test-iters", "1",
        "--output", str(tmp_path / "out"),
    ]) == 0
