"""Solver tests: analytic-update verification, mirroring the reference's
methodology of recomputing the expected update by hand and comparing
(ref: caffe/src/caffe/test/test_gradient_based_solver.cpp:197-208 — there
via a 2-param least-squares net; here directly on the update rules plus an
end-to-end convergence check)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.ops.base import ParamSpec
from sparknet_tpu.proto import parse, parse_file
from sparknet_tpu.solvers import Solver, SolverConfig, apply_update, init_slots
from sparknet_tpu.solvers.lr_policy import learning_rate

REF = "/root/reference/caffe"


# ---------------------------------------------------------------------------
# LR policies (ref: sgd_solver.cpp:27-66)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "cfg_kw,it,expected",
    [
        (dict(lr_policy="fixed", base_lr=0.01), 500, 0.01),
        (dict(lr_policy="step", base_lr=0.01, gamma=0.1, stepsize=100), 250, 0.01 * 0.1**2),
        (dict(lr_policy="exp", base_lr=0.01, gamma=0.99), 10, 0.01 * 0.99**10),
        (dict(lr_policy="inv", base_lr=0.01, gamma=0.0001, power=0.75), 1000, 0.01 * (1 + 0.0001 * 1000) ** -0.75),
        (dict(lr_policy="multistep", base_lr=0.01, gamma=0.5, stepvalue=(10, 20, 30)), 25, 0.01 * 0.5**2),
        (dict(lr_policy="poly", base_lr=0.01, power=2.0, max_iter=100), 50, 0.01 * 0.25),
        (dict(lr_policy="sigmoid", base_lr=0.01, gamma=-0.1, stepsize=50), 50, 0.005),
    ],
)
def test_lr_policies(cfg_kw, it, expected):
    cfg = SolverConfig(**cfg_kw)
    assert float(learning_rate(cfg, it)) == pytest.approx(expected, rel=1e-5)


# ---------------------------------------------------------------------------
# Analytic update checks
# ---------------------------------------------------------------------------
def _one_step(cfg, w, g, slots=None, specs=None, it=0):
    params = {"l": [jnp.asarray(w, jnp.float32)]}
    grads = {"l": [jnp.asarray(g, jnp.float32)]}
    slots = slots if slots is not None else init_slots(cfg.solver_type, params)
    specs = specs or {"l": [ParamSpec()]}
    new_p, new_s = apply_update(cfg, params, grads, slots, specs, learning_rate(cfg, it), jnp.asarray(it))
    return np.asarray(new_p["l"][0]), new_s


@pytest.mark.smoke
def test_sgd_momentum_two_steps():
    """V = mu*V + lr*g; W -= V (ref: sgd_solver.cpp ComputeUpdateValue)."""
    cfg = SolverConfig(base_lr=0.1, momentum=0.9, solver_type="SGD")
    w, g = np.array([1.0, -2.0]), np.array([0.5, 0.25])
    w1, s = _one_step(cfg, w, g)
    v1 = 0.1 * g
    np.testing.assert_allclose(w1, w - v1, rtol=1e-6)
    w2, _ = _one_step(cfg, w1, g, slots=s)
    v2 = 0.9 * v1 + 0.1 * g
    np.testing.assert_allclose(w2, w1 - v2, rtol=1e-6)


def test_sgd_weight_decay_and_multipliers():
    """local_rate = lr*lr_mult; decay = wd*decay_mult applied to the grad."""
    cfg = SolverConfig(base_lr=0.1, momentum=0.0, weight_decay=0.01, solver_type="SGD")
    specs = {"l": [ParamSpec(lr_mult=2.0, decay_mult=0.5)]}
    w, g = np.array([1.0]), np.array([0.2])
    w1, _ = _one_step(cfg, w, g, specs=specs)
    expected = w - 0.1 * 2.0 * (g + 0.01 * 0.5 * w)
    np.testing.assert_allclose(w1, expected, rtol=1e-6)


def test_l1_regularization():
    cfg = SolverConfig(base_lr=0.1, weight_decay=0.01, regularization_type="L1")
    w, g = np.array([1.0, -3.0]), np.array([0.0, 0.0])
    w1, _ = _one_step(cfg, w, g)
    np.testing.assert_allclose(w1, w - 0.1 * 0.01 * np.sign(w), rtol=1e-6)


def test_clip_gradients_global_norm():
    cfg = SolverConfig(base_lr=1.0, clip_gradients=1.0)
    w, g = np.array([0.0, 0.0]), np.array([3.0, 4.0])  # norm 5
    w1, _ = _one_step(cfg, w, g)
    np.testing.assert_allclose(w1, -np.array([0.6, 0.8]), rtol=1e-5)


def test_nesterov_update():
    cfg = SolverConfig(base_lr=0.1, momentum=0.9, solver_type="Nesterov")
    w, g = np.array([1.0]), np.array([0.5])
    w1, s = _one_step(cfg, w, g)
    h1 = 0.1 * 0.5
    np.testing.assert_allclose(w1, w - ((1 + 0.9) * h1 - 0.9 * 0.0), rtol=1e-6)
    w2, _ = _one_step(cfg, w1, g, slots=s)
    h2 = 0.9 * h1 + 0.1 * 0.5
    np.testing.assert_allclose(w2, w1 - ((1 + 0.9) * h2 - 0.9 * h1), rtol=1e-6)


def test_adagrad_update():
    cfg = SolverConfig(base_lr=0.1, delta=1e-8, solver_type="AdaGrad")
    w, g = np.array([1.0]), np.array([0.5])
    w1, s = _one_step(cfg, w, g)
    np.testing.assert_allclose(w1, w - 0.1 * 0.5 / (np.sqrt(0.25) + 1e-8), rtol=1e-5)
    w2, _ = _one_step(cfg, w1, g, slots=s)
    np.testing.assert_allclose(w2, w1 - 0.1 * 0.5 / (np.sqrt(0.5) + 1e-8), rtol=1e-5)


def test_rmsprop_update():
    cfg = SolverConfig(base_lr=0.1, rms_decay=0.9, delta=1e-8, solver_type="RMSProp")
    w, g = np.array([1.0]), np.array([0.5])
    w1, _ = _one_step(cfg, w, g)
    h = 0.1 * 0.25
    np.testing.assert_allclose(w1, w - 0.1 * 0.5 / (np.sqrt(h) + 1e-8), rtol=1e-5)


def test_adadelta_update():
    cfg = SolverConfig(base_lr=1.0, momentum=0.95, delta=1e-6, solver_type="AdaDelta")
    w, g = np.array([1.0]), np.array([0.5])
    w1, _ = _one_step(cfg, w, g)
    h = 0.05 * 0.25
    val = 0.5 * np.sqrt((0 + 1e-6) / (h + 1e-6))
    np.testing.assert_allclose(w1, w - val, rtol=1e-4)


def test_adam_update():
    cfg = SolverConfig(base_lr=0.001, momentum=0.9, momentum2=0.999, delta=1e-8, solver_type="Adam")
    w, g = np.array([1.0]), np.array([0.5])
    w1, _ = _one_step(cfg, w, g, it=0)
    m = 0.1 * 0.5
    v = 0.001 * 0.25
    corr = np.sqrt(1 - 0.999) / (1 - 0.9)
    np.testing.assert_allclose(w1, w - 0.001 * corr * m / (np.sqrt(v) + 1e-8), rtol=1e-5)


# ---------------------------------------------------------------------------
# End-to-end: tiny net converges; snapshot/restore reproduces trajectory
# ---------------------------------------------------------------------------
TINY_NET = """
name: "linreg"
layer { name: "data" type: "MemoryData" top: "data" top: "target"
        memory_data_param { batch_size: 16 channels: 4 height: 1 width: 1 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "pred"
        inner_product_param { num_output: 1 weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "pred" bottom: "target" top: "loss" }
"""


def _linreg_data_fn(seed=0):
    rs = np.random.RandomState(seed)
    true_w = np.array([[1.0, -2.0, 3.0, 0.5]], np.float32)

    def data_fn(it):
        rs2 = np.random.RandomState(seed + it)
        x = rs2.randn(16, 4, 1, 1).astype(np.float32)
        y = x.reshape(16, 4) @ true_w.T
        return {"data": jnp.asarray(x), "target": jnp.asarray(y)}

    return data_fn, true_w


def _make_solver(cfg):
    """MemoryData declares (16,) for its 2nd top; this net's target is (16,1)."""
    return Solver(cfg, parse(TINY_NET), feed_shapes={"target": (16, 1)})


@pytest.mark.parametrize("stype", ["SGD", "Nesterov", "Adam"])
def test_solver_converges(stype):
    lr = 0.02 if stype != "Adam" else 0.05
    cfg = SolverConfig(base_lr=lr, momentum=0.9, solver_type=stype)
    solver = _make_solver(cfg)
    data_fn, true_w = _linreg_data_fn()
    loss = solver.step(200, data_fn)
    assert loss < 0.05, f"{stype} failed to converge: {loss}"
    got = np.asarray(solver.variables.params["ip"][0])
    np.testing.assert_allclose(got, true_w, atol=0.15)


def test_snapshot_restore_reproduces_trajectory(tmp_path):
    cfg = SolverConfig(base_lr=0.02, momentum=0.9, solver_type="SGD")
    data_fn, _ = _linreg_data_fn()

    make = lambda: _make_solver(cfg)

    a = make()
    a.step(5, data_fn)
    ckpt = a.save(str(tmp_path / "snap"))
    a.step(5, data_fn)
    final_direct = np.asarray(a.variables.params["ip"][0])

    b = make()
    b.restore(ckpt)
    assert b.iter == 5
    b.step(5, data_fn)
    final_restored = np.asarray(b.variables.params["ip"][0])
    np.testing.assert_allclose(final_direct, final_restored, rtol=1e-6)


def test_scan_steps_match_separate_dispatches():
    """jitted_scan_steps(n): n solver iterations fused into one device
    program must produce the SAME trajectory as n separate dispatches —
    including the per-iteration lr schedule (step policy flips mid-scan
    to pin that ``it0 + i`` really drives GetLearningRate)."""
    cfg = SolverConfig(base_lr=0.1, momentum=0.9, solver_type="SGD",
                       lr_policy="step", gamma=0.5, stepsize=3)
    data_fn, _ = _linreg_data_fn()
    feeds = data_fn(0)

    a = _make_solver(cfg)
    step, v, s, key = a.jitted_train_step(donate=False)
    for i in range(6):  # crosses the stepsize=3 lr drop
        v, s, loss = step(v, s, i, feeds, key)

    b = _make_solver(cfg)
    scan_fn, sv, ss, skey = b.jitted_scan_steps(6, donate=False)
    sv, ss, losses = scan_fn(sv, ss, 0, feeds, skey)

    assert losses.shape == (6,)
    np.testing.assert_allclose(
        np.asarray(sv.params["ip"][0]), np.asarray(v.params["ip"][0]),
        rtol=1e-5,
    )


def test_scan_steps_stacked_feeds():
    """stacked_feeds=True: step i consumes feed slice i (staged
    minibatches, one dispatch) — equivalent to feeding them one by one."""
    cfg = SolverConfig(base_lr=0.05, solver_type="SGD")
    data_fn, _ = _linreg_data_fn()

    a = _make_solver(cfg)
    step, v, s, key = a.jitted_train_step(donate=False)
    for i in range(4):
        v, s, _ = step(v, s, i, data_fn(i), key)

    b = _make_solver(cfg)
    scan_fn, sv, ss, skey = b.jitted_scan_steps(
        4, donate=False, stacked_feeds=True)
    stacked = {
        k: jnp.stack([data_fn(i)[k] for i in range(4)])
        for k in data_fn(0)
    }
    sv, ss, losses = scan_fn(sv, ss, 0, stacked, skey)
    assert losses.shape == (4,)
    np.testing.assert_allclose(
        np.asarray(sv.params["ip"][0]), np.asarray(v.params["ip"][0]),
        rtol=1e-5,
    )


def test_step_scanned_matches_per_iteration(tmp_path, capsys):
    """Solver.step(scan_chunk=N): same trajectory, same display lines at
    the same iterations, snapshots at the exact reference boundaries."""
    def make():
        cfg = SolverConfig(base_lr=0.02, momentum=0.9, solver_type="SGD",
                           display=2, snapshot=4,
                           snapshot_prefix=str(tmp_path / "snap"))
        return _make_solver(cfg)

    data_fn, _ = _linreg_data_fn()

    a = make()
    a.step(12, data_fn)
    out_a = capsys.readouterr().out
    snaps_a = sorted(p.name for p in tmp_path.glob("snap_iter_*"))
    for p in tmp_path.glob("snap_iter_*"):
        p.unlink()

    b = make()
    b.step(12, data_fn, scan_chunk=4)  # gcd(4, display 2, snapshot 4) = 2
    out_b = capsys.readouterr().out
    snaps_b = sorted(p.name for p in tmp_path.glob("snap_iter_*"))

    np.testing.assert_allclose(
        np.asarray(b.variables.params["ip"][0]),
        np.asarray(a.variables.params["ip"][0]), rtol=1e-5)
    assert b.iter == a.iter == 12
    assert [l for l in out_b.splitlines() if l.startswith("Iteration")] == \
           [l for l in out_a.splitlines() if l.startswith("Iteration")]
    assert snaps_b == snaps_a and snaps_a  # same boundary files


def test_step_scanned_callback_sees_every_iteration():
    cfg = SolverConfig(base_lr=0.02, solver_type="SGD")
    solver = _make_solver(cfg)
    data_fn, _ = _linreg_data_fn()
    seen = []
    solver.step(9, data_fn, callback=lambda it, loss: seen.append(it),
                scan_chunk=4)
    assert seen == list(range(1, 10))


def test_iter_size_accumulation():
    """iter_size=2 with two half-batches == one full batch step (SGD)."""
    cfg1 = SolverConfig(base_lr=0.1, solver_type="SGD", iter_size=1)
    cfg2 = SolverConfig(base_lr=0.1, solver_type="SGD", iter_size=2)
    net = parse(TINY_NET)
    data_fn, _ = _linreg_data_fn()
    full = data_fn(0)

    def make(cfg):
        return Solver(cfg, net, feed_shapes={"target": (16, 1)})

    a = make(cfg1)
    a.step(1, lambda it: full)
    # same data split into two stacked micro-batches of 8... but EuclideanLoss
    # divides by batch num, so two half-batches avg = full-batch result * 2.
    # Use identical micro-batches instead: mean of equal grads == the grad.
    b = make(cfg2)
    half = {k: jnp.stack([v, v]) for k, v in full.items()}
    b.step(1, lambda it: half)
    np.testing.assert_allclose(
        np.asarray(a.variables.params["ip"][0]),
        np.asarray(b.variables.params["ip"][0]),
        rtol=1e-5,
    )


@pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
def test_reference_solver_prototxts_parse():
    for f in [
        "examples/cifar10/cifar10_full_solver.prototxt",
        "examples/mnist/lenet_solver_adam.prototxt",
        "examples/mnist/lenet_solver_rmsprop.prototxt",
        "examples/mnist/lenet_adadelta_solver.prototxt",
        "examples/mnist/mnist_autoencoder_solver_nesterov.prototxt",
        "models/bvlc_alexnet/solver.prototxt",
        "models/bvlc_googlenet/quick_solver.prototxt",
    ]:
        cfg = SolverConfig.from_proto(parse_file(os.path.join(REF, f)))
        assert cfg.base_lr > 0
    cfg = SolverConfig.from_proto(parse_file(f"{REF}/examples/mnist/lenet_solver_adam.prototxt"))
    assert cfg.solver_type == "Adam"
    cfg = SolverConfig.from_proto(parse_file(f"{REF}/models/bvlc_googlenet/quick_solver.prototxt"))
    assert cfg.lr_policy == "poly"


@pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
def test_multi_test_nets_from_test_state():
    """test_state stages build one TEST net each with its own data layers
    (ref: Solver::InitTestNets solver.cpp:135-190; the mnist_autoencoder
    solver's test-on-train / test-on-test pair)."""
    solver_msg = parse_file(f"{REF}/examples/mnist/mnist_autoencoder_solver.prototxt")
    cfg = SolverConfig.from_proto(solver_msg)
    assert cfg.test_states == (("test-on-train",), ("test-on-test",))
    assert cfg.test_iter == (500, 100)

    net_param = parse_file(f"{REF}/examples/mnist/mnist_autoencoder.prototxt")
    solver = Solver(cfg, net_param, feed_shapes={"data": (4, 1, 28, 28)})
    assert len(solver.test_nets) == 2
    # each test net selected exactly its stage's data layer
    for net, stage in zip(solver.test_nets, ("test-on-train", "test-on-test")):
        data_layers = [l for l in net.layers if l.type == "Data"]
        assert len(data_layers) == 1
        assert stage in net.stages

    rs = np.random.RandomState(0)
    fn = lambda b: {"data": rs.rand(4, 1, 28, 28).astype(np.float32)}
    # run both test nets with their own (small) iteration counts
    solver.config = dataclasses_replace_test_iter(cfg, (3, 2))
    res = solver.test_all([fn, fn])
    assert len(res) == 2
    for scores in res:
        assert any("loss" in k or "error" in k for k in scores), scores


def dataclasses_replace_test_iter(cfg, new_iter):
    import dataclasses as _dc

    return _dc.replace(cfg, test_iter=new_iter)


def test_test_state_level_and_validation():
    """NetState level reaches the test net's rule matching; test_iter /
    test net count mismatch fails like InitTestNets' CHECK_EQ."""
    net_param = parse(
        """
        name: "lvl"
        layer { name: "d" type: "Input" top: "data"
                input_param { shape { dim: 2 dim: 4 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "out"
                inner_product_param { num_output: 2 } }
        layer { name: "extra" type: "Power" bottom: "out" top: "pow"
                include { min_level: 1 } }
        """
    )
    base = parse("base_lr: 0.01")
    base.add("test_state", parse("level: 1"))
    base.add("test_iter", 1)
    cfg = SolverConfig.from_proto(base)
    assert cfg.test_levels == (1,)
    solver = Solver(cfg, net_param)
    assert any(l.name == "extra" for l in solver.test_nets[0].layers)
    # default level 0 filters the min_level:1 layer out
    solver0 = Solver(SolverConfig(), net_param)
    assert not any(l.name == "extra" for l in solver0.test_nets[0].layers)

    # CHECK_EQ(test_iter size, num test nets)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="one test_iter per test net"):
        Solver(SolverConfig(test_iter=(5, 5)), net_param)
    # test_all arity mismatch is a clear error
    with _pytest.raises(ValueError, match="one data_fn per test net"):
        solver0.test_all([lambda b: {}, lambda b: {}])


def test_orbax_snapshot_roundtrip(tmp_path):
    """Pod-scale checkpoint backend: params + BN state + optimizer slots +
    iter roundtrip through orbax, sharded arrays preserved (SURVEY §5:
    orbax-style checkpoint of params+opt-state)."""
    pytest.importorskip("orbax.checkpoint")
    from sparknet_tpu import models

    cfg = SolverConfig(base_lr=0.01, momentum=0.9, solver_type="SGD")
    s1 = Solver(cfg, models.cifar10_quick(4))
    rs = np.random.RandomState(0)
    fn = lambda it: {
        "data": rs.randn(4, 3, 32, 32).astype(np.float32) * 40,
        "label": rs.randint(0, 10, 4).astype(np.int32),
    }
    s1.step(3, fn)
    # capture the exact at-snapshot state BEFORE diverging
    at_snap_params = {
        k: [np.asarray(p).copy() for p in v]
        for k, v in s1.variables.params.items()
    }
    at_snap_slot = np.asarray(s1.slots["conv1"][0][0]).copy()
    path = s1.save(str(tmp_path / "snap"), format="orbax")
    assert path.endswith(".orbax")
    s1.step(2, fn)  # diverge after the snapshot

    s2 = Solver(cfg, models.cifar10_quick(4))
    s2.restore(path)
    assert s2.iter == 3
    for lname, plist in s2.variables.params.items():
        for i, p in enumerate(plist):
            np.testing.assert_array_equal(
                np.asarray(p), at_snap_params[lname][i]
            )
    np.testing.assert_array_equal(
        np.asarray(s2.slots["conv1"][0][0]), at_snap_slot
    )
    # momentum history restored too: continuing training matches exactly
    s3 = Solver(cfg, models.cifar10_quick(4))
    s3.restore(path)
    rs_a, rs_b = np.random.RandomState(7), np.random.RandomState(7)
    fa = lambda it: {
        "data": rs_a.randn(4, 3, 32, 32).astype(np.float32) * 40,
        "label": rs_a.randint(0, 10, 4).astype(np.int32),
    }
    fb = lambda it: {
        "data": rs_b.randn(4, 3, 32, 32).astype(np.float32) * 40,
        "label": rs_b.randint(0, 10, 4).astype(np.int32),
    }
    s2.step(2, fa)
    s3.step(2, fb)
    np.testing.assert_allclose(
        np.asarray(s2.variables.params["conv1"][0]),
        np.asarray(s3.variables.params["conv1"][0]),
        atol=0,
    )

    # wrong solver type rejected
    s4 = Solver(SolverConfig(solver_type="Adam"), models.cifar10_quick(4))
    with pytest.raises(ValueError, match="solver_type"):
        s4.restore(path)


def test_orbax_snapshot_sharded_arrays(tmp_path):
    """Sharded params save from their owning devices and restore with the
    live shardings intact (the reason orbax exists next to the npz path)."""
    pytest.importorskip("orbax.checkpoint")
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from sparknet_tpu import models
    from sparknet_tpu.compiler.graph import NetVars

    cfg = SolverConfig(base_lr=0.01)
    s1 = Solver(cfg, models.lenet(8))
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sh = NamedSharding(mesh, P(None, "data"))  # ip1 (500, 800): 800/8
    # shard ip1 weight over its input dim across the mesh
    w = jax.device_put(s1.variables.params["ip1"][0], sh)
    params = {k: list(v) for k, v in s1.variables.params.items()}
    params["ip1"][0] = w
    s1.variables = NetVars(params=params, state=s1.variables.state)

    path = s1.save(str(tmp_path / "sharded"), format="orbax")

    s2 = Solver(cfg, models.lenet(8))
    p2 = {k: list(v) for k, v in s2.variables.params.items()}
    p2["ip1"][0] = jax.device_put(s2.variables.params["ip1"][0], sh)
    s2.variables = NetVars(params=p2, state=s2.variables.state)
    s2.restore(path)
    restored = s2.variables.params["ip1"][0]
    assert restored.sharding == sh
    np.testing.assert_allclose(np.asarray(restored), np.asarray(w))


def test_solve_full_run(tmp_path, capsys):
    """Solver.solve: Step to max_iter, snapshot_after_train, final
    forward display, resume path (ref: Solver::Solve solver.cpp:285-326)."""
    cfg = SolverConfig(
        base_lr=0.02, momentum=0.9, max_iter=30, display=10,
        snapshot_prefix=str(tmp_path / "s"),
    )
    solver = _make_solver(cfg)
    data_fn, _ = _linreg_data_fn()
    loss = solver.solve(data_fn)
    assert solver.iter == 30
    assert os.path.exists(str(tmp_path / "s_iter_30.solverstate.npz"))
    # final display pass printed the post-update loss
    assert "Iteration 30, loss" in capsys.readouterr().out
    assert loss < 1.0

    # resume: restores iter then runs the remaining iterations
    cfg2 = SolverConfig(
        base_lr=0.02, momentum=0.9, max_iter=40,
        snapshot_prefix=str(tmp_path / "r"),
    )
    solver2 = _make_solver(cfg2)
    solver2.solve(data_fn, resume_file=str(tmp_path / "s_iter_30.solverstate.npz"))
    assert solver2.iter == 40


def test_solve_early_exit_and_no_snapshot(tmp_path):
    """Early exit (STOP action) still snapshots but skips the final
    passes; snapshot_after_train=False skips the snapshot; a max_iter
    aligned with the snapshot interval does not double-snapshot."""
    data_fn, _ = _linreg_data_fn()

    cfg = SolverConfig(
        base_lr=0.02, max_iter=20, snapshot_prefix=str(tmp_path / "e"),
    )
    solver = _make_solver(cfg)

    def stop_at_5(it, loss):
        if it >= 5:
            raise KeyboardInterrupt

    solver.solve(data_fn, callback=stop_at_5)
    assert solver.iter == 5
    assert os.path.exists(str(tmp_path / "e_iter_5.solverstate.npz"))

    cfg2 = SolverConfig(base_lr=0.02, max_iter=5, snapshot_after_train=False,
                        snapshot_prefix=str(tmp_path / "n"))
    solver2 = _make_solver(cfg2)
    solver2.solve(data_fn)
    assert not os.path.exists(str(tmp_path / "n_iter_5.solverstate.npz"))

    # snapshot interval lands exactly on max_iter -> Step already saved it;
    # solve must not overwrite (ref: the `iter_ % snapshot != 0` guard)
    cfg3 = SolverConfig(base_lr=0.02, max_iter=6, snapshot=3,
                        snapshot_prefix=str(tmp_path / "a"))
    solver3 = _make_solver(cfg3)
    p = str(tmp_path / "a_iter_6.solverstate.npz")
    solver3.solve(data_fn)
    assert os.path.exists(p)


def test_solve_final_testall(capsys):
    """max_iter on a test_interval boundary triggers the final TestAll."""
    cfg = SolverConfig(
        base_lr=0.02, max_iter=10, test_interval=5, test_iter=(2,),
        snapshot_after_train=False,
    )
    solver = _make_solver(cfg)
    data_fn, _ = _linreg_data_fn()
    results = []
    orig = solver.test_all
    solver.test_all = lambda fns: results.append(orig(fns))
    solver.solve(data_fn, test_fns=[lambda b: data_fn(b)])
    assert len(results) == 1 and len(results[0]) == 1


def test_solve_iter_size_display_and_early_loss(tmp_path):
    """solve() final display handles iter_size>1 feeds; early exit
    returns the live smoothed loss, not a stale 0.0."""
    data_fn, _ = _linreg_data_fn()

    def stacked_fn(it):
        a, b = data_fn(2 * it), data_fn(2 * it + 1)
        return {k: np.stack([a[k], b[k]]) for k in a}

    cfg = SolverConfig(base_lr=0.02, max_iter=10, display=5, iter_size=2,
                       snapshot_after_train=False)
    solver = _make_solver(cfg)
    loss = solver.solve(stacked_fn)
    assert np.isfinite(loss) and loss < 10.0

    cfg2 = SolverConfig(base_lr=0.02, max_iter=50, snapshot_after_train=False)
    solver2 = _make_solver(cfg2)

    def stop(it, loss):
        if it >= 10:
            raise KeyboardInterrupt

    got = solver2.solve(data_fn, callback=stop)
    assert got > 0.0  # live smoothed loss, not the stale init value

    # empty snapshot_prefix + interval dividing max_iter: Step wrote
    # nothing, so solve must still write the final snapshot
    import os
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg3 = SolverConfig(base_lr=0.02, max_iter=6, snapshot=3)
        solver3 = _make_solver(cfg3)
        solver3.solve(data_fn)
        assert os.path.exists("solver_iter_6.solverstate.npz")
    finally:
        os.chdir(cwd)


def test_snapshot_writes_model_file_pair(tmp_path):
    """Snapshots produce the reference's model+state pair (ref:
    Solver::Snapshot solver.cpp:447-466): .caffemodel (BINARYPROTO,
    default) or .caffemodel.h5 (HDF5), loadable by the finetune path."""
    from sparknet_tpu.net import copy_caffemodel_params, copy_hdf5_params

    data_fn, _ = _linreg_data_fn()

    solver = _make_solver(SolverConfig(base_lr=0.02))
    solver.step(3, data_fn)
    solver.save(str(tmp_path / "snap"))
    model = tmp_path / "snap.caffemodel"
    assert model.exists()
    fresh = _make_solver(SolverConfig(base_lr=0.02))
    params, loaded = copy_caffemodel_params(fresh.variables.params, str(model))
    assert "ip" in loaded
    np.testing.assert_allclose(
        np.asarray(params["ip"][0]), np.asarray(solver.variables.params["ip"][0])
    )

    solver_h5 = _make_solver(
        SolverConfig(base_lr=0.02, snapshot_format="HDF5")
    )
    solver_h5.save(str(tmp_path / "h5snap"))
    h5 = tmp_path / "h5snap.caffemodel.h5"
    assert h5.exists()
    _, loaded = copy_hdf5_params(fresh.variables.params, str(h5))
    assert "ip" in loaded

    solver_none = _make_solver(SolverConfig(base_lr=0.02, snapshot_format=""))
    solver_none.save(str(tmp_path / "bare"))
    assert not (tmp_path / "bare.caffemodel").exists()

    # bad values fail at construction, not at the first snapshot boundary
    with pytest.raises(ValueError, match="snapshot_format"):
        _make_solver(SolverConfig(base_lr=0.02, snapshot_format="npz"))


def test_debug_info_prints_per_layer_stats(capsys):
    """SolverParameter.debug_info parity (ref: net.cpp:658-735): every
    iteration prints top-blob data abs-means, param diff abs-means, and
    param data abs-means, computed in-graph."""
    import numpy as np

    from sparknet_tpu import models
    from sparknet_tpu.proto import parse

    solver_msg = parse("base_lr: 0.01\ndebug_info: true\nmax_iter: 5\n")
    cfg = SolverConfig.from_proto(solver_msg)
    assert cfg.debug_info is True

    solver = Solver(cfg, models.lenet(4))
    rs = np.random.RandomState(0)

    def feed(_):
        return {
            "data": rs.randn(4, 1, 28, 28).astype(np.float32),
            "label": rs.randint(0, 10, 4).astype(np.int32),
        }

    solver.step(2, feed)
    out = capsys.readouterr().out
    # one [Forward] line per top blob, Caffe's format
    assert "[Forward] Layer conv1, top blob conv1 data:" in out
    # in-place layers get their OWN execution-time line (relu1 rebinds
    # ip1 — Caffe prints both, net.cpp:658)
    assert "[Forward] Layer relu1, top blob ip1 data:" in out
    assert "[Forward] Layer ip1, top blob ip1 data:" in out
    assert "[Backward] Layer conv1, param blob conv1[0] diff:" in out
    assert "[Update] Layer ip2, param blob ip2[1] data:" in out
    # values are finite numbers, not zeros across the board
    import re

    vals = [float(m) for m in re.findall(r"data: ([0-9.e+-]+)", out)]
    assert vals and all(np.isfinite(v) for v in vals)
    assert any(v > 0 for v in vals)

    # off by default: no debug lines, 3-tuple step path
    solver2 = Solver(SolverConfig(base_lr=0.01), models.lenet(4))
    solver2.step(1, feed)
    assert "[Forward]" not in capsys.readouterr().out


def test_orbax_background_snapshot(tmp_path):
    """background=True streams the snapshot while training continues:
    the save call must not block, the step loop keeps running, and the
    checkpoint commits (with its meta sidecar) by the next restore —
    wait_pending() guards every read path."""
    pytest.importorskip("orbax.checkpoint")
    from sparknet_tpu import models
    from sparknet_tpu.solvers import orbax_io

    cfg = SolverConfig(base_lr=0.01, momentum=0.9, solver_type="SGD")
    s1 = Solver(cfg, models.lenet(4))
    rs = np.random.RandomState(0)
    fn = lambda it: {
        "data": rs.randn(4, 1, 28, 28).astype(np.float32),
        "label": rs.randint(0, 10, 4).astype(np.int32),
    }
    s1.step(2, fn)
    at_snap = {k: [np.asarray(p).copy() for p in v]
               for k, v in s1.variables.params.items()}
    path = s1.save(str(tmp_path / "bg"), format="orbax", background=True)
    s1.step(2, fn)  # training continues while the write streams

    s2 = Solver(cfg, models.lenet(4))
    s2.restore(path)  # wait_pending() inside finalizes the commit
    assert s2.iter == 2
    for lname, plist in s2.variables.params.items():
        for i, p in enumerate(plist):
            np.testing.assert_array_equal(np.asarray(p), at_snap[lname][i])
    # sidecar landed after commit (solver-type validation active)
    assert os.path.exists(os.path.join(path, "sparknet_meta.json"))
    assert not orbax_io._PENDING

    # npz + background is a loud error, not a silent sync save
    with pytest.raises(ValueError, match="background"):
        s1.save(str(tmp_path / "x"), background=True)


@pytest.mark.parametrize("stype", ["SGD", "Adam"])
def test_pure_bf16_scan_slot_dtype_fixpoint(stype):
    """Pure-bf16 training (params AND slots stored bf16, the
    SPARKNET_BENCH_PARAM_DTYPE=bf16 arm): the update must return slots
    in the stored dtype.  ctx.rate is an f32 scalar, so unchecked rule
    math promotes a bf16 history to f32 — under jitted_scan_steps that
    breaks the lax.scan carry contract (probe-40 on-chip failure,
    docs/evidence_r4/alexnet_bf16params_ab.txt)."""
    from sparknet_tpu.common import set_config

    set_config(compute_dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    try:
        cfg = SolverConfig(base_lr=0.02, momentum=0.9, solver_type=stype)
        solver = _make_solver(cfg)
        data_fn, _ = _linreg_data_fn()
        scan_fn, sv, ss, skey = solver.jitted_scan_steps(3, donate=False)
        sv, ss, losses = scan_fn(sv, ss, 0, data_fn(0), skey)
        assert losses.shape == (3,)
        assert np.all(np.isfinite(np.asarray(losses, np.float32)))
        for lname, plist in ss.items():
            for blob_slots in plist:
                for h in blob_slots:
                    assert h.dtype == jnp.bfloat16, (lname, h.dtype)
    finally:
        set_config(compute_dtype=jnp.float32, param_dtype=jnp.float32)
