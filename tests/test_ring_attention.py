"""Ring attention correctness on the virtual 8-device mesh.

The sharded collective must match unsharded full-sequence attention to
float tolerance, for causal and bidirectional masks, under jit and grad,
and on a combined (data, seq) 2-D mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparknet_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ring_self_attention,
)

from sparknet_tpu.parallel import shard_map


def _qkv(B=2, H=2, S=32, D=8, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    q, k, v = _qkv()
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_under_jit_and_2d_mesh():
    """(data=2, seq=4) mesh: batch sharded over data, sequence over seq."""
    q, k, v = _qkv(B=4, S=16)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "seq"))
    spec = P("data", None, "seq", None)

    fn = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )
    sharding = NamedSharding(mesh, spec)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    out = fn(*args)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match_reference():
    """d(loss)/d(q,k,v) through the ring equals the unsharded gradient —
    the primitive is trainable, not inference-only."""
    q, k, v = _qkv(S=16)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    spec = P(None, None, "seq", None)
    sharding = NamedSharding(mesh, spec)

    ring_fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )

    def ring_loss(q, k, v):
        return jnp.sum(ring_fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(*args)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_long_sequence_memory_shape():
    """Each device only ever holds S_local-size score blocks: a sequence 8x
    the per-device block runs and matches (the linear-scaling property)."""
    q, k, v = _qkv(B=1, H=1, S=256, D=4, seed=3)
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    out = ring_self_attention(mesh, q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_reference_attention_softmax_rows_sum_to_one():
    q, k, v = _qkv(S=8)
    out = reference_attention(q, k, jnp.ones_like(v), causal=False)
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)


# ---------------------------------------------------------------- ulysses
class TestUlysses:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style) must agree
    with the unsharded oracle and with ring attention."""

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]), ("seq",))

    def _qkv(self, rng, B=2, H=8, S=32, D=4):
        mk = lambda: jnp.asarray(rng.randn(B, H, S, D) * 0.5, jnp.float32)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, causal):
        from sparknet_tpu.parallel.ulysses import ulysses_self_attention

        q, k, v = self._qkv(rng)
        mesh = self._mesh()
        out = ulysses_self_attention(mesh, q, k, v, causal=causal)
        expect = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5
        )

    def test_matches_ring(self, rng):
        from sparknet_tpu.parallel.ulysses import ulysses_self_attention

        q, k, v = self._qkv(rng)
        mesh = self._mesh()
        u = ulysses_self_attention(mesh, q, k, v, causal=True)
        r = ring_self_attention(mesh, q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(u), np.asarray(r), atol=3e-5, rtol=3e-5)

    def test_grad_flows(self, rng):
        from sparknet_tpu.parallel.ulysses import ulysses_attention

        q, k, v = self._qkv(rng)
        mesh = self._mesh()
        spec = P(None, None, "seq", None)
        fn = shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis_name="seq"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
        loss = lambda a: jnp.sum(fn(a, k, v) ** 2)
        g = jax.jit(jax.grad(loss))(q)
        assert np.isfinite(np.asarray(g)).all()
        # matches grad of the unsharded oracle
        loss_ref = lambda a: jnp.sum(reference_attention(a, k, v) ** 2)
        g_ref = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4, rtol=5e-4)

    def test_head_divisibility_enforced(self, rng):
        from sparknet_tpu.parallel.ulysses import ulysses_self_attention

        q, k, v = self._qkv(rng, H=6)  # 6 heads on an 8-way mesh
        with pytest.raises(ValueError, match="divisible"):
            ulysses_self_attention(self._mesh(), q, k, v)
        q, k, v = self._qkv(rng, S=30)  # 30 not divisible by 8
        with pytest.raises(ValueError, match="sequence length"):
            ulysses_self_attention(self._mesh(), q, k, v)
