"""Ring attention correctness on the virtual 8-device mesh.

The sharded collective must match unsharded full-sequence attention to
float tolerance, for causal and bidirectional masks, under jit and grad,
and on a combined (data, seq) 2-D mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparknet_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ring_self_attention,
)

from sparknet_tpu.parallel import shard_map


def _qkv(B=2, H=2, S=32, D=8, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    q, k, v = _qkv()
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_under_jit_and_2d_mesh():
    """(data=2, seq=4) mesh: batch sharded over data, sequence over seq."""
    q, k, v = _qkv(B=4, S=16)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "seq"))
    spec = P("data", None, "seq", None)

    fn = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )
    sharding = NamedSharding(mesh, spec)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    out = fn(*args)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match_reference():
    """d(loss)/d(q,k,v) through the ring equals the unsharded gradient —
    the primitive is trainable, not inference-only."""
    q, k, v = _qkv(S=16)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    spec = P(None, None, "seq", None)
    sharding = NamedSharding(mesh, spec)

    ring_fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )

    def ring_loss(q, k, v):
        return jnp.sum(ring_fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(*args)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_long_sequence_memory_shape():
    """Each device only ever holds S_local-size score blocks: a sequence 8x
    the per-device block runs and matches (the linear-scaling property)."""
    q, k, v = _qkv(B=1, H=1, S=256, D=4, seed=3)
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    out = ring_self_attention(mesh, q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_reference_attention_softmax_rows_sum_to_one():
    q, k, v = _qkv(S=8)
    out = reference_attention(q, k, jnp.ones_like(v), causal=False)
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)
