"""bench.py helper units: the pieces that must fail fast BEFORE a dial
(a malformed A/B knob costing chip time is a round-4-class loss) and the
zoo guard added for the crop-96 GoogLeNet walkthrough."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _parse_compiler_options  # noqa: E402


def test_parse_compiler_options_roundtrip():
    assert _parse_compiler_options("") == {}
    assert _parse_compiler_options("a=1") == {"a": "1"}
    assert _parse_compiler_options(" a = 1 , b=x=y ") == {
        "a": "1", "b": "x=y"}


def test_parse_compiler_options_malformed_fails_fast():
    with pytest.raises(SystemExit, match="key=value"):
        _parse_compiler_options("xla_tpu_foo")


def test_googlenet_rejects_non_multiple_of_32_crop():
    """ceil-mode pooling would silently leave pool5 non-global for such
    crops (round-5 review finding) — the builder rejects them loudly."""
    from sparknet_tpu.models import zoo

    with pytest.raises(ValueError, match="multiple of 32"):
        zoo.googlenet(batch=1, num_classes=10, crop=95)


# -- bank_guard: the one blessed evidence sink (graftlint bank-guard) -------


@pytest.mark.smoke
def test_bank_guard_measured_writes_in_place(tmp_path):
    from sparknet_tpu.common import bank_guard

    path = str(tmp_path / "int8_bench_last.json")
    written = bank_guard(path, {"arms": [1, 2]}, measured=True)
    assert written == path
    import json

    with open(path) as f:
        payload = json.load(f)
    assert payload == {"arms": [1, 2]}  # no rehearsal stamp on evidence
    assert not os.path.exists(path + ".tmp")  # atomic: tmp file consumed


@pytest.mark.smoke
def test_bank_guard_unmeasured_diverts_and_stamps(tmp_path):
    """A CPU rehearsal must land OUTSIDE the requested (docs/) location,
    stamped so it can never read as chip evidence — the round-5 rule
    after a smoke run overwrote docs/int8_bench_last.json."""
    import json
    import tempfile

    from sparknet_tpu.common import bank_guard

    path = str(tmp_path / "docs" / "int8_bench_last.json")
    written = bank_guard(path, {"arms": []}, measured=False)
    assert written is not None
    assert not os.path.exists(path)  # nothing under the evidence path
    assert written == os.path.join(tempfile.gettempdir(),
                                   "int8_bench_last_rehearsal.json")
    with open(written) as f:
        payload = json.load(f)
    assert payload["rehearsal"] is True
    assert payload["arms"] == []


@pytest.mark.smoke
def test_bank_path_idempotent_on_rehearsal_names():
    from sparknet_tpu.common import bank_path

    p1 = bank_path("docs/bench_extra_last.json", measured=False)
    assert bank_path(p1, measured=False) == p1  # no _rehearsal_rehearsal
    assert bank_path("docs/x_last.json", measured=True) == "docs/x_last.json"


@pytest.mark.smoke
def test_record_last_good_refuses_unmeasured_records(tmp_path, monkeypatch):
    """Defense in depth behind the callers' platform gate: a rec without
    measured:true diverts away from docs/bench_last_good.json."""
    import bench

    path = str(tmp_path / "bench_last_good.json")
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", path)
    bench.record_last_good({"metric": "m", "value": 1.0, "measured": False})
    assert not os.path.exists(path)
    bench.record_last_good({"metric": "m", "value": 2.0, "measured": True})
    assert os.path.exists(path)


@pytest.mark.smoke
def test_measured_bw_frac_reads_newest_banked_artifact():
    """The measured half of the bandwidth story (VERDICT item 4): the
    record field comes from the newest banked
    docs/evidence_r*/traffic_<model>_*_<dtype>.json, or is absent."""
    import bench

    hit = bench.measured_bw_frac("alexnet", "f32")
    assert hit is not None
    assert 0 < hit["measured_bw_frac"] <= 1.2  # GoogLeNet-style >1 is real
    assert hit["measured_bw_source"].startswith("docs/evidence_r")
    # no banked bf16 traffic artifact yet -> no field, never a guess
    assert bench.measured_bw_frac("alexnet", "bf16") is None
    assert bench.measured_bw_frac("nope", "f32") is None
