"""bench.py helper units: the pieces that must fail fast BEFORE a dial
(a malformed A/B knob costing chip time is a round-4-class loss) and the
zoo guard added for the crop-96 GoogLeNet walkthrough."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _parse_compiler_options  # noqa: E402


def test_parse_compiler_options_roundtrip():
    assert _parse_compiler_options("") == {}
    assert _parse_compiler_options("a=1") == {"a": "1"}
    assert _parse_compiler_options(" a = 1 , b=x=y ") == {
        "a": "1", "b": "x=y"}


def test_parse_compiler_options_malformed_fails_fast():
    with pytest.raises(SystemExit, match="key=value"):
        _parse_compiler_options("xla_tpu_foo")


def test_googlenet_rejects_non_multiple_of_32_crop():
    """ceil-mode pooling would silently leave pool5 non-global for such
    crops (round-5 review finding) — the builder rejects them loudly."""
    from sparknet_tpu.models import zoo

    with pytest.raises(ValueError, match="multiple of 32"):
        zoo.googlenet(batch=1, num_classes=10, crop=95)
