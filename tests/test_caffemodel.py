"""Binary .caffemodel wire-format tests + reference-zoo prototxt compat.

The codec must interoperate with files written by the reference's protobuf
(ref: net.cpp:911 ToProto / solver.cpp Snapshot), so beyond roundtrips the
tests pin hand-computed wire bytes and decode a synthesized legacy
V1LayerParameter snapshot.
"""

import os
import struct

import jax
import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.net import TPUNet
from sparknet_tpu.proto.binary import (
    CaffeModel,
    CaffeModelLayer,
    dumps_caffemodel,
    loads_caffemodel,
    _varint,
    _tag,
    _len_field,
    _LEN,
    _VARINT,
)

REF = "/root/reference/caffe"


# ---------------------------------------------------------------- wire level
def test_roundtrip():
    rs = np.random.RandomState(0)
    model = CaffeModel(
        "m",
        [
            CaffeModelLayer("conv1", "Convolution",
                            [rs.randn(4, 3, 5, 5).astype(np.float32),
                             rs.randn(4).astype(np.float32)]),
            CaffeModelLayer("relu1", "ReLU", []),
            CaffeModelLayer("ip1", "InnerProduct",
                            [rs.randn(10, 64).astype(np.float32),
                             rs.randn(10).astype(np.float32)]),
        ],
    )
    out = loads_caffemodel(dumps_caffemodel(model))
    assert out.name == "m"
    assert [l.name for l in out.layers] == ["conv1", "relu1", "ip1"]
    assert out.layers[0].type == "Convolution"
    for a, b in zip(model.layers[0].blobs, out.layers[0].blobs):
        np.testing.assert_array_equal(a, b)
    assert out.layers[2].blobs[0].shape == (10, 64)


def test_golden_wire_bytes():
    """A minimal NetParameter encoded by hand must decode identically —
    pins the exact field numbers/wire types against caffe.proto."""
    # BlobProto { shape { dim: 2 dim: 1 } data: [1.5, -2.0] }
    shape_msg = _len_field(1, _varint(2) + _varint(1))  # packed dims
    blob = _len_field(7, shape_msg) + _len_field(
        5, struct.pack("<2f", 1.5, -2.0))
    # LayerParameter { name:"ip" type:"InnerProduct" blobs:blob }
    layer = _len_field(1, b"ip") + _len_field(2, b"InnerProduct") + _len_field(7, blob)
    # NetParameter { name:"g" layer:layer }  (field 100)
    net = _len_field(1, b"g") + _len_field(100, layer)
    m = loads_caffemodel(net)
    assert m.name == "g"
    assert m.layers[0].name == "ip" and m.layers[0].type == "InnerProduct"
    np.testing.assert_allclose(m.layers[0].blobs[0], [[1.5], [-2.0]])


def test_v1_legacy_layers_decode():
    """Old snapshots use NetParameter.layers (field 2, V1LayerParameter:
    name=4, type=5 enum, blobs=6) and legacy 4D num/channels/height/width."""
    legacy_blob = (
        _tag(1, _VARINT) + _varint(1)   # num
        + _tag(2, _VARINT) + _varint(1)  # channels
        + _tag(3, _VARINT) + _varint(2)  # height
        + _tag(4, _VARINT) + _varint(2)  # width
        + _len_field(5, struct.pack("<4f", 1, 2, 3, 4))
    )
    v1_layer = (
        _len_field(4, b"ip1")
        + _tag(5, _VARINT) + _varint(14)  # LayerType.INNER_PRODUCT
        + _len_field(6, legacy_blob)
    )
    net = _len_field(1, b"old") + _len_field(2, v1_layer)
    m = loads_caffemodel(net)
    l = m.layers[0]
    assert l.name == "ip1" and l.type == "InnerProduct"
    assert l.blobs[0].shape == (1, 1, 2, 2)
    np.testing.assert_allclose(l.blobs[0].reshape(-1), [1, 2, 3, 4])


def test_unpacked_float_data_decodes():
    """proto2 allows packed fields to arrive unpacked; readers must accept
    both encodings."""
    from sparknet_tpu.proto.binary import _I32

    def f32(field, v):
        return _tag(field, _I32) + struct.pack("<f", v)

    blob = f32(5, 7.0) + f32(5, 8.0)
    layer = _len_field(1, b"b") + _len_field(2, b"Bias") + _len_field(7, blob)
    m = loads_caffemodel(_len_field(100, layer))
    np.testing.assert_allclose(m.layers[0].blobs[0], [7.0, 8.0])


# ---------------------------------------------------------------- net level
@pytest.mark.smoke
def test_tpunet_caffemodel_roundtrip(tmp_path):
    net = TPUNet(models.lenet_solver(), models.lenet(4))
    path = str(tmp_path / "lenet.caffemodel")
    net.save_caffemodel(path)

    net2 = TPUNet(models.lenet_solver(), models.lenet(4))
    loaded = net2.load_caffemodel(path)
    assert set(loaded) == {"conv1", "conv2", "ip1", "ip2"}
    for lname in loaded:
        for a, b in zip(net.solver.variables.params[lname],
                        net2.solver.variables.params[lname]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # extension-dispatching path
    net2.load_weights_from_file(path)


def test_load_caffemodel_shape_mismatch_raises(tmp_path):
    net = TPUNet(models.lenet_solver(), models.lenet(4))
    path = str(tmp_path / "lenet.caffemodel")
    net.save_caffemodel(path)
    other = TPUNet(models.lenet_solver(), models.lenet(4, num_classes=7))
    with pytest.raises(ValueError, match="shape"):
        other.load_caffemodel(path)


def test_load_caffemodel_ignores_unknown_layers(tmp_path):
    """CopyTrainedLayersFrom: source layers missing from the target net are
    skipped (ref: net.cpp:737-805)."""
    model = CaffeModel("x", [CaffeModelLayer("nonexistent", "Convolution",
                                             [np.zeros((2, 2), np.float32)])])
    path = str(tmp_path / "x.caffemodel")
    with open(path, "wb") as f:
        f.write(dumps_caffemodel(model))
    net = TPUNet(models.lenet_solver(), models.lenet(2))
    assert net.load_caffemodel(path) == []


# ------------------------------------------------------- reference zoo compat
@pytest.mark.skipif(not os.path.isdir(REF), reason="reference tree not mounted")
@pytest.mark.parametrize(
    "prototxt,feed",
    [
        ("examples/mnist/lenet_train_test.prototxt", (2, 1, 28, 28)),
        ("examples/cifar10/cifar10_quick_train_test.prototxt", (2, 3, 32, 32)),
        ("examples/cifar10/cifar10_full_train_test.prototxt", (2, 3, 32, 32)),
        ("models/bvlc_alexnet/train_val.prototxt", (1, 3, 227, 227)),
        ("models/bvlc_reference_caffenet/train_val.prototxt", (1, 3, 227, 227)),
        ("models/bvlc_googlenet/train_val.prototxt", (1, 3, 224, 224)),
    ],
)
def test_reference_zoo_prototxt_compiles(prototxt, feed):
    """Every zoo model file the reference ships parses with our text-format
    parser, survives the data-layer surgery, compiles, and runs forward."""
    import jax.numpy as jnp

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.proto_loader import load_net_prototxt, replace_data_layers

    b, c, h, w = feed
    net_param = replace_data_layers(
        load_net_prototxt(os.path.join(REF, prototxt)), b, b, c, h, w
    )
    net = Network(net_param, Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {"data": jnp.zeros(feed, jnp.float32), "label": jnp.zeros((b,), jnp.int32)}
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    assert np.isfinite(float(loss)), prototxt


def test_split_packed_chunks_concatenate():
    """Packed repeated data split across chunks (legal proto2) accumulates."""
    blob = (_len_field(5, struct.pack("<2f", 1.0, 2.0))
            + _len_field(5, struct.pack("<2f", 3.0, 4.0)))
    layer = _len_field(1, b"w") + _len_field(2, b"X") + _len_field(7, blob)
    m = loads_caffemodel(_len_field(100, layer))
    np.testing.assert_allclose(m.layers[0].blobs[0], [1, 2, 3, 4])


def test_load_caffemodel_permissive_skips_mismatch(tmp_path):
    net = TPUNet(models.lenet_solver(), models.lenet(4))
    path = str(tmp_path / "lenet.caffemodel")
    net.save_caffemodel(path)
    other = TPUNet(models.lenet_solver(), models.lenet(4, num_classes=7))
    loaded = other.load_caffemodel(path, strict_shapes=False)
    # ip2 (10 classes vs 7) skipped; the rest load
    assert "ip2" not in loaded and "conv1" in loaded


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference tree not mounted")
def test_reference_siamese_prototxt_compiles():
    """The weight-sharing siamese example parses, survives surgery with its
    nonstandard pair_data/sim tops, compiles, and shares params."""
    import jax.numpy as jnp

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler.graph import Network
    from sparknet_tpu.proto_loader import load_net_prototxt, replace_data_layers

    B = 2
    net_param = replace_data_layers(
        load_net_prototxt(os.path.join(
            REF, "examples/siamese/mnist_siamese_train_test.prototxt")),
        B, B, 2, 28, 28,
    )
    net = Network(net_param, Phase.TRAIN)
    assert ("conv1_p", 0) in net.param_aliases
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "pair_data": jnp.zeros((B, 2, 28, 28), jnp.float32),
        "sim": jnp.zeros((B,), jnp.float32),
    }
    blobs, _, loss = net.apply(variables, feeds, rng=jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))


def test_net_surgery_full_conv_transplant():
    """The net_surgery workflow (ref: caffe/examples/net_surgery/
    net_surgery.ipynb + bvlc_caffenet_full_conv.prototxt): transplant an
    InnerProduct's weights into an equivalent Convolution whose kernel
    covers its whole input — outputs must match exactly."""
    import jax

    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler import Network
    from sparknet_tpu.compiler.graph import NetVars
    from sparknet_tpu.proto import parse

    fc_net = Network(parse(
        """
        input: "data" input_shape { dim: 2 dim: 3 dim: 6 dim: 6 }
        layer { name: "fc" type: "InnerProduct" bottom: "data" top: "out"
                inner_product_param { num_output: 5
                  weight_filler { type: "xavier" } } }
        """
    ), Phase.TEST)
    conv_net = Network(parse(
        """
        input: "data" input_shape { dim: 2 dim: 3 dim: 6 dim: 6 }
        layer { name: "fc-conv" type: "Convolution" bottom: "data" top: "out"
                convolution_param { num_output: 5 kernel_size: 6 } }
        """
    ), Phase.TEST)
    fcv = fc_net.init(jax.random.PRNGKey(1))
    cv = conv_net.init(jax.random.PRNGKey(2))
    # the notebook's transplant: conv W = fc W reshaped to (out, C, kh, kw)
    w, b = fcv.params["fc"]
    cv = NetVars(
        params={"fc-conv": [w.reshape(5, 3, 6, 6), b]}, state=cv.state
    )
    x = np.random.RandomState(0).randn(2, 3, 6, 6).astype(np.float32)
    fc_out, _, _ = fc_net.apply(fcv, {"data": x}, rng=None)
    conv_out, _, _ = conv_net.apply(cv, {"data": x}, rng=None)
    assert np.allclose(
        np.asarray(fc_out["out"]),
        np.asarray(conv_out["out"]).reshape(2, 5),
        atol=1e-4,
    )
