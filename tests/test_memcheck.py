"""memcheck: per-defect fixtures + the banked memory-contract smoke gate.

Mirrors test_graphcheck.py for the third analysis engine: the liveness
walk is pinned against a hand-computed toy program (including the
donation credit whose absence double-counts the carry), the two
estimators must agree on the cheap real modes (solo + dp) within the
documented tolerance, the batch-fit arithmetic is monotone by
construction, the VMEM audit flags an over-budget kernel, the manifest
loop round-trips bank/drift/allow, and the window runner's queue
pre-flight refuses a predicted-OOM job — journaled ``preflight_oom``,
dial never attempted.  The full mode sweep is the slow-marked twin
(tests/test_memcheck_sweep.py).
"""

import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from sparknet_tpu.analysis import mem_model
from sparknet_tpu.analysis.mem_model import (
    HBM_USABLE_FRAC,
    MemEqn,
    MemProgram,
    PEAK_RATIO_WINDOW,
    RESIDENCY_TOL_BYTES,
    V5E_HBM_BYTES,
    V5E_VMEM_BYTES,
    affine_fit,
    max_fit_batch,
    mode_footprint,
    parse_bench_job,
    peak_residency,
    predicted_bytes,
    preflight_job,
)
from sparknet_tpu.analysis.memcheck import (
    MEM_RULES,
    extract_program,
    run_batch_fit,
    run_memcheck,
    run_vmem_audit,
    sources_fingerprint,
    trace_mem,
)

pytestmark = pytest.mark.smoke

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the liveness walk vs a hand-computed toy program -----------------------


def _toy(donated=("a",)):
    """inputs a=100 (donated by default), b=40; a -> t1(30) -> out(20).

    Hand walk (donated case): entry live {a, b} = 140; eqn0 writes t1
    -> 170 (the peak; a dies after, its last read); eqn1 writes out ->
    90.  Donation credit subtracts a's 100 once (the donated input and
    the output aliasing it are one allocation): peak 70, residency
    100+40+20-100 = 60, temp 10.
    """
    return MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("t1",)),
              MemEqn(reads=("t1", "b"), writes=("out",))],
        sizes={"a": 100, "b": 40, "t1": 30, "out": 20},
        inputs=["a", "b"], outputs=["out"],
        donated=frozenset(donated),
    )


def test_liveness_walk_matches_hand_computation():
    res = peak_residency(_toy())
    assert res == {"peak_bytes": 70, "residency_bytes": 60,
                   "temp_bytes": 10, "peak_at_eqn": 0}


def test_undonated_carry_is_counted_twice():
    """Dropping the donation holds both the input carry and the output
    alongside each other: residency and peak grow by exactly the
    carry's bytes — the 2x params+slots class the residency tolerance
    exists to catch."""
    donated = peak_residency(_toy())
    undonated = peak_residency(_toy(donated=()))
    assert undonated["residency_bytes"] - donated["residency_bytes"] == 100
    # peak grows by AT LEAST the carry (here more: the undying input
    # also overlaps the buffers the donated walk had already freed)
    assert undonated["peak_bytes"] - donated["peak_bytes"] >= 100
    assert undonated["peak_bytes"] == 190  # 140 entry + t1 + out, hand-walked


def test_scratch_term_only_counts_on_the_xcheck_side():
    prog = MemProgram(
        eqns=[MemEqn(reads=("a",), writes=("out",), scratch=1000)],
        sizes={"a": 10, "out": 10}, inputs=["a"], outputs=["out"])
    assert peak_residency(prog)["peak_bytes"] == 20
    assert peak_residency(prog, xcheck=True)["peak_bytes"] == 1020


def test_extract_program_credits_only_established_donation():
    """The same step jitted with and without donate_argnums: only the
    lowering that actually establishes aliasing gets the credit."""
    def step(w, x):
        return w + x.sum(), (w * w).sum()

    w = jnp.ones((128,), jnp.float32)
    x = jnp.ones((16,), jnp.float32)
    traced = jax.jit(step, donate_argnums=(0,)).trace(w, x)
    donated = extract_program(traced.jaxpr, donated_flags=[True, False])
    plain = extract_program(traced.jaxpr, donated_flags=[False, False])
    assert donated.donated_bytes() == w.nbytes
    assert plain.donated_bytes() == 0
    d = peak_residency(donated)
    p = peak_residency(plain)
    assert p["residency_bytes"] - d["residency_bytes"] == w.nbytes


# -- the estimator-agreement gate on the cheap real modes -------------------


def test_memcheck_smoke_gate_solo_and_dp():
    """THE ratchet, memory edition: the two cheap modes must match the
    banked manifests with zero unsuppressed findings, and the two
    independent estimators must agree within the documented tolerance
    (residency tight, peak inside the ratio window)."""
    findings, manifests = run_memcheck(["solo", "dp"])
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "unsuppressed memcheck findings:\n" + "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    for mode in ("solo", "dp"):
        c = manifests[mode]["contract"]
        assert c["residency_delta_bytes"] <= RESIDENCY_TOL_BYTES
        lo, hi = PEAK_RATIO_WINDOW
        assert lo <= c["peak_ratio"] <= hi
        # donation is established on both real modes (the carry credit)
        assert c["donated_bytes"] > 0
        budget = int(V5E_HBM_BYTES * HBM_USABLE_FRAC)
        assert c["analytic"]["peak_bytes"] < budget
    # dp shards the batch over 8 devices: its per-device activation
    # footprint must come in below solo's single-chip one
    assert (manifests["dp"]["contract"]["analytic"]["peak_bytes"]
            < manifests["solo"]["contract"]["analytic"]["peak_bytes"])


def test_trace_mem_residency_matches_xla_on_solo():
    from sparknet_tpu.parallel.modes import build_target

    art = trace_mem(build_target("solo", 8))
    res = peak_residency(art.program)
    assert abs(res["residency_bytes"] - art.xla["residency_bytes"]) \
        <= RESIDENCY_TOL_BYTES


# -- batch-fit arithmetic ---------------------------------------------------


def test_affine_fit_and_monotonicity():
    c0, c1 = affine_fit(8, 800, 16, 1600)
    assert (c0, c1) == (0, 100)
    assert predicted_bytes(c0, c1, 32) == 3200
    # monotone in budget, anti-monotone in the coefficients
    assert max_fit_batch(0, 100, 10_000) == 96  # floor to multiple of 8
    assert max_fit_batch(0, 100, 20_000) >= max_fit_batch(0, 100, 10_000)
    assert max_fit_batch(5_000, 100, 10_000) <= max_fit_batch(0, 100, 10_000)
    assert max_fit_batch(0, 200, 10_000) <= max_fit_batch(0, 100, 10_000)
    # an infeasible constant term is 0, not negative
    assert max_fit_batch(20_000, 100, 10_000) == 0
    with pytest.raises(ValueError):
        affine_fit(8, 1, 8, 2)


def test_mode_footprint_divisors():
    entry = {"c0": 1000, "c1": 10, "params_slots_bytes": 600,
             "tp_params_slots_bytes": 350}
    solo = mode_footprint(entry, "solo", 80)
    assert solo == 1000 + 800
    # dp divides the activation term by the data axis (8), not params
    assert mode_footprint(entry, "dp", 80) == 1000 + 100
    # tp swaps in the per-blob-sharded params+slots figure
    assert mode_footprint(entry, "tp", 80) == 1000 - 600 + 350 + 800
    # gpipe places 1/S of the params but holds every microbatch
    gpipe = mode_footprint(entry, "gpipe", 80)
    assert gpipe == int(1000 - 600 + 600 / 8 + 800)


def test_batch_fit_real_family_is_monotone(tmp_path):
    """cifar10_quick through the real abstract-trace path: activations
    linear in batch, bf16 fits at least as many images as f32, dp at
    least as many as solo."""
    findings, table = run_batch_fit(
        families=["cifar10_quick"],
        banked_path=str(tmp_path / "fit.json"), update=True)
    assert findings == []
    fam = table["families"]["cifar10_quick"]
    for dtype in ("f32", "bf16"):
        entry = fam[dtype]
        assert entry["c1"] > 0
        assert entry["max_batch"]["dp"] >= entry["max_batch"]["solo"]
    assert (fam["bf16"]["max_batch"]["solo"]
            >= fam["f32"]["max_batch"]["solo"])
    # the table reloads clean (bank -> verify round-trip)
    findings, _ = run_batch_fit(
        families=["cifar10_quick"],
        banked_path=str(tmp_path / "fit.json"))
    assert findings == []


# -- VMEM audit -------------------------------------------------------------


def test_vmem_audit_real_kernels_fit():
    problems, contract = run_vmem_audit()
    assert problems == []
    assert len(contract["points"]) >= 3
    for p in contract["points"]:
        assert p["fits"] and p["bytes"] <= V5E_VMEM_BYTES


def test_vmem_audit_flags_over_budget_kernel(monkeypatch):
    import sparknet_tpu.ops.pallas_kernels as pk

    points = pk.vmem_audit_points() + [{
        "kernel": "flash",
        "note": "fixture: S=1M full-fiber K/V",
        "bytes": pk.flash_vmem_bytes(1 << 20, 64),
    }]
    monkeypatch.setattr(pk, "vmem_audit_points", lambda: points)
    problems, contract = run_vmem_audit()
    assert [p["rule"] for p in problems] == ["mem-vmem-exceeded"]
    assert "fixture" in problems[0]["message"]
    assert contract["points"][-1]["fits"] is False


def test_vmem_bounds_read_the_tiling_constants():
    from sparknet_tpu.ops.pallas_kernels import (
        _BK, _TILE, flash_vmem_bytes, lrn_vmem_bytes)

    # linear in the channel fiber / sequence length by construction
    assert lrn_vmem_bytes(256) == 2 * lrn_vmem_bytes(128)
    assert lrn_vmem_bytes(96) == 7 * 96 * _TILE * 4
    assert flash_vmem_bytes(4096, 64) > flash_vmem_bytes(2048, 64)
    # sequence length rounds up to the K-step tile
    assert flash_vmem_bytes(_BK + 1, 16) == flash_vmem_bytes(2 * _BK, 16)


# -- manifest machinery -----------------------------------------------------


def test_manifest_bank_diff_and_allow(tmp_path):
    """moe (sub-second to trace) exercises the full manifest loop:
    missing -> banked -> clean -> drift -> allow-suppressed."""
    banked = str(tmp_path / "contracts")
    findings, _ = run_memcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["mem-manifest-missing"]

    findings, _ = run_memcheck(["moe"], banked_dir=banked, update=True)
    assert findings == []
    mpath = tmp_path / "contracts" / "moe.json"
    assert mpath.exists()

    findings, _ = run_memcheck(["moe"], banked_dir=banked)
    assert findings == []  # steady state: re-run diffs clean

    banked_manifest = json.loads(mpath.read_text())
    banked_manifest["contract"]["analytic"]["peak_bytes"] = 99
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_memcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["mem-manifest-drift"]
    assert not findings[0].suppressed
    assert "peak_bytes" in findings[0].message

    banked_manifest["allow"] = {
        "mem-manifest-drift": "fixture: tampered peak"}
    mpath.write_text(json.dumps(banked_manifest))
    findings, _ = run_memcheck(["moe"], banked_dir=banked)
    assert [f.rule for f in findings] == ["mem-manifest-drift"]
    assert findings[0].suppressed


def test_sources_fingerprint_covers_the_contract_surface():
    fp = sources_fingerprint()
    for rel in ("sparknet_tpu/models/zoo.py",
                "sparknet_tpu/parallel/sharding.py",
                "sparknet_tpu/ops/pallas_kernels.py",
                "sparknet_tpu/solvers/solver.py",
                "sparknet_tpu/analysis/mem_model.py"):
        assert rel in fp
    assert all(len(h) == 64 for h in fp.values())


def test_rule_catalog():
    assert set(MEM_RULES) == {
        "mem-residency-mismatch", "mem-estimator-divergence",
        "mem-hbm-exceeded", "mem-vmem-exceeded", "mem-fit-infeasible",
        "mem-manifest-missing", "mem-manifest-drift",
    }


# -- queue pre-flight (mem_model side: stdlib-only, runner-consumable) ------


def test_parse_bench_job_shapes():
    assert parse_bench_job({
        "name": "headline", "argv": ["python", "-u", "bench.py"],
        "env": {"SPARKNET_BENCH_MODEL": "vgg16",
                "SPARKNET_BENCH_BATCH": "128"},
    }) == {"model": "vgg16", "batch": 128, "dtype": "bf16"}
    # bench.py defaulting mirrors the tool (alexnet/256/bf16)
    assert parse_bench_job({"argv": ["python", "-u", "bench.py"]}) == \
        {"model": "alexnet", "batch": 256, "dtype": "bf16"}
    assert parse_bench_job({
        "argv": ["python", "-u", "tools/layout_ab.py", "--model",
                 "alexnet", "--batch", "256"],
    }) == {"model": "alexnet", "batch": 256, "dtype": "bf16"}
    # A/B tools start from their OWN argparse defaults (layout_ab is a
    # vgg16 tool, not an alexnet one)
    assert parse_bench_job({
        "argv": ["python", "-u", "tools/layout_ab.py"],
    }) == {"model": "vgg16", "batch": 128, "dtype": "bf16"}
    assert parse_bench_job({
        "argv": ["python", "-u", "tools/scaling_bench.py",
                 "--batch-per-device", "64"],
    }) == {"model": "alexnet", "batch": 64, "dtype": "bf16"}
    assert parse_bench_job({
        "argv": ["python", "-u", "-m", "sparknet_tpu.cli", "time",
                 "--solver", "zoo:googlenet", "--batch", "128",
                 "--dtype", "bf16"],
    }) == {"model": "googlenet", "batch": 128, "dtype": "bf16"}
    # host-side setup steps have no bench shape: never priced
    assert parse_bench_job({
        "argv": ["python", "tools/setup_e2e_db.py"]}) is None
    # pallas_bench must not substring-match bench.py, and the forward-
    # only deploy bench is deliberately unpriceable by a TRAIN model
    assert parse_bench_job({
        "argv": ["python", "-u", "tools/pallas_bench.py", "--op",
                 "flash"]}) is None
    assert parse_bench_job({
        "argv": ["python", "-u", "tools/int8_bench.py", "--model",
                 "resnet50", "--batch", "128"]}) is None


def test_preflight_job_verdicts():
    table = {"families": {"alexnet": {"bf16": {"c0": 10_000, "c1": 10}}}}
    fits = preflight_job(
        {"name": "ok", "argv": ["python", "-u", "bench.py"]}, table)
    assert fits["fits"] and fits["model"] == "alexnet"
    oom = preflight_job(
        {"name": "oom", "argv": ["python", "-u", "bench.py"],
         "env": {"SPARKNET_BENCH_BATCH": "256"}},
        {"families": {"alexnet": {"bf16": {"c0": 2**34, "c1": 2**30}}}})
    assert oom["fits"] is False
    assert oom["predicted_bytes"] > oom["budget_bytes"]
    # unknown family => None => pass (the pre-flight saves dials, it
    # never blocks a job it cannot price)
    assert preflight_job(
        {"name": "x", "argv": ["python", "-u", "bench.py"],
         "env": {"SPARKNET_BENCH_MODEL": "not_a_zoo_family"}},
        table) is None


# -- queue pre-flight (runner side: refusal journaled, dial never tried) ----


@pytest.fixture
def runner(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "tpu_window_runner",
        os.path.join(ROOT, "tools", "tpu_window_runner.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "EVIDENCE_DIR", str(tmp_path / "evidence"))
    monkeypatch.setattr(
        mod, "JOURNAL", str(tmp_path / "evidence" / "journal.jsonl"))
    monkeypatch.setattr(mod, "MIN_DIAL_PERIOD_S", 0.05)
    return mod


def _queue(tmp_path, jobs, **kw):
    p = tmp_path / "queue.json"
    p.write_text(json.dumps({"max_hours": 0.01, "jobs": jobs, **kw}))
    return str(p)


def _fit_table(tmp_path, c0, c1):
    p = tmp_path / "batch_fit.json"
    p.write_text(json.dumps(
        {"families": {"alexnet": {"bf16": {"c0": c0, "c1": c1}}}}))
    return str(p)


def test_runner_refuses_predicted_oom_without_dialing(
        runner, tmp_path, monkeypatch):
    """The acceptance path: an over-HBM bench job is journaled as
    preflight_oom and marked dead; with nothing else runnable the
    runner exits blocked — and the dial subprocess NEVER runs."""
    monkeypatch.setattr(runner, "FIT_TABLE_PATH",
                        _fit_table(tmp_path, 2**34, 2**30))
    dialed = []
    monkeypatch.setattr(runner, "dial",
                        lambda probe_id=0: dialed.append(probe_id) or True)
    q = _queue(tmp_path, [{
        "name": "oom_bench", "argv": ["python", "-u", "bench.py"],
        "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
        "deadline_s": 30,
    }])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3  # queue blocked, not drained
    assert dialed == []  # the whole point: no dial burned
    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path / "evidence"),
                                "journal.jsonl"))]
    oom = [e for e in events if e["event"] == "preflight_oom"]
    assert len(oom) == 1  # journaled once, not once per loop pass
    assert oom[0]["job"] == "oom_bench"
    assert oom[0]["model"] == "alexnet" and oom[0]["batch"] == 256
    assert oom[0]["predicted_bytes"] > oom[0]["budget_bytes"]
    assert not any(e["event"] == "dial_start" for e in events)
    blocked = [e for e in events if e["event"] == "runner_done"]
    assert blocked[0]["reason"] == "queue blocked"
    assert blocked[0]["blocked_jobs"] == ["oom_bench"]


def test_runner_preflight_passes_fitting_and_unpriceable_jobs(
        runner, tmp_path, monkeypatch):
    """A job the table prices as fitting runs; a job with no bench
    shape runs; only the OOM one is refused."""
    monkeypatch.setattr(runner, "FIT_TABLE_PATH",
                        _fit_table(tmp_path, 1000, 10))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    fits = {"name": "fits_bench",
            "argv": [sys.executable, "-c", "print('ran bench.py')"],
            "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
            "deadline_s": 30}
    oom = {"name": "oom_bench", "argv": ["python", "-u", "bench.py"],
           "env": {"SPARKNET_BENCH_MODEL": "alexnet",
                   "SPARKNET_BENCH_BATCH": str(2**40)},
           "deadline_s": 30}
    plain = {"name": "host_step",
             "argv": [sys.executable, "-c", "print('ok')"],
             "deadline_s": 30}
    monkeypatch.setattr(sys, "argv",
                        ["runner", _queue(tmp_path, [fits, oom, plain])])
    assert runner.main() == 3  # oom_bench can never run
    state = runner.load_done()
    assert state["fits_bench"] == -1 and state["host_step"] == -1
    assert "oom_bench" not in state  # never attempted, not failed


def test_runner_preflight_refusal_not_rejournaled_on_restart(
        runner, tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "FIT_TABLE_PATH",
                        _fit_table(tmp_path, 2**34, 2**30))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [{
        "name": "oom_bench", "argv": ["python", "-u", "bench.py"],
        "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
        "deadline_s": 30}])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 3
    assert runner.main() == 3  # resume against the same journal
    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path / "evidence"),
                                "journal.jsonl"))]
    assert sum(e["event"] == "preflight_oom" for e in events) == 1


def test_preflight_oom_journal_line_is_schema_valid(
        runner, tmp_path, monkeypatch):
    from sparknet_tpu.obs import schema

    monkeypatch.setattr(runner, "FIT_TABLE_PATH",
                        _fit_table(tmp_path, 2**34, 2**30))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [{
        "name": "oom_bench", "argv": ["python", "-u", "bench.py"],
        "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
        "deadline_s": 30}])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    runner.main()
    journal = os.path.join(str(tmp_path / "evidence"), "journal.jsonl")
    n_lines, n_allow, errors = schema.validate_journal(journal,
                                                       allowlist=())
    assert n_lines >= 2 and n_allow == 0 and errors == []


def test_runner_without_fit_table_passes_everything(
        runner, tmp_path, monkeypatch):
    """No banked table => the pre-flight is inert (it exists to save
    dials, not to gate rounds on memcheck adoption)."""
    monkeypatch.setattr(runner, "FIT_TABLE_PATH",
                        str(tmp_path / "no_such_table.json"))
    monkeypatch.setattr(runner, "dial", lambda probe_id=0: True)
    q = _queue(tmp_path, [{
        "name": "bench_like",
        "argv": [sys.executable, "-c", "print('bench.py stand-in')"],
        "env": {"SPARKNET_BENCH_REQUIRE_MEASURED": "1"},
        "deadline_s": 30}])
    monkeypatch.setattr(sys, "argv", ["runner", q])
    assert runner.main() == 0
    assert runner.load_done()["bench_like"] == -1


# -- CLI: shared schema with lint/graph -------------------------------------


def test_cli_mem_json_schema(tmp_path, capsys, monkeypatch):
    from sparknet_tpu.analysis import memcheck as mc
    from sparknet_tpu.analysis.__main__ import main as cli_main

    monkeypatch.setattr(mc, "MANIFEST_DIR", str(tmp_path))
    rc = cli_main(["mem", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # manifest missing in the tmp dir
    assert set(out) == {"findings", "unsuppressed", "suppressed"}
    assert out["findings"][0]["rule"] == "mem-manifest-missing"
    for key in ("rule", "path", "line", "message", "suppressed"):
        assert key in out["findings"][0]

    rc = cli_main(["mem", "--mode", "moe", "--update"])
    assert rc == 0
    capsys.readouterr()
    rc = cli_main(["mem", "--mode", "moe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["unsuppressed"] == 0


def test_cli_mem_unknown_mode_is_usage_error(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["mem", "--mode", "no-such-mode"]) == 2


def test_cli_mem_list_rules(capsys):
    from sparknet_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["mem", "--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "mem-estimator-divergence" in out
    assert "mem-vmem-exceeded" in out


def test_cli_parse_bytes():
    from sparknet_tpu.analysis.__main__ import _parse_bytes

    assert _parse_bytes("16GiB") == 16 * 2**30
    assert _parse_bytes("8g") == 8 * 2**30
    assert _parse_bytes("123456") == 123456
    with pytest.raises(ValueError):
        _parse_bytes("lots")
