"""Layout polymorphism (ops/layout.py): NCHW↔NHWC equivalence,
cross-layout checkpoints, the graphcheck layout-census fixtures, and
the default-path bit-identity pin.

The contract under test (ISSUE 4 tentpole): ``Config.layout`` flips the
INTERNAL orientation of rank-4 activations only — params stay Caffe
wire order (conv OIHW, fc (num_output, C·H·W)) in both layouts, so the
same weight bytes must produce the same math, checkpoints must
cross-load with zero conversion, and with ``layout="nchw"`` every
helper returns the exact constants the pre-layout code used (the
lowered StableHLO of the default path is bit-identical — banked in
docs/graph_contracts/).
"""

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import get_config, set_config
from sparknet_tpu.models import zoo
from sparknet_tpu.ops import layout
from sparknet_tpu.solvers.solver import Solver

pytestmark = pytest.mark.smoke

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_layout():
    prior = get_config().layout
    yield
    set_config(layout=prior)


# -- pure helpers -----------------------------------------------------------


def test_helpers_default_layout_is_identity():
    """Under nchw every helper returns the historical constants — the
    off-path contract that keeps the default lowering bit-identical."""
    set_config(layout="nchw")
    assert layout.conv_dimnums() == ("NCHW", "OIHW", "NCHW")
    assert layout.channel_axis() == 1
    assert layout.spatial_axes() == (2, 3)
    assert layout.channel_bshape(4) == (1, -1, 1, 1)
    assert layout.internal_axis(2, 4) == 2
    assert layout.internal_shape((8, 3, 32, 32)) == (8, 3, 32, 32)
    x = np.arange(24).reshape(1, 2, 3, 4)
    assert layout.to_internal(x) is x
    dims, strides, padding = layout.pool_window((3, 3), (2, 2),
                                                (1, 0, 1, 0))
    assert dims == (1, 1, 3, 3) and strides == (1, 1, 2, 2)
    assert padding == ((0, 0), (0, 0), (1, 0), (1, 0))


def test_helpers_nhwc_mapping_roundtrips():
    set_config(layout="nhwc")
    assert layout.conv_dimnums() == ("NHWC", "OIHW", "NHWC")
    assert layout.channel_axis() == 3
    assert layout.channel_axis(ndim=2) == 1  # only rank-4 moves
    assert layout.spatial_axes() == (1, 2)
    assert layout.channel_bshape(4) == (1, 1, 1, -1)
    assert layout.channel_bshape(2) == (1, -1)
    # canonical NCHW axes (N, C, H, W) -> internal (N, H, W, C) slots
    assert [layout.internal_axis(a, 4) for a in range(4)] == [0, 3, 1, 2]
    shp = (8, 3, 32, 16)
    assert layout.internal_shape(shp) == (8, 32, 16, 3)
    assert layout.canonical_shape(layout.internal_shape(shp)) == shp
    assert layout.internal_shape((8, 10)) == (8, 10)
    x = np.arange(24).reshape(1, 2, 3, 4)
    np.testing.assert_array_equal(
        layout.from_internal(layout.to_internal(x)), x)
    dims, strides, padding = layout.pool_window((3, 3), (2, 2),
                                                (1, 0, 1, 0))
    assert dims == (1, 3, 3, 1) and strides == (1, 2, 2, 1)
    assert padding == ((0, 0), (1, 0), (1, 0), (0, 0))


def test_set_config_rejects_unknown_layout():
    with pytest.raises(ValueError, match="layout"):
        set_config(layout="nchw8")
    with pytest.raises(ValueError):
        layout.normalize("NHCW")


# -- NCHW <-> NHWC training equivalence (zoo:alexnet) -----------------------


def _alexnet_feeds(B, crop):
    rs = np.random.RandomState(7)
    return {
        "data": (rs.randn(B, 3, crop, crop) * 10).astype(np.float32),
        "label": rs.randint(0, 10, B).astype(np.int32),
    }


def _train_alexnet(lay, feeds, B, crop, steps=1):
    """Build + step zoo:alexnet under ``lay``; returns (loss, params)."""
    set_config(layout=lay)
    solver = Solver(zoo.alexnet_solver(), zoo.alexnet(B, 10, crop=crop))
    internal = {k: layout.to_internal(v) for k, v in feeds.items()}
    loss = solver.step(steps, lambda it: internal)
    return loss, solver


def test_alexnet_nchw_nhwc_loss_and_grads_match():
    """The headline-shape equivalence gate: same params (layout-
    invariant, same seed), same canonical bytes -> same loss AND same
    post-SGD params (grads match transitively, through LRN, grouped
    convs, dropout — whose mask is drawn in canonical order — and the
    fc-as-conv boundary)."""
    B, crop = 2, 63
    feeds = _alexnet_feeds(B, crop)
    loss_c, solver_c = _train_alexnet("nchw", feeds, B, crop)
    loss_h, solver_h = _train_alexnet("nhwc", feeds, B, crop)
    assert np.allclose(loss_c, loss_h, rtol=1e-5, atol=1e-6), (
        loss_c, loss_h)
    for lname, plist in solver_c.variables.params.items():
        for p_c, p_h in zip(plist, solver_h.variables.params[lname]):
            np.testing.assert_allclose(
                np.asarray(p_c), np.asarray(p_h), rtol=1e-5, atol=1e-6,
                err_msg=f"post-step params diverge at {lname}")


def test_alexnet_checkpoint_roundtrips_across_layouts(tmp_path):
    """A snapshot written under nchw restores into an nhwc solver with
    ZERO conversion (params are wire-order in both layouts), carries a
    layout provenance tag, and continued training matches."""
    B, crop = 2, 63
    feeds = _alexnet_feeds(B, crop)
    loss_c, solver_c = _train_alexnet("nchw", feeds, B, crop)
    prefix = str(tmp_path / "ab")
    solver_c.save(prefix)
    state_path = f"{prefix}.solverstate.npz"
    meta = json.loads(bytes(np.load(state_path)["__meta__"]).decode())
    assert meta["layout"] == "nchw"  # provenance, not a gate

    set_config(layout="nhwc")
    solver_h = Solver(zoo.alexnet_solver(), zoo.alexnet(B, 10, crop=crop))
    solver_h.restore(state_path)
    internal = {k: layout.to_internal(v) for k, v in feeds.items()}
    loss_h = solver_h.step(1, lambda it: internal)

    set_config(layout="nchw")
    loss_c2 = solver_c.step(1, lambda it: feeds)
    assert np.allclose(loss_c2, loss_h, rtol=1e-5, atol=1e-6), (
        loss_c2, loss_h)


# -- feed link: DeviceAugment speaks the internal layout --------------------


def test_device_augment_layout_equivalence():
    from sparknet_tpu.data.device_transform import DeviceAugment
    from sparknet_tpu.data.transform import TransformConfig

    cfg = TransformConfig(crop_size=8, mirror=True,
                          mean_value=[10.0, 20.0, 30.0], scale=0.5)
    rs = np.random.RandomState(3)
    imgs = rs.randint(0, 255, (4, 3, 12, 12)).astype(np.uint8)
    key = jax.random.PRNGKey(0)
    out_c = DeviceAugment(cfg, layout="nchw")(imgs, key, train=True)
    out_h = DeviceAugment(cfg, layout="nhwc")(
        imgs.transpose(0, 2, 3, 1), key, train=True)
    # same key -> same crop offsets and flip draws; the nhwc output is
    # the nchw output reoriented, from a feed that never transposed
    np.testing.assert_allclose(np.asarray(out_h),
                               np.asarray(out_c).transpose(0, 2, 3, 1),
                               rtol=1e-6, atol=1e-6)


# -- graphcheck layout-census fixtures --------------------------------------


def test_layout_census_counts_by_rank():
    from sparknet_tpu.analysis.graphcheck import layout_census

    shlo = """\
    %1 = stablehlo.transpose %0, dims = [0, 3, 1, 2] : (tensor<2x4x4x3xf32>) -> tensor<2x3x4x4xf32>
    %2 = stablehlo.transpose %1, dims = [1, 0] : (tensor<8x16xf32>) -> tensor<16x8xf32>
    """
    hlo = """\
      %t = f32[2,3,4,4]{3,2,1,0} transpose(f32[2,4,4,3]{3,2,1,0} %p), dimensions={0,3,1,2}
      %c = f32[8]{0} copy(f32[8]{0} %q)
      %t2 = f32[8,16]{1,0} transpose(f32[16,8]{1,0} %r), dimensions={1,0}
    """
    out = layout_census(shlo, hlo)
    # the rank-2 weight flip is NOT data formatting; the rank-4 one is
    assert out["stablehlo_transposes"] == 2
    assert out["stablehlo_transposes_4d"] == 1
    assert out["stablehlo_transpose_4d_elems"] == 2 * 4 * 4 * 3
    assert out["hlo_transposes"] == 2
    assert out["hlo_transposes_4d"] == 1
    assert out["hlo_copies"] == 1


def test_fixture_nhwc_interior_transpose_is_caught():
    """An nhwc-tagged mode whose program reorients an image blob ->
    graph-layout-transpose; the dimension_numbers-riding twin is clean."""
    from sparknet_tpu.analysis.comm_model import CommExpectation
    from sparknet_tpu.analysis.graphcheck import audit_target, trace_artifacts
    from sparknet_tpu.parallel.modes import TraceTarget

    no_exp = CommExpectation(required={}, forbidden=())
    x = jnp.ones((2, 8, 8, 3))
    w = jnp.ones((4, 3, 3, 3))

    def bad(x, w):
        # a layer "fell off" the dimension_numbers path: canonicalize,
        # conv NCHW, reorient back
        xc = jnp.transpose(x, (0, 3, 1, 2))
        y = jax.lax.conv_general_dilated(
            xc, w, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return jnp.transpose(y, (0, 2, 3, 1)).sum()

    target = TraceTarget(
        name="fx_layout_bad", fn=jax.jit(bad), args=(x, w),
        meta={"dtype": "f32", "layout": "nhwc"},
        param_bytes=0, state_bytes=0)
    problems, contract = audit_target(target, trace_artifacts(target),
                                      no_exp)
    assert [p["rule"] for p in problems] == ["graph-layout-transpose"]
    assert contract["layout"]["stablehlo_transposes_4d"] == 2

    def good(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "OIHW", "NHWC")).sum()

    clean = TraceTarget(
        name="fx_layout_ok", fn=jax.jit(good), args=(x, w),
        meta={"dtype": "f32", "layout": "nhwc"},
        param_bytes=0, state_bytes=0)
    problems, contract = audit_target(clean, trace_artifacts(clean),
                                      no_exp)
    assert problems == []
    assert contract["layout"]["stablehlo_transposes_4d"] == 0


# -- the default-path bit-identity pin --------------------------------------


def test_default_layout_stablehlo_matches_banked_manifest():
    """The solo train step lowered under the DEFAULT layout hashes to
    exactly the banked manifest's stablehlo_sha256 — the layout knob is
    invisible off-path (same discipline as the obs off-contract).  A
    legitimate jax upgrade moves this hash; rebank with
    `python -m sparknet_tpu.analysis graph --update` in that case."""
    from sparknet_tpu.analysis.graphcheck import trace_artifacts
    from sparknet_tpu.parallel.modes import build_target

    banked = json.load(open(os.path.join(
        _REPO, "docs", "graph_contracts", "solo.json")))
    target = build_target("solo", 8)
    art = trace_artifacts(target)
    assert hashlib.sha256(art.stablehlo.encode()).hexdigest() == \
        banked["stablehlo_sha256"]


def test_int8_deploy_path_layout_equivalence():
    """PTQ is layout-invariant end to end: scales calibrated under nchw
    drive the int8 deploy path under nhwc (conv dequant moves to the
    trailing channel axis, the fc arm canonicalizes its flatten) and
    the logits match the nchw deploy run on the same canonical bytes."""
    from sparknet_tpu.quant import calibrate, quantized_inference

    B = 8
    rs = np.random.RandomState(5)
    data = rs.rand(B, 1, 28, 28).astype(np.float32)
    label = np.zeros(B, np.int32)

    set_config(layout="nchw")
    solver_c = Solver(zoo.lenet_solver(), zoo.lenet(B))
    net_c, vars_c = solver_c.test_net, solver_c.variables
    qstate = calibrate(net_c, vars_c,
                       iter([{"data": data, "label": label}] * 2),
                       num_batches=2)
    assert set(qstate) == {"conv1", "conv2", "ip1", "ip2"}
    with quantized_inference(qstate):
        out_c, _, _ = net_c.apply(vars_c, {"data": data, "label": label},
                                  rng=None, train=False)

    set_config(layout="nhwc")
    solver_h = Solver(zoo.lenet_solver(), zoo.lenet(B))
    with quantized_inference(qstate):
        out_h, _, _ = solver_h.test_net.apply(
            solver_h.variables,
            {"data": layout.to_internal(data), "label": label},
            rng=None, train=False)
    np.testing.assert_allclose(np.asarray(out_c["ip2"]),
                               np.asarray(out_h["ip2"]),
                               rtol=1e-4, atol=1e-5)
