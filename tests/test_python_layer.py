"""Python layer type (ref: layer_factory.cpp:199-214 GetPythonLayer +
examples/pycaffe/linreg.prototxt + layers/pyloss.py) and duplicate layer
names (mnist_autoencoder has two param-less "loss" layers)."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.proto import parse, parse_file

REF = "/root/reference/caffe"


# ---------------------------------------------------------------- fixtures
# a module the prototxt can name (the PYTHONPATH contract)
_MODULE_SRC = '''
import numpy as np
import jax.numpy as jnp


class EuclideanLossLayer:
    """pycaffe-compat clone of examples/pycaffe/layers/pyloss.py."""

    def setup(self, bottom, top):
        if len(bottom) != 2:
            raise Exception("Need two inputs to compute distance.")

    def reshape(self, bottom, top):
        if bottom[0].count != bottom[1].count:
            raise Exception("Inputs must have the same dimension.")
        self.diff = np.zeros_like(bottom[0].data, dtype=np.float32)
        top[0].reshape(1)

    def forward(self, bottom, top):
        self.diff[...] = bottom[0].data - bottom[1].data
        top[0].data[...] = np.sum(self.diff ** 2) / bottom[0].num / 2.0

    def backward(self, top, propagate_down, bottom):
        for i in range(2):
            if not propagate_down[i]:
                continue
            sign = 1 if i == 0 else -1
            bottom[i].diff[...] = sign * self.diff / bottom[i].num


class ScaledTanh:
    """JAX-native style: traced into XLA, autodiff for free."""

    def apply(self, x):
        scale = float(self.param_str) if self.param_str else 1.0
        return jnp.tanh(x) * scale
'''


@pytest.fixture(scope="module", autouse=True)
def pylayer_module(tmp_path_factory):
    d = tmp_path_factory.mktemp("pylayers")
    (d / "my_layers.py").write_text(_MODULE_SRC)
    sys.path.insert(0, str(d))
    yield
    sys.path.remove(str(d))


LINREG = """
name: "linreg"
layer { type: "DummyData" name: "x" top: "x"
  dummy_data_param { shape: { dim: 10 dim: 3 dim: 2 }
                     data_filler: { type: "gaussian" } } }
layer { type: "DummyData" name: "y" top: "y"
  dummy_data_param { shape: { dim: 10 dim: 3 dim: 2 }
                     data_filler: { type: "gaussian" } } }
layer { type: "InnerProduct" name: "ipx" top: "ipx" bottom: "x"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layer { type: "InnerProduct" name: "ipy" top: "ipy" bottom: "y"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layer { type: "Python" name: "loss" top: "loss" bottom: "ipx" bottom: "ipy"
  python_param { module: "my_layers" layer: "EuclideanLossLayer" }
  loss_weight: 1 }
"""


class TestCaffeCompatStyle:
    def test_linreg_compiles_and_matches_analytic_loss(self):
        net = Network(parse(LINREG), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))
        blobs, _, loss = net.apply(variables, {}, rng=jax.random.key(1))
        a, b = np.asarray(blobs["ipx"]), np.asarray(blobs["ipy"])
        expect = np.sum((a - b) ** 2) / a.shape[0] / 2.0
        assert float(loss) == pytest.approx(expect, rel=1e-5)

    def test_custom_vjp_matches_analytic_gradient(self):
        net = Network(parse(LINREG), Phase.TRAIN)
        variables = net.init(jax.random.PRNGKey(0))

        def loss_fn(params):
            from sparknet_tpu.compiler.graph import NetVars

            _, _, loss = net.apply(
                NetVars(params=params, state=variables.state), {},
                rng=jax.random.key(1),
            )
            return loss

        grads = jax.grad(loss_fn)(variables.params)
        # finite-difference check on one ipx weight entry (the layer's own
        # backward() supplies the vjp — GradientChecker-style validation,
        # ref: test_gradient_check_util.hpp)
        p0 = variables.params["ipx"][0]
        eps = 1e-3
        for idx in [(0, 0), (3, 2)]:
            bumped = {
                k: list(v) for k, v in variables.params.items()
            }
            bumped["ipx"][0] = p0.at[idx].add(eps)
            up = loss_fn(bumped)
            bumped["ipx"][0] = p0.at[idx].add(-eps)
            down = loss_fn(bumped)
            fd = (up - down) / (2 * eps)
            assert float(grads["ipx"][0][idx]) == pytest.approx(
                float(fd), rel=2e-2, abs=1e-4
            )

    def test_trains_under_jit(self):
        # the host bridge must survive jit: loss shrinks over SGD steps
        from sparknet_tpu.net import TPUNet
        from sparknet_tpu.solvers.solver import SolverConfig

        net = TPUNet(SolverConfig(base_lr=0.01), parse(LINREG))
        net.set_train_data(lambda it: {})
        l0 = net.train(1)
        net.train(60)
        l1 = net.train(1)
        assert l1 < l0 * 0.2, (l0, l1)


class TestJaxNativeStyle:
    def test_apply_traced_and_differentiable(self):
        npz = parse(
            """
            name: "t"
            input: "data" input_shape { dim: 4 dim: 3 }
            layer { type: "Python" name: "act" bottom: "data" top: "act"
              python_param { module: "my_layers" layer: "ScaledTanh"
                             param_str: "2.5" } }
            """
        )
        net = Network(npz, Phase.TEST)
        variables = net.init(jax.random.PRNGKey(0))
        x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        blobs, _, _ = net.apply(variables, {"data": x}, rng=None)
        assert np.allclose(np.asarray(blobs["act"]), np.tanh(x) * 2.5, atol=1e-6)

        # fully traceable: grad flows through without custom vjp
        f = lambda x: jnp.sum(
            net.apply(variables, {"data": x}, rng=None)[0]["act"]
        )
        g = jax.grad(f)(jnp.asarray(x))
        assert np.allclose(np.asarray(g), (1 - np.tanh(x) ** 2) * 2.5, atol=1e-5)


class TestPycaffeContract:
    def test_phase_is_int(self, tmp_path):
        # pycaffe layers check `self.phase == 0` (TRAIN) — int, not enum
        import sys as _sys

        (tmp_path / "phasemod.py").write_text(
            "class PhaseProbe:\n"
            "    def setup(self, bottom, top): pass\n"
            "    def reshape(self, bottom, top):\n"
            "        top[0].reshape(*bottom[0].data.shape)\n"
            "    def forward(self, bottom, top):\n"
            "        assert self.phase in (0, 1), repr(self.phase)\n"
            "        top[0].data[...] = bottom[0].data + (1 if self.phase == 0 else 2)\n"
        )
        _sys.path.insert(0, str(tmp_path))
        try:
            proto = (
                'input: "data" input_shape { dim: 2 dim: 3 } '
                'layer { type: "Python" name: "p" bottom: "data" top: "out" '
                'python_param { module: "phasemod" layer: "PhaseProbe" } }'
            )
            for phase, offset in ((Phase.TRAIN, 1.0), (Phase.TEST, 2.0)):
                net = Network(parse(proto), phase)
                v = net.init(jax.random.PRNGKey(0))
                x = np.zeros((2, 3), np.float32)
                blobs, _, _ = net.apply(v, {"data": x}, rng=None, train=False)
                assert np.allclose(np.asarray(blobs["out"]), offset)
        finally:
            _sys.path.remove(str(tmp_path))

    def test_zero_arg_init_is_called_and_errors_propagate(self, tmp_path):
        import sys as _sys

        (tmp_path / "initmod.py").write_text(
            "class GoodInit:\n"
            "    def __init__(self): self.tag = 41\n"
            "    def apply(self, x): return x + self.tag\n"
            "class BadInit:\n"
            "    def __init__(self): raise TypeError('broken ctor')\n"
            "    def apply(self, x): return x\n"
        )
        _sys.path.insert(0, str(tmp_path))
        try:
            good = (
                'input: "data" input_shape { dim: 2 } '
                'layer { type: "Python" name: "p" bottom: "data" top: "out" '
                'python_param { module: "initmod" layer: "GoodInit" } }'
            )
            net = Network(parse(good), Phase.TEST)
            v = net.init(jax.random.PRNGKey(0))
            blobs, _, _ = net.apply(v, {"data": np.ones(2, np.float32)}, rng=None)
            assert np.allclose(np.asarray(blobs["out"]), 42.0)
            # a TypeError raised INSIDE a zero-arg __init__ must surface
            bad = good.replace("GoodInit", "BadInit")
            with pytest.raises(TypeError, match="broken ctor"):
                Network(parse(bad), Phase.TEST)
        finally:
            _sys.path.remove(str(tmp_path))


class TestValidation:
    def test_missing_python_param(self):
        with pytest.raises(ValueError, match="python_param"):
            Network(
                parse('layer { type: "Python" name: "p" bottom: "x" top: "y" }'),
                Phase.TRAIN,
            )

    def test_class_without_protocol(self, tmp_path):
        import sys as _sys

        (tmp_path / "badmod.py").write_text("class Nope:\n    pass\n")
        _sys.path.insert(0, str(tmp_path))
        try:
            with pytest.raises(ValueError, match="must define either"):
                Network(
                    parse(
                        'layer { type: "Python" name: "p" bottom: "x" top: "y" '
                        'python_param { module: "badmod" layer: "Nope" } }'
                    ),
                    Phase.TRAIN,
                )
        finally:
            _sys.path.remove(str(tmp_path))


class TestDuplicateNames:
    def test_mnist_autoencoder_compiles(self):
        npz = parse_file(f"{REF}/examples/mnist/mnist_autoencoder.prototxt")
        net = Network(npz, Phase.TRAIN)
        names = [l.name for l in net.layers]
        assert names.count("loss") == 2  # Caffe-permitted duplicate
        shapes = {"data": (4, 1, 28, 28)}
        variables = net.init(jax.random.PRNGKey(0), feed_shapes=shapes)
        feeds = {"data": np.random.RandomState(0).rand(4, 1, 28, 28).astype(np.float32)}
        blobs, _, loss = net.apply(
            variables, feeds, rng=jax.random.key(0)
        )
        assert "cross_entropy_loss" in blobs and "l2_error" in blobs
        assert np.isfinite(float(loss))

    def test_param_owner_sharing_name_with_paramless_layer_rejected(self):
        # one owner + one param-less namesake still poisons every
        # name-keyed lookup (param_specs_for, snapshots) — reject it
        npz = parse(
            """
            input: "data" input_shape { dim: 2 dim: 4 }
            layer { name: "ip" type: "ReLU" bottom: "data" top: "a" }
            layer { name: "ip" type: "InnerProduct" bottom: "a" top: "b"
                    inner_product_param { num_output: 3 } }
            """
        )
        with pytest.raises(ValueError, match="shares its name"):
            Network(npz, Phase.TRAIN).init(jax.random.PRNGKey(0))

    def test_duplicate_param_owning_names_rejected(self):
        npz = parse(
            """
            input: "data" input_shape { dim: 2 dim: 4 }
            layer { name: "ip" type: "InnerProduct" bottom: "data" top: "a"
                    inner_product_param { num_output: 3 } }
            layer { name: "ip" type: "InnerProduct" bottom: "a" top: "b"
                    inner_product_param { num_output: 3 } }
            """
        )
        net = Network(npz, Phase.TRAIN)
        with pytest.raises(ValueError, match="shares its name"):
            net.init(jax.random.PRNGKey(0))
