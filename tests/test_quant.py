"""Post-training int8 inference (sparknet_tpu.quant).

Beyond-parity feature: per-output-channel int8 weights + calibrated
per-tensor int8 activations, int32 accumulation — the MXU int8 deploy
path (v5e: 394 int8 TOPS vs 197 bf16 TFLOP/s).  Pinned here: the
quantizer's numerics, the op-level int8 forwards against their float
oracles, and end-to-end classification agreement on a trained net.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu.quant import (
    calibrate,
    int8_matmul,
    quantize_weight,
    quantized_inference,
)


def test_quantize_weight_per_channel_roundtrip():
    rs = np.random.RandomState(0)
    # channels with wildly different ranges: per-channel scales must
    # reconstruct each to ~1/127 relative error (per-tensor would not)
    w = rs.randn(4, 8).astype(np.float32) * np.array(
        [[0.01], [1.0], [50.0], [0.3]], np.float32)
    w_q, scale = quantize_weight(w, channel_axis=0)
    assert w_q.dtype == jnp.int8 and scale.shape == (4, 1)
    w_hat = np.asarray(w_q, np.float32) * np.asarray(scale)
    err = np.abs(w_hat - w) / np.maximum(np.abs(w).max(axis=1, keepdims=True), 1e-9)
    assert err.max() < 1.0 / 127 + 1e-6


def test_int8_matmul_close_to_float():
    rs = np.random.RandomState(1)
    x = rs.randn(16, 32).astype(np.float32)
    w = rs.randn(8, 32).astype(np.float32)
    w_q, w_scale = quantize_weight(w)
    q = {"w_q": w_q, "w_scale": w_scale,
         "x_scale": np.float32(np.abs(x).max() / 127.0)}
    y = np.asarray(int8_matmul(jnp.asarray(x), q))
    ref = x @ w.T
    # int8 PTQ error budget: ~1% of the output scale for gaussian data
    assert np.abs(y - ref).max() < 0.02 * np.abs(ref).max() + 1e-6


def test_calibrate_and_quantized_forward_lenet():
    """End-to-end: calibrate a trained LeNet on real digits, then the
    int8 forward must agree with the float forward on >=95% of top-1
    predictions and stay within a few points of its accuracy."""
    pytest.importorskip("sklearn")
    from sparknet_tpu.data.digits import load_digits_dataset
    from sparknet_tpu.solvers.solver import Solver, SolverConfig

    xtr, ytr, xte, yte = load_digits_dataset()
    xtr, xte = xtr / 16.0, xte / 16.0
    B = 64
    # the zoo recipe (docs/CONVERGENCE.md: 98.4% at 400 iters; ~90%+ by
    # 200) — SolverConfig kept imported for the explicit-recipe variants
    del SolverConfig
    solver = Solver(models.lenet_solver(), models.lenet(B))
    rs = np.random.RandomState(0)

    def fn(it):
        idx = rs.randint(0, len(ytr), B)
        return {"data": xtr[idx], "label": ytr[idx]}

    solver.step(200, fn)

    net = solver.test_net
    variables = solver.variables
    calib = ({"data": xtr[i * B:(i + 1) * B],
              "label": ytr[i * B:(i + 1) * B]} for i in range(4))
    qstate = calibrate(net, variables, calib)
    assert set(qstate) == {"conv1", "conv2", "ip1", "ip2"}
    assert all(r["w_q"].dtype == jnp.int8 for r in qstate.values())

    feeds = {"data": xte[:128], "label": yte[:128]}
    float_blobs, _, _ = net.apply(variables, feeds, rng=None, train=False)
    with quantized_inference(qstate):
        q_blobs, _, _ = net.apply(variables, feeds, rng=None, train=False)

    f_pred = np.argmax(np.asarray(float_blobs["ip2"]), axis=-1)
    q_pred = np.argmax(np.asarray(q_blobs["ip2"]), axis=-1)
    agree = float((f_pred == q_pred).mean())
    f_acc = float((f_pred == yte[:128]).mean())
    q_acc = float((q_pred == yte[:128]).mean())
    assert f_acc > 0.9, f_acc  # the float net trained
    assert agree >= 0.95, (agree, f_acc, q_acc)
    assert q_acc >= f_acc - 0.05, (f_acc, q_acc)


def test_quantized_inference_traces_under_jit():
    """The context is consulted at trace time: a jitted forward traced
    inside quantized_inference() carries int8 ops (int8 weight constants
    live in the program), and outside it stays float."""
    net = Network(models.lenet(4), Phase.TEST)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {"data": np.zeros((4, 1, 28, 28), np.float32),
             "label": np.zeros(4, np.int32)}
    qstate = calibrate(net, variables, [
        {"data": np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32),
         "label": np.zeros(4, np.int32)}])

    def make_fwd():
        # distinct function objects: jax.jit caches traces by function
        # identity, and the point here is that the CONTEXT at trace time
        # decides the program
        def fwd(v, f):
            blobs, _, _ = net.apply(v, f, rng=None, train=False)
            return blobs["ip2"]
        return fwd

    with quantized_inference(qstate):
        text = jax.jit(make_fwd()).lower(variables, feeds).as_text()
    assert "i8" in text  # int8 tensors present in the lowered program
    text_float = jax.jit(make_fwd()).lower(variables, feeds).as_text()
    assert "i8" not in text_float


def test_uncalibrated_layers_stay_float():
    """Partial quantization: layers absent from qstate run the float
    path; outputs still finite and close."""
    net = Network(models.lenet(4), Phase.TEST)
    variables = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    feeds = {"data": rs.randn(4, 1, 28, 28).astype(np.float32),
             "label": np.zeros(4, np.int32)}
    qstate = calibrate(net, variables, [feeds])
    only_conv1 = {"conv1": qstate["conv1"]}
    with quantized_inference(only_conv1):
        blobs, _, _ = net.apply(variables, feeds, rng=None, train=False)
    ref, _, _ = net.apply(variables, feeds, rng=None, train=False)
    assert np.all(np.isfinite(np.asarray(blobs["ip2"])))
    np.testing.assert_allclose(
        np.asarray(blobs["ip2"]), np.asarray(ref["ip2"]), atol=0.05)


def test_calibrate_resolves_shared_weights():
    """Weight-shared layers (param { name } — the siamese pattern) hold a
    0-size placeholder at the aliased position; calibration must resolve
    the owner's array, not quantize the placeholder."""
    net = Network(models.mnist_siamese(4), Phase.TEST)
    variables = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    feeds = {"pair_data": rs.randn(4, 2, 28, 28).astype(np.float32) * 40,
             "sim": np.zeros(4, np.int32)}
    qstate = calibrate(net, variables, [feeds])
    # every quantized record carries a REAL weight (no empty placeholders)
    assert qstate, "siamese conv/ip layers should calibrate"
    for name, rec in qstate.items():
        assert rec["w_q"].size > 0, name
    with quantized_inference(qstate):
        blobs, _, _ = net.apply(variables, feeds, rng=None, train=False)
    for v in blobs.values():
        assert np.all(np.isfinite(np.asarray(v)))


def test_int8_depthwise_conv_close_to_float():
    """Grouped conv at group == channels (MobileNet's hot op): the
    per-output-channel scale (channel_axis 0 on the (C,1,kh,kw) blob)
    must dequantize each depthwise channel independently — a per-tensor
    scale would smear large-channel error across small channels."""
    from sparknet_tpu.proto import parse

    NET = """
    name: "dwq"
    layer { name: "data" type: "MemoryData" top: "data" top: "label"
            memory_data_param { batch_size: 2 channels: 16 height: 8 width: 8 } }
    layer { name: "dw" type: "Convolution" bottom: "data" top: "y"
            convolution_param { num_output: 16 kernel_size: 3 pad: 1 group: 16
                                weight_filler { type: "msra" } } }
    """
    net = Network(parse(NET), Phase.TEST)
    variables = net.init(jax.random.PRNGKey(3))
    rs = np.random.RandomState(0)
    # wildly different per-channel magnitudes to punish per-tensor scales
    w = np.asarray(variables.params["dw"][0]) * (
        10.0 ** rs.uniform(-2, 2, size=(16, 1, 1, 1)))
    variables.params["dw"][0] = jnp.asarray(w, jnp.float32)
    feeds = {"data": rs.randn(2, 16, 8, 8).astype(np.float32),
             "label": np.zeros(2, np.int32)}
    qstate = calibrate(net, variables, [feeds])
    assert "dw" in qstate and qstate["dw"]["w_q"].shape == (16, 1, 3, 3)
    with quantized_inference(qstate):
        q, _, _ = net.apply(variables, feeds, rng=None, train=False)
    ref, _, _ = net.apply(variables, feeds, rng=None, train=False)
    qy, ry = np.asarray(q["y"]), np.asarray(ref["y"])
    # per-channel relative error stays small for EVERY channel
    for c in range(16):
        denom = np.abs(ry[:, c]).max() + 1e-9
        assert np.abs(qy[:, c] - ry[:, c]).max() / denom < 0.03, c
