"""Distributed-training tests on the virtual 8-device CPU mesh.

The reference's closest analog is the in-process multi-GPU equivalence test
(ref: caffe/src/caffe/test/test_gradient_based_solver.cpp:197-208,468-469 —
single-vs-multi-device update equivalence with constant data); we reproduce
that exact property for the tau=1 sync path, plus convergence + averaging
semantics for the tau>1 SparkNet mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.layers_dsl import (
    AccuracyLayer,
    ConvolutionLayer,
    InnerProductLayer,
    NetParam,
    Pooling,
    PoolingLayer,
    RDDLayer,
    ReLULayer,
    SoftmaxWithLoss,
)
from sparknet_tpu.parallel import (
    ParallelTrainer,
    ShardingRules,
    auto_mesh,
    data_parallel_mesh,
)
from sparknet_tpu.solvers import Solver, SolverConfig

BATCH = 64  # global batch; 8 devices -> 8 per device


def small_net(batch=BATCH, num_output=256):
    return NetParam(
        "pnet",
        RDDLayer("data", shape=[batch, 1, 12, 12]),
        RDDLayer("label", shape=[batch]),
        ConvolutionLayer("conv1", ["data"], kernel=(3, 3), num_output=8),
        PoolingLayer("pool1", ["conv1"], Pooling.Max, kernel=(2, 2), stride=(2, 2)),
        InnerProductLayer("ip1", ["pool1"], num_output=num_output),
        ReLULayer("relu1", ["ip1"]),
        InnerProductLayer("ip2", ["relu1"], num_output=10),
        SoftmaxWithLoss("loss", ["ip2", "label"]),
        AccuracyLayer("accuracy", ["ip2", "label"]),
    )


def synth(n, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n).astype(np.int32)
    imgs = rs.randn(n, 1, 12, 12).astype(np.float32) * 0.2
    for i, k in enumerate(labels):
        imgs[i, 0, :, k] += 2.0  # class k = bright column k
    return imgs, labels


def feeds_of(imgs, labels):
    return {"data": imgs, "label": labels}


def test_mesh_shapes():
    assert jax.device_count() == 8, "conftest must fake 8 CPU devices"
    m = data_parallel_mesh()
    assert m.shape == {"data": 8}
    m2 = auto_mesh(model_parallel=2)
    assert m2.shape == {"data": 4, "model": 2}


def test_sync_dp_matches_single_device():
    """tau=1 sharded step == unsharded step bit-for-bit-ish (the
    multi-device equivalence property, ref: test_gradient_based_solver.cpp)."""
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    imgs, labels = synth(BATCH, seed=3)

    s1 = Solver(cfg, small_net())
    s2 = Solver(cfg, small_net())
    # identical init — fresh buffers, not aliases: Solver.step and the
    # trainer both donate their carries now
    copy = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.array(np.asarray(x)), t)
    s2.variables = copy(s1.variables)
    s2.slots = copy(s1.slots)

    tr = ParallelTrainer(s2, mesh=data_parallel_mesh(), tau=1)
    for it in range(3):
        s1.step(1, lambda i: feeds_of(imgs, labels))
        tr.train_round(lambda i: feeds_of(imgs, labels))
    w_single = s1.variables.params["ip2"][0]
    w_multi = tr._averaged_variables().params["ip2"][0]
    np.testing.assert_allclose(np.asarray(w_single), np.asarray(w_multi), atol=2e-5)


def test_train_rounds_scan_matches_round_loop():
    """train_rounds(n): n fused sync-SGD rounds == n train_round calls
    (same data sequence) — the dispatch-batched tau=1 path."""
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    imgs, labels = synth(4 * BATCH, seed=5)

    def data_fn(it):
        lo = (it * BATCH) % (3 * BATCH)
        return feeds_of(imgs[lo:lo + BATCH], labels[lo:lo + BATCH])

    s1 = Solver(cfg, small_net())
    s2 = Solver(cfg, small_net())
    # fresh buffers, not aliases: both trainers donate their state
    copy = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.array(np.asarray(x)), t)
    s2.variables = copy(s1.variables)
    s2.slots = copy(s1.slots)

    a = ParallelTrainer(s1, mesh=data_parallel_mesh(), tau=1)
    b = ParallelTrainer(s2, mesh=data_parallel_mesh(), tau=1)
    for _ in range(4):
        loss_loop = a.train_round(data_fn)
    loss_scan = b.train_rounds(4, data_fn)

    assert a.iter == b.iter == 4
    np.testing.assert_allclose(loss_scan, loss_loop, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(b._averaged_variables().params["ip2"][0]),
        np.asarray(a._averaged_variables().params["ip2"][0]),
        atol=2e-5,
    )


def test_train_rounds_falls_back_for_tau():
    """tau>1 already amortizes dispatch over tau local steps: the API
    falls back to the per-round loop, same results."""
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    solver = Solver(cfg, small_net(batch=BATCH // 8))
    tr = ParallelTrainer(solver, mesh=data_parallel_mesh(), tau=2)
    imgs, labels = synth(BATCH, seed=5)
    stacked = {
        k: np.stack([v, v])
        for k, v in feeds_of(imgs, labels).items()
    }
    loss = tr.train_rounds(2, lambda it: stacked)
    assert np.isfinite(loss) and tr.iter == 4  # 2 rounds x tau=2


def test_sync_dp_converges():
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    solver = Solver(cfg, small_net())
    tr = ParallelTrainer(solver, tau=1)
    imgs, labels = synth(4096, seed=0)
    timgs, tlabels = synth(BATCH, seed=9)
    rs = np.random.RandomState(1)

    def data_fn(it):
        idx = rs.randint(0, len(imgs), BATCH)
        return feeds_of(imgs[idx], labels[idx])

    tr.train(40, data_fn)
    scores = tr.test(5, lambda b: feeds_of(timgs, tlabels))
    assert scores["accuracy"] > 0.8, scores


@pytest.mark.smoke
def test_tau_local_sgd_round():
    """The SparkNet algorithm: tau local steps then model averaging.
    All replicas must hold identical params after a round (post-pmean),
    and the model must learn."""
    tau = 5
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    solver = Solver(cfg, small_net())
    tr = ParallelTrainer(solver, tau=tau)
    imgs, labels = synth(4096, seed=0)
    timgs, tlabels = synth(BATCH, seed=9)
    rs = np.random.RandomState(2)

    def data_fn(it):
        idx = rs.randint(0, len(imgs), (tau, BATCH))
        return feeds_of(imgs[idx], labels[idx])

    loss = tr.train(10, data_fn)
    assert np.isfinite(loss)
    # replicas are in sync after averaging
    stacked = np.asarray(tr.variables.params["ip2"][0])
    assert stacked.shape[0] == 8
    for r in range(1, 8):
        np.testing.assert_allclose(stacked[r], stacked[0], atol=1e-6)
    scores = tr.test(5, lambda b: feeds_of(timgs, tlabels))
    assert scores["accuracy"] > 0.8, scores
    assert tr.iter == 10 * tau


def test_tau_weight_exchange_roundtrip():
    cfg = SolverConfig(base_lr=0.01)
    solver = Solver(cfg, small_net())
    tr = ParallelTrainer(solver, tau=3)
    wc = tr.get_weights()
    tr.set_weights(wc)
    wc2 = tr.get_weights()
    np.testing.assert_allclose(wc["ip2"][0], wc2["ip2"][0], rtol=1e-6)


def test_tensor_parallel_shards_big_fc():
    """Megatron-style output-dim sharding of large InnerProduct blobs over
    the model axis; step still runs and matches the replicated result."""
    mesh = auto_mesh(model_parallel=2)
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    solver = Solver(cfg, small_net())
    tr = ParallelTrainer(
        solver, mesh=mesh, tau=1, rules=ShardingRules(min_tp_dim=128)
    )
    # ip1 weight (256, D) is sharded over model axis
    sh = tr.variables.params["ip1"][0].sharding
    assert sh.spec[0] == "model", sh
    # conv1 (8, ...) too small -> replicated
    assert tr.variables.params["conv1"][0].sharding.spec == ()

    imgs, labels = synth(BATCH, seed=3)
    ref = Solver(cfg, small_net())
    # fresh buffers, not aliases: ref.step donates its carry
    copy = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.array(np.asarray(x)), t)
    ref.variables = copy(solver.variables)
    ref.slots = copy(solver.slots)
    for it in range(2):
        ref.step(1, lambda i: feeds_of(imgs, labels))
        tr.train_round(lambda i: feeds_of(imgs, labels))
    np.testing.assert_allclose(
        np.asarray(ref.variables.params["ip1"][0]),
        np.asarray(tr.variables.params["ip1"][0]),
        atol=2e-5,
    )


def test_sync_to_solver_and_snapshot(tmp_path):
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    solver = Solver(cfg, small_net())
    tr = ParallelTrainer(solver, tau=2)
    imgs, labels = synth(BATCH, seed=5)

    def data_fn(it):
        return feeds_of(
            np.stack([imgs, imgs]), np.stack([labels, labels])
        )

    tr.train(2, data_fn)
    tr.sync_to_solver()
    assert solver.iter == 4
    path = solver.save(str(tmp_path / "snap"))
    solver2 = Solver(cfg, small_net())
    solver2.restore(path)
    np.testing.assert_allclose(
        np.asarray(solver2.variables.params["ip2"][0]),
        np.asarray(tr._averaged_variables().params["ip2"][0]),
        atol=1e-6,
    )


def test_tau_convergence_parity():
    """The paper's claim: tau-step local SGD + periodic averaging converges
    like fully-sync SGD on the same budget of local steps (SparkNet Fig. 4
    regime, small tau).  tau=4 with 5 rounds == 20 local iterations; both
    modes must solve the synthetic task."""
    imgs, labels = synth(1024, seed=3)

    def run(tau, rounds):
        solver = Solver(
            SolverConfig(base_lr=0.1, momentum=0.9, solver_type="SGD"),
            small_net(batch=BATCH if tau == 1 else BATCH // 8),
        )
        trainer = ParallelTrainer(solver, tau=tau)
        rs = np.random.RandomState(tau)
        for _ in range(rounds):
            idx = rs.randint(0, 1024, BATCH * max(tau, 1))
            if tau == 1:
                trainer.train_round(lambda it: feeds_of(imgs[idx], labels[idx]))
            else:
                shape = (tau, BATCH)
                f = {
                    "data": imgs[idx].reshape(shape + imgs.shape[1:]),
                    "label": labels[idx].reshape(shape),
                }
                trainer.train_round(lambda it: f)
        test_idx = np.arange(512)
        scores = trainer.test(
            4, lambda b: feeds_of(imgs[test_idx[b::4][:BATCH]],
                                  labels[test_idx[b::4][:BATCH]])
        )
        return scores["accuracy"]

    acc_sync = run(tau=1, rounds=20)   # 20 sync steps
    acc_tau = run(tau=4, rounds=5)     # 5 rounds x 4 local steps
    assert acc_sync > 0.9, acc_sync
    assert acc_tau > 0.9, acc_tau


def test_pipeline_blocks_match_sequential():
    """GPipe schedule over a 4-stage mesh == sequential block stack,
    including bubble-dominated cases (M < S)."""
    from jax.sharding import Mesh

    from sparknet_tpu.parallel.pipeline import (
        pipeline_blocks,
        sequential_blocks,
        stack_stage_params,
        stage_sharding,
    )

    S, D = 4, 16
    mesh = Mesh(np.array(jax.devices()[:S]), ("stage",))
    rs = np.random.RandomState(0)
    stacked = stack_stage_params([
        {
            "w": jnp.asarray(rs.randn(D, D) * 0.3, jnp.float32),
            "b": jnp.asarray(rs.randn(D) * 0.1, jnp.float32),
        }
        for _ in range(S)
    ])

    def block(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    # place each stage's weight slice on its own device up front
    stacked = jax.tree_util.tree_map(
        jax.device_put, stacked, stage_sharding(mesh, stacked)
    )

    for M in (1, 2, 6):
        x = jnp.asarray(rs.randn(M, 3, D), jnp.float32)
        out = pipeline_blocks(mesh, block, stacked, x)
        ref = sequential_blocks(block, stacked, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, err_msg=f"M={M}"
        )


def test_expert_parallel_matches_dense():
    """all_to_all MoE dispatch == the dense oracle at full capacity;
    tight capacity drops tokens to zero instead of corrupting others."""
    from jax.sharding import Mesh

    from sparknet_tpu.ops.moe import moe_dense
    from sparknet_tpu.parallel.expert import expert_parallel_moe

    E, T, D, H = 8, 64, 16, 32
    mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
    rs = np.random.RandomState(0)
    params = (
        jnp.asarray(rs.randn(E, D) * 0.5, jnp.float32),
        jnp.asarray(rs.randn(E, H, D) * 0.3, jnp.float32),
        jnp.asarray(rs.randn(E, H) * 0.1, jnp.float32),
        jnp.asarray(rs.randn(E, D, H) * 0.3, jnp.float32),
        jnp.asarray(rs.randn(E, D) * 0.1, jnp.float32),
    )
    x = jnp.asarray(rs.randn(T, D), jnp.float32)
    ref = np.asarray(moe_dense(params, x))
    out = np.asarray(expert_parallel_moe(mesh, params, x, capacity_factor=float(E)))
    np.testing.assert_allclose(out, ref, atol=2e-5)

    tight = np.asarray(expert_parallel_moe(mesh, params, x, capacity_factor=1.0))
    dropped = np.all(tight == 0, axis=1)
    kept = ~dropped
    assert dropped.any()  # this seed overflows some expert
    np.testing.assert_allclose(tight[kept], ref[kept], atol=2e-5)


def test_expert_parallel_validations():
    from jax.sharding import Mesh

    from sparknet_tpu.parallel.expert import expert_parallel_moe

    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    params = (jnp.zeros((8, 4)),) + tuple(jnp.zeros((8, 2, 2)) for _ in range(4))
    with pytest.raises(ValueError, match="num_experts"):
        expert_parallel_moe(mesh, params, jnp.zeros((8, 4)))


def test_multihost_two_process_cluster():
    """Real multi-process bring-up over the DCN path: 2 processes x 4
    CPU devices via initialize_distributed; per-process feed shards;
    sync-DP and tau-averaging rounds; replica params must agree
    bit-for-bit across hosts (the P2PSync-equivalence analog, ref:
    test_gradient_based_solver.cpp:197-208, upgraded to actual
    multi-process)."""
    import os
    import socket
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "multihost_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")

    import tempfile

    ckpt_dir = tempfile.mkdtemp(prefix="mh_ckpt_")

    def run_cluster():
        # bind-then-close port allocation can race other suites; the
        # retry below absorbs a stolen port
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(pid), str(port), ckpt_dir],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            )
            for pid in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                outs.append(out)
        except subprocess.TimeoutExpired:
            return None
        finally:
            for p in procs:
                p.poll() is None and p.kill()
        if any(p.returncode != 0 for p in procs):
            # known env drift: guard shared with the CLI multihost test
            # (conftest.skip_if_cpu_multiprocess_drift)
            from conftest import skip_if_cpu_multiprocess_drift

            skip_if_cpu_multiprocess_drift(outs)
            return None
        return outs

    try:
        outs = run_cluster() or run_cluster()
    finally:
        import shutil

        shutil.rmtree(ckpt_dir, ignore_errors=True)
    assert outs is not None, "multihost cluster failed twice"

    digests = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("DIGEST"):
                _, pid, d1, d2, l1, l2 = line.split()
                digests[pid] = (d1, d2)
    assert set(digests) == {"0", "1"}, outs
    assert digests["0"] == digests["1"], digests
    try:
        import orbax.checkpoint  # noqa: F401

        want = "ok"
    except ImportError:
        want = "skipped"
    assert all(f"CKPT {p} {want}" in o for p, o in zip("01", outs)), outs


def test_elastic_averaging_easgd():
    """EASGD mode: converges on the synthetic task, keeps workers as
    DISTINCT replicas exploring around the center, and the center (the
    consensus model exposed by get_weights/test) tracks them."""
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, solver_type="SGD")
    solver = Solver(cfg, small_net())
    R = len(jax.devices())
    trainer = ParallelTrainer(solver, tau=2, elastic_alpha=0.9 / R)

    imgs, labels = synth(BATCH * R * 2)

    def data_fn(it):
        f = feeds_of(imgs, labels)
        return {k: np.stack([v, v]) for k, v in f.items()}  # [tau=2, B*R, ...]

    l0 = trainer.train_round(data_fn)
    for _ in range(20):
        loss = trainer.train_round(data_fn)
    assert loss < l0, (l0, loss)

    # workers differ from the center (exploration), but are coupled to it
    leaves = jax.tree_util.tree_leaves(trainer.variables.params)
    centers = jax.tree_util.tree_leaves(trainer.center)
    gaps = [
        float(jnp.max(jnp.abs(w - c[None]))) for w, c in zip(leaves, centers)
    ]
    assert max(gaps) > 0.0
    scale = max(float(jnp.max(jnp.abs(c))) for c in centers)
    assert max(gaps) < max(scale, 1.0)  # bounded: the elastic force works

    # eval + weight exchange go through the center
    scores = trainer.test(2, lambda b: feeds_of(imgs, labels))
    assert np.isfinite(scores["accuracy"])
    wc = trainer.get_weights()
    np.testing.assert_allclose(
        wc[list(wc.layers())[0]][0],
        np.asarray(jax.tree_util.tree_leaves(trainer.center)[0]),
        rtol=1e-6,
    )

    # snapshot path: solver sees the consensus model
    trainer.sync_to_solver()
    assert trainer.solver.variables.params.keys() == solver.variables.params.keys()

    with pytest.raises(ValueError, match="elastic_alpha"):
        ParallelTrainer(solver, tau=1, elastic_alpha=1.5)
    # alpha in (0,1) but violating alpha*p <= 1 on this mesh: rejected
    # (1.5/R trips the bound for any worker count)
    with pytest.raises(ValueError, match="stability"):
        ParallelTrainer(solver, tau=1, elastic_alpha=1.5 / R)


def test_trainer_distributed_checkpoint(tmp_path):
    """Trainer-level orbax checkpoint of the live sharded state: resuming
    from the snapshot reproduces the uninterrupted trajectory exactly
    (the P2PSync-free pod-scale resume path)."""
    pytest.importorskip("orbax.checkpoint")
    cfg = SolverConfig(base_lr=0.05, momentum=0.9)
    imgs, labels = synth(BATCH * 8)

    def data_fn(it):
        f = feeds_of(imgs, labels)
        return {k: np.stack([v, v]) for k, v in f.items()}

    def make():
        return ParallelTrainer(Solver(cfg, small_net()), tau=2)

    a = make()
    a.train(2, data_fn)
    ckpt = a.save(str(tmp_path / "live"))
    a.train(2, data_fn)
    direct = np.asarray(jax.tree_util.tree_leaves(a.variables.params)[0])

    b = make()
    b.restore(ckpt)
    assert b.iter == a.iter - 4
    b.train(2, data_fn)
    resumed = np.asarray(jax.tree_util.tree_leaves(b.variables.params)[0])
    np.testing.assert_allclose(direct, resumed, rtol=1e-6)

    # EASGD: the center rides along
    e1 = ParallelTrainer(Solver(cfg, small_net()), tau=2, elastic_alpha=0.1)
    e1.train(2, data_fn)
    ck = e1.save(str(tmp_path / "elastic"))
    e2 = ParallelTrainer(Solver(cfg, small_net()), tau=2, elastic_alpha=0.1)
    e2.restore(ck)
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(e1.center)[0]),
        np.asarray(jax.tree_util.tree_leaves(e2.center)[0]),
        rtol=1e-6,
    )

    # mode mismatches fail with a diagnosis, not an orbax tree error
    with pytest.raises(ValueError, match="EASGD center"):
        make().restore(ck)  # elastic checkpoint into a plain trainer
    with pytest.raises(ValueError, match="solver_type"):
        ParallelTrainer(
            Solver(
                SolverConfig(base_lr=0.05, momentum=0.9, solver_type="Nesterov"),
                small_net(),
            ),
            tau=2,
        ).restore(ckpt)


def test_moe_layer_expert_sharded_tp():
    """In-graph MoE under a (data, model) mesh: expert-major params shard
    over the model axis and the sharded step matches unsharded training
    step-for-step (GSPMD expert parallelism by layout)."""
    from sparknet_tpu.layers_dsl import (
        MoELayer,
        NetParam,
        SoftmaxWithLoss,
    )
    from sparknet_tpu.proto.text_format import Message

    def build():
        net_param = NetParam(
            "moe_tp",
            MoELayer("moe", ["x"], num_experts=4, hidden_dim=32, top="h"),
            Message().set("name", "cls").set("type", "InnerProduct")
            .add("bottom", "h").add("top", "cls")
            .set("inner_product_param",
                 Message().set("num_output", 3)
                 .set("weight_filler", Message().set("type", "xavier"))),
            SoftmaxWithLoss("loss", ["cls", "label"]),
        )
        net_param.add("input", "x")
        net_param.add("input_shape", Message().add("dim", 8).add("dim", 16))
        net_param.add("input", "label")
        net_param.add("input_shape", Message().add("dim", 8))
        return Solver(SolverConfig(base_lr=0.05), net_param)

    def data_fn(it):
        rs2 = np.random.RandomState(100 + it)
        return {
            "x": rs2.randn(8, 16).astype(np.float32),
            "label": rs2.randint(0, 3, 8).astype(np.int32),
        }

    from sparknet_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(model_parallel=4)
    trainer = ParallelTrainer(build(), mesh=mesh, tau=1)
    # expert-major MoE blobs sharded over 'model'
    spec = trainer._pshard.params["moe"][1].spec
    assert spec == jax.sharding.PartitionSpec("model")

    plain = build()
    for it in range(3):
        f = data_fn(it)
        trainer.train_round(lambda _: f)
        plain.step(1, lambda _: f)

    for a, b in zip(
        jax.tree_util.tree_leaves(trainer.variables.params),
        jax.tree_util.tree_leaves(plain.variables.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_tau_round_averages_bn_state():
    """tau>1 + BatchNorm: each worker's tau local steps accumulate
    DIFFERENT moving statistics on its own shard; the round-end sync
    must average state along with params (trainer.py pmean over the
    full NetVars) or eval-time stats silently diverge per replica."""
    from sparknet_tpu.layers_dsl import BatchNormLayer, ScaleLayer

    tau, per_dev = 3, 4
    net = NetParam(
        "bn_tau",
        RDDLayer("data", shape=[per_dev, 3, 8, 8]),
        RDDLayer("label", shape=[per_dev]),
        ConvolutionLayer("conv", ["data"], kernel=(3, 3), num_output=8,
                         pad=(1, 1), bias_term=False),
        BatchNormLayer("bn", ["conv"], moving_average_fraction=0.9),
        ScaleLayer("scale", ["conv"]),
        ReLULayer("relu", ["conv"], in_place=True),
        InnerProductLayer("ip", ["conv"], num_output=4),
        SoftmaxWithLoss("loss", ["ip", "label"]),
    )
    cfg = SolverConfig(base_lr=0.01, momentum=0.9)
    tr = ParallelTrainer(Solver(cfg, net), tau=tau)
    rs = np.random.RandomState(0)
    B = per_dev * 8

    def data_fn(it):
        return {
            "data": (rs.randn(tau, B, 3, 8, 8) * 20).astype(np.float32),
            "label": rs.randint(0, 4, (tau, B)).astype(np.int32),
        }

    loss = tr.train(2, data_fn)
    assert np.isfinite(loss)
    # BN state is per-replica stacked [8, ...]: after the round-end
    # average every replica must hold the SAME statistics, and they
    # must be non-zero (the workers really accumulated)
    bn = {k: np.asarray(v) for k, v in tr.variables.state["bn"].items()}
    for name, arr in bn.items():
        assert arr.shape[0] == 8, (name, arr.shape)
        for r in range(1, 8):
            np.testing.assert_allclose(arr[r], arr[0], atol=1e-6,
                                       err_msg=name)
    assert float(bn["scale_factor"][0][0]) > 0
