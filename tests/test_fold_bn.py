"""BN folding (models/fold_bn.py) — the merge_bn deploy flow.

The pin that matters: a TRAINED net's TEST-phase scores are IDENTICAL
(to float tolerance) before and after folding, on the real ResNet-50
wiring (bias-free convs + in-place BatchNorm/Scale pairs), and the
folded net has no BatchNorm/Scale layers left.
"""

import dataclasses

import jax
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network, NetVars
from sparknet_tpu.models import zoo
from sparknet_tpu.models.fold_bn import fold_batchnorm
from sparknet_tpu.solvers.solver import Solver


@pytest.fixture(scope="module")
def trained_resnet():
    """A few real solver steps so BN state carries nontrivial statistics."""
    cfg = dataclasses.replace(zoo.resnet50_solver(), base_lr=1e-3)
    solver = Solver(cfg, zoo.resnet50(batch=4, num_classes=5, crop=64,
                                      bn_fraction=0.9))
    rs = np.random.RandomState(0)

    def feed(it):
        return {
            "data": rs.randn(4, 3, 64, 64).astype(np.float32) * 40,
            "label": rs.randint(0, 5, size=(4,)).astype(np.int32),
        }

    solver.step(3, feed)
    return solver


def test_folded_resnet_scores_identically(trained_resnet):
    solver = trained_resnet
    net_param = solver.train_net.net_param
    rs = np.random.RandomState(1)
    feeds = {
        "data": np.asarray(rs.randn(4, 3, 64, 64) * 40, np.float32),
        "label": np.asarray(rs.randint(0, 5, 4), np.int32),
    }

    test_net = Network(net_param, Phase.TEST)
    ref, _, _ = test_net.apply(solver.variables, feeds, rng=None, train=False)

    net2, params2, state2, folded = fold_batchnorm(
        net_param, solver.variables.params, solver.variables.state)
    # every BN/Scale pair folded: conv1 + 16 blocks x 3 + 4 projections
    assert len(folded) == 53, len(folded)
    types = {lp.get_str("type") for lp in net2.get_all("layer")}
    assert "BatchNorm" not in types and "Scale" not in types

    folded_net = Network(net2, Phase.TEST)
    out, _, _ = folded_net.apply(
        NetVars(params=params2, state=state2), feeds, rng=None, train=False)
    np.testing.assert_allclose(
        np.asarray(out["fc1000"]), np.asarray(ref["fc1000"]),
        rtol=2e-4, atol=2e-4)


def test_fold_is_noop_on_bn_free_net():
    net = zoo.cifar10_quick(batch=2)
    n = Network(net, Phase.TRAIN)
    v = n.init(jax.random.PRNGKey(0))
    net2, params2, state2, folded = fold_batchnorm(net, v.params, v.state)
    assert folded == []
    assert len(net2.get_all("layer")) == len(net.get_all("layer"))


def test_fresh_unscored_stats_are_not_baked():
    """A never-trained net (scale_factor 0) must not fold garbage: the
    zero-statistics guard skips nothing here because scale_factor==0
    maps to factor 1 with zero mean/var — folding is still EXACT vs the
    TEST-phase forward, which uses the same convention."""
    net_param = zoo.resnet50(batch=2, num_classes=5, crop=64)
    n = Network(net_param, Phase.TRAIN)
    v = n.init(jax.random.PRNGKey(0))
    net2, params2, state2, folded = fold_batchnorm(net_param, v.params, v.state)
    assert len(folded) == 53


def test_folded_resnet_quantizes_int8(trained_resnet):
    """The capability folding unlocks: a BN net reduced to pure Conv/IP
    form goes through the int8 PTQ path; int8 top-1 agrees with the
    folded float net on the training-distribution fixture."""
    from sparknet_tpu import quant

    solver = trained_resnet
    net2, params2, state2, folded = fold_batchnorm(
        solver.train_net.net_param, solver.variables.params,
        solver.variables.state)
    assert folded
    folded_net = Network(net2, Phase.TEST)
    v2 = NetVars(params=params2, state=state2)
    rs = np.random.RandomState(2)
    feeds = {
        "data": np.asarray(rs.randn(4, 3, 64, 64) * 40, np.float32),
        "label": np.asarray(rs.randint(0, 5, 4), np.int32),
    }
    ref, _, _ = folded_net.apply(v2, feeds, rng=None, train=False)
    qstate = quant.calibrate(folded_net, v2, [feeds])
    assert qstate  # conv/ip layers got scales
    with quant.quantized_inference(qstate):
        out, _, _ = jax.jit(
            lambda v, f: folded_net.apply(v, f, rng=None, train=False)
        )(v2, feeds)
    a = np.asarray(ref["fc1000"])
    b = np.asarray(out["fc1000"])
    # argmax agreement is the wrong metric on a 3-step fixture (logit
    # margins are ~0 and per-tensor int8 noise compounds over 50
    # layers); the path claim is that int8 TRACKS the float net —
    # centered per-sample cosine (measured 0.92-0.999 on this fixture)
    for i in range(len(a)):
        ca, cb = a[i] - a[i].mean(), b[i] - b[i].mean()
        cos = float(ca @ cb / (np.linalg.norm(ca) * np.linalg.norm(cb)
                               + 1e-9))
        assert cos >= 0.85, (i, cos, a[i], b[i])


def test_intermediate_reader_blocks_fold():
    """A layer reading the RAW pre-BN blob between producer and BN makes
    the fold unsound (it would see normalized values) — such chains must
    be skipped, per the module's leave-untouched contract."""
    from sparknet_tpu.layers_dsl import (
        BatchNormLayer, ConvolutionLayer, NetParam, PoolingLayer, Pooling,
        RDDLayer, ScaleLayer,
    )

    net = NetParam(
        "tap",
        RDDLayer("data", shape=[2, 3, 8, 8]),
        ConvolutionLayer("conv", ["data"], kernel=(3, 3), num_output=4,
                         bias_term=False),
        # reads the raw conv output BEFORE the in-place BN rewrites it
        PoolingLayer("tap", ["conv"], Pooling.Max, kernel=(2, 2),
                     stride=(2, 2)),
        BatchNormLayer("bn", ["conv"]),
        ScaleLayer("scale", ["conv"]),
    )
    n = Network(net, Phase.TRAIN)
    v = n.init(jax.random.PRNGKey(0))
    net2, params2, state2, folded = fold_batchnorm(net, v.params, v.state)
    assert folded == []
    assert len(net2.get_all("layer")) == len(net.get_all("layer"))


def test_fold_after_quantize_raises(trained_resnet):
    from sparknet_tpu.models.deploy import DeployNet

    solver = trained_resnet
    dep = DeployNet(solver.train_net.net_param)
    dep.variables = solver.variables
    rs = np.random.RandomState(3)
    feeds = {"data": np.asarray(rs.randn(4, 3, 64, 64) * 40, np.float32),
             "label": np.asarray(rs.randint(0, 5, 4), np.int32)}
    dep.quantize_int8([feeds], num_batches=1)
    with pytest.raises(RuntimeError, match="BEFORE quantize_int8"):
        dep.fold_batchnorm()


def test_caffemodel_roundtrip_preserves_bn_stats(trained_resnet, tmp_path):
    """The interchange bug the fold surfaced: Caffe stores BN statistics
    in the SAME blobs_ vector as weights, so the wire formats must carry
    state blobs both ways — a round-tripped BN net scores identically."""
    from sparknet_tpu.net import (
        copy_caffemodel_params, copy_hdf5_params,
        export_caffemodel, export_hdf5,
    )

    solver = trained_resnet
    test_net = Network(solver.train_net.net_param, Phase.TEST)
    rs = np.random.RandomState(4)
    feeds = {"data": np.asarray(rs.randn(4, 3, 64, 64) * 40, np.float32),
             "label": np.asarray(rs.randint(0, 5, 4), np.int32)}
    ref, _, _ = test_net.apply(solver.variables, feeds, rng=None,
                               train=False)

    for ext, exp, cp in (
        (".caffemodel", export_caffemodel, copy_caffemodel_params),
        (".h5", export_hdf5, copy_hdf5_params),
    ):
        path = str(tmp_path / f"rt{ext}")
        exp(solver.train_net, solver.variables.params, path,
            state=solver.variables.state)
        fresh = Network(solver.train_net.net_param, Phase.TRAIN)
        v0 = fresh.init(jax.random.PRNGKey(9))
        params, state, loaded = cp(v0.params, path, state=v0.state)
        # BN stats actually landed (fresh init has scale_factor 0)
        sf = next(s["scale_factor"] for s in state.values()
                  if "scale_factor" in s)
        assert float(np.asarray(sf)[0]) > 0, ext
        out, _, _ = test_net.apply(
            NetVars(params=params, state=state), feeds, rng=None,
            train=False)
        np.testing.assert_allclose(
            np.asarray(out["fc1000"]), np.asarray(ref["fc1000"]),
            rtol=1e-5, atol=1e-5, err_msg=ext)


SHARED_TOWERS = """
name: "shared_towers"
layer { name: "data" type: "MemoryData" top: "data" top: "label"
        memory_data_param { batch_size: 2 channels: 3 height: 8 width: 8 } }
layer { name: "convA" type: "Convolution" bottom: "data" top: "a"
        param { name: "wshared" }
        convolution_param { num_output: 4 kernel_size: 3 bias_term: false
                            weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "bnA" type: "BatchNorm" bottom: "a" top: "a" }
layer { name: "scA" type: "Scale" bottom: "a" top: "a"
        scale_param { bias_term: true } }
layer { name: "convB" type: "Convolution" bottom: "data" top: "b"
        param { name: "wshared" }
        convolution_param { num_output: 4 kernel_size: 3 bias_term: false
                            weight_filler { type: "gaussian" std: 0.1 } } }
"""


def test_shared_param_producer_is_not_folded():
    """A producer whose weight blob is SHARED (param{name} declared by
    two layers, siamese-style) must be skipped: folding would bake one
    branch's BN statistics into a blob the other branch still reads
    (round-4 advisor finding)."""
    from sparknet_tpu.proto import parse

    net = parse(SHARED_TOWERS)
    n = Network(net, Phase.TRAIN)
    v = n.init(jax.random.PRNGKey(0))
    net2, _, _, folded = fold_batchnorm(net, v.params, v.state)
    assert folded == []
    assert len(net2.get_all("layer")) == len(net.get_all("layer"))

    # control: the identical chain WITHOUT the sharing folds
    solo = parse(SHARED_TOWERS.replace('param { name: "wshared" }', ""))
    n2 = Network(solo, Phase.TRAIN)
    v2 = n2.init(jax.random.PRNGKey(0))
    _, _, _, folded2 = fold_batchnorm(solo, v2.params, v2.state)
    assert folded2 == ["convA <- bnA + scA"]


def test_shared_scale_gamma_is_not_folded():
    """The guard must also cover the DROPPED layers: a Scale whose gamma
    is shared (owner of a param{name} another layer aliases) cannot be
    folded away — deleting the owner's arrays would orphan the alias's
    0-size placeholder."""
    from sparknet_tpu.proto import parse

    net_txt = SHARED_TOWERS.replace('param { name: "wshared" }', "")
    net_txt = net_txt.replace(
        'layer { name: "scA" type: "Scale" bottom: "a" top: "a"\n'
        '        scale_param { bias_term: true } }',
        'layer { name: "scA" type: "Scale" bottom: "a" top: "a"\n'
        '        param { name: "gshared" }\n'
        '        scale_param { bias_term: true } }')
    net_txt += ('layer { name: "scB" type: "Scale" bottom: "b" top: "bs"\n'
                '        param { name: "gshared" }\n'
                '        scale_param { bias_term: true } }\n')
    net = parse(net_txt)
    n = Network(net, Phase.TRAIN)
    v = n.init(jax.random.PRNGKey(0))
    net2, params2, _, folded = fold_batchnorm(net, v.params, v.state)
    assert folded == []
    assert len(net2.get_all("layer")) == len(net.get_all("layer"))
