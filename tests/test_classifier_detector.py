"""Classifier / Detector model-usage parity (ref: caffe/python/caffe/
classifier.py, detector.py; exercised like pycaffe's test_net usage)."""

import numpy as np
import pytest

from sparknet_tpu.models.classifier import Classifier
from sparknet_tpu.models.detector import Detector
from sparknet_tpu.proto import parse

DEPLOY = """
name: "tiny_deploy"
input: "data"
input_dim: 4 input_dim: 3 input_dim: 8 input_dim: 8
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1
    weight_filler { type: "gaussian" std: 0.1 } }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "ip1" type: "InnerProduct" bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "gaussian" std: 0.1 } }
}
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


@pytest.fixture(scope="module")
def deploy_param():
    return parse(DEPLOY)


class TestClassifier:
    def test_predict_center_crop(self, deploy_param, rng):
        clf = Classifier(deploy_param, image_dims=(12, 12))
        images = [rng.rand(20, 24, 3).astype(np.float32) for _ in range(3)]
        preds = clf.predict(images, oversample=False)
        assert preds.shape == (3, 5)
        assert np.allclose(preds.sum(1), 1.0, atol=1e-4)  # softmax rows

    def test_predict_oversample_averages_ten_crops(self, deploy_param, rng):
        clf = Classifier(deploy_param, image_dims=(12, 12))
        images = [rng.rand(16, 16, 3).astype(np.float32) for _ in range(2)]
        preds = clf.predict(images, oversample=True)
        assert preds.shape == (2, 5)
        assert np.allclose(preds.sum(1), 1.0, atol=1e-4)

    def test_batching_beyond_net_batch(self, deploy_param, rng):
        # net batch is 4; 7 images * 10 crops = 70 samples run in chunks
        clf = Classifier(deploy_param)
        images = [rng.rand(8, 8, 3).astype(np.float32) for _ in range(7)]
        preds = clf.predict(images, oversample=True)
        assert preds.shape == (7, 5)

    def test_deterministic_per_image(self, deploy_param, rng):
        clf = Classifier(deploy_param)
        im = rng.rand(8, 8, 3).astype(np.float32)
        a = clf.predict([im], oversample=False)
        b = clf.predict([im, im], oversample=False)
        assert np.allclose(a[0], b[0], atol=1e-5)
        assert np.allclose(b[0], b[1], atol=1e-5)

    def test_transformer_options_applied(self, deploy_param, rng):
        mean = np.array([0.2, 0.3, 0.4], np.float32)
        clf = Classifier(
            deploy_param, mean=mean, raw_scale=255.0, channel_swap=(2, 1, 0)
        )
        im = rng.rand(8, 8, 3).astype(np.float32)
        preds = clf.predict([im], oversample=False)
        base = Classifier(deploy_param).predict([im], oversample=False)
        assert preds.shape == base.shape
        assert not np.allclose(preds, base)  # preprocessing changed the input


class TestDetector:
    def test_detect_windows_plain(self, deploy_param, rng):
        det = Detector(deploy_param)
        im = rng.rand(32, 40, 3).astype(np.float32)
        windows = [(0, 0, 16, 16), (8, 10, 30, 38)]
        dets = det.detect_windows([(im, windows)])
        assert len(dets) == 2
        for d, w in zip(dets, windows):
            assert d["prediction"].shape == (5,)
            assert tuple(d["window"]) == w
            assert d["filename"] is None

    def test_detect_windows_context_pad(self, deploy_param, rng):
        det = Detector(
            deploy_param,
            mean=np.array([0.5, 0.5, 0.5], np.float32),
            context_pad=2,
        )
        im = rng.rand(32, 40, 3).astype(np.float32)
        # window touching the image border: context must be mean-padded
        dets = det.detect_windows([(im, [(0, 0, 10, 10), (20, 28, 32, 40)])])
        assert len(dets) == 2
        assert all(np.isfinite(d["prediction"]).all() for d in dets)

    def test_crop_without_context_is_plain_slice(self, deploy_param, rng):
        det = Detector(deploy_param)
        im = rng.rand(20, 20, 3).astype(np.float32)
        w = np.array([2, 3, 10, 12])
        assert np.allclose(det.crop(im, w), im[2:10, 3:12])

    def test_crop_with_context_is_input_sized(self, deploy_param, rng):
        det = Detector(deploy_param, context_pad=1)
        im = rng.rand(20, 20, 3).astype(np.float32)
        crop = det.crop(im, np.array([4, 4, 12, 12]))
        assert crop.shape == tuple(det.crop_dims)

    def test_filename_input(self, deploy_param, rng, tmp_path):
        from PIL import Image

        arr = (rng.rand(24, 24, 3) * 255).astype(np.uint8)
        p = str(tmp_path / "im.png")
        Image.fromarray(arr).save(p)
        det = Detector(deploy_param)
        dets = det.detect_windows([(p, [(0, 0, 12, 12)])])
        assert dets[0]["filename"] == p


def test_cli_classify(tmp_path, capsys, rng):
    """`tpunet classify` — the cpp_classification example tool
    (ref: examples/cpp_classification/classification.cpp)."""
    import json

    from PIL import Image

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.io_utils import save_mean_binaryproto

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY)
    labels = tmp_path / "labels.txt"
    labels.write_text("\n".join(f"class_{i}" for i in range(5)))
    mean = tmp_path / "mean.binaryproto"
    save_mean_binaryproto(str(mean), np.full((3, 8, 8), 120, np.float32))
    imgs = []
    for i in range(2):
        p = tmp_path / f"im{i}.png"
        Image.fromarray((rng.rand(16, 16, 3) * 255).astype(np.uint8)).save(p)
        imgs.append(str(p))

    assert main([
        "classify", "--model", str(model), "--mean", str(mean),
        "--labels", str(labels), "--top", "3", "--bgr",
        "--oversample", "--images-dim", "12,12", *imgs,
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out) == 2
    for rec in out:
        assert len(rec["predictions"]) == 3
        assert rec["predictions"][0]["label"].startswith("class_")
        probs = [p["prob"] for p in rec["predictions"]]
        assert probs == sorted(probs, reverse=True)


def test_cli_classify_grayscale_mean_and_exclusive_flags(tmp_path, capsys, rng):
    """2-D grayscale .npy means collapse correctly; --snapshot/--weights
    are mutually exclusive in train (ref: caffe.cpp:161-163)."""
    import json

    import pytest
    from PIL import Image

    from sparknet_tpu.cli import main

    model = tmp_path / "gray_deploy.prototxt"
    model.write_text(
        'name: "g"\ninput: "data"\n'
        "input_dim: 2 input_dim: 1 input_dim: 8 input_dim: 8\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        "  inner_product_param { num_output: 3\n"
        '    weight_filler { type: "gaussian" std: 0.1 } } }\n'
        'layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }\n'
    )
    mean = tmp_path / "mean.npy"
    np.save(mean, np.full((8, 8), 100, np.float32))  # 2-D grayscale mean
    img = tmp_path / "g.png"
    Image.fromarray((rng.rand(8, 8) * 255).astype(np.uint8), mode="L").save(img)

    assert main([
        "classify", "--model", str(model), "--mean", str(mean),
        "--top", "2", str(img),
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out[0]["predictions"]) == 2

    with pytest.raises(SystemExit, match="mutually exclusive"):
        main(["train", "--solver", "zoo:lenet", "--batch", "4",
              "--iterations", "1", "--snapshot", "x.npz",
              "--weights", "y.caffemodel"])


def test_cli_classify_images_dim_validation(tmp_path, rng):
    import pytest
    from PIL import Image

    from sparknet_tpu.cli import main

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY)
    img = tmp_path / "im.png"
    Image.fromarray((rng.rand(16, 16, 3) * 255).astype(np.uint8)).save(img)
    with pytest.raises(SystemExit, match="must be"):
        main(["classify", "--model", str(model), "--images-dim", "224",
              str(img)])
    with pytest.raises(SystemExit, match="smaller than the net input"):
        main(["classify", "--model", str(model), "--images-dim", "4,4",
              str(img)])
    # deprecated --center-only still accepted (no-op; center is default)
    assert main(["classify", "--model", str(model), "--center-only",
                 str(img)]) == 0
    with pytest.raises(SystemExit, match="mutually exclusive"):
        main(["classify", "--model", str(model), "--center-only",
              "--oversample", str(img)])


def test_classifier_int8_agrees_with_float(tmp_path, rng):
    """calibrate_int8: the quantized deploy forward's top-1 agrees with
    the float forward (tiny net, self-calibration on the input batch)."""
    from sparknet_tpu.models.classifier import Classifier

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY)
    imgs = [rng.rand(8, 8, 3).astype(np.float32) for _ in range(4)]

    f = Classifier(str(model))
    float_probs = f.predict(imgs, oversample=False)

    q = Classifier(str(model))
    qstate = q.calibrate_int8(imgs)
    assert set(qstate) == {"conv1", "ip1"}
    q_probs = q.predict(imgs, oversample=False)
    # different random init per Classifier? both init from jax.random.key(0)
    # => identical weights; quantization is the only difference
    np.testing.assert_array_equal(
        np.argmax(float_probs, -1), np.argmax(q_probs, -1))
    np.testing.assert_allclose(q_probs, float_probs, atol=0.05)


def test_cli_classify_int8(tmp_path, rng, capsys):
    import json

    from PIL import Image

    from sparknet_tpu.cli import main

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY)
    img = tmp_path / "im.png"
    Image.fromarray((rng.rand(8, 8, 3) * 255).astype(np.uint8)).save(img)
    assert main(["classify", "--model", str(model), "--int8",
                 str(img)]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    meta = json.loads(lines[-2])
    assert meta["int8"] == ["conv1", "ip1"]
    out = json.loads(lines[-1])
    assert out[0]["predictions"]


def test_detector_inherits_int8(tmp_path, rng):
    """quantize_int8 lives on DeployNet: the Detector gets the int8
    deploy path for free (windowed R-CNN scoring, ref: pycaffe
    detector.py)."""
    from sparknet_tpu.models.detector import Detector

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY)
    det = Detector(str(model))
    feeds = {"data": rng.rand(4, 3, 8, 8).astype(np.float32)}
    qstate = det.quantize_int8([feeds])
    assert set(qstate) == {"conv1", "ip1"}
    out = det.forward_all("data", feeds["data"])
    assert np.all(np.isfinite(out["prob"]))
