"""DeviceAugment: the on-device (XLA) twin of the host DataTransformer.

TEST mode must be bit-identical to the host path; TRAIN mode must draw
from exactly the space of valid (offset, flip) crops with the same
mean→crop→mirror→scale order (ref: data_transformer.cpp:19-119).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.data.device_transform import DeviceAugment
from sparknet_tpu.data.prefetch import DevicePrefetcher
from sparknet_tpu.data.transform import DataTransformer, TransformConfig


@pytest.fixture
def u8_batch(rng):
    return (rng.rand(6, 3, 12, 10) * 255).astype(np.uint8)


def test_test_mode_matches_host_exactly(u8_batch, rng):
    mean = rng.rand(3, 12, 10).astype(np.float32) * 100
    cfg = TransformConfig(crop_size=8, mirror=True, mean_image=mean, scale=0.5)
    host = DataTransformer(cfg)(u8_batch, train=False)
    dev = DeviceAugment(cfg)(jnp.asarray(u8_batch), jax.random.key(0),
                             train=False)
    np.testing.assert_allclose(np.asarray(dev), host, atol=1e-5, rtol=1e-6)


def test_test_mode_mean_value(u8_batch):
    cfg = TransformConfig(crop_size=6, mean_value=(10.0, 20.0, 30.0))
    host = DataTransformer(cfg)(u8_batch, train=False)
    dev = DeviceAugment(cfg)(jnp.asarray(u8_batch), jax.random.key(1),
                             train=False)
    np.testing.assert_allclose(np.asarray(dev), host, atol=1e-5, rtol=1e-6)


def test_train_outputs_are_valid_crops(rng):
    """Every TRAIN sample must equal some (offset, flip) window of the
    mean-subtracted input — the exact candidate space of the host path."""
    x = (rng.rand(8, 2, 6, 7) * 255).astype(np.uint8)
    cfg = TransformConfig(crop_size=4, mirror=True)
    out = np.asarray(DeviceAugment(cfg)(jnp.asarray(x), jax.random.key(7)))
    xf = x.astype(np.float32)
    for i in range(len(x)):
        candidates = []
        for ho in range(6 - 4 + 1):
            for wo in range(7 - 4 + 1):
                win = xf[i, :, ho : ho + 4, wo : wo + 4]
                candidates.append(win)
                candidates.append(win[:, :, ::-1])
        assert any(np.allclose(out[i], w, atol=1e-4) for w in candidates), i


def test_mirror_statistics_and_correctness(rng):
    x = (rng.rand(512, 1, 4, 4) * 255).astype(np.uint8)
    cfg = TransformConfig(mirror=True)
    out = np.asarray(DeviceAugment(cfg)(jnp.asarray(x), jax.random.key(3)))
    xf = x.astype(np.float32)
    flipped = np.array(
        [not np.allclose(out[i], xf[i]) for i in range(len(x))]
    )
    assert 0.3 < flipped.mean() < 0.7  # fair coin
    for i in np.where(flipped)[0][:16]:
        np.testing.assert_allclose(out[i], xf[i, :, :, ::-1], atol=1e-5)


def test_jit_and_dtype(u8_batch):
    cfg = TransformConfig(crop_size=8, mirror=True)
    aug = DeviceAugment(cfg)
    f = jax.jit(lambda x, k: aug(x, k, train=True))
    y = f(jnp.asarray(u8_batch), jax.random.key(0))
    assert y.shape == (6, 3, 8, 8) and y.dtype == jnp.float32


def test_rejects_native_backend_and_double_mean(rng):
    with pytest.raises(ValueError, match="backend"):
        DeviceAugment(TransformConfig(backend="native"))
    with pytest.raises(ValueError, match="not both"):
        DeviceAugment(TransformConfig(mean_value=(1.0,),
                                      mean_image=np.zeros((1, 2, 2), np.float32)))


def test_prefetcher_device_fn_integration(rng):
    """uint8 host batches -> device_put -> DeviceAugment in the worker."""
    batches = [(rng.rand(4, 3, 10, 10) * 255).astype(np.uint8)
               for _ in range(3)]
    aug = DeviceAugment(TransformConfig(crop_size=8, mirror=True))
    fetcher = DevicePrefetcher(
        lambda it: {"data": batches[it]},
        num_iters=3,
        device_fn=lambda feeds, it: {
            "data": aug(feeds["data"], jax.random.key(it))
        },
    )
    with fetcher:
        got = list(fetcher)
    assert len(got) == 3
    for feeds in got:
        assert feeds["data"].shape == (4, 3, 8, 8)
        assert feeds["data"].dtype == jnp.float32
