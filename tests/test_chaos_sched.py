"""Chaos-schedule sanitizer: the instrumented-lock mode + the pinned
SIGKILL-holding-lock regression (ISSUE 16 satellites).

Three surfaces:

* ``sparknet_tpu/_chaoslock.py`` unit contracts — plain primitives when
  ``SPARKNET_CHAOS_SCHED`` is unset (the off path must be byte-identical
  runtime behavior), edge recording + reentrancy semantics when armed.
* The dryrun chaos gate (``obs/__main__._chaos_gate``) — rc 1 exactly
  when an observed acquisition edge is absent from the banked static
  graph.
* PR 8's SIGKILLed-worker-holding-a-queue-lock bug, pinned as a seeded
  interleaving at the multiprocessing.Queue level: a child SIGKILLed
  while blocked in ``q.get()`` on an EMPTY queue dies holding the
  queue's reader lock (a POSIX semaphore — not robust, never released),
  so a replacement handed the SAME queue deadlocks even once an item
  arrives; a replacement handed a FRESH queue (what
  ``ProcessPipeline._respawn_or_raise`` builds) drains immediately.
  The kill timing is jittered per trial from the chaos seed, so the
  interleaving is deterministic per seed and replayable.
"""

import json
import multiprocessing
import os
import signal
import threading
import time

import pytest

from sparknet_tpu._chaoslock import (
    _ChaosProxy,
    _lock_rng,
    chaos_armed,
    chaos_seed,
    named_condition,
    named_lock,
    named_rlock,
    observed_edges,
    reset_observed,
)

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def clean_registry():
    reset_observed()
    yield
    reset_observed()


# -- off path ---------------------------------------------------------------


def test_factories_return_plain_primitives_when_off(monkeypatch):
    monkeypatch.delenv("SPARKNET_CHAOS_SCHED", raising=False)
    assert not chaos_armed()
    assert chaos_seed() is None
    assert type(named_lock("X._l")) is type(threading.Lock())
    assert type(named_rlock("X._r")) is type(threading.RLock())
    assert isinstance(named_condition("X._c"), threading.Condition)


def test_malformed_seed_never_arms(monkeypatch):
    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "not-an-int")
    assert not chaos_armed()
    assert type(named_lock("X._l")) is type(threading.Lock())


def test_off_mode_records_nothing(monkeypatch):
    monkeypatch.delenv("SPARKNET_CHAOS_SCHED", raising=False)
    a, b = named_lock("A._l"), named_lock("B._l")
    with a:
        with b:
            pass
    assert observed_edges() == set()


# -- armed path -------------------------------------------------------------


def test_armed_proxy_records_nesting_edges(monkeypatch):
    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "7")
    assert chaos_seed() == 7
    a, b = named_lock("A._l"), named_lock("B._l")
    assert isinstance(a, _ChaosProxy)
    with a:
        with b:
            pass
    assert observed_edges() == {("A._l", "B._l")}
    # the reverse order is a distinct edge
    with b:
        with a:
            pass
    assert observed_edges() == {("A._l", "B._l"), ("B._l", "A._l")}


def test_reentrant_rlock_records_no_self_edge(monkeypatch):
    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "7")
    r = named_rlock("R._l")
    with r:
        with r:  # reentrant re-acquire: no (R._l, R._l) edge
            pass
    assert observed_edges() == set()


def test_condition_proxy_wait_notify_roundtrip(monkeypatch):
    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "3")
    cv = named_condition("CV._cv")
    state = {"go": False, "seen": False}

    def waiter():
        with cv:
            while not state["go"]:
                cv.wait(timeout=5.0)
            state["seen"] = True

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        state["go"] = True
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive() and state["seen"]


def test_jitter_is_deterministic_per_seed_and_name():
    r1 = [_lock_rng("A._l", 5).random() for _ in range(4)]
    r2 = [_lock_rng("A._l", 5).random() for _ in range(4)]
    r3 = [_lock_rng("B._l", 5).random() for _ in range(4)]
    assert r1 == r2
    assert r1 != r3


# -- the dryrun chaos gate --------------------------------------------------


def _bank_graph(tmp_path, edges):
    bank = tmp_path / "conc_contracts"
    bank.mkdir()
    (bank / "lock_graph.json").write_text(json.dumps(
        {"contract": {"locks": sorted({x for e in edges for x in e}),
                      "edges": [list(e) for e in edges]},
         "allow": {}}))
    return str(bank)


def test_chaos_gate_clean_when_observed_subset(monkeypatch, tmp_path):
    from sparknet_tpu.analysis import conccheck
    from sparknet_tpu.obs.__main__ import _chaos_gate

    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "11")
    monkeypatch.setattr(conccheck, "MANIFEST_DIR", _bank_graph(
        tmp_path, [("A._l", "B._l"), ("B._l", "C._l")]))
    a, b = named_lock("A._l"), named_lock("B._l")
    with a:
        with b:
            pass
    assert _chaos_gate() == 0


def test_chaos_gate_fails_on_novel_edge(monkeypatch, tmp_path, capsys):
    from sparknet_tpu.analysis import conccheck
    from sparknet_tpu.obs.__main__ import _chaos_gate

    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "11")
    monkeypatch.setattr(conccheck, "MANIFEST_DIR", _bank_graph(
        tmp_path, [("A._l", "B._l")]))
    a, b = named_lock("A._l"), named_lock("B._l")
    with b:
        with a:  # B -> A is NOT in the static graph
            pass
    assert _chaos_gate() == 1
    assert "B._l -> A._l" in capsys.readouterr().err


def test_chaos_gate_fails_without_banked_manifest(monkeypatch, tmp_path):
    from sparknet_tpu.analysis import conccheck
    from sparknet_tpu.obs.__main__ import _chaos_gate

    monkeypatch.setenv("SPARKNET_CHAOS_SCHED", "11")
    monkeypatch.setattr(conccheck, "MANIFEST_DIR",
                        str(tmp_path / "nowhere"))
    assert _chaos_gate() == 1


def test_chaos_gate_noop_when_off(monkeypatch):
    from sparknet_tpu.obs.__main__ import _chaos_gate

    monkeypatch.delenv("SPARKNET_CHAOS_SCHED", raising=False)
    assert _chaos_gate() == 0


# -- PR 8 regression: SIGKILL holding the free-queue reader lock ------------


def _block_in_get(q, entered):
    entered.set()
    q.get()  # empty queue: blocks in recv with the reader lock held


def _drain_one(q, out):
    out.put(q.get(timeout=5.0))


def _kill_reader_mid_get(ctx, q, delay_s: float) -> None:
    """Spawn a reader, SIGKILL it while it is blocked inside ``get()``
    on the empty queue (the PR 8 death site)."""
    entered = ctx.Event()
    victim = ctx.Process(target=_block_in_get, args=(q, entered),
                         daemon=True)
    victim.start()
    assert entered.wait(10.0)
    # seeded jitter, then kill: by now the reader has acquired the
    # queue's _rlock and parked in recv — SIGKILL leaks the semaphore
    time.sleep(0.2 + delay_s)
    os.kill(victim.pid, signal.SIGKILL)
    victim.join(10.0)


@pytest.mark.parametrize("seed", [77])
def test_sigkill_in_get_deadlocks_shared_queue_but_not_fresh(seed):
    """The old free-queue design (respawn reuses the dead worker's
    queue) deadlocks; the current design (fresh queue, recomputed free
    set — pipeline._respawn_or_raise) drains.  Kill timing is jittered
    from the chaos seed so the interleaving replays by seed."""
    ctx = multiprocessing.get_context("fork")
    delay = _lock_rng("free_q", seed).random() * 0.2

    # OLD design: replacement handed the SAME queue
    shared = ctx.Queue()
    _kill_reader_mid_get(ctx, shared, delay)
    out = ctx.Queue()
    shared.put(0)  # an item is available, yet...
    stuck = ctx.Process(target=_drain_one, args=(shared, out),
                        daemon=True)
    stuck.start()
    stuck.join(3.0)
    deadlocked = stuck.is_alive()
    stuck.kill()
    stuck.join(10.0)
    shared.cancel_join_thread()
    out.cancel_join_thread()
    assert deadlocked, (
        "reusing the dead reader's queue should deadlock the "
        "replacement (the PR 8 bug) — if this starts passing, the "
        "platform's queue lock became robust and the fresh-queue "
        "respawn path can be revisited")

    # CURRENT design: replacement handed a FRESH queue with the free
    # set rebuilt by the parent
    fresh = ctx.Queue()
    _kill_reader_mid_get(ctx, fresh, delay)
    replacement_q = ctx.Queue()  # what _respawn_or_raise constructs
    replacement_q.put(0)
    out2 = ctx.Queue()
    ok = ctx.Process(target=_drain_one, args=(replacement_q, out2),
                     daemon=True)
    ok.start()
    got = out2.get(timeout=10.0)
    ok.join(10.0)
    fresh.cancel_join_thread()
    assert got == 0 and not ok.is_alive()


def test_respawn_hands_replacement_a_fresh_queue():
    """Source-level pin of the fix: ``_respawn_or_raise`` must build a
    NEW context queue for the replacement worker, never reuse
    ``self._free_qs[wid]`` (the exact regression the trial above
    demonstrates at the mechanism level)."""
    import ast
    import inspect

    from sparknet_tpu.data import pipeline

    src = inspect.getsource(pipeline.ProcessPipeline._respawn_or_raise)
    tree = ast.parse("class _W:\n" + src if src.startswith("    ")
                     else src)
    replaces = [
        n for n in ast.walk(tree)
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Subscript)
            and isinstance(t.value, ast.Attribute)
            and t.value.attr == "_free_qs" for t in n.targets)
        and isinstance(n.value, ast.Call)
    ]
    assert replaces, "_respawn_or_raise no longer rebuilds _free_qs[wid]"
