"""Production-loop gates (sparknet_tpu/loop; ROADMAP item 3).

Five contract families:

1. **Hot-swap drain** — tickets submitted before a swap all resolve
   (through the incumbent's OWN executables), the batcher stays open
   (drain != close), and the version lineage advances.
2. **Bitwise rollback** — ``rollback`` restores the SAME retained
   ``ServedModel``: post-rollback scores are bit-identical to
   pre-rollout scores.
3. **Priced rollout refusal** — an over-HBM candidate raises
   ``AdmissionRefused`` with the verdict journaled and the incumbent
   serving untouched (refused, not fatal).
4. **Atomic checkpoints** — ``Solver.save`` npz commits via temp +
   ``os.replace``: a reader polling DURING a slow save never sees a
   partial archive, and the loop's checkpoint->deploy round-trip
   (loop/deploy.py) restores byte-identical weights.
5. **Per-thread compile attribution** — the sentinel separates a
   builder thread's compiles from the serving thread's
   (obs/sentinel.py), the ledger behind the loop dryrun's
   zero-serving-path-compiles gate.

ref: apps/FeaturizerApp.scala:1 (the reference's single driver app
owning both training and scoring; hot reload is new TPU-first surface).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.serve import AdmissionRefused, DynamicBatcher, ServeEngine


def _serve_items(engine, name, n, seed=3):
    from sparknet_tpu.serve.loadgen import synthetic_items

    return synthetic_items(engine._models[name],
                           n, np.random.RandomState(seed))


# -- batcher drain (jax-free) -----------------------------------------------


@pytest.mark.smoke
def test_drain_returns_pending_and_stays_open():
    """drain() hands back every pending ticket WITHOUT closing — the
    hot-swap steal; a rolled-back model's batcher must accept new
    submits afterwards (close() is permanent, drain() is not)."""
    b = DynamicBatcher(buckets=(1, 8), max_wait_ms=5.0,
                       clock=lambda: 0.0)
    tickets = [b.submit(i) for i in range(11)]
    batches = b.drain()
    drained = [t for batch in batches for t in batch]
    assert sorted(t.id for t in drained) == sorted(t.id for t in tickets)
    assert b.pending() == 0
    assert not b.closed
    late = b.submit("after-drain")  # would raise if drain had closed
    assert late.id > tickets[-1].id


# -- hot swap / rollback ----------------------------------------------------


def test_swap_zero_dropped_and_version_lineage():
    """Tickets pending at swap time all resolve (drained through the
    incumbent's own executables); routing flips to the candidate; the
    incumbent is retained one generation for rollback."""
    engine = ServeEngine(buckets=(1, 8))
    engine.load_model("m", family="lenet", seed=0)
    incumbent = engine._models["m"]
    pending = [engine.submit("m", it) for it in
               _serve_items(engine, "m", 3)]
    assert not any(t.done() for t in pending)

    candidate = engine.build_candidate("m", family="lenet", seed=1)
    info = engine.swap_model("m", candidate)

    assert all(t.done() for t in pending), "swap dropped tickets"
    assert all(t.error is None for t in pending)
    assert info["drained"] == 3
    assert info["version"] == 1
    assert engine._models["m"] is candidate
    assert candidate.previous is incumbent
    # both generations priced resident during the rollback window
    assert engine.resident_bytes() == (candidate.predicted_bytes
                                       + incumbent.predicted_bytes)
    engine.shutdown()


def test_rollback_restores_scores_bitwise():
    """The retired generation comes back as the same object with the
    same executables — pre-rollout and post-rollback scores are
    bit-identical; tickets pending at rollback drain through the
    rolled-back candidate's own executables."""
    engine = ServeEngine(buckets=(1, 8))
    engine.load_model("m", family="lenet", seed=0)
    probe = _serve_items(engine, "m", 1)[0]
    s0 = np.asarray(engine.infer("m", probe))

    candidate = engine.build_candidate("m", family="lenet", seed=1)
    engine.swap_model("m", candidate)
    s1 = np.asarray(engine.infer("m", probe))
    assert not np.array_equal(s0, s1), "candidate must score differently"

    pending = [engine.submit("m", it) for it in
               _serve_items(engine, "m", 2)]
    prev = engine.rollback("m")
    assert all(t.done() for t in pending), "rollback dropped tickets"
    assert prev.version == 0 and engine._models["m"] is prev
    s2 = np.asarray(engine.infer("m", probe))
    assert np.array_equal(s0, s2), "rollback is not bitwise"
    # candidate's bytes released; new submits ride the restored batcher
    assert engine.resident_bytes() == prev.predicted_bytes
    engine.shutdown()


def test_refused_candidate_leaves_incumbent_serving(tmp_path):
    """An over-HBM rollout candidate refuses BEFORE any compile, the
    verdict lands in the journal, and the incumbent keeps serving the
    same scores — refused, not fatal."""
    from sparknet_tpu.obs.recorder import Recorder, set_recorder
    from sparknet_tpu.serve.engine import SERVE_BUCKETS

    path = str(tmp_path / "refusal.jsonl")
    rec = set_recorder(Recorder(path, run_id="loop-test"))
    try:
        engine = ServeEngine(buckets=(1,))  # banked fit table
        engine.load_model("m", family="lenet", seed=0)
        probe = _serve_items(engine, "m", 1)[0]
        s0 = np.asarray(engine.infer("m", probe))
        with pytest.raises(AdmissionRefused) as ei:
            engine.build_candidate("m", family="resnet50",
                                   buckets=(SERVE_BUCKETS[-1],))
        assert ei.value.verdict["predicted_bytes"] > 0
        # incumbent untouched: same object, same scores, version 0
        assert engine._models["m"].version == 0
        assert np.array_equal(
            s0, np.asarray(engine.infer("m", probe)))
        engine.shutdown()
    finally:
        rec.close()
        set_recorder(None)
    kinds = [json.loads(line) for line in open(path)]
    refusals = [e for e in kinds if e.get("event") == "serve"
                and e.get("kind") == "load_refused"]
    assert len(refusals) == 1
    assert "incumbent keeps serving" in refusals[0]["note"]


def test_unload_releases_retained_generation():
    """unload_model releases BOTH generations' residency when a
    previous generation is still retained (a priced fit-table row so
    the ledger carries real bytes)."""
    fit = {"families": {"lenet": {"f32": {
        "c0": 1 << 20, "c1": 1 << 10,
        "params_bytes": 1 << 20, "slots_bytes": 0}}}}
    engine = ServeEngine(buckets=(1,), fit_table=fit)
    engine.load_model("m", family="lenet", seed=0)
    candidate = engine.build_candidate("m", family="lenet", seed=1)
    assert candidate.predicted_bytes > 0
    engine.swap_model("m", candidate)
    assert engine.resident_bytes() > candidate.predicted_bytes
    engine.unload_model("m")
    assert engine.resident_bytes() == 0


# -- atomic checkpoints -----------------------------------------------------


def _small_solver():
    from sparknet_tpu.models import zoo
    from sparknet_tpu.solvers.solver import Solver

    return Solver(zoo.lenet_solver(), zoo.lenet(2))


def test_atomic_save_never_shows_a_torn_archive(tmp_path, monkeypatch):
    """A reader polling the final npz name during a SLOW save must see
    either nothing or a complete archive — the os.replace commit.  The
    slow writer dribbles the archive bytes into the temp file, so any
    torn-window bug (writing the final name in place) would surface as
    a zipfile error in the poller."""
    import sparknet_tpu.solvers.solver as solver_mod

    solver = _small_solver()
    prefix = str(tmp_path / "snap")
    final = f"{prefix}.solverstate.npz"
    real_savez = np.savez

    def slow_savez(f, **arrays):
        buf = io.BytesIO()
        real_savez(buf, **arrays)
        payload = buf.getvalue()
        step = max(1, len(payload) // 20)
        for i in range(0, len(payload), step):
            f.write(payload[i:i + step])
            time.sleep(0.002)

    monkeypatch.setattr(solver_mod.np, "savez", slow_savez)
    torn: list[str] = []
    stop = threading.Event()

    def poller():
        while not stop.is_set():
            if os.path.exists(final):
                try:
                    with np.load(final) as data:
                        assert "__iter__" in data.files
                except Exception as e:  # torn archive = the bug
                    torn.append(repr(e))
            time.sleep(0.001)

    t = threading.Thread(target=poller, daemon=True)
    t.start()
    try:
        out = solver.save(prefix)
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert out == final and os.path.exists(final)
    assert not torn, f"poller saw a torn archive: {torn[:3]}"
    # the temp file was committed, not left behind
    leftovers = [n for n in os.listdir(tmp_path) if ".tmp." in n]
    assert not leftovers, leftovers


def test_checkpoint_watcher_sees_only_complete_new_files(tmp_path):
    from sparknet_tpu.loop.watcher import CheckpointWatcher

    w = CheckpointWatcher(str(tmp_path))
    assert w.poll() == []
    solver = _small_solver()
    path = solver.save(str(tmp_path / "round00001"))
    assert w.poll() == [path]
    assert w.poll() == []  # never the same path twice
    path2 = solver.save(str(tmp_path / "round00002"))
    assert w.poll() == [path2]


def test_checkpoint_deploy_roundtrip_bitwise(tmp_path):
    """loop/deploy.py restores byte-identical weights from the saved
    archive — the checkpoint is the durable train->serve hand-off."""
    from sparknet_tpu.loop.deploy import variables_from_checkpoint

    solver = _small_solver()
    path = solver.save(str(tmp_path / "snap"))
    variables = variables_from_checkpoint(path)
    for lname, plist in solver.variables.params.items():
        got = variables.params[lname]
        assert len(got) == len(plist)
        for a, b in zip(got, plist):
            assert np.array_equal(a, np.asarray(b)), lname
    for lname, state in solver.variables.state.items():
        for k, v in state.items():
            assert np.array_equal(variables.state[lname][k],
                                  np.asarray(v)), (lname, k)


def test_deploy_rejects_paramless_archive(tmp_path):
    from sparknet_tpu.loop.deploy import variables_from_checkpoint

    path = str(tmp_path / "empty.npz")
    np.savez(path, **{"__iter__": np.asarray(0)})
    with pytest.raises(ValueError, match="no param/"):
        variables_from_checkpoint(path)


# -- shard feed -------------------------------------------------------------


@pytest.mark.smoke
def test_shard_batches_adapts_source_to_shard_ids():
    from sparknet_tpu.data.pipeline import (SyntheticImageSource,
                                            shard_batches)

    fn = shard_batches(SyntheticImageSource(4, shape=(3, 8, 8), seed=1))
    a, b = fn(0), fn(1)
    assert a["data"].shape == (4, 3, 8, 8)
    assert not np.array_equal(a["data"], b["data"])
    assert np.array_equal(fn(0)["data"], a["data"])  # deterministic


@pytest.mark.smoke
def test_synthetic_shard_feed_shapes_and_determinism():
    from sparknet_tpu.loop.feed import synthetic_shard_feed
    from sparknet_tpu.models.zoo import GRAPH_SWEEP_FAMILIES

    fam = GRAPH_SWEEP_FAMILIES["cifar10_quick"]
    fn = synthetic_shard_feed(fam, 2, seed=0)
    feed = fn(7)
    assert feed["data"].dtype == np.float32
    assert feed["data"].shape[0] == 2
    assert feed["label"].dtype == np.int32
    assert np.array_equal(fn(7)["data"], feed["data"])
    assert not np.array_equal(fn(8)["data"], feed["data"])
    assert float(np.abs(feed["data"]).max()) <= 0.5

    tok = GRAPH_SWEEP_FAMILIES["transformer"]
    tfn = synthetic_shard_feed(tok, 2, seed=0)
    tfeed = tfn(3)
    assert tfeed["data"].shape == (2, tok.seq_len)
    assert tfeed["data"].dtype == np.int32
    assert int(tfeed["data"].max()) < tok.vocab
    assert np.array_equal(tfn(3)["data"], tfeed["data"])


# -- per-thread compile attribution -----------------------------------------


def test_sentinel_attributes_compiles_per_thread():
    """The listener fires on the COMPILING thread: a builder thread's
    fresh jit compile moves its own counter, never the caller's — the
    mechanism behind engine.serve_path_compiles."""
    import jax

    from sparknet_tpu.obs.sentinel import get_sentinel

    sentinel = get_sentinel().install()
    if not sentinel.available:
        pytest.skip("jax monitoring hook unavailable")
    main0 = sentinel.thread_count()
    builder_delta: list[int] = []

    def builder():
        b0 = sentinel.thread_count()
        # a shape never used elsewhere in the suite forces a compile
        x = np.arange(137, dtype=np.float32)
        np.asarray(jax.jit(lambda v: v * 3 + 1)(x))
        builder_delta.append(sentinel.thread_count() - b0)

    t = threading.Thread(target=builder)
    t.start()
    t.join(timeout=120.0)
    assert builder_delta and builder_delta[0] >= 1
    assert sentinel.thread_count() == main0  # caller's ledger untouched


# -- the full loop (chip-free) ----------------------------------------------


def test_loop_run_gates(tmp_path):
    """The integrated drive at minimal scale: every gate the dryrun
    mode 19 pins — zero serving-path compiles, zero dropped, scores
    change on rollout and restore bitwise on rollback, refusal
    journaled with the incumbent intact."""
    from sparknet_tpu.loop.dryrun import loop_run

    summary = loop_run(iterations=1, rounds_per_rollout=1, width=2,
                       tau=1, requests=6, per_worker_batch=2,
                       workdir=str(tmp_path / "loop"))
    assert summary["ok"], summary
    assert summary["serve_path_compiles"] == 0
    assert summary["dropped"] == 0
    assert summary["scores_changed"] and summary["scores_restored"]
    assert summary["refused"] and summary["incumbent_intact_after_refusal"]
    assert summary["checkpoints"] == 1 and summary["rollouts"] == 1
