"""Numerical gradient checks for the op library.

Parity with the reference's GradientChecker methodology (ref:
caffe/include/caffe/test/test_gradient_check_util.hpp:16-63): centered
finite differences against autodiff.  Where Caffe needed per-layer
hand-written Backward passes (the thing being checked), here this validates
that each op's *forward* is autodiff-clean (no non-differentiable
primitives, no precision traps) — the failure mode that actually exists in
a JAX framework.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.common import Phase
from sparknet_tpu.ops import create_layer
from sparknet_tpu.proto import parse


def make_layer(prototxt: str, phase=Phase.TRAIN):
    msg = parse(prototxt)
    return create_layer(msg.get_all("layer")[0], phase)


def num_grad(f, x, eps=1e-3):
    """Centered-difference gradient of scalar f at x (numpy loop, tiny shapes)."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = float(f(jnp.asarray(x, jnp.float32)))
        flat[i] = orig - eps
        fm = float(f(jnp.asarray(x, jnp.float32)))
        flat[i] = orig
        gf[i] = (fp - fm) / (2 * eps)
    return g


def check_layer_grad(layer, in_arrays, params=None, state=None, atol=5e-2, rtol=5e-2, wrt="input"):
    params = params or []
    state = state or {}
    rng = jax.random.key(7)

    def scalar_out(x):
        if wrt == "input":
            ins = [x] + list(in_arrays[1:])
            out = layer.apply(params, state, ins, train=True, rng=rng)
        else:  # wrt first param
            out = layer.apply([x] + params[1:], state, list(in_arrays), train=True, rng=rng)
        # random-ish fixed projection to a scalar, like checking every top elt
        total = 0.0
        for o in out.outputs:
            w = np.cos(np.arange(o.size)).reshape(o.shape)
            total = total + jnp.sum(o * jnp.asarray(w, o.dtype))
        return total

    target = in_arrays[0] if wrt == "input" else params[0]
    auto = np.asarray(jax.grad(scalar_out)(target))
    numeric = num_grad(scalar_out, target)
    np.testing.assert_allclose(auto, numeric, atol=atol, rtol=rtol)


@pytest.fixture
def x44(rng):
    return jnp.asarray(rng.randn(2, 3, 4, 4), jnp.float32)


@pytest.mark.smoke
def test_convolution_grad(rng, x44):
    layer = make_layer(
        'layer { name: "c" type: "Convolution" bottom: "x" top: "y" '
        "convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 "
        'weight_filler { type: "gaussian" std: 0.5 } bias_filler { type: "uniform" min: -0.3 max: 0.3 } } }'
    )
    params, state = layer.init(jax.random.key(0), [x44.shape])
    check_layer_grad(layer, [x44], params, state)
    check_layer_grad(layer, [x44], params, state, wrt="param")


def test_convolution_group_dilation_grad(rng):
    x = jnp.asarray(rng.randn(2, 4, 5, 5), jnp.float32)
    layer = make_layer(
        'layer { name: "c" type: "Convolution" bottom: "x" top: "y" '
        "convolution_param { num_output: 4 kernel_size: 3 pad: 2 dilation: 2 group: 2 "
        'weight_filler { type: "gaussian" std: 0.5 } } }'
    )
    params, state = layer.init(jax.random.key(0), [x.shape])
    check_layer_grad(layer, [x], params, state)


def test_deconvolution_grad(rng):
    x = jnp.asarray(rng.randn(2, 4, 3, 3), jnp.float32)
    layer = make_layer(
        'layer { name: "d" type: "Deconvolution" bottom: "x" top: "y" '
        "convolution_param { num_output: 2 kernel_size: 3 stride: 2 pad: 1 "
        'weight_filler { type: "gaussian" std: 0.5 } } }'
    )
    params, state = layer.init(jax.random.key(0), [x.shape])
    check_layer_grad(layer, [x], params, state)
    check_layer_grad(layer, [x], params, state, wrt="param")


def test_pooling_max_grad(rng):
    # perturbation smaller than typical gaps; kinks are the classic
    # nonsmooth case the reference handles with kink-exclusion windows
    x = jnp.asarray(rng.randn(2, 2, 6, 6) * 10, jnp.float32)
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: MAX kernel_size: 3 stride: 2 pad: 1 } }"
    )
    check_layer_grad(layer, [x])


def test_pooling_ave_grad(rng):
    x = jnp.asarray(rng.randn(2, 2, 5, 5), jnp.float32)
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 } }"
    )
    check_layer_grad(layer, [x])


def test_pooling_rejects_degenerate_geometry():
    """A kernel larger than the input must fail loudly at shape inference
    (e.g. GoogLeNet's 7x7 pool5 fed a sub-224 crop), not surface as a
    zero-size shape exploding in a downstream layer."""
    from sparknet_tpu.ops.base import conv_out_dim, pool_out_dim

    with pytest.raises(ValueError, match="produces no output"):
        pool_out_dim(4, 7, 0, 1)
    with pytest.raises(ValueError, match="produces no output"):
        conv_out_dim(8, 11, 0, 1)
    x = jnp.zeros((1, 1, 4, 4), jnp.float32)
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: MAX kernel_size: 7 stride: 1 } }"
    )
    with pytest.raises(ValueError, match="produces no output"):
        layer.apply([], {}, [x], train=True, rng=jax.random.key(0))


def test_pooling_stochastic_test_mode_grad(rng):
    """TEST-mode stochastic pooling (sum(a^2)/sum(a)) is smooth where the
    window sum is bounded away from 0 — FD-checkable like AVE."""
    x = jnp.asarray(np.abs(rng.randn(2, 2, 5, 5)) + 0.5, jnp.float32)
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: STOCHASTIC kernel_size: 3 stride: 2 } }",
        phase=Phase.TEST,
    )

    def scalar_out(inp):
        out = layer.apply([], {}, [inp], train=False)
        w = np.cos(np.arange(out.outputs[0].size)).reshape(out.outputs[0].shape)
        return jnp.sum(out.outputs[0] * jnp.asarray(w, jnp.float32))

    g_auto = np.asarray(jax.grad(scalar_out)(x))
    g_num = num_grad(scalar_out, x)
    np.testing.assert_allclose(g_auto, g_num, atol=5e-2, rtol=5e-2)


def test_pooling_stochastic_train_grad_routes_to_sampled_element(rng):
    """TRAIN-mode autodiff must scatter the gradient to exactly the sampled
    window element — the reference's StoPoolBackward index routing
    (pooling_layer.cu:300-330).  FD is meaningless across a sampling kink,
    so the check is structural: d(sum y)/dx is one 1.0 per window, placed
    where the forward's sampled value came from."""
    x = jnp.asarray(np.abs(rng.randn(1, 1, 4, 4)) + 0.1, jnp.float32)
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 } }"
    )
    key = jax.random.key(3)
    y = layer.apply([], {}, [x], train=True, rng=key).outputs[0]
    g = jax.grad(
        lambda inp: jnp.sum(layer.apply([], {}, [inp], train=True, rng=key).outputs[0])
    )(x)
    g = np.asarray(g)
    xn, yn = np.asarray(x), np.asarray(y)
    # one selected element per 2x2 window, gradient 1 there, 0 elsewhere
    assert np.all(np.sort(np.unique(g)) == np.asarray([0.0, 1.0]))
    for oh in range(2):
        for ow in range(2):
            win_g = g[0, 0, 2 * oh : 2 * oh + 2, 2 * ow : 2 * ow + 2]
            win_x = xn[0, 0, 2 * oh : 2 * oh + 2, 2 * ow : 2 * ow + 2]
            assert win_g.sum() == 1.0
            # and the forwarded value is the selected activation
            assert np.isclose(yn[0, 0, oh, ow], win_x[win_g == 1.0][0])


def test_pooling_stochastic_samples_by_activation_mass():
    """Over many rng draws, each window element is selected with frequency
    proportional to its activation (StoPoolForwardTrain's r*sum threshold
    rule); TEST mode returns the exact activation-weighted average."""
    x = jnp.asarray([[[[1.0, 3.0], [0.0, 4.0]]]], jnp.float32)  # one 2x2 window
    layer = make_layer(
        'layer { name: "p" type: "Pooling" bottom: "x" top: "y" '
        "pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 } }"
    )
    draws = np.asarray([
        np.asarray(
            layer.apply([], {}, [x], train=True, rng=jax.random.key(i)).outputs[0]
        ).item()
        for i in range(400)
    ])
    freq = {v: float((draws == v).mean()) for v in (1.0, 3.0, 4.0)}
    assert abs(freq[1.0] - 1 / 8) < 0.06
    assert abs(freq[3.0] - 3 / 8) < 0.07
    assert abs(freq[4.0] - 4 / 8) < 0.07
    assert not np.any(draws == 0.0)  # zero-mass element never sampled
    y_test = np.asarray(layer.apply([], {}, [x], train=False).outputs[0]).item()
    assert np.isclose(y_test, (1 + 9 + 16) / 8.0)  # sum(a^2)/sum(a)


def test_lrn_across_grad(rng, x44):
    layer = make_layer(
        'layer { name: "n" type: "LRN" bottom: "x" top: "y" '
        "lrn_param { local_size: 3 alpha: 0.001 beta: 0.75 } }"
    )
    check_layer_grad(layer, [x44])


def test_lrn_within_grad(rng, x44):
    layer = make_layer(
        'layer { name: "n" type: "LRN" bottom: "x" top: "y" '
        "lrn_param { local_size: 3 alpha: 0.001 beta: 0.75 norm_region: WITHIN_CHANNEL } }"
    )
    check_layer_grad(layer, [x44])


def test_inner_product_grad(rng, x44):
    layer = make_layer(
        'layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y" '
        'inner_product_param { num_output: 5 weight_filler { type: "xavier" } } }'
    )
    params, state = layer.init(jax.random.key(0), [x44.shape])
    check_layer_grad(layer, [x44], params, state)
    check_layer_grad(layer, [x44], params, state, wrt="param")


@pytest.mark.parametrize(
    "ltype,extra",
    [
        ("ReLU", ""),
        ("ReLU", "relu_param { negative_slope: 0.1 }"),
        ("Sigmoid", ""),
        ("TanH", ""),
        ("AbsVal", ""),
        ("BNLL", ""),
        ("ELU", ""),
        ("Exp", "exp_param { base: 2.0 scale: 0.5 shift: 0.1 }"),
        ("Power", "power_param { power: 2.0 scale: 0.5 shift: 1.5 }"),
    ],
)
def test_neuron_grads(rng, ltype, extra):
    x = jnp.asarray(rng.randn(2, 3, 4, 4) + 0.1, jnp.float32)
    layer = make_layer(f'layer {{ name: "n" type: "{ltype}" bottom: "x" top: "y" {extra} }}')
    check_layer_grad(layer, [x])


def test_prelu_grad(rng, x44):
    layer = make_layer('layer { name: "p" type: "PReLU" bottom: "x" top: "y" }')
    params, state = layer.init(jax.random.key(0), [x44.shape])
    check_layer_grad(layer, [x44], params, state)
    check_layer_grad(layer, [x44], params, state, wrt="param")


def test_eltwise_sum_coeff_grad(rng, x44):
    y = jnp.asarray(np.random.RandomState(5).randn(2, 3, 4, 4), jnp.float32)
    layer = make_layer(
        'layer { name: "e" type: "Eltwise" bottom: "a" bottom: "b" top: "y" '
        "eltwise_param { operation: SUM coeff: 1.5 coeff: -0.5 } }"
    )
    check_layer_grad(layer, [x44, y])


def test_softmax_with_loss_grad(rng):
    x = jnp.asarray(rng.randn(4, 5), jnp.float32)
    labels = jnp.asarray([0, 2, 4, 1], jnp.int32)
    layer = make_layer('layer { name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "lab" top: "loss" }')
    check_layer_grad(layer, [x, labels], atol=1e-2)


def test_softmax_with_loss_spatial_ignore(rng):
    x = jnp.asarray(rng.randn(2, 5, 3, 3), jnp.float32)
    labels = jnp.asarray(np.random.RandomState(3).randint(0, 5, (2, 3, 3)), jnp.int32)
    labels = labels.at[0, 0, 0].set(255)
    layer = make_layer(
        'layer { name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "lab" top: "loss" '
        "loss_param { ignore_label: 255 } }"
    )
    check_layer_grad(layer, [x, labels], atol=1e-2)


def test_euclidean_loss_grad(rng):
    a = jnp.asarray(rng.randn(4, 3), jnp.float32)
    b = jnp.asarray(np.random.RandomState(9).randn(4, 3), jnp.float32)
    layer = make_layer('layer { name: "l" type: "EuclideanLoss" bottom: "a" bottom: "b" top: "loss" }')
    check_layer_grad(layer, [a, b])


def test_hinge_l2_grad(rng):
    x = jnp.asarray(rng.randn(4, 5), jnp.float32)
    labels = jnp.asarray([0, 2, 4, 1], jnp.int32)
    layer = make_layer(
        'layer { name: "l" type: "HingeLoss" bottom: "x" bottom: "lab" top: "loss" '
        "hinge_loss_param { norm: L2 } }"
    )
    check_layer_grad(layer, [x, labels])


def test_sigmoid_ce_grad(rng):
    x = jnp.asarray(rng.randn(4, 6), jnp.float32)
    t = jnp.asarray(np.random.RandomState(2).rand(4, 6), jnp.float32)
    layer = make_layer('layer { name: "l" type: "SigmoidCrossEntropyLoss" bottom: "x" bottom: "t" top: "loss" }')
    check_layer_grad(layer, [x, t], atol=1e-2)


def test_contrastive_loss_grad(rng):
    a = jnp.asarray(rng.randn(4, 3) * 0.5, jnp.float32)
    b = jnp.asarray(np.random.RandomState(8).randn(4, 3) * 0.5, jnp.float32)
    y = jnp.asarray([1, 0, 1, 0], jnp.int32)
    layer = make_layer('layer { name: "l" type: "ContrastiveLoss" bottom: "a" bottom: "b" bottom: "y" top: "loss" }')
    check_layer_grad(layer, [a, b, y], atol=1e-2)


def test_batchnorm_train_matches_manual(rng, x44):
    layer = make_layer('layer { name: "bn" type: "BatchNorm" bottom: "x" top: "y" }')
    params, state = layer.init(jax.random.key(0), [x44.shape])
    out = layer.apply(params, state, [x44], train=True, rng=None)
    y = np.asarray(out.outputs[0])
    xn = np.asarray(x44)
    mu = xn.mean(axis=(0, 2, 3), keepdims=True)
    var = (xn**2).mean(axis=(0, 2, 3), keepdims=True) - mu**2
    np.testing.assert_allclose(y, (xn - mu) / np.sqrt(var + 1e-5), atol=1e-4)
    # moving stats updated: scale_factor 0 -> 1
    assert float(out.state["scale_factor"][0]) == pytest.approx(1.0)
    # test phase uses accumulated stats
    out2 = layer.apply(params, out.state, [x44], train=False, rng=None)
    np.testing.assert_allclose(np.asarray(out2.outputs[0]), y, atol=1e-3)


def test_dropout_train_scaling(rng, x44):
    layer = make_layer(
        'layer { name: "d" type: "Dropout" bottom: "x" top: "y" dropout_param { dropout_ratio: 0.4 } }'
    )
    x = jnp.ones((1000,))
    out = layer.apply([], {}, [x], train=True, rng=jax.random.key(0)).outputs[0]
    kept = np.asarray(out) != 0
    assert abs(kept.mean() - 0.6) < 0.05
    np.testing.assert_allclose(np.asarray(out)[kept], 1.0 / 0.6, rtol=1e-5)
    # test phase = identity
    out = layer.apply([], {}, [x], train=False, rng=None).outputs[0]
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_eltwise_coeff_count_mismatch_rejected(rng, x44):
    y = jnp.asarray(np.random.RandomState(5).randn(2, 3, 4, 4), jnp.float32)
    layer = make_layer(
        'layer { name: "e" type: "Eltwise" bottom: "a" bottom: "b" top: "y" '
        "eltwise_param { operation: SUM coeff: 1.5 } }"
    )
    with pytest.raises(ValueError, match="coeffs"):
        layer.apply([], {}, [x44, y], train=True, rng=None)


def test_partial_kernel_hw_rejected():
    with pytest.raises(ValueError, match="kernel_h"):
        layer = make_layer(
            'layer { name: "c" type: "Convolution" bottom: "x" top: "y" '
            "convolution_param { num_output: 2 kernel_h: 3 } }"
        )
        layer.init(jax.random.key(0), [(1, 3, 8, 8)])


# ---- coverage widening: remaining differentiable op set ----------------


def test_embed_param_grad(rng):
    idx = jnp.asarray(rng.randint(0, 6, (4,)), jnp.int32)
    layer = make_layer(
        'layer { name: "e" type: "Embed" bottom: "i" top: "y" '
        "embed_param { input_dim: 6 num_output: 3 bias_term: true "
        'weight_filler { type: "uniform" min: -1 max: 1 } } }'
    )
    params, state = layer.init(jax.random.key(0), [(4,)])
    check_layer_grad(layer, [idx], params, state, wrt="param")


def test_scale_grad_param_and_input(rng, x44):
    layer = make_layer(
        'layer { name: "s" type: "Scale" bottom: "x" top: "y" '
        "scale_param { bias_term: true } }"
    )
    params, state = layer.init(jax.random.key(0), [x44.shape])
    check_layer_grad(layer, [x44], params, state, wrt="input")
    check_layer_grad(layer, [x44], params, state, wrt="param")


def test_bias_grad(rng, x44):
    layer = make_layer('layer { name: "b" type: "Bias" bottom: "x" top: "y" }')
    params, state = layer.init(jax.random.key(0), [x44.shape])
    check_layer_grad(layer, [x44], params, state, wrt="param")


def test_mvn_grad(rng, x44):
    for extra in ("", "mvn_param { normalize_variance: false }",
                  "mvn_param { across_channels: true }"):
        layer = make_layer(
            f'layer {{ name: "m" type: "MVN" bottom: "x" top: "y" {extra} }}'
        )
        check_layer_grad(layer, [x44])


def test_log_grad(rng):
    x = jnp.asarray(np.abs(rng.randn(2, 3, 4, 4)) + 0.5, jnp.float32)
    layer = make_layer(
        'layer { name: "l" type: "Log" bottom: "x" top: "y" '
        "log_param { base: 10.0 scale: 2.0 shift: 0.5 } }"
    )
    check_layer_grad(layer, [x])


def test_tile_grad(rng, x44):
    layer = make_layer(
        'layer { name: "t" type: "Tile" bottom: "x" top: "y" '
        "tile_param { axis: 1 tiles: 3 } }"
    )
    check_layer_grad(layer, [x44])


@pytest.mark.parametrize("op", ["SUM", "MEAN", "ASUM", "SUMSQ"])
def test_reduction_grads(rng, op, x44):
    layer = make_layer(
        f'layer {{ name: "r" type: "Reduction" bottom: "x" top: "y" '
        f"reduction_param {{ operation: {op} coeff: 0.5 }} }}"
    )
    # ASUM is non-smooth at 0 — keep inputs away from the kink, like the
    # reference's GradientChecker kink handling
    x = jnp.asarray(np.sign(np.asarray(x44)) * (np.abs(np.asarray(x44)) + 0.3),
                    jnp.float32)
    check_layer_grad(layer, [x])


def test_concat_slice_grads(rng, x44):
    x2 = jnp.asarray(rng.randn(2, 2, 4, 4), jnp.float32)
    concat = make_layer(
        'layer { name: "c" type: "Concat" bottom: "a" bottom: "b" top: "y" }'
    )
    check_layer_grad(concat, [x44, x2])
    sl = make_layer(
        'layer { name: "s" type: "Slice" bottom: "x" top: "y1" top: "y2" '
        "slice_param { axis: 1 slice_point: 1 } }"
    )
    check_layer_grad(sl, [x44])


def test_multinomial_logistic_loss_grad(rng):
    # probabilities in, like the reference layer (post-softmax)
    p = np.abs(rng.rand(4, 5)) + 0.1
    p = jnp.asarray(p / p.sum(1, keepdims=True), jnp.float32)
    y = jnp.asarray(rng.randint(0, 5, (4,)), jnp.int32)
    layer = make_layer(
        'layer { name: "m" type: "MultinomialLogisticLoss" '
        'bottom: "p" bottom: "y" top: "l" }'
    )
    check_layer_grad(layer, [p, y])


def test_infogain_loss_grad(rng):
    p = np.abs(rng.rand(3, 4)) + 0.1
    p = jnp.asarray(p / p.sum(1, keepdims=True), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, (3,)), jnp.int32)
    H = jnp.asarray(np.eye(4) + 0.1, jnp.float32)
    layer = make_layer(
        'layer { name: "i" type: "InfogainLoss" '
        'bottom: "p" bottom: "y" bottom: "H" top: "l" }'
    )
    check_layer_grad(layer, [p, y, H])


def test_multihead_attention_layer(rng):
    """In-graph attention layer: correct math vs a manual reference, grads
    flow through params and inputs, causal masking honored."""
    x = jnp.asarray(rng.randn(2, 6, 8) * 0.5, jnp.float32)
    layer = make_layer(
        'layer { name: "a" type: "MultiHeadAttention" bottom: "x" top: "y" '
        "attention_param { num_heads: 2 causal: true } }"
    )
    params, state = layer.init(jax.random.key(0), [x.shape])
    assert [tuple(p.shape) for p in params] == [(24, 8), (24,), (8, 8), (8,)]
    out = layer.apply(params, state, [x], train=True, rng=None).outputs[0]
    assert out.shape == (2, 6, 8)

    # manual oracle
    w_qkv, b_qkv, w_out, b_out = params
    qkv = x @ w_qkv.T + b_qkv
    q, k, v = jnp.split(qkv, 3, axis=-1)
    sp = lambda t: t.reshape(2, 6, 2, 4).transpose(0, 2, 1, 3)
    from sparknet_tpu.parallel.ring_attention import reference_attention

    o = reference_attention(sp(q), sp(k), sp(v), causal=True)
    expect = o.transpose(0, 2, 1, 3).reshape(2, 6, 8) @ w_out.T + b_out
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)

    # causal: output at position t is independent of inputs at positions > t
    x2 = x.at[:, -1, :].set(99.0)
    out2 = layer.apply(params, state, [x2], train=True, rng=None).outputs[0]
    np.testing.assert_allclose(
        np.asarray(out[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
    )

    # gradient check wrt W_qkv
    check_layer_grad(layer, [x], params, state, wrt="param")


def test_attention_embed_dim_validation(rng):
    layer = make_layer(
        'layer { name: "a" type: "MultiHeadAttention" bottom: "x" top: "y" '
        "attention_param { num_heads: 3 } }"
    )
    with pytest.raises(ValueError, match="divisible"):
        layer.init(jax.random.key(0), [(2, 4, 8)])


def test_attention_net_trains_and_snapshots(tmp_path, rng):
    """A small sequence model through the FULL framework path: prototxt ->
    compile -> train -> caffemodel roundtrip."""
    from sparknet_tpu.net import TPUNet
    from sparknet_tpu.proto import parse
    from sparknet_tpu.solvers.solver import SolverConfig

    proto = parse(
        """
        name: "seq"
        input: "x" input_shape { dim: 8 dim: 10 dim: 16 }
        input: "label" input_shape { dim: 8 }
        layer { name: "attn" type: "MultiHeadAttention" bottom: "x" top: "h"
                attention_param { num_heads: 4 causal: true } }
        layer { name: "pool" type: "Reduction" bottom: "h" top: "hp"
                reduction_param { operation: MEAN axis: 1 } }
        layer { name: "cls" type: "InnerProduct" bottom: "hp" top: "logits"
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss"
                bottom: "logits" bottom: "label" }
        """
    )
    net = TPUNet(SolverConfig(base_lr=0.05), proto)
    T = rng.randn(3, 16).astype(np.float32)

    def batch(it):
        y = rng.randint(0, 3, 8)
        x = rng.randn(8, 10, 16).astype(np.float32) * 0.3 + T[y][:, None, :]
        return {"x": x, "label": y.astype(np.int32)}

    net.set_train_data(batch)
    l0 = net.train(1)
    net.train(40)
    l1 = net.train(1)
    assert l1 < l0 * 0.5, (l0, l1)
    # weights roundtrip like any zoo model
    p = str(tmp_path / "seq.caffemodel")
    net.save_caffemodel(p)
    net2 = TPUNet(SolverConfig(), proto)
    loaded = net2.load_caffemodel(p)
    assert "attn" in loaded
    np.testing.assert_allclose(
        np.asarray(net2.solver.variables.params["attn"][0]),
        np.asarray(net.solver.variables.params["attn"][0]),
    )


def test_moe_layer(rng):
    """In-graph MoE layer: dense top-1 math vs hand computation, grads
    flow, full prototxt net trains."""
    from sparknet_tpu.ops.moe import expert_ffn, gate_top1

    x = jnp.asarray(rng.randn(4, 6, 8) * 0.5, jnp.float32)
    layer = make_layer(
        'layer { name: "m" type: "MoE" bottom: "x" top: "y" '
        "moe_param { num_experts: 4 hidden_dim: 16 } }"
    )
    params, state = layer.init(jax.random.key(0), [x.shape])
    assert [tuple(p.shape) for p in params] == [
        (4, 8), (4, 16, 8), (4, 16), (4, 8, 16), (4, 8)]
    out = layer.apply(params, state, [x], train=True, rng=None).outputs[0]
    assert out.shape == x.shape

    # manual oracle: route each token through its argmax expert alone
    tokens = np.asarray(x.reshape(-1, 8))
    idx, prob = gate_top1(params[0], jnp.asarray(tokens))
    expect = np.zeros_like(tokens)
    for t in range(tokens.shape[0]):
        e = int(idx[t])
        pe = tuple(p[e] for p in params[1:])
        expect[t] = np.asarray(
            expert_ffn(pe, jnp.asarray(tokens[None, t]))[0]
        ) * float(prob[t])
    np.testing.assert_allclose(
        np.asarray(out.reshape(-1, 8)), expect, atol=2e-5
    )

    # gate argmax is piecewise constant, so the output is differentiable
    # almost everywhere: centered differences agree with autodiff
    check_layer_grad(layer, [x], params, state, wrt="input")


def test_moe_net_trains(rng):
    """MoE through the full framework path: prototxt -> compile -> train."""
    from sparknet_tpu.net import TPUNet
    from sparknet_tpu.solvers.solver import SolverConfig

    proto = parse(
        """
        name: "moe_seq"
        input: "x" input_shape { dim: 8 dim: 16 }
        input: "label" input_shape { dim: 8 }
        layer { name: "moe" type: "MoE" bottom: "x" top: "h"
                moe_param { num_experts: 4 hidden_dim: 32 } }
        layer { name: "cls" type: "InnerProduct" bottom: "h" top: "logits"
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss"
                bottom: "logits" bottom: "label" }
        """
    )
    net = TPUNet(SolverConfig(base_lr=0.05), proto)
    T = rng.randn(3, 16).astype(np.float32)

    def batch(it):
        y = rng.randint(0, 3, 8)
        x = rng.randn(8, 16).astype(np.float32) * 0.3 + T[y]
        return {"x": x, "label": y.astype(np.int32)}

    net.set_train_data(batch)
    l0 = net.train(1)
    l1 = net.train(60)
    assert l1 < l0
