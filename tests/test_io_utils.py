"""pycaffe ``caffe.io`` surface parity (ref: caffe/python/caffe/io.py;
roundtrip style mirrors caffe/python/caffe/test/test_io.py)."""

import numpy as np
import pytest

from sparknet_tpu.data import io_utils as cio
from sparknet_tpu.data.transform import load_mean_file


class TestBlobProto:
    def test_roundtrip(self, rng):
        a = rng.randn(2, 3, 4, 5).astype(np.float32)
        out = cio.blobproto_to_array(cio.array_to_blobproto(a))
        assert out.shape == a.shape
        assert np.allclose(out, a)

    def test_scalar_and_1d(self, rng):
        v = rng.randn(7).astype(np.float32)
        assert np.allclose(cio.blobproto_to_array(cio.array_to_blobproto(v)), v)

    def test_mean_binaryproto_file(self, rng, tmp_path):
        mean = rng.rand(3, 8, 8).astype(np.float32) * 255
        path = str(tmp_path / "mean.binaryproto")
        cio.save_mean_binaryproto(path, mean)
        back = cio.load_mean_binaryproto(path)
        assert back.shape == (3, 8, 8)
        assert np.allclose(back, mean)
        # load_mean_file dispatches on extension
        assert np.allclose(load_mean_file(path), mean)
        npy = str(tmp_path / "mean.npy")
        np.save(npy, mean)
        assert np.allclose(load_mean_file(npy), mean)


class TestDatum:
    def test_uint8_roundtrip(self, rng):
        arr = (rng.rand(3, 10, 10) * 255).astype(np.uint8)
        buf = cio.array_to_datum(arr, label=42)
        back, label = cio.datum_to_array(buf)
        assert label == 42
        assert back.dtype == np.uint8
        assert (back == arr).all()

    def test_float_roundtrip(self, rng):
        arr = rng.randn(1, 4, 6).astype(np.float32)
        back, label = cio.datum_to_array(cio.array_to_datum(arr, label=0))
        assert label == 0
        assert np.allclose(back, arr)

    def test_bad_rank_rejected(self):
        with pytest.raises(ValueError):
            cio.array_to_datum(np.zeros((4, 4)))

    def test_negative_label_roundtrip(self):
        # Datum.label is signed int32; negatives are 10-byte varints
        arr = np.zeros((1, 2, 2), np.uint8)
        back, label = cio.datum_to_array(cio.array_to_datum(arr, label=-1))
        assert label == -1
        back, label = cio.datum_to_array(cio.array_to_datum(arr, label=-1000))
        assert label == -1000


class TestImageOps:
    def test_resize_shapes_and_range(self, rng):
        im = rng.rand(16, 20, 3).astype(np.float32)
        out = cio.resize_image(im, (8, 10))
        assert out.shape == (8, 10, 3)
        assert out.min() >= im.min() - 1e-5 and out.max() <= im.max() + 1e-5

    def test_resize_constant_image(self):
        im = np.full((5, 5, 1), 3.25, np.float32)
        out = cio.resize_image(im, (9, 7))
        assert out.shape == (9, 7, 1)
        assert (out == 3.25).all()

    def test_resize_nearest(self):
        im = np.arange(4, dtype=np.float32).reshape(2, 2, 1)
        out = cio.resize_image(im, (4, 4), interp_order=0)
        # nearest keeps only original values
        assert set(np.unique(out)) <= set(im.ravel().tolist())

    def test_oversample_ten_crops(self, rng):
        im = rng.rand(12, 14, 3).astype(np.float32)
        crops = cio.oversample([im, im], (8, 8))
        assert crops.shape == (20, 8, 8, 3)
        # crop 0 is the top-left corner; crop 5 is its horizontal mirror
        assert np.allclose(crops[0], im[:8, :8])
        assert np.allclose(crops[5], crops[0][:, ::-1, :])
        # crop 4 is the center crop
        assert np.allclose(crops[4], im[2:10, 3:11])

    def test_load_image_color_and_gray(self, tmp_path, rng):
        from PIL import Image

        arr = (rng.rand(6, 5, 3) * 255).astype(np.uint8)
        p = str(tmp_path / "x.png")
        Image.fromarray(arr).save(p)
        im = cio.load_image(p)
        assert im.shape == (6, 5, 3) and im.dtype == np.float32
        assert np.allclose(im, arr / 255.0, atol=1e-6)
        gray = cio.load_image(p, color=False)
        assert gray.shape == (6, 5, 1)


class TestTransformer:
    def make(self):
        t = cio.Transformer({"data": (1, 3, 8, 10)})
        t.set_transpose("data", (2, 0, 1))
        t.set_channel_swap("data", (2, 1, 0))
        t.set_raw_scale("data", 255.0)
        t.set_mean("data", np.array([104.0, 117.0, 123.0], np.float32))
        t.set_input_scale("data", 0.5)
        return t

    def test_preprocess_shape_and_inverse(self, rng):
        t = self.make()
        im = rng.rand(16, 20, 3).astype(np.float32)
        blob = t.preprocess("data", im)
        assert blob.shape == (3, 8, 10)
        # deprocess inverts everything but the resize
        resized = cio.resize_image(im, (8, 10))
        assert np.allclose(t.deprocess("data", blob), resized, atol=1e-4)

    def test_preprocess_order_matches_reference(self):
        # input_scale applies AFTER mean; raw_scale BEFORE (io.py:261-276)
        t = cio.Transformer({"data": (1, 1, 2, 2)})
        t.set_raw_scale("data", 10.0)
        t.set_mean("data", np.array([1.0], np.float32))
        t.set_input_scale("data", 2.0)
        im = np.ones((2, 2, 1), np.float32)
        t.set_transpose("data", (2, 0, 1))
        out = t.preprocess("data", im)
        assert np.allclose(out, (1 * 10 - 1) * 2)

    def test_validation(self):
        t = cio.Transformer({"data": (1, 3, 8, 10)})
        with pytest.raises(ValueError):
            t.preprocess("nope", np.zeros((8, 10, 3)))
        with pytest.raises(ValueError):
            t.set_transpose("data", (0, 1))
        with pytest.raises(ValueError):
            t.set_channel_swap("data", (0, 1))
        with pytest.raises(ValueError):
            t.set_mean("data", np.zeros(4, np.float32))
