"""Telemetry control plane (loop/autoctl.py + tools/ctl_scenarios.py):
the decision ladder on a fake plane, the four banked scenario A/Bs,
and the rendering surfaces (report / top / slo vacuous visibility).

The controller's CLAIMS: burn answers with the cheapest reversible
move (canary rollback > priced join > width loan), every action is
separated by a cooldown, a priced refusal journals instead of booting,
release is patient (healthy_s before any give-back, replicas before
width), and the whole ladder replays bit-identically against the
traces banked in docs/ctl_contracts/.  Virtual time throughout — no
sleeps, no jax, smoke-tier.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from sparknet_tpu.loop.autoctl import SLOController
from sparknet_tpu.obs import schema
from sparknet_tpu.obs import slo as _slo
from sparknet_tpu.obs.report import render_path

pytestmark = pytest.mark.smoke

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MANIFEST = {"version": 1, "slos": [
    {"id": "warm-queue-p99", "kind": "warm_queue_p99", "max_ms": 40.0,
     "warmup_requests": 0},
    {"id": "zero-drop", "kind": "dropped_zero"},
]}


class FakePlane:
    """Duck-typed control plane with programmable capacity."""

    def __init__(self, free=1, fits=True, lendable=0, rollback_ok=False):
        self.width = 2
        self.free = free
        self.fits = fits
        self.lendable = lendable
        self.rollback_ok = rollback_ok
        self.calls = []

    def serve_width(self):
        return self.width

    def can_grow(self):
        if self.free <= 0:
            return None
        return {"fits": self.fits, "predicted_bytes": 640,
                "budget_bytes": 1300}

    def grow(self):
        self.calls.append("grow")
        self.free -= 1
        self.width += 1
        return {"replica": self.width - 1, "width": self.width}

    def shrink(self):
        self.calls.append("shrink")
        self.width -= 1
        self.free += 1
        return {"replica": self.width, "width": self.width, "rerouted": 0}

    def can_lend(self):
        return self.lendable > 0

    def lend(self):
        self.calls.append("lend")
        self.lendable -= 1
        return {"count": 1, "from_width": 4, "to_width": 3, "round": 2}

    def restore(self):
        self.calls.append("restore")
        return {"count": 1, "from_width": 3, "to_width": 4, "round": 5}

    def rollback(self):
        self.calls.append("rollback")
        if self.rollback_ok:
            return {"ok": True, "version": 1}
        return None


def _ctl(plane, **kw):
    kw.setdefault("manifest", _MANIFEST)
    kw.setdefault("cooldown_s", 3.0)
    kw.setdefault("healthy_s", 10.0)
    kw.setdefault("clock", lambda: 0.0)
    return SLOController(plane, **kw)


def _burn(ctl, t0=0.0, n=40, wait_ms=90.0):
    """Sustained breach: fills both windows over the 40 ms bound."""
    for i in range(n):
        ctl.observe("request", {"model": "m", "bucket": 8,
                                "queue_wait_ms": wait_ms},
                    t=t0 + i * 0.1)


def _recover(ctl, t0, n=20):
    for i in range(n):
        ctl.observe("request", {"model": "m", "bucket": 8,
                                "queue_wait_ms": 5.0},
                    t=t0 + i * 0.05)


# -- the decision ladder ----------------------------------------------------


def test_join_on_burn():
    plane = FakePlane(free=1)
    ctl = _ctl(plane)
    _burn(ctl)
    acts = ctl.step(t=4.0)
    assert [a["action"] for a in acts] == ["join_replica"]
    assert plane.calls == ["grow"]
    assert acts[0]["replica"] == 2 and acts[0]["width"] == 3
    assert acts[0]["fits"] is True  # the admission verdict rides along


def test_cooldown_suppresses_and_journals_once():
    plane = FakePlane(free=2)
    ctl = _ctl(plane, cooldown_s=3.0)
    _burn(ctl)
    assert ctl.step(t=4.0)  # first join
    _burn(ctl, t0=4.05)  # still breaching
    assert ctl.step(t=5.0) == []  # inside cooldown: suppressed
    assert ctl.step(t=6.0) == []  # still inside: no re-log
    assert ctl.counts["cooldowns"] == 1
    _burn(ctl, t0=6.5)
    assert ctl.step(t=7.5)  # cooldown over: second join allowed
    assert plane.calls == ["grow", "grow"]


def test_priced_refusal_journals_without_booting():
    plane = FakePlane(free=1, fits=False)
    ctl = _ctl(plane)
    _burn(ctl)
    assert ctl.step(t=4.0) == []
    assert ctl.counts["refused"] == 1
    assert "grow" not in plane.calls  # refusal is an outcome, no boot


def test_lend_when_pool_exhausted():
    plane = FakePlane(free=0, lendable=1)
    ctl = _ctl(plane)
    _burn(ctl)
    acts = ctl.step(t=4.0)
    assert [a["action"] for a in acts] == ["lend_width"]
    assert plane.calls == ["lend"]
    assert acts[0]["round"] == 2  # applied at the NEXT round boundary


def test_canary_burn_rolls_back_first():
    plane = FakePlane(free=1, rollback_ok=True)
    ctl = _ctl(plane, canary_s=60.0)
    ctl.observe("serve", {"kind": "rollout"}, t=0.0)
    # the rollout suspends the latency gate for suspend_s — burn AFTER
    # the settle window so the canary answers for it, not the swap
    _burn(ctl, t0=5.1)
    acts = ctl.step(t=9.2)
    assert [a["action"] for a in acts] == ["rollback"]
    assert plane.calls == ["rollback"]  # capacity never consulted
    # a second burn AFTER the rollback scales instead (canary closed)
    _burn(ctl, t0=13.0)
    acts = ctl.step(t=17.0)
    assert [a["action"] for a in acts] == ["join_replica"]


def test_release_is_patient_replicas_then_width():
    plane = FakePlane(free=1, lendable=1)
    ctl = _ctl(plane, cooldown_s=1.0, healthy_s=10.0)
    _burn(ctl)
    assert ctl.step(t=4.0)  # join
    plane.free = 0
    _burn(ctl, t0=5.5)
    assert ctl.step(t=6.5)  # lend (pool now exhausted)
    assert plane.calls == ["grow", "lend"]
    # recovery AFTER the breach stream ends (the burn samples ran to
    # t=9.4): the fast window fills with healthy waits and clears
    _recover(ctl, t0=10.0)
    assert ctl.step(t=11.0) == []  # cleared but not healthy long enough
    assert ctl.step(t=20.0) == []  # healthy_s counts from the CLEAR
    acts = ctl.step(t=21.5)  # 11.0 + 10.0 healthy_s elapsed
    assert [a["action"] for a in acts] == ["kill_replica"]
    acts = ctl.step(t=23.0)  # next cooldown-separated step
    assert [a["action"] for a in acts] == ["restore_width"]
    assert plane.calls == ["grow", "lend", "shrink", "restore"]


def test_summary_counts_round_trip():
    plane = FakePlane(free=1)
    ctl = _ctl(plane)
    _burn(ctl)
    ctl.step(t=4.0)
    s = ctl.summary(t=5.0)
    assert s["acts"] == 1 and s["decides"] == 1 and s["observes"] == 1
    line = schema.make_event("ctl", run_id="t", kind="summary", **s)
    assert schema.validate_line(line) == []


# -- the banked scenario replay ---------------------------------------------


def _load_harness():
    spec = importlib.util.spec_from_file_location(
        "ctl_scenarios", os.path.join(_REPO, "tools", "ctl_scenarios.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_all_four_scenarios_replay_against_banked_traces(tmp_path):
    mod = _load_harness()
    summary = mod.replay(update=False, journal_dir=str(tmp_path),
                         log=lambda m: None)
    assert summary["ok"], summary
    assert len(summary["scenarios"]) == 4
    for pair in summary["scenarios"]:
        bare, ctl = pair["bare"], pair["controlled"]
        assert bare["slo_burned"], bare["scenario"]  # A-arm must burn
        assert ctl["slo_burned"] == [], ctl["scenario"]
        assert ctl["dropped"] == 0, ctl["scenario"]
        banked = json.load(open(os.path.join(
            _REPO, "docs", "ctl_contracts",
            f"{ctl['scenario']}.json")))
        assert ctl["actions"] == banked["actions"], ctl["scenario"]


def test_flash_crowd_lends_and_returns_width(tmp_path):
    mod = _load_harness()
    rec = mod.run_scenario("flash_crowd", controlled=True,
                           journal=str(tmp_path / "fc.jsonl"))
    names = [a["action"] for a in rec["actions"]]
    assert "lend_width" in names and "restore_width" in names
    assert names.index("lend_width") < names.index("restore_width")
    assert rec["train_width"] == mod.SCENARIOS["flash_crowd"]["train_width"]
    assert rec["end_burning"] == []


def test_poison_canary_rolls_back_not_scales(tmp_path):
    mod = _load_harness()
    rec = mod.run_scenario("poison_canary", controlled=True,
                           journal=str(tmp_path / "pc.jsonl"))
    names = [a["action"] for a in rec["actions"]]
    assert names == ["rollback"]  # capacity cannot fix a poisoned model


# -- rendering surfaces -----------------------------------------------------


def _ctl_journal(tmp_path):
    path = tmp_path / "ctl.jsonl"
    events = [
        schema.make_event("run_start", run_id="r", argv=["test"]),
        schema.make_event("ctl", run_id="r", kind="observe", t=1.0,
                          gates=[], burning=[]),
        schema.make_event("ctl", run_id="r", kind="decide", t=2.0,
                          gate="warm-queue-p99", action="join_replica",
                          reason="projected-wait burn", fast=1.4,
                          slow=1.2),
        schema.make_event("ctl", run_id="r", kind="act", t=2.0,
                          action="join_replica", replica=2, width=3),
        schema.make_event("ctl", run_id="r", kind="cooldown", t=3.0,
                          gate="warm-queue-p99", cooldown_s=2.0,
                          note="suppressed"),
        schema.make_event("ctl", run_id="r", kind="summary", t=9.0,
                          ok=True, observes=1, decides=1, acts=1,
                          cooldowns=1, refused=0, burning=[]),
        schema.make_event("run_end", run_id="r", rounds=0, spans=0,
                          compiles=0),
    ]
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(path)


def test_report_renders_control_plane_section(tmp_path):
    md = render_path(_ctl_journal(tmp_path))
    assert "### control plane" in md
    assert "**ACT** `join_replica`" in md
    assert "decide `join_replica` on gate `warm-queue-p99`" in md
    assert "1 burn evaluation(s) folded" in md
    assert "cooldown" in md
    assert "1 act(s)" in md


def test_top_renders_ctl_decision_stream(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "sparknet_tpu.obs", "top",
         _ctl_journal(tmp_path), "--once"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "ctl decisions" in out.stdout
    assert "join_replica" in out.stdout


def test_slo_vacuous_pass_is_visible(tmp_path):
    # a journal with ONLY a serve summary: compiles/dropped measure,
    # the latency/feed/roofline gates pass vacuously — and must say so
    path = tmp_path / "v.jsonl"
    events = [
        schema.make_event("run_start", run_id="r", argv=["test"]),
        schema.make_event("serve", run_id="r", kind="summary",
                          model="m", requests=4, dropped=0, compiles=0,
                          wall_s=1.0),
        schema.make_event("run_end", run_id="r", rounds=0, spans=0,
                          compiles=0),
    ]
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    results = _slo.evaluate_journal(str(path))
    vac = [r for r in results if r["ok"] and not r["applicable"]]
    assert vac, "expected at least one vacuous gate"
    for r in vac:
        assert r["detail"].startswith("vacuous pass")
    fields = _slo.verdict_fields("job", results, journal=str(path))
    assert set(fields["vacuous"]) == {r["id"] for r in vac}
    # the rendered report carries the distinction
    verdict = schema.make_event("slo", **fields)
    with open(path, "a") as f:
        f.write(json.dumps(verdict) + "\n")
    md = render_path(str(path))
    assert "vacuous (no subject events)" in md
