"""Net -> DOT visualization (ref: caffe/python/caffe/draw.py +
python/draw_net.py)."""

import os

import pytest

from sparknet_tpu import models
from sparknet_tpu.proto import parse
from sparknet_tpu.utils.draw import draw_net_to_file, get_edge_label, get_layer_label, net_to_dot

REF = "/root/reference/caffe"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")


def test_layer_labels():
    conv = parse(
        'name: "c1" type: "Convolution" '
        "convolution_param { num_output: 8 kernel_size: 5 stride: 2 pad: 1 }"
    )
    lab = get_layer_label(conv, "LR")
    assert "c1" in lab and "kernel size: 5" in lab and "stride: 2" in lab
    assert get_edge_label(conv) == "8"
    pool = parse('name: "p1" type: "Pooling" pooling_param { pool: AVE kernel_size: 3 }')
    assert "AVE" in get_layer_label(pool, "TB")
    ip = parse('name: "ip" type: "InnerProduct" inner_product_param { num_output: 10 }')
    assert get_edge_label(ip) == "10"


def test_lenet_dot_structure():
    dot = net_to_dot(models.lenet(8))
    assert dot.startswith("digraph")
    assert dot.rstrip().endswith("}")
    # layer nodes with colors, blob octagons, and edges all present
    assert '"layer_conv1"' in dot and "#FF5050" in dot
    assert '"blob_data"' in dot and "octagon" in dot
    assert '"blob_data" -> "layer_conv1"' in dot
    # in-place ReLU folds onto its blob: no relu blob self-edge
    assert '"layer_relu1" -> "blob_' not in dot


def test_phase_filter_drops_test_only_layers():
    net = models.lenet(8)
    full = net_to_dot(net)
    train = net_to_dot(net, phase="TRAIN")
    assert "accuracy" in full.lower()
    assert "accuracy" not in train.lower()


def test_draw_net_to_file(tmp_path):
    p = str(tmp_path / "net.dot")
    draw_net_to_file(models.cifar10_quick(4), p, rankdir="TB")
    src = open(p).read()
    assert "rankdir=TB" in src and src.count("->") > 10


@needs_ref
def test_googlenet_from_reference_prototxt():
    from sparknet_tpu.proto import parse_file

    npz = parse_file(f"{REF}/models/bvlc_googlenet/train_val.prototxt")
    dot = net_to_dot(npz)
    # 166-layer prototxt: every non-in-place layer gets a box; in-place ones
    # (ReLU/Dropout, single top == bottom) fold into their blob's label
    layers = npz.get_all("layer")
    inplace = sum(
        1 for l in layers
        if [str(t) for t in l.get_all("top")] == [str(b) for b in l.get_all("bottom")]
        and len(l.get_all("top")) == 1
    )
    assert inplace > 0
    assert dot.count("shape=box") == len(layers) - inplace


def test_cli_draw(tmp_path, capsys):
    from sparknet_tpu.cli import main

    out = str(tmp_path / "z.dot")
    assert main(["draw", "--net", "zoo:lenet", "--out", out, "--batch", "4"]) == 0
    assert "digraph" in open(out).read()
