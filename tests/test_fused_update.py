"""One-pass fused optimizer update: equivalence, arenas, contracts.

The fused path (``Config.fused_update``: ``solvers/arena.py`` flat
arenas + ``ops/pallas_kernels.fused_update``) must be a pure
re-layout of ``solvers/updates.apply_update`` — same Caffe semantics,
different memory traffic.  Pinned here from every side:

* all six solver rules x {f32, bf16-storage} x {xla, interpret} match
  the per-blob chain at one REAL zoo step's geometry and gradients
  (exact — bitwise up to signed zeros — for SGD/Nesterov f32 on the
  xla formulation, allclose elsewhere);
* the fused Solver step / scan path reproduce the unfused trajectory;
* checkpoints round-trip through the arena index map (a fused run's
  snapshot restores into an UNFUSED solver and continues identically
  — snapshots stay blob-wise and storage-dtype-invariant);
* the kernel's static VMEM bounds fit the v5e budget and the TPU
  cross-export collapses the whole update chain to ONE custom call
  (zero chip time — jax.export lowers Mosaic host-side).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.common import Phase, get_config, set_config
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu.solvers import arena, updates
from sparknet_tpu.solvers.solver import Solver

B = 8


@pytest.fixture
def zoo_step_state():
    """One real cifar10_quick geometry + REAL gradients (one actual
    backward at init), shared by the rule-sweep tests — one compile
    total instead of one per rule."""
    rs = np.random.RandomState(0)
    net = Network(models.cifar10_quick(B), Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    specs = net.param_specs_for(variables)
    feeds = {
        "data": jnp.asarray(rs.randn(B, 3, 32, 32) * 40, jnp.float32),
        "label": jnp.asarray(rs.randint(0, 10, B), jnp.int32),
    }

    def loss_fn(params):
        _, _, loss = net.apply(
            dataclasses.replace(variables, params=params), feeds,
            rng=jax.random.PRNGKey(1))
        return loss

    grads = jax.grad(loss_fn)(variables.params)
    return variables.params, grads, specs


def _fixture_feed(rs):
    return {"data": (rs.randn(B, 3, 32, 32) * 40).astype(np.float32),
            "label": rs.randint(0, 10, B).astype(np.int32)}


@pytest.fixture
def fused_off():
    """Restore the default config after any fused-arm test."""
    yield
    set_config(fused_update=False, storage_dtype="f32",
               activation_dtype="")


# -- the six-rule equivalence sweep -----------------------------------------


@pytest.mark.smoke
@pytest.mark.parametrize("rule", list(updates.OPTIMIZERS))
def test_rule_fused_matches_updates_at_zoo_step(zoo_step_state, rule):
    """All six rules, f32 + bf16 storage, xla + interpret impls, vs
    the per-blob chain on one real zoo step's params/grads; exact for
    SGD/Nesterov in f32 (same op sequence, same rounding)."""
    params, grads, specs = zoo_step_state
    cfg = dataclasses.replace(models.cifar10_quick_solver(),
                              solver_type=rule)
    slots = updates.init_slots(rule, params)
    # second-step shape: nonzero histories exercise every rule term
    slots = jax.tree_util.tree_map(lambda h: h + 0.01, slots)
    rate, it = jnp.float32(cfg.base_lr), jnp.int32(2)
    ref_p, ref_s = updates.apply_update(cfg, params, grads, slots,
                                        specs, rate, it)
    for storage in ("f32", "bf16"):
        layout = arena.build_layout(params, specs, cfg,
                                    storage_dtype=storage)
        P = arena.pack(layout, params)
        G = arena.pack(layout, grads)
        S = arena.pack_slots(layout, slots)
        for impl in ("xla", "interpret"):
            P2, S2 = arena.arena_apply_update(cfg, layout, P, G, S,
                                              rate, it, force=impl)
            got_p = arena.unpack(layout, P2)
            got_s = arena.unpack_slots(layout, S2)
            tol = 1e-6 if storage == "f32" else 4e-2
            for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                            jax.tree_util.tree_leaves(got_p)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=tol, atol=tol)
            for a, b in zip(jax.tree_util.tree_leaves(ref_s),
                            jax.tree_util.tree_leaves(got_s)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=tol, atol=tol)
            if storage == "f32" and impl == "xla" \
                    and rule in ("SGD", "Nesterov"):
                for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                                jax.tree_util.tree_leaves(got_p)):
                    assert np.array_equal(np.asarray(a), np.asarray(b))


# -- arena geometry ----------------------------------------------------------


@pytest.mark.smoke
def test_pack_unpack_roundtrip_and_index_map(zoo_step_state):
    params, _, specs = zoo_step_state
    cfg = models.cifar10_quick_solver()
    layout = arena.build_layout(params, specs, cfg)
    # geometry: spans tile-aligned, offsets contiguous, tables sized
    from sparknet_tpu.ops.pallas_kernels import ARENA_TILE

    off = 0
    for e in layout.entries:
        assert e.offset == off and e.span % ARENA_TILE == 0
        assert e.span >= e.size
        off += e.span
    assert layout.total == off == layout.n_tiles * ARENA_TILE
    assert len(layout.tile_lr) == len(layout.tile_decay) == layout.n_tiles
    # the index map is the checkpoint contract: blob -> span, exact
    rt = arena.unpack(layout, arena.pack(layout, params))
    assert (jax.tree_util.tree_structure(rt)
            == jax.tree_util.tree_structure(params))
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(rt)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype
    rows = layout.index_map()
    assert len(rows) == len(layout.entries)
    assert all(r["size"] <= r["span"] for r in rows)


@pytest.mark.smoke
def test_pad_zones_are_update_fixpoints(zoo_step_state):
    """Pad elements (zero param, zero grad) must stay exactly zero
    under the sweep — the property that makes arena reductions equal
    their blob-wise twins."""
    params, grads, specs = zoo_step_state
    cfg = dataclasses.replace(models.cifar10_quick_solver(),
                              solver_type="Adam")
    layout = arena.build_layout(params, specs, cfg)
    P = arena.pack(layout, params)
    G = arena.pack(layout, grads)
    S = arena.pack_slots(layout, updates.init_slots("Adam", params))
    P2, S2 = arena.arena_apply_update(cfg, layout, P, G, S,
                                      jnp.float32(0.01), jnp.int32(0),
                                      force="xla")
    pad = np.ones(layout.total, bool)
    for e in layout.entries:
        pad[e.offset:e.offset + e.size] = False
    assert np.all(np.asarray(P2)[pad] == 0)
    for s in S2:
        assert np.all(np.asarray(s)[pad] == 0)


# -- the fused Solver path ---------------------------------------------------


def _run_solver(fused, storage="f32", n=2, scan=0, act=""):
    set_config(fused_update=fused, storage_dtype=storage,
               activation_dtype=act)
    try:
        rs = np.random.RandomState(3)
        feed = _fixture_feed(rs)
        solver = Solver(models.cifar10_quick_solver(),
                        models.cifar10_quick(B))
        if scan:
            fn, v, sl, key = solver.jitted_scan_steps(scan, donate=False)
            v, sl, losses = fn(
                v, sl, 0, {k: jnp.asarray(x) for k, x in feed.items()},
                key)
            return np.asarray(losses), v
        loss = solver.step(n, lambda it: feed)
        return loss, solver.variables
    finally:
        set_config(fused_update=False, storage_dtype="f32",
                   activation_dtype="")


def test_fused_solver_step_matches_unfused():
    l0, v0 = _run_solver(False)
    l1, v1 = _run_solver(True)
    assert np.allclose(l0, l1, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(v0.params),
                    jax.tree_util.tree_leaves(v1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_fused_scan_steps_match_unfused():
    """The arena-resident scan (arenas donated through the carry) is
    trajectory-identical to the unfused scan."""
    l0, _ = _run_solver(False, scan=3)
    l1, _ = _run_solver(True, scan=3)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)


def test_storage_bf16_arm_trains():
    l32, _ = _run_solver(False)
    lbf, vbf = _run_solver(True, storage="bf16")
    assert np.isfinite(lbf)
    # bf16 storage drifts but must stay loss-close at 2 steps from init
    assert abs(lbf - l32) < 0.05
    # persistent state stays blob-wise f32 (dtype-invariant snapshots)
    for p in jax.tree_util.tree_leaves(vbf.params):
        assert p.dtype == jnp.float32


def test_three_knob_composition_trains_and_restores(tmp_path):
    """All three precision/fusion knobs stacked (fused arena update x
    bf16 slot storage x bf16 activation storage): the composed solver
    trains finite and loss-close to the all-off baseline, the lowered
    step is iteration-stable (ONE compile covers it=0 and it=1 — the
    act policy binds at trace time, never per step), persistent params
    stay blob-wise f32, and the snapshot restores into a plain
    all-knobs-off solver on the same trajectory."""
    l32, _ = _run_solver(False)
    rs = np.random.RandomState(3)
    feed = _fixture_feed(rs)
    set_config(fused_update=True, storage_dtype="bf16",
               activation_dtype="blocks")
    try:
        solver = Solver(models.cifar10_quick_solver(),
                        models.cifar10_quick(B))
        fn, v, sl, key = solver.jitted_train_step(donate=False)
        feeds = {k: jnp.asarray(x) for k, x in feed.items()}
        v, sl, loss0 = fn(v, sl, 0, feeds, key)
        v, sl, loss1 = fn(v, sl, 1, feeds, key)
        assert fn._cache_size() == 1  # no per-step retrace
        assert np.isfinite(loss0) and np.isfinite(loss1)

        loss = solver.step(2, lambda it: feed)
        assert np.isfinite(loss)
        assert abs(loss - l32) < 0.05
        for p in jax.tree_util.tree_leaves(solver.variables.params):
            assert p.dtype == jnp.float32
        snap = solver.save(str(tmp_path / "three_knob_snap"))
    finally:
        set_config(fused_update=False, storage_dtype="f32",
                   activation_dtype="")
    plain = Solver(models.cifar10_quick_solver(),
                   models.cifar10_quick(B))
    plain.restore(snap)
    assert plain.iter == 2
    assert np.isfinite(plain.step(1, lambda it: feed))


def test_checkpoint_roundtrip_through_index_map(tmp_path):
    """A fused run's snapshot (written blob-wise through the arena
    index map) restores into an UNFUSED solver and continues on the
    same trajectory — and vice versa."""
    rs = np.random.RandomState(5)
    feed = _fixture_feed(rs)
    set_config(fused_update=True)
    try:
        fused_solver = Solver(models.cifar10_quick_solver(),
                              models.cifar10_quick(B))
        fused_solver.step(2, lambda it: feed)
        snap = fused_solver.save(str(tmp_path / "fused_snap"))
    finally:
        set_config(fused_update=False)
    plain = Solver(models.cifar10_quick_solver(),
                   models.cifar10_quick(B))
    plain.restore(snap)
    assert plain.iter == 2
    l_plain = plain.step(1, lambda it: feed)
    set_config(fused_update=True)
    try:
        l_fused = fused_solver.step(1, lambda it: feed)
    finally:
        set_config(fused_update=False)
    assert abs(l_plain - l_fused) < 1e-4


def test_dp_fused_trainer_round():
    """tau=1 GSPMD DP with the fused step: same loss as the unfused
    round (the trainer path never sees the arena — blob-boundary
    contract)."""
    from jax.sharding import Mesh

    from sparknet_tpu.parallel.trainer import ParallelTrainer

    devices = jax.devices()[:8]
    mesh = Mesh(np.array(devices), ("data",))
    rs = np.random.RandomState(7)
    Bg = 16
    feed = {"data": (rs.randn(Bg, 3, 32, 32) * 40).astype(np.float32),
            "label": rs.randint(0, 10, Bg).astype(np.int32)}
    losses = {}
    for fused in (False, True):
        set_config(fused_update=fused)
        try:
            solver = Solver(models.cifar10_quick_solver(),
                            models.cifar10_quick(Bg))
            trainer = ParallelTrainer(solver, mesh=mesh, tau=1)
            losses[fused] = trainer.train_round(lambda it: feed)
        finally:
            set_config(fused_update=False)
    assert np.allclose(losses[False], losses[True], rtol=1e-5, atol=1e-6)


# -- static contracts --------------------------------------------------------


@pytest.mark.smoke
def test_vmem_bounds_fit_and_audited():
    from sparknet_tpu.analysis.mem_model import V5E_VMEM_BYTES
    from sparknet_tpu.ops.pallas_kernels import (
        fused_update_vmem_bytes,
        vmem_audit_points,
    )

    for n_slots in (1, 2):
        for itemsize in (2, 4):
            assert fused_update_vmem_bytes(n_slots, itemsize) \
                < V5E_VMEM_BYTES
    kinds = [p["kernel"] for p in vmem_audit_points()]
    assert kinds.count("fused_update") == 3


@pytest.mark.smoke
def test_fused_update_hbm_model_is_single_pass():
    from sparknet_tpu.ops.pallas_kernels import fused_update_hbm_bytes

    ab = 1 << 20
    # 1 read + 1 write per param byte, per slot byte, + 1 grad read
    assert fused_update_hbm_bytes(ab, 1) == 5 * ab
    assert fused_update_hbm_bytes(ab, 2) == 7 * ab


@pytest.mark.smoke
def test_tpu_export_single_custom_call():
    """The whole normalize/regularize/clip/rule chain lowers (TPU
    cross-export, zero chip time) as EXACTLY one custom call — the
    graph-contract pin the solo_fused/dp_fused manifests bank."""
    from sparknet_tpu.ops.pallas_kernels import (
        fused_update_tpu_custom_calls,
    )

    assert fused_update_tpu_custom_calls(rule="SGD", n_slots=1) == 1
    assert fused_update_tpu_custom_calls(rule="Adam", n_slots=2) == 1


@pytest.mark.smoke
def test_config_knobs_validate(fused_off):
    assert get_config().fused_update is False  # default path untouched
    assert get_config().storage_dtype == "f32"
    set_config(storage_dtype="bfloat16")  # alias normalizes
    assert get_config().storage_dtype == "bf16"
    with pytest.raises(ValueError):
        set_config(storage_dtype="int8")
    # the third knob (numcheck's activation-storage policy) validates
    # through the same gate and defaults off
    assert get_config().activation_dtype == ""
    set_config(activation_dtype="bf16")  # dtype alias -> banked default
    assert get_config().activation_dtype == "blocks"
    set_config(activation_dtype="off")
    assert get_config().activation_dtype == ""
    with pytest.raises(ValueError):
        set_config(activation_dtype="f16")


@pytest.mark.smoke
def test_fused_update_rejects_bad_shapes(zoo_step_state):
    from sparknet_tpu.ops.pallas_kernels import (
        UpdateStatics,
        fused_update,
    )

    w = jnp.zeros((100,), jnp.float32)  # not a tile multiple
    with pytest.raises(ValueError):
        fused_update("SGD", UpdateStatics(), w, w, [w],
                     jnp.ones((1,)), jnp.zeros((1,)), jnp.ones((3,)))
