"""The full graphcheck mode sweep vs the banked golden manifests.

Slow-marked twin of tests/test_graphcheck.py's dp+tau smoke gate: every
registered parallel mode — including the compile-heavy mobilenet_dp —
is lowered on the virtual 8-device mesh and diffed against
docs/graph_contracts/.  CLI equivalent: `python -m sparknet_tpu.analysis
graph` (regenerate with `--update`).
"""

import pytest

from sparknet_tpu.analysis.graphcheck import run_graphcheck
from sparknet_tpu.parallel.modes import list_modes

pytestmark = pytest.mark.slow


def test_graphcheck_full_sweep_is_clean():
    findings, manifests = run_graphcheck()
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "\n".join(
        f"{f.path}: [{f.rule}] {f.message}" for f in bad)
    assert set(manifests) == set(list_modes())
    assert len(manifests) >= 6 and "mobilenet_dp" in manifests
