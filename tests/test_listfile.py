"""Host readers for ImageData / WindowData / HDF5Data prototxt sources.

Tiny on-disk fixtures exercise the reference semantics: listfile parse +
resize + epoch shuffle (ref: image_data_layer.cpp:1-167), R-CNN fg/bg
window sampling with context-pad warping (ref: window_data_layer.cpp:
1-470), and the .h5-list row stream (ref: hdf5_data_layer.cpp) — ending
with a reference-shaped ImageData prototxt training end to end.
"""

import os

import numpy as np
import pytest

pytest.importorskip("PIL")

from sparknet_tpu.common import Phase
from sparknet_tpu.compiler import Network
from sparknet_tpu.data.listfile import (
    Hdf5DataSource,
    ImageDataSource,
    WindowDataSource,
    source_from_net,
)
from sparknet_tpu.proto import parse


def _write_png(path, h, w, value):
    from PIL import Image

    arr = np.full((h, w, 3), value, np.uint8)
    Image.fromarray(arr).save(path)


@pytest.fixture
def image_list(tmp_path):
    """4 solid-color images at mixed sizes + a '<path> <label>' listfile."""
    for i, (h, w) in enumerate([(10, 12), (8, 8), (16, 10), (12, 12)]):
        _write_png(tmp_path / f"im{i}.png", h, w, 40 * i + 20)
    listfile = tmp_path / "list.txt"
    listfile.write_text(
        "".join(f"im{i}.png {i % 3}\n" for i in range(4))
    )
    return tmp_path, listfile


def _image_layer(listfile, root, extra="", transform=""):
    return parse(
        'layer { name: "d" type: "ImageData" top: "data" top: "label" '
        f'image_data_param {{ source: "{listfile}" root_folder: "{root}/" '
        f"batch_size: 3 new_height: 9 new_width: 9 {extra} }} {transform} }}"
    ).get_all("layer")[0]


@pytest.mark.smoke
def test_image_data_source_shapes_and_loop(image_list):
    root, listfile = image_list
    src = ImageDataSource(_image_layer(listfile, root), train=True)
    for it in range(3):  # 3 batches of 3 from 4 images: wraps mid-batch
        b = src(it)
        assert b["data"].shape == (3, 3, 9, 9)
        assert b["data"].dtype == np.float32
        assert b["label"].dtype == np.int32
    # unshuffled wrap order: labels cycle the listfile
    src2 = ImageDataSource(_image_layer(listfile, root), train=True)
    seen = np.concatenate([src2(i)["label"] for i in range(4)])
    assert list(seen) == [0, 1, 2, 0, 0, 1, 2, 0, 0, 1, 2, 0]


def test_image_data_transform_and_shuffle(image_list):
    root, listfile = image_list
    lp = _image_layer(
        listfile, root, extra="shuffle: true",
        transform="transform_param { crop_size: 6 mean_value: 20 scale: 0.5 }",
    )
    src = ImageDataSource(lp, train=True, seed=7)
    b = src(0)
    assert b["data"].shape == (3, 3, 6, 6)
    # solid-color images make the transform chain exact: values are
    # 40i+20, so (v - 20) * 0.5 lands in {0, 20, 40, 60}
    flat = b["data"].reshape(3, -1)
    assert all(len(np.unique(r)) == 1 for r in flat)
    assert set(np.unique(b["data"])) <= {0.0, 20.0, 40.0, 60.0}
    # same seed -> identical shuffled stream
    src_same = ImageDataSource(lp, train=True, seed=7)
    np.testing.assert_array_equal(b["label"], src_same(0)["label"])
    np.testing.assert_array_equal(src(1)["label"], src_same(1)["label"])


def test_image_data_pooled_decode_matches_serial(image_list, monkeypatch):
    root, listfile = image_list
    monkeypatch.setenv("SPARKNET_DECODE_WORKERS", "1")
    serial = ImageDataSource(_image_layer(listfile, root), train=False)
    monkeypatch.setenv("SPARKNET_DECODE_WORKERS", "4")
    pooled = ImageDataSource(_image_layer(listfile, root), train=False)
    for it in range(3):
        a, b = serial(it), pooled(it)
        np.testing.assert_array_equal(a["data"], b["data"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_image_data_rejects_half_resize(image_list):
    root, listfile = image_list
    lp = parse(
        'layer { name: "d" type: "ImageData" top: "data" top: "label" '
        f'image_data_param {{ source: "{listfile}" root_folder: "{root}/" '
        "batch_size: 2 new_height: 9 } }"
    ).get_all("layer")[0]
    with pytest.raises(ValueError, match="new_height and new_width"):
        ImageDataSource(lp, train=True)


# ---------------------------------------------------------------------------
@pytest.fixture
def window_file(tmp_path):
    """2 images, each with 1 fg (overlap .8) + 2 bg (overlap .1) windows."""
    for i in range(2):
        _write_png(tmp_path / f"w{i}.png", 24, 24, 100 + 50 * i)
    wf = tmp_path / "windows.txt"
    lines = []
    for i in range(2):
        lines += [f"# {i}", str(tmp_path / f"w{i}.png"), "3 24 24", "3",
                  f"{i + 1} 0.8 4 4 15 15",
                  "0 0.1 0 0 7 7",
                  "0 0.1 10 10 23 23"]
    wf.write_text("\n".join(lines) + "\n")
    return wf


def _window_layer(wf, extra=""):
    return parse(
        'layer { name: "w" type: "WindowData" top: "data" top: "label" '
        f'window_data_param {{ source: "{wf}" batch_size: 8 '
        f"fg_threshold: 0.5 bg_threshold: 0.5 fg_fraction: 0.25 {extra} }} "
        "transform_param { crop_size: 16 mean_value: 50 } }"
    ).get_all("layer")[0]


def test_window_data_fg_bg_sampling(window_file):
    src = WindowDataSource(_window_layer(window_file), train=True, seed=0)
    b = src(0)
    assert b["data"].shape == (8, 3, 16, 16)
    # batch*fg_fraction = 2 fg samples, placed after the 6 bg (ref order:
    # is_fg 0 then 1); bg labels forced to 0, fg labels > 0
    assert list(b["label"][:6]) == [0] * 6
    assert all(l in (1, 2) for l in b["label"][6:])
    # solid-color source: warped fg pixels = value - mean, exactly
    fg_img = int(b["label"][6]) - 1
    assert np.allclose(np.unique(b["data"][6]), 100 + 50 * fg_img - 50)


def test_window_data_context_pad_square(window_file):
    src = WindowDataSource(
        _window_layer(window_file, extra='context_pad: 2 crop_mode: "square"'),
        train=True, seed=1,
    )
    b = src(0)
    assert b["data"].shape == (8, 3, 16, 16)
    assert np.isfinite(b["data"]).all()
    # context-padded windows near the border get zero padding rows/cols:
    # every sample still carries real (nonzero) content
    assert (np.abs(b["data"]).reshape(8, -1).max(1) > 0).all()


def test_window_data_needs_fg_and_bg(tmp_path):
    _write_png(tmp_path / "only.png", 8, 8, 10)
    wf = tmp_path / "w.txt"
    wf.write_text(f"# 0\n{tmp_path / 'only.png'}\n3 8 8\n1\n1 0.9 0 0 7 7\n")
    with pytest.raises(ValueError, match="fg and.*bg|at least one"):
        WindowDataSource(_window_layer(wf), train=True)


# ---------------------------------------------------------------------------
def test_hdf5_data_source(tmp_path):
    h5py = pytest.importorskip("h5py")
    from sparknet_tpu.data.hdf5 import write_hdf5_file

    for i in range(2):
        write_hdf5_file(
            str(tmp_path / f"p{i}.h5"),
            {"data": np.full((5, 4), i, np.float32),
             "label": np.arange(5, dtype=np.float32) + 10 * i},
        )
    listfile = tmp_path / "h5list.txt"
    listfile.write_text(f"{tmp_path}/p0.h5\n{tmp_path}/p1.h5\n")
    lp = parse(
        'layer { name: "h" type: "HDF5Data" top: "data" top: "label" '
        f'hdf5_data_param {{ source: "{listfile}" batch_size: 4 }} }}'
    ).get_all("layer")[0]
    src = Hdf5DataSource(lp, train=True)
    b0, b1, b2 = src(0), src(1), src(2)
    assert b0["data"].shape == (4, 4)
    assert b0["label"].dtype == np.int32
    # rows stream in file order and wrap at 10
    assert list(b0["label"]) == [0, 1, 2, 3]
    assert list(b1["label"]) == [4, 10, 11, 12]
    assert list(b2["label"]) == [13, 14, 0, 1]


# ---------------------------------------------------------------------------
def test_image_data_prototxt_trains_end_to_end(image_list):
    """A reference-shaped ImageData prototxt (conv net + SoftmaxWithLoss)
    trains through Solver with feeds produced by source_from_net — the
    finetune_flickr_style flow (ref: models/finetune_flickr_style/
    train_val.prototxt sources ImageData) at fixture scale."""
    import jax

    from sparknet_tpu.solvers.solver import Solver, SolverConfig

    root, listfile = image_list
    npz = parse(
        'name: "tiny_imagedata" '
        'layer { name: "d" type: "ImageData" top: "data" top: "label" '
        f'image_data_param {{ source: "{listfile}" root_folder: "{root}/" '
        "batch_size: 3 new_height: 9 new_width: 9 shuffle: true } "
        "transform_param { crop_size: 8 mirror: true scale: 0.0078125 } } "
        'layer { name: "conv" type: "Convolution" bottom: "data" top: "conv" '
        "convolution_param { num_output: 4 kernel_size: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "ip" type: "InnerProduct" bottom: "conv" top: "ip" '
        "inner_product_param { num_output: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }'
    )
    solver = Solver(SolverConfig(base_lr=0.01, max_iter=10), npz)
    src = source_from_net(solver.train_net)
    step, variables, slots, key = solver.jitted_train_step()
    losses = []
    for i in range(4):
        variables, slots, loss = step(variables, slots, i, src(i), key)
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(l) for l in losses)


def test_cli_train_imagedata_proto(image_list, tmp_path, monkeypatch, capsys):
    """`tpunet train --data proto` end to end through main() on an
    ImageData prototxt (the finetune_flickr_style CLI flow)."""
    from sparknet_tpu.cli import main

    root, listfile = image_list
    net = tmp_path / "net.prototxt"
    net.write_text(
        'name: "t" '
        'layer { name: "d" type: "ImageData" top: "data" top: "label" '
        f'image_data_param {{ source: "{listfile}" root_folder: "{root}/" '
        "batch_size: 3 new_height: 9 new_width: 9 } "
        "transform_param { crop_size: 8 scale: 0.01 } } "
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip" '
        "inner_product_param { num_output: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }'
    )
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f'net: "{net}"\nbase_lr: 0.01\nlr_policy: "fixed"\n'
                      "max_iter: 3\ndisplay: 1\n")
    monkeypatch.chdir(tmp_path)
    assert main(["train", "--solver", str(solver), "--data", "proto",
                 "--iterations", "3"]) == 0
    out = capsys.readouterr().out
    assert "loss" in out


def test_cli_train_windowdata_proto(window_file, tmp_path, monkeypatch, capsys):
    """`tpunet train --data proto` on a WindowData prototxt (the
    pascal-detection CLI flow): fg/bg sampling feeds a tiny window head."""
    from sparknet_tpu.cli import main

    net = tmp_path / "net.prototxt"
    net.write_text(
        'name: "w" '
        'layer { name: "d" type: "WindowData" top: "data" top: "label" '
        f'window_data_param {{ source: "{window_file}" batch_size: 4 '
        "fg_threshold: 0.5 bg_threshold: 0.5 fg_fraction: 0.25 } "
        "transform_param { crop_size: 12 mean_value: 50 } } "
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip" '
        "inner_product_param { num_output: 3 "
        'weight_filler { type: "xavier" } } } '
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }'
    )
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f'net: "{net}"\nbase_lr: 0.001\nlr_policy: "fixed"\n'
                      "max_iter: 2\ndisplay: 1\n")
    monkeypatch.chdir(tmp_path)
    assert main(["train", "--solver", str(solver), "--data", "proto",
                 "--iterations", "2"]) == 0
    assert "loss" in capsys.readouterr().out


def test_source_from_net_no_listfile_layer():
    npz = parse(
        'name: "plain" input: "data" input_dim: 1 input_dim: 3 '
        "input_dim: 4 input_dim: 4"
    )
    net = Network(npz, Phase.TRAIN)
    with pytest.raises(LookupError, match="no Data/ImageData/WindowData/HDF5Data"):
        source_from_net(net)
