"""TPU pod provisioning CLI (the spark-ec2 role; ref: ec2/spark_ec2.py
launch/destroy/login verbs).  Dry-run only — this environment has no
gcloud and no network; the command builder IS the logic."""

import pytest

from sparknet_tpu.cli import main
from sparknet_tpu.pods import (
    PodConfig,
    create_command,
    delete_command,
    run_command,
    scp_command,
    ssh_command,
)

CFG = PodConfig(name="sparknet-pod", zone="us-west4-a",
                accelerator_type="v5litepod-32", project="proj")


def test_create_command_shape():
    cmd = create_command(CFG)
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "sparknet-pod" in cmd
    assert ["--zone", "us-west4-a"] == cmd[cmd.index("--zone"):][:2]
    assert ["--accelerator-type", "v5litepod-32"] == \
        cmd[cmd.index("--accelerator-type"):][:2]
    assert "--spot" not in cmd
    assert "--spot" in create_command(
        PodConfig(name="p", zone="z", spot=True))


def test_delete_is_quiet_and_scoped():
    cmd = delete_command(CFG)
    assert "--quiet" in cmd and "--project" in cmd


def test_run_spans_all_workers():
    cmd = run_command(CFG, "python train.py")
    assert ["--worker", "all"] == cmd[cmd.index("--worker"):][:2]
    assert ["--command", "python train.py"] == \
        cmd[cmd.index("--command"):][:2]


def test_ssh_single_worker_no_command():
    cmd = ssh_command(CFG, worker="3")
    assert ["--worker", "3"] == cmd[cmd.index("--worker"):][:2]
    assert "--command" not in cmd


def test_scp_recurse_to_pod_path():
    cmd = scp_command(CFG, "/repo", "/home/u/repo")
    assert "--recurse" in cmd
    assert "sparknet-pod:/home/u/repo" in cmd


def test_cli_dry_run_prints_command(capsys):
    rc = main(["pods", "create", "--name", "p1", "--zone", "us-west4-a",
               "--type", "v5litepod-8", "--dry-run"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("gcloud compute tpus tpu-vm create p1")
    assert "--accelerator-type v5litepod-8" in out


def test_cli_validation():
    with pytest.raises(SystemExit, match="--name"):
        main(["pods", "create", "--zone", "z", "--dry-run"])
    with pytest.raises(SystemExit, match="--zone"):
        main(["pods", "create", "--name", "p", "--dry-run"])
    with pytest.raises(SystemExit, match="--command"):
        main(["pods", "run", "--name", "p", "--zone", "z", "--dry-run"])
    with pytest.raises(SystemExit, match="--src"):
        main(["pods", "scp", "--name", "p", "--zone", "z", "--dry-run"])


def test_cli_run_dry_run(capsys):
    rc = main(["pods", "run", "--name", "p", "--zone", "z", "--command",
               "tpunet train --solver zoo:caffenet --distributed",
               "--dry-run"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "--worker all" in out and "tpunet train" in out
