"""Prototxt parser tests (parity target: the C-side parse service,
ref: libccaffe/ccaffe.cpp:275-296 + LayerSpec.scala:10-51 — every zoo
prototxt must load without error)."""

import glob
import os

import pytest

from sparknet_tpu.proto import parse, parse_file, serialize

REF = "/root/reference/caffe"

SAMPLE = """
name: "TinyNet"  # trailing comment
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  param { lr_mult: 1 decay_mult: 1 }
  convolution_param {
    num_output: 96
    kernel_size: 11
    stride: 4
    weight_filler { type: "gaussian" std: 0.01 }
  }
  include { phase: TRAIN }
}
base_lr: 0.01
gamma: 1e-4
mirror: true
stepvalue: [10, 20, 30]
"""


def test_basic_fields():
    msg = parse(SAMPLE)
    assert msg.get_str("name") == "TinyNet"
    assert msg.get_float("base_lr") == 0.01
    assert msg.get_float("gamma") == 1e-4
    assert msg.get_bool("mirror") is True
    assert msg.get_all("stepvalue") == [10, 20, 30]


def test_nested_and_enums():
    msg = parse(SAMPLE)
    (layer,) = msg.get_all("layer")
    assert layer.get_str("type") == "Convolution"
    conv = layer.get_msg("convolution_param")
    assert conv.get_int("num_output") == 96
    assert conv.get_msg("weight_filler").get_float("std") == 0.01
    assert layer.get_msg("include").get_str("phase") == "TRAIN"


def test_repeated_params():
    msg = parse("layer { param { lr_mult: 1 } param { lr_mult: 2 } }")
    (layer,) = msg.get_all("layer")
    assert [p.get_float("lr_mult") for p in layer.get_all("param")] == [1.0, 2.0]


def test_roundtrip():
    msg = parse(SAMPLE)
    again = parse(serialize(msg))
    assert serialize(again) == serialize(msg)


def test_string_escapes_and_concat():
    msg = parse('source: "a" "b"  note: "line\\nbreak"')
    assert msg.get_str("source") == "ab"
    assert msg.get_str("note") == "line\nbreak"


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference tree not mounted")
def test_parses_entire_reference_zoo():
    """Every prototxt in the reference model zoo + examples must parse."""
    paths = glob.glob(f"{REF}/models/**/*.prototxt", recursive=True)
    paths += glob.glob(f"{REF}/examples/**/*.prototxt", recursive=True)
    assert len(paths) > 20
    for p in paths:
        msg = parse_file(p)
        assert msg.fields, p
        # and roundtrip parses again
        parse(serialize(msg))
