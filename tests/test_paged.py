"""Paged KV-cache decode gates (sparknet_tpu/serve/paged.py, ISSUE 19).

Five contract families:

1. **Block pool** — stdlib-only allocator tests: all-or-nothing alloc,
   loud double-free/null-block/foreign-id refusal, the exact zero-leak
   ledger, and the capacity byte model (paged admits >= 2x the
   rectangle's concurrent sequences at equal HBM for mixed lengths).
2. **Exactness** — a request decoded on the paged engine interleaved
   with arbitrary neighbours produces the SAME greedy continuation as
   decoded alone AND as the cacheless rectangle ``ContinuousDecoder``,
   with ZERO decode-path compiles (CPU compiles pin single-thread
   Eigen via the engine's ``_exactness_compiler_options``).
3. **Occupancy-churn fuzz** — seeded random admit/retire schedules
   (variable lengths, pool backpressure included) must never leak or
   double-free a block, must keep every continuation bitwise-equal to
   its decoded-alone reference at every churn point, and must hold the
   recompile sentinel at zero throughout.
4. **Admission & routing** — the decode plane prices params + pool
   BEFORE any compile (``AdmissionRefused`` on a predicted miss), the
   ``TokenRouter`` drains with a zero-drop ledger, and submit refuses
   over-window requests (the paged cache never slides).
5. **Contract twins & telemetry** — the occupancy twins lower to
   byte-identical StableHLO (shape stability IS the zero-recompile
   claim), the ``token`` obs events are schema-valid and rendered, the
   TTFT SLO gate burns/passes/goes-vacuous correctly, and
   ``generate_chars`` rides the cache bitwise.

ref: apps/FeaturizerApp.scala:1 (the reference's batch scoring — RDD
granularity; paged slot-level decode is new TPU-first surface).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from sparknet_tpu.serve.paged import (
    BlockPool, PagedDecoder, PoolExhausted, TokenRouter, capacity_ratio,
    pool_bytes)

# small-but-real decoder geometry shared by every jax-touching test:
# 2 attention blocks so the per-layer pool indexing is exercised
GEO = dict(slots=4, seq_len=16, vocab=32, embed_dim=32, heads=4,
           ffn_dim=32, blocks=2, seed=0, block_tokens=4)


@pytest.fixture(scope="module")
def decoder_pair():
    """One interleaved decoder + one decoded-alone reference sharing
    variables (same seed), compiled once for the whole module."""
    d = PagedDecoder(**GEO)
    ref = PagedDecoder(**GEO, variables=d.variables)
    return d, ref


def _alone(ref: PagedDecoder, cache: dict, prompt, max_new):
    """Decoded-alone continuation, memoized (the bitwise reference)."""
    key = (tuple(prompt), max_new)
    if key not in cache:
        t = ref.submit(prompt, max_new)
        ref.run()
        cache[key] = t.result
    return cache[key]


# -- 1. block pool ----------------------------------------------------------


@pytest.mark.smoke
def test_block_pool_ledger_and_refusals():
    pool = BlockPool(num_blocks=8, block_tokens=4)
    assert pool.available() == 7  # block 0 is the null block
    a = pool.alloc(3)
    b = pool.alloc(2)
    assert 0 not in a + b and len(set(a + b)) == 5
    pool.free(a)
    with pytest.raises(ValueError, match="double-free|not allocated"):
        pool.free(a)  # double-free is loud
    with pytest.raises(ValueError, match="null block"):
        pool.free([0])
    with pytest.raises(ValueError, match="not allocated"):
        pool.free([6 if 6 not in b else 5])  # foreign id
    pool.free(b)
    led = pool.ledger()
    assert led == {"allocated": 5, "freed": 5, "in_use": 0, "leaked": 0}


@pytest.mark.smoke
def test_block_pool_alloc_is_all_or_nothing():
    pool = BlockPool(num_blocks=4, block_tokens=4)
    pool.alloc(2)
    with pytest.raises(PoolExhausted):
        pool.alloc(2)  # only 1 free: must not hand out a partial set
    assert pool.available() == 1  # nothing was consumed by the refusal
    with pytest.raises(ValueError):
        pool.alloc(0)
    with pytest.raises(ValueError):
        BlockPool(num_blocks=1, block_tokens=4)  # null block only


@pytest.mark.smoke
def test_capacity_model_doubles_sequences_at_equal_hbm():
    """The acceptance byte model: rectangle reserves seq_len lines per
    sequence no matter the request; paged reserves whole blocks of the
    request's own length.  At the serving shape (long max context,
    mixed short requests) the ratio clears 2x."""
    seq_len, T = 2048, 16
    totals = [32, 64, 96, 128, 256, 512, 777]  # mixed real lengths
    ratio = capacity_ratio(seq_len, T, totals)
    assert ratio >= 2.0
    # degenerate: every request fills the window -> no advantage
    assert capacity_ratio(256, 16, [256, 256]) == pytest.approx(1.0)
    # pool_bytes is the exact arena price (K and V, per layer)
    assert pool_bytes(2, 8, 4, 4, 8, itemsize=4) == 2 * 2 * 8 * 4 * 4 * 8 * 4


# -- 2. exactness -----------------------------------------------------------


def test_paged_interleaved_matches_alone_and_rectangle(decoder_pair):
    from sparknet_tpu.serve.continuous import ContinuousDecoder

    d, ref = decoder_pair
    cache: dict = {}
    rs = np.random.RandomState(3)
    reqs = []
    for _ in range(9):
        n_p = int(rs.randint(1, 10))
        reqs.append((list(rs.randint(0, GEO["vocab"], n_p)),
                     int(rs.randint(1, GEO["seq_len"] - n_p + 1))))
    tickets = [d.submit(p, m) for p, m in reqs]
    d.run()
    rect = ContinuousDecoder(slots=4, seq_len=GEO["seq_len"],
                             vocab=GEO["vocab"],
                             embed_dim=GEO["embed_dim"],
                             heads=GEO["heads"], ffn_dim=GEO["ffn_dim"],
                             blocks=GEO["blocks"],
                             variables=d.variables)
    rect_tickets = [rect.submit(p, m) for p, m in reqs]
    rect.run()
    for t, rt, (p, m) in zip(tickets, rect_tickets, reqs):
        assert t.result == _alone(ref, cache, p, m)  # interleaved == alone
        assert t.result == rt.result  # paged == rectangle
    assert d.decode_path_compiles == 0
    assert rect.decode_path_compiles == 0
    assert d.pool.ledger()["leaked"] == 0


@pytest.mark.smoke
def test_submit_refuses_over_window_and_bad_ids(decoder_pair):
    d, _ = decoder_pair
    with pytest.raises(ValueError, match="never slides"):
        d.submit([1] * 10, GEO["seq_len"])  # prompt + max_new > window
    with pytest.raises(ValueError, match="non-empty"):
        d.submit([], 4)
    with pytest.raises(ValueError, match="outside"):
        d.submit([GEO["vocab"]], 4)
    with pytest.raises(ValueError, match="positive"):
        d.submit([1], 0)


# -- 3. occupancy-churn fuzz ------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_occupancy_churn_fuzz_never_leaks_and_stays_bitwise(
        decoder_pair, seed):
    """Seeded random admit/retire schedules: a tight pool forces
    backpressure (PoolExhausted -> FIFO wait), retirements interleave
    with admissions at every occupancy, and at EVERY churn point the
    pool invariants hold.  Every continuation must equal its
    decoded-alone reference and the sentinel must stay at zero."""
    _, ref = decoder_pair
    # tight pool: 10 usable blocks < slots * blocks_per_slot (16),
    # so admission regularly waits on blocks, not just on slots
    d = PagedDecoder(**{**GEO, "num_blocks": 11},
                     variables=ref.variables)
    rs = np.random.RandomState(seed)
    cache: dict = {}
    live: list = []
    done = 0
    while done < 14:
        if len(live) < 14 and rs.rand() < 0.6:
            n_p = int(rs.randint(1, 9))
            m = int(rs.randint(1, GEO["seq_len"] - n_p + 1))
            p = list(rs.randint(0, GEO["vocab"], n_p))
            live.append((d.submit(p, m), p, m))
        d.step()
        # churn-point invariants: the ledger is exact and the free
        # list + owned set tile the usable pool with no double-count
        led = d.pool.ledger()
        assert led["leaked"] == 0
        assert d.pool.available() + d.pool.in_use() == d.pool.num_blocks - 1
        for t, p, m in [x for x in live if x[0].done()]:
            assert t.result == _alone(ref, cache, p, m)
            live.remove((t, p, m))
            done += 1
    d.run()  # drain stragglers
    for t, p, m in live:
        assert t.result == _alone(ref, cache, p, m)
    assert d.decode_path_compiles == 0
    led = d.pool.ledger()
    assert led["in_use"] == 0 and led["leaked"] == 0
    assert led["allocated"] == led["freed"] > 0


# -- 4. admission & routing -------------------------------------------------


@pytest.mark.smoke
def test_admission_refuses_before_any_compile():
    from sparknet_tpu.serve.engine import AdmissionRefused

    with pytest.raises(AdmissionRefused) as exc:
        PagedDecoder(**GEO, hbm_bytes=1024)  # nothing fits 1 KiB
    v = exc.value.verdict
    assert v["fits"] is False and v["priced"] is True
    assert v["predicted_bytes"] > v["budget_bytes"]


def test_token_router_zero_drop_ledger(decoder_pair):
    _, ref = decoder_pair
    r = TokenRouter(replicas=2, **{**GEO, "variables": ref.variables})
    rs = np.random.RandomState(5)
    cache: dict = {}
    reqs = []
    for _ in range(10):
        n_p = int(rs.randint(1, 8))
        reqs.append((list(rs.randint(0, GEO["vocab"], n_p)),
                     int(rs.randint(1, GEO["seq_len"] - n_p + 1))))
    tickets = [r.submit(p, m) for p, m in reqs]
    r.run()
    led = r.ledger()
    assert led["submitted"] == 10 and led["resolved"] == 10
    assert led["dropped"] == 0
    assert led["pool"]["leaked"] == 0 and led["pool"]["in_use"] == 0
    # routing never changes results: replicas share bitwise weights
    for t, (p, m) in zip(tickets, reqs):
        assert t.result == _alone(ref, cache, p, m)


# -- 5. contract twins & telemetry ------------------------------------------


def test_decode_twins_lower_byte_identical_across_occupancy():
    """Shape stability, machine-checked: occupancy changes DATA only,
    so every decode_paged_o* twin must lower to the SAME StableHLO —
    which is why the engine can never recompile under admission
    churn."""
    import hashlib

    from sparknet_tpu.parallel.modes import build_target

    shas = {}
    for o in (1, 4):
        t = build_target(f"decode_paged_o{o}")
        txt = t.fn.lower(*t.args).as_text()
        shas[o] = hashlib.sha256(txt.encode()).hexdigest()
    assert shas[1] == shas[4]


def test_token_events_schema_valid_and_rendered(tmp_path, decoder_pair):
    from sparknet_tpu.obs import report as obs_report
    from sparknet_tpu.obs import schema
    from sparknet_tpu.obs.recorder import Recorder

    _, ref = decoder_pair
    path = tmp_path / "token.jsonl"
    rec = Recorder(str(path), run_id="paged_test")
    d = PagedDecoder(**GEO, variables=ref.variables, recorder=rec)
    for p, m in [([1, 2, 3], 4), ([4], 2), ([5, 6], 3)]:
        d.submit(p, m)
    d.run()
    rec.close()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    toks = [ev for ev in lines if ev.get("event") == "token"]
    kinds = {ev["kind"] for ev in toks}
    assert {"prefill", "request", "summary"} <= kinds
    for ev in toks:
        assert schema.validate_line(ev) == []
    summary = [ev for ev in toks if ev["kind"] == "summary"][-1]
    assert summary["leaked"] == 0 and summary["dropped"] == 0
    assert summary["compiles"] == 0
    md = obs_report.render_path(str(path))
    assert "token serving (paged decode)" in md
    assert "ledger exact, zero compiles" in md


@pytest.mark.smoke
def test_slo_ttft_gate_burns_passes_and_goes_vacuous():
    from sparknet_tpu.obs import slo

    manifest = slo.load_manifest()
    ids = [s["id"] for s in manifest["slos"]]
    assert "ttft-p99" in ids

    def results(events):
        return {r["id"]: r for r in slo.evaluate(events, manifest)}

    def req(ttft):
        return {"event": "token", "kind": "request", "run_id": "r",
                "ttft_ms": ttft, "tokens": 2}

    # vacuous on a journal with no token events (PR 18 semantics)
    r = results([{"event": "serve", "kind": "summary", "run_id": "r",
                  "dropped": 0}])
    assert r["ttft-p99"]["ok"] and not r["ttft-p99"]["applicable"]
    # warm pass: post-warmup TTFTs inside the bound
    r = results([req(10.0)] * 40)
    assert r["ttft-p99"]["applicable"] and r["ttft-p99"]["ok"]
    # burn: warmup excused, steady tail over the bound trips it
    r = results([req(10.0)] * 8 + [req(10_000.0)] * 30)
    assert r["ttft-p99"]["applicable"] and not r["ttft-p99"]["ok"]


@pytest.mark.smoke
def test_token_summary_counts_into_compile_and_drop_gates():
    from sparknet_tpu.obs import slo

    manifest = slo.load_manifest()
    bad = [{"event": "token", "kind": "summary", "run_id": "r",
            "compiles": 2, "dropped": 1}]
    r = {x["id"]: x for x in slo.evaluate(bad, manifest)}
    assert not r["post-warmup-compiles"]["ok"]
    assert not r["zero-drop"]["ok"]


def test_generate_chars_rides_the_cache_bitwise():
    """The demo decode path (models/generate.py): cached greedy output
    must equal the legacy sliding-window full-forward decode, and the
    cached executables must be built exactly once per net handle."""
    from sparknet_tpu.data.text import CharVocab
    from sparknet_tpu.models.generate import generate_chars
    from sparknet_tpu.models.zoo import charlm, charlm_solver
    from sparknet_tpu.net import TPUNet

    vocab = CharVocab("abcdefgh")
    S = 16
    net = TPUNet(charlm_solver(),
                 charlm(batch=1, seq_len=S, vocab=vocab.size,
                        embed_dim=32, heads=4, ffn_dim=32, blocks=1))

    def legacy(prompt, n):
        ids = list(vocab.encode(prompt))
        n_prompt = len(ids)
        dummy = np.zeros((1, S), np.int32)
        for _ in range(n):
            window = ids[-S:]
            data = np.zeros((1, S), np.int32)
            data[0, :len(window)] = window
            blobs = net.forward({"data": data, "label": dummy})
            ids.append(int(np.argmax(
                np.asarray(blobs["fc"])[0, len(window) - 1])))
        return vocab.decode(ids[n_prompt:])

    for prompt, n in [("abac", 8), ("h", 12), ("abcdefgh", 5)]:
        assert generate_chars(net, vocab, prompt, n, S,
                              temperature=0.0) == legacy(prompt, n)
    assert len(net._decode_cache) == 1  # one build, every call reuses
    # over-window requests fall back to the sliding full-forward path
    assert generate_chars(net, vocab, "abac", 20, S,
                          temperature=0.0) == legacy("abac", 20)
