"""Record-streaming ring sources (`data/records.py`) + the device-arm
e2e feed (ISSUE 12): byte-offset shard indexes make db/tar cursors
epoch-addressable, decode rides the ring workers as the `decode` stage,
and the uint8 wire feeds DeviceAugment post-placement.

Pins the tentpole contracts: deterministic ``(epoch, index)``
addressing per backend, LMDB locator == reader-value bytes, the
SIGKILL-respawn exact-contents resume THROUGH a record stream, the
uint8-wire >= 3.9x byte ratio, device-arm feed equivalence vs the
host-transform twin in both layouts, and the trainers' post-placement
augment hook.
"""

import io
import os
import signal
import tarfile
import time

import numpy as np
import pytest

from sparknet_tpu.data.createdb import create_db, db_minibatches
from sparknet_tpu.data.pipeline import ProcessPipeline
from sparknet_tpu.data.records import RecordShardSource, probe_record_backend

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def no_leaked_shm():
    """Ring tests must leave /dev/shm exactly as found (the
    unlink-on-close contract test_pipeline.py pins for every source)."""
    if not os.path.isdir("/dev/shm"):
        yield
        return
    before = set(os.listdir("/dev/shm"))
    yield
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = set(os.listdir("/dev/shm")) - before
        if not leaked:
            return
        time.sleep(0.1)
    assert not leaked, f"leaked shared-memory segments: {leaked}"


def _samples(n, shape=(3, 8, 8)):
    rs = np.random.RandomState(0)
    return [(rs.randint(0, 255, shape).astype(np.uint8), i % 10)
            for i in range(n)]


def _jpeg_tar(tmp_path, n=10, side=16, mapped=None):
    """A plain tar of JPEGs + train.txt label map; ``mapped`` limits how
    many members the map names (the rest must be skipped)."""
    from PIL import Image

    rs = np.random.RandomState(3)
    tar_p = str(tmp_path / "shard.tar")
    names = []
    with tarfile.open(tar_p, "w") as tf:
        for i in range(n):
            buf = io.BytesIO()
            Image.fromarray(
                rs.randint(0, 255, (side, side, 3), np.uint8)
            ).save(buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img_{i}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            names.append(info.name)
    lm = str(tmp_path / "train.txt")
    with open(lm, "w") as f:
        for i, name in enumerate(names[:mapped or n]):
            f.write(f"{name} {i * 3}\n")
    return tar_p, lm


# ------------------------------------------------------- backend probing


def test_probe_detects_every_backend(tmp_path):
    create_db(str(tmp_path / "lm"), _samples(4), backend="lmdb")
    create_db(str(tmp_path / "r.rdb"), _samples(4), backend="record")
    create_db(str(tmp_path / "lv"), _samples(4), backend="leveldb")
    tar_p, _ = _jpeg_tar(tmp_path, n=2)
    assert probe_record_backend(str(tmp_path / "lm")) == "lmdb"
    assert probe_record_backend(str(tmp_path / "r.rdb")) == "record"
    assert probe_record_backend(str(tmp_path / "lv")) == "leveldb"
    assert probe_record_backend(tar_p) == "tar"
    other = tmp_path / "noise.bin"
    other.write_bytes(b"\x00" * 64)
    assert probe_record_backend(str(other)) == "unknown"


# --------------------------------------- (epoch, index) determinism / order


@pytest.mark.parametrize("backend", ["record", "lmdb"])
def test_db_batches_match_threaded_cursor_order(tmp_path, backend):
    """The index walk reproduces exactly what the stateful cursor
    (db_minibatches, the threaded feed) would have yielded — migrating
    a db: feed to the ring changes the transport, not the data."""
    samples = _samples(24)
    p = str(tmp_path / "db")
    create_db(p, samples, backend=backend)
    src = RecordShardSource(p, 8)
    ref = db_minibatches(p, 8)
    for i in range(3):
        got = src.get(0, i)
        want = next(ref)
        np.testing.assert_array_equal(
            got["data"].astype(np.float32), want["data"])
        np.testing.assert_array_equal(got["label"], want["label"])
    # pure function of (epoch, index): same address, same bytes
    np.testing.assert_array_equal(src.get(0, 1)["data"],
                                  src.get(0, 1)["data"])
    assert src.batches_per_epoch == 3
    assert src.consume_decode_s > 0  # decode wall surfaced for the ring


def test_nhwc_wire_is_worker_side_transpose(tmp_path):
    p = str(tmp_path / "db")
    create_db(p, _samples(8), backend="record")
    chw = RecordShardSource(p, 8).get(0, 0)["data"]
    hwc = RecordShardSource(p, 8, layout="nhwc").get(0, 0)["data"]
    assert hwc.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(hwc, chw.transpose(0, 2, 3, 1))


def test_tar_backend_decodes_mapped_members_only(tmp_path):
    tar_p, lm = _jpeg_tar(tmp_path, n=10, mapped=8)
    src = RecordShardSource(tar_p, 4, layout="nhwc",
                            decode_size=(12, 12), label_map=lm)
    assert src.batches_per_epoch == 2  # 8 mapped // 4
    b = src.get(0, 0)
    assert b["data"].shape == (4, 12, 12, 3)
    assert b["data"].dtype == np.uint8
    assert b["label"].tolist() == [0, 3, 6, 9]
    np.testing.assert_array_equal(b["data"], src.get(0, 0)["data"])
    # layout twins decode the same pixels
    chw = RecordShardSource(tar_p, 4, decode_size=(12, 12), label_map=lm)
    np.testing.assert_array_equal(chw.get(0, 0)["data"],
                                  b["data"].transpose(0, 3, 1, 2))


def test_shuffle_is_per_epoch_seeded_and_covering(tmp_path):
    p = str(tmp_path / "db")
    samples = _samples(24)
    create_db(p, samples, backend="record")
    src = RecordShardSource(p, 8, shuffle=True, seed=5)
    a = src.get(1, 0)["data"]
    np.testing.assert_array_equal(a, src.get(1, 0)["data"])  # re-producible
    assert not np.array_equal(a, src.get(2, 0)["data"])  # epochs re-draw
    got = np.sort(np.concatenate(
        [src.get(3, i)["label"] for i in range(src.batches_per_epoch)]))
    np.testing.assert_array_equal(
        got, np.sort(np.asarray([s[1] for s in samples], np.int32)))


def test_stride_offset_reproduces_shared_db_interleave(tmp_path):
    """stride/offset = the shared-DB multi-process thread interleave:
    process p takes batches p, p+n, ... of the looped stream."""
    p = str(tmp_path / "db")
    create_db(p, _samples(24), backend="record")
    full = RecordShardSource(p, 8)
    s0 = RecordShardSource(p, 8, stride=2, offset=0)
    s1 = RecordShardSource(p, 8, stride=2, offset=1)
    for i, b in [(0, 0), (1, 2), (2, 1)]:  # (i*2) % 3
        np.testing.assert_array_equal(s0.get(0, i)["data"],
                                      full.get(0, b)["data"])
    np.testing.assert_array_equal(s1.get(0, 0)["data"],
                                  full.get(0, 1)["data"])
    assert s0.batches_per_epoch == full.batches_per_epoch == 3


# ----------------------------------------------------------- LMDB locators


def test_lmdb_locators_address_exact_value_bytes(tmp_path):
    """Every (offset, size) the locator walk yields slices the SAME
    bytes the reader's cursor returns — inline nodes and overflow
    (F_BIGDATA) values both."""
    from sparknet_tpu.data.lmdb_io import LmdbReader, LmdbWriter, _data_file

    items = [(f"k{i:03d}".encode(), os.urandom(20 + 400 * i))
             for i in range(12)]  # tails large enough to overflow a page
    p = str(tmp_path / "db")
    with LmdbWriter(p) as w:
        for k, v in items:
            w.put(k, v)
    with open(_data_file(p), "rb") as f:
        raw = f.read()
    with LmdbReader(p) as r:
        via_cursor = dict(r)
        locs = list(r.iter_locators())
    assert len(locs) == len(items)
    for key, off, size in locs:
        assert raw[off:off + size] == via_cursor[key]


# ---------------------------------------------------------------- refusals


def test_leveldb_refused_naming_convert_db(tmp_path):
    p = str(tmp_path / "lv")
    create_db(p, _samples(4), backend="leveldb")
    with pytest.raises(ValueError, match="convert_db"):
        RecordShardSource(p, 2)


def test_compressed_tar_refused(tmp_path):
    tar_p, lm = _jpeg_tar(tmp_path, n=2)
    gz = tar_p + ".gz"
    import gzip

    with open(tar_p, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    with pytest.raises(ValueError, match="repack as plain .tar"):
        RecordShardSource(gz, 2, decode_size=(8, 8), label_map=lm)


def test_tar_needs_decode_size_and_label_map(tmp_path):
    tar_p, lm = _jpeg_tar(tmp_path, n=4)
    with pytest.raises(ValueError, match="decode_size"):
        RecordShardSource(tar_p, 2, label_map=lm)
    with pytest.raises(ValueError, match="label map"):
        RecordShardSource(tar_p, 2, decode_size=(8, 8))


def test_process_feed_refusal_names_migration_path(tmp_path):
    """The remaining stateful sources' refusal tells the operator HOW to
    migrate (RecordShardSource / convert_db), not just no."""
    from sparknet_tpu.cli import _process_feed

    def stateful(it):
        return {"x": np.zeros(2, np.float32)}

    with pytest.raises(SystemExit, match="RecordShardSource"):
        _process_feed(stateful, 4, 0, object(), lambda *a, **k: None)


# ----------------------------------------------- through the process ring


@pytest.mark.parametrize("backend", ["record", "lmdb"])
def test_record_stream_through_ring_matches_direct(tmp_path, backend):
    p = str(tmp_path / "db")
    create_db(p, _samples(24), backend=backend)
    src = RecordShardSource(p, 8, layout="nhwc")
    with ProcessPipeline(src, None, num_batches=6, workers=2,
                         name="feed.rec") as pipe:
        got = [{k: np.array(v) for k, v in f.items()}
               for f in pipe.batches()]
        stats = dict(pipe.stats)
    for g, feeds in enumerate(got):
        e, i = divmod(g, src.batches_per_epoch)
        ref = src.get(e, i)
        np.testing.assert_array_equal(feeds["data"], ref["data"])
        np.testing.assert_array_equal(feeds["label"], ref["label"])
    # decode runs IN the workers and journals as its own stage
    assert stats["decode"] > 0.0


def test_sigkill_respawn_resumes_exact_record_stream(tmp_path):
    """ISSUE 12 acceptance pin: SIGKILL a ring worker mid-record-stream;
    the respawned worker resumes at the exact undelivered
    ``(epoch, index)`` and the stream's total contents are bitwise what
    the index defines — across an epoch boundary."""
    p = str(tmp_path / "db")
    create_db(p, _samples(32), backend="lmdb")
    src = RecordShardSource(p, 8, shuffle=True, seed=9)
    N = 12  # 3 epochs of 4 batches: the resume crosses epochs
    with ProcessPipeline(src, None, num_batches=N, workers=2,
                         max_respawns=2, name="feed.rec") as pipe:
        it = pipe.batches()
        got = [{k: np.array(v) for k, v in next(it).items()}
               for _ in range(3)]
        os.kill(pipe._procs[0].pid, signal.SIGKILL)
        got += [{k: np.array(v) for k, v in next(it).items()}
                for _ in range(N - 3)]
        assert pipe._respawns_used == 1
    assert len(got) == N
    for g, feeds in enumerate(got):
        e, i = divmod(g, src.batches_per_epoch)
        ref = src.get(e, i)
        np.testing.assert_array_equal(feeds["data"], ref["data"])
        np.testing.assert_array_equal(feeds["label"], ref["label"])


# ------------------------------------------------------- uint8 wire pin


def test_uint8_wire_at_least_3_9x_smaller_than_f32():
    """The thin-wire claim, pinned against the real slot allocator: the
    raw=True spec of the AlexNet wire is >= 3.9x smaller than the f32
    spec at the SAME geometry."""
    from sparknet_tpu.data.pipeline import FeedSpec
    from sparknet_tpu.ops.data_layers import wire_spec

    shapes = {"data": (256, 227, 227, 3), "label": (256,)}

    def slot_bytes(raw):
        spec = FeedSpec(tuple(
            (name, shape, dtype)
            for name, (shape, dtype) in wire_spec(shapes, raw=raw).items()))
        return spec.slot_bytes

    ratio = slot_bytes(False) / slot_bytes(True)
    assert ratio >= 3.9, ratio


# ------------------------------------- device arm vs host-transform twin


def _cpu_augment(cfg_kwargs, layout):
    from sparknet_tpu.data.device_transform import DeviceAugment
    from sparknet_tpu.data.transform import TransformConfig

    return DeviceAugment(TransformConfig(**cfg_kwargs), layout=layout)


def test_device_arm_test_mode_bitwise_matches_host_twin(tmp_path):
    """TEST-mode e2e equivalence: uint8 records through the ring +
    DeviceAugment == the host DataTransformer on the same records,
    bitwise, in both layouts."""
    import jax

    from sparknet_tpu.data.transform import DataTransformer, TransformConfig

    p = str(tmp_path / "db")
    create_db(p, _samples(16, shape=(3, 16, 16)), backend="record")
    rs = np.random.RandomState(2)
    mean = rs.rand(3, 16, 16).astype(np.float32) * 255
    cfg = dict(mean_image=mean, crop_size=12, scale=0.004)
    host = DataTransformer(TransformConfig(**cfg))
    key = jax.random.key(11)
    for layout in ("nchw", "nhwc"):
        src = RecordShardSource(p, 8, layout=layout)
        with ProcessPipeline(src, None, num_batches=1, workers=1,
                             name="feed.dev") as pipe:
            wire = {k: np.array(v)
                    for k, v in next(pipe.batches()).items()}
        assert wire["data"].dtype == np.uint8
        out = np.asarray(_cpu_augment(cfg, layout)(
            wire["data"], key, train=False))
        want = host(src.get(0, 0)["data"] if layout == "nchw"
                    else src.get(0, 0)["data"].transpose(0, 3, 1, 2),
                    False)
        if layout == "nhwc":
            out = out.transpose(0, 3, 1, 2)
        np.testing.assert_array_equal(out, want)


def test_device_arm_train_mode_same_key_same_crops_both_layouts():
    """TRAIN-mode draw-order pin: the SAME key produces the SAME crop
    offsets and mirror coins in both layouts — nchw output is exactly
    the transpose of the nhwc output."""
    import jax

    rs = np.random.RandomState(4)
    x_chw = rs.randint(0, 255, (6, 3, 16, 16)).astype(np.uint8)
    mean = rs.rand(3, 16, 16).astype(np.float32) * 255
    cfg = dict(mean_image=mean, crop_size=12, mirror=True, scale=0.004)
    key = jax.random.key(21)
    o_chw = np.asarray(_cpu_augment(cfg, "nchw")(x_chw, key, train=True))
    o_hwc = np.asarray(_cpu_augment(cfg, "nhwc")(
        np.ascontiguousarray(x_chw.transpose(0, 2, 3, 1)), key,
        train=True))
    np.testing.assert_array_equal(o_chw, o_hwc.transpose(0, 3, 1, 2))


# ------------------------------------------- trainer post-placement hook


def test_trainer_device_fn_key_policy_rank4_and_rank5():
    """The trainers' post-placement adapter: rank-4 feeds augment with
    ``fold_in(base, it)``; rank-5 [tau, B, ...] feeds give slot t the
    documented ``fold_in(fold_in(base, it), t)`` key — independent
    draws per slot, same family as the solo device_fn."""
    import jax

    rs = np.random.RandomState(5)
    x = rs.randint(0, 255, (4, 3, 16, 16)).astype(np.uint8)
    cfg = dict(crop_size=12, mirror=True)
    aug = _cpu_augment(cfg, "nchw")
    fn = aug.trainer_device_fn(pid=2, seed=3)
    out4 = np.asarray(fn({"data": x}, 7)["data"])
    assert out4.shape == (4, 3, 12, 12)
    x5 = np.stack([x, x])
    out5 = np.asarray(fn({"data": x5}, 7)["data"])
    assert out5.shape == (2, 4, 3, 12, 12)
    base = jax.random.key(1234 + 2 + 3)
    k_it = jax.random.fold_in(base, 7)
    for t in range(2):
        want = np.asarray(aug(x, jax.random.fold_in(k_it, t), train=True))
        np.testing.assert_array_equal(out5[t], want)
    # identical slot inputs still draw independently
    assert not np.array_equal(out5[0], out5[1])


def test_cli_train_device_arm_tau_process_feed(tmp_path, monkeypatch):
    """End-to-end: db record source -> process ring (uint8 wire) ->
    _stack_tau -> ParallelTrainer.feed_device_fn augment post-placement.
    Threaded and process feeds must deliver the same training sequence
    (the ring reproduces the cursor order)."""
    from sparknet_tpu.cli import main
    from sparknet_tpu.common import set_config

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("SPARKNET_TRAIN_LOG_DIR", str(tmp_path))
    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (1, 28, 28)).astype(np.uint8), i % 10)
               for i in range(64)]
    p = str(tmp_path / "train_lmdb")
    create_db(p, samples, backend="lmdb")
    args = ["--platform", "cpu", "train", "--solver", "zoo:lenet",
            "--batch", "8", "--iterations", "4", "--tau", "2",
            "--data", f"db:{p}", "--augment", "device", "--seed", "0"]
    assert main(args + ["--output", str(tmp_path / "m_thread")]) == 0
    set_config(feed="process")
    try:
        assert main(args + ["--output", str(tmp_path / "m_proc")]) == 0
    finally:
        set_config(feed="threaded")
    a = np.load(str(tmp_path / "m_thread.solverstate.npz"))
    b = np.load(str(tmp_path / "m_proc.solverstate.npz"))
    for k in a.files:
        if a[k].dtype.kind in "fiu":
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)
