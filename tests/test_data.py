"""Data-plane tests: loader formats, sampler window semantics, transformer
crops, minibatch packing, prefetcher overlap.

Mirrors the reference's pure-JVM data tests (ref:
src/test/scala/libs/MinibatchSamplerSpec.scala:4-44 pull-ordering on
synthetic data; CifarLoader exercised through CifarSpec).
"""

import io
import os
import tarfile

import numpy as np
import pytest

from sparknet_tpu.data import (
    CifarLoader,
    DataTransformer,
    DevicePrefetcher,
    ImageNetLoader,
    MinibatchSampler,
    TransformConfig,
    compute_mean,
    compute_mean_from_minibatches,
    make_minibatches,
    make_minibatches_compressed,
)
from sparknet_tpu.data.cifar import write_synthetic_cifar
from sparknet_tpu.data.sampler import partition_feed


# ---------------------------------------------------------------- CIFAR
def test_cifar_loader_roundtrip(tmp_path):
    write_synthetic_cifar(str(tmp_path), seed=3)
    loader = CifarLoader(str(tmp_path), seed=1)
    assert loader.train_images.shape == (500, 3, 32, 32)
    assert loader.test_images.shape == (100, 3, 32, 32)
    assert loader.train_labels.min() >= 0 and loader.train_labels.max() < 10
    # mean-subtracted train set has ~zero mean
    x, y = loader.train_arrays()
    assert abs(float(x.mean())) < 1.0
    # deterministic shuffle
    loader2 = CifarLoader(str(tmp_path), seed=1)
    np.testing.assert_array_equal(loader.train_labels, loader2.train_labels)


def test_cifar_loader_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        CifarLoader(str(tmp_path / "nope"))


# ---------------------------------------------------------------- sampler
def test_sampler_contiguous_window():
    batches = [{"i": np.full(2, k)} for k in range(10)]
    s = MinibatchSampler(batches, num_sampled_batches=4, seed=7)
    got = [int(b["i"][0]) for b in s]
    assert got == list(range(s.start, s.start + 4))
    assert 0 <= s.start <= 6


def test_sampler_from_iterator_matches_sequence():
    batches = [{"i": np.full(1, k)} for k in range(8)]
    s1 = MinibatchSampler(batches, num_sampled_batches=3, seed=5)
    s2 = MinibatchSampler(iter(batches), total_num_batches=8,
                          num_sampled_batches=3, seed=5)
    assert [int(b["i"][0]) for b in s1] == [int(b["i"][0]) for b in s2]


def test_sampler_too_many_raises():
    with pytest.raises(ValueError):
        MinibatchSampler([{"a": 1}], num_sampled_batches=2)


def test_partition_feed_tau_stack():
    images = np.arange(40 * 3 * 4 * 4, dtype=np.uint8).reshape(40, 3, 4, 4)
    labels = np.arange(40) % 10
    fn = partition_feed(images, labels, batch_size=4, tau=3, seed=0)
    feeds = fn(0)
    assert feeds["data"].shape == (3, 4, 3, 4, 4)
    assert feeds["label"].shape == (3, 4)
    # window is contiguous in the partition
    flat = feeds["label"].reshape(-1)
    start = flat[0]
    np.testing.assert_array_equal(flat, (np.arange(12) + start) % 10)


# ---------------------------------------------------------------- transform
def test_transform_center_vs_random_crop():
    cfg = TransformConfig(crop_size=8, mirror=True, seed=0)
    t = DataTransformer(cfg)
    x = np.random.RandomState(0).randint(0, 255, (16, 3, 12, 12)).astype(np.uint8)
    test_out = t(x, train=False)
    assert test_out.shape == (16, 3, 8, 8)
    np.testing.assert_allclose(test_out, x[:, :, 2:10, 2:10].astype(np.float32))
    train_out = t(x, train=True)
    assert train_out.shape == (16, 3, 8, 8)
    # every train crop is an actual window of the source image
    src = x.astype(np.float32)
    for i in range(4):
        found = any(
            np.array_equal(train_out[i], w) or np.array_equal(train_out[i], w[:, :, ::-1])
            for ho in range(5) for wo in range(5)
            for w in [src[i, :, ho:ho+8, wo:wo+8]]
        )
        assert found, i


def test_transform_mean_value_and_scale():
    cfg = TransformConfig(mean_value=(10.0, 20.0, 30.0), scale=0.5)
    t = DataTransformer(cfg)
    x = np.full((2, 3, 4, 4), 40.0, np.float32)
    out = t(x, train=True)
    np.testing.assert_allclose(out[:, 0], 15.0)
    np.testing.assert_allclose(out[:, 2], 5.0)


def test_transform_mean_image():
    mean = np.ones((3, 4, 4), np.float32) * 7
    t = DataTransformer(TransformConfig(mean_image=mean))
    out = t(np.full((2, 3, 4, 4), 10.0), train=False)
    np.testing.assert_allclose(out, 3.0)


# ---------------------------------------------------------------- minibatch
def _jpeg_bytes(arr_hwc: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr_hwc).save(buf, format="JPEG")
    return buf.getvalue()


def test_make_minibatches_drops_ragged_tail():
    images = np.zeros((10, 3, 4, 4), np.uint8)
    labels = np.arange(10)
    out = list(make_minibatches(images, labels, batch_size=4))
    assert len(out) == 2
    assert out[0][0].shape == (4, 3, 4, 4)


def test_make_minibatches_compressed_decodes_and_drops_bad():
    rs = np.random.RandomState(0)
    good = [( _jpeg_bytes(rs.randint(0, 255, (20, 30, 3)).astype(np.uint8)), k)
            for k in range(5)]
    bad = [(b"not a jpeg", 99)]
    out = list(make_minibatches_compressed(good[:3] + bad + good[3:],
                                           batch_size=2, height=8, width=8))
    assert len(out) == 2  # 5 good images -> 2 full batches of 2, tail dropped
    assert out[0][0].shape == (2, 3, 8, 8)
    assert 99 not in np.concatenate([b[1] for b in out])


def test_make_minibatches_compressed_pooled_matches_serial():
    """Thread-pooled decode yields byte-identical batches in identical
    order to the serial path, including broken-image drops."""
    rs = np.random.RandomState(1)
    samples = [(_jpeg_bytes(rs.randint(0, 255, (16, 16, 3)).astype(np.uint8)), k)
               for k in range(9)]
    samples.insert(4, (b"broken", 99))
    serial = list(make_minibatches_compressed(samples, 3, 8, 8, workers=1))
    pooled = list(make_minibatches_compressed(samples, 3, 8, 8, workers=4))
    assert len(serial) == len(pooled) == 3
    for (si, sl), (pi, pl) in zip(serial, pooled):
        np.testing.assert_array_equal(si, pi)
        np.testing.assert_array_equal(sl, pl)


def test_compute_mean_streaming_matches_direct():
    rs = np.random.RandomState(0)
    images = rs.randint(0, 255, (30, 3, 5, 5)).astype(np.uint8)
    labels = np.zeros(30, np.int64)
    direct = compute_mean(images)
    streamed = compute_mean_from_minibatches(
        make_minibatches(images, labels, 10), (3, 5, 5))
    np.testing.assert_allclose(direct, streamed, atol=1e-5)


# ---------------------------------------------------------------- archive
def test_imagenet_loader_tar_shards(tmp_path):
    rs = np.random.RandomState(0)
    names, labels = [], {}
    for shard in range(2):
        tar_path = tmp_path / f"shard{shard}.tar"
        with tarfile.open(tar_path, "w") as tf:
            for i in range(4):
                name = f"img_{shard}_{i}.jpg"
                data = _jpeg_bytes(rs.randint(0, 255, (10, 10, 3)).astype(np.uint8))
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
                labels[name] = shard * 4 + i
    label_file = tmp_path / "train.txt"
    label_file.write_text("".join(f"{n} {l}\n" for n, l in labels.items()))

    loader = ImageNetLoader(str(tmp_path), str(label_file))
    assert len(loader) == 2
    # worker sharding partitions the archives
    s0 = list(loader.shard(0, 2))
    s1 = list(loader.shard(1, 2))
    assert len(s0) == 4 and len(s1) == 4
    assert {l for _, l in s0} == {0, 1, 2, 3}
    assert {l for _, l in s1} == {4, 5, 6, 7}
    # pipeline composes into decoded minibatches
    batches = list(make_minibatches_compressed(s0, 2, 8, 8))
    assert len(batches) == 2


# ---------------------------------------------------------------- prefetch
def test_prefetcher_yields_all_in_order():
    made = []

    def data_fn(it):
        made.append(it)
        return {"x": np.full((2, 2), it, np.float32)}

    pf = DevicePrefetcher(data_fn, num_iters=6)
    got = [int(np.asarray(f["x"])[0, 0]) for f in pf]
    assert got == list(range(6))
    assert made == list(range(6))


def test_prefetcher_close_releases_worker():
    import threading

    def data_fn(it):
        return {"x": np.zeros((4, 4), np.float32)}

    pf = DevicePrefetcher(data_fn, num_iters=1000, depth=2)
    it = iter(pf)
    next(it)  # consume one, then abandon
    pf.close()
    assert not pf._thread.is_alive()
    assert pf._q.qsize() == 0
    # active threads back to baseline (no leaked workers)
    assert threading.active_count() < 20


def test_partition_feed_too_small_raises():
    with pytest.raises(ValueError, match="contiguous window"):
        partition_feed(np.zeros((10, 3, 4, 4)), np.zeros(10), batch_size=4, tau=3)


def test_prefetcher_reiteration_returns_immediately():
    pf = DevicePrefetcher(lambda it: {"x": np.zeros(1)}, num_iters=3)
    assert len(list(pf)) == 3
    assert list(pf) == []  # exhausted stream: no deadlock, no items


def test_prefetcher_propagates_errors():
    def data_fn(it):
        if it == 2:
            raise RuntimeError("boom")
        return {"x": np.zeros(1)}

    pf = DevicePrefetcher(data_fn, num_iters=5)
    with pytest.raises(RuntimeError, match="boom"):
        list(pf)


def test_feed_bench_tool_smoke():
    """tools/feed_bench.py variants run and report sane numbers."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "feed_bench",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "feed_bench.py"),
    )
    fb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fb)

    rec = fb.bench_transform("numpy", batch=8, iters=2)
    assert rec["value"] > 0 and "numpy" in rec["metric"]
    pre = fb.bench_prefetch(batch=8, iters=3)
    assert pre["value"] > 0
