"""End-to-end slice: LeNet built with the DSL trains on one device.

Mirrors the reference's statistical sanity tests (ref:
src/test/scala/libs/CifarSpec.scala:10-94 — untrained accuracy ~ chance,
then training works) and the README LeNet example (README.md:115-128).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.layers_dsl import (
    AccuracyLayer,
    ConvolutionLayer,
    InnerProductLayer,
    NetParam,
    Pooling,
    PoolingLayer,
    RDDLayer,
    ReLULayer,
    SoftmaxWithLoss,
)
from sparknet_tpu.net import TPUNet, WeightCollection
from sparknet_tpu.proto_loader import replace_data_layers
from sparknet_tpu.proto import parse
from sparknet_tpu.solvers import SolverConfig

BATCH = 32


def lenet(batch=BATCH):
    """The README's LeNet, built with the DSL (ref: README.md:115-128)."""
    return NetParam(
        "LeNet",
        RDDLayer("data", shape=[batch, 1, 28, 28]),
        RDDLayer("label", shape=[batch]),
        ConvolutionLayer("conv1", ["data"], kernel=(5, 5), num_output=20),
        PoolingLayer("pool1", ["conv1"], Pooling.Max, kernel=(2, 2), stride=(2, 2)),
        ConvolutionLayer("conv2", ["pool1"], kernel=(5, 5), num_output=50),
        PoolingLayer("pool2", ["conv2"], Pooling.Max, kernel=(2, 2), stride=(2, 2)),
        InnerProductLayer("ip1", ["pool2"], num_output=500),
        ReLULayer("relu1", ["ip1"]),
        InnerProductLayer("ip2", ["relu1"], num_output=10),
        SoftmaxWithLoss("loss", ["ip2", "label"]),
        AccuracyLayer("accuracy", ["ip2", "label"]),
    )


def synth_digits(n, seed=0):
    """Learnable synthetic 'digits': class k = bright 7x7 block at position
    k on a 28x28 canvas + noise.  Chance = 10%."""
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n)
    imgs = rs.randn(n, 1, 28, 28).astype(np.float32) * 0.3
    for i, k in enumerate(labels):
        r, c = divmod(int(k), 4)
        imgs[i, 0, 2 + r * 9 : 9 + r * 9, 2 + c * 6 : 9 + c * 6] += 2.0
    return imgs, labels.astype(np.int32)


def batches(imgs, labels, batch, seed=1):
    rs = np.random.RandomState(seed)
    n = len(imgs)
    while True:
        idx = rs.randint(0, n, batch)
        yield {"data": jnp.asarray(imgs[idx]), "label": jnp.asarray(labels[idx])}


@pytest.fixture(scope="module")
def trained():
    cfg = SolverConfig(base_lr=0.01, momentum=0.9, solver_type="SGD", display=0)
    net = TPUNet(cfg, lenet())
    imgs, labels = synth_digits(2000)
    test_imgs, test_labels = synth_digits(640, seed=42)
    test_stream = batches(test_imgs, test_labels, BATCH, seed=2)
    net.set_train_data(batches(imgs, labels, BATCH))
    net.set_test_data(test_stream, length=10)
    return net


def test_untrained_accuracy_is_chance(trained):
    """ref: CifarSpec.scala:92 asserts 7-13% for 10 classes."""
    fresh = TPUNet(SolverConfig(), lenet())
    test_imgs, test_labels = synth_digits(640, seed=43)
    fresh.set_test_data(batches(test_imgs, test_labels, BATCH, seed=3), length=20)
    scores = fresh.test()
    assert 0.02 <= scores["accuracy"] <= 0.25, scores


def test_training_learns(trained):
    loss0 = trained.solver.smoothed_loss
    trained.train(60)
    scores = trained.test()
    assert scores["accuracy"] > 0.5, scores
    assert trained.solver.smoothed_loss < 1.0


def test_weight_roundtrip(trained):
    wc = trained.get_weights()
    assert set(wc.layers()) == {"conv1", "conv2", "ip1", "ip2"}
    assert wc["conv1"][0].shape == (20, 1, 5, 5)
    # averaging two copies == identity (the SparkNet sync path algebra,
    # ref: CifarApp.scala:132-134)
    averaged = wc.add(wc).scalar_divide(2.0)
    trained.set_weights(averaged)
    got = trained.get_weights()
    np.testing.assert_allclose(got["ip2"][0], wc["ip2"][0], rtol=1e-6)


def test_forward_featurization(trained):
    """ref: FeaturizerApp.scala:88-102 — forward once, read a mid blob."""
    imgs, _ = synth_digits(BATCH, seed=7)
    blobs = trained.forward({"data": imgs, "label": np.zeros(BATCH, np.int32)})
    assert blobs["ip1"].shape == (BATCH, 500)
    assert blobs["pool2"].shape == (BATCH, 50, 4, 4)


def test_backward_returns_grads(trained):
    imgs, labels = synth_digits(BATCH, seed=8)
    grads = trained.backward({"data": imgs, "label": labels})
    assert grads["conv1"][0].shape == (20, 1, 5, 5)
    assert float(jnp.sum(jnp.abs(grads["ip2"][0]))) > 0


def test_save_load_weights(trained, tmp_path):
    p = str(tmp_path / "lenet_weights")
    trained.save_weights_to_file(p)
    w0 = trained.get_weights()["ip2"][0].copy()
    # perturb then reload
    wc = trained.get_weights()
    wc.weights["ip2"][0] = wc.weights["ip2"][0] * 0 + 5.0
    trained.set_weights(wc)
    trained.load_weights_from_file(p)
    np.testing.assert_allclose(trained.get_weights()["ip2"][0], w0, rtol=1e-6)


def test_replace_data_layers():
    """ref: ProtoLoader.replaceDataLayers surgery on a zoo prototxt."""
    npz = parse(
        """
        name: "z"
        layer { name: "d" type: "Data" top: "data" top: "label"
                data_param { batch_size: 256 } include { phase: TRAIN } }
        layer { name: "d" type: "Data" top: "data" top: "label"
                data_param { batch_size: 50 } include { phase: TEST } }
        layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
                inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
        """
    )
    surgered = replace_data_layers(npz, 32, 16, 3, 8, 8)
    from sparknet_tpu.common import Phase
    from sparknet_tpu.compiler import Network

    train = Network(surgered, Phase.TRAIN)
    assert train.feed_shapes()["data"] == (32, 3, 8, 8)
    test = Network(surgered, Phase.TEST)
    assert test.feed_shapes()["data"] == (16, 3, 8, 8)
    variables = train.init(jax.random.key(0))
    assert variables.params["ip"][0].shape == (10, 3 * 8 * 8)
