"""End-to-end ImageNetApp run on synthetic tar shards.

The reference validated its ImageNet path only on a live cluster
(ImageNetLoaderSpec is ``ignore``d without S3 credentials); here the
whole pipeline — tar shards → JPEG decode pool → resize 256 → mean →
random-crop/mirror transform → τ-round trainer on a device mesh — runs
against generated fixtures in CI (ref: ImageNetApp.scala:32-192).
"""

import io
import os
import tarfile

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    """Two tar shards x 24 JPEGs with a learnable class signal, plus the
    train.txt filename->label map (ref: ImageNetLoader.scala:41-54)."""
    root = tmp_path_factory.mktemp("imagenet_shards")
    rs = np.random.RandomState(0)
    lines = []
    idx = 0
    for shard in range(2):
        tar_path = os.path.join(root, f"shard_{shard:02d}.tar")
        with tarfile.open(tar_path, "w") as tf:
            for _ in range(24):
                label = rs.randint(0, 4)
                # pixel-scale class signal: one bright quadrant per class
                img = (rs.rand(64, 60, 3) * 60).astype(np.uint8)
                r, c = (label % 2) * 32, (label // 2) * 30
                img[r : r + 32, c : c + 30] += 120
                buf = io.BytesIO()
                Image.fromarray(img).save(buf, format="JPEG", quality=90)
                name = f"img_{idx:04d}.jpg"
                idx += 1
                info = tarfile.TarInfo(name)
                info.size = buf.getbuffer().nbytes
                buf.seek(0)
                tf.addfile(info, buf)
                lines.append(f"{name} {label}")
    label_file = os.path.join(root, "train.txt")
    with open(label_file, "w") as f:
        f.write("\n".join(lines) + "\n")
    return str(root), label_file


def test_imagenet_app_end_to_end(shard_dir, tmp_path):
    from sparknet_tpu.apps.imagenet_app import ImageNetApp
    from sparknet_tpu.parallel.mesh import data_parallel_mesh

    root, label_file = shard_dir
    app = ImageNetApp(
        root,
        label_file,
        mesh=data_parallel_mesh(2),  # 2 workers, one shard each
        tau=2,
        batch=3,
        model="caffenet",
        num_classes=4,
        log_dir=str(tmp_path),
    )
    assert app.num_workers == 2
    assert app.mean_image.shape == (3, 256, 256)
    # mean of raw pixels: strictly inside (0, 255)
    assert 0.0 < float(app.mean_image.mean()) < 255.0

    loss = app.run(num_outer=2)
    assert np.isfinite(loss)
    # 24 imgs/shard, tau(2) x batch(3) = 6 per worker per round: 2 rounds
    # consume 12 of 24 per shard without re-epoching
    logs = [f for f in os.listdir(tmp_path) if f.startswith("imagenet_training_log")]
    assert logs, "event log missing"


def test_imagenet_app_dataset_too_small(shard_dir, tmp_path):
    from sparknet_tpu.apps.imagenet_app import ImageNetApp
    from sparknet_tpu.parallel.mesh import data_parallel_mesh

    root, label_file = shard_dir
    app = ImageNetApp(
        root,
        label_file,
        mesh=data_parallel_mesh(2),
        tau=30,  # 30 x 3 = 90 > 24 images per worker shard
        batch=3,
        model="caffenet",
        num_classes=4,
        log_dir=str(tmp_path),
    )
    with pytest.raises(ValueError, match="dataset too small"):
        app.run(num_outer=1)
