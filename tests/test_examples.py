"""Smoke-run the examples/ scripts (the reference ships its workflows as
examples/ notebooks; ours are runnable scripts — ref:
caffe/examples/01-learning-lenet.ipynb et al., mapped in
docs/EXAMPLES.md).  Each runs as a subprocess the way a user would."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-script timeout: the distributed walkthrough compiles three
# shard_map programs on an 8-device host mesh (~6 min locally)
SCRIPTS = {
    "00_classification.py": 560,
    "01_learning_lenet.py": 560,
    "07_siamese.py": 560,
    "02_brewing_logreg.py": 560,
    "03_fine_tuning.py": 560,
    "net_surgery.py": 560,
    # full run is the convergence evidence (~10 min, over the tier-1
    # deadline); the smoke arm compiles all three shard_map programs
    # and runs 2 rounds each, gated on finiteness
    "04_distributed_training.py": (560, ["--smoke"]),
    "06_listfile_sources.py": 560,
    "08_db_backends.py": 560,
    "09_int8_deploy.py": 560,
    # full run is the convergence evidence (~20 min); CI smoke-checks
    # the plumbing only
    "10_resnet50_digits.py": (560, ["--smoke"]),
    "11_vgg16_digits.py": (560, ["--smoke"]),
    "12_googlenet_digits.py": (560, ["--smoke"]),
    "13_squeezenet_digits.py": (560, ["--smoke"]),
    "14_mobilenet_digits.py": (560, ["--smoke"]),
}


@pytest.mark.parametrize("script", sorted(SCRIPTS))
def test_example_runs(script):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    spec = SCRIPTS[script]
    timeout, extra = spec if isinstance(spec, tuple) else (spec, [])
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script),
         "--platform", "cpu", *extra],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
