"""Smoke-run the examples/ scripts (the reference ships its workflows as
examples/ notebooks; ours are runnable scripts — ref:
caffe/examples/01-learning-lenet.ipynb et al., mapped in
docs/EXAMPLES.md).  Each runs as a subprocess the way a user would."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPTS = [
    "01_learning_lenet.py",
    "02_brewing_logreg.py",
    "03_fine_tuning.py",
    "net_surgery.py",
]


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script),
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
