"""Utils (event log, signals, timing) + apps + CLI tests."""

import json
import os
import signal

import jax
import numpy as np
import pytest

from sparknet_tpu import models
from sparknet_tpu.common import Phase
from sparknet_tpu.compiler.graph import Network
from sparknet_tpu.data.cifar import write_synthetic_cifar
from sparknet_tpu.utils import EventLogger, SignalHandler, SolverAction
from sparknet_tpu.utils.timing import time_layers


# ---------------------------------------------------------------- utils
def test_event_logger_format(tmp_path):
    log = EventLogger(str(tmp_path), prefix="t", echo=False)
    log("hello")
    log("step", i=7)
    lines = open(log.path).read().splitlines()
    assert lines[0].startswith("start ")
    assert "hello" in lines[1]
    assert lines[2].endswith("step, i = 7")


def test_signal_handler_snapshot_then_stop():
    with SignalHandler() as sig:
        assert sig.check() is SolverAction.NONE
        os.kill(os.getpid(), signal.SIGHUP)
        assert sig.check() is SolverAction.SNAPSHOT
        assert sig.check() is SolverAction.NONE  # one-shot
        os.kill(os.getpid(), signal.SIGINT)
        assert sig.check() is SolverAction.STOP
    # uninstalled: default handlers restored
    assert signal.getsignal(signal.SIGHUP) not in (None,)


def test_time_layers_lenet():
    net = Network(models.lenet(2), Phase.TRAIN)
    variables = net.init(jax.random.PRNGKey(0))
    feeds = {
        "data": np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32),
        "label": np.zeros(2, np.int32),
    }
    rows = time_layers(net, variables, feeds, iterations=1)
    names = [r["layer"] for r in rows]
    assert "conv1" in names and "loss" in names
    conv = next(r for r in rows if r["layer"] == "conv1")
    assert conv["forward_ms"] > 0
    assert conv["backward_ms"] is not None and conv["backward_ms"] > 0
    # accuracy is TEST-only (include { phase: TEST }, like the reference
    # prototxts) — absent from the TRAIN table, forward-only in the TEST one
    assert "accuracy" not in names
    test_rows = time_layers(
        Network(models.lenet(2), Phase.TEST), variables, feeds, iterations=1
    )
    acc = next(r for r in test_rows if r["layer"] == "accuracy")
    assert acc["forward_ms"] > 0  # non-differentiable: forward only


def test_cli_time_hlo_cost_analysis(capsys):
    """`tpunet time --hlo`: XLA cost model of the compiled step (the
    per-op HLO cost breakdown, SURVEY §5's `caffe time` analog)."""
    import json as _json

    from sparknet_tpu.cli import main

    assert main(["time", "--hlo", "--solver", "zoo:lenet", "--batch", "4"]) == 0
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["flops_per_step"] > 1e6  # lenet fwd+bwd at batch 4
    assert out["hbm_bytes_per_step"] > 0
    assert out["batch"] == 4


def test_cli_time_trace_stages_banked(tmp_path, capsys):
    """`tpunet time --trace --trace-out`: the artifact is flushed after
    every stage (compile stats, untraced wall timing, short trace, full
    trace) so a relay wedge mid-trace still leaves evidence.  On CPU the
    final stage lands with measured wall numbers and empty device rows."""
    import json as _json

    from sparknet_tpu.cli import main

    out = tmp_path / "trace.artifact.json"
    assert main(["time", "--trace", "--trace-out", str(out),
                 "--solver", "zoo:lenet", "--batch", "4",
                 "--iterations", "2"]) == 0
    line = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["wall_ms_per_step"] > 0 and line["batch"] == 4
    art = _json.loads(out.read_text())
    assert art["stage"] == "final"
    # every earlier stage's fields survive in the artifact (the banking
    # is cumulative, so partial stages are supersets of their ancestors)
    assert art["gflop_per_step"] > 0            # stage: compiled
    assert art["wall_ms_per_step_untraced"] > 0  # stage: wall_timed
    assert "rows_short" in art                   # stage: trace_short
    assert art["img_per_sec"] > 0                # stage: final


def test_pull_shards_and_create_labelfile(tmp_path, capsys):
    """Dataset staging tools (ref: ec2/pull.py + ec2/create_labelfile.py)."""
    import io
    import tarfile

    from sparknet_tpu.cli import main

    store = tmp_path / "store"
    store.mkdir()
    for i in range(3):
        with tarfile.open(store / f"files-shuf-{i:03d}.tar", "w") as tar:
            for j in range(2):
                data = f"img {i}-{j}".encode()
                info = tarfile.TarInfo(name=f"n{i:04d}_{j}.JPEG")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
    out = tmp_path / "staged"
    assert main(["pull_shards", "--store", str(store),
                 "--start", "0", "--stop", "2", "--out", str(out)]) == 0
    staged = out / "000-002"
    files = sorted(p.name for p in staged.iterdir())
    assert len(files) == 4  # shards 0 and 1 only
    assert "n0002_0.JPEG" not in files

    # selection is by shard NUMBER in the filename, not list position:
    # with shard 001 deleted, [2, 3) still means shard 002
    (store / "files-shuf-001.tar").unlink()
    out2 = tmp_path / "staged2"
    assert main(["pull_shards", "--store", str(store),
                 "--start", "2", "--stop", "3", "--out", str(out2)]) == 0
    files2 = sorted(p.name for p in (out2 / "002-003").iterdir())
    assert files2 == ["n0002_0.JPEG", "n0002_1.JPEG"]

    # empty numeric range is an error, not a silent 0-file success
    import pytest as _pytest

    with _pytest.raises(SystemExit, match="no shards numbered"):
        main(["pull_shards", "--store", str(store),
              "--start", "7", "--stop", "9", "--out", str(out2)])

    master = tmp_path / "master_train.txt"
    master.write_text(
        "N0000_0.jpeg 7\nn0000_1.JPEG 3\nn0001_0.JPEG 1\n"
        "n0001_1.JPEG 2\nunrelated.JPEG 9\n"
    )
    labelfile = tmp_path / "train.txt"
    assert main(["create_labelfile", str(staged), str(master), str(labelfile)]) == 0
    lines = dict(l.split() for l in labelfile.read_text().splitlines())
    # case-normalized lookup; only staged files appear
    assert lines == {"n0000_0.JPEG": "7", "n0000_1.JPEG": "3",
                     "n0001_0.JPEG": "1", "n0001_1.JPEG": "2"}


# ---------------------------------------------------------------- apps
@pytest.fixture(scope="module")
def cifar_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cifar")
    write_synthetic_cifar(str(d), seed=2)
    return str(d)


def test_cifar_app_runs(cifar_dir, tmp_path):
    from sparknet_tpu.apps import CifarApp

    app = CifarApp(cifar_dir, tau=2, batch=4, log_dir=str(tmp_path))
    scores = app.run(num_outer=2, num_test_batches=2)
    assert "accuracy" in scores and np.isfinite(scores["accuracy"])
    # event log recorded phases
    content = open(app.log.path).read()
    assert "training" in content and "testing" in content
    # snapshot path works
    p = app.snapshot(str(tmp_path / "snap"))
    assert os.path.exists(p)


def test_featurizer(cifar_dir):
    from sparknet_tpu.apps import FeaturizerApp
    from sparknet_tpu.net import TPUNet

    net = TPUNet(models.lenet_solver(), models.lenet(4))
    app = FeaturizerApp(net, feature_blob="ip1")
    feeds = [{
        "data": np.zeros((4, 1, 28, 28), np.float32),
        "label": np.zeros(4, np.int32),
    }]
    feats = list(app.featurize(feeds))
    assert feats[0].shape == (4, 500)
    with pytest.raises(KeyError):
        list(FeaturizerApp(net, "nope").featurize(feeds))


def test_imagenet_app_tau_feeds(tmp_path):
    """ImageNetApp packs tau x workers minibatches with the crop applied."""
    import io
    import tarfile
    from PIL import Image

    rs = np.random.RandomState(0)
    labels = {}
    tar_path = tmp_path / "shard0.tar"
    with tarfile.open(tar_path, "w") as tf:
        for i in range(8):
            name = f"img{i}.jpg"
            buf = io.BytesIO()
            Image.fromarray(rs.randint(0, 255, (64, 64, 3)).astype(np.uint8)).save(
                buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            labels[name] = i % 3
    (tmp_path / "train.txt").write_text(
        "".join(f"{n} {l}\n" for n, l in labels.items()))

    from sparknet_tpu.apps.imagenet_app import ImageNetApp

    # tiny: alexnet at batch 2 never compiles here — only feed packing is
    # exercised, so stub the trainer-heavy ctor pieces via small model
    app = ImageNetApp.__new__(ImageNetApp)
    app.loader = __import__("sparknet_tpu.data", fromlist=["ImageNetLoader"]).ImageNetLoader(
        str(tmp_path), str(tmp_path / "train.txt"))
    app.batch = 2
    app.tau = 2
    app.num_workers = 1
    from sparknet_tpu.data import DataTransformer, TransformConfig
    app.transform = DataTransformer(TransformConfig(crop_size=48, mirror=True, seed=0))
    import sparknet_tpu.apps.imagenet_app as mod
    mod.RESIZE, old_resize = 64, mod.RESIZE
    mod.CROP, old_crop = 48, mod.CROP
    try:
        streams = [app.minibatch_stream(0)]
        feeds = app._tau_feeds(streams)
        assert feeds["data"].shape == (2, 2, 3, 48, 48)
        assert feeds["label"].shape == (2, 2)
    finally:
        mod.RESIZE, mod.CROP = old_resize, old_crop


def test_cifar_app_capacity_check(cifar_dir, tmp_path):
    """tau x global batch beyond the train set raises the clear error, not a
    numpy reshape failure."""
    from sparknet_tpu.apps import CifarApp

    app = CifarApp(cifar_dir, tau=2, batch=4, log_dir=str(tmp_path))
    app.tau = 1000  # force need > n
    with pytest.raises(ValueError, match="reduce tau"):
        app._train_feeds(0)


# ---------------------------------------------------------------- CLI
def test_cli_device_query(capsys):
    from sparknet_tpu.cli import main

    assert main(["device_query"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == len(jax.devices())
    assert json.loads(out[0])["platform"] == "cpu"


def test_cli_train_and_test_zoo_synthetic(tmp_path, monkeypatch, capsys):
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    rc = main([
        "train", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "3",
        "--test-iters", "2", "--output", "final",
    ])
    assert rc == 0
    assert os.path.exists("final.solverstate.npz")
    rc = main([
        "test", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "2",
        "--snapshot", "final.solverstate.npz",
    ])
    assert rc == 0
    scores = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "accuracy" in scores


def test_net_root_walks_up_from_solver_file(tmp_path, monkeypatch):
    """A solver whose relative ``net:`` path is rooted at the tree top
    (the Caffe layout: run from the caffe root) must still resolve when
    tpunet runs from an unrelated CWD — cli._net_root walks up from the
    solver file (ref: examples/cifar10/train_full.sh runs build/tools/
    caffe from the repo root with examples/... paths)."""
    import argparse

    from sparknet_tpu.cli import _build_net_and_solver

    root = tmp_path / "tree"
    (root / "examples" / "toy").mkdir(parents=True)
    (root / "examples" / "toy" / "net.prototxt").write_text(
        'name: "toy"\n'
        'layer { name: "data" type: "Input" top: "data"\n'
        "  input_param { shape { dim: 2 dim: 3 } } }\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        "  inner_product_param { num_output: 4 } }\n"
    )
    solver = root / "examples" / "toy" / "solver.prototxt"
    solver.write_text(
        'net: "examples/toy/net.prototxt"\nbase_lr: 0.1\nmax_iter: 1\n'
    )
    monkeypatch.chdir(tmp_path)  # NOT the tree root: CWD-relative fails
    args = argparse.Namespace(solver=str(solver), batch=None)
    net_param, cfg = _build_net_and_solver(args)
    assert net_param.get_str("name") == "toy"
    assert cfg.base_lr == 0.1


def test_cli_train_cifar_tau(cifar_dir, tmp_path, monkeypatch):
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    rc = main([
        "train", "--solver", "zoo:cifar10_quick", "--batch", "4",
        "--data", f"cifar:{cifar_dir}", "--iterations", "4", "--tau", "2",
    ])
    assert rc == 0


def test_cli_train_cifar_device_augment(cifar_dir, tmp_path, monkeypatch):
    """--augment device: uint8 over the feed link, mean-subtract in XLA
    on the prefetch thread (DeviceAugment via device_fn)."""
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    rc = main([
        "train", "--solver", "zoo:cifar10_quick", "--batch", "4",
        "--data", f"cifar:{cifar_dir}", "--iterations", "4",
        "--prefetch", "2", "--augment", "device",
    ])
    assert rc == 0


def test_cli_train_db_device_augment(tmp_path, monkeypatch):
    """--augment device on a db: source — records larger than the net's
    blob ship as raw uint8 and crop/mirror/scale run in XLA on the
    prefetch thread (the ImageNet 256-px-DB → 227-crop recipe shape)."""
    import numpy as np

    from sparknet_tpu.cli import main
    from sparknet_tpu.data.createdb import create_db

    monkeypatch.chdir(tmp_path)
    rs = np.random.RandomState(0)
    samples = [(rs.randint(0, 255, (3, 16, 16)).astype(np.uint8), i % 4)
               for i in range(24)]
    db = str(tmp_path / "aug_lmdb")
    create_db(db, samples, backend="lmdb")

    (tmp_path / "net.prototxt").write_text(
        'name: "devaug"\n'
        'layer { name: "d" type: "Data" top: "data" top: "label"\n'
        '  data_param { source: "gone_lmdb" batch_size: 6 }\n'
        "  transform_param { crop_size: 12 mirror: true scale: 0.0039 }\n"
        "}\n"
        'layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"\n'
        "  inner_product_param { num_output: 4 } }\n"
        'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
        'bottom: "label" top: "loss" }\n'
    )
    (tmp_path / "solver.prototxt").write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nmax_iter: 4\ndisplay: 0\n'
    )
    rc = main([
        "train", "--solver", str(tmp_path / "solver.prototxt"),
        "--data", f"db:{db}", "--iterations", "4",
        "--prefetch", "2", "--augment", "device",
        "--output", str(tmp_path / "out"),
    ])
    assert rc == 0
    assert (tmp_path / "out.solverstate.npz").exists()


def test_cli_device_augment_guards(cifar_dir, tmp_path, monkeypatch):
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    base = ["train", "--solver", "zoo:cifar10_quick", "--batch", "4",
            "--iterations", "2"]
    with pytest.raises(SystemExit, match="--prefetch"):
        main(base + ["--data", f"cifar:{cifar_dir}", "--augment", "device"])
    with pytest.raises(SystemExit, match="cifar"):
        main(base + ["--data", "synthetic", "--prefetch", "2",
                     "--augment", "device"])
    # the trainer path needs NO async-feed precondition: the augment
    # runs post-placement via ParallelTrainer.feed_device_fn, so
    # --augment device --tau trains end-to-end (uint8 tau wire)
    rc = main(base + ["--data", f"cifar:{cifar_dir}", "--augment",
                      "device", "--tau", "2", "--output",
                      str(tmp_path / "aug_tau")])
    assert rc == 0
    assert (tmp_path / "aug_tau.solverstate.npz").exists()


def test_cli_time_lenet(capsys):
    from sparknet_tpu.cli import main

    rc = main(["time", "--solver", "zoo:lenet", "--batch", "2",
               "--data", "synthetic", "--iterations", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "conv1" in out and "TOTAL" in out


def test_profiling_trace_writes_files(tmp_path):
    from sparknet_tpu.utils import profiling

    d = str(tmp_path / "prof")
    with profiling.trace(d):
        jnp_sum = jax.jit(lambda x: x * 2)(np.ones(16, np.float32))
        jax.block_until_ready(jnp_sum)
    # a plugins/profile/<ts>/ tree with at least one trace artifact
    found = [f for root, _, fs in os.walk(d) for f in fs]
    assert found, "profiler produced no artifacts"


def test_device_memory_stats_shape():
    from sparknet_tpu.utils.profiling import device_memory_stats

    stats = device_memory_stats()
    assert isinstance(stats, dict)  # CPU backends may expose nothing


def test_cli_dataset_tools_pipeline(tmp_path, monkeypatch, capsys):
    """convert_imageset -> compute_image_mean -> extract_features chain."""
    import io as _io
    from PIL import Image

    from sparknet_tpu.cli import main

    native = pytest.importorskip("sparknet_tpu.native")
    if not native.available():
        pytest.skip("native record DB unavailable")

    rs = np.random.RandomState(0)
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    lines = []
    for i in range(6):
        arr = rs.randint(0, 255, (20, 20, 3)).astype(np.uint8)
        Image.fromarray(arr).save(imgdir / f"im{i}.jpg")
        lines.append(f"im{i}.jpg {i % 3}")
    listfile = tmp_path / "list.txt"
    listfile.write_text("\n".join(lines) + "\n")

    monkeypatch.chdir(tmp_path)
    db = str(tmp_path / "set.sndb")
    assert main(["convert_imageset", "--root", str(imgdir), "--listfile",
                 str(listfile), "--db", db, "--resize", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["records"] == 6

    assert main(["compute_image_mean", "--db", db, "--out",
                 str(tmp_path / "mean.npy"), "--batch", "2"]) == 0
    mean = np.load(tmp_path / "mean.npy")
    assert mean.shape == (3, 16, 16)

    assert main(["extract_features", "--solver", "zoo:lenet", "--batch", "4",
                 "--data", "synthetic", "--iterations", "2",
                 "--blob", "ip1", "--out", str(tmp_path / "feats.npy")]) == 0
    feats = np.load(tmp_path / "feats.npy")
    assert feats.shape == (8, 500)


def test_db_apps_cifar_and_imagenet(tmp_path, cifar_dir):
    """CifarDBApp materializes DBs and trains; ImageNetCreateDBApp +
    ImageNetRunDBApp round-trip through the record-DB pipeline."""
    import io as _io
    import tarfile
    from PIL import Image

    native = pytest.importorskip("sparknet_tpu.native")
    if not native.available():
        pytest.skip("native record DB unavailable")

    from sparknet_tpu.apps.db_apps import CifarDBApp, ImageNetCreateDBApp

    app = CifarDBApp(cifar_dir, str(tmp_path / "dbs"), batch=10,
                     log_dir=str(tmp_path))
    scores = app.run(num_iters=3, test_batches=2)
    assert "accuracy" in scores
    # DBs persisted; a second construction reuses them
    app2 = CifarDBApp(cifar_dir, str(tmp_path / "dbs"), batch=10,
                      log_dir=str(tmp_path))
    assert app2.mean_image.shape == (3, 32, 32)

    # the reference's actual backend (CifarDBApp.scala writes LevelDB)
    app3 = CifarDBApp(cifar_dir, str(tmp_path / "dbs_ldb"), batch=10,
                      log_dir=str(tmp_path), backend="leveldb")
    scores3 = app3.run(num_iters=2, test_batches=1)
    assert "accuracy" in scores3

    # a crash mid-materialize leaves a half-DB (no done marker):
    # reconstruction must clear and rebuild instead of wedging on reuse
    import shutil

    shutil.rmtree(str(tmp_path / "dbs_ldb" / "cifar_test_leveldb"))
    (tmp_path / "dbs_ldb" / "cifar_test_leveldb").mkdir()  # empty husk
    os.remove(str(tmp_path / "dbs_ldb" / ".materialized_leveldb"))
    app4 = CifarDBApp(cifar_dir, str(tmp_path / "dbs_ldb"), batch=10,
                      log_dir=str(tmp_path), backend="leveldb")
    assert app4.run(num_iters=1, test_batches=1)["accuracy"] >= 0.0

    with pytest.raises(ValueError, match="unknown db backend"):
        CifarDBApp(cifar_dir, str(tmp_path / "x"),
                   log_dir=str(tmp_path), backend="lvldb")

    # tiny imagenet-style shard
    rs = np.random.RandomState(0)
    labels = {}
    with tarfile.open(tmp_path / "s0.tar", "w") as tf:
        for i in range(5):
            name = f"i{i}.jpg"
            buf = _io.BytesIO()
            Image.fromarray(rs.randint(0, 255, (40, 40, 3)).astype(np.uint8)).save(
                buf, format="JPEG")
            data = buf.getvalue()
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, _io.BytesIO(data))
            labels[name] = i
    (tmp_path / "labels.txt").write_text(
        "".join(f"{n} {l}\n" for n, l in labels.items()))
    creator = ImageNetCreateDBApp(str(tmp_path), str(tmp_path / "labels.txt"),
                                  str(tmp_path / "in_dbs"), resize=32, batch=2)
    info = creator.run()
    assert info["workers"][0]["records"] == 4  # 2 full batches of 2
    mean = np.load(info["mean"])
    assert mean.shape == (3, 32, 32)

    # the reference's actual backend, per-worker LevelDBs
    from sparknet_tpu.data.createdb import db_minibatches
    from sparknet_tpu.data.leveldb_io import is_leveldb

    creator2 = ImageNetCreateDBApp(
        str(tmp_path), str(tmp_path / "labels.txt"),
        str(tmp_path / "in_dbs_ldb"), resize=32, batch=2, backend="leveldb")
    info2 = creator2.run()
    db2 = info2["workers"][0]["db"]
    assert is_leveldb(db2) and info2["workers"][0]["records"] == 4
    assert next(db_minibatches(db2, 4))["data"].shape == (4, 3, 32, 32)


def test_cli_time_fused(capsys):
    from sparknet_tpu.cli import main

    rc = main(["time", "--solver", "zoo:lenet", "--batch", "4",
               "--data", "synthetic", "--iterations", "2", "--fused"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["batch"] == 4 and out["fused_step_ms"] > 0


def test_cli_train_profile(tmp_path, monkeypatch):
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    rc = main(["train", "--solver", "zoo:lenet", "--batch", "4",
               "--data", "synthetic", "--iterations", "2",
               "--profile", str(tmp_path / "prof")])
    assert rc == 0
    found = [f for root, _, fs in os.walk(tmp_path / "prof") for f in fs]
    assert found, "no profiler artifacts written"


def test_cli_train_finetune_weights(tmp_path, capsys, monkeypatch):
    """`tpunet train --weights model.caffemodel` copies params by layer
    name before training (ref: caffe.cpp:184-189 CopyLayers /
    finetune_flickr_style)."""
    import json as _json

    from sparknet_tpu import models
    from sparknet_tpu.cli import main
    from sparknet_tpu.net import TPUNet, copy_caffemodel_params
    from sparknet_tpu.solvers.solver import SolverConfig

    monkeypatch.chdir(tmp_path)  # cmd_train writes its event log to cwd
    donor = TPUNet(SolverConfig(), models.lenet(4))
    weights = str(tmp_path / "donor.caffemodel")
    donor.save_caffemodel(weights)
    w_donor = np.asarray(donor.solver.variables.params["conv1"][0])

    out_prefix = str(tmp_path / "ft")
    assert main([
        "train", "--solver", "zoo:lenet", "--batch", "4",
        "--iterations", "1", "--weights", weights, "--output", out_prefix,
    ]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    meta = _json.loads(lines[0])
    assert meta["finetune_from"] == weights
    assert "conv1" in meta["layers_loaded"]
    # the copy itself delivers the donor's values (not just metadata):
    # a fresh net finetuned from the file starts at w_donor exactly
    fresh = TPUNet(SolverConfig(), models.lenet(4))
    params, loaded = copy_caffemodel_params(
        fresh.solver.variables.params, weights
    )
    assert "conv1" in loaded
    assert np.array_equal(np.asarray(params["conv1"][0]), w_donor)


def test_parse_log_tables(tmp_path):
    """ref: tools/extra/parse_log.py — train/test tables from a mixed log."""
    from sparknet_tpu.utils.log_parse import parse_log, parse_log_to_csv

    log = tmp_path / "tpunet_train_123.txt"
    log.write_text(
        "start 123\n"
        "0.100: profiling -> /tmp/x\n"
        "Iteration 100, loss = 2.2984, lr = 0.001\n"
        "1.500: loss: 2.10000, i = 150\n"
        "Iteration 200, loss = 0.68188, lr = 0.0005\n"
        "2.750: scores: {'accuracy': 0.727, 'loss': 0.6228}, i = 200\n"
        "3.000: scores: {'accuracy': 0.939, 'loss': 0.2027}\n"
        "garbage line that matches nothing\n"
        "192.168.0.1: connection refused\n"
    )
    train_rows, test_rows = parse_log(str(log))
    assert [r["NumIters"] for r in train_rows] == [100, 150, 200]
    assert train_rows[0]["LearningRate"] == 0.001
    assert train_rows[1] == {"NumIters": 150, "loss": 2.1, "Seconds": 1.5}
    assert train_rows[2]["loss"] == 0.68188
    assert [r["NumIters"] for r in test_rows] == [200, 200]
    assert test_rows[0]["accuracy"] == 0.727
    assert test_rows[1]["Seconds"] == 3.0

    train_csv, test_csv = parse_log_to_csv(str(log))
    header = open(train_csv).readline().strip().split(",")
    assert header[0] == "NumIters" and "loss" in header
    rows = open(test_csv).read().strip().splitlines()
    assert len(rows) == 3  # header + 2
    assert rows[0].startswith("NumIters,Seconds,accuracy")

    # stdout captures carry both the display line and its event-log mirror:
    # one merged row per iteration, display fields winning
    log2 = tmp_path / "stdout_capture.log"
    log2.write_text(
        "Iteration 100, loss = 2.0, lr = 0.001\n"
        "5.000: loss: 2.10000, i = 100\n"
    )
    merged, _ = parse_log(str(log2))
    assert merged == [
        {"NumIters": 100, "loss": 2.0, "LearningRate": 0.001, "Seconds": 5.0}
    ]

    # out_dir that does not exist yet is created
    t2, _ = parse_log_to_csv(str(log2), str(tmp_path / "results"))
    assert open(t2).readline().startswith("NumIters")


def test_cli_parse_log_roundtrip(tmp_path, monkeypatch, capsys):
    """End to end: tpunet train writes a log parse_log can tabulate."""
    import glob

    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    # Default log dir is the system tempdir; pin it to the sandbox to
    # exercise the SPARKNET_TRAIN_LOG_DIR route and keep the glob local.
    monkeypatch.setenv("SPARKNET_TRAIN_LOG_DIR", str(tmp_path))
    assert main([
        "train", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "3",
        "--test-iters", "2", "--output", "final",
    ]) == 0
    (logfile,) = glob.glob("tpunet_train_*.txt")
    capsys.readouterr()
    assert main(["parse_log", logfile, str(tmp_path)]) == 0
    paths = json.loads(capsys.readouterr().out.strip())
    test_rows = open(paths["test"]).read().strip().splitlines()
    assert len(test_rows) == 2  # header + the --test-iters scores line
    assert "accuracy" in test_rows[0]
    assert test_rows[1].startswith("3,")  # scores stamped with i=<final iter>


def test_cli_deprecated_tools():
    from sparknet_tpu.cli import main

    for cmd in ("train_net", "finetune_net", "test_net", "net_speed_benchmark"):
        with pytest.raises(SystemExit, match="Deprecated"):
            main([cmd, "whatever.prototxt"])


def test_cli_train_multihost_two_processes(tmp_path):
    """tpunet train --distributed across 2 processes: DCN bring-up via
    CLI flags, per-process synthetic shards, both exit clean."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"

    def spawn(pid):
        return subprocess.Popen(
            [sys.executable, "-m", "sparknet_tpu.cli", "--platform", "cpu",
             "train", "--solver", "zoo:lenet", "--batch", "8",
             "--data", "synthetic", "--iterations", "2", "--distributed",
             "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
             "--process-id", str(pid), "--output", str(tmp_path / f"out{pid}")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=str(tmp_path),
        )

    procs = [spawn(0), spawn(1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            p.poll() is None and p.kill()
    if any(p.returncode != 0 for p in procs):
        # known env drift (CHANGES.md PR 3/7: "fails identically at the
        # pre-PR tree"): the CPU backend's multiprocess device_put
        # rejection means the capability under test does not exist here
        # — skip like test_multihost_two_process_cluster does instead
        # of paying the re-verification tax every PR
        from conftest import skip_if_cpu_multiprocess_drift

        skip_if_cpu_multiprocess_drift(outs)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    assert any("distributed: process" in o for o in outs)


def test_plot_training_log(tmp_path, capsys):
    """ref: tools/extra/plot_training_log.py.example — chart types render
    from a parsed log; missing-table requests fail clearly."""
    from sparknet_tpu.cli import main

    log = tmp_path / "run.txt"
    log.write_text(
        "Iteration 100, loss = 2.0, lr = 0.01\n"
        "10.000: loss: 1.50000, i = 200\n"
        "Iteration 300, loss = 1.0, lr = 0.005\n"
        "20.000: scores: {'accuracy': 0.5, 'loss': 1.2}, i = 300\n"
        "30.000: scores: {'accuracy': 0.8, 'loss': 0.6}, i = 600\n"
    )
    for ct in (0, 6, 4):
        out = tmp_path / f"chart{ct}.png"
        assert main(["plot_training_log", str(ct), str(out), str(log)]) == 0
        assert out.exists() and out.stat().st_size > 1000

    from sparknet_tpu.utils.plotting import plot_chart

    with pytest.raises(ValueError, match="unknown chart type"):
        plot_chart(9, str(log), str(tmp_path / "x.png"))
    empty = tmp_path / "empty.txt"
    empty.write_text("nothing here\n")
    with pytest.raises(ValueError, match="no .*rows"):
        plot_chart(0, str(empty), str(tmp_path / "x.png"))


def test_resize_images_tree(tmp_path, capsys):
    """ref: tools/extra/resize_and_crop_images.py — shorter-side resize +
    center crop over a tree, structure preserved, broken files survive."""
    from PIL import Image

    from sparknet_tpu.cli import main

    src = tmp_path / "in"
    (src / "synset_a").mkdir(parents=True)
    (src / "synset_b").mkdir()
    Image.new("RGB", (100, 60), (200, 10, 10)).save(src / "synset_a" / "wide.jpg")
    Image.new("RGB", (30, 90), (10, 200, 10)).save(src / "synset_b" / "tall.png")
    (src / "synset_b" / "broken.jpg").write_bytes(b"not an image")

    out = tmp_path / "out"
    # workers=2 exercises the multiprocessing.Pool path (worker fn and
    # args must stay picklable/spawn-safe — the default CLI path)
    rc = main([
        "resize_images", "--input-folder", str(src),
        "--output-folder", str(out), "--side", "32", "--workers", "2",
    ])
    assert rc == 1  # broken.jpg reported
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec == {"resized": 2, "errors": 1}
    for rel in ("synset_a/wide.jpg", "synset_b/tall.png"):
        with Image.open(out / rel) as img:
            assert img.size == (32, 32)


def test_cli_train_elastic(tmp_path, monkeypatch):
    """tpunet train --elastic-alpha: EASGD through the CLI (tau=1 and
    tau>1 both take the stacked feed contract)."""
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    n = len(jax.devices())
    for tau in (1, 2):
        rc = main([
            "train", "--solver", "zoo:lenet", "--batch", "4",
            "--data", "synthetic", "--iterations", "2", "--tau", str(tau),
            "--elastic-alpha", str(0.9 / n), "--output", f"e{tau}",
        ])
        assert rc == 0
        assert os.path.exists(f"e{tau}.solverstate.npz")


def test_cli_test_weights(tmp_path, monkeypatch, capsys):
    """tpunet test --weights: score a caffemodel directly (the reference's
    canonical `caffe test --weights` usage)."""
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    assert main([
        "train", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "2", "--output", "m",
    ]) == 0
    capsys.readouterr()
    assert main([
        "test", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "2",
        "--weights", "m.caffemodel",
    ]) == 0
    scores = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "accuracy" in scores

    with pytest.raises(SystemExit, match="mutually exclusive"):
        main(["test", "--solver", "zoo:lenet", "--batch", "8",
              "--data", "synthetic", "--snapshot", "m.solverstate.npz",
              "--weights", "m.caffemodel"])

    # extract_features from the same caffemodel (the reference tool's
    # pretrained_net_param argument, extract_features.cpp)
    capsys.readouterr()
    assert main([
        "extract_features", "--solver", "zoo:lenet", "--batch", "8",
        "--data", "synthetic", "--iterations", "2",
        "--weights", "m.caffemodel", "--blob", "ip1", "--out", "feats.npy",
    ]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["shape"] == [16, 500]  # 2 batches x 8, ip1 width


def test_cli_bench_brew(capsys, monkeypatch):
    """tpunet bench: the headline benchmark as a brew (one JSON line)."""
    from sparknet_tpu.cli import main

    # conftest pins JAX_PLATFORMS=cpu, which bench.py honors as the
    # forced-CPU fast path (no probe subprocess, no watchdog); assert
    # that coupling so a conftest change fails here, not by hanging
    assert os.environ.get("JAX_PLATFORMS") == "cpu"
    assert main(["bench", "--batch", "4", "--dtype", "f32"]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "alexnet_train_images_per_sec_per_chip"
    assert rec["measured"] is True
    assert rec["value"] > 0


def test_bench_require_measured_partial_exits_nonzero(tmp_path):
    """SPARKNET_BENCH_REQUIRE_MEASURED=1: a partial (unmeasured) record
    exits rc 4 so the window runner retries the job in a later window
    instead of marking a wedge-raced bench as done."""
    import subprocess
    import sys as _sys

    code = (
        "import bench\n"
        "bench.probe_backend = lambda **kw: "
        "{'ok': False, 'reason': 'test wedge'}\n"
        "bench.cost_model_estimate = lambda *a, **k: {}\n"
        "import sys\n"
        "sys.exit(bench.main())\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # not cpu: take the probe path
    env.update({
        "SPARKNET_BENCH_REQUIRE_MEASURED": "1",
        "SPARKNET_BENCH_BATCH": "4",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    })
    out = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 4, (out.stdout + out.stderr)[-1500:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["measured"] is False and rec["partial"] is True

    # without the knob the same partial record is an rc=0 answer
    env.pop("SPARKNET_BENCH_REQUIRE_MEASURED")
    out2 = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out2.returncode == 0, (out2.stdout + out2.stderr)[-1500:]


def test_cli_train_distributed_scan(tmp_path, monkeypatch):
    """tpunet train --distributed --scan N: tau=1 sync-SGD rounds fused
    N per dispatch (ParallelTrainer.train_rounds) through the CLI."""
    from sparknet_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    assert main([
        "train", "--solver", "zoo:lenet", "--batch", "4",
        "--data", "synthetic", "--iterations", "4", "--distributed",
        "--scan", "2", "--output", str(tmp_path / "out"),
    ]) == 0
    assert (tmp_path / "out.solverstate.npz").exists()


def test_cli_train_dtype_bf16(tmp_path, monkeypatch):
    """--dtype bf16 on the train brew: the central dispatch point sets
    the global compute dtype before any net is built (mixed precision
    as a first-class CLI path, not just bench env plumbing)."""
    import jax.numpy as jnp

    from sparknet_tpu import cli
    from sparknet_tpu.common import get_config, set_config

    monkeypatch.chdir(tmp_path)
    try:
        rc = cli.main(["train", "--solver", "zoo:lenet", "--batch", "4",
                       "--dtype", "bf16", "--iterations", "1",
                       "--data", "synthetic"])
        assert rc == 0
        # the dispatch point RESTORES the global dtype afterwards (an
        # in-process cli.main() must not leak bf16 into the caller)
        assert get_config().compute_dtype == jnp.float32
        # and the dtype took EFFECT during the run: the staged trace
        # artifact banks the active compute dtype at build time
        import json as _json

        rc2 = cli.main(["time", "--solver", "zoo:lenet", "--batch", "4",
                        "--dtype", "bf16", "--iterations", "1", "--trace",
                        "--trace-out", str(tmp_path / "t.json")])
        assert rc2 == 0
        art = _json.load(open(tmp_path / "t.json"))
        assert art["dtype"] == "bf16"
        assert get_config().compute_dtype == jnp.float32
    finally:
        set_config(compute_dtype=jnp.float32)
