"""Pallas LRN kernel: interpret-mode equivalence with the XLA formulation
(value and gradient), mirroring the reference's per-layer gradient-check
discipline (ref: caffe/src/caffe/test/test_lrn_layer.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.ops.pallas_kernels import (
    lrn_across_channels,
    lrn_across_channels_xla,
)

CASES = [
    # (shape, size, alpha, beta, k)
    ((2, 5, 4, 4), 5, 1e-4, 0.75, 1.0),     # AlexNet params, tiny shape
    ((1, 96, 6, 6), 5, 1e-4, 0.75, 1.0),    # AlexNet conv1 channel count
    ((2, 8, 3, 7), 3, 5e-5, 0.75, 2.0),     # odd spatial, k != 1
]


@pytest.mark.parametrize("shape,size,alpha,beta,k", CASES)
def test_pallas_lrn_matches_xla(shape, size, alpha, beta, k):
    x = jnp.asarray(np.random.RandomState(0).randn(*shape) * 10, jnp.float32)
    ref = lrn_across_channels_xla(x, size, alpha, beta, k)
    out = lrn_across_channels(x, size, alpha, beta, k, force="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pallas_lrn_gradient_matches_xla():
    x = jnp.asarray(np.random.RandomState(1).randn(1, 6, 4, 4) * 5, jnp.float32)

    g_pallas = jax.grad(
        lambda t: jnp.sum(lrn_across_channels(t, 5, 1e-4, 0.75, 1.0,
                                              force="interpret") ** 2))(x)
    g_xla = jax.grad(
        lambda t: jnp.sum(lrn_across_channels_xla(t, 5, 1e-4, 0.75, 1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla), atol=1e-4)


def test_pallas_lrn_nonaligned_spatial_padding():
    """Spatial size not a multiple of the tile exercises the pad/crop path."""
    x = jnp.asarray(np.random.RandomState(2).randn(1, 4, 13, 11), jnp.float32)
    ref = lrn_across_channels_xla(x, 3, 1e-4, 0.75, 1.0)
    out = lrn_across_channels(x, 3, 1e-4, 0.75, 1.0, force="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_lrn_layer_uses_selector_and_stays_correct():
    """The LRN layer's output is unchanged after the pallas wiring (CPU
    backend routes to XLA)."""
    from sparknet_tpu.common import Phase
    from sparknet_tpu.ops.registry import create_layer
    from sparknet_tpu.proto.text_format import Message

    lp = Message().set("name", "n").set("type", "LRN")
    lp.add("bottom", "x"); lp.add("top", "n")
    lp.set("lrn_param", Message().set("local_size", 5).set("alpha", 1e-4).set("beta", 0.75))
    layer = create_layer(lp, Phase.TRAIN)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 5, 5), jnp.float32)
    out = layer.apply([], {}, [x], train=True).outputs[0]
    ref = lrn_across_channels_xla(x, 5, 1e-4, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_lrn_even_size_rejected():
    x = jnp.zeros((1, 4, 2, 2), jnp.float32)
    with pytest.raises(ValueError, match="odd"):
        lrn_across_channels(x, 4, 1e-4, 0.75, 1.0)
