"""Pallas LRN kernel: interpret-mode equivalence with the XLA formulation
(value and gradient), mirroring the reference's per-layer gradient-check
discipline (ref: caffe/src/caffe/test/test_lrn_layer.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.ops.pallas_kernels import (
    lrn_across_channels,
    lrn_across_channels_xla,
)

CASES = [
    # (shape, size, alpha, beta, k)
    ((2, 5, 4, 4), 5, 1e-4, 0.75, 1.0),     # AlexNet params, tiny shape
    ((1, 96, 6, 6), 5, 1e-4, 0.75, 1.0),    # AlexNet conv1 channel count
    ((2, 8, 3, 7), 3, 5e-5, 0.75, 2.0),     # odd spatial, k != 1
]


@pytest.mark.parametrize("shape,size,alpha,beta,k", CASES)
def test_pallas_lrn_matches_xla(shape, size, alpha, beta, k):
    x = jnp.asarray(np.random.RandomState(0).randn(*shape) * 10, jnp.float32)
    ref = lrn_across_channels_xla(x, size, alpha, beta, k)
    out = lrn_across_channels(x, size, alpha, beta, k, force="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pallas_lrn_gradient_matches_xla():
    x = jnp.asarray(np.random.RandomState(1).randn(1, 6, 4, 4) * 5, jnp.float32)

    g_pallas = jax.grad(
        lambda t: jnp.sum(lrn_across_channels(t, 5, 1e-4, 0.75, 1.0,
                                              force="interpret") ** 2))(x)
    g_xla = jax.grad(
        lambda t: jnp.sum(lrn_across_channels_xla(t, 5, 1e-4, 0.75, 1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla), atol=1e-4)


def test_pallas_lrn_nonaligned_spatial_padding():
    """Spatial size not a multiple of the tile exercises the pad/crop path."""
    x = jnp.asarray(np.random.RandomState(2).randn(1, 4, 13, 11), jnp.float32)
    ref = lrn_across_channels_xla(x, 3, 1e-4, 0.75, 1.0)
    out = lrn_across_channels(x, 3, 1e-4, 0.75, 1.0, force="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_lrn_layer_uses_selector_and_stays_correct():
    """The LRN layer's output is unchanged after the pallas wiring (CPU
    backend routes to XLA)."""
    from sparknet_tpu.common import Phase
    from sparknet_tpu.ops.registry import create_layer
    from sparknet_tpu.proto.text_format import Message

    lp = Message().set("name", "n").set("type", "LRN")
    lp.add("bottom", "x"); lp.add("top", "n")
    lp.set("lrn_param", Message().set("local_size", 5).set("alpha", 1e-4).set("beta", 0.75))
    layer = create_layer(lp, Phase.TRAIN)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 5, 5), jnp.float32)
    out = layer.apply([], {}, [x], train=True).outputs[0]
    ref = lrn_across_channels_xla(x, 5, 1e-4, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_lrn_even_size_rejected():
    x = jnp.zeros((1, 4, 2, 2), jnp.float32)
    with pytest.raises(ValueError, match="odd"):
        lrn_across_channels(x, 4, 1e-4, 0.75, 1.0)


# ------------------------------------------------------------ flash attention
class TestFlashAttention:
    """Blocked online-softmax kernel vs the unblocked oracle (interpret
    mode pins the pallas lowering on CPU; the TPU path shares the code)."""

    def _qkv(self, rng, B=2, H=3, S=256, D=64):
        mk = lambda: jnp.asarray(rng.randn(B, H, S, D) * 0.5, jnp.float32)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("S", [128, 256, 200])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, rng, S, causal):
        from sparknet_tpu.ops.pallas_kernels import attention_xla, flash_attention

        q, k, v = self._qkv(rng, S=S)
        ref = attention_xla(q, k, v, causal)
        out = flash_attention(q, k, v, causal, force="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grad_matches_oracle(self, rng):
        from sparknet_tpu.ops.pallas_kernels import attention_xla, flash_attention

        q, k, v = self._qkv(rng, B=1, H=2, S=128, D=32)
        f = lambda a: jnp.sum(flash_attention(a, k, v, True, force="interpret") ** 2)
        g = lambda a: jnp.sum(attention_xla(a, k, v, True) ** 2)
        np.testing.assert_allclose(
            np.asarray(jax.grad(f)(q)), np.asarray(jax.grad(g)(q)), atol=5e-5
        )

    def test_env_dispatch_and_xla_default(self, rng, monkeypatch):
        from sparknet_tpu.ops.pallas_kernels import attention_xla, flash_attention

        q, k, v = self._qkv(rng, S=128)
        monkeypatch.delenv("SPARKNET_ATTN_IMPL", raising=False)
        default = flash_attention(q, k, v)  # default = xla formulation
        np.testing.assert_allclose(
            np.asarray(default), np.asarray(attention_xla(q, k, v)), atol=1e-6
        )
        monkeypatch.setenv("SPARKNET_ATTN_IMPL", "interpret")
        env = flash_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(env), np.asarray(attention_xla(q, k, v)), atol=2e-5
        )

    def test_bf16_inputs(self, rng):
        from sparknet_tpu.ops.pallas_kernels import attention_xla, flash_attention

        q, k, v = (x.astype(jnp.bfloat16) for x in self._qkv(rng, S=128))
        out = flash_attention(q, k, v, force="interpret")
        assert out.dtype == jnp.bfloat16
        ref = attention_xla(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_ulysses_with_interpret_kernel(self, rng, monkeypatch):
        """The sharded path composes with the kernel: ulysses local attention
        through the interpret-mode flash kernel still matches the oracle."""
        from jax.sharding import Mesh

        from sparknet_tpu.parallel.ring_attention import reference_attention
        from sparknet_tpu.parallel.ulysses import ulysses_self_attention

        monkeypatch.setenv("SPARKNET_ATTN_IMPL", "interpret")
        mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
        q, k, v = self._qkv(rng, B=1, H=8, S=256, D=16)
        out = ulysses_self_attention(mesh, q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

class TestFusedLrn:
    """The shifted-add + rsqrt + hand-VJP formulation must be numerically
    interchangeable with the reduce_window/power one (value AND gradient —
    the VJP is hand-derived, so the gradient check is the load-bearing
    pin; ref discipline: caffe/src/caffe/test/test_lrn_layer.cpp)."""

    @pytest.mark.parametrize("shape,size,alpha,beta,k", CASES)
    def test_value_matches_xla(self, shape, size, alpha, beta, k):
        from sparknet_tpu.ops.pallas_kernels import lrn_across_channels_fused

        x = jnp.asarray(np.random.RandomState(7).randn(*shape) * 10, jnp.float32)
        ref = lrn_across_channels_xla(x, size, alpha, beta, k)
        out = lrn_across_channels_fused(x, size, alpha, beta, k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("beta", [0.75, 0.5, 1.0, 0.6])
    def test_grad_matches_autodiff_of_xla(self, beta):
        from sparknet_tpu.ops.pallas_kernels import lrn_across_channels_fused

        x = jnp.asarray(np.random.RandomState(8).randn(2, 7, 4, 5) * 5,
                        jnp.float32)
        # non-uniform cotangent so the windowed-sum adjoint is actually
        # exercised (sum() would feed g=1 everywhere)
        g_fused = jax.grad(lambda t: jnp.sum(
            lrn_across_channels_fused(t, 5, 1e-4, beta, 2.0) ** 2))(x)
        g_ref = jax.grad(lambda t: jnp.sum(
            lrn_across_channels_xla(t, 5, 1e-4, beta, 2.0) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_selector_routes_fused(self, monkeypatch):
        monkeypatch.setenv("SPARKNET_LRN_IMPL", "fused")
        x = jnp.asarray(np.random.RandomState(9).randn(1, 6, 3, 3) * 4,
                        jnp.float32)
        out = lrn_across_channels(x, 5, 1e-4, 0.75, 1.0)
        ref = lrn_across_channels_xla(x, 5, 1e-4, 0.75, 1.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_numeric_gradient(self):
        """Central-difference check of the hand VJP itself, independent of
        the XLA formulation (both could share a bug through _pow_neg)."""
        from sparknet_tpu.ops.pallas_kernels import lrn_across_channels_fused

        rs = np.random.RandomState(10)
        x = rs.randn(1, 5, 2, 3).astype(np.float32) * 3
        co = rs.randn(1, 5, 2, 3).astype(np.float32)

        def f(t):
            return float(jnp.vdot(
                lrn_across_channels_fused(jnp.asarray(t), 5, 1e-2, 0.75, 1.0),
                jnp.asarray(co)))

        g = jax.grad(lambda t: jnp.vdot(
            lrn_across_channels_fused(t, 5, 1e-2, 0.75, 1.0),
            jnp.asarray(co)))(jnp.asarray(x))
        eps = 1e-2
        for idx in [(0, 0, 0, 0), (0, 2, 1, 1), (0, 4, 0, 2)]:
            xp = x.copy(); xp[idx] += eps
            xm = x.copy(); xm[idx] -= eps
            num = (f(xp) - f(xm)) / (2 * eps)
            assert abs(num - float(g[idx])) < 5e-3 * max(1.0, abs(num)), (
                idx, num, float(g[idx]))

    def test_window_wider_than_channels(self):
        """size=7 window on a 2-channel blob: shifts past the channel
        count contribute nothing; must match the reduce_window path
        instead of crashing (review finding, round 4)."""
        from sparknet_tpu.ops.pallas_kernels import lrn_across_channels_fused

        x = jnp.asarray(np.random.RandomState(11).randn(1, 2, 3, 3) * 5,
                        jnp.float32)
        ref = lrn_across_channels_xla(x, 7, 1e-2, 0.75, 1.0)
        out = lrn_across_channels_fused(x, 7, 1e-2, 0.75, 1.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        g_f = jax.grad(lambda t: jnp.sum(
            lrn_across_channels_fused(t, 7, 1e-2, 0.75, 1.0) ** 2))(x)
        g_r = jax.grad(lambda t: jnp.sum(
            lrn_across_channels_xla(t, 7, 1e-2, 0.75, 1.0) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r),
                                   rtol=1e-4, atol=1e-4)


def test_pallas_interpret_window_wider_than_channels():
    """The pallas kernel shares the shift clamp: size=7 on 2 channels in
    interpret mode must match reduce_window, not crash."""
    x = jnp.asarray(np.random.RandomState(12).randn(1, 2, 3, 3) * 5,
                    jnp.float32)
    ref = lrn_across_channels_xla(x, 7, 1e-2, 0.75, 1.0)
    out = lrn_across_channels(x, 7, 1e-2, 0.75, 1.0, force="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
